"""Mechanism ABI: shape-bucketed traced-operand specs (ROADMAP item 3).

Every program in the zoo historically closed over ``ModelSpec``'s numpy
arrays as XLA constants, so each new mechanism re-paid the full prewarm
wall and AOT packs were valid for exactly one mechanism. This module
inverts that contract: a mechanism's dense operands (stoichiometry,
index tables, thermo tables, reaction masks) are zero-padded into a
small set of static shape buckets and threaded through the programs as
a *traced argument*. Programs then specialize only on the bucket --
``AbiStatic`` -- and the second mechanism that lands in a warm bucket
runs with zero new compiles.

Object model (three layers):

``AbiStatic``
    The bucket: padded species/reaction/dynamic dims plus the two
    genuinely trace-shaping scalars (reactor code, desorption model).
    Everything a compiled program is allowed to specialize on.

``AbiProgramSpec``
    One interned instance per ``AbiStatic``. This is what the program
    builders, the compile-pool registry and ``spec_fingerprint`` see in
    place of a ``ModelSpec`` -- its identity (and ``abi_fingerprint``)
    is shared by every mechanism in the bucket, which is exactly what
    makes the caches cross-mechanism. ``bind(ops)`` reconstitutes a
    spec-shaped namespace from traced operands inside a program body.

``AbiLowered``
    One per mechanism: the zero-padded ``ModelSpec`` (host-side
    orchestration reads fall through to it), the operand pytree, and
    the padding/unpadding helpers for conditions and results.

Padding semantics (proven exact no-ops, see docs/mechanism_abi.md):
pad reactions are ghosts (``is_ghost=1`` -> kf=kr=0); pad species have
zero stoichiometry rows and zero thermo masks; the legacy activity
sentinel ``n_s`` is remapped to the padded sentinel ``S``; pad dynamic
slots point at the last (pad) species slot and carry an ``x' = -x``
residual via ``dyn_mask`` so the padded Jacobian is exactly
``blkdiag(J_real, -I)``.

This module keeps jax imports function-local so the bucket tables can
be imported by the (host-only) validation layer.
"""

from __future__ import annotations

import os
import threading
from dataclasses import fields as _dc_fields
from typing import NamedTuple

import numpy as np

from .. import precision as _precision
from .spec import REACTOR_CSTR, Conditions, ModelSpec

ABI_VERSION = 1
ABI_ENV = "PYCATKIN_ABI"

# Primary buckets: padded species dim S (>= n_s + 1: the last slot is
# reserved so pad dynamic/scaling indices never alias a real species)
# and padded reaction dim R (>= n_r).
SPECIES_BUCKETS = (16, 32, 128, 512)
REACTION_BUCKETS = (16, 64, 256, 1024)

# Secondary dims, fixed across ALL buckets so mechanisms differing only
# in their small dims (frequency count, reaction arity, conservation
# groups, scaling states) still land in the same program. A mechanism
# exceeding any of these falls back to the legacy constant-folded path.
FREQ_PAD = 32        # F: vibrational modes per species
ARITY_PAD = 6        # A: reac_idx / prod_idx width
GROUPS_PAD = 8       # n_g: site-conservation groups
SCALING_PAD = 8      # n_sc: linear-scaling states
LYAP_PAD = 4         # m: deflated dim of the Lyapunov certificate basis

# The dynamic dim is its own power-of-two sub-bucket (solver cost is
# cubic in it; tying it to S would be ruinous for small mechanisms).
_BOUNDARY_MARGIN = 0.05   # validate.py warns within 5% of a bucket edge


class AbiStatic(NamedTuple):
    """Everything a compiled ABI program may specialize on.

    ``precision`` is the solver precision tier the bucket's programs
    are built for (:mod:`pycatkin_tpu.precision`): an f32-bulk program
    computes different math from the f64 one, so the tiers must intern
    as DIFFERENT buckets and can never share an AOT entry. The traced
    operand dtypes themselves stay f64 under every tier -- the f64
    polish-and-verify stage needs full-precision mechanism data, and
    the in-program downcast of the bulk stage is free -- so padding and
    operand layout are tier-invariant."""
    abi_version: int
    n_species: int       # S (padded, includes the reserved pad slot)
    n_reactions: int     # R (padded)
    n_dynamic: int       # D (padded dynamic dim)
    reactor_type: int
    desorption_model: str
    precision: str = "f64"


def abi_fingerprint_of(static: AbiStatic) -> str:
    # The f64 tag is empty: every pre-tier fingerprint (and the AOT
    # pack entries keyed on it) stays byte-identical.
    return ("abi-v{0}:s{1}:r{2}:d{3}:rt{4}:{5}{6}".format(
        static.abi_version, static.n_species, static.n_reactions,
        static.n_dynamic, static.reactor_type, static.desorption_model,
        _precision.tier_tag(static.precision)))


def abi_enabled() -> bool:
    return os.environ.get(ABI_ENV, "0").lower() not in ("", "0", "false")


class AbiBucketError(ValueError):
    """A mechanism does not fit any ABI bucket. Carries a
    ``ValidationReport``-style diagnostic in ``.report``."""

    def __init__(self, issues):
        from .validate import ValidationReport
        report = ValidationReport()
        for loc, msg in issues:
            report.error(loc, msg)
        self.report = report
        lines = ["mechanism does not fit the ABI buckets:"]
        lines += [f"  {i.location}: {i.message}" for i in report.issues]
        super().__init__("\n".join(lines))


def _pow2_at_least(n: int) -> int:
    p = 1
    while p < max(int(n), 1):
        p *= 2
    return p


def _bucket_for(n: int, buckets) -> int | None:
    for b in buckets:
        if n <= b:
            return int(b)
    return None


def select_static(spec: ModelSpec,  # pclint: disable=PCL013 -- host-side spec metadata; asarray touches numpy index arrays, no device round trip
                  species_bucket: int | None = None,
                  reaction_bucket: int | None = None) -> AbiStatic:
    """Pick the bucket for ``spec`` (or validate a forced one), raising
    :class:`AbiBucketError` with a per-dimension diagnostic when the
    mechanism cannot fit."""
    n_s, n_r = spec.n_species, spec.n_reactions
    issues = []

    S = species_bucket or _bucket_for(n_s + 1, SPECIES_BUCKETS)
    if S is None or n_s + 1 > S:
        issues.append((
            "/abi/species",
            f"{n_s} species (+1 reserved pad slot) exceed "
            f"bucket {S or max(SPECIES_BUCKETS)}"))
    R = reaction_bucket or _bucket_for(n_r, REACTION_BUCKETS)
    if R is None or n_r > R:
        issues.append((
            "/abi/reactions",
            f"{n_r} reactions exceed bucket {R or max(REACTION_BUCKETS)}"))
    if spec.freq.shape[1] > FREQ_PAD:
        issues.append(("/abi/freq",
                       f"{spec.freq.shape[1]} vibrational modes exceed "
                       f"the fixed pad {FREQ_PAD}"))
    if spec.reac_idx.shape[1] > ARITY_PAD:
        issues.append(("/abi/arity",
                       f"reaction arity {spec.reac_idx.shape[1]} exceeds "
                       f"the fixed pad {ARITY_PAD}"))
    if spec.groups.shape[0] > GROUPS_PAD:
        issues.append(("/abi/groups",
                       f"{spec.groups.shape[0]} conservation groups exceed "
                       f"the fixed pad {GROUPS_PAD}"))
    if spec.scl_idx.size > SCALING_PAD:
        issues.append(("/abi/scaling",
                       f"{spec.scl_idx.size} scaling states exceed "
                       f"the fixed pad {SCALING_PAD}"))
    if issues:
        raise AbiBucketError(issues)

    n_dyn = int(np.asarray(spec.dynamic_indices).size)
    D = _pow2_at_least(n_dyn)
    m = _deflated_dim(spec)
    if 0 < m <= LYAP_PAD:
        # The Lyapunov basis needs LYAP_PAD - m distinct pad dynamic
        # slots for its unit pad columns (QtJQ = blkdiag(B, -I)).
        while D - n_dyn < LYAP_PAD - m:
            D *= 2
    return AbiStatic(abi_version=ABI_VERSION, n_species=S, n_reactions=R,
                     n_dynamic=D, reactor_type=int(spec.reactor_type),
                     desorption_model=str(spec.desorption_model),
                     precision=_precision.active_tier())


def _deflated_dim(spec: ModelSpec) -> int:
    from ..solvers.newton import deflation_basis_for_spec
    return int(deflation_basis_for_spec(spec).shape[1])


# ----------------------------------------------------------------------
# TracedSpec: the spec-shaped namespace programs run on

class TracedSpec:
    """Duck-typed ``ModelSpec`` built inside a jitted program body from
    ``(AbiStatic, traced operands)``. The engine runs on it unchanged;
    the always-on scaling/udar/gfree blocks are exact no-ops for
    mechanisms that lack them (their padded matrices are zero)."""

    has_udar = True
    has_gfree = True

    def __init__(self, static: AbiStatic, ops: dict):
        self.abi_static = static
        self.reactor_type = static.reactor_type
        self.desorption_model = static.desorption_model
        for k, v in ops.items():
            setattr(self, k, v)

    @property
    def n_species(self) -> int:
        return self.abi_static.n_species

    @property
    def n_reactions(self) -> int:
        return self.abi_static.n_reactions


class AbiProgramSpec:
    """The bucket-identity object handed to program builders and the
    compile pool in place of a ``ModelSpec``. Interned: one instance
    per ``AbiStatic``, and hash/eq by bucket, so identity-keyed builder
    caches and the executable registry are shared by every mechanism
    that lowers into the bucket."""

    def __init__(self, static: AbiStatic):
        self.static = static
        self.abi_fingerprint = abi_fingerprint_of(static)

    def bind(self, ops: dict) -> TracedSpec:
        return TracedSpec(self.static, ops)

    def __hash__(self):
        return hash(self.static)

    def __eq__(self, other):
        return (isinstance(other, AbiProgramSpec)
                and self.static == other.static)

    def __repr__(self):
        return f"AbiProgramSpec({self.abi_fingerprint})"


_PROGRAM_SPECS: dict = {}
_PS_LOCK = threading.Lock()


def program_spec_for(static: AbiStatic) -> AbiProgramSpec:
    with _PS_LOCK:
        ps = _PROGRAM_SPECS.get(static)
        if ps is None:
            ps = _PROGRAM_SPECS[static] = AbiProgramSpec(static)
        return ps


# ----------------------------------------------------------------------
# lowering: ModelSpec -> AbiLowered

def _pad_to(a, shape, fill=0.0):
    """Zero-extend ``a`` (trailing pads, value ``fill``) to ``shape``."""
    a = np.asarray(a)
    widths = [(0, t - s) for s, t in zip(a.shape, shape)]
    return np.pad(a, widths, constant_values=np.asarray(fill, a.dtype))


def _padded_spec(spec: ModelSpec, st: AbiStatic) -> ModelSpec:
    """The zero-padded host-side ModelSpec for a bucket. Pad rules:

    - pad reactions are ghosts (kf=kr=0) with neutral physical fields
      (area/masses 1.0 so no log/0-division paths are fed zeros);
    - pad species have zero thermo masks, zero stoichiometry rows and
      unit mass/sigma/inertia;
    - index tables remap the legacy activity sentinel n_s -> S and send
      pad entries to S (reac/prod) or S-1 (scaling/dynamic scatter
      targets, which land in the reserved pad species slot).
    """
    S, R = st.n_species, st.n_reactions
    n_s, n_r = spec.n_species, spec.n_reactions
    F, A = FREQ_PAD, ARITY_PAD

    reac_idx = np.asarray(spec.reac_idx).copy()
    prod_idx = np.asarray(spec.prod_idx).copy()
    reac_idx[reac_idx == n_s] = S
    prod_idx[prod_idx == n_s] = S

    n_dyn = int(np.asarray(spec.dynamic_indices).size)
    dyn = _pad_to(spec.dynamic_indices, (st.n_dynamic,), S - 1)
    pad_sp = [f"__abi_pad_s{i}" for i in range(S - n_s)]
    pad_rx = [f"__abi_pad_r{i}" for i in range(R - n_r)]

    kw = dict(
        snames=tuple(spec.snames) + tuple(pad_sp),
        state_types=tuple(spec.state_types) + ("abi_pad",) * (S - n_s),
        freq=_pad_to(spec.freq, (S, F)),
        fmask=_pad_to(spec.fmask, (S, F)),
        mass=_pad_to(spec.mass, (S,), 1.0),
        sigma=_pad_to(spec.sigma, (S,), 1.0),
        inertia=_pad_to(spec.inertia, (S, 3), 1.0),
        is_gas=_pad_to(spec.is_gas, (S,)),
        is_linear=_pad_to(spec.is_linear, (S,)),
        mix=_pad_to(spec.mix, (S, S)),
        gelec0=_pad_to(spec.gelec0, (S,)),
        add0=_pad_to(spec.add0, (S,)),
        gvibr0=_pad_to(spec.gvibr0, (S,)),
        gvibr_mask=_pad_to(spec.gvibr_mask, (S,)),
        gtran0=_pad_to(spec.gtran0, (S,)),
        gtran_mask=_pad_to(spec.gtran_mask, (S,)),
        grota0=_pad_to(spec.grota0, (S,)),
        grota_mask=_pad_to(spec.grota_mask, (S,)),
        gfree0=_pad_to(spec.gfree0, (S,)),
        gfree_mask=_pad_to(spec.gfree_mask, (S,)),
        scl_idx=_pad_to(spec.scl_idx, (SCALING_PAD,), S - 1),
        scl_b=_pad_to(spec.scl_b, (SCALING_PAD,)),
        scl_We=_pad_to(spec.scl_We, (SCALING_PAD, S)),
        scl_Ws=_pad_to(spec.scl_Ws, (SCALING_PAD, SCALING_PAD)),
        scl_WuE=_pad_to(spec.scl_WuE, (SCALING_PAD, R)),
        udar_mask=_pad_to(spec.udar_mask, (S,)),
        udar_Ce=_pad_to(spec.udar_Ce, (S, S)),
        udar_Cg=_pad_to(spec.udar_Cg, (S, S)),
        udar_CuE=_pad_to(spec.udar_CuE, (S, R)),
        udar_CuG=_pad_to(spec.udar_CuG, (S, R)),
        rnames=tuple(spec.rnames) + tuple(pad_rx),
        reac_types=tuple(spec.reac_types) + ("abi_pad",) * (R - n_r),
        SR=_pad_to(spec.SR, (R, S)),
        SP=_pad_to(spec.SP, (R, S)),
        ST=_pad_to(spec.ST, (R, S)),
        has_TS=_pad_to(spec.has_TS, (R,)),
        reversible=_pad_to(spec.reversible, (R,)),
        base_reversible=_pad_to(spec.base_reversible, (R,)),
        is_arr_type=_pad_to(spec.is_arr_type, (R,)),
        is_ads=_pad_to(spec.is_ads, (R,)),
        is_des=_pad_to(spec.is_des, (R,)),
        is_ghost=_pad_to(spec.is_ghost, (R,), 1.0),
        is_user=_pad_to(spec.is_user, (R,)),
        area=_pad_to(spec.area, (R,), 1.0),
        rscaling=_pad_to(spec.rscaling, (R,), 1.0),
        site_density=_pad_to(spec.site_density, (R,)),
        gas_mass=_pad_to(spec.gas_mass, (R,), 1.0),
        gas_sigma=_pad_to(spec.gas_sigma, (R,), 1.0),
        gas_inertia=_pad_to(spec.gas_inertia, (R, 3), 1.0),
        gas_polyatomic=_pad_to(spec.gas_polyatomic, (R,)),
        reac_idx=_pad_to(reac_idx, (R, A), S),
        prod_idx=_pad_to(prod_idx, (R, A), S),
        stoich=_pad_to(spec.stoich, (S, R)),
        reactor_type=spec.reactor_type,
        volume=spec.volume,
        catalyst_area=spec.catalyst_area,
        residence_time=spec.residence_time,
        is_adsorbate=_pad_to(spec.is_adsorbate, (S,)),
        is_gas_dyn=_pad_to(spec.is_gas_dyn, (S,)),
        dynamic_indices=dyn,
        adsorbate_indices=np.asarray(spec.adsorbate_indices).copy(),
        gas_indices=np.asarray(spec.gas_indices).copy(),
        groups=_pad_to(spec.groups, (GROUPS_PAD, S)),
        desorption_model=spec.desorption_model,
    )
    missing = {f.name for f in _dc_fields(ModelSpec)} - set(kw)
    if missing:   # a new ModelSpec field must pick a pad rule explicitly
        raise AbiBucketError([("/abi/fields",
                               f"no ABI pad rule for spec fields "
                               f"{sorted(missing)} (bump ABI_VERSION)")])
    assert n_dyn <= st.n_dynamic
    return ModelSpec(**kw)


# Padded-spec array fields that become traced operands. Host-only /
# build-time fields (gelec0, is_arr_type, base_reversible, rscaling,
# site_density, is_gas_dyn, adsorbate/gas index lists) stay off the
# operand pytree.
_OPERAND_FIELDS = (
    "freq", "fmask", "mass", "sigma", "inertia", "is_gas", "is_linear",
    "mix", "add0", "gvibr0", "gvibr_mask", "gtran0", "gtran_mask",
    "grota0", "grota_mask", "gfree0", "gfree_mask",
    "scl_idx", "scl_b", "scl_We", "scl_Ws", "scl_WuE",
    "udar_mask", "udar_Ce", "udar_Cg", "udar_CuE", "udar_CuG",
    "SR", "SP", "ST", "has_TS", "reversible", "is_ads", "is_des",
    "is_ghost", "is_user", "area", "gas_mass", "gas_sigma",
    "gas_inertia", "gas_polyatomic", "reac_idx", "prod_idx", "stoich",
    "is_adsorbate", "dynamic_indices", "groups",
)


def _lyapunov_operands(spec: ModelSpec, st: AbiStatic):
    """Padded deflation basis Q [D, LYAP_PAD] and its validity flag.

    The real basis (computed from the ORIGINAL spec, so its real block
    is bit-identical to the legacy screen's) is extended with unit
    columns on distinct pad dynamic slots, making QtJQ =
    blkdiag(B_real, -I): the certificate's verdict on the padded system
    equals its verdict on the real one. When the real deflated dim
    exceeds LYAP_PAD (or is 0), lyap_ok=0 soundly abstains and those
    lanes take the tier-2 eigensolve, exactly like legacy mechanisms
    above LYAPUNOV_MAX_DIM."""
    from ..solvers.newton import deflation_basis_for_spec
    n_dyn = int(np.asarray(spec.dynamic_indices).size)
    Q_real = np.asarray(deflation_basis_for_spec(spec), dtype=np.float64)
    m = Q_real.shape[1]
    Q = np.zeros((st.n_dynamic, LYAP_PAD), dtype=np.float64)
    ok = 0 < m <= LYAP_PAD and (st.n_dynamic - n_dyn) >= (LYAP_PAD - m)
    if ok:
        Q[:n_dyn, :m] = Q_real
        for j in range(LYAP_PAD - m):
            Q[n_dyn + j, m + j] = 1.0
    return Q, np.float64(1.0 if ok else 0.0)


class AbiLowered:
    """One mechanism lowered into a bucket: the padded host-side spec,
    the traced operand pytree, and the pad/unpad helpers. Host
    attribute reads fall through to the padded ``ModelSpec``."""

    def __init__(self, base: ModelSpec, static: AbiStatic):
        self.base = base
        self.static = static
        self.spec_padded = _padded_spec(base, static)
        self.program_spec = program_spec_for(static)
        self.abi_fingerprint = self.program_spec.abi_fingerprint
        self.n_s_real = base.n_species
        self.n_r_real = base.n_reactions
        self.n_dyn_real = int(np.asarray(base.dynamic_indices).size)

        ops = {k: np.asarray(getattr(self.spec_padded, k))
               for k in _OPERAND_FIELDS}
        dyn_mask = np.zeros((static.n_dynamic,), dtype=np.float64)
        dyn_mask[:self.n_dyn_real] = 1.0
        ops["dyn_mask"] = dyn_mask
        ops["lyap_q"], ops["lyap_ok"] = _lyapunov_operands(base, static)
        if static.reactor_type == REACTOR_CSTR:
            ops["volume"] = np.float64(base.volume)
            ops["catalyst_area"] = np.float64(base.catalyst_area)
            ops["residence_time"] = np.float64(base.residence_time)
        self._np_operands = {k: ops[k] for k in sorted(ops)}
        self._device_operands = None

    def operands(self) -> dict:
        """The traced operand pytree (device arrays, cached)."""
        if self._device_operands is None:
            import jax.numpy as jnp
            self._device_operands = {
                k: jnp.asarray(v) for k, v in self._np_operands.items()}
        return self._device_operands

    def __getattr__(self, name):
        return getattr(self.spec_padded, name)

    # -- boundary padding -------------------------------------------------
    def pad_conditions(self, conds: Conditions) -> Conditions:
        S, R = self.static.n_species, self.static.n_reactions
        sp = lambda a, fill=0.0: _pad_last(a, S - self.n_s_real, fill)
        rx = lambda a, fill=0.0: _pad_last(a, R - self.n_r_real, fill)
        return conds._replace(
            gelec=sp(conds.gelec), eps=sp(conds.eps), y0=sp(conds.y0),
            inflow=sp(conds.inflow),
            uE_rxn=rx(conds.uE_rxn), uG_rxn=rx(conds.uG_rxn),
            uEa=rx(conds.uEa), uGa=rx(conds.uGa),
            u_rxn_mask=rx(conds.u_rxn_mask), u_bar_mask=rx(conds.u_bar_mask),
            is_activated=rx(conds.is_activated),
            kscale=rx(conds.kscale, 1.0))

    def pad_x0(self, x0):
        if x0 is None:
            return None
        return _pad_last(x0, self.static.n_dynamic - self.n_dyn_real, 0.0)

    def pad_tof_mask(self, mask):
        if mask is None:
            return None
        return _pad_last(mask, self.static.n_reactions - self.n_r_real, 0.0)

    def unpad_y(self, y):
        """Strip pad species from a [..., S] composition axis."""
        return y[..., :self.n_s_real]


def _pad_last(a, pad: int, fill):
    a = np.asarray(a)
    if pad == 0:
        return a
    widths = [(0, 0)] * (a.ndim - 1) + [(0, pad)]
    return np.pad(a, widths, constant_values=np.asarray(fill, a.dtype))


# ----------------------------------------------------------------------
# multi-tenant packing

class PackedLowered:
    """K same-bucket :class:`AbiLowered` mechanisms stacked along a new
    leading *tenant* axis -- the multi-tenant unit the packed fused
    sweep program (parallel/batch.py) dispatches as ONE device program.

    The tenant axis is padded to a power of two (``k_bucket``) with
    *ghost tenants* that replicate tenant 0's operands and inputs, so
    packed program shapes -- and therefore compile_pool keys -- form a
    small closed family per bucket instead of one per occupancy. Ghost
    results are simply never unpacked. ``k_bucket == 1`` is the
    degenerate pack; callers (``packed_sweep_steady_state``) route it
    through the ordinary solo path so every pre-packing program key,
    AOT entry and exported pack stays byte-identical.

    ``abi_fingerprint`` is the bucket fingerprint plus the tenant-count
    sub-bucket tag (``:tK`` for K > 1 -- see
    :func:`parallel.compile_pool.tenant_tag`); ``program_spec`` and
    ``operands()`` mirror :class:`AbiLowered`'s interface so the batch
    layer's ``_prog_spec``/``_prog_args`` seam handles both."""

    def __init__(self, lows, k_bucket: int | None = None):
        lows = tuple(lows)
        if not lows:
            raise AbiBucketError([("pack", "cannot pack zero tenants")])
        issues = []
        for i, low in enumerate(lows):
            if not isinstance(low, AbiLowered):
                issues.append((f"tenant {i}",
                               f"not an AbiLowered (got "
                               f"{type(low).__name__}); lower each "
                               f"mechanism with lower_spec/maybe_lower "
                               f"first"))
            elif low.program_spec is not lows[0].program_spec:
                issues.append((f"tenant {i}",
                               f"bucket {low.abi_fingerprint} != tenant "
                               f"0's {lows[0].abi_fingerprint}; only "
                               f"same-bucket mechanisms can share a "
                               f"packed program"))
        if issues:
            raise AbiBucketError(issues)
        self.tenants = lows
        self.k = len(lows)
        kb = _pow2_at_least(self.k if k_bucket is None else k_bucket)
        if kb < self.k:
            raise AbiBucketError([
                ("pack", f"k_bucket {kb} < {self.k} tenants")])
        self.k_bucket = kb
        self.static = lows[0].static
        self.program_spec = lows[0].program_spec
        from ..parallel.compile_pool import tenant_tag
        self.abi_fingerprint = (self.program_spec.abi_fingerprint
                                + tenant_tag(kb))
        # Ghost tenants replicate tenant 0 up to the pow2 bucket.
        self._order = tuple(range(self.k)) + (0,) * (kb - self.k)
        self._np_operands = {
            key: np.stack([lows[i]._np_operands[key]
                           for i in self._order])
            for key in lows[0]._np_operands}
        self._device_operands = None

    @property
    def occupancy(self) -> float:
        """Real tenants over the pow2 tenant bucket (ghosts excluded)."""
        return self.k / self.k_bucket

    def operands(self) -> dict:
        """The stacked traced operand pytree: every leaf of the solo
        operand dict with a leading ``[k_bucket]`` tenant axis."""
        if self._device_operands is None:
            import jax.numpy as jnp
            self._device_operands = {
                k: jnp.asarray(v) for k, v in self._np_operands.items()}
        return self._device_operands

    def stack_tenants(self, per_tenant):
        """Stack K per-tenant pytrees (pre-padded to the bucket shape)
        along the tenant axis, replicating tenant 0 into the ghost
        slots. ``None`` passes through (an absent optional input is
        absent for every tenant)."""
        per_tenant = list(per_tenant)
        if len(per_tenant) != self.k:
            raise ValueError(f"expected {self.k} per-tenant values, "
                             f"got {len(per_tenant)}")
        if all(v is None for v in per_tenant):
            return None
        if any(v is None for v in per_tenant):
            raise ValueError("per-tenant inputs must be all-present or "
                             "all-None across the pack")
        import jax
        import jax.numpy as jnp
        full = [per_tenant[i] for i in self._order]
        return jax.tree_util.tree_map(
            lambda *leaves: jnp.stack([jnp.asarray(x) for x in leaves]),
            *full)

    def pad_conditions(self, conds_list):
        """Per-tenant boundary padding then tenant stacking:
        ``[K x Conditions(lanes, real dims)]`` -> one stacked
        ``Conditions`` pytree of ``[k_bucket, lanes, bucket dims]``
        leaves."""
        return self.stack_tenants(
            [low.pad_conditions(c)
             for low, c in zip(self.tenants, conds_list)])

    def pad_tof_mask(self, masks):
        if masks is None or all(m is None for m in masks):
            return None
        return self.stack_tenants(
            [low.pad_tof_mask(m)
             for low, m in zip(self.tenants, masks)])

    def pad_x0(self, x0s):
        if x0s is None or all(x is None for x in x0s):
            return None
        return self.stack_tenants(
            [low.pad_x0(x) for low, x in zip(self.tenants, x0s)])

    def unpad_y(self, y, tenant: int):
        """Strip pad species from tenant ``tenant``'s composition axis."""
        return self.tenants[tenant].unpad_y(y)


def pack_lowered(lows, k_bucket: int | None = None) -> PackedLowered:
    """Pack K lowered mechanisms of ONE ABI bucket into a
    :class:`PackedLowered` (tenant axis padded to a power of two with
    ghost replicas of tenant 0). Raises :class:`AbiBucketError` when
    the tenants span buckets or precision tiers -- the request
    coalescer (parallel/dispatch.py) groups by fingerprint precisely so
    this can never fire on its watch."""
    return PackedLowered(lows, k_bucket=k_bucket)


# ----------------------------------------------------------------------
# gating

_LOWER_CACHE: dict = {}
_LOWER_LOCK = threading.Lock()
_FALLBACK_WARNED: set = set()


def lower_spec(spec: ModelSpec, species_bucket: int | None = None,
               reaction_bucket: int | None = None) -> AbiLowered:
    """Lower ``spec`` into its ABI bucket (cached per (spec identity,
    precision tier) for the default-bucket case -- flipping the tier
    env var must re-intern into the tier's own bucket, never reuse a
    stale lowering; forced buckets are not cached)."""
    cache_key = (spec, _precision.active_tier())
    if species_bucket is None and reaction_bucket is None:
        with _LOWER_LOCK:
            low = _LOWER_CACHE.get(cache_key)
        if low is not None:
            return low
    st = select_static(spec, species_bucket, reaction_bucket)
    low = AbiLowered(spec, st)
    if species_bucket is None and reaction_bucket is None:
        # Headroom advisory (once per mechanism, thanks to the cache):
        # landing within _BOUNDARY_MARGIN of the bucket edge means tiny
        # mechanism growth will spill into the next bucket and repay
        # the compile wall the ABI amortizes.
        from .validate import check_abi_headroom
        import warnings
        for issue in check_abi_headroom(spec).warnings:
            warnings.warn(f"mechanism ABI: {issue}", UserWarning,
                          stacklevel=3)
        with _LOWER_LOCK:
            _LOWER_CACHE[cache_key] = low
    return low


def maybe_lower(spec):
    """The batch-layer gate: returns an :class:`AbiLowered` when the
    ABI path is enabled and ``spec`` fits a bucket, else None (legacy
    constant-folded path; unfittable mechanisms warn once)."""
    if not abi_enabled() or not isinstance(spec, ModelSpec):
        return None
    try:
        return lower_spec(spec)
    except AbiBucketError as e:
        key = id(spec)
        if key not in _FALLBACK_WARNED:
            _FALLBACK_WARNED.add(key)
            import warnings
            warnings.warn(
                f"PYCATKIN_ABI=1 but the mechanism does not fit any ABI "
                f"bucket; falling back to the legacy constant-folded "
                f"programs. {e}", stacklevel=3)
        return None


def clear_lowering_cache():
    with _LOWER_LOCK:
        _LOWER_CACHE.clear()
    _FALLBACK_WARNED.clear()
