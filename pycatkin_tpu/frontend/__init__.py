from .loader import read_from_input_file
from .reactions import Reaction, ReactionDerivedReaction, UserDefinedReaction
from .spec import Conditions, ModelSpec, build_spec, default_conditions
from .states import ScalingState, State
