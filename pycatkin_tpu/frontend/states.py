"""Host-side species description.

A :class:`State` is a *data resolver*: it loads electronic energies,
vibrational frequencies, masses and moments of inertia from DFT artifacts
(or takes them from the input file) and exposes them as static arrays for
the spec compiler. It performs **no** thermochemistry itself -- all free
energy math lives in :mod:`pycatkin_tpu.ops.thermo` as jitted kernels, so
there is exactly one implementation of the physics.

Capability parity with the reference ``State``/``ScalingState``
(/root/reference/pycatkin/classes/state.py:10-590): state types
(gas/adsorbate/surface/TS), energy/frequency sources (datafile, inputfile,
OUTCAR, log.vib), frequency floor + DOF padding rules, mode-truncation
counts, gas shape detection, gas-mixture (``gasdata``) corrections, energy
modifiers, and linear scaling relations (incl. ``dereference`` and
``use_descriptor_as_reactant``).
"""

from __future__ import annotations

import os
import sys
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from . import parsers

GAS = "gas"
ADSORBATE = "adsorbate"
SURFACE = "surface"
TS = "TS"

STATE_TYPES = (GAS, ADSORBATE, SURFACE, TS)

# Moments of inertia below this (amu*A^2) are treated as numerically zero
# when detecting linear molecules (reference state.py:69,99).
INERTIA_CUTOFF = 1.0e-12

# State names already warned about degenerate inertia tensors (the
# warning fires once per process per state, not once per rebuild).
_ZERO_INERTIA_WARNED: set = set()

# CPK/jmol-ish element colors + covalent-radius-ish sizes for the
# headless structure render (State.save_png). Unlisted elements fall
# back to gray / 1.2 A.
ELEMENT_COLORS = {
    "H": "#f2f2f2", "C": "#555555", "N": "#3050f8", "O": "#ff0d0d",
    "F": "#90e050", "Al": "#bfa6a6", "Si": "#f0c8a0", "P": "#ff8000",
    "S": "#ffff30", "Cl": "#1ff01f", "Ti": "#bfc2c7", "Fe": "#e06633",
    "Co": "#f090a0", "Ni": "#50d050", "Cu": "#c88033", "Zn": "#7d80b0",
    "Pd": "#006985", "Ag": "#c0c0c0", "Pt": "#d0d0e0", "Au": "#ffd123",
}
ELEMENT_RADII = {
    "H": 0.4, "C": 0.75, "N": 0.72, "O": 0.7, "F": 0.6, "Al": 1.2,
    "Si": 1.1, "P": 1.05, "S": 1.0, "Cl": 1.0, "Ti": 1.5, "Fe": 1.35,
    "Co": 1.3, "Ni": 1.25, "Cu": 1.3, "Zn": 1.25, "Pd": 1.4, "Ag": 1.45,
    "Pt": 1.4, "Au": 1.4,
}


@dataclass
class State:
    """One species: gas molecule, adsorbate, bare surface or transition state."""

    name: str
    state_type: str = None
    path: Optional[str] = None
    vibs_path: Optional[str] = None
    sigma: Optional[float] = None
    mass: Optional[float] = None
    inertia: Optional[np.ndarray] = None
    gasdata: Optional[dict] = None
    add_to_energy: Optional[float] = None
    truncate_freq: bool = True
    energy_source: Optional[str] = None
    freq_source: Optional[str] = None
    freq: Optional[np.ndarray] = None
    i_freq: Optional[np.ndarray] = None
    Gelec: Optional[float] = None
    Gzpe: Optional[float] = None
    Gvibr: Optional[float] = None
    Gtran: Optional[float] = None
    Grota: Optional[float] = None
    Gfree: Optional[float] = None
    read_from_alternate: Optional[dict] = None

    # Resolved lazily:
    shape: Optional[int] = field(default=None, repr=False)
    _loaded: bool = field(default=False, repr=False)

    def __post_init__(self):
        if self.state_type not in STATE_TYPES and self.state_type is not None:
            raise ValueError(
                f"state {self.name}: unknown state_type {self.state_type!r}")
        # Fixed-value thermo contributions supplied directly in the input
        # file short-circuit the corresponding kernel (reference
        # state.py:52-55 "inputfile" sources).
        self.tran_source = None if self.Gtran is None else "inputfile"
        self.rota_source = None if self.Grota is None else "inputfile"
        self.vibr_source = None if self.Gvibr is None else "inputfile"
        self.free_source = None if self.Gfree is None else "inputfile"
        if self.freq is not None:
            self.freq_source = "inputfile"
            self.freq = np.array(sorted(self.freq, reverse=True), dtype=float)
            self.i_freq = (np.array(sorted(self.i_freq, reverse=True), dtype=float)
                           if self.i_freq is not None else np.array([]))
        if self.inertia is not None:
            self._set_inertia(np.asarray(self.inertia, dtype=float))
        if self.state_type == GAS and self.sigma is None:
            raise ValueError(f"gas state {self.name} requires a symmetry number")

    # ------------------------------------------------------------------
    # construction from in-memory structure objects
    @classmethod
    def from_atoms(cls, name: str, atoms, state_type: str,
                   sigma: Optional[float] = None, freq=None, i_freq=None,
                   energy: Optional[float] = None, **kwargs) -> "State":
        """Build a State from an in-memory ASE ``Atoms``(-like) object.

        The reference reads structures through ASE and holds ``Atoms``
        objects directly (reference state.py:77-105: ``get_atoms``
        computes mass/inertia via ``atoms.get_masses()`` /
        ``atoms.get_moments_of_inertia()``); this is the entry point for
        users who already hold such an object instead of an
        OUTCAR/log.vib tree. ASE itself is NOT required (and is not a
        dependency): any object exposing ``get_masses()`` and -- for gas
        states -- ``get_moments_of_inertia()`` (amu*A^2) works. The
        electronic energy is taken from ``energy`` if given, else from
        ``atoms.get_potential_energy()`` when the object has a
        calculator attached (errors there are treated as "no energy",
        matching a bare structure file).

        ``freq``/``i_freq`` (Hz) seed the vibrational modes exactly like
        input-file frequencies. The structure (symbols + positions) is
        kept for :meth:`get_structure`/:meth:`save_pdb` when the object
        exposes ``get_chemical_symbols()``/``get_positions()``.
        """
        mass = float(np.sum(np.asarray(atoms.get_masses(), dtype=float)))
        inertia = None
        if state_type == GAS:
            inertia = np.asarray(atoms.get_moments_of_inertia(),
                                 dtype=float)
        if energy is None and hasattr(atoms, "get_potential_energy"):
            try:
                energy = float(atoms.get_potential_energy())
            except Exception:      # no calculator attached -> no energy
                energy = None
        st = cls(name=name, state_type=state_type, sigma=sigma,
                 mass=mass, inertia=inertia, freq=freq, i_freq=i_freq,
                 Gelec=energy, **kwargs)
        if (hasattr(atoms, "get_chemical_symbols")
                and hasattr(atoms, "get_positions")):
            st._structure = (list(atoms.get_chemical_symbols()),
                             np.asarray(atoms.get_positions(),
                                        dtype=float))
        return st

    # ------------------------------------------------------------------
    # data resolution
    def _set_inertia(self, inertia: np.ndarray):
        inertia = np.where(inertia > INERTIA_CUTOFF, inertia, 0.0)
        self.inertia = inertia
        self.shape = int((inertia > 0.0).sum())
        if (self.state_type == GAS and self.shape < 2
                and self.name not in _ZERO_INERTIA_WARNED):
            # Warn once per process per state: every rebuild/sweep setup
            # re-derives the same inertia tensor, and repeating the
            # warning per rebuild buries real diagnostics in the log.
            _ZERO_INERTIA_WARNED.add(self.name)
            print(f"state {self.name}: too many zero moments of inertia",
                  file=sys.stderr)

    def load(self, verbose: bool = False):
        """Resolve electronic energy, frequencies and geometry from sources."""
        if self._loaded:
            return self
        self._load_structure(verbose)
        self._load_frequencies(verbose)
        self._load_energy(verbose)
        self._loaded = True
        return self

    def _load_structure(self, verbose: bool):
        needs_geometry = (self.state_type == GAS and
                          (self.mass is None or self.inertia is None))
        if not needs_geometry:
            return
        if self.read_from_alternate and "get_atoms" in self.read_from_alternate:
            _, self.mass, inertia = self.read_from_alternate["get_atoms"]()
            self._set_inertia(np.asarray(inertia, dtype=float))
            return
        if self.path is None:
            if self.mass is None:
                raise ValueError(
                    f"gas state {self.name}: no mass and no path to read it")
            # Mass given but no inertia source: rotational contributions
            # are unavailable (engine returns 0 for them). Legitimate for
            # species whose free energy never enters the model (e.g.
            # user-defined reaction members, COOxVolcano CO/O2/CO2).
            self._set_inertia(np.zeros(3))
            return
        data = parsers.read_outcar(parsers.resolve_outcar_path(self.path))
        if self.mass is None:
            self.mass = data["mass"]
        if self.inertia is None:
            self._set_inertia(data["inertia"])

    def _load_frequencies(self, verbose: bool):
        if self.freq is not None or self.vibr_source == "inputfile":
            return
        if self.freq_source == "datafile":
            freq, i_freq = parsers.read_frequency_dat(self.vibs_path)
            self.freq = np.array(sorted(freq, reverse=True))
            self.i_freq = np.asarray(i_freq)
            return
        freq = i_freq = None
        if self.read_from_alternate and "get_vibrations" in self.read_from_alternate:
            freq, i_freq = self.read_from_alternate["get_vibrations"]()
        if not freq:
            base = self.vibs_path if self.vibs_path is not None else self.path
            if base is None:
                self.freq = np.zeros(0)
                self.i_freq = np.zeros(0)
                return
            log_vib = os.path.join(base, "log.vib")
            if os.path.isfile(log_vib):
                freq, i_freq = parsers.read_log_vib(log_vib)
            else:
                freq, i_freq = parsers.read_outcar_frequencies(
                    parsers.resolve_outcar_path(self.path))
        if self.truncate_freq:
            if self.state_type == GAS and self.shape is None:
                self._load_structure(verbose)
            freq = parsers.apply_frequency_floor(
                list(freq), list(i_freq), self.state_type, verbose)
        self.freq = np.array(sorted(freq, reverse=True))
        self.i_freq = np.asarray(list(i_freq), dtype=float)

    def _load_energy(self, verbose: bool):
        if self.Gelec is not None:
            return
        if self.energy_source == "datafile":
            self.Gelec = parsers.read_energy_dat(self.path)
            return
        if (self.read_from_alternate and
                "get_electronic_energy" in self.read_from_alternate):
            self.Gelec = self.read_from_alternate["get_electronic_energy"]()
            return
        if self.path is not None:
            data = parsers.read_outcar(parsers.resolve_outcar_path(self.path))
            self.Gelec = data["energy"]
        # else: stays None -- scaling states and runtime-overridden
        # descriptor states resolve their Gelec elsewhere.

    # ------------------------------------------------------------------
    # spec inputs
    @property
    def n_truncate(self) -> int:
        """Number of highest-index (smallest) modes dropped from vibrational
        sums: gas drops ``shape`` rotational placeholders, a TS without an
        identified imaginary mode drops one (reference state.py:276-283)."""
        if self.state_type == GAS:
            return int(self.shape or 0)
        if self.state_type == TS and (self.i_freq is None or len(self.i_freq) == 0):
            return 1
        return 0

    def used_frequencies(self) -> np.ndarray:
        """Frequencies (Hz, descending) that enter ZPE/vibrational sums."""
        self.load()
        if self.freq is None or self.freq.size == 0:
            return np.zeros(0)
        nfreqs = self.freq.shape[0] - self.n_truncate
        return self.freq[:max(nfreqs, 0)]

    def set_energy_modifier(self, modifier):
        self.add_to_energy = modifier

    def get_structure(self):
        """(symbols, positions [A]) of the final ionic step, read from the
        state's OUTCAR (or kept from :meth:`from_atoms`). None when the
        state has no structure source."""
        if getattr(self, "_structure", None) is not None:
            return self._structure
        if self.path is None:
            return None
        try:
            outcar = parsers.resolve_outcar_path(self.path)
            data = parsers.read_outcar(outcar)
        except (OSError, ValueError):
            return None
        return data["symbols"], data["positions"]

    def save_pdb(self, path: str = ""):
        """Write the state's structure as a .pdb file (reference
        state.py:413-434 via ase.io.write; native minimal writer here).
        Returns the file path, or None when no structure is available."""
        struct = self.get_structure()
        if struct is None:
            return None
        symbols, positions = struct
        import os
        if path:
            os.makedirs(path, exist_ok=True)
        fname = os.path.join(path, f"{self.name}.pdb")
        with open(fname, "w") as fh:
            fh.write(f"TITLE     {self.name}\n")
            for i, (sym, (x, y, z)) in enumerate(zip(symbols, positions),
                                                 start=1):
                # Fixed columns per the PDB spec: serial 7-11, name
                # 13-16, altLoc 17, resName 18-20, chain 22, resSeq
                # 23-26, x/y/z 31-54, occupancy 55-60, tempFactor
                # 61-66, element 77-78 (right-justified, so two-letter
                # species like Pd survive strict readers).
                fh.write(
                    f"HETATM{i:>5d} {sym:<4s} MOL A{1:>4d}    "
                    f"{x:8.3f}{y:8.3f}{z:8.3f}{1.0:6.2f}{0.0:6.2f}"
                    f"          {sym:>2s}\n")
            fh.write("END\n")
        return fname

    def save_png(self, path: str = ""):
        """Headless .png render of the state's structure (parity with
        the reference's ``view_atoms`` image export, state.py:444-463,
        which writes .png through ASE's renderer; the interactive viewer
        has no headless counterpart). Matplotlib 3D scatter with CPK-ish
        element colors, atoms depth-sorted and sized by covalent radius.
        Returns the file path, or None when no structure is available."""
        struct = self.get_structure()
        if struct is None:
            return None
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
        symbols, positions = struct
        pos = np.asarray(positions, dtype=float)
        colors = [ELEMENT_COLORS.get(s, "#909090") for s in symbols]
        radii = np.array([ELEMENT_RADII.get(s, 1.2) for s in symbols])
        fig = plt.figure(figsize=(4.5, 4.5))
        ax = fig.add_subplot(projection="3d")
        ax.scatter(pos[:, 0], pos[:, 1], pos[:, 2], c=colors,
                   s=(radii * 18.0) ** 2, edgecolors="black",
                   linewidths=0.4, depthshade=True)
        # Equal aspect so slabs don't look sheared.
        spans = pos.max(axis=0) - pos.min(axis=0)
        mids = (pos.max(axis=0) + pos.min(axis=0)) / 2.0
        half = max(float(spans.max()) / 2.0, 1.0)
        ax.set_xlim(mids[0] - half, mids[0] + half)
        ax.set_ylim(mids[1] - half, mids[1] + half)
        ax.set_zlim(mids[2] - half, mids[2] + half)
        ax.set_axis_off()
        ax.set_title(self.name)
        if path:
            os.makedirs(path, exist_ok=True)
        fname = os.path.join(path, f"{self.name}.png")
        fig.savefig(fname, dpi=120, bbox_inches="tight")
        plt.close(fig)
        return fname

    @property
    def is_scaling(self) -> bool:
        return False


@dataclass
class ScalingState(State):
    """Species whose electronic energy is a linear scaling relation over
    descriptor reaction energies (reference state.py:466-565).

    ``Gelec = intercept + sum_i multiplicity_i * gradient_i * dE_i`` with
    ``dE_i`` the electronic energy of descriptor reaction i. With
    ``dereference``, each term adds the descriptor reaction's summed
    reactant electronic energies. With ``use_descriptor_as_reactant``, the
    free energy is assembled from descriptor reaction free/electronic
    energies instead of this state's own partition functions.
    """

    scaling_coeffs: Optional[dict] = None
    scaling_reactions: Optional[dict] = None
    dereference: bool = False
    use_descriptor_as_reactant: bool = False

    def __post_init__(self):
        super().__post_init__()
        if self.scaling_coeffs is None or self.scaling_reactions is None:
            raise ValueError(
                f"scaling state {self.name} needs scaling_coeffs and "
                "scaling_reactions")

    def _load_energy(self, verbose: bool):
        # Electronic energy comes from the scaling relation at engine time.
        pass

    @property
    def is_scaling(self) -> bool:
        return True

    def gradients(self) -> list[float]:
        g = self.scaling_coeffs["gradient"]
        n = len(self.scaling_reactions)
        if np.isscalar(g):
            return [float(g)] * n
        g = list(g)
        if len(g) == 1:
            return [float(g[0])] * n
        assert len(g) == n, (
            f"scaling state {self.name}: {len(g)} gradients for {n} reactions")
        return [float(x) for x in g]

    def multiplicities(self) -> list[float]:
        return [float(r.get("multiplicity", 1.0))
                for r in self.scaling_reactions.values()]
