"""ModelSpec: compile the host object graph into static arrays for the engine.

The reference mutates a graph of lazy objects at solve time; here the whole
mechanism is compiled ONCE into an immutable bundle of padded numpy arrays
(the *spec*) plus a runtime :class:`Conditions` pytree. Everything that can
vary between solves -- temperature, pressure, descriptor/user energies,
electronic-energy overrides, energy noise, DRC rate multipliers, initial and
inflow compositions -- lives in ``Conditions`` so that sweeps become a
``vmap`` axis instead of object mutation (the TPU-native replacement for
reference presets.py loops / cooxvolcano.py:22-49 grid mutation).

Species ordering matches the reference legacy engine (alphabetically sorted
state names, old_system.py:66), because every golden regression number was
produced with it. Gas solution entries are in bar.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import NamedTuple, Optional

import numpy as np

from ..ops.network import REACTOR_CSTR, REACTOR_ID
from .reactions import (ADSORPTION, ARRHENIUS, DESORPTION, GHOST, Reaction,
                        UserDefinedReaction)
from .states import ADSORBATE, GAS, SURFACE, TS, ScalingState, State


class Conditions(NamedTuple):
    """Runtime inputs to the engine; a JAX pytree, vmappable over any leaf.

    Energies in eV; T in K; p in Pa; y0/inflow in legacy solution units
    (gas: bar, coverages: fraction).
    """
    T: object
    p: object
    gelec: object        # [n_s] electronic energies (plain states)
    eps: object          # [n_s] additive free-energy modifier (UQ noise etc.)
    uE_rxn: object       # [n_r] user electronic reaction energies
    uG_rxn: object       # [n_r] user free reaction energies
    uEa: object          # [n_r] user electronic barriers
    uGa: object          # [n_r] user free barriers
    u_rxn_mask: object   # [n_r] 1 where user reaction energies apply
    u_bar_mask: object   # [n_r] 1 where user barriers apply
    is_activated: object  # [n_r] 1 -> Arrhenius/activated rate expression
    kscale: object       # [n_r] multiplier on kf and kr (DRC channel)
    y0: object           # [n_s] initial / clamped-boundary composition
    inflow: object       # [n_s] CSTR inflow composition (bar)


@dataclass(frozen=True, eq=False)
class ModelSpec:
    """Immutable compiled mechanism. All arrays are numpy (static data,
    closed over by jitted functions -- they become XLA constants).

    ``eq=False``: identity hashing/equality, so a spec can key jit caches
    (field-wise dataclass equality would compare ndarrays and is
    meaningless for compiled immutable bundles anyway)."""

    # --- species ---
    snames: tuple
    state_types: tuple
    freq: np.ndarray          # [n_s, F] Hz, descending, zero-padded
    fmask: np.ndarray         # [n_s, F] modes entering vibrational sums
    mass: np.ndarray          # [n_s]
    sigma: np.ndarray         # [n_s]
    inertia: np.ndarray       # [n_s, 3]
    is_gas: np.ndarray        # [n_s]
    is_linear: np.ndarray     # [n_s]
    mix: np.ndarray           # [n_s, n_s] gasdata fraction weights
    gelec0: np.ndarray        # [n_s] default electronic energies
    add0: np.ndarray          # [n_s] baseline add_to_energy
    gvibr0: np.ndarray
    gvibr_mask: np.ndarray
    gtran0: np.ndarray
    gtran_mask: np.ndarray
    grota0: np.ndarray
    grota_mask: np.ndarray
    gfree0: np.ndarray
    gfree_mask: np.ndarray

    # --- scaling relations (electronic) ---
    # e_full = e_plain + scl_onehot^T @ (b + We @ e_plain + Ws @ e_scl + WuE @ uE)
    scl_idx: np.ndarray       # [n_sc] species index of each scaling state
    scl_b: np.ndarray         # [n_sc]
    scl_We: np.ndarray        # [n_sc, n_s]
    scl_Ws: np.ndarray        # [n_sc, n_sc]
    scl_WuE: np.ndarray       # [n_sc, n_r]

    # --- use_descriptor_as_reactant free-energy correction ---
    udar_mask: np.ndarray     # [n_s]
    udar_Ce: np.ndarray       # [n_s, n_s] applied to e_full
    udar_Cg: np.ndarray       # [n_s, n_s] applied to base free energies
    udar_CuE: np.ndarray      # [n_s, n_r]
    udar_CuG: np.ndarray      # [n_s, n_r]

    # --- reactions ---
    rnames: tuple
    reac_types: tuple
    SR: np.ndarray            # [n_r, n_s] reactant counts (energy states)
    SP: np.ndarray            # [n_r, n_s] product counts (energy states)
    ST: np.ndarray            # [n_r, n_s] TS counts (energy states)
    has_TS: np.ndarray        # [n_r]
    reversible: np.ndarray    # [n_r]
    base_reversible: np.ndarray  # [n_r] reversibility of energy-source rxn
    is_arr_type: np.ndarray   # [n_r] declared Arrhenius type
    is_ads: np.ndarray        # [n_r]
    is_des: np.ndarray        # [n_r]
    is_ghost: np.ndarray      # [n_r]
    is_user: np.ndarray       # [n_r] UserDefinedReaction (energies from cond)
    area: np.ndarray          # [n_r]
    rscaling: np.ndarray      # [n_r]
    site_density: np.ndarray  # [n_r]
    gas_mass: np.ndarray      # [n_r]
    gas_sigma: np.ndarray     # [n_r]
    gas_inertia: np.ndarray   # [n_r, 3]
    gas_polyatomic: np.ndarray  # [n_r]
    reac_idx: np.ndarray      # [n_r, A] padded with n_s
    prod_idx: np.ndarray      # [n_r, A]
    stoich: np.ndarray        # [n_s, n_r] weighted stoichiometric matrix

    # --- reactor / conservation ---
    reactor_type: int
    volume: Optional[float]
    catalyst_area: Optional[float]
    residence_time: Optional[float]
    is_adsorbate: np.ndarray  # [n_s] appears in reactions as ads/surface
    is_gas_dyn: np.ndarray    # [n_s] appears in reactions as gas
    dynamic_indices: np.ndarray
    adsorbate_indices: np.ndarray
    gas_indices: np.ndarray
    groups: np.ndarray        # [n_g, n_s] site-conservation groups
    # 'detailed_balance' (upstream convention, golden-number compatible) or
    # 'collision' (the fork's kdes rotational-partition-function model).
    desorption_model: str = "detailed_balance"

    @property
    def n_species(self) -> int:
        return len(self.snames)

    @property
    def n_reactions(self) -> int:
        return len(self.rnames)

    @property
    def has_udar(self) -> bool:
        # Static use_descriptor_as_reactant gate; the ABI's TracedSpec
        # overrides this with an always-True class attribute (its padded
        # correction matrices make the block an exact no-op).
        return bool(np.asarray(self.udar_mask).any())

    @property
    def has_gfree(self) -> bool:
        return bool(np.asarray(self.gfree_mask).any())

    def to_abi(self, species_bucket: int | None = None,
               reaction_bucket: int | None = None):
        """Lower this mechanism into its ABI shape bucket (see
        frontend/abi.py); raises AbiBucketError when it cannot fit."""
        from .abi import lower_spec
        return lower_spec(self, species_bucket=species_bucket,
                          reaction_bucket=reaction_bucket)

    def sindex(self, name: str) -> int:
        return self.snames.index(name)

    def rindex(self, name: str) -> int:
        return self.rnames.index(name)


def _species_counts(states: list, oindex, n_s: int) -> np.ndarray:
    row = np.zeros(n_s)
    for s in states:
        row[oindex(s)] += 1.0
    return row


def build_spec(states: dict, reactions: dict, reactor=None,
               reactor_params: dict | None = None,
               desorption_model: str = "detailed_balance") -> ModelSpec:
    """Compile states + reactions (+ reactor) into a :class:`ModelSpec`.

    ``states``: name -> State (all loaded or loadable); ``reactions``:
    name -> Reaction, insertion-ordered. ``reactor``: REACTOR_ID /
    REACTOR_CSTR code; ``reactor_params``: volume/catalyst_area/
    residence_time for CSTR.
    """
    # Foreign energy-states: ReactionDerivedReaction bases may live in a
    # different system (reference reaction.py:312-334 computes their
    # energetics from that donor system's State objects). They join the
    # spec as energy-only species: thermo rows, no dynamics, no
    # conservation groups. Name collisions with system states get a
    # '@base' suffix so both energy sources stay distinct.
    all_states = dict(states)
    id2name = {id(st): n for n, st in states.items()}
    for rx in reactions.values():
        es = rx.energy_states
        for s in list(es.reactants) + list(es.products) + list(es.TS or []):
            if id(s) in id2name:
                continue
            name = s.name
            k = 1
            while name in all_states:
                name = f"{s.name}@base{k}"
                k += 1
            if s.is_scaling:
                raise NotImplementedError(
                    f"foreign scaling state {s.name} referenced by "
                    f"reaction {rx.name}: scaling relations must resolve "
                    "within one system")
            all_states[name] = s
            id2name[id(s)] = name

    snames = tuple(sorted(states.keys()) +
                   sorted(n for n in all_states if n not in states))
    n_s = len(snames)
    sindex = {n: i for i, n in enumerate(snames)}

    def oindex(st):
        return sindex[id2name[id(st)]]

    rnames = tuple(reactions.keys())
    n_r = len(rnames)
    rindex = {n: i for i, n in enumerate(rnames)}

    for st in all_states.values():
        st.load()

    # ---------------- species arrays ----------------
    fcounts = [len(all_states[n].freq) if all_states[n].freq is not None
               else 0 for n in snames]
    F = max(max(fcounts), 1)
    freq = np.zeros((n_s, F))
    fmask = np.zeros((n_s, F))
    mass = np.ones(n_s)
    sig = np.ones(n_s)
    inertia = np.zeros((n_s, 3))
    is_gas = np.zeros(n_s)
    is_linear = np.zeros(n_s)
    mix = np.zeros((n_s, n_s))
    gelec0 = np.zeros(n_s)
    add0 = np.zeros(n_s)
    override = {k: (np.zeros(n_s), np.zeros(n_s))
                for k in ("gvibr", "gtran", "grota", "gfree")}
    state_types = []

    for i, name in enumerate(snames):
        st = all_states[name]
        state_types.append(st.state_type)
        if st.freq is not None and st.freq.size:
            f = np.asarray(st.freq, dtype=float).ravel()
            freq[i, :len(f)] = f
            used = len(st.used_frequencies())
            fmask[i, :used] = 1.0
        if st.mass is not None:
            mass[i] = st.mass
        if st.sigma is not None:
            sig[i] = st.sigma
        if st.inertia is not None:
            vals = np.asarray(st.inertia, dtype=float).ravel()
            inertia[i, :len(vals)] = vals
        if st.state_type == GAS:
            is_gas[i] = 1.0
            if st.shape == 2:
                is_linear[i] = 1.0
        if st.gasdata is not None:
            for frac, gstate in zip(st.gasdata["fraction"], st.gasdata["state"]):
                if isinstance(gstate, State):
                    mix[i, oindex(gstate)] += frac
                else:
                    mix[i, sindex[gstate]] += frac
        if st.Gelec is not None:
            gelec0[i] = st.Gelec
        # add_to_energy is deliberately NOT baked into the spec: energy
        # modifiers are a runtime channel (Conditions.eps) so UQ noise and
        # entropy corrections batch under vmap.
        for key, attr in (("gvibr", "Gvibr"), ("gtran", "Gtran"),
                          ("grota", "Grota"), ("gfree", "Gfree")):
            val = getattr(st, attr)
            if val is not None:
                override[key][0][i] = val
                override[key][1][i] = 1.0

    # ---------------- scaling relations ----------------
    scl_names = [n for n in snames if all_states[n].is_scaling]
    n_sc = len(scl_names)
    scl_pos = {n: j for j, n in enumerate(scl_names)}
    scl_idx = np.array([sindex[n] for n in scl_names], dtype=np.int32)
    scl_b = np.zeros(n_sc)
    scl_We = np.zeros((n_sc, n_s))
    scl_Ws = np.zeros((n_sc, n_sc))
    scl_WuE = np.zeros((n_sc, n_r))

    udar_mask = np.zeros(n_s)
    udar_Ce = np.zeros((n_s, n_s))
    udar_Cg = np.zeros((n_s, n_s))
    udar_CuE = np.zeros((n_s, n_r))
    udar_CuG = np.zeros((n_s, n_r))

    def _acc_state(j_row, We, Ws, st, coeff):
        name = id2name[id(st)]
        if name in scl_pos:
            Ws[j_row, scl_pos[name]] += coeff
        else:
            We[j_row, sindex[name]] += coeff

    for name in scl_names:
        st: ScalingState = all_states[name]
        j = scl_pos[name]
        scl_b[j] = float(st.scaling_coeffs["intercept"])
        grads = st.gradients()
        mults = st.multiplicities()
        deref = 1.0 if st.dereference else 0.0
        for (rx_cfg, grad, mult) in zip(st.scaling_reactions.values(), grads, mults):
            rx: Reaction = rx_cfg["reaction"]
            ri = rindex[rx.name]
            # electronic reaction energy term: mult * grad * dE
            if rx.is_user_defined:
                scl_WuE[j, ri] += mult * grad
            else:
                for s in rx.energy_states.products:
                    _acc_state(j, scl_We, scl_Ws, s, mult * grad)
                for s in rx.energy_states.reactants:
                    _acc_state(j, scl_We, scl_Ws, s, -mult * grad)
            # dereference term: + mult * sum(reactant Gelec)
            if deref:
                for s in rx.energy_states.reactants:
                    _acc_state(j, scl_We, scl_Ws, s, mult)

        if st.use_descriptor_as_reactant:
            i = sindex[name]
            udar_mask[i] = 1.0
            for (rx_cfg, grad, mult) in zip(st.scaling_reactions.values(),
                                            st.gradients(), st.multiplicities()):
                rx: Reaction = rx_cfg["reaction"]
                ri = rindex[rx.name]
                # correction = mult * (-refE - dE + dG + refG)
                if rx.is_user_defined:
                    udar_CuE[i, ri] += -mult
                    udar_CuG[i, ri] += mult
                else:
                    for s in rx.energy_states.products:
                        udar_Ce[i, oindex(s)] += -mult            # -dE
                        udar_Cg[i, oindex(s)] += mult             # +dG
                    for s in rx.energy_states.reactants:
                        udar_Ce[i, oindex(s)] += mult             # -dE
                        udar_Cg[i, oindex(s)] += -mult            # +dG
                if deref:
                    for s in rx.energy_states.reactants:
                        udar_Ce[i, oindex(s)] += -mult            # -refE
                        udar_Cg[i, oindex(s)] += mult             # +refG

    # ---------------- reactions ----------------
    SR = np.zeros((n_r, n_s))
    SP = np.zeros((n_r, n_s))
    ST_ = np.zeros((n_r, n_s))
    has_TS = np.zeros(n_r)
    reversible = np.zeros(n_r)
    base_reversible = np.zeros(n_r)
    is_arr_type = np.zeros(n_r)
    is_ads = np.zeros(n_r)
    is_des = np.zeros(n_r)
    is_ghost = np.zeros(n_r)
    is_user = np.zeros(n_r)
    area = np.ones(n_r)
    rscaling = np.ones(n_r)
    site_density = np.zeros(n_r)
    gas_mass = np.ones(n_r)
    gas_sigma = np.ones(n_r)
    gas_inertia = np.zeros((n_r, 3))
    gas_polyatomic = np.zeros(n_r)
    reac_types = []

    arity = 1
    for rx in reactions.values():
        arity = max(arity, len(rx.reactants), len(rx.products))
    reac_idx = np.full((n_r, arity), n_s, dtype=np.int32)
    prod_idx = np.full((n_r, arity), n_s, dtype=np.int32)
    stoich = np.zeros((n_s, n_r))

    for j, rname in enumerate(rnames):
        rx = reactions[rname]
        reac_types.append(rx.reac_type)
        es = rx.energy_states
        SR[j] = _species_counts(es.reactants, oindex, n_s)
        SP[j] = _species_counts(es.products, oindex, n_s)
        if es.TS is not None:
            ST_[j] = _species_counts(es.TS, oindex, n_s)
            has_TS[j] = 1.0
        reversible[j] = 1.0 if rx.reversible else 0.0
        base_reversible[j] = 1.0 if es.reversible else 0.0
        is_arr_type[j] = 1.0 if rx.reac_type == ARRHENIUS else 0.0
        is_ads[j] = 1.0 if rx.reac_type == ADSORPTION else 0.0
        is_des[j] = 1.0 if rx.reac_type == DESORPTION else 0.0
        is_ghost[j] = 1.0 if rx.reac_type == GHOST else 0.0
        is_user[j] = 1.0 if rx.is_user_defined else 0.0
        area[j] = rx.area if rx.area else 0.0
        rscaling[j] = rx.scaling
        site_density[j] = rx.site_density
        gs = rx.gas_species()
        if gs is not None:
            gas_mass[j] = gs.mass
            gas_sigma[j] = gs.sigma
            vals = np.asarray(gs.inertia, dtype=float).ravel()
            gas_inertia[j, :len(vals)] = vals
            gas_polyatomic[j] = 1.0 if (len(vals) == 3 and
                                        np.all(np.abs(vals) > 0.001)) else 0.0

        for a, s in enumerate(rx.reactants):
            reac_idx[j, a] = oindex(s)
        for a, s in enumerate(rx.products):
            prod_idx[j, a] = oindex(s)
        # Weighted stoichiometry (reference old_system.py:239-247): surface
        # rows get +/-scaling, gas rows additionally site_density.
        for s in rx.reactants:
            i = oindex(s)
            w = rx.scaling * (rx.site_density if s.state_type == GAS else 1.0)
            stoich[i, j] -= w
        for s in rx.products:
            i = oindex(s)
            w = rx.scaling * (rx.site_density if s.state_type == GAS else 1.0)
            stoich[i, j] += w

    # ---------------- conservation / reactor ----------------
    is_adsorbate = np.zeros(n_s)
    is_gas_dyn = np.zeros(n_s)
    for rx in reactions.values():
        for s in list(rx.reactants) + list(rx.products):
            i = oindex(s)
            if s.state_type in (ADSORBATE, SURFACE):
                is_adsorbate[i] = 1.0
            elif s.state_type == GAS:
                is_gas_dyn[i] = 1.0
    adsorbate_indices = np.flatnonzero(is_adsorbate).astype(np.int32)
    gas_indices = np.flatnonzero(is_gas_dyn).astype(np.int32)

    rtype = REACTOR_ID if reactor is None else reactor
    if rtype == REACTOR_CSTR:
        dynamic_indices = np.concatenate([adsorbate_indices, gas_indices])
    else:
        dynamic_indices = adsorbate_indices.copy()

    # Site-conservation groups: per explicit surface (adsorbates associated
    # by name prefix, reference system.py:224-247) or, absent explicit
    # surface states, one group with every surface-bound species (the
    # legacy/DMTM convention).
    # Only SYSTEM states define site groups: foreign energy-only species
    # (derived-reaction bases) never carry coverage.
    surfaces = [n for n in snames
                if n in states and states[n].state_type == SURFACE]
    groups = []
    if surfaces:
        for surf in sorted(surfaces):
            g = np.zeros(n_s)
            g[sindex[surf]] = 1.0
            for n in snames:
                if (all_states[n].state_type == ADSORBATE and n[0] == surf
                        and is_adsorbate[sindex[n]]):
                    g[sindex[n]] = 1.0
            groups.append(g)
        covered = np.sum(groups, axis=0)
        leftover = is_adsorbate * (covered == 0)
        if leftover.any():
            # Adsorbates the name-prefix rule did not associate with any
            # surface: with exactly ONE surface in the system they must be
            # its adsorbates (e.g. Butadiene-style '*'/'H*' naming, where
            # no adsorbate name starts with '*'). With multiple surfaces
            # but exactly one that matched nothing, assume (and warn, so a
            # mis-assignment is visible) that the leftovers are its
            # adsorbates; otherwise the association is ambiguous and they
            # get their own conservation group, with a warning.
            names = [snames[i] for i in np.flatnonzero(leftover)]
            lonely = [k for k, g in enumerate(groups) if g.sum() == 1.0]
            if len(surfaces) == 1:
                groups[0] = np.maximum(groups[0], leftover)
            elif len(lonely) == 1:
                import warnings
                warnings.warn(
                    f"adsorbates {names} match no surface by name prefix; "
                    f"assuming they occupy {sorted(surfaces)[lonely[0]]!r} "
                    "(the only surface with no prefix-matched adsorbates)",
                    stacklevel=2)
                groups[lonely[0]] = np.maximum(groups[lonely[0]], leftover)
            else:
                import warnings
                warnings.warn(
                    f"adsorbates {names} match no surface by name prefix "
                    f"(surfaces: {sorted(surfaces)}); giving them their own "
                    "site-conservation group", stacklevel=2)
                groups.append(leftover)
    else:
        groups.append(is_adsorbate.copy())
    groups = np.asarray(groups)

    params = reactor_params or {}
    residence_time = params.get("residence_time")
    if (rtype == REACTOR_CSTR and residence_time is None):
        residence_time = params["volume"] / params["flow_rate"]

    return ModelSpec(
        snames=snames, state_types=tuple(state_types),
        freq=freq, fmask=fmask, mass=mass, sigma=sig, inertia=inertia,
        is_gas=is_gas, is_linear=is_linear, mix=mix, gelec0=gelec0,
        add0=add0,
        gvibr0=override["gvibr"][0], gvibr_mask=override["gvibr"][1],
        gtran0=override["gtran"][0], gtran_mask=override["gtran"][1],
        grota0=override["grota"][0], grota_mask=override["grota"][1],
        gfree0=override["gfree"][0], gfree_mask=override["gfree"][1],
        scl_idx=scl_idx, scl_b=scl_b, scl_We=scl_We, scl_Ws=scl_Ws,
        scl_WuE=scl_WuE,
        udar_mask=udar_mask, udar_Ce=udar_Ce, udar_Cg=udar_Cg,
        udar_CuE=udar_CuE, udar_CuG=udar_CuG,
        rnames=rnames, reac_types=tuple(reac_types),
        SR=SR, SP=SP, ST=ST_, has_TS=has_TS, reversible=reversible,
        base_reversible=base_reversible,
        is_arr_type=is_arr_type, is_ads=is_ads, is_des=is_des,
        is_ghost=is_ghost, is_user=is_user, area=area, rscaling=rscaling,
        site_density=site_density, gas_mass=gas_mass, gas_sigma=gas_sigma,
        gas_inertia=gas_inertia, gas_polyatomic=gas_polyatomic,
        reac_idx=reac_idx, prod_idx=prod_idx, stoich=stoich,
        reactor_type=rtype,
        volume=params.get("volume"),
        catalyst_area=params.get("catalyst_area"),
        residence_time=residence_time,
        is_adsorbate=is_adsorbate, is_gas_dyn=is_gas_dyn,
        dynamic_indices=dynamic_indices.astype(np.int32),
        adsorbate_indices=adsorbate_indices, gas_indices=gas_indices,
        groups=groups, desorption_model=desorption_model,
    )


def default_conditions(spec: ModelSpec, reactions: dict, T: float, p: float,
                       start_state: dict | None = None,
                       inflow_state: dict | None = None,
                       gelec_overrides: dict | None = None,
                       eps: dict | np.ndarray | None = None,
                       kscale: np.ndarray | None = None) -> Conditions:
    """Assemble a :class:`Conditions` pytree from host-side objects.

    Re-reads user energies from the (possibly mutated) reaction objects --
    the bridge between the reference's mutate-and-solve style and the
    engine's functional style.
    """
    n_s, n_r = spec.n_species, spec.n_reactions
    uE = np.zeros(n_r)
    uG = np.zeros(n_r)
    uEa = np.zeros(n_r)
    uGa = np.zeros(n_r)
    u_rxn_mask = np.zeros(n_r)
    u_bar_mask = np.zeros(n_r)
    is_activated = np.zeros(n_r)

    for j, rname in enumerate(spec.rnames):
        rx = reactions[rname]
        if isinstance(rx, UserDefinedReaction):
            vals = rx.resolved_user_energies(T)
            if vals["has_rxn_energy"]:
                uE[j] = vals["dErxn"]
                uG[j] = vals["dGrxn"]
                u_rxn_mask[j] = 1.0
            uEa[j] = vals["dEa_fwd"]
            uGa[j] = vals["dGa_fwd"]
            if vals["has_barrier"]:
                u_bar_mask[j] = 1.0
            # Reference dispatch (reaction.py:121): Arrhenius expression if
            # declared Arrhenius OR the resolved forward barrier is truthy.
            is_activated[j] = 1.0 if (spec.is_arr_type[j] or
                                      vals["dGa_fwd"]) else 0.0
        else:
            is_activated[j] = 1.0 if (spec.is_arr_type[j] or
                                      spec.has_TS[j]) else 0.0

    gelec = spec.gelec0.copy()
    if gelec_overrides:
        for name, val in gelec_overrides.items():
            gelec[spec.sindex(name)] = val

    eps_vec = np.zeros(n_s)
    if isinstance(eps, dict):
        for name, val in eps.items():
            eps_vec[spec.sindex(name)] = val
    elif eps is not None:
        eps_vec = np.asarray(eps, dtype=float)

    y0 = np.zeros(n_s)
    for name, val in (start_state or {}).items():
        y0[spec.sindex(name)] = val
    inflow = np.zeros(n_s)
    for name, val in (inflow_state or {}).items():
        inflow[spec.sindex(name)] = val

    return Conditions(
        T=float(T), p=float(p), gelec=gelec, eps=eps_vec,
        uE_rxn=uE, uG_rxn=uG, uEa=uEa, uGa=uGa,
        u_rxn_mask=u_rxn_mask, u_bar_mask=u_bar_mask,
        is_activated=is_activated,
        kscale=(np.ones(n_r) if kscale is None
                else np.asarray(kscale, dtype=float)),
        y0=y0, inflow=inflow,
    )
