"""Host-side parsers for DFT artifacts.

The reference delegates OUTCAR reading to ASE (reference state.py:92) and
parses ``.dat``/``log.vib`` files inline (reference state.py:107-211).
ASE is not a dependency here: everything is parsed natively so the frontend
works in a minimal environment. Output conventions match the reference:

- energies in eV (VASP ``free  energy   TOTEN``, force-consistent)
- frequencies in Hz
- masses in amu (standard atomic weights, as ASE's defaults)
- moments of inertia in amu*A^2, principal values sorted ascending
"""

from __future__ import annotations

import os
import re

import numpy as np

from ..constants import FREQ_FLOOR_HZ, JtoeV, h

# Standard atomic weights (IUPAC abridged), indexed by symbol. These are the
# same defaults ASE assigns to Atoms objects, which the reference relies on
# for total masses and moments of inertia.
ATOMIC_MASSES = {
    "H": 1.008, "He": 4.002602, "Li": 6.94, "Be": 9.0121831, "B": 10.81,
    "C": 12.011, "N": 14.007, "O": 15.999, "F": 18.998403163, "Ne": 20.1797,
    "Na": 22.98976928, "Mg": 24.305, "Al": 26.9815385, "Si": 28.085,
    "P": 30.973761998, "S": 32.06, "Cl": 35.45, "Ar": 39.948, "K": 39.0983,
    "Ca": 40.078, "Sc": 44.955908, "Ti": 47.867, "V": 50.9415, "Cr": 51.9961,
    "Mn": 54.938044, "Fe": 55.845, "Co": 58.933194, "Ni": 58.6934,
    "Cu": 63.546, "Zn": 65.38, "Ga": 69.723, "Ge": 72.630, "As": 74.921595,
    "Se": 78.971, "Br": 79.904, "Kr": 83.798, "Rb": 85.4678, "Sr": 87.62,
    "Y": 88.90584, "Zr": 91.224, "Nb": 92.90637, "Mo": 95.95, "Tc": 97.90721,
    "Ru": 101.07, "Rh": 102.90550, "Pd": 106.42, "Ag": 107.8682,
    "Cd": 112.414, "In": 114.818, "Sn": 118.710, "Sb": 121.760, "Te": 127.60,
    "I": 126.90447, "Xe": 131.293, "Cs": 132.90545196, "Ba": 137.327,
    "La": 138.90547, "Ce": 140.116, "Pr": 140.90766, "Nd": 144.242,
    "Sm": 150.36, "Eu": 151.964, "Gd": 157.25, "Tb": 158.92535,
    "Dy": 162.500, "Ho": 164.93033, "Er": 167.259, "Tm": 168.93422,
    "Yb": 173.054, "Lu": 174.9668, "Hf": 178.49, "Ta": 180.94788,
    "W": 183.84, "Re": 186.207, "Os": 190.23, "Ir": 192.217, "Pt": 195.084,
    "Au": 196.966569, "Hg": 200.592, "Tl": 204.38, "Pb": 207.2,
    "Bi": 208.98040, "Th": 232.0377, "U": 238.02891,
}


def read_energy_dat(path: str) -> float:
    """Read an electronic energy in eV from a one-line ``*_energy.dat`` file.

    Format: ``<float> eV`` (reference state.py:253-256).
    """
    with open(path) as fh:
        first = fh.readlines()[0]
    return float(first.split("eV")[0])


def read_frequency_dat(path: str) -> tuple[np.ndarray, np.ndarray]:
    """Read real/imaginary frequencies (Hz) from a ``*_frequencies.dat`` file.

    Lines look like ``0 f = 7.05e12 Hz`` (real) or ``3 f/i = ... Hz``
    (imaginary); a '/' marks imaginary modes (reference state.py:112-120).
    """
    freq, i_freq = [], []
    with open(path) as fh:
        for line in fh:
            if "=" not in line or "Hz" not in line:
                continue
            value = float(line.split("=")[1].split("Hz")[0])
            (i_freq if "/" in line else freq).append(value)
    return np.asarray(freq, dtype=float), np.asarray(i_freq, dtype=float)


def read_log_vib(path: str) -> tuple[list[float], list[float]]:
    """Parse an ASE vibration summary (``log.vib``) into Hz.

    The table's meV column is converted via f = meV*1e-3/(h*JtoeV); entries
    containing 'i' are imaginary modes (reference state.py:137-156).
    """
    with open(path) as fh:
        lines = fh.readlines()
    initat = 0
    endat = 0
    for lind, line in enumerate(lines):
        if "#" in line:
            initat = lind + 2
            endat = 0
        if lind > initat and not endat and "---" in line:
            endat = lind - 1
    freq = [float(line.strip().split()[1]) * 1e-3 / (h * JtoeV)
            for line in lines[initat:endat + 1] if "i" not in line]
    i_freq = [float(line.strip().split()[1].split("i")[0]) * 1e-3 / (h * JtoeV)
              for line in lines[initat:endat + 1] if "i" in line]
    return freq, i_freq


_POTCAR_RE = re.compile(r"^\s*POTCAR:\s+\S+\s+(\S+)")


def _outcar_symbols(lines: list[str]) -> list[str]:
    """Extract the per-atom chemical symbols from OUTCAR header lines."""
    species: list[str] = []
    counts: list[int] = []
    for line in lines:
        m = _POTCAR_RE.match(line)
        if m:
            sym = m.group(1).split("_")[0]
            species.append(sym)
        if "ions per type" in line:
            counts = [int(tok) for tok in line.split("=")[1].split()]
            break
    # The POTCAR header block lists each pseudopotential twice (once in the
    # summary, once per-species detail); keep the first n_types entries.
    if counts:
        species = species[: len(counts)]
    symbols: list[str] = []
    for sym, cnt in zip(species, counts):
        symbols += [sym] * cnt
    return symbols


def read_outcar(path: str) -> dict:
    """Parse a VASP OUTCAR: final force-consistent energy, masses, geometry.

    Mirrors what the reference obtains through
    ``ase.io.read(..., format='vasp-out')`` + ``get_potential_energy
    (force_consistent=True)`` + ``get_masses`` + ``get_moments_of_inertia``
    (reference state.py:77-105).

    Returns dict with keys: energy (eV), symbols, masses (amu per atom),
    mass (total amu), positions (A, final ionic step), inertia
    (principal moments, amu*A^2, ascending).
    """
    with open(path) as fh:
        lines = fh.readlines()

    symbols = _outcar_symbols(lines)
    masses = np.array([ATOMIC_MASSES[s] for s in symbols], dtype=float)

    energy = None
    positions = None
    i = 0
    n = len(lines)
    while i < n:
        line = lines[i]
        if "free  energy   TOTEN" in line or "free energy    TOTEN" in line:
            energy = float(line.split("=")[1].split("eV")[0])
        if line.lstrip().startswith("POSITION"):
            block = []
            j = i + 2
            while j < n and "----" not in lines[j]:
                toks = lines[j].split()
                if len(toks) >= 3:
                    block.append([float(t) for t in toks[:3]])
                j += 1
            positions = np.asarray(block, dtype=float)
            i = j
        i += 1

    if energy is None:
        raise ValueError(f"No TOTEN energy found in OUTCAR: {path}")
    if positions is None or len(positions) != len(symbols):
        raise ValueError(f"Could not read final positions from OUTCAR: {path}")

    return {
        "energy": energy,
        "symbols": symbols,
        "masses": masses,
        "mass": float(masses.sum()),
        "positions": positions,
        "inertia": moments_of_inertia(positions, masses),
    }


def moments_of_inertia(positions: np.ndarray, masses: np.ndarray) -> np.ndarray:
    """Principal moments of inertia (amu*A^2) about the center of mass.

    Eigenvalues sorted ascending, matching ASE's
    ``Atoms.get_moments_of_inertia``.
    """
    com = (masses[:, None] * positions).sum(axis=0) / masses.sum()
    rel = positions - com
    x, y, z = rel[:, 0], rel[:, 1], rel[:, 2]
    ixx = (masses * (y**2 + z**2)).sum()
    iyy = (masses * (x**2 + z**2)).sum()
    izz = (masses * (x**2 + y**2)).sum()
    ixy = -(masses * x * y).sum()
    ixz = -(masses * x * z).sum()
    iyz = -(masses * y * z).sum()
    tensor = np.array([[ixx, ixy, ixz], [ixy, iyy, iyz], [ixz, iyz, izz]])
    return np.linalg.eigvalsh(tensor)


def read_outcar_frequencies(path: str) -> tuple[list[float], list[float]]:
    """Parse vibrational frequencies (Hz) from OUTCAR ``THz`` lines.

    Keeps only the first copy of the frequency table (VASP repeats it), as
    the reference does (state.py:158-182). Column -8 is the value in THz.
    """
    freq: list[float] = []
    i_freq: list[float] = []
    firstcopy = 0
    index = -8
    with open(path) as fh:
        for line in fh:
            data = line.split()
            if "THz" in data:
                if (firstcopy + 1) == int(data[0]):
                    f_hz = float(data[index]) * 1.0e12
                    if "f/i=" not in data and "f/i" not in data:
                        freq.append(f_hz)
                    else:
                        i_freq.append(f_hz)
                    firstcopy = int(data[0])
                else:
                    break
    return freq, i_freq


def apply_frequency_floor(freq: list[float], i_freq: list[float],
                          state_type: str | None,
                          verbose: bool = False) -> list[float]:
    """Floor small parsed frequencies at 12.4 meV and pad missing DOF.

    Applied ONLY to frequencies parsed from log.vib/OUTCAR, never to
    datafile/inputfile frequencies (reference state.py:183-203 runs in that
    branch only) -- golden numbers depend on this asymmetry.
    """
    freq = [FREQ_FLOOR_HZ if (f * h * JtoeV * 1e3) < 12.4 else f for f in freq]
    n_freq = len(freq)
    n_dof = len(freq) + len(i_freq)
    if state_type == "gas":
        n_dof -= 3
    if n_freq < n_dof:
        if verbose:
            print(f"Padding {n_dof - n_freq} frequencies at 12.4 meV")
        freq = freq + [FREQ_FLOOR_HZ] * (n_dof - n_freq)
    return freq


def resolve_outcar_path(path: str) -> str:
    """A state's ``path`` may be a directory containing OUTCAR or the file
    itself (reference state.py:88-91)."""
    cand = os.path.join(path, "OUTCAR")
    return cand if os.path.isfile(cand) else path
