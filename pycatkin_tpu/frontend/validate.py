"""Input-validation gate: reject broken mechanisms before they compile.

Malformed specs -- non-finite energies, stoichiometrically unbalanced
reactions, orphan species, missing frequencies -- otherwise sail
straight into the jitted solvers and come back out as per-lane NaNs
with no attribution (the quarantine layer in ``parallel/batch.py``
then catches them, but a fault that is knowable at LOAD time should
never reach a device). This module runs host-side checks over a
:class:`~pycatkin_tpu.api.system.System`'s in-memory states, reactions
and parameters and collects every finding into a structured
:class:`ValidationReport` whose issues carry JSON-pointer-style
locations (``/reactions/CO_ox/reactants``) that map 1:1 onto the
input-file schema.

Severity model: an **error** is a spec the solvers cannot give a
meaningful answer for (non-finite energy, unbalanced stoichiometry,
non-physical T/p, negative inflow); a **warning** is a spec that will
run but probably not the one the user meant (orphan species, missing
adsorbate/TS frequencies, absurd-magnitude energies).

Gate modes (the ``PYCATKIN_VALIDATE`` environment variable, or
``System.build(strict=...)``):

- ``strict``: errors raise :class:`ValidationError`; warnings warn.
- ``warn`` (default): every issue becomes a ``UserWarning``.
- ``off``: the gate is skipped entirely.

The checks never trigger DFT-artifact loading (``State.load``): only
values already in memory are judged, so validating a path-based input
stays I/O-free and cannot itself raise a parser error.
"""

from __future__ import annotations

import math
import os
import warnings
from dataclasses import dataclass, field

from .reactions import GHOST
from .states import ADSORBATE, GAS, SURFACE, TS

# |Gelec| beyond this (eV) is almost certainly a unit mistake
# (Hartree/kJ/mol pasted into an eV field); finite, so it only warns.
ABSURD_ENERGY_EV = 1.0e4
# T above this (K) warns; <= 0 or non-finite errors.
ABSURD_T_K = 1.0e4
# p above this (Pa) warns (1e10 Pa = 100 GPa).
ABSURD_P_PA = 1.0e10
# Relative mass-imbalance tolerance per reaction.
MASS_BALANCE_RTOL = 1.0e-6

VALIDATE_ENV = "PYCATKIN_VALIDATE"
_MODES = ("strict", "warn", "off")


@dataclass(frozen=True)
class ValidationIssue:
    """One finding: severity ('error'|'warning'), JSON-pointer-style
    location into the input schema, and a human-readable message."""
    severity: str
    location: str
    message: str

    def __str__(self):
        return f"[{self.severity}] {self.location}: {self.message}"


@dataclass
class ValidationReport:
    """Structured result of the validation gate.

    ``source`` names the input file (or None for in-memory systems);
    ``issues`` accumulate in check order. ``ok`` is True when no
    issue is an error (warnings never fail a build)."""
    source: str | None = None
    issues: list = field(default_factory=list)

    def error(self, location: str, message: str):
        self.issues.append(ValidationIssue("error", location, message))

    def warn(self, location: str, message: str):
        self.issues.append(ValidationIssue("warning", location, message))

    @property
    def errors(self) -> list:
        return [i for i in self.issues if i.severity == "error"]

    @property
    def warnings(self) -> list:
        return [i for i in self.issues if i.severity == "warning"]

    @property
    def ok(self) -> bool:
        return not self.errors

    def __str__(self):
        src = f" for {self.source}" if self.source else ""
        if not self.issues:
            return f"validation report{src}: clean"
        lines = [f"validation report{src}: {len(self.errors)} error(s), "
                 f"{len(self.warnings)} warning(s)"]
        lines += [f"  {i}" for i in self.issues]
        return "\n".join(lines)

    def raise_for_errors(self):
        if self.errors:
            raise ValidationError(self)
        return self

    def emit(self, mode: str):
        """Apply gate semantics: 'strict' raises on errors (and warns
        the warnings), 'warn' warns everything, 'off' does nothing.
        Returns the report for chaining."""
        if mode not in _MODES:
            raise ValueError(
                f"validation mode must be one of {_MODES}, got {mode!r}")
        if mode == "off":
            return self
        if mode == "strict":
            self.raise_for_errors()
        for issue in self.issues:
            warnings.warn(f"{self.source or 'mechanism'}: {issue}",
                          UserWarning, stacklevel=3)
        return self


class ValidationError(RuntimeError):
    """Strict-mode gate failure; carries the full report as
    ``.report``."""

    def __init__(self, report: ValidationReport):
        super().__init__(str(report))
        self.report = report


def validation_mode(default: str = "warn") -> str:
    """Resolve the gate mode from :data:`VALIDATE_ENV` (default
    'warn'). An unrecognized value raises rather than silently
    disabling the gate."""
    mode = os.environ.get(VALIDATE_ENV, "").strip().lower() or default
    if mode not in _MODES:
        raise ValueError(
            f"{VALIDATE_ENV} must be one of {_MODES}, got {mode!r}")
    return mode


def _finite(value) -> bool:
    try:
        return math.isfinite(float(value))
    except (TypeError, ValueError):
        return False


def _check_energy(report, location: str, value):
    """Non-finite scalar energies error; absurd magnitudes warn.
    Per-temperature dict values are checked entry-wise."""
    if value is None:
        return
    if isinstance(value, dict):
        for k, v in value.items():
            _check_energy(report, f"{location}/{k}", v)
        return
    if not _finite(value):
        report.error(location, f"non-finite energy {value!r}")
    elif abs(float(value)) > ABSURD_ENERGY_EV:
        report.warn(location,
                    f"energy {float(value):g} eV is absurdly large -- "
                    f"wrong units?")


def _surface_sites(states) -> int:
    """Number of surface sites a reaction side occupies: each bare
    surface or adsorbate state holds one site; gas and TS hold none."""
    return sum(1 for s in states
               if s.state_type in (SURFACE, ADSORBATE))


def _reaction_mass(states):
    """Total mass of a reaction side, or None when any participant's
    mass is unknown in memory (path-based states resolve lazily; the
    gate never triggers loading)."""
    total = 0.0
    for s in states:
        if s.mass is None or not _finite(s.mass):
            return None
        total += float(s.mass)
    return total


def validate_system(system, source: str | None = None) -> ValidationReport:
    """Run every check over a :class:`System`'s host-side objects.

    Pure inspection: no spec build, no DFT-artifact loading, no device
    work. Returns the :class:`ValidationReport`; callers apply gate
    semantics via :meth:`ValidationReport.emit` or
    :meth:`ValidationReport.raise_for_errors`.
    """
    report = ValidationReport(source=source)
    states = dict(getattr(system, "states", {}) or {})
    reactions = dict(getattr(system, "reactions", {}) or {})
    params = dict(getattr(system, "params", {}) or {})

    # -- states: energies, frequencies ---------------------------------
    for name, st in states.items():
        _check_energy(report, f"/states/{name}/Gelec", st.Gelec)
        for attr in ("Gzpe", "Gvibr", "Gtran", "Grota", "Gfree",
                     "add_to_energy"):
            _check_energy(report, f"/states/{name}/{attr}",
                          getattr(st, attr, None))
        # Adsorbates/TS with neither in-memory frequencies nor any
        # lazy source (path / vibs_path / fixed Gvibr or Gfree) have
        # no vibrational entropy at all -- legal, rarely intended.
        if (st.state_type in (ADSORBATE, TS)
                and not getattr(st, "is_scaling", False)
                and st.freq is None and st.path is None
                and st.vibs_path is None and st.Gvibr is None
                and st.Gfree is None):
            report.warn(f"/states/{name}/freq",
                        f"{st.state_type} state has no vibrational "
                        f"frequencies and no source to load them from")

    # -- reactions: balance, dangling references, user energies --------
    referenced: set = set()
    for rname, rx in reactions.items():
        reac = list(getattr(rx, "reactants", []) or [])
        prod = list(getattr(rx, "products", []) or [])
        ts = list(getattr(rx, "TS", None) or [])
        for s in reac + prod + ts:
            referenced.add(s.name)
        for attr in ("dErxn_user", "dGrxn_user", "dEa_fwd_user",
                     "dGa_fwd_user", "dEa_rev_user", "dGa_rev_user"):
            _check_energy(report, f"/reactions/{rname}/{attr}",
                          getattr(rx, attr, None))
        if rx.reac_type == GHOST:
            # Ghost steps are bookkeeping devices, exempt from
            # stoichiometric balance by construction.
            continue
        if not reac or not prod:
            report.error(f"/reactions/{rname}",
                         "reaction must have at least one reactant and "
                         "one product")
            continue
        # Site balance: mean-field kinetics conserve surface sites in
        # every elementary step; an imbalance means a missing/extra
        # surface species in the input.
        ns_r, ns_p = _surface_sites(reac), _surface_sites(prod)
        if ns_r != ns_p:
            report.error(
                f"/reactions/{rname}",
                f"surface-site imbalance: reactants occupy {ns_r} "
                f"site(s) ({[s.name for s in reac]}), products occupy "
                f"{ns_p} ({[s.name for s in prod]})")
        # Mass balance, where every participant's mass is known
        # in memory (adsorbate masses usually resolve lazily -> skip).
        m_r, m_p = _reaction_mass(reac), _reaction_mass(prod)
        if m_r is not None and m_p is not None:
            tol = MASS_BALANCE_RTOL * max(m_r, m_p, 1.0)
            if abs(m_r - m_p) > tol:
                report.error(
                    f"/reactions/{rname}",
                    f"mass imbalance: reactants {m_r:g} amu vs "
                    f"products {m_p:g} amu")

    # -- orphan species ------------------------------------------------
    if reactions:
        for name, st in states.items():
            if st.state_type in (SURFACE, TS):
                continue          # sites/TS legitimately appear nowhere
            if getattr(st, "is_scaling", False):
                continue          # descriptors live in scaling relations
            if name not in referenced:
                report.warn(f"/states/{name}",
                            "species appears in no reaction (orphan)")

    # -- conditions: T, p ----------------------------------------------
    T = params.get("temperature")
    if T is not None:
        if not _finite(T) or float(T) <= 0.0:
            report.error("/system/T",
                         f"temperature must be finite and positive, "
                         f"got {T!r}")
        elif float(T) > ABSURD_T_K:
            report.warn("/system/T",
                        f"temperature {float(T):g} K is absurdly high")
    p = params.get("pressure")
    if p is not None:
        if not _finite(p) or float(p) <= 0.0:
            report.error("/system/p",
                         f"pressure must be finite and positive, "
                         f"got {p!r}")
        elif float(p) > ABSURD_P_PA:
            report.warn("/system/p",
                        f"pressure {float(p):g} Pa is absurdly high")

    # -- start/inflow compositions -------------------------------------
    for key in ("start_state", "inflow_state"):
        comp = params.get(key) or {}
        for name, frac in comp.items():
            loc = f"/system/{key}/{name}"
            if name not in states:
                report.error(loc, "references an unknown state")
                continue
            if not _finite(frac) or float(frac) < 0.0:
                report.error(loc,
                             f"fraction must be finite and >= 0, "
                             f"got {frac!r}")
            if key == "inflow_state" and \
                    states[name].state_type != GAS:
                report.error(loc,
                             "only gas states can comprise the inflow")
    return report


def check_abi_headroom(spec, report: ValidationReport | None = None
                       ) -> ValidationReport:
    """Warn when a BUILT mechanism lands within the boundary margin
    (frontend/abi.py ``_BOUNDARY_MARGIN``, 5%) of its ABI shape
    bucket's edge. A mechanism hugging the boundary is one species or
    a few reactions away from spilling into the next bucket -- which
    under ``PYCATKIN_ABI=1`` means new program identities and the full
    compile/prewarm wall again, exactly the cost the ABI exists to
    amortize. Runs on a :class:`~pycatkin_tpu.frontend.spec.ModelSpec`
    (the counts the bucket selector sees), unlike the host-object
    checks above; :func:`pycatkin_tpu.frontend.abi.lower_spec` emits
    these warnings once per mechanism."""
    from .abi import (_BOUNDARY_MARGIN, REACTION_BUCKETS, SPECIES_BUCKETS,
                      _bucket_for)
    if report is None:
        report = ValidationReport()
    pct = int(round(_BOUNDARY_MARGIN * 100))
    for loc, n, buckets, what in (
            ("/abi/species", spec.n_species + 1, SPECIES_BUCKETS,
             "species (incl. the reserved pad slot)"),
            ("/abi/reactions", spec.n_reactions, REACTION_BUCKETS,
             "reactions")):
        b = _bucket_for(n, buckets)
        if b is None:
            continue            # unfittable: lowering raises, not warns
        if n > b * (1.0 - _BOUNDARY_MARGIN):
            report.warn(
                loc,
                f"{n} {what} is within {pct}% of the ABI bucket "
                f"boundary {b}; slight mechanism growth spills into "
                f"the next bucket (padded shape {loc.rsplit('/', 1)[-1]}"
                f"={b} -> {_next_bucket(b, buckets)}) and repays the "
                f"full compile/prewarm wall")
    return report


def _next_bucket(b: int, buckets) -> object:
    larger = [x for x in buckets if x > b]
    return min(larger) if larger else "unfittable"
