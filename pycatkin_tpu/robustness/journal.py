"""Append-only sweep journal: checkpoint/resume for chunked sweeps.

A long volcano/uncertainty sweep that dies N-1 chunks in (process kill,
exhausted retries, device loss) today forfeits every already-solved
chunk. The journal makes chunk completion durable: after each chunk the
runner appends one manifest record (chunk id, lane range, status,
per-lane failure count, degradation events) to ``journal.jsonl`` and
writes the chunk's result arrays to an ``.npz`` next to it (via
utils/io -- the same lossless checkpoint format the dispatcher uses).
A ``--resume`` run replays the manifest, verifies the conditions
fingerprint, loads the completed chunks' arrays bit-for-bit and
re-dispatches ONLY missing or failed chunks.

Crash safety: manifest lines are flushed+fsynced per record and a
truncated final line (kill mid-write) is ignored on replay; chunk
``.npz`` files are written to a temp name and atomically renamed, so a
manifest record never points at a partial file.

Manifest schema (one JSON object per line):
  {"kind": "header", "fingerprint": ..., "n_lanes": ..., "chunk": ...,
   "version": 1}
  {"kind": "chunk", "chunk_id": ..., "start": ..., "stop": ...,
   "status": "done"|"salvaged", "npz": "chunk_00003.npz",
   "n_failed": ..., "events": [...]}

Later records for the same chunk_id supersede earlier ones, so a
resumed run can overwrite a previously salvaged chunk with a clean
re-solve by simply appending.
"""

from __future__ import annotations

import hashlib
import os

from ..utils.io import (append_json_line, atomic_save_results,
                        load_results, read_json_lines)

MANIFEST = "journal.jsonl"
_VERSION = 1

# Statuses that carry a usable result payload; "salvaged" chunks are
# deliberately NOT reused on resume -- a restart is the chance to
# re-solve what degraded.
_COMPLETE = ("done",)


class JournalMismatchError(RuntimeError):
    """Resume attempted against a journal written for different
    conditions/options (fingerprint mismatch)."""


def conditions_fingerprint(conds, extra=None) -> str:
    """Order-stable content hash of a Conditions pytree (dtype, shape
    and bytes of every leaf) plus any extra context (solver options,
    chunk size, ...) -- the resume guard that a journal is only ever
    replayed against the sweep that wrote it."""
    import jax
    import numpy as np

    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(conds):
        a = np.ascontiguousarray(np.asarray(leaf))
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    if extra is not None:
        h.update(repr(extra).encode())
    return h.hexdigest()[:32]


class SweepJournal:
    """One sweep's on-disk journal (a directory).

    Opening modes:
    - fresh (``resume=False``): the directory must not already hold a
      manifest (refuses to silently mix two sweeps' records).
    - resume (``resume=True``): replays an existing manifest; when
      ``fingerprint`` is given it must match the header.
    """

    def __init__(self, path: str, fingerprint: str | None = None,
                 n_lanes: int | None = None, chunk: int | None = None,
                 resume: bool = False):
        self.path = str(path)
        os.makedirs(self.path, exist_ok=True)
        self.manifest_path = os.path.join(self.path, MANIFEST)
        self._records = []
        if os.path.exists(self.manifest_path):
            if not resume:
                raise RuntimeError(
                    f"journal already exists at {self.manifest_path}; "
                    "pass resume=True to continue it (or use a fresh "
                    "directory)")
            self._records = read_json_lines(self.manifest_path,
                                            tolerate_torn_tail=True)
        header = next((r for r in self._records
                       if r.get("kind") == "header"), None)
        if header is None:
            from ..obs.manifest import run_manifest
            # The run manifest makes a journal self-describing (what
            # code/backend/knobs wrote it). Resume ignores unknown
            # header keys, so old journals stay replayable.
            header = {"kind": "header", "version": _VERSION,
                      "fingerprint": fingerprint, "n_lanes": n_lanes,
                      "chunk": chunk, "manifest": run_manifest()}
            append_json_line(self.manifest_path, header)
            self._records.append(header)
        elif fingerprint is not None and \
                header.get("fingerprint") not in (None, fingerprint):
            raise JournalMismatchError(
                f"journal at {self.path} was written for fingerprint "
                f"{header.get('fingerprint')!r}, not {fingerprint!r}: "
                "the conditions/options differ from the original sweep")
        self.header = header

    # -----------------------------------------------------------------
    def completed(self) -> dict:
        """{chunk_id: latest manifest record} for chunks whose latest
        record carries a loadable result ('done')."""
        latest: dict[int, dict] = {}
        for rec in self._records:
            if rec.get("kind") == "chunk":
                latest[int(rec["chunk_id"])] = rec
        return {cid: rec for cid, rec in latest.items()
                if rec.get("status") in _COMPLETE
                and os.path.exists(os.path.join(self.path, rec["npz"]))}

    def chunk_records(self) -> list[dict]:
        return [r for r in self._records if r.get("kind") == "chunk"]

    def load_chunk(self, rec: dict) -> dict:
        """Result arrays of a completed chunk record, bit-identical to
        what the original run computed (lossless .npz round trip)."""
        return load_results(os.path.join(self.path, rec["npz"]))

    def record_chunk(self, chunk_id: int, start: int, stop: int,
                     status: str, arrays: dict | None = None,
                     events=(), n_failed: int = 0) -> dict:
        """Durably record one finished (or salvaged) chunk: arrays to
        an atomically-renamed .npz, then the manifest line."""
        rec = {"kind": "chunk", "chunk_id": int(chunk_id),
               "start": int(start), "stop": int(stop),
               "status": str(status), "n_failed": int(n_failed),
               "events": list(events)}
        if arrays is not None:
            fname = f"chunk_{chunk_id:05d}.npz"
            # Write-then-rename (plus the PYCATKIN_JOURNAL_FSYNC
            # durability knob) so this manifest line can never point
            # at a torn payload, even for a worker killed mid-write.
            atomic_save_results(os.path.join(self.path, fname), arrays)
            rec["npz"] = fname
        append_json_line(self.manifest_path, rec)
        self._records.append(rec)
        return rec
