"""Elastic sweep scheduler: lease-based work queue + worker supervision.

ROADMAP item 6: the static dispatcher (parallel/dispatch.py) cuts the
lane grid into fixed blocks up front and every worker must survive to
the merge -- one preempted process stalls or fails the whole sweep.
This module makes worker death a *requeue*, not a failure, the same
preemption-tolerance shape a long stiff-kinetics fleet needs (a lost
shard must cost one chunk of work, not hours of sweep).

The coordination substrate is the filesystem, deliberately: leases are
files, so the protocol is process- and host-agnostic (any worker that
can see the work directory can join, steal, and complete work -- NFS
across hosts works the same as one laptop), and every state transition
is a crash-atomic primitive:

  claim    write tmp record, ``os.link(tmp, lease)`` -- an atomic
           first-wins create; losers see ``FileExistsError``.
  renew    heartbeat thread rewrites the lease (tmp + ``os.replace``)
           every ``heartbeat_s``; a renewal that finds the lease gone
           or re-owned reports the loss (fencing) instead of writing.
  steal    a lease whose deadline passed is ``os.unlink``ed (exactly
           one racer wins; the rest get ``FileNotFoundError``) and
           then re-claimed through the normal claim path.
  done     result ``.npz`` written atomically (utils.io
           ``atomic_save_results``), then a done record created
           ``O_EXCL`` -- first completion wins.

The one unfenceable race -- a stalled owner renewing over a thief's
fresh lease -- is benign by construction: both run the identical lane
span through the same deterministic sweep, result writes are atomic
with bit-identical payloads, and the ``O_EXCL`` done record dedupes
the completion. Duplicate work is wasted, never wrong.

Supervision: :func:`run_elastic` spawns N worker subprocesses, polls
them, classifies every exit through the retry taxonomy
(``utils.retry.classify_worker_exit``) and restarts dead workers with
bounded full-jitter backoff (``utils.retry.backoff_delay``). A worker
that dies *holding a valid lease* implicates its task: after
``max_kills`` such deaths the task is bisected and requeued (children
inherit a fresh kill budget, so a data-dependent crash follows the
poisoned lanes down), until the span reaches ``min_chunk`` -- then the
span is quarantined through the existing ladder rung
(``ladder.record_quarantine``) and the sweep keeps going. An expired
lease whose owner is still alive (a stalled heartbeat) gets the owner
killed and restarted; the lease is requeued for stealing either way.

Chaos harness: the fault kinds ``worker-crash`` / ``heartbeat-stall``
/ ``slow-worker`` (robustness/faults.py) fire at the worker sites
``worker:<i>``, ``lease:<tid>`` and ``heartbeat:<i>``, driven by a
``PYCATKIN_FAULTS`` plan in the *worker* environment (never the
supervisor's -- the run-manifest env audit must stay clean). The
fleet-wide ticket budget (``state_dir``) keeps a ``times=1`` crash
from re-firing in every restarted incarnation. :func:`chaos_drill`
packages the standard carnage plan for ``make chaos`` and the bench
smoke gate.

Every lifecycle transition (spawn/exit/restart, lease granted/expired/
stolen, bisection, quarantine) is appended to ``events.jsonl`` in the
work directory, recorded as ``kind="worker"`` events on the ambient
trace, and counted in the obs metrics registry; ``events.jsonl``
opens with a run-manifest header so a degraded run is explainable
post-hoc from the directory alone (robustness/forensics.py renders
the worker-lifecycle section from exactly these records).

Env knobs (all overridable per call): ``PYCATKIN_ELASTIC_TTL``,
``PYCATKIN_ELASTIC_HEARTBEAT``, ``PYCATKIN_ELASTIC_MAX_RESTARTS``,
``PYCATKIN_ELASTIC_MIN_CHUNK``, ``PYCATKIN_ELASTIC_MAX_KILLS``.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from typing import Optional

import numpy as np

from ..utils.retry import backoff_delay, classify_worker_exit

EVENTS = "events.jsonl"
_STOP = "stop"


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name, "")
    return float(v) if v.strip() else float(default)


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name, "")
    return int(v) if v.strip() else int(default)


# ---------------------------------------------------------------------
# Pure lease/task math (unit-tested directly; ``now`` is always a
# parameter so tests never sleep).

def task_id(start: int, stop: int) -> str:
    """Span-encoding task id (``t00004_00008`` = lanes [4, 8)). The id
    IS the lane range, so an fnmatch fault-site pattern like
    ``lease:t00004_*`` keeps matching the poisoned data as bisection
    splits the span into children."""
    return f"t{int(start):05d}_{int(stop):05d}"


def parse_task_id(tid: str) -> tuple[int, int]:
    a, b = tid[1:].split("_")
    return int(a), int(b)


def lease_record(owner: str, ttl_s: float, now: float,
                 stolen_from: str | None = None) -> dict:
    """A fresh lease: ``deadline`` is wall-clock (``time.time`` --
    leases must be comparable across processes and hosts, which
    monotonic clocks are not), renewed by rewriting the record."""
    rec = {"owner": str(owner), "granted": float(now),
           "deadline": float(now) + float(ttl_s), "ttl_s": float(ttl_s)}
    if stolen_from:
        rec["stolen_from"] = str(stolen_from)
    return rec


def lease_expired(lease: dict, now: float) -> bool:
    return float(now) >= float(lease.get("deadline", -np.inf))


def bisect_span(start: int, stop: int, min_chunk: int):
    """Midpoint of a poison-suspect span, or None when either child
    would fall under ``min_chunk`` (the quarantine floor). A width of
    exactly ``2 * min_chunk`` still splits -- the floor bounds child
    size, not parent size."""
    if stop - start < 2 * max(1, int(min_chunk)):
        return None
    return (start + stop) // 2


def _write_json(path: str, record: dict) -> None:
    """Crash-atomic small-file write (tmp + rename), the same pattern
    as the result payloads -- a reader never sees a torn record."""
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as fh:
        json.dump(record, fh, sort_keys=True)
    os.replace(tmp, path)


def _read_json(path: str):
    try:
        with open(path) as fh:
            return json.load(fh)
    except (FileNotFoundError, json.JSONDecodeError):
        # A concurrently-replaced file can briefly read torn only on
        # non-POSIX rename semantics; treat like absent and let the
        # caller's next poll see the settled state.
        return None


class WorkQueue:
    """One elastic sweep's on-disk queue (a directory).

    Layout::

        tasks/<tid>.json    span + kill count (supervisor-maintained)
        leases/<tid>.lease  current owner + deadline
        results/<tid>.npz   atomic result payload
        done/<tid>.json     completion record (O_EXCL, first wins)
        events.jsonl        supervisor-written lifecycle journal
        stop                cooperative shutdown marker

    Workers and the supervisor share this class; every mutation is one
    of the crash-atomic primitives in the module docstring.
    """

    def __init__(self, root: str):
        self.root = str(root)
        self.tasks_dir = os.path.join(self.root, "tasks")
        self.leases_dir = os.path.join(self.root, "leases")
        self.results_dir = os.path.join(self.root, "results")
        self.done_dir = os.path.join(self.root, "done")

    def setup(self) -> "WorkQueue":
        for d in (self.tasks_dir, self.leases_dir, self.results_dir,
                  self.done_dir):
            os.makedirs(d, exist_ok=True)
        return self

    # -- paths ---------------------------------------------------------
    def task_path(self, tid: str) -> str:
        return os.path.join(self.tasks_dir, f"{tid}.json")

    def lease_path(self, tid: str) -> str:
        return os.path.join(self.leases_dir, f"{tid}.lease")

    def result_path(self, tid: str) -> str:
        return os.path.join(self.results_dir, f"{tid}.npz")

    def done_path(self, tid: str) -> str:
        return os.path.join(self.done_dir, f"{tid}.json")

    # -- task table ----------------------------------------------------
    def add_task(self, start: int, stop: int, kills: int = 0) -> str:
        tid = task_id(start, stop)
        _write_json(self.task_path(tid),
                    {"tid": tid, "start": int(start), "stop": int(stop),
                     "kills": int(kills)})
        return tid

    def remove_task(self, tid: str) -> None:
        try:
            os.unlink(self.task_path(tid))
        except FileNotFoundError:
            pass

    def tasks(self) -> dict:
        out = {}
        for name in sorted(os.listdir(self.tasks_dir)):
            if not name.endswith(".json"):
                continue
            rec = _read_json(os.path.join(self.tasks_dir, name))
            if rec is not None:
                out[rec["tid"]] = rec
        return out

    # -- leases --------------------------------------------------------
    def claim(self, tid: str, owner: str, ttl_s: float,
              now: float | None = None,
              stolen_from: str | None = None) -> bool:
        """Atomically claim ``tid``: True iff this caller won. The
        lease is materialized with ``os.link`` (hard-link create fails
        if the name exists), the one portable first-wins primitive that
        also carries a payload."""
        now = time.time() if now is None else now
        rec = lease_record(owner, ttl_s, now, stolen_from=stolen_from)
        tmp = os.path.join(self.leases_dir, f".claim.{owner}.{tid}.tmp")
        with open(tmp, "w") as fh:
            json.dump(rec, fh, sort_keys=True)
        try:
            os.link(tmp, self.lease_path(tid))
            return True
        except FileExistsError:
            return False
        finally:
            os.unlink(tmp)

    def read_lease(self, tid: str):
        return _read_json(self.lease_path(tid))

    def leases(self) -> dict:
        out = {}
        for name in sorted(os.listdir(self.leases_dir)):
            if not name.endswith(".lease"):
                continue
            rec = _read_json(os.path.join(self.leases_dir, name))
            if rec is not None:
                out[name[:-len(".lease")]] = rec
        return out

    def renew(self, tid: str, owner: str, ttl_s: float,
              now: float | None = None) -> bool:
        """Extend ``owner``'s lease on ``tid``; False means the lease
        was lost (stolen or released) and the caller must treat its
        work as speculative -- the fencing read. (The read-then-replace
        window can overwrite a thief's lease; see the module docstring
        for why that race is benign.)"""
        now = time.time() if now is None else now
        cur = self.read_lease(tid)
        if cur is None or cur.get("owner") != owner:
            return False
        rec = lease_record(owner, ttl_s, now,
                           stolen_from=cur.get("stolen_from"))
        rec["granted"] = cur.get("granted", rec["granted"])
        _write_json(self.lease_path(tid), rec)
        return True

    def release(self, tid: str, owner: str) -> None:
        cur = self.read_lease(tid)
        if cur is not None and cur.get("owner") == owner:
            try:
                os.unlink(self.lease_path(tid))
            except FileNotFoundError:
                pass

    def requeue(self, tid: str) -> bool:
        """Unlink ``tid``'s lease (expiry requeue / steal step 1).
        True iff this caller did the unlink -- exactly one concurrent
        requeuer wins, so a steal never double-counts."""
        try:
            os.unlink(self.lease_path(tid))
            return True
        except FileNotFoundError:
            return False

    def claim_next(self, owner: str, ttl_s: float,
                   now: float | None = None):
        """Claim the first available task in id order: unleased tasks
        first; then expired leases are stolen (unlink + claim).
        Returns ``(tid, stolen_from)`` or None when nothing is
        claimable right now."""
        now = time.time() if now is None else now
        done = set(self.done())
        for tid in sorted(self.tasks()):
            if tid in done:
                continue
            cur = self.read_lease(tid)
            if cur is None:
                if self.claim(tid, owner, ttl_s, now):
                    return tid, None
                continue
            if lease_expired(cur, now) and self.requeue(tid) and \
                    self.claim(tid, owner, ttl_s, now,
                               stolen_from=cur.get("owner")):
                return tid, cur.get("owner")
        return None

    # -- completion ----------------------------------------------------
    def write_done(self, tid: str, record: dict) -> bool:
        """Create ``tid``'s completion record exclusively: False means
        another completer already won (benign duplicate)."""
        path = self.done_path(tid)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as fh:
            json.dump(record, fh, sort_keys=True)
        try:
            os.link(tmp, path)
            return True
        except FileExistsError:
            return False
        finally:
            os.unlink(tmp)

    def done(self) -> dict:
        out = {}
        for name in sorted(os.listdir(self.done_dir)):
            if not name.endswith(".json"):
                continue
            rec = _read_json(os.path.join(self.done_dir, name))
            if rec is not None:
                out[name[:-len(".json")]] = rec
        return out

    # -- shutdown ------------------------------------------------------
    def request_stop(self) -> None:
        # Existence-only marker: readers test os.path.exists and never
        # parse the content, so a torn write is indistinguishable from
        # a complete one.
        with open(os.path.join(self.root, _STOP), "w") as fh:  # pclint: disable=PCL012 -- existence-only stop marker; content never read
            fh.write("stop\n")

    def stop_requested(self) -> bool:
        return os.path.exists(os.path.join(self.root, _STOP))


# ---------------------------------------------------------------------
# Coverage: the sweep is complete when done spans tile [0, n). Spans
# form a binary bisection hierarchy, so overlaps are exact-subset
# (a stalled owner completing a parent AFTER its children were
# re-solved); preferring the widest span at each boundary resolves
# them deterministically.

def covering_spans(done_records, n: int):
    """Minimal ordered list of done records tiling ``[0, n)``, or None
    while coverage is incomplete."""
    spans = sorted(((int(r["start"]), int(r["stop"]), r)
                    for r in done_records), key=lambda s: (s[0], -s[1]))
    cur, out = 0, []
    for a, b, rec in spans:
        if b <= cur:
            continue                         # fully covered already
        if a > cur:
            return None                      # gap -- keep working
        out.append((a, b, rec))
        cur = b
    return out if cur >= n else None


def stderr_tail(path: str, max_lines: int = 12) -> list[str]:
    try:
        with open(path, "rb") as fh:
            fh.seek(0, os.SEEK_END)
            fh.seek(max(0, fh.tell() - 16384))
            text = fh.read().decode("utf-8", "replace")
    except OSError:
        return []
    lines = [ln for ln in text.splitlines() if ln.strip()]
    return lines[-max_lines:]


# ---------------------------------------------------------------------
# Worker side.

class _Heartbeat(threading.Thread):
    """Renews one lease every ``interval_s`` until stopped. Runs the
    ``heartbeat:<i>`` fault site before each renewal, so a scripted
    ``heartbeat-stall`` blocks exactly the renewals while the worker
    thread keeps (obliviously) solving -- the live-but-expired state
    the supervisor must detect. A failed renewal sets :attr:`lost`
    (the fencing signal) and ends the thread."""

    def __init__(self, queue: WorkQueue, tid: str, owner: str,
                 idx: int, ttl_s: float, interval_s: float):
        super().__init__(daemon=True, name=f"heartbeat-{tid}")
        self.queue, self.tid, self.owner = queue, tid, owner
        self.idx, self.ttl_s, self.interval_s = idx, ttl_s, interval_s
        self.lost = threading.Event()
        self._halt = threading.Event()
        # Renewal bookkeeping is read by the OWNING worker thread (the
        # done-record stamps how fresh its lease ran) while this thread
        # writes it -- a lock, not an Event, so the count stays exact.
        self._stats_lock = threading.Lock()
        self._renewals = 0           # guarded-by: _stats_lock

    def run(self):
        from . import faults
        while not self._halt.wait(self.interval_s):
            faults.inject(f"heartbeat:{self.idx}")
            if self._halt.is_set():
                return
            if not self.queue.renew(self.tid, self.owner, self.ttl_s):
                self.lost.set()
                return
            with self._stats_lock:
                self._renewals += 1

    def renewals(self) -> int:
        """How many times this lease has been renewed so far."""
        with self._stats_lock:
            return self._renewals

    def halt(self):
        self._halt.set()


def _worker_main(cfg_path: str) -> None:
    """One elastic worker process: claim -> heartbeat -> sweep ->
    atomic result -> done record, until coverage is complete or the
    stop marker appears. Crashing (injected or real) at any point is
    safe: the lease expires and the span is re-solved elsewhere."""
    with open(cfg_path) as fh:
        cfg = json.load(fh)
    idx = int(cfg["worker"])
    q = WorkQueue(cfg["work_dir"])
    owner = f"w{idx}-{os.getpid()}"
    ttl_s = float(cfg["ttl_s"])
    heartbeat_s = float(cfg["heartbeat_s"])
    poll_s = float(cfg["poll_s"])
    n_lanes = int(cfg["n_lanes"])

    import pycatkin_tpu as pk
    from .. import engine
    from ..parallel.batch import sweep_steady_state, warm_from_aot_cache
    from ..parallel.dispatch import load_conditions
    from ..utils.io import atomic_save_results
    from ..utils.profiling import span
    from . import faults

    sim = pk.read_from_input_file(cfg["model"])
    conds = load_conditions(cfg["conds"])
    mask = (engine.tof_mask_for(sim.spec, cfg["tof_terms"])
            if cfg.get("tof_terms") else None)
    check_stability = bool(cfg.get("check_stability", False))
    warmed: set[int] = set()

    while True:
        if q.stop_requested():
            return
        if covering_spans(q.done().values(), n_lanes) is not None:
            return
        claimed = q.claim_next(owner, ttl_s)
        if claimed is None:
            time.sleep(poll_s)
            continue
        tid, stolen_from = claimed
        start, stop = parse_task_id(tid)
        hb = _Heartbeat(q, tid, owner, idx, ttl_s, heartbeat_s)
        hb.start()
        try:
            # Fault sites, broad to narrow: worker:<i> models
            # whole-worker carnage (preemption, stragglers);
            # lease:<tid> models data-poisoned spans -- the id encodes
            # the lane range, so the pattern follows the poison
            # through bisection.
            faults.inject(f"worker:{idx}")
            faults.inject(f"lease:{tid}")
            sub = type(conds)(**{
                f: np.asarray(getattr(conds, f))[start:stop]
                for f in conds._fields})
            if (stop - start) not in warmed:
                # Free on miss; spares restarted workers the recompile
                # for spans a previous incarnation already built.
                with span("worker aot warm", worker=idx):
                    warm_from_aot_cache(sim.spec, sub, tof_mask=mask,
                                        check_stability=check_stability)
                warmed.add(stop - start)
            with span("elastic task", worker=idx, task=tid,
                      lanes=stop - start):
                out = sweep_steady_state(sim.spec, sub, tof_mask=mask,
                                         check_stability=check_stability)
            out = {k: np.asarray(v) for k, v in out.items()}
            out = faults.transform(f"lease:{tid}", out)
            atomic_save_results(q.result_path(tid), out)
            q.write_done(tid, {
                "tid": tid, "start": start, "stop": stop,
                "status": "done", "owner": owner, "worker": idx,
                "stolen_from": stolen_from,
                "renewals": hb.renewals(),
                "n_failed": int(np.sum(~np.asarray(out["success"],
                                                   dtype=bool)))})
        finally:
            hb.halt()
            q.release(tid, owner)


# ---------------------------------------------------------------------
# Supervisor side.

class _Slot:
    """One worker slot's supervision state (the slot persists across
    restarts; the process does not)."""

    def __init__(self, idx: int):
        self.idx = idx
        self.proc: subprocess.Popen | None = None
        self.pid: int | None = None
        self.incarnation = -1
        self.restarts = 0
        self.next_spawn: float | None = 0.0   # due immediately
        self.abandoned = False
        self.self_killed = False

    @property
    def owner(self) -> str:
        return f"w{self.idx}-{self.pid}"


def run_elastic(sim, conds, *, n_workers: int = 2,
                chunk: Optional[int] = None,
                work_dir: Optional[str] = None,
                tof_terms=None, check_stability: bool = False,
                worker_env: Optional[dict] = None,
                aot_cache: Optional[str] = None,
                ttl_s: Optional[float] = None,
                heartbeat_s: Optional[float] = None,
                min_chunk: Optional[int] = None,
                max_kills: Optional[int] = None,
                max_restarts: Optional[int] = None,
                restart_base_s: float = 0.5,
                restart_max_s: float = 8.0,
                timeout: Optional[float] = None,
                poll_s: float = 0.2,
                resume: bool = False):
    """Elastically dispatch ``sweep_steady_state`` over ``conds``.

    Returns ``(out, report)``: ``out`` matches the in-process sweep
    (host numpy, lane order preserved; quarantined spans carry
    ``chunked.salvage_arrays`` rows); ``report`` is the structured
    lifecycle summary (restarts, lease traffic, bisections,
    quarantines, per-exit classifications) that forensics renders.

    The supervisor stays JAX-free (like ``dispatch_sweep``'s parent).
    Defaults come from the ``PYCATKIN_ELASTIC_*`` env knobs;
    ``chunk`` defaults to ~2 tasks per worker so there is slack to
    steal. ``resume=True`` reuses completed spans in an existing
    ``work_dir`` and re-runs the rest (quarantined spans get a fresh
    chance -- a wider re-solved parent takes precedence at merge).
    """
    import tempfile

    from ..obs import metrics as _metrics
    from ..obs.manifest import run_manifest
    from ..utils.io import append_json_line
    from ..utils.profiling import record_event, span
    from .chunked import salvage_arrays
    from .ladder import record_quarantine

    ttl_s = _env_float("PYCATKIN_ELASTIC_TTL", 30.0) \
        if ttl_s is None else float(ttl_s)
    heartbeat_s = _env_float("PYCATKIN_ELASTIC_HEARTBEAT", ttl_s / 4.0) \
        if heartbeat_s is None else float(heartbeat_s)
    min_chunk = _env_int("PYCATKIN_ELASTIC_MIN_CHUNK", 1) \
        if min_chunk is None else int(min_chunk)
    max_kills = _env_int("PYCATKIN_ELASTIC_MAX_KILLS", 2) \
        if max_kills is None else int(max_kills)
    max_restarts = _env_int("PYCATKIN_ELASTIC_MAX_RESTARTS", 8) \
        if max_restarts is None else int(max_restarts)

    own_dir = work_dir is None
    if own_dir:
        work_dir = tempfile.mkdtemp(prefix="pycatkin_elastic_")
    q = WorkQueue(work_dir).setup()
    if q.done() and not resume:
        raise RuntimeError(
            f"elastic work dir {work_dir} already holds completed "
            "tasks; pass resume=True to continue it (or use a fresh "
            "directory)")

    from ..utils.io import save_system_json
    from ..parallel.dispatch import save_conditions

    model_path = os.path.join(work_dir, "model.json")
    conds_path = os.path.join(work_dir, "conds.npz")
    save_system_json(sim, model_path)
    save_conditions(conds_path, conds)

    n = len(np.asarray(conds.T))
    if chunk is None:
        chunk = max(min_chunk, -(-n // max(1, 2 * n_workers)))
    chunk = max(1, min(int(chunk), n))

    events_path = os.path.join(work_dir, EVENTS)
    counters = {
        "granted": _metrics.counter(
            "pycatkin_elastic_leases_granted_total",
            "work-queue leases observed granted"),
        "expired": _metrics.counter(
            "pycatkin_elastic_leases_expired_total",
            "leases that hit their deadline and were requeued"),
        "stolen": _metrics.counter(
            "pycatkin_elastic_leases_stolen_total",
            "expired leases re-claimed by a different worker"),
        "restarts": _metrics.counter(
            "pycatkin_elastic_worker_restarts_total",
            "dead/stalled workers restarted by the supervisor"),
        "bisected": _metrics.counter(
            "pycatkin_elastic_tasks_bisected_total",
            "poison-suspect tasks split and requeued"),
        "quarantined": _metrics.counter(
            "pycatkin_elastic_tasks_quarantined_total",
            "minimum-size tasks quarantined after repeated kills"),
    }
    report = {"n_lanes": n, "chunk": int(chunk), "n_workers": n_workers,
              "ttl_s": ttl_s, "heartbeat_s": heartbeat_s,
              "restarts": 0, "exits": [], "leases": {
                  "granted": 0, "expired": 0, "stolen": 0},
              "bisected": [], "quarantined": [], "events": []}

    def emit(action: str, label: str, **fields):
        ev = {"kind": "worker", "action": action, "label": label,
              "t": time.time(), **fields}
        append_json_line(events_path, ev)
        record_event("worker", action=action, label=label, **fields)
        report["events"].append(ev)
        return ev

    if not os.path.exists(events_path):
        append_json_line(events_path, {
            "kind": "header", "manifest": run_manifest(), "n_lanes": n,
            "chunk": int(chunk), "n_workers": n_workers})

    done0 = q.done()
    for a in range(0, n, chunk):
        tid = task_id(a, min(n, a + chunk))
        if tid not in done0 or resume and \
                done0[tid].get("status") == "quarantined":
            if tid in done0:                  # re-arm a quarantined span
                os.unlink(q.done_path(tid))
            q.add_task(a, min(n, a + chunk))

    slots = [_Slot(i) for i in range(n_workers)]
    seen_leases: set[tuple] = set()
    counted_done: set[str] = set()
    deadline = (time.monotonic() + timeout) if timeout else None

    def spawn(slot: _Slot):
        slot.incarnation += 1
        slot.self_killed = False
        cfg = {"work_dir": work_dir, "worker": slot.idx,
               "incarnation": slot.incarnation, "model": model_path,
               "conds": conds_path, "n_lanes": n, "ttl_s": ttl_s,
               "heartbeat_s": heartbeat_s, "poll_s": poll_s,
               "tof_terms": list(tof_terms) if tof_terms else None,
               "check_stability": bool(check_stability)}
        cfg_path = os.path.join(work_dir, f"worker_{slot.idx}.json")
        _write_json(cfg_path, cfg)
        env = dict(os.environ)
        if aot_cache is not None:
            env["PYCATKIN_AOT_CACHE"] = str(aot_cache)
        if worker_env:
            env.update({k: str(v) for k, v in worker_env.items()})
        stderr_path = os.path.join(
            work_dir, f"worker_{slot.idx}.stderr.log")
        with open(stderr_path, "ab") as errf:
            slot.proc = subprocess.Popen(
                [sys.executable, "-m",
                 "pycatkin_tpu.robustness.scheduler", cfg_path],
                env=env, cwd=os.getcwd(), stderr=errf)
        slot.pid = slot.proc.pid
        slot.next_spawn = None
        emit("spawn", f"worker:{slot.idx}", pid=slot.pid,
             incarnation=slot.incarnation)

    def implicate(tid: str, owner: str, why: str):
        """A worker died holding a valid lease on ``tid``: charge the
        task one kill, requeue it, and bisect/quarantine past the
        budget."""
        done = q.done()
        q.requeue(tid)
        if tid in done:
            return
        task = q.tasks().get(tid)
        if task is None:
            return
        start, stop = int(task["start"]), int(task["stop"])
        kills = int(task.get("kills", 0)) + 1
        q.add_task(start, stop, kills=kills)    # rewrite with new count
        emit("task-killed", f"lease:{tid}", kills=kills, cause=why,
             owner=owner)
        if kills < max_kills:
            return
        mid = bisect_span(start, stop, min_chunk)
        if mid is not None:
            q.add_task(start, mid)
            q.add_task(mid, stop)
            q.remove_task(tid)
            counters["bisected"].inc()
            report["bisected"].append(tid)
            emit("task-bisected", f"lease:{tid}", mid=mid,
                 children=[task_id(start, mid), task_id(mid, stop)])
        elif q.write_done(tid, {"tid": tid, "start": start,
                                "stop": stop, "status": "quarantined",
                                "kills": kills}):
            q.remove_task(tid)
            counters["quarantined"].inc()
            report["quarantined"].append(tid)
            ev = record_quarantine(range(start, stop),
                                   label=f"lease:{tid}",
                                   detail=f"span killed {kills} "
                                          f"worker(s) at minimum size")
            append_json_line(events_path, {"kind": "worker",
                                           "action": "task-quarantined",
                                           "label": f"lease:{tid}",
                                           "t": time.time(), **ev})
            report["events"].append(ev)

    def scan_leases(now: float):
        for tid, lease in q.leases().items():
            key = (tid, lease.get("owner"))
            if key not in seen_leases:
                seen_leases.add(key)
                counters["granted"].inc()
                report["leases"]["granted"] += 1
                if lease.get("stolen_from"):
                    counters["stolen"].inc()
                    report["leases"]["stolen"] += 1
                    emit("lease-stolen", f"lease:{tid}",
                         owner=lease.get("owner"),
                         stolen_from=lease.get("stolen_from"))
            if not lease_expired(lease, now):
                continue
            if not q.requeue(tid):
                continue                      # a worker stole it first
            counters["expired"].inc()
            report["leases"]["expired"] += 1
            emit("lease-expired", f"lease:{tid}",
                 owner=lease.get("owner"))
            # A live owner that let its lease lapse is a stalled
            # heartbeat: kill it (the work is requeued; the process is
            # not trustworthy) and let the restart path revive it.
            for slot in slots:
                if slot.proc is not None and slot.proc.poll() is None \
                        and slot.owner == lease.get("owner"):
                    slot.self_killed = True
                    emit("kill-stalled", f"worker:{slot.idx}",
                         task=tid)
                    slot.proc.kill()

    def note_done():
        for tid, rec in q.done().items():
            if tid in counted_done or rec.get("status") != "done":
                continue
            counted_done.add(tid)
            key = (tid, rec.get("owner"))
            if key not in seen_leases:        # completed between scans
                seen_leases.add(key)
                counters["granted"].inc()
                report["leases"]["granted"] += 1
                if rec.get("stolen_from"):
                    counters["stolen"].inc()
                    report["leases"]["stolen"] += 1
                    emit("lease-stolen", f"lease:{tid}",
                         owner=rec.get("owner"),
                         stolen_from=rec.get("stolen_from"))
            emit("task-done", f"lease:{tid}", owner=rec.get("owner"),
                 n_failed=rec.get("n_failed"))

    def handle_exit(slot: _Slot, now: float):
        rc = slot.proc.returncode
        exit_info = classify_worker_exit(rc)
        tail = stderr_tail(os.path.join(
            work_dir, f"worker_{slot.idx}.stderr.log"))
        report["exits"].append({
            "worker": slot.idx, "incarnation": slot.incarnation,
            "returncode": rc, "kind": exit_info.kind,
            "detail": exit_info.detail, "self_killed": slot.self_killed,
            "stderr_tail": tail})
        emit("exit", f"worker:{slot.idx}", returncode=rc,
             exit_kind=exit_info.kind, incarnation=slot.incarnation)
        if exit_info.kind == "ok":
            slot.proc = None                  # drained cleanly
            slot.next_spawn = None
            return
        # A death while holding a valid lease implicates the task --
        # unless the supervisor itself killed the worker for a stalled
        # heartbeat (the lease was already requeued; the task is
        # innocent).
        if not slot.self_killed:
            for tid, lease in q.leases().items():
                if lease.get("owner") == slot.owner:
                    implicate(tid, slot.owner, exit_info.kind)
        slot.proc = None
        if slot.restarts >= max_restarts:
            slot.abandoned = True
            emit("abandon", f"worker:{slot.idx}",
                 restarts=slot.restarts)
            return
        slot.restarts += 1
        report["restarts"] += 1
        counters["restarts"].inc()
        delay = backoff_delay(slot.restarts - 1, restart_base_s,
                              restart_max_s)
        slot.next_spawn = now + delay
        emit("restart", f"worker:{slot.idx}", attempt=slot.restarts,
             delay_s=round(delay, 3), cause=exit_info.kind)

    with span("elastic sweep", lanes=n, workers=n_workers):
        try:
            cover = covering_spans(q.done().values(), n)
            while cover is None:
                now = time.time()
                if deadline is not None and time.monotonic() > deadline:
                    raise RuntimeError(
                        f"run_elastic: timed out after {timeout} s with "
                        f"incomplete coverage; state left in {work_dir}")
                for slot in slots:
                    if slot.proc is not None and \
                            slot.proc.poll() is not None:
                        handle_exit(slot, time.monotonic())
                    if slot.proc is None and not slot.abandoned and \
                            slot.next_spawn is not None and \
                            time.monotonic() >= slot.next_spawn:
                        spawn(slot)
                scan_leases(now)
                note_done()
                cover = covering_spans(q.done().values(), n)
                if cover is not None:
                    break
                if all(s.proc is None and (s.abandoned or
                                           s.next_spawn is None)
                       for s in slots):
                    tails = {s.idx: stderr_tail(os.path.join(
                        work_dir, f"worker_{s.idx}.stderr.log"))
                        for s in slots}
                    kinds = [f"worker {e['worker']}: {e['kind']} "
                             f"({e['detail']})"
                             for e in report["exits"][-n_workers:]]
                    raise RuntimeError(
                        "run_elastic: every worker slot is dead or "
                        "abandoned with coverage incomplete; last "
                        "exits: " + "; ".join(kinds) +
                        f"; stderr tails: {tails}; state left in "
                        f"{work_dir}")
                time.sleep(poll_s)
        finally:
            q.request_stop()
            for slot in slots:
                if slot.proc is not None and slot.proc.poll() is None:
                    slot.proc.terminate()
            grace = time.monotonic() + 5.0
            for slot in slots:
                if slot.proc is None:
                    continue
                while slot.proc.poll() is None and \
                        time.monotonic() < grace:
                    time.sleep(0.05)
                if slot.proc.poll() is None:
                    slot.proc.kill()
                    slot.proc.wait()

        note_done()

        # Merge in lane order. Quarantined spans degrade to per-lane
        # salvage rows (same keys/dtypes as real results); overlapped
        # prefixes from parent/child duplicates are sliced off.
        parts = []
        cur = 0
        for a, b, rec in cover:
            lo = max(a, cur)
            if rec.get("status") == "quarantined":
                arrs = salvage_arrays(sim.spec, b - lo,
                                      tof_mask=(tof_terms or None),
                                      check_stability=check_stability)
                # Unlike a salvaged chunk (lanes merely unsolved),
                # these lanes were actively quarantined by the poison
                # ladder -- mark them so forensics lists them.
                arrs["quarantined"][:] = True
            else:
                from ..utils.io import load_results
                arrs = load_results(q.result_path(rec["tid"]))
                if lo > a:
                    arrs = {k: v[lo - a:] for k, v in arrs.items()}
            parts.append(arrs)
            cur = b
        out = {k: np.concatenate([p[k] for p in parts], axis=0)
               for k in parts[0].keys()}

    report["n_failed_lanes"] = int(
        np.sum(~np.asarray(out["success"], dtype=bool)))
    report["n_done"] = len(counted_done)
    report["work_dir"] = None if own_dir else work_dir
    if own_dir:
        import shutil
        shutil.rmtree(work_dir, ignore_errors=True)
    return out, report


# ---------------------------------------------------------------------
# Chaos drill: the standard carnage plan, packaged for `make chaos`
# and the bench smoke gate.

def packed_group_runner(work_dir: Optional[str] = None,
                        n_workers: int = 2, tof_terms=None,
                        **elastic_opts):
    """Build the scheduler-integrated runner for
    :class:`parallel.dispatch.SweepCoalescer`: coalesced groups FEED
    the elastic tier instead of bypassing it.

    - K>1 groups (same ABI bucket by construction) run as one packed
      in-process dispatch -- multi-tenant packing IS the scheduling
      decision for them, process isolation would forfeit the shared
      executable.
    - K=1 groups whose tenant is a full ``System`` run through
      :func:`run_elastic` in a per-group subdirectory of ``work_dir``
      (lease queue, restarts, poison bisection), with ``tof_terms``
      forwarded (masks cannot ride to a subprocess; a K=1 group that
      only has a mask array falls back in-process).

    Both paths append their lifecycle to ``work_dir`` events
    (run_elastic writes its own ``events.jsonl`` per group dir; the
    coalescer's ``pack-flush`` event lands in the shared one), so
    ``tools/obsview.py --workers`` sees packs and solo escapes in one
    timeline."""

    def run(sims, conds_list, masks, x0s, *, check_stability, opts,
            pos_jac_tol):
        from ..parallel.batch import packed_sweep_steady_state
        from ..solvers.newton import SolverOptions
        solver_opts = SolverOptions() if opts is None else opts
        if (len(sims) == 1 and work_dir is not None
                and hasattr(sims[0], "spec") and x0s[0] is None
                and (masks[0] is None or tof_terms is not None)):
            import tempfile
            os.makedirs(work_dir, exist_ok=True)
            group_dir = tempfile.mkdtemp(prefix="packgroup_",
                                         dir=work_dir)
            out, _report = run_elastic(
                sims[0], conds_list[0], n_workers=n_workers,
                work_dir=group_dir, tof_terms=tof_terms,
                check_stability=check_stability, **elastic_opts)
            return [out]
        return packed_sweep_steady_state(
            [getattr(s, "spec", s) for s in sims], conds_list,
            tof_mask=masks, x0=x0s, opts=solver_opts,
            check_stability=check_stability, pos_jac_tol=pos_jac_tol)

    return run


def chaos_drill(n_lanes: int = 8, chunk: int = 2, n_workers: int = 2,
                verbose: bool = False) -> dict:
    """Run a small elastic sweep with one worker-crash injected via
    the worker environment (never the supervisor's -- the manifest
    env audit stays clean), and fail loudly on any lost lane.

    Returns ``{"ok": bool, "restarts": ..., "n_failed_lanes": ...,
    "quarantined": [...], "wall_s": ...}`` for the bench smoke gate.
    """
    import tempfile

    from ..models.synthetic import synthetic_system
    from ..parallel.batch import broadcast_conditions

    sim = synthetic_system(n_species=8, n_reactions=10, seed=0)
    conds = broadcast_conditions(sim.conditions(), n_lanes)
    conds = conds._replace(T=np.linspace(450.0, 650.0, n_lanes))
    with tempfile.TemporaryDirectory(prefix="pycatkin_chaos_") as td:
        plan = {"specs": [{"site": "worker:0", "kind": "worker-crash",
                           "times": 1}],
                "state_dir": os.path.join(td, "faultstate")}
        t0 = time.monotonic()
        out, report = run_elastic(
            sim, conds, n_workers=n_workers, chunk=chunk,
            work_dir=os.path.join(td, "work"),
            worker_env={"PYCATKIN_FAULTS": json.dumps(plan),
                        "JAX_PLATFORMS": "cpu"},
            ttl_s=6.0, heartbeat_s=0.5, max_kills=3,
            restart_base_s=0.2, restart_max_s=1.0, timeout=600.0)
        wall = time.monotonic() - t0
    lost = int(np.sum(~np.asarray(out["success"], dtype=bool)))
    ok = (lost == 0 and not report["quarantined"]
          and report["restarts"] >= 1)
    result = {"ok": bool(ok), "restarts": report["restarts"],
              "n_failed_lanes": lost,
              "quarantined": report["quarantined"],
              "leases": report["leases"], "wall_s": round(wall, 2)}
    if verbose:
        print(json.dumps(result, indent=2))
    return result


def _main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        description="elastic scheduler worker entry / chaos drill")
    ap.add_argument("cfg", nargs="?", help="worker config JSON path")
    ap.add_argument("--drill", action="store_true",
                    help="run the chaos drill and exit nonzero on "
                         "any lost lane")
    args = ap.parse_args(argv)
    if args.drill:
        result = chaos_drill(verbose=True)
        return 0 if result["ok"] else 1
    if not args.cfg:
        ap.error("worker config path required (or --drill)")
    _worker_main(args.cfg)
    return 0


if __name__ == "__main__":
    sys.exit(_main())
