"""Deterministic fault injection for the failure-handling machinery.

Production sweeps run on a flaky tunneled TPU backend where transport
drops, NaN-poisoned lanes and outright device loss are routine (the
round-4 driver bench died to a single dropped remote-compile
connection). None of the code that handles those failures -- retry
classification (utils/retry.py), the rescue ladder
(robustness/ladder.py), journal resume (robustness/journal.py) -- can
be exercised against the real backend deterministically. This module
makes every failure mode a *scriptable event*: a :class:`FaultPlan`
names injection sites (the retry labels of the jitted-dispatch
boundaries in parallel/batch.py, plus the ``chunk:<i>`` sites of the
chunked sweep runner) and fires scripted faults at chosen occurrences,
so every branch of the degradation ladder becomes unit-testable.

Faults:

- ``transient``  -- raises a ``jax.errors.JaxRuntimeError`` whose text
  matches :data:`pycatkin_tpu.utils.retry.TRANSIENT_MARKERS`, so the
  bounded-retry machinery classifies and absorbs it exactly like a
  real transport flake.
- ``permanent``  -- raises :class:`InjectedDeviceLossError` (never
  classified transient): models device loss; only the ladder's
  requeue/host-fallback/salvage rungs can recover.
- ``nan``        -- poisons the result of a completed call: float
  array leaves (optionally only chosen lanes) are overwritten with
  NaN, modeling silently corrupted chunk outputs.
- ``stall``      -- sleeps ``delay_s`` before the call proceeds,
  modeling slow compiles / stalled transports for deadline tests.
- ``worker-crash``    -- SIGKILLs the calling process, modeling a
  preempted/OOM-killed elastic worker. Only meaningful inside a
  subprocess worker (the elastic scheduler's ``worker:<i>`` /
  ``lease:<i>`` sites); the supervisor observes the signal death and
  requeues the lease.
- ``heartbeat-stall`` -- sleeps ``delay_s`` at a heartbeat site
  (``heartbeat:<i>``): the worker stays alive but stops renewing its
  lease, so the supervisor must detect the expired lease and let
  another worker steal the work.
- ``slow-worker``     -- sleeps ``delay_s`` at a worker site: a
  straggler that makes progress, just slowly, for work-stealing and
  deadline drills.
- ``replica-crash`` / ``replica-stall`` / ``conn-reset`` /
  ``torn-line`` -- serve-tier chaos kinds (docs/serving.md). These are
  *externally enacted*: the fault layer cannot SIGKILL a different
  process or sever a socket it does not own, so the fleet supervisor
  and front router poll :func:`take` at their ``router:replica:<i>`` /
  ``router:dispatch:<i>`` sites and enact the fired spec themselves
  (SIGKILL the replica subprocess, SIGSTOP it, abort the replica
  connection, write a truncated JSON line). ``on_call`` never fires
  them, so a plan mixing serve-tier and in-process kinds stays safe.

Activation: pass a plan to :func:`fault_scope` (tests), or set the
``PYCATKIN_FAULTS`` environment variable to the JSON list of fault
specs (survives into subprocess workers, enabling end-to-end
kill/resume drills). With no plan active every hook is a single
``is None`` check -- the production hot path pays nothing.

Fleet-wide fault budgets: ``PYCATKIN_FAULTS`` may also be a JSON
OBJECT ``{"specs": [...], "state_dir": "..."}``. With a ``state_dir``,
each spec's ``times`` budget is enforced across EVERY process sharing
that directory (ticket files created ``O_EXCL``, so concurrent workers
race for firings atomically), not per process. This is what makes
``worker-crash`` drills terminate: a restarted worker re-reads the
same plan from its environment, but the already-consumed ticket stops
it from dying again on every incarnation. ``index`` stays per-process
(occurrence counters are local by design).
"""

from __future__ import annotations

import fnmatch
import json
import os
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass

ENV_VAR = "PYCATKIN_FAULTS"

_KINDS = ("transient", "permanent", "nan", "stall",
          "worker-crash", "heartbeat-stall", "slow-worker",
          "replica-crash", "replica-stall", "conn-reset", "torn-line",
          "router-crash")

# Kinds enacted by the serve tier itself (fleet supervisor / front
# router) via take(), never by on_call.
EXTERNAL_KINDS = ("replica-crash", "replica-stall", "conn-reset",
                  "torn-line", "router-crash")


class InjectedDeviceLossError(RuntimeError):
    """Permanent injected failure (device loss). Deliberately NOT a
    ``JaxRuntimeError`` and carries no transient marker, so
    ``is_transient_backend_error`` never classifies it retryable --
    only the degradation ladder's later rungs can absorb it."""


@dataclass(frozen=True)
class FaultSpec:
    """One scripted fault.

    site:    fnmatch pattern against the injection-site label (retry
             labels like ``"batched steady solve"``, chunk sites like
             ``"chunk:3"``, elastic-scheduler sites like ``"worker:0"``
             / ``"lease:t00004_00008"`` / ``"heartbeat:2"``;
             ``"chunk:*"`` matches every chunk).
    kind:    one of ``transient | permanent | nan | stall |
             worker-crash | heartbeat-stall | slow-worker``.
    index:   fire only at this occurrence of the site (0-based count
             of calls at that site, retries included); None = any.
    times:   maximum number of firings (None = unlimited; a permanent
             device loss is typically ``times=None``).
    lanes:   for 'nan': lane indices (leading axis) to poison;
             None = every lane.
    delay_s: for 'stall'/'heartbeat-stall'/'slow-worker': seconds to
             sleep before the call proceeds.
    """
    site: str
    kind: str
    index: int | None = None
    times: int | None = 1
    lanes: tuple | None = None
    delay_s: float = 0.0

    def __post_init__(self):
        if self.kind not in _KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r} "
                             f"(expected one of {_KINDS})")
        if self.lanes is not None:
            object.__setattr__(self, "lanes", tuple(self.lanes))


def _transient_error(site: str, occurrence: int):
    import jax
    return jax.errors.JaxRuntimeError(
        f"UNAVAILABLE: injected transient fault at site={site!r} "
        f"occurrence={occurrence} (socket closed)")


def _poison(tree, lanes):
    """NaN-overwrite float array leaves (whole arrays, or the given
    leading-axis lanes) of an arbitrary result pytree."""
    import jax
    import numpy as np

    def one(x):
        try:
            a = np.asarray(x)
        except Exception:
            return x
        if a.ndim < 1 or not np.issubdtype(a.dtype, np.inexact):
            return x
        a = np.array(a)                      # writable host copy
        if lanes is None:
            a[...] = np.nan
        else:
            idx = [i for i in lanes if i < a.shape[0]]
            if idx:
                a[idx] = np.nan
        return a

    return jax.tree_util.tree_map(one, tree)


class FaultPlan:
    """A deterministic schedule of faults over named injection sites.

    Occurrence counters advance per :meth:`on_call` at each site, so a
    spec with ``index=1, times=1`` fires exactly at the second call of
    its site (e.g. the first retry attempt) and never again. Thread-safe
    counter updates; the fired log (:attr:`log`) records every injection
    for test assertions.
    """

    def __init__(self, specs=(), state_dir: str | None = None):
        self.specs = [s if isinstance(s, FaultSpec) else FaultSpec(**s)
                      for s in specs]
        self.state_dir = None if state_dir is None else str(state_dir)
        self._calls: dict[str, int] = {}
        self._fired: dict[int, int] = {}
        self.log: list[dict] = []
        self._lock = threading.Lock()

    @classmethod
    def from_env(cls, text: str | None = None) -> "FaultPlan | None":
        """Build a plan from ``PYCATKIN_FAULTS`` (JSON list of spec
        dicts, or ``{"specs": [...], "state_dir": ...}`` for
        fleet-wide budgets); None when the variable is unset/empty."""
        if text is None:
            text = os.environ.get(ENV_VAR, "")
        if not text.strip():
            return None
        data = json.loads(text)
        if isinstance(data, dict):
            return cls(data.get("specs", ()),
                       state_dir=data.get("state_dir"))
        return cls(data)

    def _acquire(self, i: int, spec: FaultSpec) -> bool:
        """Consume one firing of spec ``i`` (called under the lock,
        AFTER :meth:`_due` matched it). Per-process plans just count;
        with a ``state_dir`` a bounded spec must win an ``O_EXCL``
        ticket file, so at most ``times`` firings happen across every
        process sharing the directory -- first-claimer-wins, no
        cross-process lock needed."""
        if self.state_dir is None or spec.times is None:
            self._fired[i] = self._fired.get(i, 0) + 1
            return True
        os.makedirs(self.state_dir, exist_ok=True)
        for k in range(spec.times):
            path = os.path.join(self.state_dir, f"spec{i:03d}_fire{k:03d}")
            try:
                fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            except FileExistsError:
                continue
            os.write(fd, f"{os.getpid()}\n".encode())
            os.close(fd)
            self._fired[i] = self._fired.get(i, 0) + 1
            return True
        # Budget exhausted fleet-wide: remember locally so _due stops
        # offering this spec.
        self._fired[i] = spec.times
        return False

    def _due(self, site: str, occurrence: int, kinds) -> list[int]:
        due = []
        for i, spec in enumerate(self.specs):
            if spec.kind not in kinds:
                continue
            if not fnmatch.fnmatchcase(site, spec.site):
                continue
            if spec.index is not None and occurrence != spec.index:
                continue
            if spec.times is not None and \
                    self._fired.get(i, 0) >= spec.times:
                continue
            due.append(i)
        return due

    def on_call(self, site: str) -> int:
        """Injection hook BEFORE a dispatch at ``site``. May sleep
        (stall) and/or raise (transient/permanent). Returns the
        occurrence index consumed."""
        with self._lock:
            occ = self._calls.get(site, 0)
            self._calls[site] = occ + 1
            due = self._due(site, occ,
                            ("stall", "heartbeat-stall", "slow-worker",
                             "worker-crash", "transient", "permanent"))
            fired = []
            for i in due:
                spec = self.specs[i]
                if not self._acquire(i, spec):
                    continue
                self.log.append({"site": site, "occurrence": occ,
                                 "kind": spec.kind})
                fired.append(spec)
        # Act outside the lock (sleeps and raises must not serialize
        # other sites' bookkeeping).
        for spec in fired:
            if spec.kind in ("stall", "heartbeat-stall", "slow-worker"):
                time.sleep(spec.delay_s)
            elif spec.kind == "worker-crash":
                # Model an external SIGKILL (preemption / OOM-killer):
                # the process dies mid-lease with no chance to clean
                # up, which is exactly the failure the elastic
                # scheduler's lease expiry + requeue must absorb.
                import signal
                os.kill(os.getpid(), signal.SIGKILL)
            elif spec.kind == "transient":
                raise _transient_error(site, occ)
            else:
                raise InjectedDeviceLossError(
                    f"injected permanent device loss at site={site!r} "
                    f"occurrence={occ}")
        return occ

    def take(self, site: str, kinds=EXTERNAL_KINDS) -> list:
        """Consume due *externally-enacted* faults at ``site`` and
        return the fired :class:`FaultSpec` list WITHOUT acting on
        them: serve-tier kinds (replica-crash, conn-reset, ...) name
        effects only their caller can produce -- killing a replica
        subprocess, severing a routed connection -- so the caller
        enacts what comes back. Advances the site's occurrence counter
        and consumes ``times`` budgets (O_EXCL tickets under a
        ``state_dir``) exactly like :meth:`on_call`."""
        with self._lock:
            occ = self._calls.get(site, 0)
            self._calls[site] = occ + 1
            fired = []
            for i in self._due(site, occ, tuple(kinds)):
                spec = self.specs[i]
                if not self._acquire(i, spec):
                    continue
                self.log.append({"site": site, "occurrence": occ,
                                 "kind": spec.kind})
                fired.append(spec)
        return fired

    def on_result(self, site: str, out):
        """Injection hook AFTER a successful dispatch at ``site``:
        applies any due 'nan' poisoning to the result."""
        with self._lock:
            # The matching on_call already advanced the counter.
            occ = max(self._calls.get(site, 1) - 1, 0)
            due = self._due(site, occ, ("nan",))
            lanes = []
            for i in due:
                if not self._acquire(i, self.specs[i]):
                    continue
                self.log.append({"site": site, "occurrence": occ,
                                 "kind": "nan"})
                lanes.append(self.specs[i].lanes)
        for ln in lanes:
            out = _poison(out, ln)
        return out


# ---------------------------------------------------------------------
# Active-plan registry: one process-wide plan, set by fault_scope()
# (tests) or lazily from the environment (subprocess drills). The env
# plan is built ONCE so its occurrence counters persist across calls.
_ACTIVE: FaultPlan | None = None
_ENV_LOADED = False


def active_plan() -> FaultPlan | None:
    global _ACTIVE, _ENV_LOADED
    if _ACTIVE is None and not _ENV_LOADED:
        _ENV_LOADED = True
        _ACTIVE = FaultPlan.from_env()
    return _ACTIVE


@contextmanager
def fault_scope(plan: FaultPlan | None):
    """Install ``plan`` as the process-wide fault plan for the block
    (None disables injection even if PYCATKIN_FAULTS is set)."""
    global _ACTIVE, _ENV_LOADED
    prev, prev_loaded = _ACTIVE, _ENV_LOADED
    _ACTIVE, _ENV_LOADED = plan, True
    try:
        yield plan
    finally:
        _ACTIVE, _ENV_LOADED = prev, prev_loaded


def inject(site: str) -> None:
    """Module-level pre-dispatch hook: no-op without an active plan."""
    plan = active_plan()
    if plan is not None:
        plan.on_call(site)


def take(site: str, kinds=EXTERNAL_KINDS) -> list:
    """Module-level externally-enacted-fault hook (see
    :meth:`FaultPlan.take`): no-op empty list without an active plan."""
    plan = active_plan()
    if plan is None:
        return []
    return plan.take(site, kinds)


def transform(site: str, out):
    """Module-level post-dispatch hook: no-op without an active plan."""
    plan = active_plan()
    if plan is None:
        return out
    return plan.on_result(site, out)
