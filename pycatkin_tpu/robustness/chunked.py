"""Chunked, journaled, degradation-tolerant steady-state sweeps.

The production volcano/uncertainty sweeps dispatch the whole lane grid
as one program: maximal throughput, but one exhausted failure forfeits
everything. This runner trades a little dispatch overhead for
durability: lanes are split into chunks, every chunk runs through the
full sweep machinery (:func:`parallel.batch.sweep_steady_state`:
fast pass + rescue ladder + optional stability verdict) under the
graceful-degradation ladder (robustness/ladder.py), and each completed
chunk is journaled (robustness/journal.py) so a killed run resumes by
re-dispatching ONLY unfinished chunks -- with results bit-identical to
an uninterrupted run (chunks are independent and the .npz round trip
is lossless).

Fault-injection sites: each chunk dispatch passes through
``faults.inject("chunk:<i>")`` / ``faults.transform("chunk:<i>", out)``
in addition to the retry-label sites inside the sweep itself, so
tests can script a transient flake, NaN poisoning, a stall or a
permanent loss at an exact chunk.
"""

from __future__ import annotations

import contextvars
from contextlib import nullcontext

import numpy as np

from ..solvers.newton import SolverOptions
from ..utils.profiling import span
from . import faults
from .journal import SweepJournal, conditions_fingerprint
from .ladder import DegradationPolicy, run_chunk_with_ladder

# Result keys of a salvaged chunk, by sweep configuration (must mirror
# parallel.batch._finish_sweep's output dict exactly so chunk arrays
# concatenate).
_INT_KEYS = ("iterations", "attempts")
_BOOL_KEYS = ("success", "stable", "quarantined",
              "rate_ok", "pos_ok", "sums_ok")


def chunk_verdict(out) -> str | None:
    """Post-hoc validation of one chunk's sweep result: a chunk whose
    'converged' lanes carry non-finite solutions was poisoned upstream
    (NaN chunk outputs are a known failure mode of remote execution),
    and must escalate rather than enter the journal as good data."""
    y = np.asarray(out["y"])
    succ = np.asarray(out["success"]).astype(bool)
    if succ.any() and not np.all(np.isfinite(y[succ])):
        n = int(np.sum(~np.isfinite(y[succ]).all(axis=-1)))
        return f"{n} converged lane(s) carry non-finite y"
    return None


def salvage_arrays(spec, n_lanes: int, tof_mask=None,
                   check_stability: bool = False) -> dict:
    """All-lanes-failed result block for a salvaged chunk (same keys/
    shapes/dtypes as a real sweep result)."""
    out = {
        "y": np.full((n_lanes, spec.n_species), np.nan),
        "success": np.zeros(n_lanes, dtype=bool),
        "residual": np.full(n_lanes, np.inf),
        "iterations": np.zeros(n_lanes, dtype=np.int64),
        "attempts": np.zeros(n_lanes, dtype=np.int64),
        "quarantined": np.zeros(n_lanes, dtype=bool),
        "rate_ok": np.zeros(n_lanes, dtype=bool),
        "pos_ok": np.zeros(n_lanes, dtype=bool),
        "sums_ok": np.zeros(n_lanes, dtype=bool),
        "dt_exit": np.full(n_lanes, np.nan),
        "chords": np.zeros(n_lanes, dtype=np.int32),
    }
    # Packed per-lane telemetry matching the real sweep's columns
    # (iterations, chords, residual decade, strategy, tier): the lanes
    # were never solved, so 0 iterations/chords, the +99 non-finite
    # decade the inf residual encodes to, the clean strategy code -- no
    # rescue ran -- and tier 0 since no first-pass acceptance happened
    # (solvers.newton.LANE_TELEMETRY_FIELDS).
    tel = np.zeros((n_lanes, 5), dtype=np.int32)
    tel[:, 2] = 99
    out["lane_telemetry"] = tel
    if check_stability:
        out["stable"] = np.zeros(n_lanes, dtype=bool)
    if tof_mask is not None:
        out["tof"] = np.full(n_lanes, np.nan)
        out["activity"] = np.full(n_lanes, np.nan)
    return out


def chunked_sweep_steady_state(spec, conds, *, chunk: int = 4096,
                               tof_mask=None,
                               opts: SolverOptions = SolverOptions(),
                               check_stability: bool = False,
                               pos_jac_tol: float = 1e-2,
                               journal: str | SweepJournal | None = None,
                               resume: bool = False,
                               policy: DegradationPolicy | None = None,
                               verbose: bool = False,
                               pipeline: bool = True,
                               mesh=None):
    """Run ``sweep_steady_state`` chunk by chunk with journaling and
    graceful degradation.

    ``journal``: directory path (or an open :class:`SweepJournal`) for
    the on-disk journal; None runs unjournaled (ladder only).
    ``resume``: replay an existing journal, re-dispatching only chunks
    without a completed record. ``policy``: the degradation ladder
    configuration; ``policy.salvage=False`` restores fail-fast
    semantics (the journal still preserves completed chunks for a
    later resume).

    Returns ``(out, report)``: ``out`` is the assembled result dict
    (host numpy arrays, original lane order); ``report`` is the
    structured end-of-run degradation report::

        {"n_chunks": ..., "chunk": ..., "reused": [ids],
         "degraded": [ids], "salvaged": [ids], "quarantined": [ids],
         "n_failed_lanes": ..., "events": [...]}

    A chunk with quarantined lanes that stayed failed after the rescue
    ladder is journaled with status ``"quarantined"`` -- like
    ``"salvaged"``, deliberately NOT a completed status, so a resume
    re-solves exactly the lanes that degraded.

    ``pipeline``: double-buffer chunk execution -- chunk ``k+1`` is
    dispatched on a single worker thread while the main thread triages
    and journals (fsync'd ``.npz`` write) chunk ``k``, keeping the
    device busy during checkpoint I/O. Chunk SOLVES stay strictly
    serialized (the worker is one thread deep) and journal records are
    written in chunk order from the main thread, so ladder/journal
    semantics and results are bit-identical to the serial loop; the
    runner degrades to the serial loop automatically under an active
    fault-injection plan (whose per-site occurrence drills assume
    solve and triage interleave strictly).

    ``mesh``: a ``jax.sharding.Mesh`` forwarded to every per-chunk
    ``sweep_steady_state`` call -- each chunk's lanes are sharded
    across it (chunk sizes the mesh cannot divide fall back to the
    unsharded path inside the sweep, chunk by chunk). Not compatible
    with the ladder's single-device fallback rungs, which pin a
    ``jax.default_device``; those rungs drop the mesh.
    """
    import jax
    import jax.numpy as jnp

    from ..parallel.batch import sweep_steady_state

    policy = policy or DegradationPolicy()
    conds_np = jax.tree_util.tree_map(np.asarray, conds)
    n = jax.tree_util.tree_leaves(conds_np)[0].shape[0]
    chunk = max(1, min(int(chunk), n))
    n_chunks = -(-n // chunk)

    jr = journal
    if isinstance(journal, (str, bytes)) or hasattr(journal, "__fspath__"):
        fp = conditions_fingerprint(
            conds_np, extra=(repr(opts), bool(check_stability),
                             float(pos_jac_tol), int(chunk),
                             None if tof_mask is None
                             else np.asarray(tof_mask).tolist()))
        jr = SweepJournal(str(journal), fingerprint=fp, n_lanes=n,
                          chunk=chunk, resume=resume)
    done = jr.completed() if jr is not None else {}

    report = {"n_chunks": n_chunks, "chunk": chunk, "reused": [],
              "degraded": [], "salvaged": [], "quarantined": [],
              "events": []}
    def solve_chunk(ci: int):
        """Dispatch one chunk through the full sweep + ladder machinery
        (the pipelined half: no journal/report access in here)."""
        a, b = ci * chunk, min(n, (ci + 1) * chunk)
        site = f"chunk:{ci}"
        sub = jax.tree_util.tree_map(lambda x: x[a:b], conds_np)

        def run(device=None, _sub=sub, _site=site):
            faults.inject(_site)
            ctx = (jax.default_device(device) if device is not None
                   else nullcontext())
            with ctx:
                # A ladder rung that pins a fallback device cannot
                # also shard across the mesh -- drop it for that rung.
                out = sweep_steady_state(
                    spec, jax.tree_util.tree_map(jnp.asarray, _sub),
                    tof_mask=tof_mask, opts=opts,
                    check_stability=check_stability,
                    pos_jac_tol=pos_jac_tol,
                    mesh=(mesh if device is None else None))
                out = {k: np.asarray(v) for k, v in out.items()}
            return faults.transform(_site, out)

        with span("chunk solve", chunk=ci, lanes=b - a):
            return run_chunk_with_ladder(
                run, label=site, policy=policy, validate=chunk_verdict)

    todo = [ci for ci in range(n_chunks) if ci not in done]
    # One-deep double buffering: while the main thread triages/journals
    # chunk k, the worker solves chunk k+1. Disabled under an active
    # fault plan, whose occurrence counters are drill scripts that
    # assume a strict solve->triage->solve interleave.
    use_pipeline = (pipeline and len(todo) > 1
                    and faults.active_plan() is None)
    executor = None
    futures: dict = {}
    if use_pipeline:
        from concurrent.futures import ThreadPoolExecutor
        executor = ThreadPoolExecutor(max_workers=1)

        def submit_chunk(ci):
            # A pool thread starts with an EMPTY contextvars context:
            # without explicit propagation the worker's spans/syncs
            # would land in the process root trace instead of the
            # caller's ambient RunTrace. Copying the submitter's
            # context makes double-buffered chunks SIBLING spans of
            # the same trace (tests/test_observability.py pins this).
            return executor.submit(
                contextvars.copy_context().run, solve_chunk, ci)

        futures[todo[0]] = submit_chunk(todo[0])

    parts: list[dict] = []
    try:
        for ci in range(n_chunks):
            a, b = ci * chunk, min(n, (ci + 1) * chunk)
            site = f"chunk:{ci}"
            if ci in done:
                parts.append(jr.load_chunk(done[ci]))
                report["reused"].append(ci)
                continue
            if executor is not None:
                nxt = todo.index(ci) + 1
                if nxt < len(todo):
                    futures[todo[nxt]] = submit_chunk(todo[nxt])
                out, events = futures.pop(ci).result()
            else:
                out, events = solve_chunk(ci)
            parts.append(_triage_chunk(ci, a, b, out, events, spec,
                                       tof_mask, check_stability, jr,
                                       report, n_chunks, verbose))
    finally:
        if executor is not None:
            for fut in futures.values():
                fut.cancel()
            executor.shutdown(wait=True)

    keys = parts[0].keys()
    out = {k: np.concatenate([p[k] for p in parts], axis=0)
           for k in keys}
    report["n_failed_lanes"] = int(
        np.sum(~np.asarray(out["success"], dtype=bool)))
    return out, report


def _triage_chunk(ci, a, b, out, events, spec, tof_mask,
                  check_stability, jr, report, n_chunks, verbose):
    """Main-thread half of the chunk loop: salvage/quarantine triage,
    journal record (always written in chunk order) and reporting.
    Factored out so the double-buffered and serial paths share one
    copy of the PR-1/PR-2 semantics."""
    site = f"chunk:{ci}"
    if out is None:
        out = salvage_arrays(spec, b - a, tof_mask, check_stability)
        status = "salvaged"
        report["salvaged"].append(ci)
    else:
        status = "done"
        if events:
            report["degraded"].append(ci)
        # Quarantined lanes that the rescue ladder could NOT
        # re-converge leave the chunk incomplete: record the
        # quarantine rung against this chunk's site and journal a
        # non-"done" status so a resume re-solves those lanes
        # (status "quarantined" is not in journal._COMPLETE).
        quar = np.asarray(out.get("quarantined",
                                  np.zeros(b - a)), dtype=bool)
        succ = np.asarray(out["success"], dtype=bool)
        if (quar & ~succ).any():
            lanes = (a + np.flatnonzero(quar & ~succ)).tolist()
            events.append({
                "label": site, "rung": "quarantine",
                "detail": f"{len(lanes)} quarantined lane(s) "
                          f"unrecovered; chunk left incomplete "
                          f"for resume", "lanes": lanes})
            status = "quarantined"
            report["quarantined"].append(ci)
    n_failed = int(np.sum(~np.asarray(out["success"], dtype=bool)))
    if jr is not None:
        jr.record_chunk(ci, a, b, status, arrays=out, events=events,
                        n_failed=n_failed)
    report["events"].extend(events)
    if verbose:
        import sys
        print(f"chunk {ci + 1}/{n_chunks} [{a}:{b}] {status} "
              f"({n_failed} failed lane(s))", file=sys.stderr,
              flush=True)
    return out
