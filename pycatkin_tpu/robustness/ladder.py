"""Graceful-degradation ladder: escalate per chunk instead of crashing.

A failure that exhausts the bounded retry today kills the whole sweep.
This module turns per-chunk failure handling into an explicit policy
that escalates through rungs, each strictly cheaper in outcome but
strictly more likely to complete:

  1. **retry**          -- bounded full-jitter retry with an overall
                           deadline (utils/retry.py) on the original
                           device; absorbs transport/compile flakes.
  2. **requeue**        -- re-dispatch the chunk on a DIFFERENT device
                           of the local topology (device loss / one
                           sick chip shouldn't sink the run).
  3. **host fallback**  -- run the chunk on the CPU backend: slow, but
                           a working host beats a dead accelerator.
  4. **salvage**        -- mark the chunk's lanes failed and continue;
                           the sweep ends with a structured report of
                           degraded chunks instead of a dead process.

Every transition records a degradation event (also mirrored into
utils/profiling's diagnostics log), so a run that limped home says so
in its structured report -- silent degradation is the one outcome this
module refuses to produce.
"""

from __future__ import annotations

import sys
import time
from dataclasses import dataclass

from ..obs import metrics as _metrics
from ..utils import profiling
from ..utils.retry import call_with_backend_retry


@dataclass(frozen=True)
class DegradationPolicy:
    """Per-chunk escalation policy.

    attempts/base_delay_s/max_delay_s/deadline_s parameterize rung 1's
    bounded retry (full-jitter exponential backoff, overall deadline);
    the booleans enable/disable the later rungs. ``rung_attempts``
    bounds the retry wrapped around each requeue/host-fallback
    dispatch (those rungs still deserve flake absorption, but a
    cheaper one)."""
    attempts: int = 3
    base_delay_s: float = 1.0
    max_delay_s: float = 30.0
    deadline_s: float | None = None
    requeue: bool = True
    host_fallback: bool = True
    salvage: bool = True
    rung_attempts: int = 2


class ChunkAbandonedError(RuntimeError):
    """Every enabled rung failed and salvage is disabled."""


def record_quarantine(lanes, *, label: str = "quarantine:sweep",
                      detail: str = "", events: list | None = None):
    """The ``quarantine`` rung: per-LANE demotion, below the per-chunk
    rungs of :func:`run_chunk_with_ladder`.

    A lane whose device results come back non-finite while flagged
    converged (a NaN-poisoned dispatch -- the one corruption the bool
    success flag cannot witness) is demoted to failed so the rescue
    ladder re-solves it and every downstream reduction ignores it. The
    event shape matches the chunk rungs' (`label`/`rung`/`detail`), so
    journaled runs fold quarantines into the same structured report,
    and the chunked runner marks affected chunks non-complete so a
    resume re-solves them. Returns the event dict."""
    lanes = [int(i) for i in lanes]
    ev = {"label": label, "rung": "quarantine",
          "detail": detail or f"{len(lanes)} non-finite converged-flagged "
                              f"lane(s) demoted: {lanes[:16]}"
                              f"{'...' if len(lanes) > 16 else ''}",
          "lanes": lanes}
    if events is not None:
        events.append(ev)
    profiling.record_event("degradation", **ev)
    _metrics.counter("pycatkin_ladder_rung_total",
                     "degradation-ladder rungs fired").inc(
                         rung="quarantine")
    _metrics.counter("pycatkin_quarantined_lanes_total",
                     "lanes NaN-quarantined by the sweep").inc(len(lanes))
    print(f"degradation[{label}]: quarantine: {ev['detail']}",
          file=sys.stderr, flush=True)
    return ev


def _alternate_device(exclude=None):
    """A device different from ``exclude`` (or from the default
    device), or None when the topology has only one."""
    import jax
    try:
        devs = list(jax.devices())
    except RuntimeError:
        return None
    if len(devs) < 2:
        return None
    avoid = exclude if exclude is not None else devs[0]
    for d in devs:
        if d != avoid:
            return d
    return None


def _host_device():
    import jax
    try:
        return jax.devices("cpu")[0]
    except RuntimeError:
        return None


def _first_line(exc: BaseException) -> str:
    return f"{type(exc).__name__}: " + \
        (str(exc).splitlines() or [""])[0][:200]


def run_chunk_with_ladder(run, *, label: str,
                          policy: DegradationPolicy = DegradationPolicy(),
                          validate=None, events: list | None = None):
    """Drive ``run`` through the degradation ladder.

    ``run(device=None)``: the chunk callable; ``device`` (a
    ``jax.Device``) re-targets the dispatch for the requeue and
    host-fallback rungs. ``validate(out) -> str | None``: post-hoc
    verdict on a completed call (e.g. NaN-poisoned outputs); a non-None
    string escalates exactly like an exception.

    Returns ``(result, events)`` where ``result`` is None when the
    salvage rung was reached (the caller owns building salvage
    arrays). Raises :class:`ChunkAbandonedError` when salvage is
    disabled and every enabled rung failed.
    """
    events = [] if events is None else events

    def note(rung: str, detail: str):
        ev = {"label": label, "rung": rung, "detail": detail}
        events.append(ev)
        profiling.record_event("degradation", **ev)
        _metrics.counter("pycatkin_ladder_rung_total",
                         "degradation-ladder rungs fired").inc(rung=rung)
        print(f"degradation[{label}]: {rung}: {detail}",
              file=sys.stderr, flush=True)

    def attempt(rung: str, **kwargs):
        """One rung's dispatch (retry-wrapped) + validation. Returns
        (ok, out)."""
        out = call_with_backend_retry(
            run, attempts=(policy.attempts if rung == "retry"
                           else policy.rung_attempts),
            base_delay_s=policy.base_delay_s,
            max_delay_s=policy.max_delay_s,
            deadline_s=policy.deadline_s, label=label, **kwargs)
        bad = validate(out) if validate is not None else None
        if bad:
            note(rung, f"result rejected: {bad}")
            return False, None
        return True, out

    t0 = time.monotonic()
    try:
        ok, out = attempt("retry")
        if ok:
            return out, events
    except Exception as exc:                 # noqa: BLE001 -- escalates
        note("retry", f"exhausted: {_first_line(exc)}")

    if policy.requeue:
        dev = _alternate_device()
        if dev is not None:
            note("requeue", f"re-dispatching on {dev}")
            try:
                ok, out = attempt("requeue", device=dev)
                if ok:
                    note("requeue", "recovered")
                    return out, events
            except Exception as exc:         # noqa: BLE001 -- escalates
                note("requeue", f"failed: {_first_line(exc)}")

    if policy.host_fallback:
        dev = _host_device()
        if dev is not None:
            note("host-fallback", f"re-dispatching on {dev}")
            try:
                ok, out = attempt("host-fallback", device=dev)
                if ok:
                    note("host-fallback", "recovered")
                    return out, events
            except Exception as exc:         # noqa: BLE001 -- escalates
                note("host-fallback", f"failed: {_first_line(exc)}")

    if policy.salvage:
        note("salvage", f"marking lanes failed after "
                        f"{time.monotonic() - t0:.1f} s of escalation")
        return None, events
    raise ChunkAbandonedError(
        f"{label}: every enabled degradation rung failed and salvage "
        "is disabled")
