"""Host-side per-lane failure forensics for batched sweeps.

A production sweep that limps home (quarantined lanes, exhausted
rescues, demoted stability verdicts) must be able to SAY what happened
to each lane without anyone re-running it under a debugger. This
module assembles the forensic record from data the sweep already
carries: the per-lane diagnostics of ``SteadyStateResults``
(verdict-test breakdown, final residual, iterations/attempts, PTC
pseudo-step at exit -- solvers/newton.py), the quarantine mask
(parallel/batch.py), and the structured ladder/retry/quarantine events
(robustness/ladder.py, utils/profiling.py).

Everything is plain-JSON-serializable host data: reports travel
through journals, ``bench.py --forensics`` output and test assertions
unchanged.
"""

from __future__ import annotations

import numpy as np

from ..obs.manifest import run_manifest

# Diagnostic keys lifted verbatim (as python scalars) from a sweep
# result dict into each lane report, when present.
_VERDICT_KEYS = ("rate_ok", "pos_ok", "sums_ok")
_SCALAR_KEYS = ("residual", "dt_exit")
_INT_KEYS = ("iterations", "attempts")
_BOOL_KEYS = ("success", "quarantined", "stable")


def _lane_conditions(conds, lane: int, n_lanes: int) -> dict:
    """Per-lane condition values: every leaf of the conditions pytree
    batched over the lane axis, as python scalars (or short lists)."""
    import jax

    out = {}
    leaves = jax.tree_util.tree_flatten_with_path(conds)[0]
    for path, leaf in leaves:
        arr = np.asarray(leaf)
        if arr.ndim == 0 or arr.shape[0] != n_lanes:
            continue
        name = "/".join(str(getattr(p, "name", getattr(p, "key",
                                                       getattr(p, "idx",
                                                               p))))
                        for p in path)
        val = arr[lane]
        if val.ndim == 0:
            out[name] = float(val)
        elif val.size <= 8:
            out[name] = [float(v) for v in val.ravel()]
    return out


def lane_report(out: dict, lane: int, conds=None,
                events: list | None = None) -> dict:
    """Forensic record for ONE lane of a sweep result dict.

    ``out`` is the dict returned by ``sweep_steady_state`` /
    ``chunked_sweep_steady_state`` (device or numpy arrays both fine).
    ``events``: structured degradation/retry events; the lane's ladder
    history is the subset naming this lane (events carrying a
    ``lanes`` list) plus every lane-anonymous event (chunk-level rungs
    apply to all their lanes).
    """
    n_lanes = len(np.asarray(out["success"]))
    rep: dict = {"lane": int(lane)}
    for k in _BOOL_KEYS:
        if k in out:
            rep[k] = bool(np.asarray(out[k])[lane])
    for k in _INT_KEYS:
        if k in out:
            rep[k] = int(np.asarray(out[k])[lane])
    for k in _SCALAR_KEYS:
        if k in out:
            rep[k] = float(np.asarray(out[k])[lane])
    verdict = {k: bool(np.asarray(out[k])[lane])
               for k in _VERDICT_KEYS if k in out}
    if verdict:
        rep["verdict"] = verdict
    if "tof" in out:
        rep["tof"] = float(np.asarray(out["tof"])[lane])
    if conds is not None:
        rep["conditions"] = _lane_conditions(conds, int(lane), n_lanes)
    if events is not None:
        rep["history"] = [ev for ev in events
                          if int(lane) in ev.get("lanes", [])
                          or "lanes" not in ev]
    return rep


def worker_lifecycle(events) -> dict:
    """Summarize an elastic run's worker-lifecycle events (the
    ``kind="worker"`` records of robustness/scheduler.py: supervisor
    events from ``events.jsonl`` or ``report["events"]``) into the
    forensic shape: how many restarts, which leases expired or were
    stolen, which spans were bisected or quarantined -- the "who died
    and what happened to their work" half of a degraded run.

    Returns zeros/empties for runs with no worker events, so the
    section folds into every report harmlessly."""
    evs = [e for e in (events or []) if e.get("kind") == "worker"]
    by_action: dict[str, list] = {}
    for e in evs:
        by_action.setdefault(e.get("action", "?"), []).append(e)

    def labels(action):
        return [e.get("label", "?") for e in by_action.get(action, [])]

    restarts: dict[str, int] = {}
    for e in by_action.get("restart", ()):
        lbl = e.get("label", "?")
        restarts[lbl] = restarts.get(lbl, 0) + 1
    return {
        "n_events": len(evs),
        "restarts": restarts,
        "n_restarts": sum(restarts.values()),
        "spawns": len(by_action.get("spawn", ())),
        "abandoned": labels("abandon"),
        "killed_stalled": labels("kill-stalled"),
        "leases_expired": labels("lease-expired"),
        "leases_stolen": [
            {"task": e.get("label"), "by": e.get("owner"),
             "from": e.get("stolen_from")}
            for e in by_action.get("lease-stolen", ())],
        "bisected": labels("task-bisected"),
        "quarantined": labels("task-quarantined"),
    }


def sweep_failure_report(out: dict, conds=None,
                         events: list | None = None,
                         max_lanes: int = 256) -> dict:
    """Assemble the end-of-sweep forensic report: one record per
    failed or quarantined lane (capped at ``max_lanes``; the cap is
    recorded so truncation is never silent), plus sweep-level counts
    and the full structured event log.

    ``events`` should be the run's degradation/retry events -- e.g. a
    chunked run's ``report["events"]``, or the matching subset of
    ``utils.profiling.drain_events()`` for a plain sweep.
    """
    success = np.asarray(out["success"]).astype(bool)
    n = len(success)
    quarantined = np.asarray(
        out.get("quarantined", np.zeros(n))).astype(bool)
    bad = np.flatnonzero(~success | quarantined)
    report = {
        "n_lanes": int(n),
        "n_failed": int(np.sum(~success)),
        "n_quarantined": int(np.sum(quarantined)),
        "quarantined_lanes": [int(i) for i in
                              np.flatnonzero(quarantined)],
        "truncated": bool(len(bad) > max_lanes),
        "lanes": [lane_report(out, int(i), conds=conds, events=events)
                  for i in bad[:max_lanes]],
        "events": list(events or []),
        # Elastic runs thread their lifecycle events through the same
        # ``events`` list, so the worker section costs nothing to
        # always include.
        "worker_lifecycle": worker_lifecycle(events),
        # Self-describing forensics: the run manifest records what
        # code/backend/knobs produced the failures being dissected.
        "manifest": run_manifest(),
    }
    return report


def format_failure_report(report: dict) -> str:
    """Human-readable rendering of :func:`sweep_failure_report`."""
    lines = [f"sweep forensics: {report['n_failed']} failed / "
             f"{report['n_quarantined']} quarantined of "
             f"{report['n_lanes']} lane(s)"]
    if report["quarantined_lanes"]:
        lines.append(f"  quarantined lanes: "
                     f"{report['quarantined_lanes']}")
    for rep in report["lanes"]:
        verdict = rep.get("verdict", {})
        failing = [k for k, v in verdict.items() if not v]
        bits = [f"lane {rep['lane']}:"]
        if rep.get("quarantined"):
            bits.append("QUARANTINED")
        bits.append("converged" if rep.get("success") else "failed")
        if failing:
            bits.append(f"failing tests: {', '.join(failing)}")
        if "residual" in rep:
            bits.append(f"residual {rep['residual']:.3g}")
        if "dt_exit" in rep:
            bits.append(f"dt_exit {rep['dt_exit']:.3g}")
        if "iterations" in rep:
            bits.append(f"{rep['iterations']} it / "
                        f"{rep.get('attempts', 0)} att")
        lines.append("  " + " ".join(bits))
        for key, val in rep.get("conditions", {}).items():
            lines.append(f"    {key} = {val}")
        for ev in rep.get("history", []):
            lines.append(f"    {ev.get('label', '?')}: "
                         f"{ev.get('rung', ev.get('kind', '?'))}: "
                         f"{ev.get('detail', '')}")
    if report.get("truncated"):
        lines.append(f"  (lane reports truncated at "
                     f"{len(report['lanes'])})")
    wl = report.get("worker_lifecycle") or {}
    if wl.get("n_events"):
        lines.append(f"  worker lifecycle: {wl['spawns']} spawn(s), "
                     f"{wl['n_restarts']} restart(s)")
        for lbl, cnt in sorted(wl.get("restarts", {}).items()):
            lines.append(f"    {lbl}: restarted {cnt}x")
        for lbl in wl.get("killed_stalled", []):
            lines.append(f"    {lbl}: killed for stalled heartbeat")
        for lbl in wl.get("leases_expired", []):
            lines.append(f"    {lbl}: lease expired, requeued")
        for st in wl.get("leases_stolen", []):
            lines.append(f"    {st.get('task')}: stolen by "
                         f"{st.get('by')} from {st.get('from')}")
        for lbl in wl.get("bisected", []):
            lines.append(f"    {lbl}: bisected and requeued")
        for lbl in wl.get("quarantined", []):
            lines.append(f"    {lbl}: quarantined at minimum size")
        for lbl in wl.get("abandoned", []):
            lines.append(f"    {lbl}: slot abandoned "
                         f"(restart budget exhausted)")
    return "\n".join(lines)
