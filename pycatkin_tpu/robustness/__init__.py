"""Failure subsystem: deterministic fault injection, the graceful-
degradation ladder, and journaled checkpoint/resume for chunked sweeps.

See docs/failure_model.md for the full failure model; the three layers:

- :mod:`.faults`  -- scripted transient/permanent/NaN/stall faults at
  named dispatch sites (env ``PYCATKIN_FAULTS`` or
  :func:`faults.fault_scope`), making every failure branch testable.
- :mod:`.ladder`  -- per-chunk escalation: bounded retry -> requeue on
  another device -> CPU host fallback -> salvage + structured report.
- :mod:`.journal` / :mod:`.chunked` -- append-only sweep journal and
  the resumable chunked sweep runner built on it.
- :mod:`.scheduler` -- elastic multi-process dispatch: lease-based
  work queue, worker supervision/restart, poison-span bisection and
  the chaos drill.
"""

from .chunked import (chunk_verdict, chunked_sweep_steady_state,
                      salvage_arrays)
from .faults import (FaultPlan, FaultSpec, InjectedDeviceLossError,
                     fault_scope)
from .journal import (JournalMismatchError, SweepJournal,
                      conditions_fingerprint)
from .forensics import (format_failure_report, sweep_failure_report,
                        worker_lifecycle)
from .ladder import (ChunkAbandonedError, DegradationPolicy,
                     record_quarantine, run_chunk_with_ladder)
from .scheduler import WorkQueue, chaos_drill, run_elastic

__all__ = [
    "ChunkAbandonedError",
    "DegradationPolicy",
    "FaultPlan",
    "FaultSpec",
    "InjectedDeviceLossError",
    "JournalMismatchError",
    "SweepJournal",
    "WorkQueue",
    "chaos_drill",
    "chunk_verdict",
    "chunked_sweep_steady_state",
    "conditions_fingerprint",
    "fault_scope",
    "format_failure_report",
    "record_quarantine",
    "run_chunk_with_ladder",
    "run_elastic",
    "salvage_arrays",
    "sweep_failure_report",
    "worker_lifecycle",
]
