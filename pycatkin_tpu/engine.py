"""The device engine: pure jitted functions over (ModelSpec, Conditions).

Composition of the kernel layers into the quantities the reference computes
through its object graph:

    free_energies      <- ops.thermo + compiled scaling relations
    reaction_energies  <- stoichiometric sums (reference reaction.py:43-91)
    rate_constants     <- ops.rates dispatch (reference reaction.py:94-168)
    steady_state       <- solvers.newton PTC (reference find_steady paths)
    transient          <- solvers.ode TR-BDF2 (reference solve_odes)
    tof / activity     <- reference old_system.py:470-529
    drc                <- autodiff through the steady solve via the implicit
                          function theorem (replaces the reference's
                          2*n_reactions finite-difference re-solves,
                          old_system.py:490-515); FD mode kept for parity.

Every function takes the spec as a static closure constant and a
:class:`Conditions` pytree of runtime inputs, so sweeps over T, p,
descriptor energies, noise or rate multipliers are ``jax.vmap`` axes.
"""

from __future__ import annotations

import os
from functools import lru_cache as _lru_cache, partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from . import precision as _precision
from .constants import (JtoeV, LOG_H_OVER_KB, R, bartoPa, eVtokJ, h, kB)
from .frontend.spec import REACTOR_CSTR, REACTOR_ID, Conditions, ModelSpec
from .lint.hotpath import hotpath
from .obs import metrics as _metrics
from .ops import linalg, network, rates, thermo
from .solvers import newton
from .solvers.newton import SolverOptions, SteadyStateResults
from .solvers.ode import (ODEOptions, init_state as ode_init_state,
                          integrate, integrate_state as ode_integrate_state,
                          log_time_grid)
from .utils.profiling import host_sync

eVtoJmol = eVtokJ * 1.0e3


class FreeEnergies(NamedTuple):
    gelec: jnp.ndarray   # [n_s] electronic (scaling relations resolved)
    gfree: jnp.ndarray   # [n_s] total free energy
    gvibr: jnp.ndarray
    gtran: jnp.ndarray
    grota: jnp.ndarray


class ReactionEnergies(NamedTuple):
    dErxn: jnp.ndarray   # [n_r] J/mol
    dGrxn: jnp.ndarray
    dEa_fwd: jnp.ndarray
    dGa_fwd: jnp.ndarray
    dEa_rev: jnp.ndarray
    dGa_rev: jnp.ndarray


def free_energies(spec: ModelSpec, cond: Conditions) -> FreeEnergies:
    """Electronic + free energies of every species at (T, p) [eV]."""
    gv, gt, gr = thermo.thermal_contributions(
        cond.T, cond.p,
        freq=spec.freq, fmask=spec.fmask, mass=spec.mass, sigma=spec.sigma,
        inertia=spec.inertia, is_gas=spec.is_gas, is_linear=spec.is_linear,
        mix=spec.mix,
        gvibr0=spec.gvibr0, gvibr_mask=spec.gvibr_mask,
        gtran0=spec.gtran0, gtran_mask=spec.gtran_mask,
        grota0=spec.grota0, grota_mask=spec.grota_mask)

    e_full = jnp.asarray(cond.gelec)
    if spec.scl_idx.size:
        # Linear scaling relations, solved as a (tiny) linear system to
        # allow scaling states referencing each other
        # (reference state.py:490-517 evaluated sequentially).
        b = spec.scl_b + spec.scl_We @ e_full + spec.scl_WuE @ cond.uE_rxn
        n_sc = spec.scl_idx.size
        # scaling_solve, not solve: the builders caching this trace do
        # not key on the kernel/tier knobs, so the solve path must not
        # consult them (PCL014 cache-key-completeness).
        e_scl = linalg.scaling_solve(jnp.eye(n_sc) - spec.scl_Ws, b)
        e_full = e_full.at[spec.scl_idx].set(e_scl)

    mods = spec.add0 + cond.eps
    g0 = e_full + gv + gt + gr + mods
    if spec.has_udar:
        # use_descriptor_as_reactant free-energy assembly
        # (reference state.py:519-565).
        corr = (spec.udar_Ce @ e_full + spec.udar_Cg @ g0 +
                spec.udar_CuE @ cond.uE_rxn + spec.udar_CuG @ cond.uG_rxn)
        g = jnp.where(spec.udar_mask > 0, e_full + corr + mods, g0)
    else:
        g = g0
    if spec.has_gfree:
        g = jnp.where(spec.gfree_mask > 0, spec.gfree0 + mods, g)
    return FreeEnergies(gelec=e_full, gfree=g, gvibr=gv, gtran=gt, grota=gr)


def reaction_energies(spec: ModelSpec, cond: Conditions,
                      fe: FreeEnergies | None = None) -> ReactionEnergies:
    """Reaction energies and barriers [J/mol] (reference reaction.py:43-91,
    222-274, 312-339). User-defined reactions take their energies from the
    condition vectors; TS-less reactions have zero barriers."""
    if fe is None:
        fe = free_energies(spec, cond)
    e, g = fe.gelec, fe.gfree

    dE = (spec.SP - spec.SR) @ e * eVtoJmol
    dG = (spec.SP - spec.SR) @ g * eVtoJmol
    dE = jnp.where(cond.u_rxn_mask > 0, cond.uE_rxn * eVtoJmol, dE)
    dG = jnp.where(cond.u_rxn_mask > 0, cond.uG_rxn * eVtoJmol, dG)

    dEa_ts = (spec.ST - spec.SR) @ e * eVtoJmol * spec.has_TS
    dGa_ts = (spec.ST - spec.SR) @ g * eVtoJmol * spec.has_TS
    # User-defined reactions never fall back to TS sums
    # (reference reaction.py:222-274 ignores TS states entirely).
    dEa = jnp.where(spec.is_user > 0,
                    cond.uEa * eVtoJmol * cond.u_bar_mask, dEa_ts)
    dGa = jnp.where(spec.is_user > 0,
                    cond.uGa * eVtoJmol * cond.u_bar_mask, dGa_ts)
    return ReactionEnergies(
        dErxn=dE, dGrxn=dG, dEa_fwd=dEa, dGa_fwd=dGa,
        dEa_rev=dEa - dE, dGa_rev=dGa - dG)


def rate_constants(spec: ModelSpec, cond: Conditions,
                   re: ReactionEnergies | None = None):
    """(kf, kr, Keq) for every reaction (reference reaction.py:94-168)."""
    if re is None:
        re = reaction_energies(spec, cond)
    act = cond.is_activated
    return rates.rate_constants(
        cond.T,
        dGrxn=re.dGrxn, dErxn=re.dErxn, dGa_fwd=re.dGa_fwd,
        is_arr=act,
        is_ads=spec.is_ads * (1.0 - act),
        is_des=spec.is_des * (1.0 - act),
        is_ghost=spec.is_ghost,
        reversible=spec.reversible,
        area=spec.area, gas_mass=spec.gas_mass, gas_sigma=spec.gas_sigma,
        gas_inertia=spec.gas_inertia, gas_polyatomic=spec.gas_polyatomic,
        kscale=cond.kscale,
        collision_des=(spec.desorption_model == "collision"))


def _reactor_terms(spec: ModelSpec, cond: Conditions):
    if spec.reactor_type == REACTOR_CSTR:
        sigma = kB * cond.T * spec.catalyst_area / spec.volume
        return dict(reactor_type=REACTOR_CSTR,
                    sigma_over_bar=sigma / bartoPa,
                    inv_tau=1.0 / spec.residence_time,
                    inflow=jnp.asarray(cond.inflow))
    return dict(reactor_type=REACTOR_ID, sigma_over_bar=0.0, inv_tau=0.0,
                inflow=jnp.asarray(cond.inflow))


def make_rhs_and_scale(spec: ModelSpec, cond: Conditions, kf=None, kr=None):
    """Build (rhs, rhs_and_scale) closures over ONE shared static reactor
    dict, so any consumer pairing the ODE with its gross-flux scale (the
    steadiness oracle, the steady solver) sees exactly the reactor model
    being integrated."""
    if kf is None:
        kf, kr, _ = rate_constants(spec, cond)
    terms = _reactor_terms(spec, cond)
    static = dict(reac_idx=spec.reac_idx, prod_idx=spec.prod_idx,
                  is_gas=spec.is_gas, stoich=spec.stoich,
                  is_adsorbate=spec.is_adsorbate, **terms)

    def rhs(y):
        return network.reactor_rhs(y, 0.0, kf, kr, **static)

    def rhs_and_scale(y):
        return network.reactor_rhs_and_scale(y, 0.0, kf, kr, **static)

    return rhs, rhs_and_scale


def make_rhs(spec: ModelSpec, cond: Conditions, kf=None, kr=None):
    """Build the reactor ODE right-hand side y -> dy/dt as a closure."""
    rhs, _ = make_rhs_and_scale(spec, cond, kf, kr)
    return rhs


def get_dydt(spec: ModelSpec, cond: Conditions, y):
    """dy/dt of the full solution vector (reference system.py:396-416)."""
    return make_rhs(spec, cond)(y)


def get_jacobian(spec: ModelSpec, cond: Conditions, y):
    """d(dy/dt)/dy (reference system.py:493-508) via forward autodiff."""
    return jax.jacfwd(make_rhs(spec, cond))(y)


def reaction_rates_at(spec: ModelSpec, cond: Conditions, y, kf=None, kr=None):
    """Per-reaction forward/reverse rates at composition y
    (reference old_system.py:202-225)."""
    if kf is None:
        kf, kr, _ = rate_constants(spec, cond)
    return network.reaction_rates(jnp.asarray(y), kf, kr,
                                  reac_idx=spec.reac_idx,
                                  prod_idx=spec.prod_idx,
                                  is_gas=spec.is_gas)


# ----------------------------------------------------------------------
# solvers
def _dynamic_residual(spec: ModelSpec, cond: Conditions, kf, kr):
    """Residual-only view of :func:`_dynamic_fscale` (the unused gross
    output is dead-code-eliminated under jit)."""
    fscale, dyn, y_base = _dynamic_fscale(spec, cond, kf, kr)
    return (lambda x: fscale(x)[0]), dyn, y_base


def _dynamic_setup(spec: ModelSpec, cond: Conditions):
    """(dyn, static, y_base) shared by every dynamic-restriction helper,
    so the residual, its scale, and both Jacobian implementations are
    guaranteed to describe the same reactor model."""
    dyn = jnp.asarray(spec.dynamic_indices)
    terms = _reactor_terms(spec, cond)
    static = dict(reac_idx=spec.reac_idx, prod_idx=spec.prod_idx,
                  is_gas=spec.is_gas, stoich=spec.stoich,
                  is_adsorbate=spec.is_adsorbate, **terms)
    return dyn, static, jnp.asarray(cond.y0)


def _cast_float_leaves(tree: dict, dtype) -> dict:
    """Copy of a kwargs dict with every floating-point array leaf cast
    to ``dtype`` (index/bool/int leaves untouched) -- the one seam that
    rebases a reactor closure onto the precision-tier bulk dtype."""
    out = {}
    for k, v in tree.items():
        a = jnp.asarray(v)
        out[k] = a.astype(dtype) if jnp.issubdtype(a.dtype,
                                                   jnp.floating) else v
    return out


def _dynamic_fscale(spec: ModelSpec, cond: Conditions, kf, kr,
                    dtype=None):
    """fscale(x) -> (F, gross) over the dynamic indices: the residual
    plus the per-species gross-flux scale, computed in one pass (the
    solver's net-vs-gross convergence measure).

    ``dtype``: evaluation dtype of the closure (default: whatever the
    operands carry, i.e. f64). The precision-tier bulk pass requests
    ``precision.bulk_dtype(tier)``: the rate constants are ALWAYS
    computed in f64 first (exp(-Ea/kT) spans ~30 decades -- evaluating
    it in f32 overflows/underflows outright) and only the finished
    kf/kr/y0/stoichiometry values are cast down here, so the f32
    closure evaluates the same finished numbers at reduced precision.
    """
    dyn, static, y_base = _dynamic_setup(spec, cond)
    if dtype is not None:
        static = _cast_float_leaves(static, dtype)
        kf = jnp.asarray(kf, dtype)
        kr = jnp.asarray(kr, dtype)
        y_base = jnp.asarray(y_base, dtype)
    # ABI-padded specs carry a dynamic validity mask; pad slots get the
    # exactly-decoupled residual x' = -x, so the padded Jacobian is
    # blkdiag(J_real, -I): real solutions, verdicts and certificates
    # match the unpadded system bit-for-bit.
    dyn_mask = getattr(spec, "dyn_mask", None)

    def fscale(x):
        y = y_base.at[dyn].set(x)
        F, gross = network.reactor_rhs_and_scale(y, 0.0, kf, kr, **static)
        F, gross = F[dyn], gross[dyn]
        if dyn_mask is not None:
            F = jnp.where(dyn_mask > 0, F, -x)
            gross = jnp.where(dyn_mask > 0, gross, 1.0)
        return F, gross
    return fscale, dyn, y_base


def _dynamic_jacobian(spec: ModelSpec, cond: Conditions, kf, kr):
    """jac(x) -> d(residual)/dx over the dynamic indices, via the
    closed-form reactor Jacobian (ops.network.reactor_jacobian)
    restricted to the dynamic block -- clamped entries contribute no
    columns. NOT the hot path: measured SLOWER than jacfwd on TPU for
    both small and 200-species systems (XLA batches the n_dyn JVP
    passes well; the closed form's gather/one-hot contractions lower
    poorly). Kept as the independent implementation backing the
    jacfwd-vs-closed-form parity tests."""
    dyn, static, y_base = _dynamic_setup(spec, cond)

    def jac(x):
        y = y_base.at[dyn].set(x)
        J = network.reactor_jacobian(y, 0.0, kf, kr, **static)
        return J[jnp.ix_(dyn, dyn)]
    return jac


def steady_state(spec: ModelSpec, cond: Conditions,
                 x0=None, key=None,
                 opts: SolverOptions = SolverOptions(),
                 strategy: str = "ptc",
                 use_x0=None, tier: str = "f64") -> SteadyStateResults:
    """Steady-state solve over the dynamic indices (adsorbates, plus gas
    for CSTR), gas clamped otherwise -- reference system.py:512-639 /
    old_system.py:385-434 semantics with on-device retry logic.
    ``strategy``: 'ptc' or 'lm' (see newton.solve_steady).
    ``use_x0``: optional traced boolean selecting between the supplied
    ``x0`` (True) and the default initial coverages (False) -- lets the
    consolidated rescue program keep seeded/unseeded variants inside
    ONE compiled program instead of two (x0=None is a different
    treedef, hence a different program).
    ``tier``: precision tier (docs/perf_precision_tiers.md). Under
    "f32-polish" a SECOND closure over the same finished rate constants
    is built at the bulk dtype and the solver runs its march there,
    polishing and verdicting in f64; only the static single-attempt
    fast pass uses it (newton.solve_steady gates), so rescue solves
    through this same entry point stay pure f64.

    Batching contract: this function is nested under up to TWO vmap
    levels by the sweep layer -- lanes (conditions) and, for packed
    multi-tenant buckets, tenants (mechanism operands,
    parallel/batch.py's packed fused program). Per-lane bit-identity
    across those nestings is what the packed-batching acceptance gate
    pins, and it holds because every data-dependent loop in here and in
    newton.solve_steady is a ``lax.while_loop``/``lax.cond`` whose
    batching rule select-masks finished elements without changing any
    element's arithmetic, and no reduction ever crosses the lane or
    tenant axis. Do not introduce cross-lane reductions, host callbacks
    or lane-position-dependent logic in this call tree; they would
    break the tenant-packing equivalence silently."""
    kf, kr, _ = rate_constants(spec, cond)
    fscale, dyn, y_base = _dynamic_fscale(spec, cond, kf, kr)
    jac = jax.jacfwd(lambda x: fscale(x)[0])
    bulk_fns = None
    if tier != "f64":
        bulk_fscale, _, _ = _dynamic_fscale(
            spec, cond, kf, kr, dtype=_precision.bulk_dtype(tier))
        bulk_fns = (bulk_fscale,
                    jax.jacfwd(lambda x: bulk_fscale(x)[0]))
    if x0 is None:
        x0 = y_base[dyn]
    elif use_x0 is not None:
        x0 = jnp.where(use_x0, jnp.asarray(x0), y_base[dyn])
    groups_dyn = jnp.asarray(spec.groups)[:, dyn]
    (x, success, res, iters, attempts, rate_ok, pos_ok, sums_ok,
     dt_exit, chords) = newton.solve_steady(
        fscale, jac, jnp.asarray(x0), groups_dyn, opts, key=key,
        strategy=strategy, tier=tier, bulk_fns=bulk_fns)
    y_full = y_base.at[dyn].set(x)
    return SteadyStateResults(x=y_full, success=success, residual=res,
                              iterations=iters, attempts=attempts,
                              rate_ok=rate_ok, pos_ok=pos_ok,
                              sums_ok=sums_ok, dt_exit=dt_exit,
                              chords=chords)


def steady_jacobian(spec: ModelSpec, cond: Conditions, x_dyn):
    """Jacobian of the dynamic residual at x_dyn (the surface-reduced
    system; reference system.py:547-564 ``_jac_ss``)."""
    kf, kr, _ = rate_constants(spec, cond)
    residual, _, _ = _dynamic_residual(spec, cond, kf, kr)
    return jax.jacfwd(residual)(jnp.asarray(x_dyn))


def check_stability(spec: ModelSpec, cond: Conditions, y_full,
                    pos_tol: float = 1e-2) -> bool:
    """Jacobian-eigenvalue stability verdict for one steady state
    (reference solver.py:102-106): every eigenvalue's real part must lie
    below ``pos_tol``. Nonsymmetric ``eig`` is host-only in XLA, so this
    runs outside jit on the gathered solution."""
    dyn = jnp.asarray(spec.dynamic_indices)
    J = steady_jacobian(spec, cond, jnp.asarray(y_full)[dyn])
    return newton.jacobian_eigenvalues_stable(J, pos_tol)


@hotpath
def _transient_closures(spec: ModelSpec, cond: Conditions,
                        steady_rel: float = ODEOptions().steady_rel):
    """(rhs, jac, steady_fn, relax_fn) for the transient integrator.

    ``steady_rel``: the relative net-vs-gross tolerance of the relax
    oracle -- threaded from the active ODEOptions/SolverOptions so a
    caller who tightens the steady verdict gets transient error-test
    waiving judged at the same level (not at the class default).

    Two oracles with distinct jobs. ``steady_fn`` (freeze): PURELY
    relative threshold at the f64 cancellation floor of the flux sums
    -- 8 eps; no absolute term, because an absolute floor mistakes
    metastable plateaus (DMTM's s2OCH4 at 400 K drains into sCH3OH
    over ~1e10 s with tiny |net| but net/gross >= 1e-10) for steady
    states, and anything above the floor can still be REAL drift (on
    TPU's pair-emulated f64 the noise floor ~1.3e-10 overlaps the
    slowest real drift -- pointwise freezing there picks the wrong
    state; measured on DMTM 400 K). ``relax_fn`` (accelerate): once
    the state satisfies the steady VERDICT's relative tolerance, the
    noise-dominated local-error test is waived so huge L-stable steps
    relax the tail instead of stalling against max_steps -- the state
    keeps evolving, so real sub-verdict drift still completes."""
    rhs, rhs_and_scale = make_rhs_and_scale(spec, cond)
    jac = jax.jacfwd(rhs)
    floor = 8.0 * float(jnp.finfo(jnp.float64).eps)  # sync-ok: finfo is a host constant, no device value pulled
    verdict_rel = steady_rel

    def steady_fn(y):
        net, gross = rhs_and_scale(y)
        return jnp.all(jnp.abs(net) <= floor * gross)

    def relax_fn(y):
        net, gross = rhs_and_scale(y)
        return jnp.all(jnp.abs(net) <= verdict_rel * gross)

    return rhs, jac, steady_fn, relax_fn


def transient_state(spec: ModelSpec, cond: Conditions, state, save_ts,
                    opts: ODEOptions = ODEOptions()):
    """Advance a transient carry through a chunk of save times.

    Jittable chunk worker for host-driven integration: one long
    integration becomes several bounded device calls (a single
    multi-minute kernel trips execution watchdogs on shared TPU
    runtimes), all served by ONE compiled program when chunks share a
    shape. Returns (state, ys_chunk)."""
    rhs, jac, steady_fn, relax_fn = _transient_closures(
        spec, cond, steady_rel=opts.steady_rel)
    return ode_integrate_state(rhs, jac, state, save_ts, opts,
                               steady_fn=steady_fn, relax_fn=relax_fn)


def transient_finish(spec: ModelSpec, cond: Conditions, y_last, ok,
                     sopts: SolverOptions = SolverOptions()):
    """Newton finish (the reference's own integrate-then-root pattern,
    old_system.py:385-434): when relaxed stepping still runs out of
    max_steps short of t_end -- h sawtooths at the stage-convergence
    ceiling while the span is astronomic -- but the state already
    satisfies the steady verdict, the remaining "integration" is pure
    attractor relaxation; land on it exactly with the PTC solver.
    Guarded by closeness so a Newton jump to a DIFFERENT root (basin
    not actually reached) keeps the honest failure flag.
    Returns (y_final, ok)."""
    _, _, _, relax_fn = _transient_closures(
        spec, cond, steady_rel=sopts.rate_tol_rel)
    dyn = jnp.asarray(spec.dynamic_indices)
    res = steady_state(spec, cond, x0=y_last[dyn], opts=sopts)
    # 5e-2: wide enough to absorb clamp-projected pseudo-state offsets
    # (ODEOptions.clamp_lo) on top of ordinary relaxation distance,
    # still far inside typical inter-root separations (>= 0.1 on the
    # bistable test mechanism).
    near = jnp.max(jnp.abs(res.x - y_last)) <= 5.0e-2
    good = res.success & relax_fn(y_last) & near
    replace = (~ok) & good
    return jnp.where(replace, res.x, y_last), ok | good


def transient(spec: ModelSpec, cond: Conditions, save_ts,
              opts: ODEOptions = ODEOptions()):
    """Integrate the reactor ODEs over ``save_ts`` (reference
    old_system.py:315-378). Returns (ys [t, n_s], ok).

    One-shot jittable form; prefer :func:`transient_chunked` (or
    ``parallel.batch.batch_transient``) from the host for long save
    grids, which bound per-call device time."""
    rhs, jac, steady_fn, relax_fn = _transient_closures(
        spec, cond, steady_rel=opts.steady_rel)
    ys, ok = integrate(rhs, jac, jnp.asarray(cond.y0, dtype=jnp.float64),
                       jnp.asarray(save_ts), opts, steady_fn=steady_fn,
                       relax_fn=relax_fn)
    y_fin, ok = transient_finish(spec, cond, ys[-1], ok,
                                 sopts=finish_options(opts))
    return ys.at[-1].set(y_fin), ok


@_precision.kernel_keyed
@_lru_cache(maxsize=16)
def _transient_chunk_program(spec: ModelSpec, opts: ODEOptions,
                             kernel: str = "xla"):
    # ``kernel`` is a cache key only (precision.kernel_keyed): the
    # implicit ODE stages embed make_msolve direction solves, which
    # bake the PYCATKIN_LINALG_KERNEL choice in at trace time.
    def run(cond, state, part):
        return transient_state(spec, cond, state, part, opts)
    return jax.jit(run)


@_precision.kernel_keyed
@_lru_cache(maxsize=16)
def _transient_finish_program(spec: ModelSpec, sopts: SolverOptions,
                              kernel: str = "xla"):
    def run(cond, y_last, ok):
        return transient_finish(spec, cond, y_last, ok, sopts=sopts)
    return jax.jit(run)


def finish_options(opts: ODEOptions) -> SolverOptions:
    """SolverOptions for the Newton finish matching an ODEOptions: the
    finish verdict is judged at the integration's own steady_rel level,
    so a caller who tightens the transient oracle gets the endpoint
    judged at the same (not the class-default) tolerance."""
    return SolverOptions(rate_tol_rel=opts.steady_rel)


FUSED_TRANSIENT_ENV = "PYCATKIN_FUSED_TRANSIENT"


def fused_transient_enabled() -> bool:
    """Route transients through the fused single-dispatch scan program
    (``parallel.batch._fused_batch_transient``)? Mirrors the steady
    sweeps' ``PYCATKIN_FUSED_SWEEP`` gate: default on, disabled by
    ``PYCATKIN_FUSED_TRANSIENT=0`` or under an active fault plan --
    the fault-injection sites (chunk boundaries, finish) live on the
    host-driven path, so drills must keep exercising it."""
    from .robustness.faults import active_plan
    if active_plan() is not None:
        return False
    return os.environ.get(FUSED_TRANSIENT_ENV, "1").strip().lower() not in (
        "0", "off", "none", "disabled", "false")


def _transient_materialized(n: int) -> None:
    """Count dense-output materializations (blocking device->host pulls
    of transient save buffers). The chunked drive pays one per chunk
    plus the finish; the fused path pays exactly one bundle."""
    _metrics.counter(
        "pycatkin_transient_materializations_total",
        "blocking transient save-buffer materializations").inc(n)


@hotpath
def chunked_transient_drive(step, finish, conds, y0, save_ts,
                            opts: ODEOptions, chunk: int, batched: bool,
                            force_chunking: bool = False):
    """Shared host-side chunking protocol for single-lane AND batched
    transients: process the save grid in fixed-size chunks, each a
    bounded jitted device call (padding the last chunk with repeats of
    the final time, which are no-ops), so per-call device time stays
    under shared-runtime execution watchdogs; then apply the Newton
    finish to the endpoint. ``step(conds, state, part)`` and
    ``finish(conds, y_last, ok)`` are the (possibly vmapped) compiled
    programs; ``batched`` says whether arrays carry a leading lane axis;
    ``force_chunking`` keeps the real multi-chunk loop even off-TPU
    (the bench baseline measures the per-chunk dispatch cost the fused
    path removes). Returns (ys, ok)."""
    save_ts = np.asarray(save_ts)  # sync-ok: host-provided save grid
    if jax.default_backend() != "tpu" and not force_chunking:
        # No execution watchdog off-TPU: one call minimizes dispatch.
        chunk = max(chunk, len(save_ts))
    if batched:
        state = jax.vmap(lambda y: ode_init_state(y, save_ts[0], opts))(y0)
        blocks = [np.asarray(y0)[:, None, :]]  # sync-ok: y0 is host input
    else:
        state = ode_init_state(y0, save_ts[0], opts)
        blocks = [np.asarray(y0)[None, :]]  # sync-ok: y0 is host input
    ts = save_ts[1:]
    for i in range(0, len(ts), chunk):
        part = ts[i:i + chunk]
        npad = chunk - len(part)
        if npad:
            part = np.concatenate([part, np.full(npad, ts[-1])])
        state, ys_chunk = step(conds, state, jnp.asarray(part))
        ys_np = host_sync(ys_chunk, f"transient chunk[{i // chunk}]")
        _transient_materialized(1)
        if npad:
            ys_np = ys_np[:, :chunk - npad] if batched else \
                ys_np[:chunk - npad]
        blocks.append(ys_np)
    ys = np.concatenate(blocks, axis=1 if batched else 0)
    last = ys[:, -1] if batched else ys[-1]
    y_fin, ok = finish(conds, jnp.asarray(last), state[3])
    if batched:
        ys[:, -1] = host_sync(y_fin, "transient finish")
    else:
        ys[-1] = host_sync(y_fin, "transient finish")
    _transient_materialized(1)
    return jnp.asarray(ys), ok


def transient_chunked(spec: ModelSpec, cond: Conditions, save_ts,
                      opts: ODEOptions = ODEOptions(), chunk: int = 16):
    """Host-driven single-lane transient (see
    :func:`chunked_transient_drive`). Returns (ys [t, n_s], ok)."""
    return chunked_transient_drive(
        _transient_chunk_program(spec, opts),
        _transient_finish_program(spec, finish_options(opts)),
        cond, jnp.asarray(cond.y0, dtype=jnp.float64), save_ts, opts,
        chunk, batched=False)


# ----------------------------------------------------------------------
# derived quantities
def tof(spec: ModelSpec, cond: Conditions, y, tof_mask):
    """Turnover frequency: sum of net rates of the selected steps at y
    (reference old_system.py:470-488)."""
    fwd, rev = reaction_rates_at(spec, cond, y)
    return jnp.sum(jnp.asarray(tof_mask) * (fwd - rev))


def activity_from_tof(tof_value, T):
    """Activity [eV] = ln(h*TOF/kB*T) * RT (reference
    old_system.py:517-529). Log-assembled: h*TOF underflows TPU's
    f32-ranged f64 emulation for small TOF.

    Non-positive TOF guard: a negative net TOF (the selected steps run
    in REVERSE at the solution) would NaN the log -- the reference does
    exactly that, silently (old_system.py:524-529 takes np.log of a
    negative). Here the MAGNITUDE enters the log, reporting the activity
    of the reverse-running process; callers that can warn host-side
    (System.activity, sweep_steady_state) surface the sign so it is not
    silently lost. An exactly-zero TOF yields -inf (no turnover)."""
    log_term = jnp.log(jnp.abs(tof_value)) + LOG_H_OVER_KB - jnp.log(T)
    return (log_term * (R * T)) * 1.0e-3 / eVtokJ


def tof_mask_for(spec: ModelSpec, tof_terms) -> np.ndarray:
    mask = np.zeros(spec.n_reactions)
    for t in tof_terms:
        mask[spec.rindex(t)] = 1.0
    return mask


# ----------------------------------------------------------------------
# implicit differentiation through the steady state
def make_steady_x(spec: ModelSpec, opts: SolverOptions = SolverOptions(),
                  x0=None, key=None):
    """Return ``f(cond) -> x_dyn`` differentiable via the implicit function
    theorem: at F(x*, cond) = 0, dx*/dcond = -J^-1 dF/dcond. The backward
    pass costs ONE adjoint linear solve instead of the reference's
    2*n_reactions full re-solves (old_system.py:490-515)."""

    def _residual(x, cond):
        kf, kr, _ = rate_constants(spec, cond)
        residual, _, _ = _dynamic_residual(spec, cond, kf, kr)
        return residual(x)

    dyn_np = np.asarray(spec.dynamic_indices)
    G_np = spec.groups[:, dyn_np]

    def _polish(x, cond):
        """Two constrained-Newton steps at the solution. The PTC solve
        stops at its residual tolerance, which bounds the error along
        STIFF directions only -- along a soft (slow) mode the iterate
        can sit far from the root at the same residual, and the IFT
        below is exact only AT the root. Full Newton on the
        conservation-constrained system is quadratic in all directions
        and pins the soft-mode offset to the conditioning floor."""
        G = jnp.asarray(G_np)
        R, M = newton.conservation_constraints(G)

        def step(x, _):
            J = jax.jacfwd(_residual, argnums=0)(x, cond)
            B = jnp.where(M[:, None] > 0, R, J)
            dx = linalg.solve(B, _residual(x, cond) * (1.0 - M))
            x_new = x - dx
            # keep the polish monotone in residual norm
            better = (jnp.max(jnp.abs(_residual(x_new, cond)))
                      <= jnp.max(jnp.abs(_residual(x, cond))))
            return jnp.where(better, x_new, x), None

        x, _ = jax.lax.scan(step, x, None, length=2)
        return x

    def _solve(cond):
        res = steady_state(spec, cond, x0=x0, key=key, opts=opts)
        x = res.x[jnp.asarray(spec.dynamic_indices)]
        return _polish(x, cond)

    @jax.custom_vjp
    def xstar(cond):
        return _solve(cond)

    def fwd(cond):
        x = _solve(cond)
        return x, (x, cond)

    def bwd(saved, xbar):
        x, cond = saved
        J = jax.jacfwd(_residual, argnums=0)(x, cond)
        # Constrained IFT: x*(cond) satisfies the residual rows AND
        # G x* = const, so one row per group (linearly dependent on its
        # partners) is replaced by the constraint row, whose dF/dcond
        # entry is zero -- dx*/dcond = -B^{-1} Z dF/dcond with B the
        # row-replaced Jacobian and Z zeroing the replaced entries. The
        # operators come from the solver's own helper (and the same G_np
        # the polish uses) so the adjoint, the polish and the Newton
        # iteration stay in exact lockstep.
        R, M = newton.conservation_constraints(jnp.asarray(G_np))
        B = jnp.where(M[:, None] > 0, R, J)
        w = linalg.solve(B.T, xbar) * (1.0 - M)
        _, vjp_cond = jax.vjp(lambda c: _residual(x, c), cond)
        (cond_bar,) = vjp_cond(-w)
        return (cond_bar,)

    xstar.defvjp(fwd, bwd)
    return xstar


def drc(spec: ModelSpec, cond: Conditions, tof_terms,
        opts: SolverOptions = SolverOptions(), x0=None, key=None):
    """Degrees of rate control xi_r = d ln TOF / d ln k_r with both kf and
    kr scaled together (preserving Keq), exactly the reference perturbation
    channel (old_system.py:214-217,490-515) but via one reverse-mode pass.

    Returns [n_r] array ordered like spec.rnames.
    """
    mask = tof_mask_for(spec, tof_terms)
    xstar = make_steady_x(spec, opts, x0=x0, key=key)
    dyn = jnp.asarray(spec.dynamic_indices)
    y_base = jnp.asarray(cond.y0)

    def ln_tof(kscale):
        c = cond._replace(kscale=kscale)
        x = xstar(c)
        y = y_base.at[dyn].set(x)
        return jnp.log(tof(spec, c, y, mask))

    return jax.grad(ln_tof)(jnp.asarray(cond.kscale))


def drc_fd(spec: ModelSpec, cond: Conditions, tof_terms, eps: float = 1e-3,
           opts: SolverOptions | None = None, x0=None, key=None,
           return_success: bool = False):
    """Finite-difference DRC for parity with the reference
    (old_system.py:490-515): central difference with kf,kr scaled by
    (1 +/- eps), all 2*n_r+1 solves batched through ``vmap``.

    When ``opts`` is not given, the perturbed solves are tightened far
    below the default steady tolerance: an O(eps) rate perturbation
    shifts the residual by O(eps * flux), so a solve that already meets
    the default tolerance at x0 would not move at all and the difference
    quotient would collapse to frozen-coverage flux fractions. Explicit
    ``opts`` are honored verbatim.

    ``return_success``: also return the all-lanes convergence flag --
    an unconverged perturbed solve may sit on a best-effort iterate
    (possibly another branch of a multistable system), poisoning the
    difference quotient.

    KNOWN LIMIT: deep in the stiff regime (e.g. DMTM at 400 K) the
    perturbed root shift can sit below the f64 residual cancellation
    floor; no absolute-residual solve can resolve it, and FD degenerates
    while :func:`drc` (implicit differentiation, the default) remains
    exact -- the analog of the reference needing per-component relative
    ODE tolerances for its FD DRC (old_system.py:490-515)."""
    if opts is None:
        opts = SolverOptions(rate_tol=1e-14, rate_tol_rel=1e-13,
                             max_steps=400)
    mask = jnp.asarray(tof_mask_for(spec, tof_terms))
    n_r = spec.n_reactions
    base = jnp.asarray(cond.kscale)
    scales = jnp.concatenate([
        base[None, :],
        base[None, :] * (1.0 + eps * jnp.eye(n_r)),
        base[None, :] * (1.0 - eps * jnp.eye(n_r)),
    ], axis=0)

    def solve_tof(kscale):
        c = cond._replace(kscale=kscale)
        res = steady_state(spec, c, x0=x0, key=key, opts=opts)
        return tof(spec, c, res.x, mask), res.success

    tofs, ok = jax.vmap(solve_tof)(scales)
    t0, tp, tm = tofs[0], tofs[1:1 + n_r], tofs[1 + n_r:]
    xi = (tp - tm) / (2.0 * eps * t0)
    if return_success:
        return xi, jnp.all(ok)
    return xi
