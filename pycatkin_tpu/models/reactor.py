"""Reactor models: boundary conditions wrapped around the chemistry RHS.

Host-side configuration objects; the actual transform is applied on device
in :func:`pycatkin_tpu.ops.network.reactor_rhs`. Capability parity with the
reference hierarchy (/root/reference/pycatkin/classes/reactor.py:8-189):

- :class:`InfiniteDilutionReactor`: gas composition is a fixed boundary
  condition; only surface species evolve.
- :class:`CSTReactor`: continuously stirred tank; gas balances carry the
  site-rate -> pressure-rate scaling sigma = kB*T*A_cat/V and the flow
  term (p_in - p)/tau, with tau = V/Q if not given.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ..frontend.spec import REACTOR_CSTR, REACTOR_ID


@dataclass
class Reactor:
    name: str = "reactor"
    volume: Optional[float] = None
    catalyst_area: Optional[float] = None
    residence_time: Optional[float] = None
    flow_rate: Optional[float] = None

    reactor_type = REACTOR_ID

    def params(self) -> dict:
        return {"volume": self.volume, "catalyst_area": self.catalyst_area,
                "residence_time": self.residence_time,
                "flow_rate": self.flow_rate}


@dataclass
class InfiniteDilutionReactor(Reactor):
    reactor_type = REACTOR_ID


@dataclass
class CSTReactor(Reactor):
    reactor_type = REACTOR_CSTR

    def __post_init__(self):
        if self.residence_time is None:
            assert self.flow_rate is not None and self.volume is not None, (
                "CSTReactor needs residence_time or (volume, flow_rate)")
            self.residence_time = self.volume / self.flow_rate
