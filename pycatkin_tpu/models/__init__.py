from .reactor import CSTReactor, InfiniteDilutionReactor, Reactor
