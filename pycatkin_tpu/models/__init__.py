from .reactor import CSTReactor, InfiniteDilutionReactor, Reactor
from .synthetic import synthetic_system
