"""CO oxidation volcano workload: the framework's north-star model family.

The reference computes descriptor volcanoes by mutating user-defined
reaction energies inside a double Python loop
(/root/reference/examples/COOxVolcano/cooxvolcano.py:22-49). Here the
whole grid is *data*: :func:`volcano_conditions` builds a lane-batched
:class:`Conditions` pytree, vectorized host-side with numpy (the scaling
relations are resolved by the same linear-system form the engine uses),
and one batched device program solves every lane.

Standard entropies for the gas-phase entropy corrections are Atkins
values, as in the reference example (cooxvolcano.py:13-15).
"""

from __future__ import annotations

import numpy as np

from ..frontend.spec import Conditions, ModelSpec

SCOg = 2.0487e-3  # standard entropy of CO(g), eV/K
SO2g = 2.1261e-3  # standard entropy of O2(g), eV/K


def load_volcano_system(input_path: str):
    """Load the COOx volcano system from a reference-format JSON input."""
    from ..frontend.loader import read_from_input_file
    return read_from_input_file(input_path)


def set_descriptors(sim, ECO: float, EO: float) -> dict:
    """Single-point descriptor mutation on the facade (the reference's
    per-grid-point workflow, cooxvolcano.py:28-46). Returns the resolved
    electronic energies of the scaling states."""
    T = sim.params["temperature"]
    sim.reactions["CO_ads"].dErxn_user = ECO
    sim.reactions["CO_ads"].dGrxn_user = ECO + SCOg * T
    sim.reactions["2O_ads"].dErxn_user = 2.0 * EO
    sim.reactions["2O_ads"].dGrxn_user = 2.0 * EO + SO2g * T
    gelec = dict(zip(sim.snames, np.asarray(sim.free_energy_table().gelec)))
    EO2 = gelec["sO2"]
    sim.reactions["O2_ads"].dErxn_user = EO2
    sim.reactions["O2_ads"].dGrxn_user = EO2 + SO2g * T
    sim.reactions["CO_ox"].dEa_fwd_user = max(
        gelec["SRTS_ox"] - (ECO + EO), 0.0)
    sim.reactions["O2_2O"].dEa_fwd_user = max(gelec["SRTS_O2"] - EO2, 0.0)
    return gelec


def _scl_positions(spec: ModelSpec, names):
    pos = {}
    scl_idx = list(spec.scl_idx)
    for n in names:
        pos[n] = scl_idx.index(spec.sindex(n))
    return pos


def volcano_conditions(sim, ECO, EO) -> Conditions:
    """Lane-batched Conditions for paired descriptor arrays (ECO, EO).

    Vectorized equivalent of calling :func:`set_descriptors` +
    ``sim.conditions()`` per point: user energies are written into the
    lane-stacked condition arrays, and the scaling-state electronic
    energies (sO2, SRTS_ox, SRTS_O2) are resolved for all lanes at once
    via the spec's linear-relation matrices -- the same
    ``solve(I - Ws, b + We @ e + WuE @ uE)`` form the engine applies
    per lane on device.
    """
    ECO = np.asarray(ECO, dtype=float).ravel()
    EO = np.asarray(EO, dtype=float).ravel()
    assert ECO.shape == EO.shape, "ECO/EO must be paired lane arrays"
    n = ECO.size
    spec = sim.spec
    T = sim.params["temperature"]

    # Base condition defines every non-descriptor leaf and the user-energy
    # masks (barrier/rxn-energy availability does not vary across lanes).
    set_descriptors(sim, float(ECO[0]), float(EO[0]))
    base = sim.conditions()

    def tile(x):
        x = np.asarray(x, dtype=float)
        return np.broadcast_to(x, (n,) + x.shape).copy()

    uE, uG = tile(base.uE_rxn), tile(base.uG_rxn)
    uEa, uGa = tile(base.uEa), tile(base.uGa)

    iCO = spec.rindex("CO_ads")
    i2O = spec.rindex("2O_ads")
    iO2 = spec.rindex("O2_ads")
    iox = spec.rindex("CO_ox")
    idis = spec.rindex("O2_2O")

    uE[:, iCO] = ECO
    uG[:, iCO] = ECO + SCOg * T
    uE[:, i2O] = 2.0 * EO
    uG[:, i2O] = 2.0 * EO + SO2g * T

    # Resolve scaling-state electronic energies for all lanes at once.
    A = np.eye(spec.scl_idx.size) - spec.scl_Ws
    rhs = (spec.scl_b + spec.scl_We @ np.asarray(base.gelec))[None, :] \
        + uE @ spec.scl_WuE.T
    e_scl = np.linalg.solve(A, rhs.T).T                    # [n, n_sc]
    pos = _scl_positions(spec, ["sO2", "SRTS_ox", "SRTS_O2"])
    EO2 = e_scl[:, pos["sO2"]]
    uE[:, iO2] = EO2
    uG[:, iO2] = EO2 + SO2g * T
    # Barrier clamps (reference reaction.py:127 max(dG, 0)).
    uEa[:, iox] = uGa[:, iox] = np.maximum(
        e_scl[:, pos["SRTS_ox"]] - (ECO + EO), 0.0)
    uEa[:, idis] = uGa[:, idis] = np.maximum(
        e_scl[:, pos["SRTS_O2"]] - EO2, 0.0)

    return Conditions(
        T=np.full(n, float(base.T)), p=np.full(n, float(base.p)),
        gelec=tile(base.gelec), eps=tile(base.eps),
        uE_rxn=uE, uG_rxn=uG, uEa=uEa, uGa=uGa,
        u_rxn_mask=tile(base.u_rxn_mask), u_bar_mask=tile(base.u_bar_mask),
        is_activated=tile(base.is_activated), kscale=tile(base.kscale),
        y0=tile(base.y0), inflow=tile(base.inflow))


def volcano_grid_conditions(sim, be: np.ndarray):
    """Full 2-D (ECO x EO) grid over ``be``; returns (conds, shape)."""
    ECO, EO = np.meshgrid(np.asarray(be), np.asarray(be), indexing="ij")
    return volcano_conditions(sim, ECO.ravel(), EO.ravel()), ECO.shape
