"""Seeded synthetic microkinetic networks for benchmarks and scaling tests.

The reference ships no large mechanism; its biggest network is
``test/CH4_input.json`` (68 states / 58 reactions). The driver benchmark
suite (BASELINE.json config 5) additionally calls for a synthetic
200-species / 500-reaction stiff network batched over condition sweeps.
This module generates such networks deterministically: a star of
adsorption steps feeding a random surface reaction graph, with barriers
drawn over a wide range so rate constants span many decades (the
stiffness profile of real DFT landscapes).

The generator builds ordinary :class:`State`/:class:`Reaction` objects and
compiles them through the standard frontend, so benchmarks exercise the
exact production path (thermo kernels, TS barriers, adsorption kinetics,
conservation groups), not a shortcut.
"""

from __future__ import annotations

import numpy as np

from ..api.system import System
from ..frontend.reactions import (ADSORPTION, ARRHENIUS, Reaction)
from ..frontend.states import (ADSORBATE, GAS, SURFACE, TS, State)
from ..models.reactor import InfiniteDilutionReactor


def synthetic_system(n_species: int = 200, n_reactions: int = 500,
                     seed: int = 0, T: float = 500.0, p: float = 1.0e5,
                     barrier_range: tuple = (0.1, 1.6)) -> System:
    """Build a random but reproducible mechanism as a :class:`System`.

    ``n_species`` counts solution-vector species (gas + surface +
    adsorbates); transition states are extra. ``n_reactions`` =
    adsorption steps (one per gas) + random reversible surface steps.
    Barriers in ``barrier_range`` eV give rate constants spanning ~15
    decades at 500 K -- comparable stiffness to the DMTM example.
    """
    rng = np.random.default_rng(seed)
    n_gas = max(2, n_species // 20)
    n_ads = n_species - n_gas - 1
    assert n_ads >= n_gas, "n_species too small for the gas count"
    assert n_reactions > n_gas, "need more reactions than gas species"

    sys = System(T=T, p=p, times=[0.0, 1.0e6])
    surf = State(name="s", state_type=SURFACE, freq=[], Gelec=0.0)
    sys.add_state(surf)

    gas_states = []
    for g in range(n_gas):
        mass = float(rng.uniform(2.0, 60.0))
        linear = bool(rng.random() < 0.3)
        i1, i2, i3 = rng.uniform(2.0, 60.0, size=3)
        inertia = [i1, i1, 0.0] if linear else [i1, i2, i3]
        # Distinct gas energies keep the clamped-gas steady state away
        # from global equilibrium, so cycles carry sustained flux and the
        # TOF is a meaningful benchmark quantity.
        st = State(name=f"G{g:03d}", state_type=GAS, mass=mass,
                   sigma=float(rng.integers(1, 3)), inertia=inertia,
                   freq=list(rng.uniform(2.0e13, 9.0e13, size=3)),
                   Gelec=float(rng.uniform(-0.5, 0.5)))
        sys.add_state(st)
        gas_states.append(st)

    ads_states = []
    for a in range(n_ads):
        st = State(name=f"sA{a:03d}", state_type=ADSORBATE,
                   freq=list(rng.uniform(1.0e12, 6.0e13, size=3)),
                   Gelec=float(rng.uniform(-1.2, 0.3)))
        sys.add_state(st)
        ads_states.append(st)

    # One non-activated adsorption step per gas, each onto its own site
    # species: G + s -> sA  (collision-theory kf, detailed-balance kr).
    for g, gst in enumerate(gas_states):
        sys.add_reaction(Reaction(
            name=f"ads{g:03d}", reac_type=ADSORPTION, reversible=True,
            reactants=[gst, surf], products=[ads_states[g]],
            area=1.0e-19))

    # Random reversible surface interconversions sX -> sY through a TS
    # whose electronic energy sits ``barrier`` above the higher end.
    n_surface_rxns = n_reactions - n_gas
    for j in range(n_surface_rxns):
        ia, ib = rng.choice(n_ads, size=2, replace=False)
        ra, rb = ads_states[ia], ads_states[ib]
        barrier = float(rng.uniform(*barrier_range))
        ets = max(ra.Gelec, rb.Gelec) + barrier
        ts = State(name=f"TS{j:03d}", state_type=TS,
                   freq=list(rng.uniform(1.0e12, 6.0e13, size=3)),
                   Gelec=ets)
        sys.add_state(ts)
        sys.add_reaction(Reaction(
            name=f"r{j:03d}", reac_type=ARRHENIUS, reversible=True,
            reactants=[ra], products=[rb], TS=[ts], area=1.0e-19))

    sys.add_reactor(InfiniteDilutionReactor())
    start = {"s": 1.0}
    frac = (p / 1.0e5) / n_gas
    for gst in gas_states:
        start[gst.name] = frac
    sys.params["start_state"] = start
    return sys
