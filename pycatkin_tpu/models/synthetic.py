"""Seeded synthetic microkinetic networks for benchmarks and scaling tests.

The reference ships no large mechanism; its biggest network is
``test/CH4_input.json`` (68 states / 58 reactions). The driver benchmark
suite (BASELINE.json config 5) additionally calls for a synthetic
200-species / 500-reaction stiff network batched over condition sweeps.
This module generates such networks deterministically: a star of
adsorption steps feeding a random surface reaction graph, with barriers
drawn over a wide range so rate constants span many decades (the
stiffness profile of real DFT landscapes).

The generator builds ordinary :class:`State`/:class:`Reaction` objects and
compiles them through the standard frontend, so benchmarks exercise the
exact production path (thermo kernels, TS barriers, adsorption kinetics,
conservation groups), not a shortcut.
"""

from __future__ import annotations

import numpy as np

from ..api.system import System
from ..frontend.reactions import (ADSORPTION, ARRHENIUS, Reaction)
from ..frontend.states import (ADSORBATE, GAS, SURFACE, TS, State)
from ..models.reactor import InfiniteDilutionReactor


def synthetic_system(n_species: int = 200, n_reactions: int = 500,
                     seed: int = 0, T: float = 500.0, p: float = 1.0e5,
                     barrier_range: tuple = (0.1, 1.6)) -> System:
    """Build a random but reproducible mechanism as a :class:`System`.

    ``n_species`` counts solution-vector species (gas + surface +
    adsorbates); transition states are extra. ``n_reactions`` =
    adsorption steps (one per gas) + random reversible surface steps.
    Barriers in ``barrier_range`` eV give rate constants spanning ~15
    decades at 500 K -- comparable stiffness to the DMTM example.
    """
    rng = np.random.default_rng(seed)
    n_gas = max(2, n_species // 20)
    n_ads = n_species - n_gas - 1
    assert n_ads >= n_gas, "n_species too small for the gas count"
    assert n_reactions > n_gas, "need more reactions than gas species"

    sys = System(T=T, p=p, times=[0.0, 1.0e6])
    surf = State(name="s", state_type=SURFACE, freq=[], Gelec=0.0)
    sys.add_state(surf)

    gas_states = []
    for g in range(n_gas):
        mass = float(rng.uniform(2.0, 60.0))
        linear = bool(rng.random() < 0.3)
        i1, i2, i3 = rng.uniform(2.0, 60.0, size=3)
        inertia = [i1, i1, 0.0] if linear else [i1, i2, i3]
        # Distinct gas energies keep the clamped-gas steady state away
        # from global equilibrium, so cycles carry sustained flux and the
        # TOF is a meaningful benchmark quantity.
        st = State(name=f"G{g:03d}", state_type=GAS, mass=mass,
                   sigma=float(rng.integers(1, 3)), inertia=inertia,
                   freq=list(rng.uniform(2.0e13, 9.0e13, size=3)),
                   Gelec=float(rng.uniform(-0.5, 0.5)))
        sys.add_state(st)
        gas_states.append(st)

    ads_states = []
    for a in range(n_ads):
        st = State(name=f"sA{a:03d}", state_type=ADSORBATE,
                   freq=list(rng.uniform(1.0e12, 6.0e13, size=3)),
                   Gelec=float(rng.uniform(-1.2, 0.3)))
        sys.add_state(st)
        ads_states.append(st)

    # One non-activated adsorption step per gas, each onto its own site
    # species: G + s -> sA  (collision-theory kf, detailed-balance kr).
    for g, gst in enumerate(gas_states):
        sys.add_reaction(Reaction(
            name=f"ads{g:03d}", reac_type=ADSORPTION, reversible=True,
            reactants=[gst, surf], products=[ads_states[g]],
            area=1.0e-19))

    # Random reversible surface interconversions sX -> sY through a TS
    # whose electronic energy sits ``barrier`` above the higher end.
    n_surface_rxns = n_reactions - n_gas
    for j in range(n_surface_rxns):
        ia, ib = rng.choice(n_ads, size=2, replace=False)
        ra, rb = ads_states[ia], ads_states[ib]
        barrier = float(rng.uniform(*barrier_range))
        ets = max(ra.Gelec, rb.Gelec) + barrier
        ts = State(name=f"TS{j:03d}", state_type=TS,
                   freq=list(rng.uniform(1.0e12, 6.0e13, size=3)),
                   Gelec=ets)
        sys.add_state(ts)
        sys.add_reaction(Reaction(
            name=f"r{j:03d}", reac_type=ARRHENIUS, reversible=True,
            reactants=[ra], products=[rb], TS=[ts], area=1.0e-19))

    sys.add_reactor(InfiniteDilutionReactor())
    start = {"s": 1.0}
    frac = (p / 1.0e5) / n_gas
    for gst in gas_states:
        start[gst.name] = frac
    sys.params["start_state"] = start
    return sys


# Bucket-targeted shapes: one FIXED (n_species, n_reactions) pair per
# ABI species bucket. Fixing the shape (rather than drawing it from
# the seed) pins the whole fingerprint -- padded dims, dynamic
# sub-bucket, reactor code -- so every seed of a bucket lands in the
# SAME interned program spec and the serving layer's coalescer can
# pack them as co-tenants. The ABI counts TS states too, so the
# lowered species count is n_species + n_reactions - n_gas (one TS
# per surface step); shapes below sit mid-bucket under that formula.
_BUCKET_SHAPES = {
    16: (10, 5),
    32: (15, 12),
    128: (60, 40),
    512: (200, 200),
}


def _lowered_species(n_species: int, n_reactions: int) -> int:
    n_gas = max(2, n_species // 20)
    return n_species + n_reactions - n_gas


def synthetic_system_for_bucket(species_bucket: int, seed: int = 0,
                                n_species: int | None = None,
                                n_reactions: int | None = None,
                                T: float = 500.0, p: float = 1.0e5,
                                barrier_range: tuple = (0.1, 1.6)
                                ) -> System:
    """A :func:`synthetic_system` guaranteed to lower into the
    requested ABI species bucket -- the soak harness's occupancy
    control knob (``pycatkin_tpu/serve``): requests generated with the
    same ``species_bucket`` (any seed) share one ABI fingerprint and
    therefore one packed program, so a soak can steer load bucket by
    bucket.

    ``n_species`` / ``n_reactions`` override the bucket's default
    shape but are validated against it; an impossible request (unknown
    bucket, a species count that lowers elsewhere, a reaction count
    the generator cannot realize) raises ``ValueError`` with the
    reason rather than silently generating a mechanism in the wrong
    bucket. The build is verified by actually lowering the spec
    through :func:`frontend.abi.select_static`."""
    from ..frontend import abi

    if species_bucket not in abi.SPECIES_BUCKETS:
        raise ValueError(
            f"species_bucket {species_bucket} is not an ABI bucket; "
            f"choose one of {abi.SPECIES_BUCKETS}")
    lo = ([b for b in abi.SPECIES_BUCKETS if b < species_bucket]
          or [0])[-1]
    n_s, n_r = _BUCKET_SHAPES[species_bucket]
    if n_species is not None:
        n_s = int(n_species)
    if n_reactions is not None:
        n_r = int(n_reactions)
    n_gas = max(2, n_s // 20)
    # +1 below mirrors abi.select_static's reserved pad slot.
    total = _lowered_species(n_s, n_r)
    if not (lo < total + 1 <= species_bucket):
        raise ValueError(
            f"n_species={n_s}/n_reactions={n_r} lower to {total} ABI "
            f"species (TS states included), i.e. bucket "
            f"{abi._bucket_for(total + 1, abi.SPECIES_BUCKETS)}, not "
            f"the requested {species_bucket} (need {lo} < "
            f"n_species + n_reactions - {n_gas} + 1 <= {species_bucket})")
    if n_s - n_gas - 1 < n_gas:
        raise ValueError(
            f"n_species={n_s} is too small for the generator's gas "
            f"star ({n_gas} gas species need at least as many "
            f"adsorbates)")
    if n_r <= n_gas:
        raise ValueError(
            f"n_reactions={n_r} cannot cover the {n_gas} adsorption "
            f"steps the generator emits (need n_reactions > {n_gas})")
    if n_r > max(abi.REACTION_BUCKETS):
        raise ValueError(
            f"n_reactions={n_r} exceeds the largest ABI reaction "
            f"bucket {max(abi.REACTION_BUCKETS)}")
    sys = synthetic_system(n_species=n_s, n_reactions=n_r, seed=seed,
                           T=T, p=p, barrier_range=barrier_range)
    st = abi.select_static(sys.spec)
    if st.n_species != species_bucket:
        raise ValueError(
            f"generated mechanism lowered into species bucket "
            f"{st.n_species}, not the requested {species_bucket} "
            f"(generator/ABI drift -- report this)")
    return sys
