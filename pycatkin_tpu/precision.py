"""Precision tiers: the one blessed dtype-downcast entry point.

The TPU has no native f64 -- every double-precision FMA is emulated as
double-float pairs (~16x unit roundoff, 1.519e11 flop/s measured
ceiling, see docs/perf_mfu.md) -- but it has real f32 matrix units. The
precision-tier layer exploits that asymmetry: run the Newton/PTC/LM
bulk iterations in native f32, then polish-and-verify in f64 inside the
same fused program, so a lane only counts as solved when its f64
residual and stability verdict pass at the unchanged f64 thresholds
(``solvers.newton.effective_unit_roundoff`` stays the arbiter). Polish
failures fall through the existing rescue ladder exactly like an f64
failure would, so verdicts stay bit-certified while the hot loop runs
at native speed. docs/perf_precision_tiers.md is the full contract.

This module is the ONLY place solver code may obtain a reduced-
precision dtype: PCL005 (lint/dtype.py) flags any raw ``float32`` /
``float64`` literal inside ``ops/`` and ``solvers/``, so every
downcast is forced through :func:`bulk_dtype` / :func:`cast_bulk` and
every verify-side upcast through :func:`cast_verify` -- one grep-able
seam instead of scattered ``astype`` calls.

Selection is process-level configuration, resolved at CALL time (never
baked into a traced program): ``PYCATKIN_PRECISION_TIER=f32-polish``
turns the tiered path on; the default is ``f64`` (bitwise-identical to
the pre-tier solver) until the bench proves the tier on hardware.

Host-side and JAX-free at import (lint/CI tooling imports the tier
names); ``jax.numpy`` loads lazily inside the cast helpers.
"""

from __future__ import annotations

import functools
import os

TIER_ENV = "PYCATKIN_PRECISION_TIER"

#: Recognised tiers. "f64" = the historical path, every iteration at
#: full (emulated-on-TPU) double precision. "f32-polish" = bulk
#: iterations in native f32, then a short f64 polish pass and the f64
#: verdict inside the same program.
TIERS = ("f64", "f32-polish")

#: Per-lane telemetry codes (the 5th ``lane_telemetry`` column): which
#: tier produced the ACCEPTED iterate. 0 = f64 (also every rescue-
#: ladder product -- the ladder always runs f64), 1 = the f32 bulk +
#: f64 polish pipeline.
TIER_CODES = {"f64": 0, "f32-polish": 1}
TIER_NAMES = tuple(sorted(TIER_CODES, key=TIER_CODES.get))


def active_tier() -> str:
    """The process-level precision tier, resolved from the environment
    at every call (so tests can flip it without re-importing; program
    caches key on it via :func:`tier_tag`). Unknown values raise
    immediately -- a typo must not silently run f64."""
    tier = os.environ.get(TIER_ENV, "f64").strip() or "f64"
    if tier not in TIERS:
        raise ValueError(
            f"{TIER_ENV}={tier!r}: unknown precision tier "
            f"(expected one of {', '.join(TIERS)})")
    return tier


def tier_tag(tier: str) -> str:
    """Program-key / fingerprint suffix for ``tier``. Empty for f64 so
    every pre-tier program key, AOT cache entry and exported pack stays
    byte-identical; non-default tiers get a distinct tag so f32 and
    f64 programs can never share an AOT entry.

    Tag composition order is a contract: the tier tag is appended
    BEFORE the multi-tenant count tag
    (:func:`parallel.compile_pool.tenant_tag`'s ``:tK``), so a packed
    f32-polish kind ends ``...:p32:t4``. Both inverses stay valid
    under that order -- :func:`tier_of_tag` matches ``:p32`` anywhere
    in the kind, and the tenant parser anchors ``:tK`` at the end."""
    return "" if tier == "f64" else ":p32"


def tier_of_tag(kind: str) -> str:
    """Inverse of :func:`tier_tag` over a program kind string: which
    tier a registered program was built for (the cost ledger keys its
    roofline on this). Substring (not suffix) match by design: packed
    multi-tenant kinds carry a trailing ``:tK`` after the tier tag."""
    return "f32-polish" if ":p32" in kind else "f64"


#: Direction-kernel tier knob (docs/perf_pallas_linalg.md): which
#: batched dense factorize/solve implementation the linalg dispatch
#: seam (:func:`pycatkin_tpu.ops.linalg.select_solver`) routes bucket-
#: shaped systems through. "xla" = the historical arithmetic-op
#: kernels (lax.fori_loop LU / unrolled Gauss-Jordan), "pallas" = the
#: VMEM-resident Pallas kernels of :mod:`pycatkin_tpu.ops.pallas_linalg`,
#: "auto" (default) = pallas on TPU, xla elsewhere (unless
#: PYCATKIN_LINALG_INTERPRET=1 forces the interpret-mode kernel for
#: CPU testing).
KERNEL_ENV = "PYCATKIN_LINALG_KERNEL"
INTERPRET_ENV = "PYCATKIN_LINALG_INTERPRET"
KERNELS = ("auto", "pallas", "xla")


def _interpret_forced() -> bool:
    """PYCATKIN_LINALG_INTERPRET truthiness (CPU testing escape hatch
    for ``auto``; the Pallas kernels always run ``interpret=True`` off
    TPU regardless, so nothing ever requires hardware)."""
    return os.environ.get(INTERPRET_ENV, "").strip().lower() in (
        "1", "on", "true", "yes")


def linalg_kernel(backend: str = None) -> str:
    """The resolved direction-kernel tier: ``"pallas"`` or ``"xla"``.

    Resolved from PYCATKIN_LINALG_KERNEL at every call (process-level
    configuration, never baked into a traced program -- program caches
    key on it via :func:`kernel_tag`, exactly like the precision tier).
    ``auto`` resolves by executing backend: pallas on TPU (the roofline
    attack), xla everywhere else -- unless PYCATKIN_LINALG_INTERPRET=1
    opts the interpret-mode kernel in for CPU testing. Unknown values
    raise immediately -- a typo must not silently change the kernel."""
    val = os.environ.get(KERNEL_ENV, "auto").strip() or "auto"
    if val not in KERNELS:
        raise ValueError(
            f"{KERNEL_ENV}={val!r}: unknown linalg kernel "
            f"(expected one of {', '.join(KERNELS)})")
    if val != "auto":
        return val
    if backend is None:
        import jax
        backend = jax.default_backend()
    if backend == "tpu":
        return "pallas"
    return "pallas" if _interpret_forced() else "xla"


def kernel_tag(kernel: str = None) -> str:
    """Program-key / fingerprint suffix for the direction-kernel tier.
    Empty for ``xla`` so every pre-kernel program key, AOT cache entry
    and exported pack stays byte-identical; the Pallas tier gets a
    distinct ``:kpl`` tag so kernel and XLA programs can never share an
    AOT entry.

    Tag composition order is a contract: the kernel tag is appended
    AFTER the precision-tier tag (:func:`tier_tag`'s ``:p32``) and
    BEFORE the sharding / multi-tenant tags, so a packed f32-polish
    Pallas kind ends ``...:p32:kpl:t4``. Both inverses stay valid under
    that order -- :func:`kernel_of_tag` matches ``:kpl`` anywhere in
    the kind."""
    if kernel is None:
        kernel = linalg_kernel()
    return ":kpl" if kernel == "pallas" else ""


def kernel_of_tag(kind: str) -> str:
    """Inverse of :func:`kernel_tag` over a program kind string: which
    direction-kernel tier a registered program was built for (the cost
    ledger annotates its rows with this, so perfwatch scores the
    Pallas path against the XLA path program-by-program)."""
    return "pallas" if ":kpl" in kind else "xla"


def kernel_keyed(cached_fn):
    """Decorator for ``lru_cache``d jitted-program builders whose
    traces embed direction solves: appends the RESOLVED kernel tier
    (:func:`linalg_kernel`) as a trailing ``kernel`` keyword on every
    call, so flipping PYCATKIN_LINALG_KERNEL selects a DIFFERENT
    cached program. The builders bake ``select_solver``'s choice in at
    trace time; without this key a stale trace would silently serve
    the wrong kernel tier after an env flip -- the exact staleness
    class the explicit ``tier`` cache parameter already guards
    against. The wrapped builder must accept a ``kernel`` keyword
    (used only as a cache key) -- checked at decoration time, so a
    builder missing the parameter fails at import with a pointed
    error instead of a confusing TypeError on first call;
    ``cache_clear``/``cache_info`` pass through."""
    import inspect

    builder = getattr(cached_fn, "__wrapped__", cached_fn)
    try:
        params = inspect.signature(builder).parameters
    except (TypeError, ValueError):
        params = None                 # uninspectable: trust the caller
    if params is not None and "kernel" not in params and not any(
            p.kind is inspect.Parameter.VAR_KEYWORD
            for p in params.values()):
        raise TypeError(
            f"kernel_keyed: {getattr(builder, '__qualname__', builder)!r}"
            f" does not accept a `kernel` keyword -- the decorator "
            f"threads the resolved PYCATKIN_LINALG_KERNEL tier through "
            f"it as an lru_cache key parameter; add "
            f"`kernel: str = 'xla'` to the builder signature")

    @functools.wraps(cached_fn)
    def wrapper(*args, **kwargs):
        kwargs.setdefault("kernel", linalg_kernel())
        return cached_fn(*args, **kwargs)
    wrapper.cache_clear = cached_fn.cache_clear
    wrapper.cache_info = cached_fn.cache_info
    return wrapper


def bulk_dtype(tier: str):
    """The dtype the bulk Newton/PTC/LM iterations run in under
    ``tier`` -- the blessed PCL005 entry point for reduced precision."""
    import jax.numpy as jnp
    return jnp.float32 if tier == "f32-polish" else jnp.float64


def verify_dtype():
    """The dtype every residual verdict and stability certificate is
    evaluated in -- always full precision, regardless of tier."""
    import jax.numpy as jnp
    return jnp.float64


def cast_bulk(x, tier: str):
    """Blessed downcast of an array (or anything ``jnp.asarray``
    accepts) to the bulk dtype of ``tier``; identity under f64."""
    import jax.numpy as jnp
    return jnp.asarray(x, dtype=bulk_dtype(tier))


def cast_verify(x):
    """Blessed upcast back to the verification dtype (f64): the seam
    between the f32 bulk iterate and the f64 polish-and-verify pass."""
    import jax.numpy as jnp
    return jnp.asarray(x, dtype=verify_dtype())
