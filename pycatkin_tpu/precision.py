"""Precision tiers: the one blessed dtype-downcast entry point.

The TPU has no native f64 -- every double-precision FMA is emulated as
double-float pairs (~16x unit roundoff, 1.519e11 flop/s measured
ceiling, see docs/perf_mfu.md) -- but it has real f32 matrix units. The
precision-tier layer exploits that asymmetry: run the Newton/PTC/LM
bulk iterations in native f32, then polish-and-verify in f64 inside the
same fused program, so a lane only counts as solved when its f64
residual and stability verdict pass at the unchanged f64 thresholds
(``solvers.newton.effective_unit_roundoff`` stays the arbiter). Polish
failures fall through the existing rescue ladder exactly like an f64
failure would, so verdicts stay bit-certified while the hot loop runs
at native speed. docs/perf_precision_tiers.md is the full contract.

This module is the ONLY place solver code may obtain a reduced-
precision dtype: PCL005 (lint/dtype.py) flags any raw ``float32`` /
``float64`` literal inside ``ops/`` and ``solvers/``, so every
downcast is forced through :func:`bulk_dtype` / :func:`cast_bulk` and
every verify-side upcast through :func:`cast_verify` -- one grep-able
seam instead of scattered ``astype`` calls.

Selection is process-level configuration, resolved at CALL time (never
baked into a traced program): ``PYCATKIN_PRECISION_TIER=f32-polish``
turns the tiered path on; the default is ``f64`` (bitwise-identical to
the pre-tier solver) until the bench proves the tier on hardware.

Host-side and JAX-free at import (lint/CI tooling imports the tier
names); ``jax.numpy`` loads lazily inside the cast helpers.
"""

from __future__ import annotations

import os

TIER_ENV = "PYCATKIN_PRECISION_TIER"

#: Recognised tiers. "f64" = the historical path, every iteration at
#: full (emulated-on-TPU) double precision. "f32-polish" = bulk
#: iterations in native f32, then a short f64 polish pass and the f64
#: verdict inside the same program.
TIERS = ("f64", "f32-polish")

#: Per-lane telemetry codes (the 5th ``lane_telemetry`` column): which
#: tier produced the ACCEPTED iterate. 0 = f64 (also every rescue-
#: ladder product -- the ladder always runs f64), 1 = the f32 bulk +
#: f64 polish pipeline.
TIER_CODES = {"f64": 0, "f32-polish": 1}
TIER_NAMES = tuple(sorted(TIER_CODES, key=TIER_CODES.get))


def active_tier() -> str:
    """The process-level precision tier, resolved from the environment
    at every call (so tests can flip it without re-importing; program
    caches key on it via :func:`tier_tag`). Unknown values raise
    immediately -- a typo must not silently run f64."""
    tier = os.environ.get(TIER_ENV, "f64").strip() or "f64"
    if tier not in TIERS:
        raise ValueError(
            f"{TIER_ENV}={tier!r}: unknown precision tier "
            f"(expected one of {', '.join(TIERS)})")
    return tier


def tier_tag(tier: str) -> str:
    """Program-key / fingerprint suffix for ``tier``. Empty for f64 so
    every pre-tier program key, AOT cache entry and exported pack stays
    byte-identical; non-default tiers get a distinct tag so f32 and
    f64 programs can never share an AOT entry.

    Tag composition order is a contract: the tier tag is appended
    BEFORE the multi-tenant count tag
    (:func:`parallel.compile_pool.tenant_tag`'s ``:tK``), so a packed
    f32-polish kind ends ``...:p32:t4``. Both inverses stay valid
    under that order -- :func:`tier_of_tag` matches ``:p32`` anywhere
    in the kind, and the tenant parser anchors ``:tK`` at the end."""
    return "" if tier == "f64" else ":p32"


def tier_of_tag(kind: str) -> str:
    """Inverse of :func:`tier_tag` over a program kind string: which
    tier a registered program was built for (the cost ledger keys its
    roofline on this). Substring (not suffix) match by design: packed
    multi-tenant kinds carry a trailing ``:tK`` after the tier tag."""
    return "f32-polish" if ":p32" in kind else "f64"


def bulk_dtype(tier: str):
    """The dtype the bulk Newton/PTC/LM iterations run in under
    ``tier`` -- the blessed PCL005 entry point for reduced precision."""
    import jax.numpy as jnp
    return jnp.float32 if tier == "f32-polish" else jnp.float64


def verify_dtype():
    """The dtype every residual verdict and stability certificate is
    evaluated in -- always full precision, regardless of tier."""
    import jax.numpy as jnp
    return jnp.float64


def cast_bulk(x, tier: str):
    """Blessed downcast of an array (or anything ``jnp.asarray``
    accepts) to the bulk dtype of ``tier``; identity under f64."""
    import jax.numpy as jnp
    return jnp.asarray(x, dtype=bulk_dtype(tier))


def cast_verify(x):
    """Blessed upcast back to the verification dtype (f64): the seam
    between the f32 bulk iterate and the f64 polish-and-verify pass."""
    import jax.numpy as jnp
    return jnp.asarray(x, dtype=verify_dtype())
