"""Physical constants and unit conversions.

Values mirror the reference constant set actually in use
(/root/reference/pycatkin/constants/physical_constants.py:14-27, the
"Butadiene paper" set), because every golden regression number depends on
these exact values.

Internal unit conventions (identical to the reference):
- energies per species: eV
- reaction energies / barriers at the rate-constant boundary: J/mol
- gas-phase solution entries: bar (multiply by ``bartoPa`` to get Pa)
- rate constants: 1/s (Arrhenius, desorption) or 1/(s Pa) (adsorption)
"""

NA = 6.02214076e23
bartoPa = 1.0e5
atmtoPa = 1.01325e5

kB = 1.380662e-23          # [J/K]
h = 6.626176e-34           # [J s]
JtoeV = 6.242e18
eVtokJ = 96.485
eVtokcal = 23.06
kcaltoJ = 4184
amutokg = 1.66053886e-27
amuA2tokgm2 = 1.66053907e-47
R = 8.31446262             # [J/(K mol)]

# Derived, used by the thermo kernels.
eVtoJmol = eVtokJ * 1.0e3  # eV -> J/mol

# --- TPU-safe precomputed combinations --------------------------------
# XLA:TPU emulates float64 as double-float32 pairs whose EXPONENT RANGE
# is float32's (~1e-38..1e38): raw SI combinations like h**2 (~4.4e-67)
# or m_kg*kB (~6e-49) underflow to zero ON DEVICE even under x64. Every
# device kernel therefore uses these host-precomputed, in-range
# combinations (plain Python floats evaluate in true f64), and assembles
# wide-range expressions in log space.
import math as _math

LOG_TRANS_CONST = _math.log(2.0 * _math.pi * amutokg * kB / h**2)
#   ln(2*pi*amu*kB/h^2); translational q = (kBT/p)*(C*m_amu*T)^1.5
LOG_ROT_CONST = _math.log(8.0 * _math.pi**2 * kB * amuA2tokgm2 / h**2)
#   ln(8*pi^2*kB*amuA2/h^2); rotational q_lin = C*T*I_amu/sigma
ROT_THETA_AMU = h**2 / (8.0 * _math.pi**2 * kB * amuA2tokgm2)
#   rotational temperature theta = C/I[amu*A^2], in K
SQRT_2PI_AMU_KB = _math.sqrt(2.0 * _math.pi * amutokg * kB)
#   adsorption k = area/(C*sqrt(m_amu*T))
LOG_DES_POLY = _math.log(kB**2 * 2.0 * _math.pi**1.5 * amutokg) \
    - 3.0 * _math.log(h)
#   ln(kB^2*2*pi^1.5*amu/h^3), polyatomic desorption coefficient
LOG_DES_LIN = _math.log(kB**2 * 2.0 * _math.pi * amutokg) \
    - 3.0 * _math.log(h)
#   ln(kB^2*2*pi*amu/h^3), linear-molecule desorption coefficient
LOG_H_OVER_KB = _math.log(h / kB)
#   activity: ln(h*TOF/kBT) = ln(TOF) + C - ln(T)

# 12.4 meV frequency floor used when parsing DFT vibration output
# (reference state.py:184-203). Expressed in Hz.
FREQ_FLOOR_HZ = 12.4e-3 / (h * JtoeV)
