"""Physical constants and unit conversions.

Values mirror the reference constant set actually in use
(/root/reference/pycatkin/constants/physical_constants.py:14-27, the
"Butadiene paper" set), because every golden regression number depends on
these exact values.

Internal unit conventions (identical to the reference):
- energies per species: eV
- reaction energies / barriers at the rate-constant boundary: J/mol
- gas-phase solution entries: bar (multiply by ``bartoPa`` to get Pa)
- rate constants: 1/s (Arrhenius, desorption) or 1/(s Pa) (adsorption)
"""

NA = 6.02214076e23
bartoPa = 1.0e5
atmtoPa = 1.01325e5

kB = 1.380662e-23          # [J/K]
h = 6.626176e-34           # [J s]
JtoeV = 6.242e18
eVtokJ = 96.485
eVtokcal = 23.06
kcaltoJ = 4184
amutokg = 1.66053886e-27
amuA2tokgm2 = 1.66053907e-47
R = 8.31446262             # [J/(K mol)]

# Derived, used by the thermo kernels.
eVtoJmol = eVtokJ * 1.0e3  # eV -> J/mol

# 12.4 meV frequency floor used when parsing DFT vibration output
# (reference state.py:184-203). Expressed in Hz.
FREQ_FLOOR_HZ = 12.4e-3 / (h * JtoeV)
