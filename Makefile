# Test lanes. Tier-1 (the default gate) runs the fast suite on the CPU
# backend; the faults lane isolates the fault-injection / degradation /
# journal-resume tests and the validate lane the input-validation-gate
# / quarantine tests (both markers stay inside the default `not slow`
# selection). `lint-faults` statically checks that every fault-site
# label in pycatkin_tpu/ is documented in docs/failure_model.md;
# `lint-syncs` that the sweep hot path has no uncounted host
# materializations (docs/index.md "Performance"). `bench-smoke` is the
# end-to-end canary: an 8x8 CPU sweep with prewarm that fails on any
# crash or on a clean sweep exceeding the host-sync budget.

PYTEST = env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
	--continue-on-collection-errors -p no:cacheprovider

.PHONY: test test-faults test-validate test-all lint-faults lint-syncs \
	bench-smoke

test:
	$(PYTEST) -m 'not slow'

test-faults:
	$(PYTEST) -m faults

test-validate:
	$(PYTEST) -m validate

test-all:
	$(PYTEST) -m ''

lint-faults:
	python tools/lint_fault_sites.py

lint-syncs:
	python tools/lint_host_syncs.py

bench-smoke:
	env JAX_PLATFORMS=cpu python bench.py --smoke
