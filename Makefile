# Test lanes. Tier-1 (the default gate) runs the fast suite on the CPU
# backend; the faults lane isolates the fault-injection / degradation /
# journal-resume tests and the validate lane the input-validation-gate
# / quarantine tests (both markers stay inside the default `not slow`
# selection). `lint-faults` statically checks that every fault-site
# label in pycatkin_tpu/ is documented in docs/failure_model.md.

PYTEST = env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
	--continue-on-collection-errors -p no:cacheprovider

.PHONY: test test-faults test-validate test-all lint-faults

test:
	$(PYTEST) -m 'not slow'

test-faults:
	$(PYTEST) -m faults

test-validate:
	$(PYTEST) -m validate

test-all:
	$(PYTEST) -m ''

lint-faults:
	python tools/lint_fault_sites.py
