# Test lanes. Tier-1 (the default gate) runs the fast suite on the CPU
# backend; the faults lane isolates the fault-injection / degradation /
# journal-resume tests (they are also part of tier-1 -- pytest marker
# `faults` stays inside the default `not slow` selection).

PYTEST = env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
	--continue-on-collection-errors -p no:cacheprovider

.PHONY: test test-faults test-all

test:
	$(PYTEST) -m 'not slow'

test-faults:
	$(PYTEST) -m faults

test-all:
	$(PYTEST) -m ''
