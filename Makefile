# Test lanes. Tier-1 (the default gate) runs the fast suite on the CPU
# backend; the faults lane isolates the fault-injection / degradation /
# journal-resume tests and the validate lane the input-validation-gate
# / quarantine tests (both markers stay inside the default `not slow`
# selection). `lint` runs the unified pclint static-analysis pass
# (docs/static_analysis.md): host-sync budget (PCL001), fault-site
# registry (PCL002), jit purity (PCL003), tracer hygiene (PCL004),
# dtype policy (PCL005), the env-var registry (PCL006), async-blocking
# (PCL010), lock discipline (PCL011), atomic-write protocol (PCL012)
# and the cross-module fused-tail integrity rule (PCL013);
# `lint-syncs`/`lint-faults` remain as single-rule aliases. Results are
# cached in .pclint_cache/ (content-addressed; `--no-cache` bypasses).
# `test-san` is the sanitizer lane (pcsan, docs/static_analysis.md):
# the tripwire selftests plus the sync-budget and serve suites re-run
# with PYCATKIN_SAN=1, so the recompile/sync/stall tripwires ride the
# real code paths armed.
# `bench-smoke` is the end-to-end canary: pclint plus an 8x8 CPU sweep
# with prewarm that fails on any crash, any new lint finding, a prewarm
# layout over the program budget (<= 10), or a clean sweep spending
# more than 2 counted host syncs. `aot-pack-selftest` round-trips the
# shippable AOT cache pack (prewarm -> export -> import ->
# prewarm-from-pack with zero compiles -> bit-identical sweep).
# `obs-check` is the observability lane (docs/observability.md):
# tools/obsview.py --selftest --sweep round-trips a Chrome trace,
# verifies span parenting + sync-label fidelity against a real traced
# sweep, and lints the Prometheus metrics exposition. `perfwatch` is
# the perf-regression sentinel (docs/perf_cost_ledger.md): the
# selftest proves the noise-aware baseline math (injected 2x
# regression flagged, in-noise wobble not), then --check judges the
# newest checked-in BENCH_r*.json round against the prior rounds'
# median +/- MAD baseline and hard-fails on a throughput/MFU
# regression. `chaos` is the elastic-scheduler drill
# (docs/failure_model.md): a small lease-scheduled multi-process sweep
# with an injected worker crash that must finish with zero lost lanes
# and at least one supervised restart. `serve-check` is the serving
# lane (docs/serving.md): a two-process pack-boot proof -- process 1
# soaks a small request stream against an empty AOT cache and exports
# the warmed cache as a pack, process 2 boots its server FROM that
# pack (prewarm must compile nothing), streams ~64 TCP requests, and
# gates on a 100% post-warmup zero-compile rate, the p99 budget,
# schema-complete responses (manifest/telemetry/quarantine present)
# and a loss-free drain. `router-check` is the fleet-tier chaos drill
# (docs/serving.md "Fleet serving"), run with the pcsan tripwires
# armed: boot a 3-replica pack-warmed fleet behind the front router,
# SIGKILL 2 of 3 replicas mid-soak (plus one torn line and one
# connection reset at the dispatch sites), and hard-fail unless zero
# requests are lost, every answer is bitwise identical to an
# undisturbed same-grid run, the duplicate-suppression audit is clean,
# and the restarted replicas serve from the AOT pack at a 100%
# zero-compile rate. The drill by default ALSO SIGKILLs the
# journal-backed front router mid-stream and gates on a loss-free,
# bitwise-identical journal replay. `durable-check` is the JAX-free
# durable-serving smoke (docs/serving.md "Durable requests"): a
# write-ahead journal round-trip through rotation, compaction and a
# torn tail, plus a router-kill replay over stub replicas.
# `kernels-check` is the Pallas direction-kernel lane
# (docs/perf_pallas_linalg.md): the kernel equivalence/dispatch/key
# suite re-run with the kernel tier FORCED on the interpret-mode CPU
# path (PYCATKIN_LINALG_KERNEL=pallas + PYCATKIN_LINALG_INTERPRET=1),
# then a quick --linalg microbench proving every
# (bucket x tier x kernel) cell runs and reports per-bucket MFU
# against the measured matmul ceiling. `keys-check` is the cache-key
# integrity lane (pckey, docs/static_analysis.md): the PCL014
# cache-key-completeness + PCL015 key-tag-discipline rules over the
# tree, their mutation-tripwire fixture tests, and the trace-ident
# jaxpr-fingerprint sanitizer suite run armed (PYCATKIN_SAN=1).
# `transient-check` is the fused dense-output transient lane
# (docs/perf_transient.md), run with the pcsan tripwires armed: the
# fused/chunked + packed/solo bitwise equivalence suite plus the
# transient sync-budget pins, then a quick --transient bench gating on
# the >=3x fused speedup, the 1-materialization budget and
# bit-identical fused-vs-chunked output.

PYTEST = env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
	--continue-on-collection-errors -p no:cacheprovider

.PHONY: test test-faults test-validate test-sharded test-san test-all \
	lint lint-faults lint-syncs lint-baseline bench-smoke \
	aot-pack-selftest obs-check perfwatch chaos serve-check \
	router-check durable-check kernels-check keys-check \
	transient-check

test:
	$(PYTEST) -m 'not slow'

# Sharded-equality lane: the mesh-vs-no-mesh bit-identity and
# consolidated-rescue equivalence tests on exactly 2 virtual host
# devices (the configuration the equality contract is pinned to --
# see tests/test_sharded_sweep.py's module docstring).
test-sharded:
	env JAX_PLATFORMS=cpu \
		XLA_FLAGS=--xla_force_host_platform_device_count=2 \
		python -m pytest tests/test_sharded_sweep.py \
		tests/test_consolidated_rescue.py -q \
		-p no:cacheprovider

test-faults:
	$(PYTEST) -m faults

# Sanitizer lane: tripwire selftests, then the budget/serve suites with
# every pcsan tripwire armed (PYCATKIN_SAN=1) over the real paths.
test-san:
	env JAX_PLATFORMS=cpu PYCATKIN_SAN=1 python -m pytest \
		tests/test_san.py tests/test_sync_budget.py \
		tests/test_serve.py -q -p no:cacheprovider

test-validate:
	$(PYTEST) -m validate

test-all: lint
	$(PYTEST) -m ''

lint:
	python tools/pclint.py

lint-syncs:
	python tools/pclint.py --rules PCL001

lint-faults:
	python tools/pclint.py --rules PCL002

lint-baseline:
	python tools/pclint.py --update-baseline

bench-smoke:
	env JAX_PLATFORMS=cpu python bench.py --smoke

kernels-check:
	env JAX_PLATFORMS=cpu PYCATKIN_LINALG_KERNEL=pallas \
		PYCATKIN_LINALG_INTERPRET=1 python -m pytest \
		tests/test_pallas_linalg.py -q -m 'not slow' \
		-p no:cacheprovider
	env JAX_PLATFORMS=cpu python bench.py --linalg --quick

keys-check:
	python tools/pclint.py --rules PCL014,PCL015
	env JAX_PLATFORMS=cpu PYCATKIN_SAN=1 python -m pytest \
		tests/test_pckey_lint.py tests/test_trace_ident.py -q \
		-p no:cacheprovider

transient-check:
	env JAX_PLATFORMS=cpu PYCATKIN_SAN=1 python -m pytest \
		tests/test_transient_fused.py \
		"tests/test_sync_budget.py::test_fused_clean_transient_spends_one_sync" \
		"tests/test_sync_budget.py::test_packed_clean_transient_spends_one_sync_regardless_of_k" \
		-q -p no:cacheprovider
	env JAX_PLATFORMS=cpu PYCATKIN_SAN=1 python bench.py \
		--transient --quick --gate

aot-pack-selftest:
	env JAX_PLATFORMS=cpu python tools/aot_pack.py selftest

obs-check:
	env JAX_PLATFORMS=cpu python tools/obsview.py --selftest --sweep

perfwatch:
	env JAX_PLATFORMS=cpu python tools/perfwatch.py --selftest
	env JAX_PLATFORMS=cpu python tools/perfwatch.py --check

chaos:
	env JAX_PLATFORMS=cpu python -m pycatkin_tpu.robustness.scheduler \
		--drill

serve-check:
	env JAX_PLATFORMS=cpu python tools/soak.py --check

router-check:
	env JAX_PLATFORMS=cpu PYCATKIN_SAN=1 python tools/soak.py --chaos

durable-check:
	env JAX_PLATFORMS=cpu PYCATKIN_SAN=1 python tools/soak.py --durable
