"""Host-sync budget contract of the sweep hot path.

A clean (zero-failure) ``sweep_steady_state`` may perform at most 2
counted blocking device->host materializations (tightened from the
ISSUE-3 budget of 3 by the fused one-dispatch tail, which spends
exactly 1: the packed diagnostics bundle; the legacy split tail --
``PYCATKIN_FUSED_SWEEP=0``, fault plans -- spends 2: the solve fence
plus the packed tail bundle). On the tunneled production backend each
counted sync costs ~0.8-1.2 s of round trip regardless of payload, so a
PR that quietly reintroduces a per-stage
``np.asarray``/``int(jnp.sum(...))`` pull would tax every sweep; this
test makes that a hard failure, and the PCL001 checker flags the raw
idioms statically.
"""

import numpy as np
import pytest

from pycatkin_tpu import engine
# The budget AND the hot-path function list live in ONE registry module
# shared with the PCL001 static checker (make lint) -- a function added
# to the hot path is enforced by both mechanisms or neither.
from pycatkin_tpu.lint.hotpath import MAX_CLEAN_SYNCS
from pycatkin_tpu.models.synthetic import synthetic_system
from pycatkin_tpu.parallel.batch import (broadcast_conditions,
                                         sweep_steady_state)
from pycatkin_tpu.utils import profiling


@pytest.fixture(scope="module")
def problem():
    sim = synthetic_system(n_species=24, n_reactions=32)
    spec = sim.spec
    n = 48
    conds = broadcast_conditions(sim.conditions(), n)
    conds = conds._replace(T=np.linspace(400.0, 800.0, n))
    mask = engine.tof_mask_for(spec, [spec.rnames[-1]])
    return spec, conds, mask


def _run_clean(spec, conds, mask, **kwargs):
    with profiling.sync_budget() as budget:
        out = sweep_steady_state(spec, conds, tof_mask=mask, **kwargs)
    assert bool(np.all(np.asarray(out["success"]))), \
        "budget only applies to a clean sweep; this one had failures"
    return out, budget


def test_clean_sweep_within_sync_budget(problem):
    spec, conds, mask = problem
    sweep_steady_state(spec, conds, tof_mask=mask)   # warm, uncounted
    _, budget = _run_clean(spec, conds, mask)
    assert budget.count <= MAX_CLEAN_SYNCS, (
        f"clean sweep spent {budget.count} counted host syncs "
        f"(budget {MAX_CLEAN_SYNCS}): {budget.labels}")


def test_clean_sweep_with_stability_within_sync_budget(problem):
    spec, conds, mask = problem
    sweep_steady_state(spec, conds, tof_mask=mask, check_stability=True)
    out, budget = _run_clean(spec, conds, mask, check_stability=True)
    assert "stable" in out
    assert budget.count <= MAX_CLEAN_SYNCS, (
        f"clean sweep (stability on) spent {budget.count} counted host "
        f"syncs (budget {MAX_CLEAN_SYNCS}): {budget.labels}")


def test_fused_clean_sweep_spends_one_sync(problem):
    """The fused single-dispatch tail's whole clean path is ONE counted
    sync -- the packed bundle -- and the budget test would not notice a
    regression to 2, so pin it exactly."""
    spec, conds, mask = problem
    sweep_steady_state(spec, conds, tof_mask=mask, check_stability=True)
    _, budget = _run_clean(spec, conds, mask, check_stability=True)
    assert budget.count == 1, (
        f"fused clean sweep spent {budget.count} counted syncs "
        f"(expected exactly 1): {budget.labels}")
    assert budget.labels == ["fused tail bundle"]


@pytest.mark.parametrize("k", [2, 4])
def test_packed_clean_sweep_spends_one_sync_regardless_of_k(
        k, monkeypatch):
    """The packed multi-tenant clean path is ONE counted sync TOTAL --
    the stacked telemetry + bundle pull -- no matter how many tenants
    share the dispatch. A per-tenant sync would scale the serving tax
    linearly with K, which is exactly what packing exists to avoid."""
    from pycatkin_tpu.frontend import abi
    from pycatkin_tpu.parallel.batch import (clear_program_caches,
                                             packed_sweep_steady_state)
    monkeypatch.setenv(abi.ABI_ENV, "1")
    monkeypatch.setenv("PYCATKIN_AOT_CACHE", "off")
    clear_program_caches()
    tenants = []
    for seed in range(k):
        sim = synthetic_system(n_species=12, n_reactions=14, seed=seed)
        conds = broadcast_conditions(sim.conditions(), 8)
        conds = conds._replace(T=np.linspace(440.0, 700.0, 8))
        mask = engine.tof_mask_for(sim.spec, [sim.spec.rnames[-1]])
        tenants.append((sim.spec, conds, mask))
    specs = [t[0] for t in tenants]
    conds_l = [t[1] for t in tenants]
    masks = [t[2] for t in tenants]
    packed_sweep_steady_state(specs, conds_l, tof_mask=masks)  # warm
    with profiling.sync_budget() as budget:
        outs = packed_sweep_steady_state(specs, conds_l, tof_mask=masks)
    assert all(bool(np.all(np.asarray(o["success"]))) for o in outs), \
        "budget only applies to a clean pack; this one had failures"
    assert budget.count == 1, (
        f"packed clean sweep (K={k}) spent {budget.count} counted "
        f"syncs (expected exactly 1): {budget.labels}")
    assert budget.labels == ["packed fused tail bundle"]
    clear_program_caches()


def test_fused_clean_transient_spends_one_sync():
    """The fused transient sweep's whole clean path is ONE counted
    sync: the batched (ys, ok, bundle) pull (docs/perf_transient.md).
    The host chunk loop it replaces spent one per chunk plus the
    finish."""
    from pycatkin_tpu.parallel.batch import batch_transient
    sim = synthetic_system(n_species=12, n_reactions=14, seed=5)
    conds = broadcast_conditions(sim.conditions(), 4)
    conds = conds._replace(T=np.linspace(480.0, 540.0, 4))
    save_ts = np.concatenate([[0.0], np.logspace(-9, -2, 9)])
    batch_transient(sim.spec, conds, save_ts)   # warm, uncounted
    with profiling.sync_budget() as budget:
        _, ok = batch_transient(sim.spec, conds, save_ts)
    assert bool(np.all(np.asarray(ok))), \
        "budget only applies to a clean transient; this one failed"
    assert budget.count == 1, (
        f"fused clean transient spent {budget.count} counted syncs "
        f"(expected exactly 1): {budget.labels}")
    assert budget.labels == ["fused transient bundle"]


@pytest.mark.parametrize("k", [2, 4])
def test_packed_clean_transient_spends_one_sync_regardless_of_k(
        k, monkeypatch):
    """K same-bucket transient sweeps ride ONE counted sync total --
    the stacked (ys, ok, bundle) pull -- exactly like the packed
    steady-state path."""
    from pycatkin_tpu.frontend import abi
    from pycatkin_tpu.parallel.batch import (clear_program_caches,
                                             packed_batch_transient)
    monkeypatch.setenv(abi.ABI_ENV, "1")
    monkeypatch.setenv("PYCATKIN_AOT_CACHE", "off")
    clear_program_caches()
    specs, conds_l = [], []
    for seed in range(k):
        sim = synthetic_system(n_species=12, n_reactions=14, seed=seed)
        conds = broadcast_conditions(sim.conditions(), 4)
        conds_l.append(conds._replace(
            T=np.linspace(470.0, 540.0, 4) + 2.0 * seed))
        specs.append(sim.spec)
    save_ts = np.concatenate([[0.0], np.logspace(-9, -2, 9)])
    packed_batch_transient(specs, conds_l, save_ts)   # warm
    with profiling.sync_budget() as budget:
        outs = packed_batch_transient(specs, conds_l, save_ts)
    assert all(bool(np.all(np.asarray(ok))) for _, ok in outs), \
        "budget only applies to a clean pack; this one had failures"
    assert budget.count == 1, (
        f"packed clean transient (K={k}) spent {budget.count} counted "
        f"syncs (expected exactly 1): {budget.labels}")
    assert budget.labels == ["packed transient bundle"]
    clear_program_caches()


def test_legacy_clean_sweep_within_sync_budget(problem, monkeypatch):
    """The split tail (fused path disabled) must stay at 2 counted
    syncs: solve fence + packed tail bundle."""
    spec, conds, mask = problem
    monkeypatch.setenv("PYCATKIN_FUSED_SWEEP", "0")
    sweep_steady_state(spec, conds, tof_mask=mask, check_stability=True)
    _, budget = _run_clean(spec, conds, mask, check_stability=True)
    assert budget.count <= MAX_CLEAN_SYNCS, (
        f"legacy clean sweep spent {budget.count} counted host syncs "
        f"(budget {MAX_CLEAN_SYNCS}): {budget.labels}")
    assert "sweep tail bundle" in budget.labels


def test_host_sync_pytree_is_one_counted_sync():
    """A tuple of arrays through host_sync is ONE counted round trip
    with every leaf returned as numpy (the fused escalation path pulls
    its masks this way)."""
    import jax.numpy as jnp
    profiling.reset_sync_count()
    a, b = profiling.host_sync((jnp.arange(3.0), jnp.arange(4.0) > 1.0),
                               "pytree unit test")
    assert isinstance(a, np.ndarray) and a.shape == (3,)
    assert isinstance(b, np.ndarray) and b.dtype == bool
    assert profiling.sync_count() == 1
    assert profiling.sync_labels() == ["pytree unit test"]
    profiling.reset_sync_count()


def test_sync_counter_counts_and_resets():
    import jax.numpy as jnp
    profiling.reset_sync_count()
    v = profiling.host_sync(jnp.arange(3.0), "unit test")
    assert isinstance(v, np.ndarray) and v.shape == (3,)
    assert profiling.sync_count() == 1
    assert profiling.sync_labels() == ["unit test"]
    assert profiling.reset_sync_count() == 1
    assert profiling.sync_count() == 0
