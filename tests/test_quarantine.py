"""Per-lane NaN quarantine (parallel/batch.py) + forensics
(robustness/forensics.py): a NaN fault in one lane of a steady sweep
is detected (success=True + non-finite state is the silent-poisoning
signature), demoted, rescued, and -- the acceptance bar -- leaves
every OTHER lane's results bit-identical to a clean run. Forensics
name the quarantined lane with its verdict breakdown and ladder
history.

CPU-only determinism drill (markers: validate + faults).
"""

import numpy as np
import pytest

from pycatkin_tpu import engine
from pycatkin_tpu.models.synthetic import synthetic_system
from pycatkin_tpu.parallel.batch import (broadcast_conditions,
                                         sweep_steady_state)
from pycatkin_tpu.robustness import (FaultPlan, FaultSpec, fault_scope,
                                     format_failure_report,
                                     sweep_failure_report)
from pycatkin_tpu.utils import profiling

pytestmark = [pytest.mark.validate, pytest.mark.faults]

N_LANES = 64
BAD_LANE = 17


@pytest.fixture(scope="module")
def sweep_problem():
    sim = synthetic_system(n_species=16, n_reactions=24, seed=3)
    spec = sim.spec
    conds = broadcast_conditions(sim.conditions(), N_LANES)
    conds = conds._replace(T=np.linspace(480.0, 620.0, N_LANES))
    mask = engine.tof_mask_for(spec, [spec.rnames[-1]])
    opts = sim.solver_options()
    return spec, conds, mask, opts


def _run(spec, conds, mask, opts):
    return sweep_steady_state(spec, conds, tof_mask=mask, opts=opts,
                              check_stability=True)


def test_nan_lane_quarantined_others_bit_identical(sweep_problem):
    spec, conds, mask, opts = sweep_problem
    clean = _run(spec, conds, mask, opts)
    assert bool(np.all(np.asarray(clean["success"]))), \
        "drill needs a fully converging clean sweep"
    assert not np.any(np.asarray(clean.get("quarantined", False)))

    profiling.drain_events()
    plan = FaultPlan([FaultSpec(site="batched steady solve",
                                kind="nan", lanes=(BAD_LANE,),
                                times=1)])
    with fault_scope(plan):
        out = _run(spec, conds, mask, opts)
    events = profiling.drain_events()

    # The poisoned lane was caught: flagged quarantined, then re-solved
    # by the rescue ladder (un-poisoned dispatch -> converges again).
    quar = np.asarray(out["quarantined"])
    assert bool(quar[BAD_LANE])
    assert [int(i) for i in np.flatnonzero(quar)] == [BAD_LANE]

    # THE acceptance bar: all other lanes bit-identical to a clean run.
    others = np.arange(N_LANES) != BAD_LANE
    for key in ("y", "tof", "activity", "success", "stable",
                "residual"):
        a = np.asarray(clean[key])[others]
        b = np.asarray(out[key])[others]
        np.testing.assert_array_equal(
            a, b, err_msg=f"lane bleed-through in {key!r}")

    # Quarantine rung event names the lane.
    qevents = [ev for ev in events
               if ev.get("kind") == "degradation"
               and ev.get("rung") == "quarantine"]
    assert qevents and any(BAD_LANE in ev.get("lanes", [])
                           for ev in qevents)

    # Forensics: the report names the quarantined lane, its verdict
    # breakdown and its ladder history.
    rep = sweep_failure_report(out, conds=conds, events=qevents)
    assert rep["n_lanes"] == N_LANES
    assert rep["quarantined_lanes"] == [BAD_LANE]
    lane = next(r for r in rep["lanes"] if r["lane"] == BAD_LANE)
    assert lane["quarantined"]
    assert set(lane["verdict"]) == {"rate_ok", "pos_ok", "sums_ok"}
    assert lane["history"], "lane history must carry the quarantine event"
    assert "residual" in lane and "dt_exit" in lane
    assert "T" in lane["conditions"]
    text = format_failure_report(rep)
    assert f"lane {BAD_LANE}:" in text and "QUARANTINED" in text


def test_chunked_quarantine_status_forces_resume(sweep_problem,
                                                 tmp_path):
    """A chunk whose quarantined lanes stay failed (rescues poisoned
    too) is journaled with status 'quarantined' -- NOT a completed
    status, so a resume re-solves it and converges everything."""
    from pycatkin_tpu.robustness import chunked_sweep_steady_state
    from pycatkin_tpu.robustness.ladder import DegradationPolicy

    spec, conds, mask, opts = sweep_problem
    jdir = str(tmp_path / "journal")
    policy = DegradationPolicy(base_delay_s=0.001, max_delay_s=0.002)
    plan = FaultPlan([
        FaultSpec(site="batched steady solve", kind="nan",
                  lanes=(5,), times=None),
        # fnmatch: [..] is a character class, so "rescue*" (not
        # "rescue[*]") matches the rescue[ptc]/rescue[lm] sites.
        FaultSpec(site="rescue*", kind="nan", times=None),
    ])
    with fault_scope(plan):
        out, report = chunked_sweep_steady_state(
            spec, conds, chunk=32, tof_mask=mask, opts=opts,
            journal=jdir, policy=policy)
    assert report["quarantined"], "no chunk recorded as quarantined"
    quar = np.asarray(out["quarantined"])
    succ = np.asarray(out["success"])
    assert np.any(quar & ~succ)
    qevents = [ev for ev in report["events"]
               if ev.get("rung") == "quarantine"]
    assert qevents and all(ev["lanes"] for ev in qevents)

    # Resume with the faults gone: quarantined chunks re-dispatch.
    out2, report2 = chunked_sweep_steady_state(
        spec, conds, chunk=32, tof_mask=mask, opts=opts,
        journal=jdir, resume=True, policy=policy)
    assert sorted(report2["reused"]) == sorted(
        set(range(report["n_chunks"])) - set(report["quarantined"]))
    assert bool(np.all(np.asarray(out2["success"])))
    assert not np.any(np.asarray(out2["quarantined"])
                      & ~np.asarray(out2["success"]))


def test_lane_diagnostics_present_on_clean_sweep(sweep_problem):
    """The per-lane solver diagnostics ride in every sweep result (the
    forensics layer must not need a special mode to have data)."""
    spec, conds, mask, opts = sweep_problem
    out = _run(spec, conds, mask, opts)
    for key in ("rate_ok", "pos_ok", "sums_ok"):
        arr = np.asarray(out[key])
        assert arr.shape == (N_LANES,) and arr.dtype == bool
        assert bool(np.all(arr))        # converged clean sweep
    dt = np.asarray(out["dt_exit"])
    assert dt.shape == (N_LANES,) and np.all(np.isfinite(dt))
    assert not np.any(np.asarray(out["quarantined"]))
