"""The pclint framework: per-rule fixture proofs (detect /
inline-suppress / baseline-suppress), the two regression fixes the
PCL001 migration shipped (multi-line ``# sync-ok:``, keyword-argument
scalar pulls), registry consistency, and the repo-tree gate itself
(``make lint`` must exit 0 on the current checkout).

The seeded-violation corpus lives in tests/lint_fixtures/ -- excluded
from the default walk (core.EXCLUDE_DIRS) precisely so it can stay
red while the tree stays green; tests reach it via ``core.lint_file``
which bypasses scope filtering on purpose.

NOTE: PCL006 scans this test file too, so env-key literals below are
spelled as concatenations ("PYCATKIN_" + ...) to stay out of the
checker's full-match regex.
"""

import ast
import json
import os
import re
import shutil
import subprocess
import sys

import pytest

from pycatkin_tpu.lint import baseline
from pycatkin_tpu.lint import core
from pycatkin_tpu.lint.abi_capture import (SPEC_ARRAY_FIELDS,
                                           AbiCaptureChecker)
from pycatkin_tpu.lint.async_blocking import AsyncBlockingChecker
from pycatkin_tpu.lint.atomic_write import AtomicWriteChecker
from pycatkin_tpu.lint.core import Finding, checkers_for, lint_file, run_lint
from pycatkin_tpu.lint.dtype import DtypeChecker
from pycatkin_tpu.lint.env_registry import EnvRegistryChecker
from pycatkin_tpu.lint.event_kinds import EventKindChecker
from pycatkin_tpu.lint.fault_sites import FaultSiteChecker
from pycatkin_tpu.lint.fused_tail import FusedTailChecker
from pycatkin_tpu.lint.host_sync import HostSyncChecker, collect_syncs
from pycatkin_tpu.lint.hotpath import (HOT_FUNCTIONS, HOT_PATH_FILES,
                                       MAX_CLEAN_SYNCS)
from pycatkin_tpu.lint.lock_discipline import LockDisciplineChecker
from pycatkin_tpu.lint.metric_names import MetricNameChecker
from pycatkin_tpu.lint.purity import JitPurityChecker
from pycatkin_tpu.lint.tracer import TracerLeakChecker

REPO = core.REPO_ROOT
FIXTURES = os.path.join(os.path.dirname(__file__), "lint_fixtures")


def fx(name):
    return os.path.join(FIXTURES, name)


def active(findings):
    return [f for f in findings if f.suppressed is None]


def inline(findings):
    return [f for f in findings if f.suppressed == "inline"]


def _fault_checker(tmp_path):
    """PCL002 against a doc documenting only `fixture:documented`."""
    doc = tmp_path / "failure_model.md"
    doc.write_text("Known sites: `fixture:documented`.\n",
                   encoding="utf-8")
    return FaultSiteChecker(doc_path=str(doc))


def _event_checker(tmp_path):
    """PCL008 against a doc documenting only `span` and
    `degradation`."""
    doc = tmp_path / "failure_model.md"
    doc.write_text("Known kinds: `span`, `degradation`.\n",
                   encoding="utf-8")
    return EventKindChecker(doc_path=str(doc))


def _metric_checker(tmp_path):
    """PCL009 against a catalog documenting only
    `pycatkin_documented_total`."""
    doc = tmp_path / "observability.md"
    doc.write_text("Catalog: `pycatkin_documented_total`.\n",
                   encoding="utf-8")
    return MetricNameChecker(doc_path=str(doc))


# ---------------------------------------------------------------- PCL001

def test_hot_sync_fixture_detects_and_suppresses():
    findings = lint_file(HostSyncChecker(), fx("hot_sync_legacy.py"))
    act = active(findings)
    assert len(act) == 2, [f.message for f in act]
    kinds = sorted(f.message for f in act)
    assert any("np.asarray" in m for m in kinds)
    assert any("scalar pull" in m for m in kinds)
    # the `# pclint: disable=PCL001` pull is reported but suppressed
    sup = inline(findings)
    assert len(sup) == 1 and "diagnostics pull" in sup[0].reason
    # nothing leaks out of the hot function into cold_helper
    tree = ast.parse(open(fx("hot_sync_legacy.py")).read())
    cold = next(n for n in tree.body
                if isinstance(n, ast.FunctionDef)
                and n.name == "cold_helper")
    assert all(not (cold.lineno <= f.lineno <= cold.end_lineno)
               for f in findings)


def test_sync_ok_honored_on_continuation_line():
    """Regression (satellite fix): the pre-pclint script only matched
    `# sync-ok:` on the call's FIRST line; the fixture's multi-line
    np.asarray carries it on the last line and must be silent."""
    src = open(fx("hot_sync_legacy.py")).read().splitlines()
    annotated_line = next(i for i, ln in enumerate(src, 1)
                          if "# sync-ok:" in ln)
    findings = lint_file(HostSyncChecker(), fx("hot_sync_legacy.py"))
    span = range(annotated_line - 2, annotated_line + 1)
    assert all(f.lineno not in span for f in findings)


def test_keyword_scalar_pull_detected():
    """Regression (satellite fix): the pre-pclint `_is_scalar_pull`
    only inspected node.args[0]; keyword arguments slipped through."""
    findings = active(lint_file(HostSyncChecker(),
                                fx("hot_sync_legacy.py")))
    assert any("float(x=" in f.source for f in findings)


def test_collect_syncs_legacy_shape():
    hits = collect_syncs(fx("hot_sync_legacy.py"))
    assert hits == sorted(set(hits))
    assert len(hits) == 2
    assert all(isinstance(ln, int) and isinstance(s, str)
               for ln, s in hits)


def test_hot_registry_matches_batch():
    """Every registered hot function must exist as a top-level def in
    its registered file -- a renamed function must not silently fall
    out of enforcement."""
    for relpath, functions in HOT_PATH_FILES.items():
        path = os.path.join(REPO, relpath)
        tree = ast.parse(open(path, encoding="utf-8").read())
        defined = {n.name for n in tree.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))}
        missing = set(functions) - defined
        assert not missing, (
            f"{relpath}: hot-path registry names {sorted(missing)} "
            f"have no top-level def; update lint/hotpath.py")
    assert MAX_CLEAN_SYNCS >= 2   # the implementation's floor


# ---------------------------------------------------------------- PCL002

def test_fault_site_fixture(tmp_path):
    findings = lint_file(_fault_checker(tmp_path),
                         fx("fault_sites_legacy.py"))
    act = active(findings)
    labels = sorted(m.split("`")[1] for m in (f.message for f in act))
    assert labels == ["fixture:rescue[<i>]", "fixture:undocumented"]
    assert len(inline(findings)) == 1
    assert all("fixture:documented" not in f.message for f in findings)


# ---------------------------------------------------------------- PCL008

def test_event_kind_fixture(tmp_path):
    findings = lint_file(_event_checker(tmp_path),
                         fx("event_kinds_legacy.py"))
    act = active(findings)
    kinds = sorted(f.message.split("`")[1] for f in act)
    # first-positional AND kind= spellings both detected; the
    # documented kind, the dynamic kind and the inline-disabled kind
    # all stay silent.
    assert kinds == ["checkpoint", "degredation"]
    assert len(inline(findings)) == 1
    assert all("`degradation`" not in f.message for f in findings)


def test_event_kind_registry_matches_tree(tmp_path):
    """Every kind recorded by the package is documented in the REAL
    doc -- the in-tree proof that the registry is closed (the repo
    gate below covers this too, but this names the rule)."""
    from pycatkin_tpu.lint import lint_repo
    findings = lint_repo(rules=["PCL008"])
    assert findings == [], [f.message for f in findings]
    assert EventKindChecker().documented() >= {
        "span", "sync", "degradation", "rescue", "retry"}


# ---------------------------------------------------------------- PCL003

def test_purity_fixture_flags_print_under_jit():
    findings = lint_file(JitPurityChecker(), fx("batch_legacy.py"))
    act = active(findings)
    assert len(act) == 1 and "print()" in act[0].message
    assert "`batched`" in act[0].message   # the jit-by-name closure
    sup = inline(findings)
    assert len(sup) == 1 and "shape log" in sup[0].reason


# ---------------------------------------------------------------- PCL004

def test_tracer_fixture_flags_if_and_np_on_traced():
    findings = lint_file(TracerLeakChecker(), fx("batch_legacy.py"))
    act = active(findings)
    msgs = sorted(f.message for f in act)
    assert len(act) == 2, msgs
    assert any("Python `if` on a jnp expression" in m for m in msgs)
    assert any("np.asarray() on a traced value" in m for m in msgs)
    assert len(inline(findings)) == 1


def test_jit_closure_factory_is_detected():
    """Acceptance proof: `batched` in the fixture is jitted only via
    the `return jax.jit(batched)` factory idiom copied from
    parallel/batch.py -- both JAX-aware rules must see through it."""
    purity = active(lint_file(JitPurityChecker(), fx("batch_legacy.py")))
    tracer = active(lint_file(TracerLeakChecker(), fx("batch_legacy.py")))
    assert any("`batched`" in f.message for f in purity)
    assert any("`batched`" in f.message for f in tracer)


# ---------------------------------------------------------------- PCL005

def test_dtype_fixture():
    findings = lint_file(DtypeChecker(), fx("dtype_legacy.py"))
    act = active(findings)
    assert len(act) == 4
    assert any("np.float64" in f.message for f in act)
    assert any("\"float64\" dtype literal" in f.message for f in act)
    assert any("jnp.float32" in f.message
               and "precision-tier" in f.message for f in act)
    assert any("\"float32\" dtype literal" in f.message for f in act)
    sup = inline(findings)
    assert len(sup) == 2 and any("golden buffer" in s.reason
                                 for s in sup)


# ---------------------------------------------------------------- PCL006

def test_env_fixture():
    findings = lint_file(EnvRegistryChecker(), fx("env_legacy.py"))
    act = active(findings)
    assert len(act) == 1
    assert ("PYCATKIN_" + "FIXTURE_ONLY_KNOB") in act[0].message
    # the registered key and the inline-disabled key stay out
    assert all(("PYCATKIN_" + "FAULTS") not in f.message
               for f in findings)
    assert len(inline(findings)) == 1


def test_env_registry_documents_production_knobs():
    from pycatkin_tpu.lint.env_registry import registered_keys
    keys = registered_keys(os.path.join(REPO, "docs", "index.md"))
    for k in ("FAULTS", "VALIDATE", "TPU_X64", "AOT_CACHE"):
        assert ("PYCATKIN_" + k) in keys


# ---------------------------------------------------------------- PCL007

def test_abi_capture_fixture():
    findings = lint_file(AbiCaptureChecker(), fx("abi_capture_legacy.py"))
    act = active(findings)
    # stoich + is_ghost + the vmapped lambda's spec.area capture; the
    # builder-body read, scalar statics, the shadowed inner spec and
    # the non-builder helper all stay clean.
    assert len(act) == 3
    assert {("spec." + f.message.split("`")[1].split(".")[-1])
            for f in act} == {"spec.stoich", "spec.is_ghost", "spec.area"}
    assert len(inline(findings)) == 1
    assert "spec.bind(ops)" in act[0].message


def test_abi_capture_field_list_matches_modelspec():
    """SPEC_ARRAY_FIELDS (a literal -- the linter imports no package
    code) must be exactly ModelSpec's numpy-array fields, so a new
    array field cannot silently escape the rule."""
    import dataclasses

    import numpy as np

    from pycatkin_tpu.frontend.spec import ModelSpec
    from pycatkin_tpu.models.synthetic import synthetic_system

    spec = synthetic_system(n_species=6, n_reactions=8).spec
    array_fields = {f.name for f in dataclasses.fields(ModelSpec)
                    if isinstance(getattr(spec, f.name), np.ndarray)}
    assert SPEC_ARRAY_FIELDS == array_fields


# ------------------------------------------------- suppression machinery

_FIXTURE_MATRIX = [
    ("PCL001", lambda tmp: HostSyncChecker(), "hot_sync_legacy.py"),
    ("PCL002", _fault_checker, "fault_sites_legacy.py"),
    ("PCL003", lambda tmp: JitPurityChecker(), "batch_legacy.py"),
    ("PCL004", lambda tmp: TracerLeakChecker(), "batch_legacy.py"),
    ("PCL005", lambda tmp: DtypeChecker(), "dtype_legacy.py"),
    ("PCL006", lambda tmp: EnvRegistryChecker(), "env_legacy.py"),
    ("PCL007", lambda tmp: AbiCaptureChecker(), "abi_capture_legacy.py"),
    ("PCL008", _event_checker, "event_kinds_legacy.py"),
    ("PCL009", _metric_checker, "metric_legacy.py"),
    ("PCL010", lambda tmp: AsyncBlockingChecker(), "async_blocking_legacy.py"),
    ("PCL011", lambda tmp: LockDisciplineChecker(), "lock_discipline_legacy.py"),
    ("PCL012", lambda tmp: AtomicWriteChecker(), "atomic_write_legacy.py"),
]


@pytest.mark.parametrize("rule,make_checker,fixture",
                         _FIXTURE_MATRIX,
                         ids=[m[0] for m in _FIXTURE_MATRIX])
def test_every_rule_detect_inline_baseline(rule, make_checker, fixture,
                                           tmp_path):
    """The ISSUE contract per rule: the fixture detects, inline
    suppresses, and a baseline written from the active findings
    silences a re-run completely (with zero stale entries)."""
    path = fx(fixture)
    findings = lint_file(make_checker(tmp_path), path)
    assert active(findings), f"{rule}: fixture detected nothing"
    assert inline(findings), f"{rule}: fixture proves no inline suppress"
    assert all(f.rule == rule for f in findings)

    bl = tmp_path / "lint_baseline.json"
    baseline.save(str(bl), active(findings))
    rerun = lint_file(make_checker(tmp_path), path)
    rerun, stale = baseline.apply_to(rerun, str(bl))
    assert not active(rerun), f"{rule}: baseline did not suppress"
    assert not stale
    assert all(f.suppressed == "baseline" for f in rerun
               if f.suppressed != "inline")


def test_baseline_fingerprint_survives_line_drift():
    a = Finding(rule="PCL005", path="x.py", lineno=10, col=0,
                message="m", source="bad = np.float64")
    b = Finding(rule="PCL005", path="x.py", lineno=99, col=4,
                message="m", source="bad  =  np.float64")
    fa, = baseline.fingerprints([a])
    fb, = baseline.fingerprints([b])
    assert fa == fb            # content-addressed, whitespace-normalized
    c = Finding(rule="PCL005", path="x.py", lineno=10, col=0,
                message="m", source="bad = np.float32")
    fc, = baseline.fingerprints([c])
    assert fc != fa            # editing the line invalidates the entry


def test_baseline_reports_stale_entries(tmp_path):
    f = Finding(rule="PCL005", path="gone.py", lineno=1, col=0,
                message="m", source="bad = np.float64")
    bl = tmp_path / "lint_baseline.json"
    baseline.save(str(bl), [f])
    _, stale = baseline.apply_to([], str(bl))
    assert len(stale) == 1 and stale[0]["path"] == "gone.py"


def test_disable_all_silences_every_rule(tmp_path):
    p = tmp_path / "m.py"
    p.write_text("import numpy as np\n"
                 "x = np.float64(1.0)  # pclint: disable=all -- why\n",
                 encoding="utf-8")
    findings = lint_file(DtypeChecker(), str(p))
    assert findings and all(f.suppressed == "inline" for f in findings)


def test_syntax_error_becomes_pcl000(tmp_path):
    p = tmp_path / "broken.py"
    p.write_text("def oops(:\n", encoding="utf-8")
    doc = os.path.join(REPO, "docs", "index.md")
    result = run_lint(root=str(tmp_path),
                      checkers=[EnvRegistryChecker(doc_path=doc)],
                      paths=["broken.py"])
    assert [f.rule for f in result.findings] == ["PCL000"]


def test_unknown_rule_selector_raises():
    with pytest.raises(KeyError, match="PCL999"):
        checkers_for(["PCL999"])
    assert [c.rule for c in checkers_for(["tracer-leak", "PCL001"])] \
        == ["PCL004", "PCL001"]


# ------------------------------------------------------- the repo gate

def _run_pclint(*argv):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "pclint.py"),
         *argv],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120)


def test_repo_tree_is_lint_clean():
    """The hard acceptance gate: the full default run (all rules, the
    committed baseline) exits 0 on the current tree."""
    proc = _run_pclint()
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "pclint: OK" in proc.stdout


@pytest.mark.skipif(shutil.which("make") is None,
                    reason="make not installed")
def test_make_lint_exits_zero():
    proc = subprocess.run(["make", "lint"], cwd=REPO,
                          env=dict(os.environ, JAX_PLATFORMS="cpu"),
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_json_and_sarif_outputs_parse():
    js = json.loads(_run_pclint("--format", "json").stdout)
    assert js["counts"]["active"] == 0
    assert {"PCL001", "PCL006"} <= set(js["rules"])
    sarif = json.loads(_run_pclint("--format", "sarif").stdout)
    assert sarif["version"] == "2.1.0"
    rules = sarif["runs"][0]["tool"]["driver"]["rules"]
    assert {r["id"] for r in rules} >= {"PCL003", "PCL004", "PCL005"}


def test_cli_no_cache_flag_still_exits_zero():
    proc = _run_pclint("--no-cache")
    assert proc.returncode == 0, proc.stdout + proc.stderr


# A condensed-but-faithful subset of the SARIF 2.1.0 schema (the full
# OASIS document is ~15k lines and the container has no network; this
# subset pins every structural property pclint emits, with
# additionalProperties left open exactly where the spec leaves it
# open). Validated with the jsonschema package already in the image.
_SARIF_21_SCHEMA = {
    "type": "object",
    "required": ["version", "runs"],
    "properties": {
        "version": {"const": "2.1.0"},
        "$schema": {"type": "string", "format": "uri"},
        "runs": {
            "type": "array",
            "minItems": 1,
            "items": {
                "type": "object",
                "required": ["tool"],
                "properties": {
                    "tool": {
                        "type": "object",
                        "required": ["driver"],
                        "properties": {"driver": {
                            "type": "object",
                            "required": ["name"],
                            "properties": {
                                "name": {"type": "string"},
                                "informationUri": {"type": "string"},
                                "rules": {
                                    "type": "array",
                                    "items": {
                                        "type": "object",
                                        "required": ["id"],
                                        "properties": {
                                            "id": {"type": "string"},
                                            "name": {"type": "string"},
                                            "shortDescription": {
                                                "type": "object",
                                                "required": ["text"],
                                            },
                                        },
                                    },
                                },
                            },
                        }},
                    },
                    "results": {
                        "type": "array",
                        "items": {
                            "type": "object",
                            "required": ["message"],
                            "properties": {
                                "ruleId": {"type": "string"},
                                "level": {"enum": ["none", "note",
                                                   "warning", "error"]},
                                "message": {
                                    "type": "object",
                                    "required": ["text"],
                                    "properties": {
                                        "text": {"type": "string"}},
                                },
                                "locations": {
                                    "type": "array",
                                    "items": {
                                        "type": "object",
                                        "properties": {
                                            "physicalLocation": {
                                                "type": "object",
                                                "properties": {
                                                    "artifactLocation": {
                                                        "type": "object",
                                                        "properties": {
                                                            "uri": {
                                                                "type":
                                                                "string"
                                                            }},
                                                    },
                                                    "region": {
                                                        "type": "object",
                                                        "properties": {
                                                            "startLine": {
                                                                "type":
                                                                "integer",
                                                                "minimum":
                                                                1},
                                                            "startColumn":
                                                            {"type":
                                                             "integer",
                                                             "minimum":
                                                             1},
                                                        },
                                                    },
                                                },
                                            }},
                                    },
                                },
                                "suppressions": {
                                    "type": "array",
                                    "items": {
                                        "type": "object",
                                        "required": ["kind"],
                                        "properties": {
                                            "kind": {"enum": [
                                                "inSource", "external"]},
                                            "justification": {
                                                "type": "string"},
                                        },
                                    },
                                },
                            },
                        },
                    },
                },
            },
        },
    },
}


def test_sarif_output_validates_against_2_1_0_schema():
    """Structural SARIF 2.1.0 conformance, both faces: the clean-tree
    document (empty results) and a findings-bearing document produced
    from the seeded-violation fixture corpus."""
    import jsonschema

    clean = json.loads(_run_pclint("--format", "sarif").stdout)
    jsonschema.validate(clean, _SARIF_21_SCHEMA)

    dirty_proc = _run_pclint(
        os.path.join("tests", "lint_fixtures", "env_legacy.py"),
        "--format", "sarif", "--no-baseline")
    assert dirty_proc.returncode == 1
    dirty = json.loads(dirty_proc.stdout)
    jsonschema.validate(dirty, _SARIF_21_SCHEMA)
    assert dirty["runs"][0]["results"], "fixture produced no results"


_GH_ANNOTATION = re.compile(
    r"^::error file=(?P<file>[^,]+),line=(?P<line>\d+),"
    r"col=(?P<col>\d+),title=(?P<title>[^:]+)::(?P<msg>.+)$")


def test_github_format_emits_error_annotations():
    """--format=github: one parseable ::error command per ACTIVE
    finding, nothing at all on a clean tree (the annotation surface
    mirrors the exit code)."""
    clean = _run_pclint("--format", "github")
    assert clean.returncode == 0, clean.stdout + clean.stderr
    assert clean.stdout.strip() == ""

    dirty = _run_pclint(
        os.path.join("tests", "lint_fixtures", "env_legacy.py"),
        "--format", "github", "--no-baseline")
    assert dirty.returncode == 1
    lines = dirty.stdout.strip().splitlines()
    assert lines
    for ln in lines:
        m = _GH_ANNOTATION.match(ln)
        assert m is not None, f"unparseable annotation: {ln!r}"
        assert int(m.group("line")) >= 1
        assert int(m.group("col")) >= 1
        assert m.group("title").startswith("pclint PCL")
        assert "\n" not in m.group("msg")


# ------------------------------------------- PCL013 (cross-module pass)

# A miniature package tree: the decorated sweep body reaches one direct
# leak, one leak two hops down, one clean helper, and one def-line
# suppression. PCL013 is the only rule that needs a whole TREE (not a
# single fixture file) because its evidence is the call graph.
_MINI_BATCH = '''\
import jax.numpy as jnp
import numpy as np

from pycatkin_tpu.lint.hotpath import hotpath


def _leaky_tail(x):
    return np.asarray(x)


def _clean_helper(x):
    return _deep_leak(x) + 1


def _deep_leak(x):
    return float(jnp.sum(x))


def _reviewed_tail(x):  # pclint: disable=PCL013 -- host-side numpy conversion, no device round trip
    return np.asarray(x)


@hotpath
def fused_sweep(x):
    y = _clean_helper(x)
    return _leaky_tail(y) + _reviewed_tail(y)
'''


def _mini_tree(tmp_path):
    pkg = tmp_path / "pycatkin_tpu" / "parallel"
    pkg.mkdir(parents=True)
    (tmp_path / "pycatkin_tpu" / "__init__.py").write_text(
        "", encoding="utf-8")
    (pkg / "__init__.py").write_text("", encoding="utf-8")
    (pkg / "batch.py").write_text(_MINI_BATCH, encoding="utf-8")
    return str(tmp_path)


def test_fused_tail_flags_reachable_undecorated_syncs(tmp_path):
    root = _mini_tree(tmp_path)
    result = run_lint(root=root, checkers=[FusedTailChecker()])
    act = active(result.findings)
    flagged = sorted(f.message.split("`")[1] for f in act)
    # direct callee AND the two-hop callee; never the clean helper or
    # the decorated root itself
    assert flagged == ["_deep_leak", "_leaky_tail"], \
        [f.message for f in result.findings]
    sup = inline(result.findings)
    assert len(sup) == 1 and "_reviewed_tail" in sup[0].message
    assert "host-side numpy conversion" in sup[0].reason
    assert all(f.rule == "PCL013" for f in result.findings)


def test_fused_tail_silent_once_decorated(tmp_path):
    root = _mini_tree(tmp_path)
    fixed = _MINI_BATCH.replace(
        "def _leaky_tail", "@hotpath\ndef _leaky_tail").replace(
        "def _deep_leak", "@hotpath\ndef _deep_leak")
    (tmp_path / "pycatkin_tpu" / "parallel" / "batch.py").write_text(
        fixed, encoding="utf-8")
    result = run_lint(root=root, checkers=[FusedTailChecker()])
    assert not active(result.findings), \
        [f.message for f in result.findings]


def test_hotpath_runtime_registry_matches_static_scan():
    """Satellite 4 drift gate, both directions: every function
    decorated at runtime lives in a scanned file under its static
    name, and every statically scanned name is actually decorated in
    the imported module (a decorator deleted at runtime but left in a
    stale scan would silently drop enforcement)."""
    import pycatkin_tpu.parallel.batch  # noqa: F401 -- fills registry
    from pycatkin_tpu.lint.hotpath import (HOT_PATH_SCAN_FILES,
                                           runtime_registry)
    runtime = runtime_registry()
    assert runtime, "no @hotpath decorations registered at import"
    for mod, qual in runtime:
        rel = mod.replace(".", "/") + ".py"
        assert rel in HOT_PATH_SCAN_FILES, (
            f"{mod}.{qual} is @hotpath-decorated but {rel} is not in "
            f"HOT_PATH_SCAN_FILES -- invisible to the static side")
        assert qual in HOT_PATH_FILES[rel], (mod, qual)
    runtime_names = {qual for _, qual in runtime}
    for rel, names in HOT_PATH_FILES.items():
        assert names <= runtime_names, names - runtime_names


# ----------------------------------------------------------- lint cache

def _cache_tree(tmp_path):
    pkg = tmp_path / "pycatkin_tpu" / "solvers"   # in DtypeChecker scope
    pkg.mkdir(parents=True)
    (tmp_path / "pycatkin_tpu" / "__init__.py").write_text(
        "", encoding="utf-8")
    (pkg / "__init__.py").write_text("", encoding="utf-8")
    (pkg / "mod.py").write_text(
        "import numpy as np\nx = np.float64(1.0)\n", encoding="utf-8")
    return str(tmp_path)


def _cached_run(root, cache):
    return run_lint(root=root,
                    checkers=[DtypeChecker(), FusedTailChecker()],
                    cache=cache)


def test_cache_warm_hit_returns_identical_findings(tmp_path):
    from dataclasses import asdict

    from pycatkin_tpu.lint.cache import LintCache
    root = _cache_tree(tmp_path)
    c1 = LintCache(root)
    r1 = _cached_run(root, c1)
    assert c1.hits == 0 and c1.misses >= 2   # file + project entries
    assert len(active(r1.findings)) == 1
    c1.save()

    c2 = LintCache(root)
    r2 = _cached_run(root, c2)
    assert c2.misses == 0 and c2.hits >= 2
    assert ([asdict(f) for f in r1.findings]
            == [asdict(f) for f in r2.findings])


def test_cache_invalidates_on_file_edit(tmp_path):
    from pycatkin_tpu.lint.cache import LintCache
    root = _cache_tree(tmp_path)
    c1 = LintCache(root)
    _cached_run(root, c1)
    c1.save()

    # The edit must miss BOTH the per-file entry and the project-level
    # (PCL013) entry -- any package change re-keys the index pass.
    (tmp_path / "pycatkin_tpu" / "solvers" / "mod.py").write_text(
        "import numpy as np\n"
        "x = np.float64(1.0)\n"
        "y = np.float64(2.0)\n", encoding="utf-8")
    c2 = LintCache(root)
    r2 = _cached_run(root, c2)
    # edited file + project entry miss; the untouched __init__ still hits
    assert c2.misses >= 2
    assert len(active(r2.findings)) == 2


def test_cache_salt_invalidates_on_registry_doc_change(tmp_path):
    from pycatkin_tpu.lint.cache import LintCache
    root = _cache_tree(tmp_path)
    c1 = LintCache(root)
    _cached_run(root, c1)
    c1.save()

    # docs/*.md feed the salt (doc-backed registries): the whole cache
    # goes cold even though no Python file changed.
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "observability.md").write_text("`m`\n", encoding="utf-8")
    c2 = LintCache(root)
    _cached_run(root, c2)
    assert c2.hits == 0 and c2.misses >= 2


def test_cache_disabled_reads_and_writes_nothing(tmp_path):
    from pycatkin_tpu.lint.cache import LintCache
    root = _cache_tree(tmp_path)
    c = LintCache(root, enabled=False)
    r = _cached_run(root, c)
    c.save()
    assert active(r.findings)
    assert not os.path.exists(os.path.join(root, ".pclint_cache"))


def test_cache_corrupt_file_is_a_cold_start(tmp_path):
    from pycatkin_tpu.lint.cache import LintCache
    root = _cache_tree(tmp_path)
    cdir = tmp_path / ".pclint_cache"
    cdir.mkdir()
    (cdir / "cache.json").write_text("{definitely not json",
                                     encoding="utf-8")
    c = LintCache(root)
    r = _cached_run(root, c)
    assert len(active(r.findings)) == 1   # works, just uncached
    c.save()                              # and repairs the file
    from pycatkin_tpu.lint.cache import CACHE_VERSION
    data = json.load(open(cdir / "cache.json", encoding="utf-8"))
    assert data["version"] == CACHE_VERSION
