"""tools/lint_host_syncs.py: the hot path stays free of uncounted
blocking materializations, and the lint itself flags/excuses the right
idioms."""

import subprocess
import sys
import textwrap

import pytest

sys.path.insert(0, "tools")
import lint_host_syncs  # noqa: E402


def test_repo_hot_path_is_clean():
    proc = subprocess.run(
        [sys.executable, "tools/lint_host_syncs.py"],
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "OK" in proc.stdout


@pytest.fixture
def lint_target(tmp_path, monkeypatch):
    def write(source):
        path = tmp_path / "batch.py"
        path.write_text(textwrap.dedent(source))
        monkeypatch.setattr(lint_host_syncs, "TARGET", str(path))
        return path
    return write


def test_flags_raw_materializations_in_hot_functions(lint_target):
    lint_target("""
        def _finish_sweep(res):
            a = np.asarray(res.success)
            b = int(jnp.sum(res.x))
            return a, b

        def _not_hot(res):
            return np.asarray(res.x)
    """)
    flagged = lint_host_syncs.collect_syncs(lint_host_syncs.TARGET)
    assert len(flagged) == 2
    assert any("np.asarray" in src for _, src in flagged)
    assert any("int(jnp.sum" in src for _, src in flagged)


def test_counted_and_annotated_syncs_pass(lint_target):
    lint_target("""
        def _rescue(res):
            n = int(host_sync(jnp.sum(res.x), "rescue pre-check"))
            mask = np.asarray(res.success)  # sync-ok: failure path
            return n, mask
    """)
    assert lint_host_syncs.collect_syncs(lint_host_syncs.TARGET) == []


def test_nested_closures_inside_hot_functions_count(lint_target):
    lint_target("""
        def sweep_steady_state(res):
            def run():
                return np.asarray(res.x)
            return run()
    """)
    flagged = lint_host_syncs.collect_syncs(lint_host_syncs.TARGET)
    assert len(flagged) == 1
