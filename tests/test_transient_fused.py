"""Fused dense-output transient sweeps (docs/perf_transient.md).

The fused path collapses the host chunk loop into ONE traced program:
same math, same grid, ONE dispatch and ONE counted sync. Every
contract here is a bitwise one -- "close enough" would let the fused
and chunked worlds drift apart, and the serving layer advertises
fused/chunked (and packed/solo) interchangeability as an exact
equivalence, not a tolerance.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from pycatkin_tpu import engine
from pycatkin_tpu.frontend import abi
from pycatkin_tpu.models.synthetic import synthetic_system
from pycatkin_tpu.parallel import batch as _batch
from pycatkin_tpu.parallel.batch import (batch_transient,
                                         broadcast_conditions,
                                         clear_program_caches,
                                         packed_batch_transient,
                                         prewarm_transient_programs)
from pycatkin_tpu.robustness import FaultPlan, FaultSpec, fault_scope
from pycatkin_tpu.utils import profiling

LANES = 4
SAVE_TS = np.concatenate([[0.0], np.logspace(-9, -2, 9)])


def _problem(seed=7, lanes=LANES, dT=0.0):
    sim = synthetic_system(n_species=12, n_reactions=14, seed=seed)
    conds = broadcast_conditions(sim.conditions(), lanes)
    conds = conds._replace(T=np.linspace(480.0, 545.0, lanes) + dT)
    return sim.spec, conds


def _bits(ys, ok):
    ys, ok = np.asarray(ys), np.asarray(ok)
    return (ys.dtype, ys.shape, ys.tobytes(),
            ok.dtype, ok.shape, ok.tobytes())


def test_fused_matches_chunked_fallback_bitwise(monkeypatch):
    """PYCATKIN_FUSED_TRANSIENT=0 reroutes batch_transient through the
    host chunk loop; the output must be bit-identical -- the env knob
    is an escape hatch, never a different answer."""
    spec, conds = _problem()
    ys_f, ok_f = batch_transient(spec, conds, SAVE_TS)
    assert bool(np.asarray(ok_f).all())
    monkeypatch.setenv(engine.FUSED_TRANSIENT_ENV, "0")
    ys_c, ok_c = batch_transient(spec, conds, SAVE_TS)
    assert _bits(ys_f, ok_f) == _bits(ys_c, ok_c)


def test_chunked_drive_uneven_chunks_bitwise():
    """force_chunking with a chunk size that does not divide the grid
    exercises the ragged-tail chunk; still bit-identical to fused."""
    spec, conds = _problem()
    opts = engine.ODEOptions()
    ys_f, ok_f = batch_transient(spec, conds, SAVE_TS, opts=opts)
    cprog = _batch._transient_chunk_program(_batch._prog_spec(spec),
                                           opts)
    fprog = _batch._transient_finish_program(
        _batch._prog_spec(spec), engine.finish_options(opts))
    # 10 save points, chunk=4 -> chunks of 4, 4, 1 (plus the finish).
    ys_c, ok_c = engine.chunked_transient_drive(
        cprog, fprog, conds, jnp.asarray(conds.y0, dtype=jnp.float64),
        SAVE_TS, opts, chunk=4, batched=True, force_chunking=True)
    assert _bits(ys_f, ok_f) == _bits(ys_c, ok_c)


@pytest.mark.parametrize("tier", ["", "f32-polish"])
@pytest.mark.parametrize("k", [1, 2, 4])
def test_packed_matches_solo_bitwise(k, tier, monkeypatch):
    """K same-bucket transient sweeps through one packed dispatch are
    per-tenant bitwise identical to K solo runs, in both precision
    tiers (the transient trace is pure f64 -- the tier is a cache key
    only, so the answers cannot differ either)."""
    from pycatkin_tpu import precision
    if tier:
        monkeypatch.setenv(precision.TIER_ENV, tier)
    else:
        monkeypatch.delenv(precision.TIER_ENV, raising=False)
    monkeypatch.setenv(abi.ABI_ENV, "1")
    monkeypatch.setenv("PYCATKIN_AOT_CACHE", "off")
    clear_program_caches()
    try:
        specs, conds_l = [], []
        for seed in range(k):
            spec, conds = _problem(seed=seed, dT=2.0 * seed)
            specs.append(spec)
            conds_l.append(conds)
        solo = [batch_transient(s, c, SAVE_TS)
                for s, c in zip(specs, conds_l)]
        packed = packed_batch_transient(specs, conds_l, SAVE_TS)
        assert len(packed) == k
        for (ys_s, ok_s), (ys_p, ok_p) in zip(solo, packed):
            assert bool(np.asarray(ok_s).all())
            assert _bits(ys_s, ok_s) == _bits(ys_p, ok_p)
    finally:
        clear_program_caches()


def test_poisoned_tenant_is_isolated(monkeypatch):
    """A NaN-poisoned tenant fails its own lane verdicts without
    perturbing a single bit of its co-tenant -- the isolation promise
    that makes multi-tenant packing safe to serve."""
    monkeypatch.setenv(abi.ABI_ENV, "1")
    monkeypatch.setenv("PYCATKIN_AOT_CACHE", "off")
    clear_program_caches()
    try:
        spec0, conds0 = _problem(seed=0)
        spec1, conds1 = _problem(seed=1, dT=2.0)
        y0 = np.asarray(conds1.y0, dtype=np.float64).copy()
        y0[1, :] = np.nan
        conds1 = conds1._replace(y0=y0)
        ys_solo, ok_solo = batch_transient(spec0, conds0, SAVE_TS)
        packed = packed_batch_transient([spec0, spec1],
                                        [conds0, conds1], SAVE_TS)
        ys_p0, ok_p0 = packed[0]
        _, ok_p1 = packed[1]
        assert _bits(ys_solo, ok_solo) == _bits(ys_p0, ok_p0)
        assert not bool(np.asarray(ok_p1)[1]), \
            "the poisoned lane must not report success"
    finally:
        clear_program_caches()


def test_fault_plan_degrades_to_chunked_path():
    """Any active fault plan -- even one whose sites never fire --
    disables the fused route: the injection sites (chunk boundaries,
    finish) live on the host-driven path, so drills must keep
    exercising it. The sync labels prove which path ran."""
    spec, conds = _problem()
    batch_transient(spec, conds, SAVE_TS)   # warm fused (uncounted)
    plan = FaultPlan([FaultSpec(site="nosuch:site", kind="transient")])
    with fault_scope(plan):
        assert not engine.fused_transient_enabled()
        with profiling.sync_budget() as budget:
            ys, ok = batch_transient(spec, conds, SAVE_TS)
    assert bool(np.asarray(ok).all())
    assert "fused transient bundle" not in budget.labels
    assert any(lb.startswith("transient chunk[") for lb in budget.labels)
    assert "transient finish" in budget.labels
    # And back out of the scope the fused route returns.
    assert engine.fused_transient_enabled()
    with profiling.sync_budget() as budget:
        ys_f, ok_f = batch_transient(spec, conds, SAVE_TS)
    assert budget.labels == ["fused transient bundle"]
    assert _bits(ys, ok) == _bits(ys_f, ok_f)


def _compile_total():
    from pycatkin_tpu.obs import metrics as _metrics
    return float(sum(
        _metrics.counter("pycatkin_compile_total").values().values()))


def test_prewarm_covers_solo_and_packed(monkeypatch):
    """prewarm_transient_programs compiles the solo fused program plus
    one packed program per requested tenant bucket; the subsequent
    solo AND packed dispatches then compile NOTHING -- the property the
    serve layer's warm() relies on for its zero-compile SLO. Transient
    programs key on the save-grid LENGTH, so a different grid of the
    same length is covered too."""
    monkeypatch.setenv(abi.ABI_ENV, "1")
    monkeypatch.setenv("PYCATKIN_AOT_CACHE", "off")
    clear_program_caches()
    try:
        spec, conds = _problem(seed=3)
        stats = prewarm_transient_programs(spec, conds, SAVE_TS,
                                           k_buckets=(2,))
        assert stats.compiled + stats.loaded == 2
        spec_b, conds_b = _problem(seed=4, dT=3.0)
        prewarm_transient_programs(spec_b, conds_b, SAVE_TS)
        before = _compile_total()
        batch_transient(spec, conds, SAVE_TS)
        other_grid = np.concatenate([[0.0], np.logspace(-8, -1, 9)])
        packed_batch_transient([spec, spec_b], [conds, conds_b],
                               other_grid)
        assert _compile_total() == before, \
            "prewarmed transient dispatches must compile nothing"
    finally:
        clear_program_caches()


def test_fused_transient_enabled_env_parsing(monkeypatch):
    for off in ("0", "off", "NONE", "Disabled", "false"):
        monkeypatch.setenv(engine.FUSED_TRANSIENT_ENV, off)
        assert not engine.fused_transient_enabled(), off
    for on in ("1", "on", "yes", ""):
        monkeypatch.setenv(engine.FUSED_TRANSIENT_ENV, on)
        assert engine.fused_transient_enabled(), repr(on)
    monkeypatch.delenv(engine.FUSED_TRANSIENT_ENV, raising=False)
    assert engine.fused_transient_enabled()
