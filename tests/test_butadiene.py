"""Butadiene-from-ethanol example: the largest reference mechanism.

Exercises: a 118-state DFT landscape system with 16 energy landscapes
(input.json), and a 34-species microkinetic model whose 38
ReactionDerivedReactions borrow energetics from the DFT system via
``base_system`` (input_mkm.json) -- the reference's production MK
workflow (examples/Butadiene/butadiene_mkm.py). Also covers
Butadiene-style site naming ('*', 'H*'), which defeats the name-prefix
adsorbate association and must fall back to a single site group.
"""

import matplotlib

matplotlib.use("Agg")

import numpy as np
import pytest

import pycatkin_tpu as pk
from tests.conftest import reference_path


@pytest.fixture(scope="module")
def dft_system(ref_root):
    return pk.read_from_input_file(
        reference_path("examples", "Butadiene", "input.json"))


@pytest.fixture(scope="module")
def mkm_system(ref_root, dft_system):
    return pk.read_from_input_file(
        reference_path("examples", "Butadiene", "input_mkm.json"),
        base_system=dft_system)


def test_dft_system_loads(dft_system):
    assert len(dft_system.states) == 118
    assert len(dft_system.energy_landscapes) == 16


def test_energy_landscapes_evaluate(dft_system):
    """Every landscape constructs and the ES model evaluates (reference
    butadiene.py draws these; energy.py:39-60,238-318)."""
    name = next(iter(dft_system.energy_landscapes))
    lsc = dft_system.energy_landscapes[name]
    tof, espan, tdts, tdi, *_ = lsc.evaluate_energy_span_model(
        T=723.0, p=101325.0)
    assert np.isfinite(tof)
    assert espan > 0


def test_compare_energy_landscapes_renders(dft_system, tmp_path):
    from pycatkin_tpu.api.plotting import compare_energy_landscapes
    names = [n for n in dft_system.energy_landscapes
             if "dehydrogenation" in n]
    assert names, "expected dehydrogenation landscapes"
    compare_energy_landscapes([dft_system], landscapes=names,
                              etype="electronic", eunits="eV",
                              fig_path=str(tmp_path) + "/")
    import os
    assert any(f.endswith(".png") for f in os.listdir(tmp_path))


def test_mkm_derived_reactions(mkm_system, dft_system):
    """All 38 derived reactions resolve their base in the DFT system and
    produce finite rate constants at 723 K."""
    from pycatkin_tpu.frontend.reactions import ReactionDerivedReaction
    derived = [r for r in mkm_system.reactions.values()
               if isinstance(r, ReactionDerivedReaction)]
    assert len(derived) == 38
    assert all(r.base_reaction.name in dft_system.reactions
               for r in derived)
    kf, kr, keq = mkm_system.rate_constant_table()
    assert np.all(np.isfinite(kf))
    assert np.all(np.isfinite(kr))
    assert np.all(kf >= 0)


def test_mkm_star_naming_single_site_group(mkm_system):
    """'*' surface with 'H*'-style adsorbates: exactly one conservation
    group holding the empty site and every adsorbate."""
    spec = mkm_system.spec
    assert spec.groups.shape[0] == 1
    g = spec.groups[0]
    assert g[spec.sindex("*")] == 1.0
    assert g[spec.sindex("H*")] == 1.0
    assert int(g.sum()) == len(spec.adsorbate_indices)


def test_mkm_checkpoint_roundtrip(mkm_system, tmp_path):
    """Checkpoint of a derived-reaction system inlines the donor base
    reactions/states ('base reactions'/'base states' sections), so it
    reloads WITHOUT re-supplying base_system and reproduces the same
    rate constants."""
    from pycatkin_tpu.utils import save_system_json
    path = str(tmp_path / "mkm_ckpt.json")
    save_system_json(mkm_system, path)
    sim2 = pk.read_from_input_file(path)  # no base_system
    assert set(sim2.reactions) == set(mkm_system.reactions)
    kf1, kr1, _ = mkm_system.rate_constant_table()
    kf2, kr2, _ = sim2.rate_constant_table()
    r1 = list(mkm_system.spec.rnames)
    r2 = list(sim2.spec.rnames)
    order = [r2.index(n) for n in r1]
    np.testing.assert_allclose(kf2[order], kf1, rtol=1e-8)
    np.testing.assert_allclose(kr2[order], kr1, rtol=1e-8)


def test_mkm_steady_state(mkm_system):
    res = mkm_system.find_steady(use_transient_guess=False)
    assert bool(res.success)
    y = np.asarray(res.x)
    total = float(np.asarray(mkm_system.spec.groups)[0] @ y)
    assert total == pytest.approx(1.0, abs=5e-2)
