"""Test configuration: 8 virtual CPU devices for sharding tests, float64.

Must set XLA flags before jax initializes (hence env manipulation at
import time, as recommended for host-platform device emulation).
"""

import os

if os.environ.get("PYCATKIN_TEST_TPU", "0") != "1":
    # Force the CPU backend: the axon TPU plugin registers itself whenever
    # PALLAS_AXON_POOL_IPS is set, overriding JAX_PLATFORMS.
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()

    # The axon plugin registers itself in sitecustomize at interpreter
    # startup (before this file runs), so the env vars alone are not
    # enough under pytest -- override the backend choice in-config too.
    import jax
    jax.config.update("jax_platforms", "cpu")

from pycatkin_tpu.utils.cache import enable_persistent_cache  # noqa: E402

enable_persistent_cache()

import pytest  # noqa: E402

# Sanitizer layer (pcsan): registers the `san` marker; arms the
# tripwires when the PYCATKIN_SAN env knob is on (make test-san).
pytest_plugins = ("pycatkin_tpu.san.plugin",)

REFERENCE_ROOT = os.environ.get("PYCATKIN_REFERENCE_ROOT", "/root/reference")


def reference_path(*parts) -> str:
    return os.path.join(REFERENCE_ROOT, *parts)


@pytest.fixture(scope="session")
def ref_root():
    if not os.path.isdir(REFERENCE_ROOT):
        pytest.skip("reference tree not available")
    return REFERENCE_ROOT


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules():
    """Release compiled executables between test modules.

    XLA:CPU accumulates per-process JIT state across the suite's ~100
    compiled programs; past a threshold the compile-and-load path
    segfaults (measured deterministically ~40 tests in, gone when the
    crashing module runs alone). Specs are per-module anyway, so
    dropping the program caches costs little recompilation and keeps
    the long-lived pytest process inside the safe regime.
    """
    yield
    import jax

    from pycatkin_tpu.api import presets
    from pycatkin_tpu.parallel.batch import clear_program_caches

    clear_program_caches()
    presets._net_rates_program.cache_clear()
    presets._drc_program.cache_clear()
    jax.clear_caches()
