"""Descriptor-grid triage tests (reference analysis.py capabilities,
with the first-point-only repair bug fixed -- SURVEY.md §4)."""

import matplotlib

matplotlib.use("Agg")

import numpy as np
import pytest

from pycatkin_tpu.analysis.grid import (FAIL_CONSERVATION, FAIL_RATE,
                                        average_neighborhood,
                                        classify_failures,
                                        convergence_heatmap, make_heatmap)


def test_average_neighborhood_patches_all_failures():
    values = np.arange(25, dtype=float).reshape(5, 5)
    success = np.ones((5, 5), dtype=bool)
    success[1, 1] = False
    success[3, 4] = False
    values[1, 1] = np.nan
    patched, mask = average_neighborhood(values, success)
    assert mask[1, 1] and mask[3, 4], "ALL failed points must be patched"
    nb = [values[i, j] for i in (0, 1, 2) for j in (0, 1, 2)
          if (i, j) != (1, 1)]
    assert patched[1, 1] == pytest.approx(np.mean(nb))
    assert np.isfinite(patched).all()


def test_average_neighborhood_isolated_failure_stays():
    values = np.zeros((3, 3))
    success = np.zeros((3, 3), dtype=bool)  # everything failed
    patched, mask = average_neighborhood(values, success)
    assert not mask.any()


def test_classify_failures():
    from pycatkin_tpu.solvers.newton import SteadyStateResults

    class SpecStub:
        groups = np.array([[1.0, 1.0, 0.0]])

    x = np.array([
        [0.5, 0.5, 0.1],    # converged
        [0.9, 0.9, 0.0],    # failed, group sums to 1.8 -> conservation
        [0.6, 0.4, 0.0],    # failed, sums fine -> rate residual
    ])
    res = SteadyStateResults(
        x=x, success=np.array([True, False, False]),
        residual=np.array([0.1, 2.0, 5.0]),
        iterations=np.zeros(3), attempts=np.zeros(3))
    labels, detail = classify_failures(SpecStub(), res)
    assert labels[0] is None
    assert labels[1] == FAIL_CONSERVATION
    assert labels[2] == FAIL_RATE
    assert detail["n_failed"] == 2
    assert detail["worst_residual"] == 5.0


def test_heatmap_renders(tmp_path):
    rng = np.random.default_rng(0)
    x = np.linspace(-2, 0, 8)
    z = 10.0 ** rng.uniform(-9, 2, size=(8, 8))
    fig, axes = make_heatmap(x, x, z, path=str(tmp_path / "hm.png"))
    assert (tmp_path / "hm.png").exists()
    ok = rng.random((8, 8)) > 0.1
    fig, ax = convergence_heatmap(ok, x=x, y=x,
                                  path=str(tmp_path / "conv.png"))
    assert (tmp_path / "conv.png").exists()


def test_replay_lane_diagnoses_point(capsys):
    """replay_lane re-solves one sweep lane with verbose diagnostics
    (the debugging half of reference check_convergence,
    analysis.py:27-76): strategies chain until one converges, and the
    report carries residual/iterations/group sums/stability."""
    from pycatkin_tpu.analysis.grid import replay_lane
    from pycatkin_tpu.parallel.batch import stack_conditions
    from tests.test_verdicts import _toy_ads_system

    sim = _toy_ads_system("detailed_balance")
    spec = sim.spec
    conds = stack_conditions([sim.conditions()] * 3)
    res, report = replay_lane(spec, conds, lane=1)
    assert bool(res.success)
    assert report["lane"] == 1
    assert report["tries"][0]["strategy"] == "ptc"
    assert report["tries"][-1]["success"]
    assert report["tries"][-1]["stable"] is True
    sums = np.asarray(report["tries"][-1]["group_sums"])
    np.testing.assert_allclose(sums, 1.0, atol=5e-2)
    assert "replay lane 1" in capsys.readouterr().out
