"""Sharded-sweep equality: mesh vs no-mesh must be bit-identical.

The tentpole guarantee of the mesh-aware sweep tail: passing
``mesh=...`` to ``sweep_steady_state`` shards the lane axis end to end
(fast pass, rescue subsets, stability screen, tier-2 Jacobian,
TOF/activity) but changes NOTHING about the numbers -- every output
array is byte-for-byte identical to the unsharded sweep on the same
inputs.

The equality runs on a 2-device mesh, the CI sharded lane's
configuration (``--xla_force_host_platform_device_count=2``). The
CONTRACT is same-inputs/same-programs determinism at that shard shape;
XLA:CPU makes no bitwise promise across arbitrary per-shard shapes
(measured: an 8-way shard of 48 lanes perturbs a residual by 1 ulp,
which flips a convergence-threshold comparison on a handful of lanes
-- a codegen reassociation artifact, not a sharding bug, and exactly
why the sweep re-places every gathered subset deterministically
instead of hoping).
"""

import jax
import numpy as np
import pytest

from pycatkin_tpu import engine
from pycatkin_tpu.models.synthetic import synthetic_system
from pycatkin_tpu.parallel import batch
from pycatkin_tpu.solvers.newton import SolverOptions
from pycatkin_tpu.utils import profiling

needs_mesh = pytest.mark.skipif(
    jax.device_count() < 2,
    reason="needs >=2 devices (XLA_FLAGS=--xla_force_host_platform_"
           "device_count=N)")


def _mesh2():
    """The CI sharded lane's mesh: 2 devices over the lane axis."""
    return batch.make_mesh(2)


@pytest.fixture(scope="module")
def problem():
    sim = synthetic_system(n_species=24, n_reactions=32)
    spec = sim.spec
    n = 48
    conds = batch.broadcast_conditions(sim.conditions(), n)
    conds = conds._replace(T=np.linspace(400.0, 800.0, n))
    mask = engine.tof_mask_for(spec, [spec.rnames[-1]])
    return spec, conds, mask


def _run(problem, mesh=None, **kw):
    spec, conds, mask = problem
    # Fresh program caches per run: the equality must hold through a
    # real compile of each side's programs, not through accidental
    # registry sharing.
    batch.clear_program_caches()
    return batch.sweep_steady_state(spec, conds, tof_mask=mask,
                                    mesh=mesh, **kw)


def _assert_bit_identical(a, b):
    assert set(a) == set(b)
    for k in a:
        va, vb = np.asarray(a[k]), np.asarray(b[k])
        assert va.dtype == vb.dtype, k
        assert va.shape == vb.shape, k
        assert va.tobytes() == vb.tobytes(), (
            f"key {k!r} differs between unsharded and sharded sweep")


@needs_mesh
def test_clean_sweep_bit_identical(problem):
    _assert_bit_identical(_run(problem),
                          _run(problem, mesh=_mesh2()))


@needs_mesh
def test_stability_sweep_bit_identical(problem):
    _assert_bit_identical(
        _run(problem, check_stability=True),
        _run(problem, mesh=_mesh2(), check_stability=True))


@needs_mesh
def test_rescue_path_bit_identical(problem):
    # Crippled pacing so the fast pass genuinely fails lanes and the
    # consolidated rescue ladder runs on BOTH sides.
    opts = SolverOptions(max_steps=6, max_attempts=2)
    profiling.drain_events()
    a = _run(problem, opts=opts)
    n_rescues_a = len(profiling.peek_events("rescue"))
    b = _run(problem, mesh=_mesh2(), opts=opts)
    n_rescues_b = len(profiling.peek_events("rescue")) - n_rescues_a
    assert n_rescues_a > 0, "corpus did not exercise the rescue ladder"
    assert n_rescues_b == n_rescues_a
    _assert_bit_identical(a, b)


@needs_mesh
def test_stability_demote_path_bit_identical(problem):
    # An impossible Jacobian tolerance demotes every screened lane,
    # driving the tier-2 + demote re-solve tail on both sides.
    kw = dict(check_stability=True, pos_jac_tol=-1e6)
    _assert_bit_identical(_run(problem, **kw),
                          _run(problem, mesh=_mesh2(), **kw))


def test_trivial_mesh_reuses_unsharded_program_keys(problem):
    # A 1-device mesh must fingerprint exactly like no mesh at all --
    # bench.py can pass make_mesh() unconditionally and still hit the
    # stock single-device executables (registry AND AOT cache).
    from pycatkin_tpu.parallel import compile_pool
    spec, conds, mask = problem
    mesh1 = batch.make_mesh(1)
    sh = jax.sharding.NamedSharding(
        mesh1, jax.sharding.PartitionSpec(mesh1.axis_names[0]))
    plain = np.asarray(conds.T)
    placed = jax.device_put(plain, sh)
    opts = SolverOptions()
    assert (batch._steady_kind(opts, "ptc", sh)
            == batch._steady_kind(opts, "ptc"))
    assert (compile_pool.program_key("k", (placed,))
            == compile_pool.program_key("k", (plain,)))
    assert compile_pool.args_sharding_fingerprint((placed,)) == ""


@needs_mesh
def test_sharded_program_keys_do_not_collide(problem):
    # A genuinely sharded argument must key differently from the same
    # array unsharded, so mesh and single-device executables can never
    # serve each other from the registry or the AOT cache.
    from pycatkin_tpu.parallel import compile_pool
    mesh = batch.make_mesh()
    sh = jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec(mesh.axis_names[0]))
    plain = np.zeros(48)
    placed = jax.device_put(plain, sh)
    assert (compile_pool.program_key("k", (placed,))
            != compile_pool.program_key("k", (plain,)))
    assert compile_pool.args_sharding_fingerprint((placed,)) != ""
