"""Tiered stability certificates + the fused single-dispatch sweep tail.

The fused sweep program (``parallel/batch._fused_sweep_program``)
computes the solve, the NaN quarantine, the tier-0 stability
certificate (Gershgorin + deflated Lyapunov), TOF/activity and the
packed diagnostics bundle in ONE device dispatch; a clean sweep exits
on ONE counted host sync. These tests pin the contracts that made the
fusion safe:

- bit-identity with the legacy split pipeline
  (``PYCATKIN_FUSED_SWEEP=0``) on the clean, no-stability, rescue and
  tier-2-escalation corpora, and on unstable-seeded lanes that the
  demote loop must re-solve;
- tier-0 certificate verdicts agree with the host reference
  (:func:`solvers.newton.jacobian_eigenvalues_stable`) on every
  converged lane -- the certificates are sound one-way proofs and the
  escalation tier IS the reference eigensolve, so agreement is
  equality, not approximation (adversarial marginal bands within
  +-1e-10 of the threshold are exercised separately by
  tests/test_verdicts.py::test_lyapunov_certificate_sound_on_adversarial_matrices);
- the fused path stands down under an active fault plan (fault
  poisoning lands on the retried callable's RESULT, which the fused
  program's in-program quarantine would precede -- legacy semantics
  are preserved by not fusing).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pycatkin_tpu import engine
from pycatkin_tpu.models.synthetic import synthetic_system
from pycatkin_tpu.parallel import batch
from pycatkin_tpu.parallel.batch import (broadcast_conditions,
                                         stack_conditions,
                                         sweep_steady_state)
from pycatkin_tpu.solvers.newton import (SolverOptions,
                                         jacobian_eigenvalues_stable)
from pycatkin_tpu.utils import profiling


@pytest.fixture(scope="module")
def problem():
    sim = synthetic_system(n_species=24, n_reactions=32)
    spec = sim.spec
    n = 48
    conds = broadcast_conditions(sim.conditions(), n)
    conds = conds._replace(T=np.linspace(400.0, 800.0, n))
    mask = engine.tof_mask_for(spec, [spec.rnames[-1]])
    return spec, conds, mask


def _run_pair(monkeypatch, spec, conds, mask=None, **kwargs):
    """(fused result, its sync labels, legacy result): the same sweep
    through the fused dispatch and through the legacy split pipeline."""
    monkeypatch.delenv("PYCATKIN_FUSED_SWEEP", raising=False)
    with profiling.sync_budget() as budget:
        fused = sweep_steady_state(spec, conds, tof_mask=mask, **kwargs)
    monkeypatch.setenv("PYCATKIN_FUSED_SWEEP", "0")
    legacy = sweep_steady_state(spec, conds, tof_mask=mask, **kwargs)
    monkeypatch.delenv("PYCATKIN_FUSED_SWEEP", raising=False)
    return fused, budget.labels, legacy


def _assert_bitwise(fused, legacy):
    assert set(fused) == set(legacy)
    for k in sorted(fused):
        a, b = np.asarray(fused[k]), np.asarray(legacy[k])
        assert a.dtype == b.dtype, k
        assert a.tobytes() == b.tobytes(), (
            f"fused/legacy sweep results differ on {k!r}")


def test_fused_matches_legacy_clean_corpus(problem, monkeypatch):
    spec, conds, mask = problem
    fused, labels, legacy = _run_pair(monkeypatch, spec, conds, mask,
                                      check_stability=True)
    assert bool(np.all(np.asarray(fused["success"]))), \
        "corpus must converge cleanly for this test to mean anything"
    assert "fused tail bundle" in labels, \
        "the fused dispatch did not run (env leak?)"
    _assert_bitwise(fused, legacy)


def test_fused_matches_legacy_no_stability(problem, monkeypatch):
    spec, conds, mask = problem
    fused, labels, legacy = _run_pair(monkeypatch, spec, conds, mask)
    assert "fused tail bundle" in labels
    assert "stable" not in fused
    _assert_bitwise(fused, legacy)


def test_fused_matches_legacy_no_tof(problem, monkeypatch):
    spec, conds, _ = problem
    fused, labels, legacy = _run_pair(monkeypatch, spec, conds, None,
                                      check_stability=True)
    assert "fused tail bundle" in labels
    assert "tof" not in fused
    _assert_bitwise(fused, legacy)


def test_fused_matches_legacy_rescue_corpus(problem, monkeypatch):
    """Crippled pacing fails real lanes in the fast pass: the fused
    path must reconstruct the raw result and hand it to the exact
    legacy tail (rescue ladder and all), bit-for-bit."""
    spec, conds, mask = problem
    opts = SolverOptions(max_steps=6, max_attempts=2)
    n = np.asarray(conds.T).shape[0]
    keys = jax.random.split(jax.random.PRNGKey(0), n)
    fast = batch._steady_program(spec, batch._fast_pass_opts(opts))(
        conds, keys, None)
    assert np.any(~np.asarray(fast.success)), \
        "corpus produced no failed lanes -- rescue path not exercised"
    fused, _, legacy = _run_pair(monkeypatch, spec, conds, mask,
                                 opts=opts, check_stability=True)
    _assert_bitwise(fused, legacy)


def test_tier0_verdicts_agree_with_host_reference(problem, monkeypatch):
    """For every converged lane the sweep's 'stable' verdict equals the
    host reference eigensolve's: tier-0 certificates are sound one-way
    (never certify what the host would reject) and abstaining lanes
    escalate to the host eigensolve itself, so the tiers can only
    AGREE with the reference, never drift from it."""
    spec, conds, mask = problem
    monkeypatch.delenv("PYCATKIN_FUSED_SWEEP", raising=False)
    out = sweep_steady_state(spec, conds, tof_mask=mask,
                             check_stability=True)
    assert bool(np.all(np.asarray(out["success"])))
    ys = jnp.asarray(out["y"])
    Js = np.asarray(batch._jacobian_program(spec)(conds, ys))
    stable = np.asarray(out["stable"])
    for i in range(len(stable)):
        ref = jacobian_eigenvalues_stable(Js[i])
        assert bool(stable[i]) == ref, (
            f"lane {i}: tiered verdict {bool(stable[i])} != host "
            f"reference {ref}")


def test_escalation_matches_legacy_and_is_labeled(problem, monkeypatch):
    """When tier 0 abstains, the fused sweep must escalate the
    ambiguous lanes through the batched-mask pull + compacted host
    eigensolve and still match the legacy two-tier path bitwise.

    The synthetic corpus's dynamic Jacobians keep the column-sum-zero
    conservation structure, so the Gershgorin column discs certify
    every lane on their own; to force abstention we pin the TIER-0
    threshold (the two-argument, device-side call of
    ``stability_tolerance_from_scale``) far below any Gershgorin/
    Lyapunov bound while the host tier-2 path (which passes its eps
    explicitly via ``stability_tolerance``) keeps the real formula --
    every converged lane then escalates and the host eigensolve still
    clears it."""
    from pycatkin_tpu.solvers import newton

    spec, conds, mask = problem
    orig = newton.stability_tolerance_from_scale

    def tier0_never_certifies(scale, pos_tol=1e-2, eps=None):
        t = orig(scale, pos_tol, eps)
        # eps is None only on the device-side tier-0 call sites; the
        # host tier-2 threshold (stability_tolerance) passes finfo eps.
        return t - 2.0 * scale if eps is None else t

    # Patch BEFORE the programs trace; the off-default pos_jac_tol
    # gives this variant fresh cache keys so a previously-compiled
    # program cannot carry the baked-in real threshold.
    monkeypatch.setattr(newton, "stability_tolerance_from_scale",
                        tier0_never_certifies)
    monkeypatch.setattr(newton, "LYAPUNOV_MAX_DIM", 0)
    fused, labels, legacy = _run_pair(monkeypatch, spec, conds, mask,
                                      check_stability=True,
                                      pos_jac_tol=0.02)
    assert "fused tail bundle" in labels
    assert "tier-0 escalation masks" in labels, \
        "Gershgorin-only screen left nothing ambiguous -- the " \
        "escalation path was not exercised"
    assert "tier-2 jacobian" in labels
    _assert_bitwise(fused, legacy)


def test_unstable_seeded_lanes_match_legacy(monkeypatch):
    """Lanes seeded ON an unstable root converge there, fail the
    certificate AND the host eigensolve, and must ride the legacy
    demote/re-solve loop -- identically from the fused entry point."""
    from tests.test_verdicts import A_STABLE, A_UNSTABLE, _full_y
    from tests.test_verdicts import bistable as _bistable_fixture

    sim = _bistable_fixture.__wrapped__()
    spec = sim.spec
    dyn = np.asarray(spec.dynamic_indices)
    conds = stack_conditions([sim.conditions()] * 3)
    x0 = np.stack([_full_y(sim, A_UNSTABLE)[dyn],
                   _full_y(sim, A_STABLE)[dyn],
                   _full_y(sim, 0.0)[dyn]])
    fused, _, legacy = _run_pair(monkeypatch, spec, conds, None,
                                 x0=jnp.asarray(x0),
                                 check_stability=True)
    _assert_bitwise(fused, legacy)
    # The demotion actually happened: lane 0 escaped the unstable root.
    assert bool(np.all(np.asarray(fused["success"])))
    a = np.asarray(fused["y"])[:, spec.sindex("sa")]
    assert abs(a[0] - A_UNSTABLE) > 1e-3
    # And the tiered verdict agrees with the host reference on the
    # unstable seed itself (certificates must never certify it).
    ys = np.stack([_full_y(sim, A_UNSTABLE), _full_y(sim, A_STABLE),
                   _full_y(sim, 0.0)])
    verdicts = np.asarray(batch.stability_mask(spec, conds, ys))
    Js = np.asarray(batch._jacobian_program(spec)(conds,
                                                  jnp.asarray(ys)))
    for i in range(3):
        assert bool(verdicts[i]) == jacobian_eigenvalues_stable(Js[i])
    np.testing.assert_array_equal(verdicts, [False, True, True])


@pytest.mark.faults
def test_fused_stands_down_under_fault_plan(problem):
    """An active fault plan disables the fused dispatch: `on_result`
    poisoning lands AFTER the fused program's in-program quarantine,
    which would break the quarantine drill's semantics -- the legacy
    split tail (whose solve fence precedes the poisoning site) must
    run instead."""
    from pycatkin_tpu.robustness import FaultPlan, FaultSpec, fault_scope

    spec, conds, mask = problem
    # A registered site a plain (unchunked) sweep never dispatches:
    # the plan stays armed but no fault ever fires.
    plan = FaultPlan([FaultSpec(site="chunk:0", kind="transient")])
    with fault_scope(plan):
        with profiling.sync_budget() as budget:
            sweep_steady_state(spec, conds, tof_mask=mask,
                               check_stability=True)
    assert "fused tail bundle" not in budget.labels
    assert "sweep tail bundle" in budget.labels
