"""Pallas batched-LU direction kernels (PYCATKIN_LINALG_KERNEL=pallas).

The contract that makes the kernel tier safe to flip on
(docs/perf_pallas_linalg.md):

1.  EQUIVALENCE -- lane for lane, the interpret-mode kernel is a
    BITWISE twin of the XLA-op LU in :mod:`pycatkin_tpu.ops.linalg`
    (same arithmetic in the same order), at every ABI bucket shape and
    in both tier bulk dtypes. Under ``vmap`` (and for multi-column
    RHS) the XLA reference batches its contractions (reduction
    reorder), so those comparisons carry a tiny measured envelope; the
    vmapped KERNEL stays bitwise equal to its own solo runs (one grid
    program per lane).

2.  PIVOTING -- row-permuted and badly row-scaled systems factor
    accurately; a singular lane divides by a zero pivot and yields
    non-finite output WITHOUT perturbing its batch neighbours (the
    quarantine semantics the sweep relies on).

3.  DISPATCH -- :func:`pycatkin_tpu.ops.linalg.select_solver` routes
    through Pallas only when the kernel tier is resolved AND n is a
    static ABI bucket; with the kernel resolved to ``xla`` (the
    off-TPU default) the historical gauss/LU selection is reproduced
    exactly.

4.  IDENTITY -- Pallas and XLA programs never share a cache entry:
    kind strings carry the ``:kpl`` tag (after the ``:p32`` tier tag),
    and the xla tag is empty, so every pre-kernel program key / AOT
    entry stays byte-identical. Cost-ledger rows of tagged programs
    carry a ``kernel`` column; untagged rows are unchanged.

5.  SWEEPS -- an ABI-bucketed sweep under the forced kernel
    (``PYCATKIN_LINALG_KERNEL=pallas`` + ``PYCATKIN_LINALG_INTERPRET=1``
    on CPU) reproduces the XLA sweep's verdict masks bitwise, keeps
    solved states inside the solver-tolerance envelope, keeps packed
    multi-tenant runs bitwise equal to their solo runs, and spends
    zero post-warmup recompiles under the pcsan recompile sanitizer.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pycatkin_tpu import engine, precision
from pycatkin_tpu.frontend import abi
from pycatkin_tpu.models.synthetic import synthetic_system
from pycatkin_tpu.obs import costs
from pycatkin_tpu.ops import linalg
from pycatkin_tpu.ops import pallas_linalg as plk
from pycatkin_tpu.parallel import batch, compile_pool
from pycatkin_tpu.parallel.batch import (broadcast_conditions,
                                         clear_program_caches,
                                         packed_sweep_steady_state,
                                         sweep_steady_state)
from pycatkin_tpu.san import recompile
from pycatkin_tpu.solvers.newton import SolverOptions

# n=512 interpret-mode factorizations compile+run in seconds each; one
# representative case rides the slow marker, the fast buckets cover
# the logic in tier-1.
FAST_BUCKETS = (16, 32, 128)

# Measured vmapped-comparison envelope (CPU, f64): the batched XLA
# reference reassociates its contractions; observed maxrel ~2e-14 at
# n=32, ~1.4e-12 at n=128 on the well-conditioned corpus.
_VMAP_TOL = dict(rtol=1e-9, atol=1e-13)


def _well_conditioned(n, lanes=None, dtype=jnp.float64, seed=0):
    """Random square system(s) pushed diagonally dominant-ish."""
    rng = np.random.default_rng(seed)
    shape = (n, n) if lanes is None else (lanes, n, n)
    A = rng.standard_normal(shape) + 4.0 * np.eye(n)
    bshape = (n,) if lanes is None else (lanes, n)
    b = rng.standard_normal(bshape)
    return jnp.asarray(A, dtype), jnp.asarray(b, dtype)


def _bits(x):
    return np.asarray(x).tobytes()


# ------------------------------------------------------------- equivalence


@pytest.mark.parametrize("n", FAST_BUCKETS)
@pytest.mark.parametrize("dtype", [jnp.float64, jnp.float32],
                         ids=["f64", "f32"])
def test_factor_bitwise_vs_xla(n, dtype):
    A, _ = _well_conditioned(n, dtype=dtype, seed=n)
    LU_p, perm_p = jax.jit(plk.lu_factor)(A)
    LU_x, perm_x = jax.jit(linalg.lu_factor)(A)
    assert perm_p.dtype == jnp.int32
    assert np.array_equal(np.asarray(perm_p), np.asarray(perm_x))
    assert _bits(LU_p) == _bits(LU_x), \
        f"n={n}: kernel LU not bit-identical to the XLA LU"


@pytest.mark.slow
def test_factor_bitwise_vs_xla_512():
    A, _ = _well_conditioned(512, seed=512)
    LU_p, perm_p = jax.jit(plk.lu_factor)(A)
    LU_x, perm_x = jax.jit(linalg.lu_factor)(A)
    assert np.array_equal(np.asarray(perm_p), np.asarray(perm_x))
    assert _bits(LU_p) == _bits(LU_x)


@pytest.mark.parametrize("n", FAST_BUCKETS)
def test_solve_bitwise_vs_xla(n):
    A, b = _well_conditioned(n, seed=n + 1)
    LU, perm = linalg.lu_factor(A)
    x_p = jax.jit(plk.lu_solve)(LU, perm, b)
    x_x = jax.jit(linalg.lu_solve)(LU, perm, b)
    assert _bits(x_p) == _bits(x_x)
    # Matrix RHS ([n, k]): XLA vectorizes the k-column contractions
    # differently per program (reduction reorder), so the multi-RHS
    # comparison carries the envelope, like the vmapped one.
    B = jnp.stack([b, 2.0 * b], axis=-1)
    X_p = jax.jit(plk.lu_solve)(LU, perm, B)
    X_x = jax.jit(linalg.lu_solve)(LU, perm, B)
    assert X_p.shape == (n, 2)
    assert np.allclose(np.asarray(X_p), np.asarray(X_x), **_VMAP_TOL)


@pytest.mark.parametrize("n", FAST_BUCKETS)
def test_fused_factor_solve_matches_composition(n):
    A, b = _well_conditioned(n, seed=n + 2)
    fused = jax.jit(plk.factor_solve)(A, b)
    composed = plk.lu_solve(*plk.lu_factor(A), b)
    assert _bits(fused) == _bits(composed)
    x_x = linalg.lu_solve(*linalg.lu_factor(A), b)
    assert _bits(fused) == _bits(x_x)


def test_make_msolve_reuses_factorization():
    """The chord contract: factor once, solve many -- each solve
    bitwise equal to the one-shot fused path."""
    n = 32
    A, b = _well_conditioned(n, seed=7)
    msolve = plk.make_msolve(A)
    for scale in (1.0, -2.5, 1e6):
        r = scale * b
        assert _bits(msolve(r)) == _bits(plk.factor_solve(A, r))


def test_vmap_matches_solo_lanes_bitwise():
    """vmap lifts the lane axis into the kernel grid -- one grid
    program per lane, so each vmapped lane must reproduce its solo
    run bitwise (there is no cross-lane batching to reassociate)."""
    n, lanes = 32, 6
    A, b = _well_conditioned(n, lanes=lanes, seed=9)
    xs = jax.jit(jax.vmap(plk.factor_solve))(A, b)
    for i in range(lanes):
        assert _bits(xs[i]) == _bits(plk.factor_solve(A[i], b[i])), \
            f"lane {i} drifted from its solo run"


def test_vmap_envelope_vs_xla():
    """The vmapped XLA reference batches its contractions (reduction
    reorder), so lane batches agree to the documented envelope, not
    the ulp."""
    n, lanes = 32, 8
    A, b = _well_conditioned(n, lanes=lanes, seed=11)
    x_p = jax.jit(jax.vmap(plk.factor_solve))(A, b)
    x_x = jax.jit(jax.vmap(
        lambda a, r: linalg.lu_solve(*linalg.lu_factor(a), r)))(A, b)
    assert np.allclose(np.asarray(x_p), np.asarray(x_x), **_VMAP_TOL)


# ---------------------------------------------------------------- pivoting


def test_row_permuted_system_pivots_correctly():
    n = 32
    rng = np.random.default_rng(13)
    A, b = _well_conditioned(n, seed=13)
    shuffled = jnp.asarray(np.asarray(A)[rng.permutation(n)])
    x = plk.factor_solve(shuffled, b)
    ref = np.linalg.solve(np.asarray(shuffled), np.asarray(b))
    assert np.allclose(np.asarray(x), ref, rtol=1e-10, atol=1e-12)
    # The permutation is genuinely non-trivial.
    _, perm = plk.lu_factor(shuffled)
    assert not np.array_equal(np.asarray(perm), np.arange(n))


def test_ill_conditioned_rows_match_xla_bitwise():
    """Rows scaled over ~12 decades: partial pivoting picks the same
    pivots as the XLA path, so the factorization stays a bitwise
    twin even where the numerics are ugly."""
    n = 32
    A, b = _well_conditioned(n, seed=17)
    scale = jnp.asarray(np.logspace(-6, 6, n))
    As = A * scale[:, None]
    assert _bits(plk.factor_solve(As, b)) == \
        _bits(linalg.lu_solve(*linalg.lu_factor(As), b))


def test_singular_lane_goes_nonfinite_without_poisoning_neighbours():
    n, lanes = 16, 3
    A, b = _well_conditioned(n, lanes=lanes, seed=19)
    A = A.at[1].set(A.at[1, 0].get() * 0.0)  # lane 1: all-zero matrix
    xs = jax.jit(jax.vmap(plk.factor_solve))(A, b)
    assert not np.all(np.isfinite(np.asarray(xs[1]))), \
        "singular lane must yield non-finite output"
    for i in (0, 2):
        assert _bits(xs[i]) == _bits(plk.factor_solve(A[i], b[i])), \
            f"healthy lane {i} was poisoned by the singular lane"
        assert np.all(np.isfinite(np.asarray(xs[i])))


# ---------------------------------------------------------------- dispatch


def test_supported_is_exactly_the_bucket_table():
    for n in plk.PALLAS_BUCKETS:
        assert plk.supported(n)
    for n in (1, 8, 20, 48, 64, 100, 256, 1024):
        assert not plk.supported(n)
    assert plk.PALLAS_BUCKETS == abi.SPECIES_BUCKETS


def test_select_solver_xla_reproduces_historical_policy(monkeypatch):
    monkeypatch.delenv(precision.KERNEL_ENV, raising=False)
    monkeypatch.delenv(precision.INTERPRET_ENV, raising=False)
    assert linalg.select_solver(16).path == "gauss"
    assert linalg.select_solver(linalg.UNROLL_MAX).path == "gauss"
    assert linalg.select_solver(linalg.UNROLL_MAX + 1).path == "lu"
    assert linalg.select_solver(128).path == "lu"
    assert linalg.select_solver(128).kernel == "xla"


def test_select_solver_forced_pallas(monkeypatch):
    monkeypatch.setenv(precision.KERNEL_ENV, "pallas")
    for n in plk.PALLAS_BUCKETS:
        choice = linalg.select_solver(n)
        assert choice.path == "pallas" and choice.kernel == "pallas"
        assert choice.solve is plk.factor_solve
        assert choice.make_solve is plk.make_msolve
    # Non-bucket shapes fall back to the historical policy even with
    # the kernel forced.
    assert linalg.select_solver(20).path == "gauss"
    assert linalg.select_solver(100).path == "lu"


def test_select_solver_auto_resolution(monkeypatch):
    """auto == xla on CPU unless interpret mode is explicitly forced;
    nothing here may depend on TPU hardware."""
    monkeypatch.setenv(precision.KERNEL_ENV, "auto")
    monkeypatch.delenv(precision.INTERPRET_ENV, raising=False)
    assert precision.linalg_kernel("cpu") == "xla"
    assert precision.linalg_kernel("tpu") == "pallas"
    monkeypatch.setenv(precision.INTERPRET_ENV, "1")
    assert precision.linalg_kernel("cpu") == "pallas"
    monkeypatch.setenv(precision.KERNEL_ENV, "nonsense")
    with pytest.raises(ValueError, match="PYCATKIN_LINALG_KERNEL"):
        precision.linalg_kernel("cpu")


def test_solve_and_make_msolve_shims_route_through_seam(monkeypatch):
    """The legacy entry points are thin shims over select_solver: with
    the kernel forced they serve bucket shapes through Pallas."""
    monkeypatch.setenv(precision.KERNEL_ENV, "pallas")
    monkeypatch.setenv(precision.INTERPRET_ENV, "1")
    A, b = _well_conditioned(16, seed=23)
    assert _bits(linalg.solve(A, b)) == _bits(plk.factor_solve(A, b))
    assert _bits(linalg.make_msolve(A)(b)) == \
        _bits(plk.make_msolve(A)(b))
    # Unforced on CPU (interpret opt-in cleared too -- auto would
    # otherwise still resolve to pallas): the historical gauss path.
    monkeypatch.delenv(precision.KERNEL_ENV, raising=False)
    monkeypatch.delenv(precision.INTERPRET_ENV, raising=False)
    assert _bits(linalg.solve(A, b)) == _bits(linalg.gauss_solve(A, b))


# ---------------------------------------------------------------- identity


def test_kernel_tag_roundtrip(monkeypatch):
    assert precision.kernel_tag("pallas") == ":kpl"
    assert precision.kernel_tag("xla") == ""
    assert precision.kernel_of_tag("steady:newton:opts:kpl") == "pallas"
    assert precision.kernel_of_tag("steady:newton:opts") == "xla"
    monkeypatch.setenv(precision.KERNEL_ENV, "pallas")
    assert precision.kernel_tag() == ":kpl"
    monkeypatch.delenv(precision.KERNEL_ENV, raising=False)


def test_xla_kind_strings_byte_identical_to_pre_kernel(monkeypatch):
    """The whole tiering is invisible until the env knob is set: kind
    strings (hence program keys and AOT entries) with the kernel unset
    or explicitly xla are byte-identical, carrying no ``:kpl``."""
    opts = SolverOptions()
    monkeypatch.delenv(precision.KERNEL_ENV, raising=False)
    monkeypatch.delenv(precision.INTERPRET_ENV, raising=False)
    unset = (batch._steady_kind(opts, "newton"),
             batch._rescue_kind(opts),
             batch._fused_kind(opts, 1e-12, "cpu", True, True))
    monkeypatch.setenv(precision.KERNEL_ENV, "xla")
    explicit = (batch._steady_kind(opts, "newton"),
                batch._rescue_kind(opts),
                batch._fused_kind(opts, 1e-12, "cpu", True, True))
    assert unset == explicit
    assert all(":kpl" not in k for k in unset)
    args = (jnp.zeros((4, 3)),)
    for a, bkind in zip(unset, explicit):
        assert compile_pool.program_key(a, args) == \
            compile_pool.program_key(bkind, args)


def test_pallas_kind_strings_carry_kpl_after_tier_tag(monkeypatch):
    opts = SolverOptions()
    monkeypatch.setenv(precision.KERNEL_ENV, "pallas")
    assert batch._steady_kind(opts, "newton").endswith(":kpl")
    assert batch._rescue_kind(opts).endswith(":kpl")
    fused32 = batch._fused_kind(opts, 1e-12, "cpu", True, True,
                                tier="f32-polish")
    assert ":p32:kpl" in fused32, \
        "kernel tag must ride AFTER the tier tag"
    # The screen program embeds no direction solves: never tagged.
    assert ":kpl" not in batch._screen_kind(1e-12, "cpu")


def test_cost_ledger_stamps_kernel_on_tagged_rows_only():
    ledger = costs.CostLedger()
    ledger.record("k1", kind="fused:opts:cpu:s1t1:kpl",
                  cost={"flops": 1e9})
    ledger.record("k2", kind="fused:opts:cpu:s1t1",
                  cost={"flops": 1e9})
    ledger.note_dispatch("k1", 0.5)
    ledger.note_dispatch("k2", 0.5)
    rows = ledger.snapshot()["programs"]
    assert rows["k1"]["kernel"] == "pallas"
    assert "kernel" not in rows["k2"], \
        "untagged rows must stay byte-identical to pre-kernel ledgers"


# ------------------------------------------------------------- sweep level

N_LANES = 8


@pytest.fixture(scope="module")
def problem():
    sim = synthetic_system(n_species=12, n_reactions=14, seed=4)
    conds = broadcast_conditions(sim.conditions(), N_LANES)
    conds = conds._replace(T=np.linspace(450.0, 700.0, N_LANES))
    mask = engine.tof_mask_for(sim.spec, [sim.spec.rnames[-1]])
    return sim.spec, conds, mask


@pytest.fixture(autouse=True)
def _sweep_env(monkeypatch):
    monkeypatch.setenv(abi.ABI_ENV, "1")
    monkeypatch.setenv("PYCATKIN_AOT_CACHE", "off")
    monkeypatch.delenv(precision.KERNEL_ENV, raising=False)
    monkeypatch.delenv(precision.INTERPRET_ENV, raising=False)
    monkeypatch.delenv(precision.TIER_ENV, raising=False)


@pytest.fixture(scope="module", autouse=True)
def _fresh_caches():
    clear_program_caches()
    yield
    clear_program_caches()


def _forced_pallas(monkeypatch):
    monkeypatch.setenv(precision.KERNEL_ENV, "pallas")
    monkeypatch.setenv(precision.INTERPRET_ENV, "1")


def test_sweep_verdicts_bitwise_under_forced_kernel(monkeypatch,
                                                    problem):
    """ABI buckets the 12-species system to n=16, so the forced kernel
    carries the whole Newton direction load; verdict masks must
    reproduce the XLA sweep bitwise, solved states agree like two
    independently converged solutions."""
    spec, conds, mask = problem
    ref = sweep_steady_state(spec, conds, tof_mask=mask,
                             check_stability=True)
    _forced_pallas(monkeypatch)
    out = sweep_steady_state(spec, conds, tof_mask=mask,
                             check_stability=True)
    for k in ("success", "stable", "quarantined"):
        assert _bits(ref[k]) == _bits(out[k]), \
            f"verdict {k!r} differs between kernel tiers"
    tel_a = np.asarray(ref["lane_telemetry"])
    tel_b = np.asarray(out["lane_telemetry"])
    assert tel_a[:, 3].tobytes() == tel_b[:, 3].tobytes(), \
        "telemetry strategy column differs between kernel tiers"
    ok = np.asarray(ref["success"], dtype=bool)
    # Cross-trajectory envelope: the legacy path solves these n=12
    # systems with unrolled Gauss-Jordan while the forced sweep runs
    # pallas-LU, so the two Newton iterations converge along different
    # trajectories to the same root. Measured divergence on this
    # problem: <= 1.6e-7 relative on non-tiny components, <= 1e-15
    # absolute on near-zero ones (docs/perf_pallas_linalg.md).
    assert np.allclose(np.asarray(ref["y"])[ok],
                       np.asarray(out["y"])[ok],
                       rtol=1e-5, atol=1e-12)


def test_packed_tenants_bitwise_vs_solo_under_forced_kernel(
        monkeypatch, problem):
    """Both sides of the packed contract run the SAME kernel tier, so
    the bitwise-vs-solo guarantee must survive the forced kernel."""
    spec, conds, mask = problem
    sim2 = synthetic_system(n_species=12, n_reactions=14, seed=5)
    conds2 = broadcast_conditions(sim2.conditions(), N_LANES)
    mask2 = engine.tof_mask_for(sim2.spec, [sim2.spec.rnames[-1]])
    _forced_pallas(monkeypatch)
    specs = [spec, sim2.spec]
    all_conds = [conds, conds2]
    masks = [mask, mask2]
    solo = [sweep_steady_state(s, c, tof_mask=m,
                               check_stability=True)
            for s, c, m in zip(specs, all_conds, masks)]
    packed = packed_sweep_steady_state(specs, all_conds,
                                       tof_mask=masks,
                                       check_stability=True)
    for t, (a, b) in enumerate(zip(solo, packed)):
        assert sorted(a) == sorted(b)
        for k in sorted(a):
            assert _bits(a[k]) == _bits(b[k]), \
                f"tenant {t}: {k!r} not bit-identical to solo"


def test_zero_post_warmup_recompiles_under_forced_kernel(monkeypatch,
                                                         problem):
    """The kernel path caches by kind like every other program: after
    one warm sweep the pcsan recompile sanitizer must see NOTHING
    compile on a re-run."""
    spec, conds, mask = problem
    _forced_pallas(monkeypatch)
    recompile.reset()
    recompile.activate()
    try:
        sweep_steady_state(spec, conds, tof_mask=mask,
                           check_stability=True)
        recompile.mark_warm()
        sweep_steady_state(spec, conds, tof_mask=mask,
                           check_stability=True)
    finally:
        recompile.deactivate()
        recompile.reset()
