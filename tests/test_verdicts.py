"""Solver verdicts and rate-model dispatch added in round 2.

Covers: the Jacobian-eigenvalue stability verdict (reference
solver.py:102-106) rejecting converged-but-unstable fixed points; the
collision/statistical desorption model (reference reaction.py:134-162 +
rate_constants.py:26-53) exposed through System/loader config; per-T
user-energy dict interpolation; and the multi-surface leftover-adsorbate
conservation-group warning.
"""

import json

import numpy as np
import pytest

import pycatkin_tpu as pk
from pycatkin_tpu import engine
from pycatkin_tpu.api.system import System
from pycatkin_tpu.constants import R, eVtokJ, h, kB
from pycatkin_tpu.frontend.reactions import Reaction, UserDefinedReaction
from pycatkin_tpu.frontend.states import State
from pycatkin_tpu.models.reactor import InfiniteDilutionReactor

eVtoJmol = eVtokJ * 1.0e3


def _ga_for_rate(k, T):
    """Forward free-energy barrier [eV] giving TST rate constant k at T."""
    return -R * T * np.log(k * h / (kB * T)) / eVtoJmol


# ---------------------------------------------------------------------
# Stability verdict
@pytest.fixture(scope="module")
def bistable():
    """Autocatalytic surface mechanism with three fixed points.

    r1: s + 2 sa -> 3 sa (rate k1*s*a^2), r2: sa -> s (rate k2*a);
    da/dt = a*(k1*a*(1-a) - k2). With k1=10, k2=1: a=0 (stable),
    a=(10-sqrt(60))/20 ~ 0.1127 (UNSTABLE), a ~ 0.8873 (stable).
    """
    T = 500.0
    s = State(name="s", state_type="surface")
    sa = State(name="sa", state_type="adsorbate")
    r1 = UserDefinedReaction(name="r1", reac_type="arrhenius",
                             reversible=False,
                             reactants=[s, sa, sa], products=[sa, sa, sa],
                             dGrxn_user=0.0,
                             dGa_fwd_user=_ga_for_rate(10.0, T))
    r2 = UserDefinedReaction(name="r2", reac_type="arrhenius",
                             reversible=False,
                             reactants=[sa], products=[s],
                             dGrxn_user=0.0,
                             dGa_fwd_user=_ga_for_rate(1.0, T))
    sim = System(start_state={"s": 1.0}, T=T, p=1.0e5)
    for st in (s, sa):
        sim.add_state(st)
    sim.add_reaction(r1)
    sim.add_reaction(r2)
    sim.add_reactor(InfiniteDilutionReactor())
    sim.build()
    return sim


A_UNSTABLE = (10.0 - np.sqrt(60.0)) / 20.0
A_STABLE = (10.0 + np.sqrt(60.0)) / 20.0


def _full_y(sim, a):
    y = np.zeros(sim.spec.n_species)
    y[sim.spec.sindex("s")] = 1.0 - a
    y[sim.spec.sindex("sa")] = a
    return y


def test_rate_constants_hit_targets(bistable):
    kf, kr, _ = bistable.rate_constant_table()
    np.testing.assert_allclose(kf, [10.0, 1.0], rtol=1e-10)
    np.testing.assert_allclose(kr, 0.0)


def test_check_stability_classifies_roots(bistable):
    cond = bistable.conditions()
    spec = bistable.spec
    assert not engine.check_stability(spec, cond, _full_y(bistable,
                                                          A_UNSTABLE))
    assert engine.check_stability(spec, cond, _full_y(bistable, A_STABLE))
    assert engine.check_stability(spec, cond, _full_y(bistable, 0.0))


def test_solver_accepts_unstable_root_without_verdict(bistable):
    """Documents the trap: started ON the unstable root, the PTC residual
    is zero and the plain convergence tests pass (reference system.py
    before the fork's solver.py verdict)."""
    res = bistable.find_steady(y0=_full_y(bistable, A_UNSTABLE),
                               use_transient_guess=False,
                               check_stability=False)
    assert bool(res.success)
    a = float(np.asarray(res.x)[bistable.spec.sindex("sa")])
    assert a == pytest.approx(A_UNSTABLE, abs=1e-6)


def test_stability_verdict_rejects_and_escapes(bistable):
    """With the verdict on (default), the unstable root is rejected and
    the retry lands on a STABLE fixed point (reference solver.py:102-106
    semantics)."""
    res = bistable.find_steady(y0=_full_y(bistable, A_UNSTABLE),
                               use_transient_guess=False)
    a = float(np.asarray(res.x)[bistable.spec.sindex("sa")])
    if bool(res.success):
        assert engine.check_stability(bistable.spec, bistable.conditions(),
                                      np.asarray(res.x))
        assert abs(a - A_UNSTABLE) > 1e-3
    else:
        pytest.fail("verdict retry should find one of the stable roots")


def test_batched_stability_mask(bistable):
    from pycatkin_tpu.parallel.batch import stability_mask, stack_conditions
    conds = stack_conditions([bistable.conditions()] * 3)
    ys = np.stack([_full_y(bistable, A_UNSTABLE),
                   _full_y(bistable, A_STABLE),
                   _full_y(bistable, 0.0)])
    mask = stability_mask(bistable.spec, conds, ys)
    np.testing.assert_array_equal(mask, [False, True, True])


def test_sweep_retries_stability_demoted_lanes(bistable):
    """A sweep lane seeded ON the unstable root converges there with zero
    residual; the stability verdict demotes it, and the sweep's
    random-restart rescue must land it on a STABLE root with
    success=True (round-2 verdict: demoted lanes were abandoned; facade
    parity with api/system.py find_steady's 3-retry loop)."""
    from pycatkin_tpu.parallel.batch import (stack_conditions,
                                             sweep_steady_state)
    spec = bistable.spec
    dyn = np.asarray(spec.dynamic_indices)
    conds = stack_conditions([bistable.conditions()] * 3)
    x0 = np.stack([_full_y(bistable, A_UNSTABLE)[dyn],
                   _full_y(bistable, A_STABLE)[dyn],
                   _full_y(bistable, 0.0)[dyn]])
    out = sweep_steady_state(spec, conds, x0=x0, check_stability=True)
    assert bool(np.all(np.asarray(out["success"])))
    assert bool(np.all(np.asarray(out["stable"])))
    a = np.asarray(out["y"])[:, spec.sindex("sa")]
    # Lane 0 must have ESCAPED the unstable root onto a stable one.
    assert abs(a[0] - A_UNSTABLE) > 1e-3
    assert (abs(a[0] - A_STABLE) < 1e-6) or (abs(a[0]) < 1e-6)
    # Lanes seeded on stable roots stay there.
    assert abs(a[1] - A_STABLE) < 1e-6
    assert abs(a[2]) < 1e-6


# ---------------------------------------------------------------------
# Collision desorption model
def _kdes_reference(T, mass, area, sigma, inertia, des_en):
    """Independent host implementation of the reference formula
    (rate_constants.py:26-53), straight from the docstring math."""
    from pycatkin_tpu.constants import amuA2tokgm2, amutokg
    inertia = list(inertia)
    if len(inertia) == 3 and all(abs(k) > 0.001 for k in inertia):
        theta = [h ** 2 / (8 * np.pi ** 2 * (I * amuA2tokgm2) * kB)
                 for I in inertia]
        coeff = (kB ** 2 * T ** 3.5 * area * 2 * np.pi ** 1.5 *
                 (mass * amutokg)) / (h ** 3 * sigma * np.prod(theta))
    else:
        theta = h ** 2 / (8 * np.pi ** 2 *
                          (max(inertia) * amuA2tokgm2) * kB)
        coeff = (kB ** 2 * T ** 3 * area * 2 * np.pi *
                 (mass * amutokg)) / (h ** 3 * sigma * theta)
    return coeff * np.exp(-des_en / (R * T))


def test_kdes_kernel_matches_reference_formula():
    from pycatkin_tpu.ops import rates
    # Polyatomic: 3 nonzero moments, T^3.5 law.
    args = dict(T=600.0, mass=16.04, area=1.0e-19, sigma=12.0,
                des_en=9.0e4)
    poly = np.array([3.1, 3.1, 3.1])
    got = float(rates.k_desorption(args["T"], args["mass"], args["area"],
                                   args["sigma"], poly, 1.0,
                                   args["des_en"]))
    want = _kdes_reference(inertia=poly, **args)
    assert got == pytest.approx(want, rel=1e-10)
    # Linear: one zero moment, T^3 law on the largest moment.
    lin = np.array([0.0, 8.9, 8.9])
    got = float(rates.k_desorption(args["T"], args["mass"], args["area"],
                                   args["sigma"], lin, 0.0,
                                   args["des_en"]))
    want = _kdes_reference(inertia=lin, **args)
    assert got == pytest.approx(want, rel=1e-10)


def _toy_ads_system(desorption_model, reac_type="adsorption"):
    co = State(name="co", state_type="gas", mass=28.01, sigma=1.0,
               inertia=[0.0, 8.9, 8.9], Gelec=0.0)
    s = State(name="s", state_type="surface", Gelec=0.0)
    sco = State(name="sco", state_type="adsorbate", Gelec=-1.0)
    if reac_type == "adsorption":
        rx = Reaction(name="ads", reac_type="adsorption",
                      reactants=[co, s], products=[sco], area=1.0e-19)
    else:
        rx = Reaction(name="des", reac_type="desorption",
                      reactants=[sco], products=[co, s], area=1.0e-19)
    sim = System(start_state={"s": 1.0, "co": 1.0}, T=500.0, p=1.0e5,
                 desorption_model=desorption_model)
    for st in (co, s, sco):
        sim.add_state(st)
    sim.add_reaction(rx)
    sim.add_reactor(InfiniteDilutionReactor())
    return sim.build()


def test_collision_model_changes_reverse_rate():
    from pycatkin_tpu.ops import rates
    db = _toy_ads_system("detailed_balance")
    col = _toy_ads_system("collision")
    assert db.spec.desorption_model == "detailed_balance"
    assert col.spec.desorption_model == "collision"
    kf_db, kr_db, keq_db = db.rate_constant_table()
    kf_col, kr_col, _ = col.rate_constant_table()
    # Forward sticking rate identical under both conventions.
    np.testing.assert_allclose(kf_db, kf_col, rtol=1e-12)
    # Detailed balance: kr = kads / Keq.
    np.testing.assert_allclose(kr_db, kf_db / keq_db, rtol=1e-12)
    # Collision: kr = kdes with des_en = -dErxn (reference
    # reaction.py:141-147); dErxn here is -1 eV.
    re = col.reaction_energy_table()
    want = _kdes_reference(T=500.0, mass=28.01, area=1.0e-19, sigma=1.0,
                           inertia=[0.0, 8.9, 8.9],
                           des_en=-float(np.asarray(re.dErxn)[0]))
    assert float(kr_col[0]) == pytest.approx(want, rel=1e-8)
    assert not np.allclose(kr_db, kr_col)


def test_collision_model_desorption_type():
    from pycatkin_tpu.ops import rates
    db = _toy_ads_system("detailed_balance", reac_type="desorption")
    col = _toy_ads_system("collision", reac_type="desorption")
    kf_db, kr_db, keq = db.rate_constant_table()
    kf_col, kr_col, _ = col.rate_constant_table()
    # Reverse (adsorption) identical; forward differs by model.
    np.testing.assert_allclose(kr_db, kr_col, rtol=1e-12)
    np.testing.assert_allclose(kf_db, kr_db * keq, rtol=1e-12)
    re = col.reaction_energy_table()
    want = _kdes_reference(T=500.0, mass=28.01, area=1.0e-19, sigma=1.0,
                           inertia=[0.0, 8.9, 8.9],
                           des_en=float(np.asarray(re.dErxn)[0]))
    assert float(kf_col[0]) == pytest.approx(want, rel=1e-8)


def test_collision_model_end_to_end_solves():
    for model in ("detailed_balance", "collision"):
        sim = _toy_ads_system(model)
        res = sim.find_steady(use_transient_guess=False)
        assert bool(res.success), model
        th = float(np.asarray(res.x)[sim.spec.sindex("sco")])
        assert 0.0 <= th <= 1.0
    # The two conventions give different equilibrium coverages here.
    th_db = _toy_ads_system("detailed_balance").find_steady(
        use_transient_guess=False)
    th_col = _toy_ads_system("collision").find_steady(
        use_transient_guess=False)
    i = _toy_ads_system("collision").spec.sindex("sco")
    assert abs(float(np.asarray(th_db.x)[i]) -
               float(np.asarray(th_col.x)[i])) > 1e-6


def test_desorption_model_from_json(tmp_path):
    cfg = {
        "states": {
            "co": {"state_type": "gas", "mass": 28.01, "sigma": 1.0,
                   "inertia": [0.0, 8.9, 8.9], "Gelec": 0.0},
            "s": {"state_type": "surface", "Gelec": 0.0},
            "sco": {"state_type": "adsorbate", "Gelec": -1.0},
        },
        "system": {"T": 500.0, "p": 1.0e5,
                   "start_state": {"s": 1.0, "co": 1.0},
                   "desorption_model": "collision"},
        "reactions": {
            "ads": {"reac_type": "adsorption", "area": 1.0e-19,
                    "reactants": ["co", "s"], "products": ["sco"]},
        },
        "reactor": "InfiniteDilutionReactor",
    }
    path = tmp_path / "collision.json"
    path.write_text(json.dumps(cfg))
    sim = pk.read_from_input_file(str(path))
    assert sim.desorption_model == "collision"
    assert sim.spec.desorption_model == "collision"
    # And it survives the checkpoint round-trip.
    from pycatkin_tpu.utils import save_system_json
    ck = tmp_path / "ckpt.json"
    save_system_json(sim, str(ck))
    sim2 = pk.read_from_input_file(str(ck))
    assert sim2.desorption_model == "collision"


def test_desorption_model_validated():
    with pytest.raises(ValueError, match="desorption_model"):
        System(desorption_model="nonsense")


# ---------------------------------------------------------------------
# Per-temperature user-energy dicts
def test_user_energy_dict_interpolates():
    from pycatkin_tpu.frontend.reactions import _resolve_user_value
    table = {400.0: 1.0, 800: 2.0}
    assert _resolve_user_value(table, 400.0) == 1.0
    assert _resolve_user_value(table, 800.0) == 2.0
    assert _resolve_user_value(table, 600.0) == pytest.approx(1.5)
    assert _resolve_user_value(table, 500) == pytest.approx(1.25)
    with pytest.raises(ValueError, match="cannot extrapolate"):
        _resolve_user_value(table, 300.0)


def test_user_energy_dict_in_sweep():
    """A T-swept solve across a per-T dict no longer KeyErrors (the
    reference sharp edge, reaction.py:228-260)."""
    T = 500.0
    s = State(name="s", state_type="surface")
    sa = State(name="sa", state_type="adsorbate")
    rx = UserDefinedReaction(name="r1", reac_type="arrhenius",
                             reactants=[s], products=[sa],
                             dGrxn_user={400.0: -0.5, 800.0: -0.1},
                             dGa_fwd_user=0.5)
    sim = System(start_state={"s": 1.0}, T=T, p=1.0e5)
    sim.add_state(s)
    sim.add_state(sa)
    sim.add_reaction(rx)
    sim.add_reactor(InfiniteDilutionReactor())
    sim.build()
    for T in (400.0, 600.0, 800.0):
        sim.T = T
        kf, kr, keq = sim.rate_constant_table()
        assert np.all(np.isfinite(kf)) and np.all(np.isfinite(kr))
    # Interpolated dGrxn at 600 K: -0.3 eV.
    sim.T = 600.0
    _, _, keq = sim.rate_constant_table()
    assert float(keq[0]) == pytest.approx(
        np.exp(0.3 * eVtoJmol / (R * 600.0)), rel=1e-10)


# ---------------------------------------------------------------------
# Multi-surface leftover adsorbates warn instead of silently merging
def test_multi_surface_leftover_warns():
    a = State(name="a", state_type="surface")
    ax = State(name="ax", state_type="adsorbate")
    b = State(name="b", state_type="surface")
    zq = State(name="zq", state_type="adsorbate")
    r1 = UserDefinedReaction(name="r1", reac_type="arrhenius",
                             reactants=[ax], products=[a],
                             dGrxn_user=0.0, dGa_fwd_user=0.5)
    r2 = UserDefinedReaction(name="r2", reac_type="arrhenius",
                             reactants=[zq], products=[b],
                             dGrxn_user=0.0, dGa_fwd_user=0.5)
    sim = System(start_state={"a": 0.5, "b": 0.5}, T=500.0, p=1.0e5)
    for st in (a, ax, b, zq):
        sim.add_state(st)
    sim.add_reaction(r1)
    sim.add_reaction(r2)
    sim.add_reactor(InfiniteDilutionReactor())
    with pytest.warns(UserWarning, match="zq"):
        sim.build()
    # Exactly one surface ('b') matched nothing, so zq is assumed to be
    # its adsorbate -- but loudly, via the warning above.
    spec = sim.spec
    assert spec.groups.shape[0] == 2
    gb = next(g for g in spec.groups if g[spec.sindex("b")] == 1.0)
    assert gb[spec.sindex("zq")] == 1.0


# ---------------------------------------------------------------------
# solve_minimize analog: projected LM strategy + lexicographic scoreboard
def test_lm_attempt_converges_on_volcano(ref_root):
    """The projected-LM strategy (reference solve_minimize,
    solver.py:293-372) independently reaches the same steady state the
    PTC march finds, from a deliberately bad uniform start."""
    import jax.numpy as jnp

    import pycatkin_tpu as pk
    import tests.test_golden_volcano as gv
    from pycatkin_tpu import engine
    from pycatkin_tpu.solvers import newton
    from tests.conftest import reference_path

    sim = pk.read_from_input_file(
        reference_path("examples", "COOxVolcano", "input.json"))
    gv.set_descriptors(sim, -1.0, -1.0)
    spec, cond = sim.spec, sim.conditions()
    kf, kr, _ = engine.rate_constants(spec, cond)
    fscale, dyn, y_base = engine._dynamic_fscale(spec, cond, kf, kr)
    import jax
    jac = jax.jacfwd(lambda x: fscale(x)[0])
    groups_dyn = jnp.asarray(spec.groups)[:, jnp.asarray(dyn)]
    n = len(np.asarray(dyn))
    x0 = jnp.full((n,), 1.0 / n)

    opts = newton.SolverOptions()
    x_lm, f_lm, _, _ = newton._lm_attempt(fscale, jac, x0, groups_dyn,
                                          opts)
    assert float(f_lm) <= 1.0, "LM did not converge"

    res = engine.steady_state(spec, cond)
    x_ref = jnp.asarray(res.x)[jnp.asarray(dyn)]
    assert np.allclose(np.asarray(x_lm), np.asarray(x_ref), atol=1e-6)


def test_lexicographic_score_ordering():
    """A candidate passing more verdict tests outranks any residual
    advantage; ties break on residual (reference compare_scores)."""
    import jax.numpy as jnp

    from pycatkin_tpu.solvers import newton

    groups = jnp.asarray([[1.0, 1.0]])
    opts = newton.SolverOptions()
    good = jnp.asarray([0.4, 0.6])       # physical, sums to 1
    bad = jnp.asarray([-0.5, 0.2])       # negative + broken sum
    # bad has a (much) smaller residual but fails two tests:
    s_good = newton._score(good, 0.9, groups, opts)
    s_bad = newton._score(bad, 1e-6, groups, opts)
    assert float(s_good) > float(s_bad)
    # tie on tests -> smaller residual wins
    s1 = newton._score(good, 0.9, groups, opts)
    s2 = newton._score(good, 0.2, groups, opts)
    assert float(s2) > float(s1)


def test_chord_steps_same_root():
    """chord_steps adds cheap frozen-Jacobian extra steps (large-network
    iteration economics, docs/perf_config5.md §9: the large-n kernel
    re-uses each iteration's LU factorization; the small-n kernel keeps
    the chord-off gauss_solve for identical numerics); the solve must
    land on the same root as the plain path for both kernels."""
    import numpy as np

    from pycatkin_tpu import engine
    from pycatkin_tpu.models.synthetic import synthetic_system
    from pycatkin_tpu.solvers.newton import SolverOptions

    for n_sp, n_rx, seed in ((20, 40, 1), (60, 150, 3)):
        sim = synthetic_system(n_species=n_sp, n_reactions=n_rx,
                               seed=seed)
        spec, cond = sim.spec, sim.conditions()
        r0 = engine.steady_state(spec, cond)
        r2 = engine.steady_state(
            spec, cond, opts=SolverOptions(chord_steps=2))
        assert bool(r0.success) and bool(r2.success)
        # Both stop at the same residual tolerance; with the stiff
        # Jacobian's conditioning (~1e10+) that pins the POSITION only
        # to ~1e-4 -- the two paths' answers differ by solver precision,
        # not by basin (a different root on these networks sits orders
        # of magnitude away in multiple coordinates).
        d = float(np.max(np.abs(np.asarray(r0.x) - np.asarray(r2.x))))
        assert d < 5e-3, f"chord root drifted: {d:.2e} (n={n_sp})"
        # chords should not lengthen the outer trajectory materially.
        # Not a hard invariant -- the chord path's dt trajectory
        # diverges from the plain one at iteration 1 and the exact
        # iteration counts shift with JAX/XLA versions and hardware
        # rounding -- so bound multiplicatively with generous slack
        # rather than pinning the trajectory.
        assert int(r2.iterations) <= 2 * int(r0.iterations)


def test_lyapunov_certificate_sound_on_adversarial_matrices():
    """The deflated-Lyapunov stability certificate must NEVER certify a
    matrix whose max Re(eig) exceeds the tolerance -- including
    marginal bands within +-1e-10 relative of the threshold -- and
    should certify a decent fraction of genuinely stable ones (it is
    one-way: abstaining is always allowed, lying is not)."""
    import jax.numpy as jnp

    from pycatkin_tpu.solvers.newton import lyapunov_certified_stable

    rng = np.random.default_rng(11)
    n_unstable = n_unsound = n_stable = n_certified = 0
    for trial in range(800):
        m = int(rng.integers(2, 6))
        A = rng.normal(size=(m, m)) * 10.0 ** rng.integers(-3, 12)
        emax = np.real(np.linalg.eigvals(A)).max()
        tol = 1e-2 + 64 * np.finfo(float).eps * np.abs(A).max()
        kind = trial % 4
        if kind == 1:    # marginally unstable
            A = A + np.eye(m) * (tol * (1 + 10.0 ** rng.uniform(-10, 0))
                                 - emax)
        elif kind == 2:  # marginally stable
            A = A + np.eye(m) * (tol * (1 - 10.0 ** rng.uniform(-10, 0))
                                 - emax)
        emax = np.real(np.linalg.eigvals(A)).max()
        cert = bool(lyapunov_certified_stable(jnp.asarray(A),
                                              np.eye(m), tol))
        if emax > tol:
            n_unstable += 1
            n_unsound += cert
        else:
            n_stable += 1
            n_certified += cert
    assert n_unsound == 0, f"{n_unsound}/{n_unstable} unsound"
    assert n_certified > 0.5 * n_stable     # it must actually certify


def test_lyapunov_certificate_on_volcano_lanes(ref_root):
    """On real COOx volcano Jacobians the certificate must agree
    one-way with the host eigensolve (certified -> stable) and clear
    the majority of lanes (the whole point of the tier: Gershgorin
    clears ~0)."""
    import jax
    import jax.numpy as jnp

    import pycatkin_tpu as pk
    from pycatkin_tpu.models import coox
    from pycatkin_tpu.parallel import batch
    from pycatkin_tpu.solvers.newton import (SolverOptions,
                                             deflation_basis_for_spec,
                                             lyapunov_certified_stable,
                                             stability_tolerance_from_scale)
    from tests.conftest import reference_path

    sim = pk.read_from_input_file(
        reference_path("examples", "COOxVolcano", "input.json"))
    spec = sim.spec
    be = np.linspace(-2.5, 0.5, 8)
    conds, _ = coox.volcano_grid_conditions(sim, be)
    res = batch.batch_steady_state(
        spec, conds, opts=batch._fast_pass_opts(SolverOptions()))
    Js = np.asarray(batch._jacobian_program(spec)(conds,
                                                  jnp.asarray(res.x)))
    # The SAME Q recipe the production screen uses (shared helper).
    Q = deflation_basis_for_spec(spec)
    # Deflation exactness: eig(J) = eig(Q^T J Q) + {0 per group}.
    B = Q.T @ Js[10] @ Q
    eJ = np.sort(np.linalg.eigvals(Js[10]).real)
    eB = np.sort(np.concatenate([np.linalg.eigvals(B).real, [0.0]]))
    np.testing.assert_allclose(eJ, eB, rtol=1e-6,
                               atol=1e-6 * np.abs(Js[10]).max())

    tol = np.asarray(stability_tolerance_from_scale(
        np.abs(Js).max(axis=(1, 2))))
    cert = np.asarray(jax.vmap(
        lambda J, t: lyapunov_certified_stable(J, Q, t))(
            jnp.asarray(Js), jnp.asarray(tol)))
    stable = np.linalg.eigvals(Js).real.max(axis=1) <= tol
    assert not np.any(cert & ~stable), "certified an unstable lane"
    # With the Higham-margin residual bound the certificate clears
    # ~99 % of volcano lanes (measured 1018/1024 on the 32x32 grid).
    assert cert.sum() >= 0.9 * len(Js)


def test_lyapunov_certificate_rejects_bistable_unstable_root(bistable):
    """The middle (unstable) root of the bistable mechanism must NOT be
    certified stable by the Lyapunov tier."""
    import jax.numpy as jnp

    from pycatkin_tpu import engine
    from pycatkin_tpu.solvers.newton import (deflation_basis_for_spec,
                                             lyapunov_certified_stable,
                                             stability_tolerance_from_scale)

    spec, cond = bistable.spec, bistable.conditions()
    for a, expect_stable in ((A_UNSTABLE, False), (A_STABLE, True)):
        y = _full_y(bistable, a)
        J = np.asarray(engine.steady_jacobian(
            spec, cond, jnp.asarray(y)[jnp.asarray(
                spec.dynamic_indices)]))
        Q = deflation_basis_for_spec(spec)
        tol = float(stability_tolerance_from_scale(np.abs(J).max()))
        cert = bool(lyapunov_certified_stable(jnp.asarray(J), Q, tol))
        if not expect_stable:
            assert not cert        # soundness: never certify unstable
