"""Transient integrator parity: TR-BDF2 vs scipy BDF trajectories.

BASELINE.json config 2 asks for scipy-vs-device integrator parity; the
golden regressions only pin endpoints. These tests compare FULL
trajectories on the two reference reactor models (DMTM infinite-dilution,
COOxReactor CSTR) over a tolerance sweep, using the same numpy RHS for
scipy that the device path compiles (same rate constants, same reactor
row transforms -- reference old_system.py:315-383 semantics).
"""

import numpy as np
import pytest
from scipy.integrate import solve_ivp

import pycatkin_tpu as pk
from pycatkin_tpu import engine
from pycatkin_tpu.constants import bartoPa
from pycatkin_tpu.solvers.ode import ODEOptions
from tests.conftest import reference_path


def _numpy_rhs(spec, cond):
    """Reference-equivalent numpy RHS (the scipy side of the parity)."""
    kf, kr, _ = engine.rate_constants(spec, cond)
    kf, kr = np.asarray(kf), np.asarray(kr)
    is_gas = spec.is_gas.astype(bool)
    is_ads = spec.is_adsorbate
    terms = engine._reactor_terms(spec, cond)
    rtype = int(terms["reactor_type"])
    row_scale = np.where(is_ads > 0, 1.0, float(terms["sigma_over_bar"]))
    inv_tau = float(terms["inv_tau"])
    inflow = np.asarray(terms["inflow"], dtype=float)

    def rhs(t, y):
        y_ext = np.concatenate([np.where(is_gas, y * bartoPa, y), [1.0]])
        fwd = kf * np.prod(y_ext[spec.reac_idx], axis=-1)
        rev = kr * np.prod(y_ext[spec.prod_idx], axis=-1)
        dy = spec.stoich @ (fwd - rev)
        if rtype == 0:
            return dy * is_ads
        return dy * row_scale + np.where(is_gas, (inflow - y) * inv_tau,
                                         0.0)

    return rhs


def _trajectories(sim, T, t_end, n_save, rtol, atol):
    sim.params["temperature"] = T
    spec, cond = sim.spec, sim.conditions()
    save_ts = np.concatenate([[0.0],
                              np.logspace(-10, np.log10(t_end), n_save)])
    ys, ok = engine.transient(spec, cond, save_ts,
                              ODEOptions(rtol=rtol, atol=atol))
    assert bool(ok), "TR-BDF2 did not complete"
    sol = solve_ivp(_numpy_rhs(spec, cond), (0.0, t_end),
                    np.asarray(cond.y0, dtype=float), method="BDF",
                    t_eval=save_ts, rtol=rtol, atol=atol)
    assert sol.success
    return np.asarray(ys), sol.y.T


@pytest.mark.parametrize("rtol,atol,tol", [
    (1.0e-6, 1.0e-9, 2.0e-4),
    (1.0e-8, 1.0e-10, 2.0e-5),
])
def test_dmtm_trajectory_parity(ref_root, rtol, atol, tol):
    """DMTM at 600 K: every species at every save point agrees between
    the two integrators within the tolerance-limited envelope. The
    comparison tightens as the tolerances tighten (both must converge to
    the same trajectory, not merely the same endpoint)."""
    sim = pk.read_from_input_file(
        reference_path("examples", "DMTM", "input.json"))
    ys, ys_ref = _trajectories(sim, 600.0, 1.0e8, 25, rtol, atol)
    dmax = float(np.max(np.abs(ys - ys_ref)))
    assert dmax < tol, f"trajectory deviation {dmax:.2e} at rtol={rtol}"


@pytest.mark.parametrize("rtol,atol,tol", [
    (1.0e-6, 1.0e-9, 1.0e-3),
    (1.0e-8, 1.0e-10, 1.0e-4),
])
def test_cstr_trajectory_parity(ref_root, rtol, atol, tol):
    """COOxReactor Pd111 CSTR at 523 K: coverages AND outlet pressures
    (flow terms, sigma scaling) track scipy BDF through the transient."""
    sim = pk.read_from_input_file(
        reference_path("examples", "COOxReactor", "input_Pd111.json"))
    ys, ys_ref = _trajectories(sim, 523.0, 3600.0, 25, rtol, atol)
    dmax = float(np.max(np.abs(ys - ys_ref)))
    assert dmax < tol, f"trajectory deviation {dmax:.2e} at rtol={rtol}"


def test_cstr_conversion_endpoint_parity(ref_root):
    """The headline CSTR observable (CO conversion) agrees to 1e-3 %
    between integrators at the golden condition."""
    sim = pk.read_from_input_file(
        reference_path("examples", "COOxReactor", "input_Pd111.json"))
    ys, ys_ref = _trajectories(sim, 523.0, 3600.0, 25, 1.0e-10, 1.0e-12)
    iCO = sim.snames.index("CO")
    pin = sim.params["inflow_state"]["CO"]
    x_dev = 100.0 * (1.0 - ys[-1][iCO] / pin)
    x_ref = 100.0 * (1.0 - ys_ref[-1][iCO] / pin)
    assert x_dev == pytest.approx(x_ref, abs=1e-3)
    assert x_dev == pytest.approx(51.143, abs=0.05)
