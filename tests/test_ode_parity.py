"""Transient integrator parity: TR-BDF2 / ESDIRK4 vs scipy BDF.

BASELINE.json config 2 asks for scipy-vs-device integrator parity; the
golden regressions only pin endpoints. These tests compare FULL
trajectories on the two reference reactor models (DMTM infinite-dilution,
COOxReactor CSTR) over a tolerance sweep, using the same numpy RHS for
scipy that the device path compiles (same rate constants, same reactor
row transforms -- reference old_system.py:315-383 semantics), for BOTH
on-device integrator families (the reference likewise ships two scipy
families, old_system.py:350-376); plus a fixed-step convergence-order
pin for the ESDIRK4 tableau.
"""

import numpy as np
import pytest
from scipy.integrate import solve_ivp

import pycatkin_tpu as pk
from pycatkin_tpu import engine
from pycatkin_tpu.constants import bartoPa
from pycatkin_tpu.solvers.ode import ODEOptions
from tests.conftest import reference_path


def _numpy_rhs(spec, cond):
    """Reference-equivalent numpy RHS (the scipy side of the parity)."""
    kf, kr, _ = engine.rate_constants(spec, cond)
    kf, kr = np.asarray(kf), np.asarray(kr)
    is_gas = spec.is_gas.astype(bool)
    is_ads = spec.is_adsorbate
    terms = engine._reactor_terms(spec, cond)
    rtype = int(terms["reactor_type"])
    row_scale = np.where(is_ads > 0, 1.0, float(terms["sigma_over_bar"]))
    inv_tau = float(terms["inv_tau"])
    inflow = np.asarray(terms["inflow"], dtype=float)

    def rhs(t, y):
        y_ext = np.concatenate([np.where(is_gas, y * bartoPa, y), [1.0]])
        fwd = kf * np.prod(y_ext[spec.reac_idx], axis=-1)
        rev = kr * np.prod(y_ext[spec.prod_idx], axis=-1)
        dy = spec.stoich @ (fwd - rev)
        if rtype == 0:
            return dy * is_ads
        return dy * row_scale + np.where(is_gas, (inflow - y) * inv_tau,
                                         0.0)

    return rhs


def _trajectories(sim, T, t_end, n_save, rtol, atol, method="trbdf2"):
    sim.params["temperature"] = T
    spec, cond = sim.spec, sim.conditions()
    save_ts = np.concatenate([[0.0],
                              np.logspace(-10, np.log10(t_end), n_save)])
    ys, ok = engine.transient(spec, cond, save_ts,
                              ODEOptions(rtol=rtol, atol=atol,
                                         method=method))
    assert bool(ok), f"{method} did not complete"
    sol = solve_ivp(_numpy_rhs(spec, cond), (0.0, t_end),
                    np.asarray(cond.y0, dtype=float), method="BDF",
                    t_eval=save_ts, rtol=rtol, atol=atol)
    assert sol.success
    return np.asarray(ys), sol.y.T


@pytest.mark.parametrize("rtol,atol,tol", [
    (1.0e-6, 1.0e-9, 2.0e-4),
    (1.0e-8, 1.0e-10, 2.0e-5),
])
def test_dmtm_trajectory_parity(ref_root, rtol, atol, tol):
    """DMTM at 600 K: every species at every save point agrees between
    the two integrators within the tolerance-limited envelope. The
    comparison tightens as the tolerances tighten (both must converge to
    the same trajectory, not merely the same endpoint)."""
    sim = pk.read_from_input_file(
        reference_path("examples", "DMTM", "input.json"))
    ys, ys_ref = _trajectories(sim, 600.0, 1.0e8, 25, rtol, atol)
    dmax = float(np.max(np.abs(ys - ys_ref)))
    assert dmax < tol, f"trajectory deviation {dmax:.2e} at rtol={rtol}"


@pytest.mark.parametrize("rtol,atol,tol", [
    (1.0e-6, 1.0e-9, 1.0e-3),
    (1.0e-8, 1.0e-10, 1.0e-4),
])
def test_cstr_trajectory_parity(ref_root, rtol, atol, tol):
    """COOxReactor Pd111 CSTR at 523 K: coverages AND outlet pressures
    (flow terms, sigma scaling) track scipy BDF through the transient."""
    sim = pk.read_from_input_file(
        reference_path("examples", "COOxReactor", "input_Pd111.json"))
    ys, ys_ref = _trajectories(sim, 523.0, 3600.0, 25, rtol, atol)
    dmax = float(np.max(np.abs(ys - ys_ref)))
    assert dmax < tol, f"trajectory deviation {dmax:.2e} at rtol={rtol}"


@pytest.mark.parametrize("rtol,atol,tol", [
    (1.0e-8, 1.0e-10, 1.0e-4),
])
def test_cstr_trajectory_parity_esdirk4(ref_root, rtol, atol, tol):
    """The 4th-order family tracks scipy BDF through the CSTR transient
    exactly like the default family does -- the independent cross-check
    integrator the reference gets from its second scipy family."""
    sim = pk.read_from_input_file(
        reference_path("examples", "COOxReactor", "input_Pd111.json"))
    ys, ys_ref = _trajectories(sim, 523.0, 3600.0, 25, rtol, atol,
                               method="esdirk4")
    dmax = float(np.max(np.abs(ys - ys_ref)))
    assert dmax < tol, f"trajectory deviation {dmax:.2e} at rtol={rtol}"


def test_esdirk4_convergence_order():
    """Fixed-step convergence on y0' = -2*y0 + y1^2, y1' = -y1 (exact
    solution y = [(1+t)e^(-2t), e^(-t)]): halving h must cut the error
    ~16x (4th order). Pins the tableau digits -- a single wrong
    coefficient degrades the observed order immediately."""
    import jax
    import jax.numpy as jnp

    from pycatkin_tpu.solvers import ode as O

    f = lambda y: jnp.array([-2.0 * y[0] + y[1] ** 2, -y[1]])  # noqa: E731
    jac = jax.jacfwd(f)
    # Tight scale: stage-solve accuracy must sit far below the
    # truncation errors being measured (steps are driven manually, so
    # the rejection path never runs).
    opts = ODEOptions(rtol=1e-12, atol=1e-14)
    errs = []
    for h in (0.1, 0.05, 0.025):
        y, t = jnp.array([1.0, 1.0]), 0.0
        while t < 1.0 - 1e-12:
            hh = min(h, 1.0 - t)
            y, _, ok = O._esdirk4_step(f, jac, y, t, hh, opts)
            assert bool(ok)
            t += hh
        exact = np.array([2.0 * np.exp(-2.0), np.exp(-1.0)])
        errs.append(float(np.max(np.abs(np.asarray(y) - exact))))
    for e_coarse, e_fine in zip(errs, errs[1:]):
        order = np.log2(e_coarse / e_fine)
        assert order > 3.5, f"observed order {order:.2f} (errors {errs})"


@pytest.mark.slow
def test_cstr_conversion_endpoint_parity(ref_root):
    """The headline CSTR observable (CO conversion) agrees to 1e-3 %
    between integrators at the golden condition."""
    sim = pk.read_from_input_file(
        reference_path("examples", "COOxReactor", "input_Pd111.json"))
    ys, ys_ref = _trajectories(sim, 523.0, 3600.0, 25, 1.0e-10, 1.0e-12)
    iCO = sim.snames.index("CO")
    pin = sim.params["inflow_state"]["CO"]
    x_dev = 100.0 * (1.0 - ys[-1][iCO] / pin)
    x_ref = 100.0 * (1.0 - ys_ref[-1][iCO] / pin)
    assert x_dev == pytest.approx(x_ref, abs=1e-3)
    assert x_dev == pytest.approx(51.143, abs=0.05)
