"""Per-lane solver telemetry: the packed [lanes, 5] diagnostics rows.

The fused sweep computes iterations / chords / residual decade /
rescue-strategy / accepted-tier per lane INSIDE the device program, so
lane-resolution
telemetry rides the existing single "fused tail bundle" sync (the sync
budget is pinned by tests/test_sync_budget.py). These tests pin the
content contracts: the packed columns agree with the result arrays the
sweep already returns, the device pack and the host-side failure-path
twin encode residual decades identically, rescue codes land only on
rescued lanes (quarantine stamped last), the fused and legacy
(``PYCATKIN_FUSED_SWEEP=0``) paths produce bit-identical telemetry,
and the JAX-free renderer tables in obs/export.py can never drift from
the solver's code registry.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pycatkin_tpu import engine, precision
from pycatkin_tpu.models.synthetic import synthetic_system
from pycatkin_tpu.obs import export, metrics
from pycatkin_tpu.parallel import batch
from pycatkin_tpu.parallel.batch import (broadcast_conditions,
                                         sweep_steady_state)
from pycatkin_tpu.solvers import newton
from pycatkin_tpu.solvers.newton import SolverOptions


@pytest.fixture(scope="module")
def problem():
    sim = synthetic_system(n_species=24, n_reactions=32)
    spec = sim.spec
    n = 32
    conds = broadcast_conditions(sim.conditions(), n)
    conds = conds._replace(T=np.linspace(420.0, 780.0, n))
    mask = engine.tof_mask_for(spec, [spec.rnames[-1]])
    return spec, conds, mask


def test_export_strategy_table_matches_solver_registry():
    """obs/export.py must stay importable without JAX, so it carries
    its own copy of the strategy table -- this is the drift guard its
    comment promises."""
    assert len(export.STRATEGY_NAMES) == len(newton.STRATEGY_CODES)
    for code, name in enumerate(export.STRATEGY_NAMES):
        assert newton.STRATEGY_CODES[name] == code, name
    assert len(export._STRATEGY_GLYPHS) == len(export.STRATEGY_NAMES)
    assert newton.LANE_TELEMETRY_FIELDS == (
        "iterations", "chords", "residual_decade", "strategy", "tier")


def test_residual_decade_encoding():
    dec = np.asarray(newton.residual_decade(jnp.asarray(
        [1e-12, 5e-3, 0.0, np.nan, np.inf, 1e-120, 1e120])))
    # floor(log10) per lane; -99 = exact zero, +99 = non-finite, both
    # clips land inside the +-99 band.
    np.testing.assert_array_equal(dec, [-12, -3, -99, 99, 99, -99, 99])
    assert dec.dtype == np.int32


def test_clean_sweep_telemetry_matches_result_arrays(problem):
    spec, conds, mask = problem
    metrics.reset()
    out = sweep_steady_state(spec, conds, tof_mask=mask)
    assert bool(np.all(np.asarray(out["success"]))), \
        "corpus must converge cleanly for this test to mean anything"
    n = np.asarray(conds.T).shape[0]
    tel = np.asarray(out["lane_telemetry"])
    assert tel.shape == (n, 5) and tel.dtype == np.int32
    np.testing.assert_array_equal(
        tel[:, 0], np.asarray(out["iterations"]).astype(np.int32))
    want_ch = (np.asarray(out["chords"]).astype(np.int32)
               if "chords" in out else np.zeros(n, np.int32))
    np.testing.assert_array_equal(tel[:, 1], want_ch)
    np.testing.assert_array_equal(
        tel[:, 2],
        np.asarray(newton.residual_decade(jnp.asarray(out["residual"]))))
    np.testing.assert_array_equal(tel[:, 3], 0)   # nothing was rescued
    # Every first-pass acceptance carries the AMBIENT tier's code (the
    # CI precision-tier lane runs this file under f32-polish).
    np.testing.assert_array_equal(
        tel[:, 4], precision.TIER_CODES[precision.active_tier()])

    # The pack fed the per-lane histograms, labeled by ABI bucket.
    hists = metrics.snapshot()["histograms"]
    for name in ("pycatkin_lane_iterations", "pycatkin_lane_chords",
                 "pycatkin_lane_residual_decade"):
        assert name in hists, name
        assert sum(s["count"] for s in hists[name].values()) >= n

    # And the JSON/heatmap renderers accept the pack as-is.
    s = export.lane_summary(tel)
    assert s["lanes"] == n
    assert sum(s["strategies"].values()) == n
    assert s["strategies"] == {"clean": n}
    assert s["iterations"]["total"] == int(tel[:, 0].sum())
    heat = export.format_lane_heatmap(tel, width=16)
    assert "lane strategy heatmap" in heat and "." in heat


def test_fused_and_legacy_telemetry_bit_identical(problem, monkeypatch):
    spec, conds, mask = problem
    monkeypatch.delenv("PYCATKIN_FUSED_SWEEP", raising=False)
    fused = sweep_steady_state(spec, conds, tof_mask=mask)
    monkeypatch.setenv("PYCATKIN_FUSED_SWEEP", "0")
    legacy = sweep_steady_state(spec, conds, tof_mask=mask)
    a = np.asarray(fused["lane_telemetry"])
    b = np.asarray(legacy["lane_telemetry"])
    assert a.dtype == b.dtype and a.shape == b.shape
    assert a.tobytes() == b.tobytes(), \
        "fused/legacy sweeps disagree on the packed lane telemetry"


def test_rescue_path_stamps_strategy_codes(problem, monkeypatch):
    """Crippled pacing fails real lanes in the fast pass; the rescue
    merge must stamp ladder codes on exactly the rescued lanes while
    fast-pass survivors keep code 0 and quarantined lanes read 6.
    Pinned to the f64 tier: under f32-polish the crippled corpus
    converges first pass (tests/test_precision_tiers.py measures
    that), so the drill's premise needs the plain f64 fast pass."""
    monkeypatch.setenv(precision.TIER_ENV, "f64")
    spec, conds, mask = problem
    opts = SolverOptions(max_steps=6, max_attempts=2)
    n = np.asarray(conds.T).shape[0]
    keys = jax.random.split(jax.random.PRNGKey(0), n)
    fast = batch._steady_program(spec, batch._fast_pass_opts(opts))(
        conds, keys, None)
    fast_ok = np.asarray(fast.success)
    assert np.any(~fast_ok), \
        "corpus produced no failed lanes -- rescue path not exercised"

    out = sweep_steady_state(spec, conds, tof_mask=mask, opts=opts)
    tel = np.asarray(out["lane_telemetry"])
    strat = tel[:, 3]
    quar = np.asarray(out["quarantined"]).astype(bool)

    assert set(np.unique(strat)) <= set(newton.STRATEGY_CODES.values())
    np.testing.assert_array_equal(
        strat[fast_ok & ~quar], newton.STRATEGY_CODES["clean"])
    rescued = ~fast_ok & np.asarray(out["success"]) & ~quar
    assert np.any(strat >= 1), "no lane carries a rescue code"
    assert np.all(strat[rescued] >= 1), \
        "a rescued lane still reads clean"
    np.testing.assert_array_equal(
        strat[quar], newton.STRATEGY_CODES["quarantine"])
    # Every rescue product is an f64 iterate (tier code 0); only the
    # fast-pass survivors carry the ambient tier's code.
    np.testing.assert_array_equal(
        tel[:, 4],
        np.where((strat == 0) & ~quar,
                 precision.TIER_CODES[precision.active_tier()], 0))

    # The failure-path (host-twin) columns still agree with the merged
    # result arrays -- same contract as the clean device pack.
    np.testing.assert_array_equal(
        tel[:, 0], np.asarray(out["iterations"]).astype(np.int32))
    if "chords" in out:
        np.testing.assert_array_equal(
            tel[:, 1], np.asarray(out["chords"]).astype(np.int32))
    np.testing.assert_array_equal(
        tel[:, 2],
        np.asarray(newton.residual_decade(jnp.asarray(out["residual"]))))

    s = export.lane_summary(tel)
    assert s["lanes"] == n
    assert any(name != "clean" for name in s["strategies"])


def test_lane_rows_reject_malformed_telemetry():
    with pytest.raises(ValueError, match="expected 5"):
        export.lane_summary([[1, 2, 3]])
    assert export.lane_summary([]) == {"lanes": 0}
    # Out-of-table codes render as '?' / 'codeN' instead of crashing.
    tel = [[3, 0, -8, 42, 7]]
    s = export.lane_summary(tel)
    assert s["strategies"] == {"code42": 1}
    assert s["tiers"] == {"code7": 1}
    assert "?" in export.format_lane_heatmap(tel)
