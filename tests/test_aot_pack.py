"""Shippable AOT cache packs (parallel/compile_pool + tools/aot_pack).

The pack is how a fleet worker (or a post-wipe checkout) skips the
compile wall: export archives a warm cache directory, import rebuilds
one elsewhere, and the rebuilt entries must load as bit-identical
executables. Verification is NOT optional courtesy: a tampered or torn
pack must refuse to import (executing a mismatched entry would run the
wrong program), foreign-toolchain entries are counted but kept
(AOTCache.load treats them as silent misses), and hostile member names
can never escape the cache root. The full prewarm -> export -> import
-> sweep bit-identity promise is exercised end-to-end by
``python tools/aot_pack.py selftest`` (the CI round-trip gate) and the
cache-level equivalent in tests/test_compile_pool.py; these tests pin
the pack FORMAT contracts cheaply with small hand-built entries.
"""

import json
import os
import pickle
import tarfile

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pycatkin_tpu.parallel import compile_pool


def _make_cache(root, n_entries=2, fingerprint="fp0"):
    """A real cache directory holding ``n_entries`` serialized
    executables; returns (cache, {key: (args, expected_output)})."""
    cache = compile_pool.AOTCache(root=str(root), fingerprint=fingerprint)
    entries = {}
    for i in range(n_entries):
        @jax.jit
        def f(x, _i=i):
            return jnp.sin(x) * (_i + 1) + jnp.sum(x)

        x = jnp.asarray(np.random.default_rng(i).normal(size=(6, 4)))
        compiled = f.lower(x).compile()
        key = compile_pool.program_key(f"pack-test:{i}", (x,))
        assert cache.save(key, compiled)
        entries[key] = (x, np.asarray(compiled(x)))
    return cache, entries


def test_pack_round_trip_loads_bit_identical(tmp_path):
    root_a = tmp_path / "a"
    root_b = tmp_path / "b"
    pack = str(tmp_path / "cache.aotpack.tgz")
    _, entries = _make_cache(root_a, n_entries=3)

    exported = compile_pool.export_cache_pack(pack, cache_root=str(root_a))
    assert exported["entries"] == 3 and exported["skipped"] == 0
    assert os.path.exists(pack)

    imported = compile_pool.import_cache_pack(pack, cache_root=str(root_b))
    assert imported["imported"] == 3
    assert imported["foreign_toolchain"] == 0

    fresh = compile_pool.AOTCache(root=str(root_b), fingerprint="fp0")
    for key, (x, want) in entries.items():
        exe = fresh.load(key)
        assert exe is not None, key
        np.testing.assert_array_equal(np.asarray(exe(x)), want)
    assert fresh.hits == 3


def test_pack_cli_export_import(tmp_path, capsys):
    """The tools/aot_pack.py CLI drives the same library entry points."""
    from tools.aot_pack import main

    root_a = tmp_path / "a"
    root_b = tmp_path / "b"
    pack = str(tmp_path / "cli.aotpack.tgz")
    _make_cache(root_a, n_entries=2)

    assert main(["export", pack, "--cache-root", str(root_a)]) == 0
    stats = json.loads(capsys.readouterr().out)
    assert stats["entries"] == 2

    assert main(["import", pack, "--cache-root", str(root_b)]) == 0
    stats = json.loads(capsys.readouterr().out)
    assert stats["imported"] == 2
    assert sorted(os.listdir(root_b)) == sorted(os.listdir(root_a))


def test_export_refuses_missing_or_empty_cache(tmp_path):
    with pytest.raises(FileNotFoundError):
        compile_pool.export_cache_pack(
            str(tmp_path / "p.tgz"), cache_root=str(tmp_path / "absent"))
    empty = tmp_path / "empty"
    empty.mkdir()
    with pytest.raises(FileNotFoundError):
        compile_pool.export_cache_pack(
            str(tmp_path / "p.tgz"), cache_root=str(empty))


def _repack_with_manifest(pack_in, pack_out, mutate):
    """Copy a pack, passing its parsed manifest through ``mutate``."""
    with tarfile.open(pack_in, "r:gz") as tar:
        members = {m.name: tar.extractfile(m).read()
                   for m in tar.getmembers() if m.isfile()}
    manifest = json.loads(members.pop(compile_pool.PACK_MANIFEST))
    mutate(manifest)
    members[compile_pool.PACK_MANIFEST] = json.dumps(manifest).encode()
    import io
    with tarfile.open(pack_out, "w:gz") as tar:
        for name, blob in members.items():
            info = tarfile.TarInfo(name)
            info.size = len(blob)
            tar.addfile(info, io.BytesIO(blob))


def test_import_rejects_tampered_fingerprint(tmp_path):
    root_a = tmp_path / "a"
    pack = str(tmp_path / "ok.tgz")
    bad = str(tmp_path / "tampered.tgz")
    _make_cache(root_a, n_entries=1)
    compile_pool.export_cache_pack(pack, cache_root=str(root_a))

    def flip_fingerprint(manifest):
        for meta in manifest["entries"].values():
            meta["fingerprint"] = "not-the-recorded-mechanism"

    _repack_with_manifest(pack, bad, flip_fingerprint)
    with pytest.raises(ValueError, match="fingerprint"):
        compile_pool.import_cache_pack(bad,
                                       cache_root=str(tmp_path / "b"))
    # --no-verify territory: without verification the bytes do land.
    out = compile_pool.import_cache_pack(
        bad, cache_root=str(tmp_path / "c"), verify=False)
    assert out["imported"] == 1


def test_import_rejects_wrong_key_version(tmp_path):
    root_a = tmp_path / "a"
    pack = str(tmp_path / "ok.tgz")
    bad = str(tmp_path / "oldkeys.tgz")
    _make_cache(root_a, n_entries=1)
    compile_pool.export_cache_pack(pack, cache_root=str(root_a))

    def age_keys(manifest):
        manifest["key_version"] = "aot-key-v1"

    _repack_with_manifest(pack, bad, age_keys)
    with pytest.raises(ValueError, match="key format"):
        compile_pool.import_cache_pack(bad,
                                       cache_root=str(tmp_path / "b"))


def test_import_refuses_traversal_member_names(tmp_path):
    """A hostile manifest naming entries with path components must be
    refused outright -- nothing may be written outside cache_root."""
    evil = str(tmp_path / "evil.tgz")
    blob = pickle.dumps({"fingerprint": "fp", "payload": b""})
    manifest = {"format": "pycatkin-aot-pack-v1",
                "key_version": compile_pool._KEY_VERSION,
                "entries": {"../escape": {"fingerprint": "fp",
                                          "size": len(blob)}}}
    import io
    with tarfile.open(evil, "w:gz") as tar:
        for name, payload in (("../escape.aot", blob),
                              (compile_pool.PACK_MANIFEST,
                               json.dumps(manifest).encode())):
            info = tarfile.TarInfo(name)
            info.size = len(payload)
            tar.addfile(info, io.BytesIO(payload))
    with pytest.raises((ValueError, KeyError)):
        compile_pool.import_cache_pack(evil,
                                       cache_root=str(tmp_path / "b"))
    assert not (tmp_path / "escape.aot").exists()


def test_import_counts_foreign_toolchain_but_keeps_entry(tmp_path):
    """An entry serialized by another jax build imports (the pack may
    serve several platforms) but is counted so operators can see it;
    AOTCache.load later treats it as a silent miss."""
    root_a = tmp_path / "a"
    root_a.mkdir()
    entry = {"fingerprint": "fp", "jax": "0.0.0-not-this-version",
             "backend": "cpu", "device_kind": "cpu", "sharding": "",
             "devices": 1, "payload": b"x" * 16,
             "in_tree": None, "out_tree": None}
    with open(root_a / "feedf00d.aot", "wb") as fh:
        pickle.dump(entry, fh)
    pack = str(tmp_path / "foreign.tgz")
    compile_pool.export_cache_pack(pack, cache_root=str(root_a))
    out = compile_pool.import_cache_pack(pack,
                                         cache_root=str(tmp_path / "b"))
    assert out["imported"] == 1
    assert out["foreign_toolchain"] == 1
    assert (tmp_path / "b" / "feedf00d.aot").exists()
