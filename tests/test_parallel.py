"""Batched + sharded execution tests: the mini volcano grid.

Validates that the vmapped/mesh-sharded steady solves reproduce the serial
facade result, on the 8 virtual CPU devices provisioned in conftest --
the same code path the driver dry-runs for multi-chip validation.
"""

import jax
import numpy as np
import pytest

import pycatkin_tpu as pk
from pycatkin_tpu import engine
from pycatkin_tpu.parallel import (batch_steady_state, make_mesh,
                                   stack_conditions, sweep_steady_state)
from tests.conftest import reference_path
from tests.test_golden_volcano import SCOg, SO2g, set_descriptors


def _volcano_conditions(sim, grid):
    """Build one Conditions per (ECO, EO) grid point via the facade."""
    conds = []
    for ECO, EO in grid:
        set_descriptors(sim, ECO, EO)
        conds.append(sim.conditions())
    return stack_conditions(conds)


@pytest.fixture(scope="module")
def volcano(ref_root):
    return pk.read_from_input_file(
        reference_path("examples", "COOxVolcano", "input.json"))


@pytest.mark.slow
def test_batched_matches_serial(volcano):
    grid = [(-1.0, -1.0), (-1.5, -0.5), (-0.5, -1.5), (-2.0, -1.0)]
    conds = _volcano_conditions(volcano, grid)
    mask = engine.tof_mask_for(volcano.spec, ["CO_ox"])
    out = sweep_steady_state(volcano.spec, conds, tof_mask=mask)
    assert bool(np.all(np.asarray(out["success"])))

    # Serial reference point: the facade's transient-then-TOF activity.
    set_descriptors(volcano, -1.0, -1.0)
    serial = volcano.activity(tof_terms=["CO_ox"], ss_solve=True)
    batched = float(np.asarray(out["activity"])[0])
    assert batched == pytest.approx(serial, abs=1e-6)
    # And the golden value transitively:
    assert batched == pytest.approx(-1.563, abs=1e-3)


def test_mesh_sharded_matches_unsharded(volcano):
    assert len(jax.devices()) == 8, "conftest should provide 8 CPU devices"
    # 6 lanes over 8 devices exercises the padding path too.
    grid = [(-1.0 - 0.2 * i, -1.0 + 0.1 * i) for i in range(6)]
    conds = _volcano_conditions(volcano, grid)
    plain = batch_steady_state(volcano.spec, conds)
    mesh = make_mesh()
    sharded = batch_steady_state(volcano.spec, conds, mesh=mesh)
    np.testing.assert_allclose(np.asarray(sharded.x), np.asarray(plain.x),
                               rtol=1e-10, atol=1e-12)
    assert np.asarray(sharded.success).shape == (6,)
