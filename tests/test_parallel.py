"""Batched + sharded execution tests: the mini volcano grid.

Validates that the vmapped/mesh-sharded steady solves reproduce the serial
facade result, on the 8 virtual CPU devices provisioned in conftest --
the same code path the driver dry-runs for multi-chip validation.
"""

import jax
import numpy as np
import pytest

import pycatkin_tpu as pk
from pycatkin_tpu import engine
from pycatkin_tpu.parallel import (batch_steady_state, make_mesh,
                                   stack_conditions, sweep_steady_state)
from tests.conftest import reference_path
from tests.test_golden_volcano import SCOg, SO2g, set_descriptors


def _volcano_conditions(sim, grid):
    """Build one Conditions per (ECO, EO) grid point via the facade."""
    conds = []
    for ECO, EO in grid:
        set_descriptors(sim, ECO, EO)
        conds.append(sim.conditions())
    return stack_conditions(conds)


@pytest.fixture(scope="module")
def volcano(ref_root):
    return pk.read_from_input_file(
        reference_path("examples", "COOxVolcano", "input.json"))


@pytest.mark.slow
def test_batched_matches_serial(volcano):
    grid = [(-1.0, -1.0), (-1.5, -0.5), (-0.5, -1.5), (-2.0, -1.0)]
    conds = _volcano_conditions(volcano, grid)
    mask = engine.tof_mask_for(volcano.spec, ["CO_ox"])
    out = sweep_steady_state(volcano.spec, conds, tof_mask=mask)
    assert bool(np.all(np.asarray(out["success"])))

    # Serial reference point: the facade's transient-then-TOF activity.
    set_descriptors(volcano, -1.0, -1.0)
    serial = volcano.activity(tof_terms=["CO_ox"], ss_solve=True)
    batched = float(np.asarray(out["activity"])[0])
    assert batched == pytest.approx(serial, abs=1e-6)
    # And the golden value transitively:
    assert batched == pytest.approx(-1.563, abs=1e-3)


def test_mesh_sharded_matches_unsharded(volcano):
    assert len(jax.devices()) == 8, "conftest should provide 8 CPU devices"
    # 6 lanes over 8 devices exercises the padding path too.
    grid = [(-1.0 - 0.2 * i, -1.0 + 0.1 * i) for i in range(6)]
    conds = _volcano_conditions(volcano, grid)
    plain = batch_steady_state(volcano.spec, conds)
    mesh = make_mesh()
    sharded = batch_steady_state(volcano.spec, conds, mesh=mesh)
    np.testing.assert_allclose(np.asarray(sharded.x), np.asarray(plain.x),
                               rtol=1e-10, atol=1e-12)
    assert np.asarray(sharded.success).shape == (6,)


def test_mesh_sharded_transient_matches_unsharded(ref_root):
    """batch_transient under a lane-sharded mesh reproduces the
    unsharded trajectories bit-for-bit (VERDICT r3 item 8: multi-chip
    coverage beyond steady solves)."""
    from pycatkin_tpu.parallel import batch_transient
    from pycatkin_tpu.parallel.batch import broadcast_conditions

    sim = pk.read_from_input_file(
        reference_path("examples", "COOxReactor", "input_Pd111.json"))
    sim.params["temperature"] = 523.0
    spec = sim.spec
    n = 6   # over 8 devices: exercises lane padding too
    Ts = np.linspace(510.0, 535.0, n)
    conds = broadcast_conditions(sim.conditions(), n)._replace(T=Ts)
    save_ts = np.concatenate([[0.0], np.logspace(-10, 2, 10)])

    ys, ok = batch_transient(spec, conds, save_ts)
    mesh = make_mesh()
    ys_s, ok_s = batch_transient(spec, conds, save_ts, mesh=mesh)
    assert np.all(np.asarray(ok)) and np.all(np.asarray(ok_s))
    # Sharded layouts change XLA fusion/reduction order, so agreement
    # is to roundoff accumulation (measured ~4e-10 rel), not bitwise.
    np.testing.assert_allclose(np.asarray(ys_s), np.asarray(ys),
                               rtol=1e-6, atol=1e-12)


def test_mesh_sharded_drc_matches_unsharded(volcano):
    """The batched implicit-differentiation DRC program (IFT custom_vjp
    through the retried steady solve) executes under lane sharding and
    matches the unsharded values."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pycatkin_tpu.api.presets import _drc_program
    from pycatkin_tpu.solvers.newton import SolverOptions

    grid = [(-1.0 - 0.1 * i, -1.0 + 0.05 * i) for i in range(8)]
    conds = _volcano_conditions(volcano, grid)
    spec = volcano.spec
    prog = _drc_program(spec, ("CO_ox",), "implicit", 1e-3,
                        SolverOptions())
    xi, ok = prog(conds, None)

    mesh = make_mesh()
    sharding = NamedSharding(mesh, P(mesh.axis_names[0]))
    conds_s = jax.device_put(conds, sharding)
    xi_s, ok_s = prog(conds_s, None)
    assert np.all(np.asarray(ok)) and np.all(np.asarray(ok_s))
    np.testing.assert_allclose(np.asarray(xi_s), np.asarray(xi),
                               rtol=1e-9, atol=1e-12)
    # The values themselves must be finite and non-trivial (an
    # all-zeros xi would make the sharded==unsharded comparison
    # vacuous).
    xi_np = np.asarray(xi)
    assert np.all(np.isfinite(xi_np))
    assert np.any(np.abs(xi_np) > 1e-6)


def test_continuation_sweep_matches_plain(volcano):
    """Warm-started continuation staging (the reference presets.py
    pattern: each sweep point seeds the next) reaches the same roots as
    the cold batched sweep, in the original lane order."""
    from pycatkin_tpu.parallel import continuation_sweep

    grid = [(-1.0 - 0.15 * i, -1.0 + 0.05 * j)
            for i in range(4) for j in range(3)]
    conds = _volcano_conditions(volcano, grid)
    mask = engine.tof_mask_for(volcano.spec, ["CO_ox"])
    plain = sweep_steady_state(volcano.spec, conds, tof_mask=mask)
    order = np.arange(12).reshape(4, 3)   # stage along the E_CO axis
    cont = continuation_sweep(volcano.spec, conds, order, tof_mask=mask)
    assert np.all(np.asarray(plain["success"]))
    assert np.all(np.asarray(cont["success"]))
    np.testing.assert_allclose(np.asarray(cont["y"]),
                               np.asarray(plain["y"]),
                               rtol=1e-6, atol=1e-9)
    # Activity is log(TOF) of a near-cancelling flux difference, so
    # solver-tolerance root differences amplify; agreement at the
    # physically meaningful scale (~10 meV) is the honest contract.
    np.testing.assert_allclose(np.asarray(cont["activity"]),
                               np.asarray(plain["activity"]),
                               rtol=0, atol=2e-2)


def test_neighbor_seed_lanes_mapping(volcano):
    """The continuation rescue's seed map: converged lanes map to
    themselves, failed lanes map to the nearest CONVERGED lane in
    z-scored condition space (never to another failed lane)."""
    from pycatkin_tpu.parallel.batch import _neighbor_seed_lanes

    grid = [(-2.4, -2.4), (-2.3, -2.4), (-1.0, -1.0), (0.4, 0.4)]
    conds = _volcano_conditions(volcano, grid)
    success = np.array([True, False, True, False])
    nn = _neighbor_seed_lanes(conds, success)
    assert nn[0] == 0 and nn[2] == 2          # converged: identity
    assert success[nn[1]] and success[nn[3]]  # failed -> converged
    # lane 1 (-2.3,-2.4) is far closer to lane 0 (-2.4,-2.4) than to
    # lane 2 (-1,-1); the z-scored metric must respect that.
    assert nn[1] == 0

    # degenerate cases: nothing converged / nothing failed -> None
    assert _neighbor_seed_lanes(conds, np.zeros(4, dtype=bool)) is None
    assert _neighbor_seed_lanes(conds, np.ones(4, dtype=bool)) is None


def test_chunked_nearest_matches_brute_force():
    """The scipy-free nearest-neighbor fallback must agree with the
    brute-force answer (it backs _neighbor_seed_lanes on minimal
    installs), including across chunk boundaries."""
    from pycatkin_tpu.parallel.batch import _chunked_nearest

    rng = np.random.default_rng(3)
    Xf = rng.normal(size=(300, 5))          # > 2 chunks of 128
    Xo = rng.normal(size=(997, 5))
    brute = np.argmin(((Xf[:, None, :] - Xo[None, :, :]) ** 2).sum(-1),
                      axis=1)
    np.testing.assert_array_equal(_chunked_nearest(Xf, Xo), brute)
