"""The fleet tier (docs/serving.md "Fleet serving"): replica
supervision, the front router, and the failure taxonomy they share.

No JAX and no real SweepServer anywhere in this module: the router is
deliberately bytes-only, so it is tested against stub TCP replicas
speaking the wire protocol, and the supervisor against a tiny
subprocess stub (``FleetConfig.command``) that boots in milliseconds.
The acceptance surface, smallest-first: the retry taxonomy classifies
asyncio/socket failures; the TCP client turns a stalled server into a
structured ``E_TIMEOUT`` and a torn stream into a fast failure; the
supervisor registers pack-order boots, classifies exits, restarts on
backoff, demotes stalled replicas and abandons crash loops; the router
fails over losslessly, opens/probes/closes breakers, hedges
interactive requests with a bitwise duplicate audit, enacts the
connection-level chaos kinds, and answers every accepted request even
when drain races a replica death.
"""

import asyncio
import json
import signal
import sys
import textwrap

import pytest

from pycatkin_tpu.robustness import faults
from pycatkin_tpu.serve import client as serve_client
from pycatkin_tpu.serve.client import TcpSweepClient, sweep_payload
from pycatkin_tpu.serve.fleet import FleetConfig, ReplicaSupervisor
from pycatkin_tpu.serve.protocol import (E_CONN_LOST, E_DRAINING,
                                         E_INTERNAL, E_OVERLOADED,
                                         E_TIMEOUT,
                                         request_timeout_for)
from pycatkin_tpu.serve.router import (CircuitBreaker, RouterConfig,
                                       SweepRouter, _canonical)
from pycatkin_tpu.utils import retry

pytestmark = pytest.mark.faults


# -- stub replicas + fake supervisor -----------------------------------


class StubReplica:
    """A wire-compatible replica: answers ``ping`` natively and routes
    ``sweep`` through a swappable ``behavior(payload, writer)``
    coroutine returning the response dict (or None to stay silent)."""

    def __init__(self, behavior=None, answer_ping=True):
        self.behavior = behavior or answer_sweep
        self.answer_ping = answer_ping
        self.up = True          # FakeSupervisor routability flag
        self.port = None
        self.sweeps_seen = 0
        self.bad_lines = 0
        self._server = None
        self._tasks = set()

    async def start(self):
        self._server = await asyncio.start_server(
            self._on_conn, "127.0.0.1", 0)
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    async def stop(self):
        self._server.close()
        await self._server.wait_closed()
        for task in list(self._tasks):
            task.cancel()
        if self._tasks:
            await asyncio.gather(*list(self._tasks),
                                 return_exceptions=True)

    async def _handle_sweep(self, payload, writer):
        # Concurrent per-request handling, like the real SweepServer:
        # the protocol is id-multiplexed, so responses may interleave
        # and come back out of order.
        try:
            resp = await self.behavior(payload, writer)
            if resp is not None:
                await _write(writer, resp)
        except (ConnectionError, OSError):
            pass

    async def _on_conn(self, reader, writer):
        try:
            while True:
                line = await reader.readline()
                if not line:
                    break
                try:
                    payload = json.loads(line)
                except ValueError:
                    self.bad_lines += 1
                    continue
                if payload.get("op") == "ping":
                    if self.answer_ping:
                        await _write(writer, {
                            "ok": True, "pong": True,
                            "id": payload.get("id")})
                    continue
                self.sweeps_seen += 1
                task = asyncio.ensure_future(
                    self._handle_sweep(payload, writer))
                self._tasks.add(task)
                task.add_done_callback(self._tasks.discard)
        except (ConnectionError, asyncio.CancelledError):
            pass
        finally:
            try:
                writer.close()
            except (ConnectionError, OSError):
                pass


async def _write(writer, obj):
    writer.write((json.dumps(obj) + "\n").encode())
    await writer.drain()


async def answer_sweep(payload, writer):
    """Deterministic answer derived from the request: two replicas
    given the same sweep produce bit-identical responses, which is the
    property the duplicate audit leans on."""
    return {"ok": True, "id": payload["id"],
            "result": {"echo": payload.get("conditions")},
            "quarantine": {"n_quarantined": 0}, "lanes": None}


async def drop_connection(payload, writer):
    writer.close()
    return None


async def stay_silent(payload, writer):
    return None


class FakeSupervisor:
    """The supervisor surface the router consumes: ``endpoints()``,
    ``stats()`` and routability-change listeners."""

    def __init__(self, replicas):
        self.replicas = list(replicas)
        self._listeners = []

    def add_listener(self, fn):
        self._listeners.append(fn)

    def endpoints(self):
        return [{"idx": i, "incarnation": 1, "host": "127.0.0.1",
                 "port": s.port}
                for i, s in enumerate(self.replicas)
                if s.up and s.port is not None]

    def stats(self):
        return {"n_replicas": len(self.replicas),
                "up": sum(s.up for s in self.replicas), "replicas": []}

    def notify(self, event, idx):
        for fn in list(self._listeners):
            fn({"event": event, "idx": idx, "incarnation": 1,
                "host": "127.0.0.1",
                "port": self.replicas[idx].port})


def fast_router_config(**overrides):
    kw = dict(max_inflight=16, breaker_fails=2,
              breaker_cooldown_s=0.05, hedge_quantile=0.95,
              hedge_min_s=0.02, retries=3, retry_base_delay_s=0.001,
              retry_max_delay_s=0.01, connect_timeout_s=1.0,
              probe_timeout_s=1.0, tick_s=0.005)
    kw.update(overrides)
    return RouterConfig(**kw)


@pytest.fixture
def short_budgets(monkeypatch):
    """Small per-class SLA budgets so retry exhaustion is fast."""
    monkeypatch.setenv("PYCATKIN_SERVE_TIMEOUT_STANDARD", "2.0")
    monkeypatch.setenv("PYCATKIN_SERVE_TIMEOUT_INTERACTIVE", "1.5")


async def _router_over(replicas, **cfg_overrides):
    for r in replicas:
        await r.start()
    sup = FakeSupervisor(replicas)
    router = await SweepRouter(
        sup, fast_router_config(**cfg_overrides)).start(listen=False)
    return sup, router


async def _teardown(router, replicas):
    await router.stop()
    for r in replicas:
        await r.stop()


def _sweep(i=0, deadline_class="standard"):
    return sweep_payload({"mech": "stub"}, [500.0 + i],
                         deadline_class=deadline_class,
                         req_id=f"r{i}")


# -- retry taxonomy (utils/retry.py) -----------------------------------


@pytest.mark.parametrize("exc", [
    ConnectionResetError("peer reset"),
    ConnectionRefusedError("nobody listening"),
    ConnectionAbortedError("aborted"),
    BrokenPipeError("write to dead peer"),
    asyncio.IncompleteReadError(b"partial", 64),
    asyncio.TimeoutError(),
    TimeoutError("deadline burned"),
])
def test_connection_failures_are_transient_by_type(exc):
    assert retry.is_transient_backend_error(exc)


@pytest.mark.parametrize("exc", [
    ValueError("connection reset"),      # marker text is NOT enough
    KeyError("port"),
    RuntimeError("shape mismatch"),
])
def test_program_errors_stay_non_transient(exc):
    assert not retry.is_transient_backend_error(exc)


def test_classify_worker_exit_taxonomy():
    ok = retry.classify_worker_exit(0)
    assert (ok.kind, ok.transient) == ("ok", False)
    sig = retry.classify_worker_exit(-signal.SIGKILL)
    assert (sig.kind, sig.transient) == ("signal-death", True)
    assert "SIGKILL" in sig.detail
    bad = retry.classify_worker_exit(3)
    assert (bad.kind, bad.transient) == ("nonzero-exit", False)
    to = retry.classify_worker_exit(None, timed_out=True)
    assert (to.kind, to.transient) == ("timeout", True)
    assert retry.classify_worker_exit(None).kind == "ok"


def test_request_timeouts_come_from_the_deadline_class(monkeypatch):
    monkeypatch.setenv("PYCATKIN_SERVE_TIMEOUT_BATCH", "7.5")
    assert request_timeout_for("batch") == 7.5
    assert request_timeout_for("interactive") == 30.0
    # Unknown classes fall back to the standard budget rather than
    # hanging forever or crashing the wire loop.
    assert request_timeout_for("nonsense") == \
        request_timeout_for("standard")


# -- TCP client deadlines + torn lines ---------------------------------


def test_client_timeout_is_structured(monkeypatch):
    async def scenario():
        stub = await StubReplica(behavior=stay_silent).start()
        cli = await TcpSweepClient("127.0.0.1", stub.port).connect()
        try:
            resp = await cli.request(_sweep(0), timeout=0.1)
            assert resp["ok"] is False
            assert resp["error"]["code"] == E_TIMEOUT
            assert resp["id"] == "r0"
            # The per-class default budget applies when no explicit
            # timeout is passed.
            monkeypatch.setenv("PYCATKIN_SERVE_TIMEOUT_INTERACTIVE",
                               "0.05")
            resp = await cli.request(
                _sweep(1, deadline_class="interactive"))
            assert resp["error"]["code"] == E_TIMEOUT
            assert "interactive" in resp["error"]["message"]
        finally:
            await cli.close()
            await stub.stop()
    asyncio.run(scenario())


def test_client_counts_torn_final_line(monkeypatch):
    async def behavior(payload, writer):
        writer.write(b'{"id": "r0", "ok": true, "resu\n')  # torn
        await writer.drain()
        writer.close()
        return None

    async def scenario():
        stub = await StubReplica(behavior=behavior).start()
        cli = await TcpSweepClient("127.0.0.1", stub.port).connect()
        try:
            resp = await cli.request(_sweep(0), timeout=5.0)
            # The torn line is counted and the dropped connection
            # fails the keyless pending request with a structured
            # connection-loss error instead of hanging it.
            assert cli.torn_lines == 1
            assert resp["ok"] is False
            assert resp["error"]["code"] == E_CONN_LOST
            assert resp["error"]["idempotency_key"] is False
            assert str(stub.port) in resp["error"]["peer"]
        finally:
            await cli.close()
            await stub.stop()
        from pycatkin_tpu.obs import metrics
        assert "pycatkin_serve_torn_lines_total" in \
            metrics.snapshot()["counters"]
    asyncio.run(scenario())


def test_client_fails_fast_after_torn_streak():
    async def behavior(payload, writer):
        for _ in range(serve_client.TORN_LINE_LIMIT):
            writer.write(b"%% not json %%\n")
        await writer.drain()
        return None            # then stall: the streak must break us

    async def scenario():
        stub = await StubReplica(behavior=behavior).start()
        cli = await TcpSweepClient("127.0.0.1", stub.port).connect()
        try:
            resp = await cli.request(_sweep(0), timeout=30.0)
            assert resp["ok"] is False
            assert "torn" in resp["error"]["message"]
            assert cli.torn_lines == serve_client.TORN_LINE_LIMIT
        finally:
            await cli.close()
            await stub.stop()
    asyncio.run(scenario())


# -- circuit breaker unit ----------------------------------------------


def test_breaker_lifecycle():
    br = CircuitBreaker(fails=2, cooldown_s=0.01)
    assert br.routable
    br.record_failure()
    assert br.routable            # below threshold
    br.record_failure()
    assert br.state == "open" and not br.routable
    assert not br.probe_due()     # cooldown not burned yet
    import time
    time.sleep(0.02)
    assert br.probe_due()
    br.begin_probe()
    assert br.state == "half-open"
    br.probe_result(False)
    assert br.state == "open"
    time.sleep(0.02)
    br.begin_probe()
    br.probe_result(True)
    assert br.state == "closed" and br.failures == 0
    # One failure in half-open reopens immediately (no threshold).
    br.record_failure()
    br.record_failure()
    time.sleep(0.02)
    br.begin_probe()
    br.record_failure()
    assert br.state == "open"


# -- router: routing, failover, admission ------------------------------


def test_router_answers_and_hides_internals(short_budgets):
    async def scenario():
        replicas = [StubReplica(), StubReplica()]
        sup, router = await _router_over(replicas)
        try:
            resp = await router.handle(_sweep(0))
            assert resp["ok"] and resp["id"] == "r0"
            assert "_replica_idx" not in resp
            st = router.stats()
            assert st["ok_total"] == 1 and st["availability"] == 1.0
        finally:
            await _teardown(router, replicas)
    asyncio.run(scenario())


def test_router_fails_over_losslessly(short_budgets):
    async def scenario():
        dead = StubReplica(behavior=drop_connection)
        live = StubReplica()
        sup, router = await _router_over([dead, live])
        try:
            resps = await asyncio.gather(*(
                router.handle(_sweep(i)) for i in range(6)))
            assert all(r["ok"] for r in resps)
            st = router.stats()
            assert st["failovers"] >= 1
            assert st["retries"] >= 1
            assert st["availability"] == 1.0
            assert st["failover_p99_s"] is not None
        finally:
            await _teardown(router, replicas=[dead, live])
    asyncio.run(scenario())


def test_router_overload_then_breaker_recovery(short_budgets):
    async def scenario():
        replicas = [StubReplica(behavior=drop_connection),
                    StubReplica(behavior=drop_connection)]
        sup, router = await _router_over(replicas)
        try:
            resp = await router.handle(_sweep(0))
            assert resp["ok"] is False
            assert resp["error"]["code"] == E_INTERNAL
            # Both breakers are open now: admission rejects with a
            # structured overload, not a hang.
            resp = await router.handle(_sweep(1))
            assert resp["error"]["code"] == E_OVERLOADED
            assert set(router.stats()["breakers"].values()) == {"open"}
            # The replicas recover; the admission path itself kicks
            # the half-open probes, so the router rediscovers them
            # even while rejecting everything.
            for r in replicas:
                r.behavior = answer_sweep
            deadline = asyncio.get_running_loop().time() + 5.0
            while True:
                resp = await router.handle(_sweep(2))
                if resp.get("ok"):
                    break
                assert asyncio.get_running_loop().time() < deadline, \
                    f"router never recovered: {router.stats()}"
                await asyncio.sleep(0.02)
        finally:
            await _teardown(router, replicas)
    asyncio.run(scenario())


def test_router_hedges_interactive_and_audits_duplicates(short_budgets):
    async def slow_answer(payload, writer):
        await asyncio.sleep(0.3)
        return await answer_sweep(payload, writer)

    async def scenario():
        slow = StubReplica(behavior=slow_answer)
        fast = StubReplica()
        sup, router = await _router_over([slow, fast])
        try:
            resp = await router.handle(
                _sweep(0, deadline_class="interactive"))
            assert resp["ok"]
            st = router.stats()
            assert st["hedges"] >= 1
            # The loser's late answer is suppressed and audited as
            # bit-identical (deterministic same-width sweeps).
            deadline = asyncio.get_running_loop().time() + 2.0
            while router.stats()["duplicates"]["suppressed"] < 1:
                assert asyncio.get_running_loop().time() < deadline
                await asyncio.sleep(0.02)
            dup = router.stats()["duplicates"]
            assert dup["mismatched"] == 0
            assert dup["identical"] >= 1
        finally:
            await _teardown(router, replicas=[slow, fast])
    asyncio.run(scenario())


def test_router_inflight_cap_rejects_structured(short_budgets):
    async def scenario():
        slow = StubReplica(behavior=stay_silent)
        sup, router = await _router_over([slow], max_inflight=1,
                                         retries=0)
        try:
            first = asyncio.ensure_future(router.handle(_sweep(0)))
            await asyncio.sleep(0.05)      # let it occupy the slot
            resp = await router.handle(_sweep(1))
            assert resp["error"]["code"] == E_OVERLOADED
            assert "in-flight cap" in resp["error"]["message"]
            first.cancel()
            try:
                await first
            except asyncio.CancelledError:
                pass
        finally:
            await _teardown(router, replicas=[slow])
    asyncio.run(scenario())


# -- router: connection-level chaos kinds ------------------------------


def test_conn_reset_chaos_fails_over(short_budgets):
    async def scenario():
        replicas = [StubReplica(), StubReplica()]
        sup, router = await _router_over(replicas)
        plan = faults.FaultPlan([{"site": "router:dispatch:*",
                                  "kind": "conn-reset", "times": 1}])
        try:
            with faults.fault_scope(plan):
                resp = await router.handle(_sweep(0))
            assert resp["ok"]
            assert [e["kind"] for e in plan.log] == ["conn-reset"]
            assert router.stats()["retries"] >= 1
        finally:
            await _teardown(router, replicas)
    asyncio.run(scenario())


def test_torn_line_chaos_recovers_under_budget(short_budgets):
    async def scenario():
        replicas = [StubReplica(), StubReplica()]
        sup, router = await _router_over(replicas)
        plan = faults.FaultPlan([{"site": "router:dispatch:*",
                                  "kind": "torn-line", "times": 1}])
        try:
            with faults.fault_scope(plan):
                resp = await router.handle(_sweep(0))
            assert resp["ok"]
            assert [e["kind"] for e in plan.log] == ["torn-line"]
            # The replica saw one undecodable line (the torn write)
            # and the router's retry answered the request anyway.
            assert sum(r.bad_lines for r in replicas) == 1
        finally:
            await _teardown(router, replicas)
    asyncio.run(scenario())


# -- router: drain during failover (loss-free) -------------------------


def test_drain_during_failover_answers_every_accepted(short_budgets):
    async def slowish(payload, writer):
        await asyncio.sleep(0.15)
        return await answer_sweep(payload, writer)

    async def scenario():
        doomed = StubReplica(behavior=slowish)
        live = StubReplica(behavior=slowish)
        sup, router = await _router_over([doomed, live])
        try:
            accepted = [asyncio.ensure_future(
                router.handle(_sweep(i))) for i in range(6)]
            await asyncio.sleep(0.05)      # all dispatched, none done
            drainer = asyncio.ensure_future(router.drain())
            # Replica 0 dies mid-drain: its in-flight dispatches must
            # fail over to the survivor, not be dropped.
            doomed.up = False
            sup.notify("down", 0)
            await doomed.stop()
            resps = await asyncio.gather(*accepted)
            await drainer
            assert all(r["ok"] for r in resps), resps
            assert router.stats()["failovers"] >= 1
            # Post-drain admission is a structured reject.
            resp = await router.handle(_sweep(99))
            assert resp["error"]["code"] == E_DRAINING
        finally:
            await router.stop()
            await live.stop()
    asyncio.run(scenario())


def test_canonical_ignores_metadata():
    a = {"ok": True, "result": {"x": 1}, "quarantine": None,
         "lanes": None, "timing": {"total_s": 0.5}, "pack": {"k": 2}}
    b = {"ok": True, "result": {"x": 1}, "quarantine": None,
         "lanes": None, "timing": {"total_s": 9.9}, "pack": {"k": 4}}
    c = {"ok": True, "result": {"x": 2}, "quarantine": None,
         "lanes": None}
    assert _canonical(a) == _canonical(b)
    assert _canonical(a) != _canonical(c)


# -- fleet supervisor over a stub subprocess ---------------------------


STUB_REPLICA = textwrap.dedent("""
    import json, socket, sys, threading

    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(16)
    print(json.dumps({"serving": True, "host": "127.0.0.1",
                      "port": srv.getsockname()[1]}), flush=True)

    def serve(conn):
        f = conn.makefile("rwb")
        for line in f:
            try:
                req = json.loads(line)
            except ValueError:
                continue
            f.write((json.dumps({"ok": True, "pong": True,
                                 "id": req.get("id")}) + "\\n")
                    .encode())
            f.flush()

    while True:
        conn, _ = srv.accept()
        threading.Thread(target=serve, args=(conn,),
                         daemon=True).start()
""")


@pytest.fixture
def stub_command(tmp_path):
    path = tmp_path / "stub_replica.py"
    path.write_text(STUB_REPLICA)
    return [sys.executable, str(path)]


def fast_fleet_config(stub_command, **overrides):
    kw = dict(n_replicas=2, command=stub_command,
              restart_base_delay_s=0.01, restart_max_delay_s=0.1,
              ping_period_s=0.1, ping_misses=2, ping_timeout_s=1.0,
              boot_timeout_s=30.0, stop_grace_s=5.0, tick_s=0.01)
    kw.update(overrides)
    return FleetConfig(**kw)


async def _wait_for(cond, timeout_s=20.0, what="condition"):
    deadline = asyncio.get_running_loop().time() + timeout_s
    while not cond():
        assert asyncio.get_running_loop().time() < deadline, \
            f"timed out waiting for {what}"
        await asyncio.sleep(0.02)


def test_supervisor_boots_registers_and_restarts(stub_command):
    async def scenario():
        events = []
        sup = ReplicaSupervisor(fast_fleet_config(stub_command))
        sup.add_listener(events.append)
        await sup.start()
        try:
            eps = sup.endpoints()
            assert len(eps) == 2
            assert all(e["incarnation"] == 1 for e in eps)
            assert [e["event"] for e in events] == ["up", "up"]
            # SIGKILL replica 0: classified signal-death (transient),
            # restarted on backoff as a NEW incarnation on a new port.
            old_port = sup.replicas[0].port
            sup.replicas[0].proc.kill()
            await _wait_for(
                lambda: sup.replicas[0].incarnation == 2
                and sup.replicas[0].routable,
                what="replica 0 reboot")
            assert sup.replicas[0].last_exit_kind == "signal-death"
            assert sup.replicas[0].restarts == 1
            assert sup.replicas[0].port != old_port
            kinds = [e["event"] for e in events]
            assert kinds == ["up", "up", "down", "up"]
        finally:
            await sup.stop()
        assert all(r.proc is None or r.proc.returncode is not None
                   for r in sup.replicas)
    asyncio.run(scenario())


def test_supervisor_enacts_chaos_kill_at_its_site(stub_command):
    async def scenario():
        sup = ReplicaSupervisor(fast_fleet_config(stub_command,
                                                  n_replicas=1))
        await sup.start()
        plan = faults.FaultPlan([{"site": "router:replica:0",
                                  "kind": "replica-crash",
                                  "times": 1}])
        try:
            with faults.fault_scope(plan):
                await _wait_for(
                    lambda: sup.replicas[0].incarnation == 2
                    and sup.replicas[0].routable,
                    what="chaos kill + reboot")
            assert [e["kind"] for e in plan.log] == ["replica-crash"]
            assert sup.replicas[0].last_exit_kind == "signal-death"
        finally:
            await sup.stop()
    asyncio.run(scenario())


def test_supervisor_demotes_stalled_replica_then_reboots(stub_command):
    async def scenario():
        events = []
        sup = ReplicaSupervisor(fast_fleet_config(stub_command,
                                                  n_replicas=1))
        sup.add_listener(events.append)
        await sup.start()
        try:
            # SIGSTOP: alive but silent. Missed pings demote it
            # (unroutable, announced), twice the miss budget kills it,
            # and the exit path reboots a fresh incarnation.
            sup.replicas[0].proc.send_signal(signal.SIGSTOP)
            await _wait_for(
                lambda: any(e["event"] == "down" for e in events),
                what="demotion")
            assert sup.endpoints() == []
            await _wait_for(
                lambda: sup.replicas[0].incarnation == 2
                and sup.replicas[0].routable,
                timeout_s=30.0, what="stall kill + reboot")
        finally:
            await sup.stop()
    asyncio.run(scenario())


def test_supervisor_abandons_crash_loops(tmp_path):
    bad = tmp_path / "crash.py"
    bad.write_text("import sys; sys.exit(3)\n")

    async def scenario():
        events = []
        sup = ReplicaSupervisor(fast_fleet_config(
            [sys.executable, str(bad)], n_replicas=1, max_restarts=1,
            restart_max_delay_s=0.02))
        sup.add_listener(events.append)
        with pytest.raises(RuntimeError, match="no replica came up"):
            await sup.start()
        try:
            assert sup.replicas[0].state == "abandoned"
            assert sup.replicas[0].last_exit_kind == "nonzero-exit"
            assert events[-1]["event"] == "abandoned"
        finally:
            await sup.stop()
    asyncio.run(scenario())


# -- perfwatch tracks the fleet metrics --------------------------------


def test_history_extracts_router_metrics():
    from pycatkin_tpu.obs.history import TRACKED_METRICS, \
        extract_metrics
    assert TRACKED_METRICS["router_availability"] == "higher"
    assert TRACKED_METRICS["failover_p99_s"] == "lower"
    record = {"bench": "serve-chaos-drill",
              "router": {"availability": 1.0,
                         "failover_p99_s": 0.25}}
    got = extract_metrics(record)
    assert got["router_availability"] == 1.0
    assert got["failover_p99_s"] == 0.25
    # Absent sub-object -> absent metrics, not zeros.
    assert "router_availability" not in extract_metrics({"bench": "x"})
