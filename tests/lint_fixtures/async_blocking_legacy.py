"""Seeded PCL010 violations: blocking calls inside ``async def``
bodies. Never imported; the serve/ scope is bypassed on purpose by
``lint_file``."""

import asyncio
import time

import numpy as np

from pycatkin_tpu.utils.profiling import host_sync


async def sleepy_handler():
    time.sleep(0.1)                 # VIOLATION: blocks the loop


async def file_reader(path):
    with open(path) as fh:          # VIOLATION: blocking file I/O
        return fh.read()


async def future_waiter(fut, thread):
    x = fut.result()                # VIOLATION: blocks on a future
    thread.join()                   # VIOLATION: no-arg thread join
    return x


async def device_puller(arr):
    return np.asarray(arr)          # VIOLATION: device pull on the loop


async def counted_puller(arr):
    return host_sync(arr, "serve")  # VIOLATION: counted, still blocking


async def sanctioned(arr, path):
    # Offload is the sanctioned idiom: the blocking callable runs on a
    # worker thread, the loop only awaits.
    data = await asyncio.to_thread(np.asarray, arr)
    await asyncio.sleep(0.01)       # async sleep: clean
    sep = ",".join(str(x) for x in data)     # string join: clean
    return sep, path


async def reviewed_blocking(path):
    with open(path) as fh:  # pclint: disable=PCL010 -- startup-only config read, loop not serving yet
        return fh.read()


def sync_helper(arr):
    # Sync def: runs wherever it is invoked (a worker thread here);
    # not the loop's problem.
    time.sleep(0.1)
    return np.asarray(arr)


async def with_nested_sync_def(arr):
    def offloaded():
        return np.asarray(arr)      # nested sync def: clean
    return await asyncio.to_thread(offloaded)
