"""Seeded PCL011 violations: guarded attributes touched outside their
lock. Never imported."""

import threading


class LeakyQueue:
    def __init__(self):
        self._lock = threading.Lock()
        self._items: list = []      # guarded-by: _lock
        self._count = 0             # guarded-by: _lock
        self._free: list = []       # no contract: never flagged

    def push(self, x):
        with self._lock:
            self._items.append(x)   # clean: lock held
            self._count += 1        # clean: lock held

    def racy_pop(self):
        if self._items:             # VIOLATION: read outside the lock
            return self._items.pop()  # VIOLATION: write outside the lock
        return None

    def racy_count(self):
        return self._count          # VIOLATION: read outside the lock

    def free_for_all(self):
        return list(self._free)     # clean: undeclared attribute

    def approx_len(self):
        return len(self._items)  # pclint: disable=PCL011 -- benign racy read for progress display
