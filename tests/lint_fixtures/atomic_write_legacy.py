"""Seeded PCL012 violations: torn-write idioms in a protocol file.
Never imported; the scheduler/io scope is bypassed on purpose by
``lint_file``."""

import json
import os


def torn_record(path, payload):
    with open(path, "w") as fh:     # VIOLATION: no atomic publish
        json.dump(payload, fh)


def clobbering_rename(src, dst):
    os.rename(src, dst)             # VIOLATION: use os.replace/os.link


def atomic_record(path, payload):
    # Clean: tmp + os.replace (last-writer-wins publish).
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(payload, fh)
    os.replace(tmp, path)


def first_wins_record(path, payload):
    # Clean: tmp + os.link (first-writer-wins publish).
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(payload, fh)
    try:
        os.link(tmp, path)
    finally:
        os.unlink(tmp)


def marker_file(path):
    with open(path, "w") as fh:  # pclint: disable=PCL012 -- existence-only marker; content never read
        fh.write("x\n")
