"""PCL007 fixture: a ``*_program`` builder whose jitted closure reads
``spec.<array>`` numpy fields -- the constant-folding idiom the
mechanism ABI (frontend/abi.py) removes from the hot builders. Legal
reads are seeded too: array reads in the builder's trace-setup body,
scalar statics inside the closure, and a shadowing inner ``spec``.
Never executed.
"""

import jax
import jax.numpy as jnp


def _steady_program(spec, engine):
    x0 = jnp.zeros(spec.dynamic_indices.shape)   # OK: builder body

    def program(conds, keys):
        S = spec.stoich                          # VIOLATION PCL007
        nu = spec.reac_idx  # pclint: disable=PCL007 -- fixture: reviewed legacy constant
        n = spec.n_species                       # OK: scalar static
        rates = jax.vmap(lambda c: engine.rhs(spec, c, x0))(conds)
        return S @ rates.T, nu, n, keys

    return jax.jit(program)


def _tof_program(spec, engine):
    def batched(conds, ys):
        mask = jnp.asarray(spec.is_ghost)        # VIOLATION PCL007

        def inner(spec):                         # shadows the builder's
            return spec.stoich                   # OK: not ours

        per_lane = jax.vmap(lambda c, y: spec.area * y)(conds, ys)
        # ^ VIOLATION PCL007 (lambda closure)
        return mask, inner, per_lane

    return jax.jit(batched)


def helper_not_a_builder(spec):
    def program(conds):
        return spec.stoich @ conds               # OK: not a *_program
    return program
