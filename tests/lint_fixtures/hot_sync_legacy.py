"""PCL001 fixture: raw host materializations in a hot-path function.

`sweep_steady_state` is a registered hot-path name
(pycatkin_tpu/lint/hotpath.py); `cold_helper` is not and must stay
silent. The multi-line `# sync-ok:` call and the keyword-argument
scalar pull are regression proofs for the two misses of the
pre-pclint script (first-line-only annotation match, args[0]-only
pull detection). Never executed -- it only needs to parse.
"""

import jax.numpy as jnp
import numpy as np

from pycatkin_tpu.utils.profiling import host_sync


def sweep_steady_state(spec, conds):
    resid = jnp.ones(4)
    out = np.asarray(resid)                 # VIOLATION: raw np.asarray
    worst = float(x=jnp.max(resid))         # VIOLATION: keyword-arg pull
    ok = np.asarray(
        resid
        > 0.0)  # sync-ok: failure path, full mask needed
    n_bad = int(jnp.sum(resid < 0.0))  # pclint: disable=PCL001 -- reviewed diagnostics pull
    counted = float(host_sync(jnp.min(resid), "fixture"))
    return out, worst, ok, n_bad, counted


def cold_helper(resid):
    # Not a registered hot function: raw pulls here are legal.
    return np.asarray(resid), float(jnp.max(resid))
