"""Seeded PCL009 violations: instrument names missing from the
metrics catalog. The paired test checks against a doc documenting ONLY
`pycatkin_documented_total`. Never imported."""

from pycatkin_tpu.obs import metrics as _metrics


def documented_metric():
    _metrics.counter("pycatkin_documented_total",
                     "in the catalog; clean").inc()


def undocumented_counter():
    # VIOLATION: name absent from the catalog table.
    _metrics.counter("pycatkin_rogue_total", "nobody will find me").inc()


def undocumented_histogram():
    # VIOLATION: histograms are checked too.
    _metrics.histogram("pycatkin_rogue_seconds", "orphaned").observe(1.0)


def reviewed_scratch_metric():
    _metrics.gauge("pycatkin_scratch_items",  # pclint: disable=PCL009 -- scratch gauge for the fixture corpus
                   "inline-suppressed").set(1.0)
