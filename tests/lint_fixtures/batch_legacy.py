"""PCL003/PCL004 fixture: the `_tof_program` closure factory from
pycatkin_tpu/parallel/batch.py with the historical host-side idioms
reintroduced on purpose.

`batched` is never decorated -- it is jitted by NAME via the package's
dominant ``return jax.jit(batched)`` factory idiom, which is exactly
what the static jit detection must see through. The seeded idioms are
the real ones the hot path once carried: a debug ``print`` under
trace, a Python ``if`` on a jnp reduction (TracerBoolConversionError,
but only when the branch first traces), and an ``np.asarray`` of a
traced local (silent trace-time constant-fold). Never executed.
"""

import jax
import jax.numpy as jnp
import numpy as np


def _tof_program(spec, engine):
    def batched(conds, ys, mask, ok):
        tofs = jax.vmap(lambda c, y: engine.tof(spec, c, y, mask))(conds,
                                                                   ys)
        print("tof trace:", tofs)               # VIOLATION PCL003
        print("lanes:", len(ys))  # pclint: disable=PCL003 -- trace-time shape log, intentional
        act = engine.activity_from_tof(
            tofs, jax.tree_util.tree_leaves(conds.T)[0])
        lane_ok = ok & jnp.isfinite(tofs)
        if jnp.any(lane_ok & (tofs < 0.0)):     # VIOLATION PCL004 (if)
            act = -act
        tof_host = np.asarray(tofs)             # VIOLATION PCL004 (np.*)
        ok_host = np.asarray(ok)  # pclint: disable=PCL004 -- fixture: pretend ok is static
        return tofs, act, tof_host, ok_host
    return jax.jit(batched)
