"""PCL002 fixture: fault-site labels, documented and not.

tests/test_pclint.py runs the checker against a temporary doc that
backticks only `fixture:documented`, so `fixture:undocumented` and the
normalized f-string label `fixture:rescue[<i>]` must be flagged while
the documented and inline-disabled sites stay silent. Never executed.
"""

from pycatkin_tpu.utils.profiling import record_event
from pycatkin_tpu.utils.retry import call_with_backend_retry


def run_with_sites(fn, lane):
    site = "fixture:undocumented"                        # VIOLATION
    record_event("degradation", label=f"fixture:rescue[{lane}]")  # VIOLATION
    out = call_with_backend_retry(fn, label="fixture:documented")
    record_event("degradation", label="fixture:reviewed")  # pclint: disable=PCL002 -- fixture-only site
    return site, out
