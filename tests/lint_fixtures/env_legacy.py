"""PCL006 fixture: PYCATKIN_* keys in and out of the registry.

`PYCATKIN_FAULTS` is in the documented registry (docs/index.md) and
must stay silent; the fixture-only key must be flagged; the inline
disable must suppress. Never executed.
"""

import os


def knobs():
    undocumented = os.environ.get("PYCATKIN_FIXTURE_ONLY_KNOB", "0")  # VIOLATION
    documented = os.environ.get("PYCATKIN_FAULTS", "")
    silenced = os.environ.get("PYCATKIN_FIXTURE_SILENCED")  # pclint: disable=PCL006 -- fixture key, not a knob
    return undocumented, documented, silenced
