"""PCL005 fixture: hardcoded float dtypes in kernel-style code.

The checker's scope is ops/ and solvers/; the fixture test calls it
directly via ``core.lint_file`` (which bypasses scope on purpose).
Never executed.
"""

import jax.numpy as jnp
import numpy as np


def make_scratch(n):
    bad_attr = np.zeros(n, dtype=np.float64)        # VIOLATION (attr)
    bad_str = jnp.asarray(bad_attr, dtype="float64")  # VIOLATION (str)
    golden = np.zeros(n, dtype=np.float64)  # pclint: disable=PCL005 -- host-side golden buffer
    inherited = jnp.zeros_like(bad_str)             # fine: inherits
    return bad_attr, bad_str, golden, inherited


def sneaky_downcast(x):
    bad32_attr = x.astype(jnp.float32)              # VIOLATION (attr)
    bad32_str = jnp.asarray(x, dtype="float32")     # VIOLATION (str)
    blessed = x.astype(jnp.float32)  # pclint: disable=PCL005 -- fixture: algorithm-intrinsic f32, not a tier choice
    return bad32_attr, bad32_str, blessed
