"""PCL008 fixture: record_event kinds, documented and not.

tests/test_pclint.py runs the checker against a temporary doc that
backticks only `span` and `degradation`, so the typo'd `degredation`
and the novel `checkpoint` kind must be flagged (first-positional and
``kind=`` spellings both), while the documented, dynamic and
inline-disabled kinds stay silent. Never executed.
"""

from pycatkin_tpu.utils.profiling import record_event


def emit_events(label, dynamic_kind):
    record_event("degradation", label=label)
    record_event("degredation", label=label)             # VIOLATION
    record_event(kind="checkpoint", label=label)         # VIOLATION
    record_event(dynamic_kind, label=label)     # dynamic: not checkable
    record_event("audit", label=label)  # pclint: disable=PCL008 -- fixture-only kind
    return label
