"""Run-scoped telemetry (ISSUE-8): trace contexts, per-trace sync
budgets, span parenting across the chunked pipeline, the metrics
registry, Chrome trace export and the run manifest.

The load-bearing contracts:

- ``sync_budget()`` attributes syncs to the AMBIENT trace, so two
  threads under separate ``run_trace`` contexts cannot pollute each
  other's budgets (the concurrency bug the old profiling docstring
  admitted);
- the double-buffered chunk pipeline propagates its submitter's
  context into the executor, so concurrent chunks are SIBLING spans
  under the submitting scope, not orphans or interleaved garbage;
- telemetry is host-side bookkeeping only: the solver-facing metrics
  a sweep emits are identical with the mechanism ABI on and off;
- the Prometheus exposition parses, and the exported Chrome trace
  reproduces counted sync labels verbatim.
"""

import json
import os
import threading

import numpy as np
import pytest

from pycatkin_tpu import engine, obs
from pycatkin_tpu.models.synthetic import synthetic_system
from pycatkin_tpu.obs import metrics as obs_metrics
from pycatkin_tpu.obs.export import (chrome_trace, load_trace,
                                     span_summary, span_tree,
                                     write_chrome_trace)
from pycatkin_tpu.obs.manifest import run_manifest
from pycatkin_tpu.parallel.batch import (broadcast_conditions,
                                         sweep_steady_state)
from pycatkin_tpu.robustness import chunked_sweep_steady_state
from pycatkin_tpu.utils import profiling

_N = 8


@pytest.fixture(scope="module")
def problem():
    sim = synthetic_system(n_species=10, n_reactions=12)
    spec = sim.spec
    conds = broadcast_conditions(sim.conditions(), _N)
    conds = conds._replace(T=np.linspace(450.0, 650.0, _N))
    mask = engine.tof_mask_for(spec, [spec.rnames[-1]])
    return spec, conds, mask


# ------------------------------------------------- per-trace attribution

def test_sync_budget_two_threads_isolated():
    """Regression for the documented concurrency bug: two threads each
    under their own run_trace, syncing CONCURRENTLY (a barrier forces
    the overlap) -- each budget must see exactly its own syncs."""
    barrier = threading.Barrier(2, timeout=10.0)
    results = {}

    def worker(name, n_syncs):
        with obs.run_trace(name):
            with profiling.sync_budget() as budget:
                barrier.wait()
                for k in range(n_syncs):
                    profiling.host_sync([float(k)], f"{name} sync")
                barrier.wait()
        results[name] = (budget.count, budget.labels)

    a = threading.Thread(target=worker, args=("thread-a", 2))
    b = threading.Thread(target=worker, args=("thread-b", 3))
    a.start(); b.start(); a.join(); b.join()

    assert results["thread-a"] == (2, ["thread-a sync"] * 2)
    assert results["thread-b"] == (3, ["thread-b sync"] * 3)


def test_sync_budget_root_fallback_unchanged():
    """Outside any run_trace, the legacy process-wide behavior holds:
    the budget and the global counters agree."""
    profiling.reset_sync_count()
    with profiling.sync_budget() as budget:
        profiling.host_sync([1.0], "root fallback")
    assert budget.count == 1
    assert budget.labels == ["root fallback"]
    assert profiling.sync_count() == 1
    assert profiling.sync_labels() == ["root fallback"]
    profiling.reset_sync_count()


def test_events_scoped_to_their_trace():
    profiling.record_event("degradation", label="outside before")
    with obs.run_trace("scoped") as tr:
        profiling.record_event("degradation", label="inside")
        assert [e["label"] for e in profiling.peek_events("degradation")] \
            == ["inside"]
    assert all(e.get("label") != "inside"
               for e in profiling.peek_events("degradation"))
    assert [e["label"] for e in tr.peek("degradation")] == ["inside"]
    # drain the root-trace leftovers so later tests start clean
    profiling.drain_events()


# ------------------------------------------------- span tree + pipeline

def test_span_nesting_records_parent_links():
    with obs.run_trace("nest") as tr:
        with profiling.span("outer"):
            with profiling.span("inner"):
                pass
            with profiling.span("inner2"):
                pass
    spans = {e["label"]: e for e in tr.peek("span")}
    assert spans["outer"]["parent_id"] is None
    assert spans["inner"]["parent_id"] == spans["outer"]["span_id"]
    assert spans["inner2"]["parent_id"] == spans["outer"]["span_id"]
    roots = span_tree(tr.peek("span"))
    assert [r["label"] for r in roots] == ["outer"]
    assert sorted(c["label"] for c in roots[0]["children"]) \
        == ["inner", "inner2"]


@pytest.mark.faults
def test_chunked_pipeline_chunks_are_sibling_spans(problem):
    """The double-buffered executor copies the submitter's context
    (robustness/chunked.py submit_chunk), so every chunk-solve span is
    a SIBLING under the submitting scope's span -- concurrently
    executing chunks must not nest under each other."""
    spec, conds, mask = problem
    with obs.run_trace("pipeline run") as tr:
        with profiling.span("pipeline"):
            out, report = chunked_sweep_steady_state(
                spec, conds, chunk=4, tof_mask=mask)
    assert report["n_failed_lanes"] == 0
    spans = tr.peek("span")
    pipeline = next(e for e in spans if e["label"] == "pipeline")
    chunks = [e for e in spans if e["label"] == "chunk solve"]
    assert len(chunks) == report["n_chunks"] == 2
    assert sorted(c["chunk"] for c in chunks) == [0, 1]
    chunk_ids = {c["span_id"] for c in chunks}
    for c in chunks:
        assert c["parent_id"] == pipeline["span_id"]
        assert c["parent_id"] not in chunk_ids


# ------------------------------------------------------- metrics registry

def _counter_totals(names):
    snap = obs_metrics.snapshot()["counters"]
    return {n: sum(snap.get(n, {}).values()) for n in names}


_SOLVER_COUNTERS = ("pycatkin_lanes_solved_total",
                    "pycatkin_host_syncs_total",
                    "pycatkin_quarantined_lanes_total",
                    "pycatkin_tier2_escalations_total")


def _sweep_metric_deltas(spec, conds, mask):
    before = _counter_totals(_SOLVER_COUNTERS)
    out = sweep_steady_state(spec, conds, tof_mask=mask)
    assert bool(np.all(np.asarray(out["success"])))
    after = _counter_totals(_SOLVER_COUNTERS)
    return {n: after[n] - before[n] for n in _SOLVER_COUNTERS}


def test_metrics_snapshot_abi_invariant(problem, monkeypatch):
    """Telemetry must be solver-neutral: the counters a clean sweep
    emits are identical with PYCATKIN_ABI=0 and =1 (lanes counted once
    per sweep either way -- the ABI gate's recursion must not double
    count)."""
    from pycatkin_tpu.frontend.abi import maybe_lower
    spec, conds, mask = problem
    monkeypatch.setenv("PYCATKIN_ABI", "0")
    d_off = _sweep_metric_deltas(spec, conds, mask)
    monkeypatch.setenv("PYCATKIN_ABI", "1")
    if maybe_lower(spec) is None:
        pytest.skip("mechanism does not fit an ABI bucket")
    d_on = _sweep_metric_deltas(spec, conds, mask)
    assert d_off == d_on
    assert d_off["pycatkin_lanes_solved_total"] == _N
    # ...and the bucket-routing counter is the one thing that differs.
    snap = obs_metrics.snapshot()["counters"]
    assert sum(snap.get("pycatkin_abi_bucket_sweeps_total",
                        {}).values()) >= 1


def test_metrics_registry_shapes():
    reg = obs_metrics.MetricsRegistry()
    c = reg.counter("t_total", "help")
    c.inc(); c.inc(2, kind="x")
    reg.gauge("t_gauge").set(4.5)
    h = reg.histogram("t_seconds")
    h.observe(0.05); h.observe(5.0)
    snap = reg.snapshot()
    assert snap["counters"]["t_total"][""] == 1.0
    assert snap["counters"]["t_total"]['kind="x"'] == 2.0
    assert snap["gauges"]["t_gauge"][""] == 4.5
    assert snap["histograms"]["t_seconds"][""]["count"] == 2
    with pytest.raises(ValueError):
        c.inc(-1)
    with pytest.raises(TypeError):
        reg.gauge("t_total")         # kind mismatch on re-registration


def test_prometheus_exposition_valid():
    reg = obs_metrics.MetricsRegistry()
    reg.counter("t_total", "a counter").inc(3, kind="demo")
    reg.gauge("t_gauge", "a gauge").set(-1.5)
    h = reg.histogram("t_seconds", "a histogram")
    for v in (0.0005, 0.2, 90.0):
        h.observe(v)
    text = reg.prometheus_text()
    assert obs_metrics.validate_prometheus_text(text) == []
    # histogram completeness: cumulative buckets, +Inf, _sum, _count
    assert 't_seconds_bucket{le="+Inf"} 3' in text
    assert "t_seconds_count 3" in text
    # the LIVE registry's exposition must lint clean too
    assert obs_metrics.validate_prometheus_text(
        obs_metrics.prometheus_text()) == []


def test_prometheus_validator_catches_garbage():
    bad = "# TYPE t_total bogus\nt_total{open 3\n"
    assert obs_metrics.validate_prometheus_text(bad)


# ------------------------------------------- chrome trace + run manifest

def test_chrome_trace_roundtrip(tmp_path):
    with obs.run_trace("roundtrip") as tr:
        with profiling.span("outer"):
            with profiling.span("inner"):
                profiling.host_sync([1.0, 2.0], "rt sync")
    path = os.path.join(tmp_path, "rt.trace.json")
    write_chrome_trace(path, tr)
    obj = load_trace(path)
    with open(path) as fh:
        assert json.load(fh) == obj          # plain JSON on disk
    xs = {e["name"]: e for e in obj["traceEvents"] if e["ph"] == "X"}
    assert set(xs) == {"outer", "inner"}
    assert xs["inner"]["dur"] <= xs["outer"]["dur"]
    syncs = [e for e in obj["traceEvents"]
             if e["ph"] == "i" and e.get("cat") == "sync"]
    assert [e["name"] for e in syncs] == ["rt sync"]
    assert obj["otherData"]["sync_labels"] == ["rt sync"]
    assert obj["otherData"]["sync_count"] == 1
    # span helpers accept the exported events directly
    assert [r["label"] for r in span_tree(obj["traceEvents"])] \
        == ["outer"]
    assert {s["label"] for s in span_summary(obj["traceEvents"])} \
        == {"outer", "inner"}


def test_load_trace_rejects_non_trace(tmp_path):
    path = os.path.join(tmp_path, "not_a_trace.json")
    with open(path, "w") as fh:
        json.dump({"hello": 1}, fh)
    with pytest.raises(ValueError):
        load_trace(path)


def test_chrome_trace_includes_other_event_kinds():
    with obs.run_trace("kinds") as tr:
        profiling.record_event("degradation", label="chunk:0",
                               rung="retry")
    obj = chrome_trace(tr)
    inst = [e for e in obj["traceEvents"]
            if e["ph"] == "i" and e.get("cat") == "degradation"]
    assert len(inst) == 1 and inst[0]["args"]["label"] == "chunk:0"


def test_run_manifest_lists_set_knobs(monkeypatch):
    # Spelled by concatenation so PCL006 (which scans tests too) does
    # not see an unregistered env-key literal.
    knob = "PYCATKIN_" + "OBS_TEST_ONLY_KNOB"
    monkeypatch.setenv(knob, "42")
    man = run_manifest()
    assert man["schema"] == "pycatkin-run-manifest/v1"
    assert man["env"][knob] == "42"
    assert set(man["env"]) == {k for k in os.environ
                               if k.startswith("PYCATKIN_")}
    # the PCL006 registry rides along so a reader can diff set-vs-known
    assert "PYCATKIN_ABI" in man["registered_env_keys"]
    assert "PYCATKIN_TRACE" in man["registered_env_keys"]
    # aot-key version pins cache compatibility
    assert man["aot_key_version"] is not None


def test_run_manifest_is_json_serializable(problem):
    from pycatkin_tpu.parallel.batch import make_mesh
    spec, _, _ = problem
    man = run_manifest(mesh=make_mesh(), spec=spec)
    text = json.dumps(man)
    assert json.loads(text) == man
    assert man["mesh"]["devices"] >= 1
