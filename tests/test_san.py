"""pcsan selftests: every tripwire must FIRE on an injected violation
and stay SILENT on the sanctioned idiom.

Each sanitizer guards a contract the suite already tests from the
positive side (zero-compile rate, sync budget, non-blocking serve
loop); these tests prove the negative side -- that when the contract
breaks, the sanitizer actually raises, at the right seam, naming the
culprit. `make test-san` re-runs the undisturbed suites under
``PYCATKIN_SAN=1`` on top of this file.
"""

import asyncio
import time

import numpy as np
import pytest

from pycatkin_tpu import engine, san
from pycatkin_tpu.lint.hotpath import MAX_CLEAN_SYNCS
from pycatkin_tpu.models.synthetic import synthetic_system
from pycatkin_tpu.parallel.batch import (broadcast_conditions,
                                         sweep_steady_state)
from pycatkin_tpu.san import (RecompileSanError, StallSanError,
                              SyncSanError, recompile, stall, syncs)
from pycatkin_tpu.utils import profiling

pytestmark = pytest.mark.san


def test_enabled_parses_env(monkeypatch):
    monkeypatch.delenv(san.ENV, raising=False)
    assert not san.enabled()
    for v in ("1", "on", "true", "YES"):
        monkeypatch.setenv(san.ENV, v)
        assert san.enabled()
    monkeypatch.setenv(san.ENV, "0")
    assert not san.enabled()


# ---------------------------------------------------- recompile sanitizer

@pytest.fixture
def recompile_armed():
    """Activate the recompile sanitizer for one test, from cold, and
    leave NOTHING armed afterwards (the state is process-global)."""
    recompile.reset()
    recompile.activate()
    yield
    recompile.deactivate()
    recompile.reset()


def test_note_compile_trips_only_when_warm(recompile_armed):
    recompile.note_compile("unit compile")        # cold: recording phase
    recompile.mark_warm()
    with pytest.raises(RecompileSanError, match="fresh XLA compile"):
        recompile.note_compile("unit compile")


def test_recompile_sanitizer_trips_on_cold_key_after_warm(
        recompile_armed):
    """The injected violation of the zero-compile contract: warm the
    cell at 8 lanes, then dispatch 16 -- a never-seen program key on a
    warm cell. The error must name the operand that churned the key."""
    sim = synthetic_system(n_species=8, n_reactions=10)
    spec = sim.spec
    mask = engine.tof_mask_for(spec, [spec.rnames[-1]])
    conds8 = broadcast_conditions(sim.conditions(), 8)

    sweep_steady_state(spec, conds8, tof_mask=mask)   # cold: records
    recompile.mark_warm()
    sweep_steady_state(spec, conds8, tof_mask=mask)   # warm replay: clean

    conds16 = broadcast_conditions(sim.conditions(), 16)
    with pytest.raises(RecompileSanError) as exc:
        sweep_steady_state(spec, conds16, tof_mask=mask)
    msg = str(exc.value)
    assert "mark_warm()" in msg
    # either seam is a correct catch: the dispatch key check names the
    # churned operand, the compile site names the program label
    assert ("churned the cache key" in msg
            or "fresh XLA compile" in msg), msg


def test_recompile_sanitizer_inactive_by_default():
    assert not recompile.is_active() or san.enabled()


# --------------------------------------------------------- sync sanitizer

def test_sync_sanitizer_trips_on_uncounted_asarray():
    import jax.numpy as jnp
    dev = jnp.arange(8.0)
    with syncs.strict(label="unit"):
        with pytest.raises(SyncSanError, match=r"np\.asarray"):
            np.asarray(dev)


def test_sync_sanitizer_trips_on_device_get():
    import jax
    import jax.numpy as jnp
    dev = jnp.arange(4.0)
    with syncs.strict(label="unit"):
        with pytest.raises(SyncSanError, match="device_get"):
            jax.device_get(dev)


def test_sync_sanitizer_ignores_host_values():
    with syncs.strict(label="unit"):
        assert np.asarray([1.0, 2.0]).shape == (2,)
        assert np.array(3.5) == 3.5


def test_sync_sanitizer_passive_outside_region():
    import jax.numpy as jnp
    syncs.install()
    # no strict region: the patched seams forward untouched
    assert np.asarray(jnp.arange(3.0)).shape == (3,)


def test_counted_choke_point_passes_strict(monkeypatch):
    import jax.numpy as jnp
    monkeypatch.setenv(san.ENV, "1")
    profiling.reset_sync_count()
    with syncs.strict(budget=2, label="unit") as region:
        v = profiling.host_sync(jnp.arange(8.0), "unit pull")
    assert isinstance(v, np.ndarray) and v.shape == (8,)
    assert region["count"] == 1 and region["labels"] == ["unit pull"]
    profiling.reset_sync_count()


def test_sync_sanitizer_budget_trips_at_choke_point(monkeypatch):
    import jax.numpy as jnp
    monkeypatch.setenv(san.ENV, "1")
    profiling.reset_sync_count()
    with syncs.strict(budget=2, label="unit"):
        profiling.host_sync(jnp.arange(2.0), "first")
        profiling.host_sync(jnp.arange(2.0), "second")
        with pytest.raises(SyncSanError, match="budget of 2"):
            profiling.host_sync(jnp.arange(2.0), "third")
    profiling.reset_sync_count()


def test_clean_sweep_passes_strict_region(monkeypatch):
    """The positive contract under the runtime teeth: a warm clean
    sweep runs inside a strict region at the documented budget without
    tripping -- the same gate ``bench.py --smoke`` reports as
    ``san_ok``."""
    import jax.numpy as jnp                        # noqa: F401
    monkeypatch.setenv(san.ENV, "1")
    sim = synthetic_system(n_species=8, n_reactions=10)
    spec = sim.spec
    mask = engine.tof_mask_for(spec, [spec.rnames[-1]])
    conds = broadcast_conditions(sim.conditions(), 8)
    sweep_steady_state(spec, conds, tof_mask=mask)     # warm, unguarded
    profiling.reset_sync_count()
    with syncs.strict(budget=MAX_CLEAN_SYNCS, label="clean sweep"):
        out = sweep_steady_state(spec, conds, tof_mask=mask)
    assert bool(np.all(np.asarray(out["success"])))
    profiling.reset_sync_count()


# --------------------------------------------------- stall sanitizer

def test_stall_threshold_env(monkeypatch):
    monkeypatch.setenv(stall.STALL_ENV, "0.5")
    assert stall.threshold_s() == 0.5
    monkeypatch.setenv(stall.STALL_ENV, "bogus")
    assert stall.threshold_s() == stall._DEFAULT_STALL_S


def test_stall_sanitizer_trips_on_blocking_callback():
    async def main():
        await stall.arm(0.05)
        loop = asyncio.get_running_loop()
        loop.call_soon(time.sleep, 0.2)       # the injected stall
        await asyncio.sleep(0.3)

    with pytest.raises(StallSanError, match="held the serve loop"):
        with stall.watchdog():
            asyncio.run(main())


def test_stall_sanitizer_clean_loop_passes():
    async def main():
        await stall.arm(0.05)
        for _ in range(3):
            await asyncio.sleep(0.01)

    with stall.watchdog() as handler:
        asyncio.run(main())
    assert handler.stalls == []
