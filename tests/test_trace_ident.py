"""pckey dynamic half: the jaxpr trace-identity sanitizer.

The acceptance tripwire (ISSUE 19): two distinct jaxprs forced under
one program key must raise ``TraceIdentSanError`` AT the compile site
while the sanitizer is armed (``PYCATKIN_SAN=1`` arms it globally;
these tests arm it per-test). Knob-duplicate traces are counted, not
raised. Fingerprints ride along in AOT cache entries and pack
manifests and are re-verified on import.
"""

from __future__ import annotations

import json
import pickle
import tarfile

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pycatkin_tpu.parallel import compile_pool
from pycatkin_tpu.san import TraceIdentSanError, trace_ident


@pytest.fixture(autouse=True)
def armed():
    trace_ident.reset()
    trace_ident.activate()
    yield
    trace_ident.deactivate()
    trace_ident.reset()


def _f_double(x):
    return x * 2.0


def _f_square(x):
    return x * x


X = jnp.arange(4.0)


def test_inactive_is_noop():
    trace_ident.deactivate()
    trace_ident.note_jaxpr("k", "key0", _f_double, (X,), force=True)
    assert trace_ident.stats()["programs"] == 0
    assert trace_ident.fingerprint_for("key0") is None
    assert trace_ident.entry_fields("key0") == {}


def test_fingerprint_is_stable_and_distinguishes_programs():
    fp1 = trace_ident.fingerprint(_f_double, (X,))
    fp2 = trace_ident.fingerprint(_f_double, (X,))
    fp3 = trace_ident.fingerprint(_f_square, (X,))
    assert fp1 == fp2
    assert fp1 != fp3
    assert len(fp1) == 32 and int(fp1, 16) >= 0


def test_injected_collision_raises_at_compile_site():
    """THE tripwire: one key, two jaxprs, armed sanitizer -> hard error
    at the second (force=True, i.e. compile-site) observation."""
    trace_ident.note_jaxpr("steady:a", "keyC", _f_double, (X,),
                           force=True)
    with pytest.raises(TraceIdentSanError, match="DIFFERENT jaxpr"):
        trace_ident.note_jaxpr("steady:a", "keyC", _f_square, (X,),
                               force=True)
    st = trace_ident.stats()
    assert st["collisions"] == 1
    # the original binding survives the error
    assert trace_ident.fingerprint_for("keyC") == \
        trace_ident.fingerprint(_f_double, (X,))


def test_same_jaxpr_under_same_key_is_fine():
    for _ in range(3):
        trace_ident.note_jaxpr("steady:a", "keyS", _f_double, (X,),
                               force=True)
    st = trace_ident.stats()
    assert st["programs"] == 1 and st["collisions"] == 0


def test_seen_key_skips_retrace_unless_forced():
    trace_ident.note_jaxpr("steady:a", "keyR", fp="a" * 32)

    def _explodes(x):
        raise RuntimeError("must not be traced on the dispatch seam")

    # dispatch seam (not forced): already-seen key returns untraced
    trace_ident.note_jaxpr("steady:a", "keyR", _explodes, (X,))
    assert trace_ident.stats()["trace_failures"] == 0
    # compile site (forced): retraces; the failure is counted, not
    # raised -- the sanitizer never takes down a working dispatch
    trace_ident.note_jaxpr("steady:a", "keyR", _explodes, (X,),
                           force=True)
    assert trace_ident.stats()["trace_failures"] == 1
    assert trace_ident.fingerprint_for("keyR") == "a" * 32


def test_knob_duplicates_counted_not_raised():
    fp = "d" * 32
    # same stripped base kind, keys differing only in grammar tags
    trace_ident.note_jaxpr("steady:opts:cpu", "keyA", fp=fp)
    trace_ident.note_jaxpr("steady:opts:cpu:p32", "keyB", fp=fp)
    # same fingerprint but a genuinely different base kind: not bloat
    trace_ident.note_jaxpr("jac:other", "keyD", fp=fp)
    groups = trace_ident.duplicate_groups()
    assert len(groups) == 1
    st = trace_ident.stats()
    assert st["collisions"] == 0
    assert st["duplicate_groups"] == 1
    assert st["duplicate_keys"] == 3
    assert st["programs"] == 3 and st["fingerprints"] == 1


def test_entry_fields_round_trip():
    trace_ident.note_jaxpr("steady:a:p32", "keyE", _f_double, (X,),
                           force=True)
    fields = trace_ident.entry_fields("keyE")
    assert fields == {
        "trace_ident": trace_ident.fingerprint(_f_double, (X,)),
        "kind": "steady:a:p32",
    }


def _saved_cache(tmp_path):
    """A one-entry AOT cache written while the sanitizer was armed."""
    f = jax.jit(_f_double)
    compiled = f.lower(X).compile()
    key = compile_pool.program_key("test:ident", (X,))
    trace_ident.note_jaxpr("test:ident", key, _f_double, (X,),
                           force=True)
    cache = compile_pool.AOTCache(root=str(tmp_path / "aot"),
                                  fingerprint="fp0")
    assert cache.save(key, compiled)
    return key, cache


def test_aot_entry_carries_trace_ident(tmp_path):
    key, cache = _saved_cache(tmp_path)
    with open(cache._path(key), "rb") as fh:
        entry = pickle.load(fh)
    assert entry["trace_ident"] == trace_ident.fingerprint_for(key)
    assert entry["kind"] == "test:ident"


def test_pack_manifest_carries_and_import_verifies(tmp_path):
    key, cache = _saved_cache(tmp_path)
    pack = str(tmp_path / "pack.tgz")
    compile_pool.export_cache_pack(pack, cache_root=cache.root)
    with tarfile.open(pack, "r:gz") as tf:
        manifest = json.load(tf.extractfile("manifest.json"))
    meta = manifest["entries"][key]
    assert meta["trace_ident"] == trace_ident.fingerprint_for(key)
    assert meta["kind"] == "test:ident"

    # clean import replays the fingerprint through the sanitizer: OK
    stats = compile_pool.import_cache_pack(
        pack, cache_root=str(tmp_path / "in1"))
    assert stats["imported"] == 1
    assert trace_ident.stats()["collisions"] == 0

    # a pack whose fingerprint contradicts the locally-observed trace
    # for the same key must trip the sanitizer on import
    trace_ident.reset()
    trace_ident.note_jaxpr("test:ident", key, _f_square, (X,),
                           force=True)
    with pytest.raises(TraceIdentSanError):
        compile_pool.import_cache_pack(
            pack, cache_root=str(tmp_path / "in2"))


def test_install_arms_trace_ident(monkeypatch):
    import pycatkin_tpu.san as san

    trace_ident.deactivate()
    monkeypatch.setenv("PYCATKIN_SAN", "1")
    san.install()
    assert trace_ident.is_active()


@pytest.mark.slow
def test_real_sweep_records_no_collisions():
    from pycatkin_tpu.models.synthetic import synthetic_system
    from pycatkin_tpu.parallel import batch

    sim = synthetic_system(n_species=8, n_reactions=10)
    conds = batch.broadcast_conditions(sim.conditions(), 4)
    batch.sweep_steady_state(sim.spec, conds)
    st = trace_ident.stats()
    assert st["programs"] >= 1
    assert st["collisions"] == 0
    assert st["trace_failures"] == 0
