"""Input-validation gate (frontend/validate.py): report structure,
strict/warn/off semantics, loader wiring and contextual loader errors.

Marker ``validate`` (in the default `not slow` selection; run alone
with `make test-validate`).
"""

import json

import pytest

import pycatkin_tpu as pk
from pycatkin_tpu.api.system import System
from pycatkin_tpu.frontend.reactions import UserDefinedReaction
from pycatkin_tpu.frontend.states import State
from pycatkin_tpu.frontend.validate import (ValidationError,
                                            validate_system,
                                            validation_mode)
from pycatkin_tpu.models.reactor import InfiniteDilutionReactor

pytestmark = pytest.mark.validate


def _bad_site_balance_system():
    """s* -> 2 sA*: occupies 1 surface site on the left, 2 on the
    right."""
    s = State(name="s", state_type="surface")
    sa = State(name="sa", state_type="adsorbate")
    rx = UserDefinedReaction(name="bad", reac_type="arrhenius",
                             reactants=[s], products=[sa, sa],
                             dGrxn_user=-0.4, dGa_fwd_user=0.7)
    sim = System(start_state={"s": 1.0}, T=500.0, p=1.0e5)
    sim.add_state(s)
    sim.add_state(sa)
    sim.add_reaction(rx)
    sim.add_reactor(InfiniteDilutionReactor())
    return sim


def _gas(name, mass):
    return State(name=name, state_type="gas", sigma=1, mass=mass)


def test_report_names_exact_reaction():
    report = validate_system(_bad_site_balance_system())
    assert not report.ok
    locs = [i.location for i in report.errors]
    assert "/reactions/bad" in locs
    msg = str(report)
    assert "surface-site imbalance" in msg and "'sa'" in msg


def test_build_strict_raises_with_report():
    sim = _bad_site_balance_system()
    with pytest.raises(ValidationError) as ei:
        sim.build(strict=True)
    assert "/reactions/bad" in str(ei.value)
    assert not ei.value.report.ok


def test_mass_imbalance_error():
    a, b = _gas("A", 28.0), _gas("B", 16.0)
    rx = UserDefinedReaction(name="iso", reac_type="arrhenius",
                             reactants=[a], products=[b],
                             dGrxn_user=0.1, dGa_fwd_user=0.5)
    sim = System(start_state={"s": 1.0}, T=500.0, p=1.0e5)
    sim.add_state(State(name="s", state_type="surface"))
    sim.add_state(a)
    sim.add_state(b)
    sim.add_reaction(rx)
    sim.add_reactor(InfiniteDilutionReactor())
    report = validate_system(sim)
    assert any(i.location == "/reactions/iso"
               and "mass imbalance" in i.message for i in report.errors)


def test_nonfinite_energy_error_names_state():
    sim = _bad_site_balance_system()
    sim.add_state(State(name="x", state_type="adsorbate",
                        freq=[1.0e13], Gelec=float("nan")))
    report = validate_system(sim)
    assert any(i.location == "/states/x/Gelec"
               and "non-finite" in i.message for i in report.errors)


def test_warn_mode_warns_instead_of_raising():
    report = validate_system(_bad_site_balance_system())
    with pytest.warns(UserWarning, match="/reactions/bad"):
        report.emit("warn")


def test_off_mode_is_silent(recwarn):
    report = validate_system(_bad_site_balance_system())
    report.emit("off")
    assert not [w for w in recwarn
                if issubclass(w.category, UserWarning)]


def test_validation_mode_env(monkeypatch):
    monkeypatch.delenv("PYCATKIN_VALIDATE", raising=False)
    assert validation_mode() == "warn"
    monkeypatch.setenv("PYCATKIN_VALIDATE", "STRICT")
    assert validation_mode() == "strict"
    monkeypatch.setenv("PYCATKIN_VALIDATE", "sometimes")
    with pytest.raises(ValueError, match="PYCATKIN_VALIDATE"):
        validation_mode()


def test_build_env_override(monkeypatch):
    monkeypatch.setenv("PYCATKIN_VALIDATE", "strict")
    with pytest.raises(ValidationError):
        _bad_site_balance_system().build()
    monkeypatch.setenv("PYCATKIN_VALIDATE", "off")
    _bad_site_balance_system().build()    # gate skipped


# ---- loader wiring + contextual error messages -----------------------

_VALID_INPUT = {
    "states": {
        "s": {"state_type": "surface"},
        "sA": {"state_type": "adsorbate", "freq": [1.0e13]},
        "A": {"state_type": "gas", "sigma": 1, "mass": 28.0,
              "Gelec": 0.0},
    },
    "system": {"p": 1.0e5, "T": 500.0, "times": [0.0, 1.0],
               "start_state": {"s": 1.0}},
    "manual reactions": {
        "ads": {"reac_type": "adsorption", "area": 1.0e-19,
                "reactants": ["A", "s"], "products": ["sA"],
                "dGrxn_user": -0.5, "dGa_fwd_user": 0.1},
    },
    "reactor": "InfiniteDilutionReactor",
}


def _write_input(tmp_path, cfg):
    path = str(tmp_path / "input.json")
    with open(path, "w") as fh:
        fh.write(json.dumps(cfg))
    return path


def test_loader_valid_input_loads(tmp_path):
    sim = pk.read_from_input_file(_write_input(tmp_path, _VALID_INPUT))
    assert set(sim.reactions) == {"ads"}


def test_loader_unknown_state_names_file_and_key(tmp_path):
    cfg = json.loads(json.dumps(_VALID_INPUT))
    cfg["manual reactions"]["ads"]["products"] = ["sB"]
    path = _write_input(tmp_path, cfg)
    with pytest.raises(KeyError) as ei:
        pk.read_from_input_file(path)
    msg = str(ei.value)
    assert path in msg
    assert "/manual reactions/ads/products" in msg and "'sB'" in msg


def test_loader_missing_pressure_names_key(tmp_path):
    cfg = json.loads(json.dumps(_VALID_INPUT))
    del cfg["system"]["p"]
    path = _write_input(tmp_path, cfg)
    with pytest.raises(KeyError, match="/system/p"):
        pk.read_from_input_file(path)


def test_loader_nongas_inflow_names_state(tmp_path):
    cfg = json.loads(json.dumps(_VALID_INPUT))
    cfg["system"]["inflow_state"] = {"sA": 1.0}
    path = _write_input(tmp_path, cfg)
    with pytest.raises(TypeError,
                       match="/system/inflow_state/sA"):
        pk.read_from_input_file(path)


def test_loader_nan_energy_strict_vs_warn(tmp_path, monkeypatch):
    # python's json parser accepts the NaN literal a crashed writer
    # can leave behind.
    cfg = json.loads(json.dumps(_VALID_INPUT))
    path = _write_input(tmp_path, cfg)
    with open(path) as fh:
        text = fh.read().replace('"Gelec": 0.0', '"Gelec": NaN')
    with open(path, "w") as fh:
        fh.write(text)

    monkeypatch.setenv("PYCATKIN_VALIDATE", "strict")
    with pytest.raises(ValidationError) as ei:
        pk.read_from_input_file(path)
    assert "/states/A/Gelec" in str(ei.value)
    assert path in str(ei.value)

    monkeypatch.setenv("PYCATKIN_VALIDATE", "warn")
    with pytest.warns(UserWarning, match="/states/A/Gelec"):
        sim = pk.read_from_input_file(path)
    assert set(sim.reactions) == {"ads"}
