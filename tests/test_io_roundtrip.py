"""Checkpoint round-trips: JSON system serialization, .dat caches, npz
results, and the profiling harness."""

import os

import numpy as np
import pytest

import pycatkin_tpu as pk
from pycatkin_tpu.utils import (load_results, run_timed, save_results,
                                save_state_energy, save_state_vibrations,
                                save_system_json)
from tests.conftest import reference_path


@pytest.fixture(scope="module")
def volcano(ref_root):
    return pk.read_from_input_file(
        reference_path("examples", "COOxVolcano", "input.json"))


def test_system_json_roundtrip_volcano(volcano, tmp_path):
    """Serialize -> reload -> identical physics (the pickle replacement:
    reference state.py:24-29 etc.). Activity reproduces the golden value
    through the checkpoint."""
    from tests.test_golden_volcano import set_descriptors
    path = str(tmp_path / "volcano_ckpt.json")
    save_system_json(volcano, path)
    sim2 = pk.read_from_input_file(path)
    assert sorted(sim2.snames) == sorted(volcano.snames)
    assert set(sim2.reactions) == set(volcano.reactions)
    set_descriptors(sim2, -1.0, -1.0)
    assert sim2.activity(tof_terms=["CO_ox"]) == pytest.approx(-1.563,
                                                               abs=1e-3)


def test_system_json_roundtrip_dmtm(ref_root, tmp_path):
    """DMTM round-trip inlines the .dat-sourced energies/frequencies so
    the checkpoint is self-contained (no data tree needed)."""
    sim = pk.read_from_input_file(
        reference_path("examples", "DMTM", "input.json"))
    fe1 = sim.free_energy_table(T=600.0)
    path = str(tmp_path / "dmtm_ckpt.json")
    save_system_json(sim, path)
    sim2 = pk.read_from_input_file(path)
    fe2 = sim2.free_energy_table(T=600.0)
    i1 = np.argsort(sim.snames)
    i2 = np.argsort(sim2.snames)
    np.testing.assert_allclose(np.asarray(fe1.gfree)[i1],
                               np.asarray(fe2.gfree)[i2], atol=1e-10)


def test_roundtrip_user_defined_donor_base(tmp_path):
    """A derived reaction whose donor base is a UserDefinedReaction
    round-trips: the checkpoint inlines the donor under 'base reactions'
    and the loader reconstitutes it with its user energies."""
    from pycatkin_tpu.api.system import System
    from pycatkin_tpu.frontend.reactions import (ReactionDerivedReaction,
                                                 UserDefinedReaction)
    from pycatkin_tpu.frontend.states import State
    from pycatkin_tpu.models.reactor import InfiniteDilutionReactor

    # Donor lives outside the system (foreign states + user energies).
    d_s = State(name="ds", state_type="surface")
    d_sa = State(name="dsa", state_type="adsorbate")
    base = UserDefinedReaction(name="b1", reac_type="arrhenius",
                               reactants=[d_s], products=[d_sa],
                               dGrxn_user=-0.4, dGa_fwd_user=0.7)
    s = State(name="s", state_type="surface")
    sa = State(name="sa", state_type="adsorbate")
    rx = ReactionDerivedReaction(name="r1", reac_type="arrhenius",
                                 reactants=[s], products=[sa],
                                 base_reaction=base)
    sim = System(start_state={"s": 1.0}, T=500.0, p=1.0e5)
    sim.add_state(s)
    sim.add_state(sa)
    sim.add_reaction(rx)
    sim.add_reactor(InfiniteDilutionReactor())
    kf1, kr1, _ = sim.rate_constant_table()

    path = str(tmp_path / "udr_base_ckpt.json")
    save_system_json(sim, path)
    sim2 = pk.read_from_input_file(path)
    assert isinstance(sim2.reactions["r1"].base_reaction,
                      UserDefinedReaction)
    kf2, kr2, _ = sim2.rate_constant_table()
    np.testing.assert_allclose(kf2, kf1, rtol=1e-10)
    np.testing.assert_allclose(kr2, kr1, rtol=1e-10)


def test_state_dat_roundtrip(volcano, tmp_path):
    from pycatkin_tpu.frontend import parsers
    from pycatkin_tpu.frontend.states import State
    st = State(name="x", state_type="adsorbate",
               freq=[2.0e13, 1.0e13], i_freq=[5.0e12], Gelec=-1.25)
    epath = str(tmp_path / "x_energy.dat")
    vpath = str(tmp_path / "x_frequencies.dat")
    save_state_energy(st, epath)
    save_state_vibrations(st, vpath)
    assert parsers.read_energy_dat(epath) == pytest.approx(-1.25)
    freq, i_freq = parsers.read_frequency_dat(vpath)
    np.testing.assert_allclose(sorted(freq), [1.0e13, 2.0e13])
    np.testing.assert_allclose(i_freq, [5.0e12])


def test_results_npz_roundtrip(tmp_path):
    path = str(tmp_path / "grid.npz")
    save_results(path, activity=np.arange(6.0).reshape(2, 3),
                 success=np.array([True, False]))
    data = load_results(path)
    np.testing.assert_allclose(data["activity"],
                               np.arange(6.0).reshape(2, 3))
    assert data["success"].dtype == bool


def test_run_timed_blocks():
    import jax.numpy as jnp

    def f(x):
        return jnp.sum(x * x)

    result, seconds = run_timed(f, jnp.arange(1000.0), repeats=2)
    assert float(result) == pytest.approx(sum(i * i for i in range(1000)))
    assert seconds >= 0.0


def test_save_pdb_structures(ref_root, tmp_path):
    """Native .pdb export from OUTCAR structure data (reference
    state.py:413-434 / test_3.py saves Pd111 states as pdb)."""
    import pycatkin_tpu as pk
    from pycatkin_tpu.api.presets import save_structures
    from tests.conftest import reference_path

    sim = pk.read_from_input_file(
        reference_path("examples", "COOxReactor", "input_Pd111.json"))
    written = save_structures(sim, fig_path=str(tmp_path))
    assert written, "no structures exported"
    name, fname = next(iter(written.items()))
    text = open(fname).read()
    assert text.startswith("TITLE")
    assert "HETATM" in text and text.rstrip().endswith("END")
    # CO gas: two atoms, carbon + oxygen
    if "CO" in written:
        co = open(written["CO"]).read().splitlines()
        atoms = [ln for ln in co if ln.startswith("HETATM")]
        assert len(atoms) == 2
    # Headless .png render next to every .pdb (reference view_atoms
    # image export, state.py:444-463).
    for name, fname in written.items():
        png = fname[:-4] + ".png"
        assert os.path.isfile(png), f"missing render {png}"
        with open(png, "rb") as fh:
            assert fh.read(8) == b"\x89PNG\r\n\x1a\n"
