"""Restricted-unpickler hardening for the reference pickle converter.

tools/convert_reference_pickle.py loads untrusted reference pickles; a
module-root allowlist would be an arbitrary-code-execution hole
(``builtins.eval`` is one REDUCE opcode away). These tests pin the
exact-name allowlist: numpy array/scalar reconstruction and plain
builtin containers deserialize as themselves, everything else in the
guarded roots raises, and unknown third-party classes still shim to
inert attribute bags (the converter's whole design).
"""

import io
import os
import pickle
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

from convert_reference_pickle import (_RefUnpickler, convert,  # noqa: E402
                                      load_reference_pickle)


def _loads(raw: bytes):
    return _RefUnpickler(io.BytesIO(raw)).load()


@pytest.mark.parametrize("protocol", [2, pickle.HIGHEST_PROTOCOL])
def test_benign_numpy_payload_roundtrips(tmp_path, protocol):
    """Arrays, numpy scalars, dtypes and builtin containers survive
    both the legacy (reference-era) and current pickle protocols."""
    import collections
    payload = {"a": np.arange(5.0), "m": np.ones((2, 3), dtype=np.int32),
               "s": np.float64(3.5), "d": np.dtype("float32"),
               "od": collections.OrderedDict(x=1), "t": (1, [2.0], {3}),
               "b": b"raw"}
    p = tmp_path / "ref.pckl"
    with open(p, "wb") as fh:
        pickle.dump(payload, fh, protocol=protocol)
    got = load_reference_pickle(str(p))
    assert np.array_equal(got["a"], payload["a"])
    assert got["m"].dtype == np.int32
    assert got["s"] == 3.5 and got["d"] == np.dtype("float32")
    assert got["od"] == payload["od"] and got["t"] == payload["t"]
    assert got["b"] == b"raw"


def test_malicious_reduce_eval_raises(tmp_path):
    """The classic RCE gadget -- REDUCE on builtins.eval -- must raise,
    not execute."""

    class Evil:
        def __reduce__(self):
            return (eval, ("__import__('os').system('true')",))

    p = tmp_path / "evil.pckl"
    with open(p, "wb") as fh:
        pickle.dump(Evil(), fh)
    with pytest.raises(pickle.UnpicklingError, match="allowlist"):
        load_reference_pickle(str(p))


@pytest.mark.parametrize("gadget", ["eval", "exec", "getattr",
                                    "__import__", "compile", "open"])
def test_builtin_gadgets_rejected(gadget):
    raw = f"cbuiltins\n{gadget}\n.".encode()
    with pytest.raises(pickle.UnpicklingError):
        _loads(raw)


def test_numpy_non_reconstruction_names_rejected():
    """numpy is an allowed *root* but only the array-reconstruction
    names pass; arbitrary numpy callables (frombuffer, load with
    pickle, ...) are refused rather than resolved or silently
    shimmed (a shimmed numpy internal would corrupt array data)."""
    with pytest.raises(pickle.UnpicklingError):
        _loads(pickle.dumps(np.frombuffer))
    with pytest.raises(pickle.UnpicklingError):
        _loads(b"cnumpy\nload\n.")


def _reference_style_pickle(**attrs) -> bytes:
    """Pickle bytes of a fake ``pycatkin.classes.state.State`` instance
    (built in a throwaway module, exactly what a real reference pickle
    references by module path)."""
    import types

    modname = "pycatkin.classes.state"
    names = ["pycatkin", "pycatkin.classes", "pycatkin.classes.state"]
    State = type("State", (), {"__module__": modname})
    try:
        for nm in names:                 # parents too: pickle imports
            sys.modules[nm] = types.ModuleType(nm)
        sys.modules[modname].State = State
        obj = State()
        obj.__dict__.update(attrs)
        return pickle.dumps(obj)
    finally:
        for nm in names:
            sys.modules.pop(nm, None)


def test_unknown_modules_still_shim_to_inert_bags():
    """Reference/ASE classes (and even os.system smuggled under an
    unguarded root) deserialize as inert attribute bags: no import, no
    constructor, no call."""
    obj = _loads(_reference_style_pickle(name="CO"))
    assert type(obj).__name__ == "State"
    assert type(obj).__module__ == "pycatkin.classes.state"
    assert obj.name == "CO"
    assert "pycatkin.classes.state" not in sys.modules  # never imported

    # A callable smuggled from an unguarded module root builds an inert
    # instance instead of executing.
    raw = b"cos\nsystem\n(S'true'\ntR."
    obj = _loads(raw)
    assert type(obj).__name__ == "system"
    assert obj._shim_args == ("true",)


def test_shimmed_state_converts_to_json_snippet():
    """The conversion path still works end to end on a shimmed
    reference State pickle."""
    raw = _reference_style_pickle(name="CO", state_type="adsorbate",
                                  Gelec=-1.5, freq=[12.0, 34.0])
    doc = convert(_loads(raw))
    assert doc == {"states": {"CO": {"state_type": "adsorbate",
                                     "Gelec": -1.5,
                                     "freq": [12.0, 34.0]}}}
