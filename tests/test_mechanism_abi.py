"""Mechanism ABI: shape-bucketed traced-operand specs (PYCATKIN_ABI=1).

The ABI inverts the program zoo's identity: mechanism arrays ride into
every program as a leading traced operand pytree, zero-padded into a
static shape bucket, so ONE compiled executable serves every mechanism
that lands in the bucket. These tests pin the three contracts that make
the inversion safe:

1.  EQUIVALENCE -- the padded traced path computes the same physics as
    the legacy constant-folded path. The padding semantics are exact
    (rate constants are bitwise identical; pad reactions produce
    exactly-zero rates), and every verdict/count output of a sweep is
    bitwise identical. Continuous outputs are compared under a tight
    tolerance instead of bytes: XLA:CPU's GEMM K-blocking reassociates
    zero-padded contraction dimensions (measurable on a plain
    ``A @ B`` with padded K), which perturbs the jacfwd matmats inside
    Newton at the last-ulp level. See docs/mechanism_abi.md
    ("Bit-identity envelope") for the measured envelope.

2.  SHARING -- two different mechanisms in one bucket intern the SAME
    program-spec object and fingerprint, so the second one prewarns
    with zero fresh compiles.

3.  DIAGNOSTICS -- a mechanism that cannot fit any bucket raises an
    AbiBucketError carrying a ValidationReport, and the batch-layer
    gate falls back to the legacy path with a single warning.
"""

import numpy as np
import pytest

from pycatkin_tpu import engine
from pycatkin_tpu.frontend import abi
from pycatkin_tpu.frontend.validate import ValidationReport
from pycatkin_tpu.models.synthetic import synthetic_system
from pycatkin_tpu.parallel import compile_pool
from pycatkin_tpu.parallel.batch import (batch_transient,
                                         broadcast_conditions,
                                         clear_program_caches,
                                         prewarm_sweep_programs,
                                         sweep_steady_state)
from pycatkin_tpu.robustness.faults import FaultPlan, FaultSpec, fault_scope
from pycatkin_tpu.solvers.ode import ODEOptions

N_LANES = 32

# Outputs that must match BITWISE between the legacy and ABI paths:
# every verdict, count and diagnostic integer/bool lane array.
_FLOAT_TOL = dict(rtol=1e-4, atol=1e-8)


def _problem(n_species=16, n_reactions=24, seed=3, n=N_LANES):
    sim = synthetic_system(n_species=n_species, n_reactions=n_reactions,
                           seed=seed)
    spec = sim.spec
    conds = broadcast_conditions(sim.conditions(), n)
    conds = conds._replace(T=np.linspace(480.0, 620.0, n))
    mask = engine.tof_mask_for(spec, [spec.rnames[-1]])
    return spec, conds, mask, sim.solver_options()


def _assert_equivalent(ref: dict, out: dict, loose_lanes=()):
    """Verdicts/counts bitwise; floats to _FLOAT_TOL -- except on
    ``loose_lanes`` (fault-injected, rescued lanes), where both paths
    re-converge from *different* perturbed iterates and only agree to
    the solver's own tolerance, not component-wise to 1e-4."""
    assert sorted(ref.keys()) == sorted(out.keys())
    loose = np.zeros(0, dtype=bool)
    for k in sorted(ref.keys()):
        a, b = np.asarray(ref[k]), np.asarray(out[k])
        assert a.shape == b.shape, f"{k}: {a.shape} vs {b.shape}"
        assert a.dtype == b.dtype, k
        if a.dtype.kind in "biu":
            assert a.tobytes() == b.tobytes(), (
                f"verdict/count output {k!r} differs between the legacy "
                f"and ABI paths")
            continue
        if loose_lanes and a.ndim >= 1:
            if loose.shape != (a.shape[0],):
                loose = np.zeros(a.shape[0], dtype=bool)
                loose[list(loose_lanes)] = True
            np.testing.assert_allclose(b[~loose], a[~loose], err_msg=k,
                                       **_FLOAT_TOL)
            np.testing.assert_allclose(b[loose], a[loose], err_msg=k,
                                       rtol=5e-2, atol=1e-6)
        else:
            np.testing.assert_allclose(b, a, err_msg=k, **_FLOAT_TOL)


@pytest.fixture()
def abi_on(monkeypatch):
    monkeypatch.setenv(abi.ABI_ENV, "1")
    clear_program_caches()
    yield
    monkeypatch.delenv(abi.ABI_ENV, raising=False)
    clear_program_caches()


# ---------------------------------------------------------------------------
# 1. equivalence


def test_operand_padding_is_exact():
    """Rate constants through the bound TracedSpec are BITWISE those of
    the legacy spec on real slots, and exactly zero on pad reactions --
    the padding rules are no-ops, not approximations."""
    import jax

    spec, conds, _, _ = _problem()
    low = abi.lower_spec(spec)
    tspec = low.program_spec.bind(low.operands())
    cond = jax.tree_util.tree_map(lambda a: np.asarray(a)[0], conds)
    pcond = low.pad_conditions(cond)
    n_r = len(spec.rnames)

    ref = jax.jit(lambda c: engine.rate_constants(spec, c))(cond)
    got = jax.jit(lambda c: engine.rate_constants(tspec, c))(pcond)
    kf, kr = np.asarray(got[0]), np.asarray(got[1])
    assert np.asarray(ref[0]).tobytes() == kf[:n_r].tobytes()
    assert np.asarray(ref[1]).tobytes() == kr[:n_r].tobytes()
    # Ghost pad reactions carry EXACTLY zero rates in both directions.
    assert np.all(kf[n_r:] == 0.0) and np.all(kr[n_r:] == 0.0)
    assert np.all(np.isfinite(np.asarray(got[2])))


@pytest.mark.parametrize("dims", [(16, 24), (24, 32)],
                         ids=["padded-small", "synthetic"])
def test_sweep_equivalence_clean(dims, abi_on, monkeypatch):
    n_s, n_r = dims
    spec, conds, mask, opts = _problem(n_s, n_r)

    monkeypatch.delenv(abi.ABI_ENV, raising=False)
    ref = sweep_steady_state(spec, conds, tof_mask=mask, opts=opts,
                             check_stability=True)
    clear_program_caches()

    monkeypatch.setenv(abi.ABI_ENV, "1")
    out = sweep_steady_state(spec, conds, tof_mask=mask, opts=opts,
                             check_stability=True)
    # The gate restored the public composition width.
    assert np.asarray(out["y"]).shape == np.asarray(ref["y"]).shape
    _assert_equivalent(ref, out)


def test_sweep_equivalence_quarantine_and_rescue(abi_on, monkeypatch):
    """Fault-injected corpus: a NaN-poisoned solve lane forces the
    quarantine demotion + rescue ladder; the ABI path must walk the
    same ladder to the same verdicts."""
    spec, conds, mask, opts = _problem()
    plan = FaultPlan([FaultSpec(site="batched steady solve", kind="nan",
                                lanes=(7,), times=1)])

    monkeypatch.delenv(abi.ABI_ENV, raising=False)
    with fault_scope(plan):
        ref = sweep_steady_state(spec, conds, tof_mask=mask, opts=opts,
                                 check_stability=True)
    clear_program_caches()

    monkeypatch.setenv(abi.ABI_ENV, "1")
    plan2 = FaultPlan([FaultSpec(site="batched steady solve", kind="nan",
                                 lanes=(7,), times=1)])
    with fault_scope(plan2):
        out = sweep_steady_state(spec, conds, tof_mask=mask, opts=opts,
                                 check_stability=True)
    _assert_equivalent(ref, out, loose_lanes=(7,))


def test_batch_transient_equivalence(abi_on, monkeypatch):
    spec, conds, _, _ = _problem(n=8)
    save_ts = np.array([0.0, 1e-6, 1e-3, 1.0])
    opts = ODEOptions()

    monkeypatch.delenv(abi.ABI_ENV, raising=False)
    ys_ref, ok_ref = batch_transient(spec, conds, save_ts, opts=opts)
    clear_program_caches()

    monkeypatch.setenv(abi.ABI_ENV, "1")
    ys, ok = batch_transient(spec, conds, save_ts, opts=opts)
    assert np.asarray(ys).shape == np.asarray(ys_ref).shape
    assert np.asarray(ok).tobytes() == np.asarray(ok_ref).tobytes()
    np.testing.assert_allclose(np.asarray(ys), np.asarray(ys_ref),
                               **_FLOAT_TOL)


# ---------------------------------------------------------------------------
# 2. bucket sharing


def test_two_mechanisms_share_one_bucket(abi_on):
    """Different mechanisms, same bucket: interned program spec and
    cache identity are THE SAME OBJECT, and prewarming the second
    mechanism after the first performs zero fresh compiles."""
    sA, cA, mA, oA = _problem(16, 24, seed=3, n=16)
    sB, cB, mB, oB = _problem(17, 24, seed=7, n=16)

    lowA, lowB = abi.lower_spec(sA), abi.lower_spec(sB)
    assert lowA.program_spec is lowB.program_spec
    assert (compile_pool.spec_fingerprint(lowA)
            == compile_pool.spec_fingerprint(lowB))
    assert lowA.abi_fingerprint.startswith(f"abi-v{abi.ABI_VERSION}:")

    stats_a = prewarm_sweep_programs(sA, cA, tof_mask=mA, opts=oA,
                                     buckets=(), check_stability=True,
                                     cache=False)
    assert stats_a.compiled > 0
    stats_b = prewarm_sweep_programs(sB, cB, tof_mask=mB, opts=oB,
                                     buckets=(), check_stability=True,
                                     cache=False)
    assert stats_b.compiled == 0, (
        "second mechanism in a warm bucket must trigger ZERO compiles")
    assert int(stats_b) == int(stats_a)

    # And the warm zoo actually solves mechanism B.
    out = sweep_steady_state(sB, cB, tof_mask=mB, opts=oB,
                             check_stability=True)
    assert bool(np.all(np.asarray(out["success"])))


# ---------------------------------------------------------------------------
# 3. diagnostics / gating


def test_out_of_bucket_raises_validation_report():
    spec, _, _, _ = _problem()
    with pytest.raises(abi.AbiBucketError) as exc:
        spec.to_abi(species_bucket=16, reaction_bucket=16)
    err = exc.value
    assert isinstance(err.report, ValidationReport)
    assert err.report.errors
    locs = {i.location for i in err.report.errors}
    assert "/abi/species" in locs and "/abi/reactions" in locs
    assert "does not fit" in str(err)


def test_unfittable_mechanism_falls_back_with_warning(abi_on, monkeypatch):
    spec, conds, mask, opts = _problem(n=8)
    monkeypatch.setattr(abi, "SPECIES_BUCKETS", (4,))
    monkeypatch.setattr(abi, "_FALLBACK_WARNED", set())
    abi.clear_lowering_cache()
    with pytest.warns(UserWarning, match="does not fit any ABI bucket"):
        assert abi.maybe_lower(spec) is None
    # Second call is silent (warn once per spec) and the sweep still
    # solves through the legacy constant-folded path.
    assert abi.maybe_lower(spec) is None
    out = sweep_steady_state(spec, conds, tof_mask=mask, opts=opts)
    assert bool(np.all(np.asarray(out["success"])))
    abi.clear_lowering_cache()


def test_abi_off_means_no_lowering(monkeypatch):
    monkeypatch.delenv(abi.ABI_ENV, raising=False)
    spec, _, _, _ = _problem()
    assert abi.maybe_lower(spec) is None


def test_bucket_boundary_headroom_warning():
    from types import SimpleNamespace

    from pycatkin_tpu.frontend.validate import check_abi_headroom

    # Comfortably inside its bucket: clean report.
    spec, _, _, _ = _problem()
    assert not check_abi_headroom(spec).warnings
    # 124 species + the pad slot = 125 > 0.95 * 128: hugging the edge.
    near = SimpleNamespace(n_species=124, n_reactions=40)
    report = check_abi_headroom(near)
    assert [i.location for i in report.warnings] == ["/abi/species"]
    assert "128" in report.warnings[0].message
    # Both dims at the edge warn independently.
    near2 = SimpleNamespace(n_species=124, n_reactions=63)
    assert {i.location for i in check_abi_headroom(near2).warnings} == {
        "/abi/species", "/abi/reactions"}
