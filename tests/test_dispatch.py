"""DCN-tier dispatcher: multi-process sweep split + merge
(parallel/dispatch.py; SURVEY.md §5.8 outer parallelism tier).

The two-worker demo splits a small COOx volcano block across two
independent OS processes (each rebuilding the mechanism from the JSON
round-trip and running its own batched device program), merges the
.npz results, and checks the merge agrees lane-for-lane with the
single-process sweep -- plus grid triage running on the merged output.
"""

import numpy as np
import pytest

import pycatkin_tpu as pk
from pycatkin_tpu import engine
from pycatkin_tpu.models import coox
from pycatkin_tpu.parallel.batch import sweep_steady_state
from pycatkin_tpu.parallel.dispatch import (_split_slices, dispatch_sweep,
                                            load_conditions,
                                            save_conditions)
from tests.conftest import reference_path


def test_split_slices_cover_and_order():
    assert _split_slices(10, 3) == [(0, 3), (3, 6), (6, 10)]
    assert _split_slices(2, 4) == [(0, 1), (1, 2)]
    assert _split_slices(8, 2) == [(0, 4), (4, 8)]


def test_conditions_npz_roundtrip(ref_root, tmp_path):
    sim = pk.read_from_input_file(
        reference_path("examples", "COOxVolcano", "input.json"))
    be = np.linspace(-2.0, 0.0, 3)
    conds, _ = coox.volcano_grid_conditions(sim, be)
    path = str(tmp_path / "conds.npz")
    save_conditions(path, conds)
    back = load_conditions(path)
    for f in conds._fields:
        np.testing.assert_array_equal(np.asarray(getattr(conds, f)),
                                      np.asarray(getattr(back, f)))


@pytest.mark.slow
def test_two_process_dispatch_matches_in_process(ref_root, tmp_path):
    sim = pk.read_from_input_file(
        reference_path("examples", "COOxVolcano", "input.json"))
    be = np.linspace(-2.5, 0.5, 4)
    conds, shape = coox.volcano_grid_conditions(sim, be)

    merged = dispatch_sweep(
        sim, conds, n_workers=2, work_dir=str(tmp_path),
        tof_terms=["CO_ox"],
        worker_env={"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""})

    ref = sweep_steady_state(sim.spec, conds,
                             tof_mask=engine.tof_mask_for(sim.spec,
                                                          ["CO_ox"]))
    assert merged["y"].shape == np.asarray(ref["y"]).shape
    assert np.array_equal(merged["success"],
                          np.asarray(ref["success"]))
    np.testing.assert_allclose(merged["y"], np.asarray(ref["y"]),
                               rtol=1e-7, atol=1e-10)
    np.testing.assert_allclose(merged["activity"],
                               np.asarray(ref["activity"]),
                               rtol=1e-7, atol=1e-9)

    # grid triage runs on the merged output exactly as on in-process
    # results (the dispatcher is invisible downstream).
    from pycatkin_tpu.analysis.grid import average_neighborhood
    act = merged["activity"].reshape(shape)
    ok = merged["success"].reshape(shape)
    patched, patched_mask = average_neighborhood(act, ok)
    assert patched.shape == shape
    assert int(patched_mask.sum()) == int((~ok).sum())
