"""UQ tests: correlated-noise ensemble semantics + batched execution
(reference uncertainty.py behavior, test numbers are ours)."""

import numpy as np
import pytest

import pycatkin_tpu as pk
from pycatkin_tpu.analysis.uncertainty import Uncertainty
from pycatkin_tpu.frontend.states import ADSORBATE, TS
from tests.conftest import reference_path


@pytest.fixture(scope="module")
def volcano(ref_root):
    import tests.test_golden_volcano as gv
    sim = pk.read_from_input_file(
        reference_path("examples", "COOxVolcano", "input.json"))
    gv.set_descriptors(sim, -1.0, -1.0)
    return sim


def test_correlated_noise_structure(volcano):
    """All adsorbates share one Gaussian draw; every TS noise is that
    draw scaled by U(0,1) (reference uncertainty.py:34-65)."""
    uq = Uncertainty(sys=volcano, sigma=0.1, nruns=1, seed=3)
    noises = uq.get_correlated_state_noises()
    ads = {n: v for n, v in noises.items()
           if uq.sys.states[n].state_type == ADSORBATE}
    ts = {n: v for n, v in noises.items()
          if uq.sys.states[n].state_type == TS}
    assert len(set(ads.values())) == 1, "adsorbate noise must be shared"
    shared = next(iter(ads.values()))
    for v in ts.values():
        frac = v / shared
        assert 0.0 <= frac <= 1.0


def test_mean_property_value(ref_root):
    """Batched ensemble on DMTM (state-derived energetics, the
    reference's own UQ workload): base run is index 0 and noise-free;
    statistics exclude it; small noise gives TOF spread around the
    base."""
    sim = pk.read_from_input_file(
        reference_path("examples", "DMTM", "input.json"))
    uq = Uncertainty(sys=sim, sigma=0.02, nruns=6, seed=0)

    def activity(sys_view):
        from pycatkin_tpu import engine
        cond = sys_view.conditions()
        mask = engine.tof_mask_for(sys_view.spec, ["r5", "r9"])
        t = engine.tof(sys_view.spec, cond, sys_view.solution[-1], mask)
        return float(engine.activity_from_tof(t, cond.T))

    values, mean, std = uq.get_mean_property_value(activity)
    assert values.shape == (7,)
    assert np.all(np.isfinite(values))
    assert std > 0.0
    assert abs(mean - values[0]) < 0.5


def test_user_energy_network_insensitive_to_state_noise(volcano):
    """The COOx volcano's five reactions are all UserDefinedReactions:
    their energetics come from dErxn/dGrxn/dEa_user, NOT from state free
    energies, so state-energy noise must leave the ensemble exactly
    degenerate (same semantics as the reference, where
    set_energy_modifier never reaches UserDefinedReaction energies).
    Guards against noise leaking into user-energy channels."""
    uq = Uncertainty(sys=volcano, sigma=0.05, nruns=3, seed=0)

    def activity(sys_view):
        from pycatkin_tpu import engine
        cond = sys_view.conditions()
        mask = engine.tof_mask_for(sys_view.spec, ["CO_ox"])
        t = engine.tof(sys_view.spec, cond, sys_view.solution[-1], mask)
        return float(engine.activity_from_tof(t, cond.T))

    values, mean, std = uq.get_mean_property_value(activity)
    assert values[0] == pytest.approx(-1.563, abs=1e-3)  # golden base
    assert std == pytest.approx(0.0, abs=1e-12)


def test_noisy_views_carry_modifiers(volcano):
    uq = Uncertainty(sys=volcano, sigma=0.05, nruns=2, seed=1)
    uq.get_noisy_sys_samples()
    assert uq.noisy_sys[0].states["sCO"].add_to_energy in (None, 0.0)
    n1 = uq.state_noises[1]
    for name, val in n1.items():
        assert uq.noisy_sys[1].states[name].add_to_energy == \
            pytest.approx(val)
