"""Unit tests for the arithmetic-only dense LU kernels (ops/linalg.py).

These replace jnp.linalg.solve / jax.scipy lu_factor on TPU, where XLA
implements LuDecomposition only for F32/C64 and float64 is part of this
framework's numerical contract.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pycatkin_tpu.ops import linalg


@pytest.mark.parametrize("n", [1, 2, 3, 5, 20, 100])
def test_solve_matches_numpy(n):
    rng = np.random.default_rng(n)
    A = rng.standard_normal((n, n))
    b = rng.standard_normal(n)
    x = np.asarray(linalg.solve(jnp.asarray(A), jnp.asarray(b)))
    np.testing.assert_allclose(x, np.linalg.solve(A, b),
                               rtol=1e-10, atol=1e-12)


def test_solve_matrix_rhs():
    rng = np.random.default_rng(7)
    A = rng.standard_normal((10, 10))
    B = rng.standard_normal((10, 3))
    X = np.asarray(linalg.solve(jnp.asarray(A), jnp.asarray(B)))
    np.testing.assert_allclose(X, np.linalg.solve(A, B),
                               rtol=1e-10, atol=1e-12)


def test_solve_needs_pivoting():
    """Zero leading pivot: fails without partial pivoting."""
    A = np.array([[0.0, 1.0], [1.0, 0.0]])
    b = np.array([2.0, 3.0])
    x = np.asarray(linalg.solve(jnp.asarray(A), jnp.asarray(b)))
    np.testing.assert_allclose(x, [3.0, 2.0], rtol=1e-14)


def test_solve_stiff_row_scaling():
    """Rows scaled over ~25 decades (microkinetic Jacobian profile)."""
    rng = np.random.default_rng(3)
    A = np.diag(10.0 ** rng.uniform(-12, 12, size=30)) @ \
        rng.standard_normal((30, 30))
    b = rng.standard_normal(30)
    x = np.asarray(linalg.solve(jnp.asarray(A), jnp.asarray(b)))
    resid = np.max(np.abs(A @ x - b) / (np.abs(A) @ np.abs(x) + 1e-300))
    assert resid < 1e-12


def test_lu_solve_reuses_factorization():
    rng = np.random.default_rng(11)
    A = rng.standard_normal((8, 8))
    LU, perm = linalg.lu_factor(jnp.asarray(A))
    for i in range(3):
        b = rng.standard_normal(8)
        x = np.asarray(linalg.lu_solve(LU, perm, jnp.asarray(b)))
        np.testing.assert_allclose(x, np.linalg.solve(A, b),
                                   rtol=1e-10, atol=1e-12)


def test_solve_vmaps():
    rng = np.random.default_rng(13)
    A = rng.standard_normal((16, 6, 6))
    b = rng.standard_normal((16, 6))
    x = np.asarray(jax.vmap(linalg.solve)(jnp.asarray(A), jnp.asarray(b)))
    ref = np.linalg.solve(A, b[..., None])[..., 0]
    np.testing.assert_allclose(x, ref, rtol=1e-9, atol=1e-11)


@pytest.mark.parametrize(
    "n", [5, 48, 49, pytest.param(190, marks=pytest.mark.slow)])
def test_blocked_lu_matches_plain(n):
    """The statically-unrolled blocked factorization (kept as the
    reference implementation for a future Pallas panel kernel; not in
    the default dispatch -- TPU compile-time wall, see
    docs/perf_config5.md) reconstructs PA = LU to machine precision and
    its solves agree with the chunked kernels."""
    rng = np.random.default_rng(n)
    A = rng.standard_normal((n, n)) * np.exp(rng.uniform(-6, 6, (n, 1)))
    b = rng.standard_normal(n)
    LU, perm = linalg.lu_factor_blocked(jnp.asarray(A))
    LUn, permn = np.asarray(LU), np.asarray(perm)
    L = np.tril(LUn, -1) + np.eye(n)
    U = np.triu(LUn)
    rec = np.max(np.abs(L @ U - A[permn])) / np.max(np.abs(A))
    assert rec < 1e-13
    x = np.asarray(linalg.lu_solve_blocked(LU, perm, jnp.asarray(b)))
    r = np.max(np.abs(A @ x - b)) / np.max(np.abs(b))
    assert r < 1e-7
    x2 = np.asarray(linalg.lu_solve(LU, perm, jnp.asarray(b)))
    np.testing.assert_allclose(x, x2, rtol=1e-9, atol=1e-12)


def test_mixed_solve_accuracy():
    """Refined f32 factorization (make_mixed_solve) delivers ~f64-quality
    solutions for moderately conditioned systems, including severe ROW
    scaling (absorbed by equilibration). Its measured limits -- and why
    it is NOT the steady-solver direction kernel -- are recorded in
    docs/perf_config5.md §9."""
    rng = np.random.default_rng(11)
    for n in (49, 96):
        A = rng.standard_normal((n, n)) + 5.0 * np.eye(n)
        S = 10.0 ** rng.uniform(-14, 14, size=(n, 1))
        for M in (A, A * S):
            b = rng.standard_normal(n)
            x = np.asarray(linalg.make_mixed_solve(jnp.asarray(M))(
                jnp.asarray(b)))
            ref = np.linalg.solve(M, b)
            rel = np.max(np.abs(x - ref)) / np.max(np.abs(ref))
            assert rel < 1e-8, f"n={n} rel={rel:.2e}"


def test_mixed_solve_matrix_rhs():
    """Multi-RHS solves scale rows (not columns) of b -- the matrix-b
    convention every other solver in this module follows."""
    rng = np.random.default_rng(21)
    n = 60
    A = rng.standard_normal((n, n)) * 10.0 ** rng.uniform(-8, 8, (n, 1))
    B = rng.standard_normal((n, 3))
    X = np.asarray(linalg.make_mixed_solve(jnp.asarray(A))(jnp.asarray(B)))
    ref = np.linalg.solve(A, B)
    rel = np.max(np.abs(X - ref)) / np.max(np.abs(ref))
    assert rel < 1e-8
    # inverse via identity RHS (the docstring's stage-matrix use case);
    # judged RELATIVE to the true inverse -- an absolute A @ Inv - I
    # residual scales with ||A|| (~1e8 here) and measures nothing.
    Inv = np.asarray(linalg.make_mixed_solve(jnp.asarray(A))(
        jnp.eye(n)))
    ref = np.linalg.inv(A)
    rel = np.max(np.abs(Inv - ref)) / np.max(np.abs(ref))
    assert rel < 1e-8


def test_mixed_solve_batched_vmap():
    rng = np.random.default_rng(12)
    A = rng.standard_normal((8, 60, 60)) + 5.0 * np.eye(60)
    b = rng.standard_normal((8, 60))
    xs = np.asarray(jax.vmap(
        lambda M, r: linalg.make_mixed_solve(M)(r))(jnp.asarray(A),
                                                    jnp.asarray(b)))
    ref = np.linalg.solve(A, b[..., None])[..., 0]
    np.testing.assert_allclose(xs, ref, rtol=1e-6, atol=1e-9)
