"""Bucket-packed multi-tenant batching (docs/perf_packed_batching.md).

The acceptance contract: K mechanisms lowered into one ABI bucket run
as ONE packed device dispatch (one counted host sync, zero marginal
compiles in a warm bucket) and every tenant's results -- values,
verdicts, lane telemetry -- are BITWISE identical to that tenant's
solo ``sweep_steady_state`` run, across clean, rescue and poisoned
corpora and both precision tiers. A poisoned tenant escalates alone;
its co-tenants stay bit-identical to their solo runs.

Key compatibility is part of the contract: ``tenant_tag(1)`` is empty
and K=1 requests delegate to the solo path, so every pre-packing
program key / AOT entry / cache pack stays byte-identical.
"""

import os

import numpy as np
import pytest

from pycatkin_tpu import engine, precision
from pycatkin_tpu.frontend import abi
from pycatkin_tpu.models.synthetic import synthetic_system
from pycatkin_tpu.parallel import compile_pool
from pycatkin_tpu.parallel.batch import (broadcast_conditions,
                                         clear_program_caches,
                                         packed_sweep_steady_state,
                                         prewarm_packed_sweep_programs,
                                         sweep_steady_state)
from pycatkin_tpu.parallel.dispatch import SweepCoalescer, dispatch_sweep
from pycatkin_tpu.robustness import FaultPlan, FaultSpec, fault_scope
from pycatkin_tpu.solvers.newton import SolverOptions
from pycatkin_tpu.utils import profiling

N_LANES = 12
SEEDS = (0, 1, 2, 3)


def _tenant(seed, n=N_LANES):
    sim = synthetic_system(n_species=12, n_reactions=14, seed=seed)
    conds = broadcast_conditions(sim.conditions(), n)
    conds = conds._replace(T=np.linspace(430.0, 720.0, n))
    mask = engine.tof_mask_for(sim.spec, [sim.spec.rnames[-1]])
    return sim, conds, mask


# Programs cache by kind string (tier/tenant tags included), so tests
# may share compiled executables freely; clearing per test would re-pay
# the packed compile bill ~10 times over. Tests that COUNT compiles
# (the zero-marginal-compile gate) clear explicitly instead.
@pytest.fixture(scope="module", autouse=True)
def fresh_caches():
    clear_program_caches()
    yield
    clear_program_caches()


@pytest.fixture(autouse=True)
def abi_on(monkeypatch):
    monkeypatch.setenv(abi.ABI_ENV, "1")
    monkeypatch.delenv("PYCATKIN_FUSED_SWEEP", raising=False)
    monkeypatch.setenv("PYCATKIN_AOT_CACHE", "off")


def _assert_tenant_bitwise(solo, packed, context=""):
    assert sorted(solo) == sorted(packed), \
        f"{context}: result keys drifted"
    for key in solo:
        a, b = np.asarray(solo[key]), np.asarray(packed[key])
        assert a.dtype == b.dtype and a.shape == b.shape, \
            f"{context}: {key!r} dtype/shape drifted"
        assert a.tobytes() == b.tobytes(), \
            f"{context}: {key!r} not bit-identical to the solo run"


def _pack_vs_solo(tenants, check_stability=True,
                  opts=SolverOptions()):
    specs = [t[0].spec for t in tenants]
    conds = [t[1] for t in tenants]
    masks = [t[2] for t in tenants]
    solo = [sweep_steady_state(s, c, tof_mask=m, opts=opts,
                               check_stability=check_stability)
            for s, c, m in zip(specs, conds, masks)]
    packed = packed_sweep_steady_state(specs, conds, tof_mask=masks,
                                       opts=opts,
                                       check_stability=check_stability)
    return solo, packed


# ---------------------------------------------------------------------------
# 1. key compatibility: the :tK sub-bucket


def test_tenant_tag_contract():
    assert compile_pool.tenant_tag(1) == ""
    assert compile_pool.tenant_tag(0) == ""
    assert compile_pool.tenant_tag(2) == ":t2"
    assert compile_pool.tenant_tag(8) == ":t8"
    with pytest.raises(ValueError):
        compile_pool.tenant_tag(3)


def test_abi_entry_fields_split_tenant_tag():
    base = "abi-v1:s16:r16:d8:rt0:none"
    f = compile_pool.abi_entry_fields(base + ":t4")
    assert f["abi_bucket"] == "s16:r16:d8:rt0:none"
    assert f["abi_tenants"] == 4
    # Untagged (solo) fingerprints parse exactly as before.
    f1 = compile_pool.abi_entry_fields(base)
    assert f1["abi_bucket"] == "s16:r16:d8:rt0:none"
    assert "abi_tenants" not in f1


def test_pack_fingerprint_and_occupancy():
    lows = [abi.lower_spec(_tenant(s)[0].spec) for s in SEEDS[:3]]
    pack = abi.pack_lowered(lows)
    assert pack.k == 3 and pack.k_bucket == 4
    assert pack.occupancy == pytest.approx(0.75)
    assert pack.abi_fingerprint == lows[0].abi_fingerprint + ":t4"
    # Ghost slots replicate tenant 0's operands.
    for key, arr in pack._np_operands.items():
        assert arr.shape[0] == 4
        np.testing.assert_array_equal(arr[3], arr[0], err_msg=key)


def test_pack_rejects_mixed_buckets():
    small = abi.lower_spec(_tenant(0)[0].spec)
    big = abi.lower_spec(
        synthetic_system(n_species=40, n_reactions=80, seed=5).spec)
    assert small.program_spec is not big.program_spec
    with pytest.raises(abi.AbiBucketError):
        abi.pack_lowered([small, big])


def test_single_tenant_delegates_to_solo_path():
    sim, conds, mask = _tenant(0)
    solo = sweep_steady_state(sim.spec, conds, tof_mask=mask)
    outs = packed_sweep_steady_state([sim.spec], [conds],
                                     tof_mask=[mask])
    assert len(outs) == 1
    _assert_tenant_bitwise(solo, outs[0], "K=1 delegation")


# ---------------------------------------------------------------------------
# 2. per-tenant bit-identity, corpora x tiers


# The f32-polish variants, the rescue corpus and the escalation-path
# drills re-trace/re-compile the packed zoo and dominate this file's
# wall time, so they ride the slow tier; the dedicated packed CI lane
# runs the file with ``-m ""`` and covers them on every push.
@pytest.mark.parametrize(
    "tier", ["f64", pytest.param("f32-polish", marks=pytest.mark.slow)])
def test_clean_corpus_bit_identical(tier, monkeypatch):
    monkeypatch.setenv(precision.TIER_ENV, tier)
    tenants = [_tenant(s) for s in SEEDS]
    solo, packed = _pack_vs_solo(tenants)
    for k, (so, pa) in enumerate(zip(solo, packed)):
        assert bool(np.all(np.asarray(so["success"]))), \
            "clean corpus must converge solo"
        _assert_tenant_bitwise(so, pa, f"clean/{tier}/tenant{k}")


@pytest.mark.slow
@pytest.mark.parametrize("tier", ["f64", "f32-polish"])
def test_rescue_corpus_bit_identical(tier, monkeypatch):
    """Crippled pacing fails real fast-pass lanes; each tenant must
    walk the identical rescue ladder inside the pack."""
    monkeypatch.setenv(precision.TIER_ENV, tier)
    sims = [synthetic_system(n_species=24, n_reactions=32, seed=s)
            for s in SEEDS[:2]]
    tenants = []
    for sim in sims:
        conds = broadcast_conditions(sim.conditions(), N_LANES)
        conds = conds._replace(
            T=np.linspace(420.0, 780.0, N_LANES))
        mask = engine.tof_mask_for(sim.spec, [sim.spec.rnames[-1]])
        tenants.append((sim, conds, mask))
    opts = SolverOptions(max_steps=6, max_attempts=2)
    solo, packed = _pack_vs_solo(tenants, opts=opts)
    if tier == "f64":
        assert any(np.asarray(s["lane_telemetry"])[:, 3].max() >= 1
                   for s in solo), \
            "corpus exercised no rescue strategy -- drill premise broken"
    for k, (so, pa) in enumerate(zip(solo, packed)):
        _assert_tenant_bitwise(so, pa, f"rescue/{tier}/tenant{k}")


@pytest.mark.slow
def test_poisoned_tenant_isolated():
    """One tenant with NaN-poisoned conditions escalates through the
    failure tail; every OTHER tenant of the pack stays bit-identical
    to its solo run, and the poisoned tenant itself matches ITS solo
    escalation bit-for-bit."""
    tenants = [_tenant(s) for s in SEEDS]
    bad_T = np.asarray(tenants[1][1].T).copy()
    bad_T[3] = np.nan
    tenants[1] = (tenants[1][0], tenants[1][1]._replace(T=bad_T),
                  tenants[1][2])
    solo, packed = _pack_vs_solo(tenants)
    assert not bool(np.all(np.asarray(solo[1]["success"]))), \
        "poisoned tenant unexpectedly converged everywhere"
    for k, (so, pa) in enumerate(zip(solo, packed)):
        _assert_tenant_bitwise(so, pa, f"poisoned/tenant{k}")


@pytest.mark.slow
def test_fault_plan_degrades_to_solo_sweeps():
    """Fault containment stays per-site: an active fault plan disables
    the fused tail, so the packed API must degrade to per-tenant solo
    sweeps (recording the degradation) rather than pack around the
    injection machinery."""
    tenants = [_tenant(s) for s in SEEDS[:2]]
    specs = [t[0].spec for t in tenants]
    conds = [t[1] for t in tenants]
    profiling.drain_events()
    plan = FaultPlan([FaultSpec(site="batched steady solve",
                                kind="nan", lanes=(2,), times=1)])
    with fault_scope(plan):
        solo = [sweep_steady_state(s, c) for s, c in zip(specs, conds)]
    plan2 = FaultPlan([FaultSpec(site="batched steady solve",
                                 kind="nan", lanes=(2,), times=1)])
    with fault_scope(plan2):
        packed = packed_sweep_steady_state(specs, conds)
    events = profiling.drain_events()
    assert any(e.get("label") == "packed:solo-fallback"
               for e in events)
    for k, (so, pa) in enumerate(zip(solo, packed)):
        _assert_tenant_bitwise(so, pa, f"faultplan/tenant{k}")


# ---------------------------------------------------------------------------
# 3. zero marginal compiles in a warm bucket


def test_warm_bucket_pack_prewarms_with_zero_compiles(tmp_path):
    clear_program_caches()     # this test COUNTS compiles: start cold
    cache_dir = str(tmp_path / "aot")
    os.environ["PYCATKIN_AOT_CACHE"] = cache_dir  # abi_on resets it
    first = [_tenant(s) for s in SEEDS]
    stats = prewarm_packed_sweep_programs(
        [t[0].spec for t in first], [t[1] for t in first],
        tof_mask=[t[2] for t in first])
    assert int(stats) == 1
    assert stats.compiled == 1 and stats.loaded == 0

    # FRESH mechanisms, same bucket/K/lanes: the warm registry serves
    # the pack -- zero marginal compiles is the acceptance gate.
    fresh = [_tenant(s + 10) for s in SEEDS]
    stats2 = prewarm_packed_sweep_programs(
        [t[0].spec for t in fresh], [t[1] for t in fresh],
        tof_mask=[t[2] for t in fresh])
    assert stats2.compiled == 0, \
        "a warm (bucket, K, lanes) pack performed a marginal compile"
    assert stats2.loaded == 1


# ---------------------------------------------------------------------------
# 4. the request coalescer


def test_coalescer_groups_by_bucket_and_flushes_on_occupancy():
    tenants = [_tenant(s) for s in SEEDS[:2]]
    co = SweepCoalescer(max_occupancy=2, max_wait_s=1e9)
    r0 = co.submit(tenants[0][0], tenants[0][1])
    assert not r0.done and co.pending == 1
    r1 = co.submit(tenants[1][0], tenants[1][1])
    assert r0.done and r1.done and co.pending == 0
    assert co.flushes == 1
    solo = sweep_steady_state(tenants[0][0].spec, tenants[0][1])
    _assert_tenant_bitwise(solo, r0.result(), "coalescer tenant 0")


def test_coalescer_poll_and_result_force_flush():
    sim, conds, _ = _tenant(0)
    co = SweepCoalescer(max_occupancy=8, max_wait_s=1e9)
    req = co.submit(sim, conds)
    assert co.poll() == 0                      # deadline far away
    assert co.poll(now=float("inf")) == 1      # max-wait expiry
    assert req.done
    co2 = SweepCoalescer(max_occupancy=8, max_wait_s=1e9)
    req2 = co2.submit(sim, conds)
    out = req2.result()                        # caller-forced flush
    assert req2.done and out["y"] is not None


def test_coalescer_emits_pack_flush_event(tmp_path):
    import json
    tenants = [_tenant(s) for s in SEEDS[:2]]
    co = SweepCoalescer(max_occupancy=2, max_wait_s=1e9,
                        work_dir=str(tmp_path))
    for sim, conds, _ in tenants:
        co.submit(sim, conds)
    lines = [json.loads(line) for line in
             open(tmp_path / "events.jsonl", encoding="utf-8")]
    ev = next(e for e in lines if e.get("action") == "pack-flush")
    assert ev["tenants"] == 2 and ev["k_bucket"] == 2
    assert ev["pack_occupancy"] == pytest.approx(1.0)
    assert ev["lanes"] == N_LANES
    assert ev["tenant_quarantined"] == [0, 0]


def test_dispatch_sweep_packed_mode():
    tenants = [_tenant(s) for s in SEEDS]
    outs = dispatch_sweep([t[0] for t in tenants],
                          [t[1] for t in tenants], mode="packed")
    assert len(outs) == len(tenants)
    solo = sweep_steady_state(tenants[2][0].spec, tenants[2][1])
    _assert_tenant_bitwise(solo, outs[2], "dispatch packed tenant 2")
    with pytest.raises(ValueError):
        dispatch_sweep(tenants[0][0], tenants[0][1], mode="bogus")
