"""Deterministic fault injection + degradation ladder unit tests.

Every failure mode the production backend exhibits (transport flakes,
stalls, NaN-poisoned outputs, device loss) is a scriptable event
(robustness/faults.py); these tests pin the plan semantics and walk the
degradation ladder (robustness/ladder.py) through each rung.
"""

import json
import time

import numpy as np
import pytest

import jax

from pycatkin_tpu.robustness import (ChunkAbandonedError, DegradationPolicy,
                                     FaultPlan, FaultSpec,
                                     InjectedDeviceLossError, fault_scope,
                                     run_chunk_with_ladder)
from pycatkin_tpu.robustness import faults
from pycatkin_tpu.utils.retry import (call_with_backend_retry,
                                      is_transient_backend_error)

pytestmark = pytest.mark.faults

_FAST = DegradationPolicy(base_delay_s=0.001, max_delay_s=0.002)


# ---------------------------------------------------------------------
# FaultPlan semantics


def test_fault_plan_site_matching_and_occurrence():
    plan = FaultPlan([FaultSpec(site="chunk:*", kind="transient",
                                index=1, times=1)])
    plan.on_call("chunk:0")                       # occurrence 0: no fire
    with pytest.raises(jax.errors.JaxRuntimeError) as ei:
        plan.on_call("chunk:0")                   # occurrence 1: fires
    assert is_transient_backend_error(ei.value)
    plan.on_call("chunk:0")                       # times=1: spent
    plan.on_call("other site")                    # no match, no fire
    assert plan.log == [{"site": "chunk:0", "occurrence": 1,
                         "kind": "transient"}]


def test_fault_plan_permanent_is_not_transient():
    plan = FaultPlan([{"site": "s", "kind": "permanent", "times": None}])
    with pytest.raises(InjectedDeviceLossError) as ei:
        plan.on_call("s")
    assert not is_transient_backend_error(ei.value)
    with pytest.raises(InjectedDeviceLossError):
        plan.on_call("s")                         # times=None: every call


def test_fault_plan_nan_poisons_chosen_lanes():
    plan = FaultPlan([{"site": "s", "kind": "nan", "lanes": [1]}])
    plan.on_call("s")
    out = plan.on_result("s", {"y": np.ones((3, 2)),
                               "n": np.arange(3),
                               "tag": "keep"})
    assert np.isnan(out["y"][1]).all()
    assert np.isfinite(out["y"][[0, 2]]).all()
    assert np.array_equal(out["n"], np.arange(3))    # ints untouched
    assert out["tag"] == "keep"


def test_fault_plan_stall_sleeps():
    plan = FaultPlan([{"site": "s", "kind": "stall", "delay_s": 0.05}])
    t0 = time.monotonic()
    plan.on_call("s")
    assert time.monotonic() - t0 >= 0.05


def test_fault_plan_from_env_roundtrip():
    text = json.dumps([{"site": "chunk:2", "kind": "transient"},
                       {"site": "*", "kind": "nan", "lanes": [0, 3]}])
    plan = FaultPlan.from_env(text)
    assert [s.kind for s in plan.specs] == ["transient", "nan"]
    assert plan.specs[1].lanes == (0, 3)
    assert FaultPlan.from_env("") is None
    with pytest.raises(ValueError):
        FaultPlan([{"site": "s", "kind": "meteor"}])


def test_fault_scope_installs_and_restores():
    assert faults.active_plan() is None
    plan = FaultPlan([{"site": "s", "kind": "transient"}])
    with fault_scope(plan):
        assert faults.active_plan() is plan
        with pytest.raises(jax.errors.JaxRuntimeError):
            faults.inject("s")
    assert faults.active_plan() is None
    faults.inject("s")                            # no-op without a plan


# ---------------------------------------------------------------------
# Faults through the retry layer (label = site)


def test_injected_transient_absorbed_by_retry():
    plan = FaultPlan([{"site": "solve", "kind": "transient"}])
    calls = {"n": 0}

    def fn():
        calls["n"] += 1
        return calls["n"]

    with fault_scope(plan):
        out = call_with_backend_retry(fn, attempts=3, base_delay_s=0.001,
                                      label="solve")
    assert out == 1          # first dispatch faulted BEFORE fn ran
    assert [e["kind"] for e in plan.log] == ["transient"]


def test_injected_transient_exhaustion_reraises():
    plan = FaultPlan([{"site": "solve", "kind": "transient",
                       "times": None}])
    with fault_scope(plan):
        with pytest.raises(jax.errors.JaxRuntimeError):
            call_with_backend_retry(lambda: 1, attempts=3,
                                    base_delay_s=0.001, label="solve")
    assert len(plan.log) == 3                     # one per attempt


def test_injected_stall_trips_retry_deadline():
    plan = FaultPlan([{"site": "solve", "kind": "stall",
                       "delay_s": 0.05, "times": None},
                      {"site": "solve", "kind": "transient",
                       "times": None}])
    t0 = time.monotonic()
    with fault_scope(plan):
        with pytest.raises(jax.errors.JaxRuntimeError):
            call_with_backend_retry(lambda: 1, attempts=50,
                                    base_delay_s=0.04, jitter=False,
                                    deadline_s=0.1, label="solve")
    assert time.monotonic() - t0 < 5.0


# ---------------------------------------------------------------------
# The degradation ladder rung by rung


def test_ladder_clean_call_passes_through():
    out, events = run_chunk_with_ladder(lambda device=None: 7,
                                        label="c", policy=_FAST)
    assert out == 7 and events == []


def test_ladder_requeue_recovers_on_other_device():
    """A permanent fault on the first dispatch only: the retry rung
    fails fast (device loss is not transient), requeue's re-dispatch
    (different device) succeeds."""
    seen = []

    plan = FaultPlan([{"site": "c", "kind": "permanent", "times": 1}])

    def run(device=None):
        seen.append(device)
        return "ok"

    with fault_scope(plan):
        out, events = run_chunk_with_ladder(run, label="c", policy=_FAST)
    assert out == "ok"
    rungs = [e["rung"] for e in events]
    assert "requeue" in rungs
    assert seen[-1] is not None                   # re-targeted device


def test_ladder_nan_validation_escalates_and_recovers():
    plan = FaultPlan([{"site": "c", "kind": "nan", "times": 1}])

    def run(device=None):
        return {"y": np.ones((2, 2))}

    def validate(out):
        return ("poisoned" if not np.isfinite(out["y"]).all() else None)

    with fault_scope(plan):
        out, events = run_chunk_with_ladder(run, label="c", policy=_FAST,
                                            validate=validate)
    assert np.isfinite(out["y"]).all()
    assert any("rejected" in e["detail"] for e in events)


def test_ladder_salvage_returns_none_and_reports():
    from pycatkin_tpu.utils import profiling

    profiling.drain_events()
    plan = FaultPlan([{"site": "c", "kind": "permanent", "times": None}])
    with fault_scope(plan):
        out, events = run_chunk_with_ladder(
            lambda device=None: 1, label="c", policy=_FAST)
    assert out is None
    rungs = [e["rung"] for e in events]
    assert rungs[-1] == "salvage"
    # mirrored into the structured diagnostics log
    evs = profiling.drain_events()
    assert any(e["kind"] == "degradation" and e["rung"] == "salvage"
               for e in evs)


def test_ladder_salvage_disabled_raises():
    plan = FaultPlan([{"site": "c", "kind": "permanent", "times": None}])
    pol = DegradationPolicy(base_delay_s=0.001, max_delay_s=0.002,
                            salvage=False)
    with fault_scope(plan):
        with pytest.raises(ChunkAbandonedError):
            run_chunk_with_ladder(lambda device=None: 1, label="c",
                                  policy=pol)
