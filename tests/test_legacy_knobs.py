"""Round-3 hardening of workflow edges.

Covers: legacy solver knobs honored-or-rejected instead of silently
ignored (reference old_system.py:154-174, 350-376); the non-positive-TOF
activity guard (reference old_system.py:517-529 silently NaNs); and the
FD-DRC convergence flag threaded through the batched sweep path
(engine.drc_fd return_success -> presets._drc_program -> _sweep warning).
"""

import numpy as np
import pytest

from pycatkin_tpu import engine
from pycatkin_tpu.api.system import System
from pycatkin_tpu.constants import R, eVtokJ, h, kB
from pycatkin_tpu.frontend.reactions import UserDefinedReaction
from pycatkin_tpu.frontend.states import State
from pycatkin_tpu.models.reactor import InfiniteDilutionReactor

eVtoJmol = eVtokJ * 1.0e3


# ---------------------------------------------------------------------
# legacy solver knobs (reference old_system.py:154-174)
def test_ode_solver_aliases_accepted():
    for alias in ("trbdf2", "solve_ivp", "ode"):
        System(ode_solver=alias)


def test_unknown_ode_solver_rejected():
    with pytest.raises(ValueError, match="ode_solver"):
        System(ode_solver="lsoda")


def test_nsteps_maps_to_max_steps():
    from pycatkin_tpu.solvers.ode import ODEOptions
    assert System(nsteps=123)._ode_options().max_steps == 123
    # the legacy default budget maps onto the native default
    assert System()._ode_options().max_steps == ODEOptions().max_steps


def test_ftol_xtol_map_to_rate_tol():
    """Reference least_squares stops when EITHER ftol or xtol fires
    (old_system.py:426-428): the tightest becomes the absolute residual
    tolerance."""
    assert System(ftol=1.0e-12).solver_options().rate_tol == 1.0e-12
    assert System(xtol=1.0e-10).solver_options().rate_tol == 1.0e-10
    assert System(ftol=1.0e-9,
                  xtol=1.0e-11).solver_options().rate_tol == 1.0e-11
    # explicit overrides still win
    assert System(ftol=1.0e-12).solver_options(
        rate_tol=1.0e-6).rate_tol == 1.0e-6


# ---------------------------------------------------------------------
# non-positive TOF activity guard (reference old_system.py:517-529)
def test_activity_from_tof_uses_magnitude():
    a_pos = float(engine.activity_from_tof(1.0e-5, 500.0))
    a_neg = float(engine.activity_from_tof(-1.0e-5, 500.0))
    assert np.isfinite(a_neg)
    assert a_neg == pytest.approx(a_pos)
    assert float(engine.activity_from_tof(0.0, 500.0)) == -np.inf


def test_system_activity_warns_on_reverse_tof():
    sim = System(T=500.0)
    # A net TOF < 0: the selected steps run in reverse at the solution.
    sim.run_and_return_tof = lambda *a, **k: -1.0e-5
    with pytest.warns(UserWarning, match="non-positive"):
        a = sim.activity(["r1"])
    assert a == pytest.approx(float(engine.activity_from_tof(1.0e-5,
                                                             500.0)))


# ---------------------------------------------------------------------
# FD-DRC convergence flag through the batched sweep path
def _ga_for_rate(k, T):
    return -R * T * np.log(k * h / (kB * T)) / eVtoJmol


def _toy_surface_system(T=500.0):
    """Two-state surface mechanism (no gas thermo needed): the sweep
    machinery exercises transient + steady + DRC batched programs on it
    in a fraction of a second."""
    s = State(name="s", state_type="surface")
    sa = State(name="sa", state_type="adsorbate")
    r1 = UserDefinedReaction(name="r1", reac_type="arrhenius",
                             reversible=True,
                             reactants=[s], products=[sa],
                             dGrxn_user=0.05,
                             dGa_fwd_user=_ga_for_rate(5.0, T))
    sim = System(start_state={"s": 1.0}, T=T, p=1.0e5,
                 times=[0.0, 100.0])
    sim.add_state(s)
    sim.add_state(sa)
    sim.add_reaction(r1)
    sim.add_reactor(InfiniteDilutionReactor())
    return sim.build()


def test_sweep_warns_on_unconverged_fd_drc(monkeypatch, capsys):
    """A failing perturbed solve in the batched FD-DRC path must surface
    as a warning naming the sweep (round-2 verdict: the facade warned,
    the batched path silently returned unreliable xi)."""
    import jax.numpy as jnp

    from pycatkin_tpu.api import presets

    def failing_drc_fd(spec, cond, tof_terms, eps=1e-3, opts=None,
                       x0=None, key=None, return_success=False):
        xi = jnp.zeros(spec.n_reactions)
        return (xi, jnp.asarray(False)) if return_success else xi

    monkeypatch.setattr(engine, "drc_fd", failing_drc_fd)
    sim = _toy_surface_system()
    presets.run_temperatures(sim, [500.0, 510.0],
                             steady_state_solve=True, tof_terms=["r1"],
                             drc_mode="fd")
    err = capsys.readouterr().err
    assert "DRC" in err and "unreliable" in err


def test_sweep_fd_drc_converged_no_warning(capsys):
    """The real FD-DRC on the toy system: all perturbed solves converge,
    so the sweep must NOT warn."""
    from pycatkin_tpu.api import presets

    sim = _toy_surface_system()
    finals, rates, drcs = presets.run_temperatures(
        sim, [500.0, 510.0], steady_state_solve=True, tof_terms=["r1"],
        drc_mode="fd")
    err = capsys.readouterr().err
    assert "unreliable" not in err
    assert set(drcs) == {500.0, 510.0}
