"""Durable JSONL helpers (utils/io.py): the sweep journal's manifest
primitives, exercised directly -- truncated-final-line recovery and
append-after-truncation repair (a kill mid-append must never be able
to corrupt the file for later appends)."""

import json

import pytest

from pycatkin_tpu.utils.io import append_json_line, read_json_lines

pytestmark = pytest.mark.validate


def test_append_read_roundtrip(tmp_path):
    path = str(tmp_path / "j.jsonl")
    records = [{"i": 0, "s": "a"}, {"i": 1, "nested": {"x": [1, 2]}}]
    for rec in records:
        append_json_line(path, rec)
    assert read_json_lines(path) == records


def test_truncated_final_line_dropped(tmp_path):
    path = str(tmp_path / "j.jsonl")
    append_json_line(path, {"i": 0})
    append_json_line(path, {"i": 1})
    with open(path, "a") as fh:
        fh.write('{"i": 2, "tr')       # kill mid-append: no newline
    assert read_json_lines(path) == [{"i": 0}, {"i": 1}]


def test_corrupt_nonfinal_line_raises(tmp_path):
    path = str(tmp_path / "j.jsonl")
    with open(path, "w") as fh:
        fh.write('{"i": 0}\nnot json\n{"i": 2}\n')
    with pytest.raises(json.JSONDecodeError):
        read_json_lines(path)


def test_append_after_truncation_repairs_tail(tmp_path):
    """Appending over a torn final line truncates the fragment first;
    gluing the new record onto it would leave a corrupt NON-final line
    that read_json_lines refuses."""
    path = str(tmp_path / "j.jsonl")
    append_json_line(path, {"i": 0})
    with open(path, "a") as fh:
        fh.write('{"i": 1, "tr')
    append_json_line(path, {"i": 2})
    assert read_json_lines(path) == [{"i": 0}, {"i": 2}]


def test_append_after_truncation_empty_file(tmp_path):
    """A file that is ONLY a torn fragment (kill during the very first
    append) truncates to empty and the append succeeds."""
    path = str(tmp_path / "j.jsonl")
    with open(path, "w") as fh:
        fh.write('{"to')
    append_json_line(path, {"i": 0})
    assert read_json_lines(path) == [{"i": 0}]


def test_append_after_long_torn_line(tmp_path):
    """Torn fragment longer than one backwards-scan chunk (4096 B)."""
    path = str(tmp_path / "j.jsonl")
    append_json_line(path, {"i": 0})
    with open(path, "a") as fh:
        fh.write('{"blob": "' + "x" * 10000)
    append_json_line(path, {"i": 1})
    assert read_json_lines(path) == [{"i": 0}, {"i": 1}]
