"""Golden regressions: DMTM methane-to-methanol example (reference test_1).

Ports the reference's end-to-end assertions (test/test_1.py:40-90) to the
unified API: transient steady coverages, DRC ranking over a temperature
sweep, energy-span TDI/TDTS identities, and state/reaction energy extrema.
"""

import os

import numpy as np
import pandas as pd
import pytest

import pycatkin_tpu as pk
from pycatkin_tpu.api import presets
from tests.conftest import reference_path


@pytest.fixture(scope="module")
def dmtm(ref_root):
    return pk.read_from_input_file(
        reference_path("examples", "DMTM", "input.json"))


def test_transient_steady_coverages(dmtm):
    """Reference test_1.py:40-46: coverages sum to 1 and sCH3OH dominates
    at 400 K."""
    presets.run(sim_system=dmtm)
    ads = dmtm.adsorbate_indices
    final = dmtm.solution[-1]
    assert abs(1 - np.sum(final[ads])) <= 1e-6
    assert np.max(final[ads]) > 0.999
    imax = ads[int(np.argmax(final[ads]))]
    assert dmtm.snames[imax] == "sCH3OH"


def test_drc_ranking_over_temperatures(dmtm, tmp_path):
    """Reference test_1.py:48-59: the max-DRC step is r9 at EVERY
    temperature of the full 9-point 400-800 K sweep (the reference checks
    the identity over the sweep; round 1 only checked the endpoints)."""
    tof_terms = ["r5", "r9"]
    temperatures = np.linspace(400, 800, 9)
    presets.run_temperatures(sim_system=dmtm, temperatures=temperatures,
                             tof_terms=tof_terms, steady_state_solve=True,
                             save_results=True, csv_path=str(tmp_path))
    fname = tmp_path / "drcs_vs_temperature.csv"
    assert os.path.isfile(fname)
    df = pd.read_csv(fname)
    assert len(df) == 9
    for i in range(len(df)):
        assert df.iloc[i, 1:].idxmax() == "r9", \
            f"max-DRC step at T={df.iloc[i, 0]} K is not r9"


@pytest.mark.slow
def test_drc_implicit_vs_fd_parity(dmtm):
    """Implicit-function-theorem DRC against reference-parity central
    finite differences on the real DMTM mechanism at 600 and 800 K:
    every reaction's xi agrees to <=1e-3, and the ID-reactor sum rule
    sum(xi) = 1 holds (scaling every k scales TOF linearly at the same
    steady state). At 400 K the FD root shift sits below the f64
    residual floor (see engine.drc_fd docstring), so parity is asserted
    where FD is numerically meaningful."""
    T0, sol0 = dmtm.params["temperature"], dmtm.solution
    try:
        for T in (600.0, 800.0):
            dmtm.params["temperature"] = T
            dmtm.solution = None
            dmtm.solve_odes()
            xi_imp = dmtm.degree_of_rate_control(["r5", "r9"],
                                                 mode="implicit")
            xi_fd = dmtm.degree_of_rate_control(["r5", "r9"], mode="fd",
                                                eps=1.0e-3)
            for rname in xi_imp:
                assert abs(xi_imp[rname] - xi_fd[rname]) <= 1e-3, \
                    (T, rname)
            assert sum(xi_imp.values()) == pytest.approx(1.0, abs=1e-6)
    finally:
        dmtm.params["temperature"], dmtm.solution = T0, sol0


def test_drc_implicit_400K_identity(dmtm):
    """At 400 K the implicit DRC resolves what FD cannot: methanol
    desorption r9 carries essentially ALL rate control (consistent with
    the ES model's TDI=sCH3OH at 400 K)."""
    T0, sol0 = dmtm.params["temperature"], dmtm.solution
    try:
        dmtm.params["temperature"] = 400.0
        dmtm.solution = None
        dmtm.solve_odes()
        xi = dmtm.degree_of_rate_control(["r5", "r9"], mode="implicit")
        assert xi["r9"] == pytest.approx(1.0, abs=5e-3)
        # Sum-rule tolerance is conditioning-limited here: at 400 K the
        # steady state has a near-degenerate slow mode (s2OCH4 <->
        # sCH3OH), and at the f64 residual cancellation floor the
        # position along it is unobservable -- the IFT gradient then
        # carries an O(cond * eps) error no solver can remove. 600/800 K
        # (better conditioned) assert 1e-6 above.
        assert sum(xi.values()) == pytest.approx(1.0, abs=5e-5)
    finally:
        dmtm.params["temperature"], dmtm.solution = T0, sol0


def test_energy_span_identities(dmtm, tmp_path):
    """Reference test_1.py:61-71: TDI = sCH3OH/s2OCH4 and TDTS = TS6/TS3
    at 400/800 K."""
    temperatures = np.linspace(400, 800, 2)
    presets.run_energy_span_temperatures(sim_system=dmtm,
                                         temperatures=temperatures,
                                         save_results=True,
                                         csv_path=str(tmp_path))
    df = pd.read_csv(tmp_path / "energy_span_summary_full_pes.csv")
    assert df["TDI"][0] == "sCH3OH"
    assert df["TDI"][1] == "s2OCH4"
    assert df["TDTS"][0] == "TS6"
    assert df["TDTS"][1] == "TS3"


def test_state_energy_extrema(dmtm, tmp_path):
    """Reference test_1.py:73-81 golden extrema at 800 K / 1 bar.

    NOTE: the reference CSV swaps the Translational/Rotational headers
    (presets.py:459-469 appends [Grota, Gtran] under
    ['Translational', 'Rotational']); ours are labelled correctly, so the
    golden values swap columns here.
    """
    dmtm.params["temperature"] = 800.0
    presets.save_state_energies(sim_system=dmtm, csv_path=str(tmp_path))
    df = pd.read_csv(tmp_path / "state_energies_800.0K_1.0bar.csv")
    assert abs(max(df["Free (eV)"]) - (-7.864)) <= 1e-3
    assert abs(max(df["Vibrational (eV)"]) - 1.142) <= 1e-3
    assert abs(min(df["Translational (eV)"]) - (-1.259)) <= 1e-3
    assert abs(min(df["Rotational (eV)"]) - (-0.659)) <= 1e-3


def test_reaction_energy_extrema(dmtm, tmp_path):
    """Reference test_1.py:83-90 golden extrema at 800 K."""
    dmtm.params["temperature"] = 800.0
    presets.save_energies(sim_system=dmtm, csv_path=str(tmp_path))
    df = pd.read_csv(
        tmp_path / "reaction_energies_and_barriers_800.0K_1.0bar.csv")
    assert abs(max(df["dEr (J/mol)"]) - 220788.916) <= 1e-3
    assert abs(max(df["dGr (J/mol)"]) - 66358.978) <= 1e-3
    assert abs(max(df["dEa (J/mol)"]) - 138934.617) <= 1e-3
    assert abs(max(df["dGa (J/mol)"]) - 230155.396) <= 1e-3
