"""DMTM humidity example: gas-mixture (``gasdata``) corrections.

Exercises the fraction-weighted co-adsorbed-gas translational/rotational
free-energy add-ons (reference state.py:335-338,362-365, driven by
examples/DMTM/humidity/input_humid.json) through the compiled ``mix``
matrix, plus the wet-data .dat tree parsing.
"""

import numpy as np
import pytest

import pycatkin_tpu as pk
from pycatkin_tpu import engine
from tests.conftest import reference_path


@pytest.fixture(scope="module")
def humid(ref_root):
    # Paths inside input_humid.json are relative to examples/DMTM (the
    # reference runs it from there), not to the humidity subdirectory.
    return pk.read_from_input_file(
        reference_path("examples", "DMTM", "humidity", "input_humid.json"),
        base_path=reference_path("examples", "DMTM"))


def test_gasdata_mix_compiled(humid):
    spec = humid.spec
    i = spec.sindex("s2OCH4")
    j_ch4 = spec.sindex("CH4")
    assert spec.mix[i, j_ch4] == pytest.approx(0.67)
    iw = spec.sindex("2CuH2O")
    j_h2o = spec.sindex("H2O")
    assert spec.mix[iw, j_h2o] == pytest.approx(0.67)


def test_gasdata_adds_gas_thermo(humid):
    """Co-adsorbed species inherit the fraction-weighted gas
    translational+rotational contributions; a plain adsorbate has none."""
    fe = humid.free_energy_table(T=500.0)
    spec = humid.spec
    i = spec.sindex("s2OCH4")
    j = spec.sindex("CH4")
    assert float(fe.gtran[i]) == pytest.approx(0.67 * float(fe.gtran[j]))
    assert float(fe.grota[i]) == pytest.approx(0.67 * float(fe.grota[j]))
    i_dry = spec.sindex("sO")
    assert float(fe.gtran[i_dry]) == 0.0


def test_humid_steady_state(humid):
    humid.solve_odes()
    res = humid.find_steady()
    assert bool(res.success)
    y = np.asarray(res.x)
    sums = np.asarray(humid.spec.groups) @ y
    np.testing.assert_allclose(sums, 1.0, atol=5e-2)
