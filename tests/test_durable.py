"""Durable serving (docs/serving.md "Durable requests"): the
write-ahead request journal, idempotent replay, and router-death
recovery.

No JAX anywhere: the journal is plain fsynced JSONL, and the router is
exercised over stub TCP replicas exactly as in test_router.py. The
acceptance surface, smallest-first: the journal's accept/answer ledger
is idempotent and crash-replayable (rotation, compaction, torn final
line); the shared torn-tail reader protects BOTH its callers (the
chunk journal and the request journal); the accepted record is on disk
before the ack closure runs (fsync-before-ack); a router booted over a
journal left by a SIGKILL at each of the three crash points (pre-ack,
post-ack pre-dispatch, post-answer pre-compaction) recovers exactly
the right work; duplicate keys are answered bitwise from the journal;
keyless requests are byte-identical with and without a journal; and
the TCP client receives durability acks, fetches journaled results,
and resubmits keyed requests across a severed connection.
"""

import asyncio
import json
import os

import pytest

from pycatkin_tpu.robustness.journal import SweepJournal
from pycatkin_tpu.serve.client import TcpSweepClient, sweep_payload
from pycatkin_tpu.serve.durable import RequestJournal
from pycatkin_tpu.serve.protocol import (E_UNKNOWN_KEY,
                                         canonical_answer)
from pycatkin_tpu.serve.router import RouterConfig, SweepRouter
from pycatkin_tpu.utils.io import read_json_lines

pytestmark = pytest.mark.faults


# -- stub replicas + fake supervisor (as in test_router.py) ------------


class StubReplica:
    """Wire-compatible replica: answers ``ping`` natively and routes
    ``sweep`` through a swappable ``behavior(payload, writer)``."""

    def __init__(self, behavior=None):
        self.behavior = behavior or answer_sweep
        self.up = True
        self.port = None
        self.sweeps_seen = 0
        self._server = None
        self._tasks = set()

    async def start(self):
        self._server = await asyncio.start_server(
            self._on_conn, "127.0.0.1", 0)
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    async def stop(self):
        self._server.close()
        await self._server.wait_closed()
        for task in list(self._tasks):
            task.cancel()
        if self._tasks:
            await asyncio.gather(*list(self._tasks),
                                 return_exceptions=True)

    async def _handle_sweep(self, payload, writer):
        try:
            resp = await self.behavior(payload, writer)
            if resp is not None:
                await _write(writer, resp)
        except (ConnectionError, OSError):
            pass

    async def _on_conn(self, reader, writer):
        try:
            while True:
                line = await reader.readline()
                if not line:
                    break
                try:
                    payload = json.loads(line)
                except ValueError:
                    continue
                if payload.get("op") == "ping":
                    await _write(writer, {"ok": True, "pong": True,
                                          "id": payload.get("id")})
                    continue
                self.sweeps_seen += 1
                task = asyncio.ensure_future(
                    self._handle_sweep(payload, writer))
                self._tasks.add(task)
                task.add_done_callback(self._tasks.discard)
        except (ConnectionError, asyncio.CancelledError):
            pass
        finally:
            try:
                writer.close()
            except (ConnectionError, OSError):
                pass


async def _write(writer, obj):
    writer.write((json.dumps(obj) + "\n").encode())
    await writer.drain()


async def answer_sweep(payload, writer):
    """Deterministic answer derived from the request: duplicates of one
    key are bit-identical, which is what every audit below leans on."""
    return {"ok": True, "id": payload["id"],
            "result": {"echo": payload.get("conditions")},
            "quarantine": {"n_quarantined": 0}, "lanes": None}


class FakeSupervisor:
    def __init__(self, replicas):
        self.replicas = list(replicas)
        self._listeners = []

    def add_listener(self, fn):
        self._listeners.append(fn)

    def endpoints(self):
        return [{"idx": i, "incarnation": 1, "host": "127.0.0.1",
                 "port": s.port}
                for i, s in enumerate(self.replicas)
                if s.up and s.port is not None]

    def stats(self):
        return {"n_replicas": len(self.replicas),
                "up": sum(s.up for s in self.replicas), "replicas": []}


def durable_config(journal_dir, **overrides):
    kw = dict(max_inflight=16, breaker_fails=2,
              breaker_cooldown_s=0.05, hedge_quantile=0.95,
              hedge_min_s=0.02, retries=3, retry_base_delay_s=0.001,
              retry_max_delay_s=0.01, connect_timeout_s=1.0,
              probe_timeout_s=1.0, tick_s=0.005,
              journal_dir=str(journal_dir) if journal_dir else None)
    kw.update(overrides)
    return RouterConfig(**kw)


async def _router_over(replicas, journal_dir, listen=False,
                       **cfg_overrides):
    for r in replicas:
        if r.port is None:
            await r.start()
    router = await SweepRouter(
        FakeSupervisor(replicas),
        durable_config(journal_dir, **cfg_overrides)).start(
            listen=listen)
    return router


async def _wait_replay(router, timeout_s=10.0):
    deadline = asyncio.get_running_loop().time() + timeout_s
    while router.stats()["durable"]["replay"]["active"]:
        assert asyncio.get_running_loop().time() < deadline, \
            f"replay never finished: {router.stats()['durable']}"
        await asyncio.sleep(0.01)


def _sweep(i=0, key=None):
    return sweep_payload({"mech": "stub"}, [500.0 + i],
                         deadline_class="standard", req_id=f"r{i}",
                         idempotency_key=key)


@pytest.fixture
def short_budgets(monkeypatch):
    monkeypatch.setenv("PYCATKIN_SERVE_TIMEOUT_STANDARD", "5.0")
    monkeypatch.setenv("PYCATKIN_SERVE_TIMEOUT_INTERACTIVE", "2.0")


def _active_segment(jdir):
    segs = sorted(f for f in os.listdir(jdir)
                  if f.startswith("requests_"))
    assert segs, f"no journal segments in {jdir}"
    return os.path.join(jdir, segs[-1])


def _tear_tail(path, torn=b'{"kind": "accepted", "key": "torn'):
    with open(path, "ab") as fh:
        fh.write(torn)


# -- journal unit: idempotent ledger -----------------------------------


def test_journal_idempotent_accept_and_answer(tmp_path):
    j = RequestJournal(str(tmp_path / "j"))
    assert j.record_accepted("k0", {"op": "sweep"}) is True
    assert j.record_accepted("k0", {"op": "sweep"}) is False
    assert j.is_accepted("k0")
    assert j.unanswered() == [("k0", {"op": "sweep"})]
    resp = {"ok": True, "id": "r0", "result": {"n": 1},
            "quarantine": None, "lanes": None}
    assert j.record_answered("k0", resp) is None
    # A second answer returns the PRIOR stored response (id stripped)
    # so the caller can audit bitwise identity.
    prior = j.record_answered("k0", dict(resp, result={"n": 2}))
    assert prior is not None and prior["result"] == {"n": 1}
    assert "id" not in prior
    assert j.answered_response("k0")["result"] == {"n": 1}
    assert j.unanswered() == []
    # Answering pins idempotency too: re-accepting an answered key is
    # a no-op (the journal, not the caller, is the source of truth).
    assert j.record_accepted("k0", {"op": "sweep"}) is False


def test_journal_rotation_compaction_and_pinning(tmp_path):
    jdir = str(tmp_path / "j")
    j = RequestJournal(jdir, segment_bytes=128)
    j.record_accepted("pin", {"op": "sweep", "n": -1})
    for i in range(8):
        j.record_accepted(f"k{i}", {"op": "sweep", "n": i})
        j.record_answered(f"k{i}", {"ok": True, "result": {"n": i},
                                    "quarantine": None, "lanes": 1})
    st = j.stats()
    assert st["rotations"] > 0
    assert st["compacted_segments"] > 0
    assert st["pending"] == 1
    # The unanswered key pins its segment: replay in a fresh process
    # still knows about it, and the newest answer (which by
    # construction lives in a segment compaction never ran on) is
    # still servable. Older answers may legitimately have been
    # compacted away -- that is the documented dedup-window bound.
    j2 = RequestJournal(jdir, segment_bytes=128)
    assert [k for k, _ in j2.unanswered()] == ["pin"]
    assert j2.answered_response("k7")["result"] == {"n": 7}
    assert j2.stats()["replayed_records"] > 0


# -- torn-tail tolerance, per read_json_lines caller -------------------


def test_request_journal_replay_tolerates_torn_tail(tmp_path):
    jdir = str(tmp_path / "j")
    j = RequestJournal(jdir)
    j.record_accepted("good", {"op": "sweep"})
    j.record_answered("good", {"ok": True, "result": {"n": 1},
                               "quarantine": None, "lanes": None})
    seg = _active_segment(jdir)
    _tear_tail(seg)
    # Strict mode sees the damage; the journal's replay mode drops
    # exactly the torn final record (which was never acked to anyone).
    with pytest.raises(json.JSONDecodeError):
        read_json_lines(seg, tolerate_torn_tail=False)
    j2 = RequestJournal(jdir)
    assert not j2.is_accepted("torn")
    assert j2.answered_response("good")["result"] == {"n": 1}
    # The next append truncates the torn tail first, so the file heals
    # instead of accreting corruption.
    assert j2.record_accepted("after", {"op": "sweep"}) is True
    for rec in read_json_lines(seg, tolerate_torn_tail=False):
        assert rec["key"] != "torn"


def test_chunk_journal_resume_tolerates_torn_tail(tmp_path):
    jdir = str(tmp_path / "chunks")
    j = SweepJournal(jdir, fingerprint="fp", n_lanes=4, chunk=2)
    j.record_chunk(0, 0, 2, "done")
    _tear_tail(j.manifest_path, b'{"kind": "chunk", "chunk_id": 1')
    j2 = SweepJournal(jdir, fingerprint="fp", resume=True)
    recs = j2.chunk_records()
    assert [r["chunk_id"] for r in recs] == [0]
    # Resume can keep appending over the healed tail.
    j2.record_chunk(1, 2, 4, "done")
    assert len(read_json_lines(j2.manifest_path,
                               tolerate_torn_tail=False)) >= 3


# -- fsync-before-ack ordering -----------------------------------------


def test_accepted_record_is_on_disk_before_ack(tmp_path, short_budgets):
    jdir = str(tmp_path / "j")

    async def scenario():
        stub = StubReplica()
        router = await _router_over([stub], jdir)
        seen_at_ack = []

        async def ack(obj):
            # The durability contract: when the ack closure runs, the
            # accepted record must already be fsynced to the journal.
            on_disk = read_json_lines(_active_segment(jdir),
                                      tolerate_torn_tail=True)
            seen_at_ack.append((dict(obj), [
                (r["kind"], r["key"]) for r in on_disk]))

        try:
            resp = await router.handle(_sweep(0, key="dk0"), ack=ack)
            assert resp["ok"], resp
        finally:
            await router.stop()
            await stub.stop()
        assert len(seen_at_ack) == 1
        obj, on_disk = seen_at_ack[0]
        assert obj["accepted"] is True and obj["key"] == "dk0"
        assert ("accepted", "dk0") in on_disk
        assert ("answered", "dk0") not in on_disk
        # And the answer was journaled before the client saw it.
        final = read_json_lines(_active_segment(jdir),
                                tolerate_torn_tail=True)
        assert ("answered", "dk0") in [(r["kind"], r["key"])
                                       for r in final]
    asyncio.run(scenario())


# -- the three crash points --------------------------------------------


def test_crash_pre_ack_leaves_no_accepted_work(tmp_path, short_budgets):
    # SIGKILL mid-append, BEFORE the ack: the journal holds one torn
    # record. Replay must treat the key as never accepted (the client
    # was never promised anything) and a resubmission runs fresh.
    jdir = tmp_path / "j"
    jdir.mkdir()
    (jdir / "requests_00000.jsonl").write_bytes(
        b'{"kind": "accepted", "key": "c0", "pay')

    async def scenario():
        stub = StubReplica()
        router = await _router_over([stub], str(jdir))
        try:
            st = router.stats()["durable"]
            assert st["replay"]["total"] == 0
            assert st["journal"]["pending"] == 0
            resp = await router.handle(_sweep(0, key="c0"))
            assert resp["ok"]
            assert stub.sweeps_seen == 1
        finally:
            await router.stop()
            await stub.stop()
    asyncio.run(scenario())


def test_crash_post_ack_replays_and_answers(tmp_path, short_budgets):
    # SIGKILL after the ack but before dispatch: the accepted record
    # is durable, no answer exists. The rebooted router must
    # re-dispatch it unprompted and journal the answer.
    jdir = str(tmp_path / "j")
    payload = {k: v for k, v in _sweep(0, key="c1").items()
               if k != "id"}
    RequestJournal(jdir).record_accepted("c1", payload)

    async def scenario():
        stub = StubReplica()
        router = await _router_over([stub], jdir)
        try:
            assert router.stats()["durable"]["replay"]["total"] == 1
            await _wait_replay(router)
            replay = router.stats()["durable"]["replay"]
            assert replay["done"] == 1 and replay["failed"] == 0
            assert replay["wall_s"] is not None
            assert stub.sweeps_seen == 1
            # The answer is fetchable by key and a duplicate submit is
            # served from the journal WITHOUT touching the fleet.
            fetched = await router.handle({"op": "result", "key": "c1",
                                           "id": "f0"})
            assert fetched["ok"] and fetched["id"] == "f0"
            dup = await router.handle(_sweep(9, key="c1"))
            assert canonical_answer(dup) == canonical_answer(fetched)
            assert stub.sweeps_seen == 1
            assert router.stats()["durable"]["duplicates_served"] == 1
        finally:
            await router.stop()
            await stub.stop()
    asyncio.run(scenario())


def test_crash_post_answer_serves_bitwise(tmp_path, short_budgets):
    # SIGKILL after the answer was journaled (but before any
    # compaction): the rebooted router has nothing to replay and must
    # serve the journaled answer bitwise to a duplicate key.
    jdir = str(tmp_path / "j")
    j = RequestJournal(jdir)
    j.record_accepted("c2", {k: v for k, v in
                             _sweep(0, key="c2").items() if k != "id"})
    answer = {"ok": True, "id": "orig", "result": {"echo": {"T": [7.0]}},
              "quarantine": {"n_quarantined": 0}, "lanes": None}
    j.record_answered("c2", answer)

    async def scenario():
        stub = StubReplica()
        router = await _router_over([stub], jdir)
        try:
            assert router.stats()["durable"]["replay"]["total"] == 0
            dup = await router.handle(_sweep(5, key="c2"))
            assert dup["ok"] and dup["id"] == "r5"
            assert canonical_answer(dup) == canonical_answer(answer)
            assert stub.sweeps_seen == 0
        finally:
            await router.stop()
            await stub.stop()
    asyncio.run(scenario())


# -- live duplicate handling -------------------------------------------


def test_duplicate_key_bitwise_and_coalescing(tmp_path, short_budgets):
    async def slowish(payload, writer):
        await asyncio.sleep(0.1)
        return await answer_sweep(payload, writer)

    async def scenario():
        stub = StubReplica(behavior=slowish)
        router = await _router_over([stub], str(tmp_path / "j"))
        try:
            # Two concurrent submissions of one key coalesce onto one
            # dispatch; a later resubmission is served from the
            # journal. All three answers are bitwise identical.
            a, b = await asyncio.gather(
                router.handle(_sweep(0, key="dup")),
                router.handle(_sweep(1, key="dup")))
            late = await router.handle(_sweep(2, key="dup"))
            assert a["ok"] and b["ok"] and late["ok"]
            assert len({canonical_answer(r)
                        for r in (a, b, late)}) == 1
            assert (a["id"], b["id"], late["id"]) == ("r0", "r1", "r2")
            assert stub.sweeps_seen == 1
            st = router.stats()["durable"]
            assert st["coalesced"] >= 1
            assert st["duplicates_served"] >= 1
            assert router.stats()["duplicates"]["mismatched"] == 0
        finally:
            await router.stop()
            await stub.stop()
    asyncio.run(scenario())


def test_keyless_requests_are_byte_identical(tmp_path, short_budgets):
    # The pinned regression of the durable extension: a keyless sweep
    # through a journal-backed router is byte-identical to one through
    # a journal-less router, no ack line, nothing journaled.
    async def scenario():
        stub_a, stub_b = StubReplica(), StubReplica()
        plain = await _router_over([stub_a], None)
        durable = await _router_over([stub_b],
                                     str(tmp_path / "j"))
        acks = []

        async def ack(obj):
            acks.append(obj)

        try:
            ra = await plain.handle(_sweep(3), ack=ack)
            rb = await durable.handle(_sweep(3), ack=ack)
            assert json.dumps(ra, sort_keys=True) == \
                json.dumps(rb, sort_keys=True)
            assert acks == []
            st = durable.stats()["durable"]["journal"]
            assert st["pending"] == 0 and st["answered"] == 0
        finally:
            await plain.stop()
            await durable.stop()
            await stub_a.stop()
            await stub_b.stop()
    asyncio.run(scenario())


# -- TCP client: acks, result fetch, keyed resubmission ----------------


def test_tcp_client_acks_and_result_fetch(tmp_path, short_budgets):
    async def scenario():
        stub = StubReplica()
        router = await _router_over([stub], str(tmp_path / "j"),
                                    listen=True)
        cli = await TcpSweepClient("127.0.0.1",
                                   router.port).connect()
        try:
            resp = await cli.request(_sweep(0, key="tk0"), timeout=5.0)
            assert resp["ok"] and resp["id"] == "r0"
            assert cli.acks == 1
            fetched = await cli.fetch_result("tk0")
            assert fetched["ok"]
            assert canonical_answer(fetched) == canonical_answer(resp)
            missing = await cli.fetch_result("nope")
            assert missing["ok"] is False
            assert missing["error"]["code"] == E_UNKNOWN_KEY
        finally:
            await cli.close()
            await router.stop()
            await stub.stop()
    asyncio.run(scenario())


def test_tcp_client_resubmits_keyed_across_severed_conn(short_budgets):
    # A server that severs the first connection mid-request, then
    # answers normally: a KEYED request must survive the cut -- the
    # client reconnects, resubmits verbatim, and resolves ok.
    class FlakyServer:
        def __init__(self):
            self.conns = 0
            self.port = None
            self._server = None

        async def start(self):
            self._server = await asyncio.start_server(
                self._on, "127.0.0.1", 0)
            self.port = self._server.sockets[0].getsockname()[1]
            return self

        async def stop(self):
            self._server.close()
            await self._server.wait_closed()

        async def _on(self, reader, writer):
            self.conns += 1
            sever = self.conns == 1
            try:
                while True:
                    line = await reader.readline()
                    if not line:
                        break
                    req = json.loads(line)
                    if sever:
                        writer.transport.abort()
                        return
                    await _write(writer, {
                        "ok": True, "id": req.get("id"),
                        "result": {"n": 1}, "quarantine": None,
                        "lanes": None})
            except (ConnectionError, asyncio.CancelledError):
                pass

    async def scenario():
        srv = await FlakyServer().start()
        cli = await TcpSweepClient(
            "127.0.0.1", srv.port,
            reconnect_base_delay_s=0.01).connect()
        try:
            resp = await cli.request(_sweep(0, key="rk0"),
                                     timeout=10.0)
            assert resp["ok"], resp
            assert resp["id"] == "r0"
            assert cli.reconnects >= 1
            assert srv.conns >= 2
        finally:
            await cli.close()
            await srv.stop()
        from pycatkin_tpu.obs import metrics
        assert "pycatkin_serve_reconnects_total" in \
            metrics.snapshot()["counters"]
    asyncio.run(scenario())


# -- perfwatch tracks the durable metrics ------------------------------


def test_history_extracts_durable_metrics():
    from pycatkin_tpu.obs.history import TRACKED_METRICS, \
        extract_metrics
    assert TRACKED_METRICS["router_recovery_s"] == "lower"
    assert TRACKED_METRICS["journal_replay_s"] == "lower"
    record = {"bench": "serve-chaos-drill",
              "durable": {"router_recovery_s": 0.8,
                          "journal_replay_s": 0.05}}
    got = extract_metrics(record)
    assert got["router_recovery_s"] == 0.8
    assert got["journal_replay_s"] == 0.05
    assert "router_recovery_s" not in extract_metrics({"bench": "x"})
