"""Golden regression: COOx volcano single point (reference test_2).

Reproduces the reference workflow (test/test_2.py:19-53) through the
unified API: descriptor energies set on user-defined reactions, scaling
states resolved by the engine, activity from the transient-settled TOF.
Golden value: activity(E_CO = E_O = -1 eV, 600 K) = -1.563 +/- 1e-3 eV.
"""

import numpy as np
import pytest

import pycatkin_tpu as pk
from tests.conftest import reference_path

SCOg = 2.0487e-3  # standard entropies (Atkins), eV/K
SO2g = 2.1261e-3


@pytest.fixture
def volcano_system(ref_root):
    return pk.read_from_input_file(
        reference_path("examples", "COOxVolcano", "input.json"))


def set_descriptors(sim, ECO, EO):
    """Per-grid-point descriptor mutation (reference test_2.py:31-49 /
    cooxvolcano.py:28-46)."""
    T = sim.params["temperature"]
    sim.reactions["CO_ads"].dErxn_user = ECO
    sim.reactions["CO_ads"].dGrxn_user = ECO + SCOg * T
    sim.reactions["2O_ads"].dErxn_user = 2.0 * EO
    sim.reactions["2O_ads"].dGrxn_user = 2.0 * EO + SO2g * T
    gelec = dict(zip(sim.snames, np.asarray(sim.free_energy_table().gelec)))
    EO2 = gelec["sO2"]
    sim.reactions["O2_ads"].dErxn_user = EO2
    sim.reactions["O2_ads"].dGrxn_user = EO2 + SO2g * T
    sim.reactions["CO_ox"].dEa_fwd_user = max(gelec["SRTS_ox"] - (ECO + EO),
                                              0.0)
    sim.reactions["O2_2O"].dEa_fwd_user = max(gelec["SRTS_O2"] - EO2, 0.0)
    return gelec


def test_scaling_state_energies(volcano_system):
    gelec = set_descriptors(volcano_system, -1.0, -1.0)
    # Linear scaling relations (reference state.py:490-517):
    assert gelec["sO2"] == pytest.approx(0.17 + 0.89 * (0.5 * -2.0), abs=1e-12)
    assert gelec["SRTS_ox"] == pytest.approx(0.02 + 0.7 * (-1.0 + 0.5 * -2.0),
                                             abs=1e-12)
    assert gelec["SRTS_O2"] == pytest.approx(1.56 + 1.39 * (0.5 * -2.0),
                                             abs=1e-12)


def test_volcano_point_activity(volcano_system):
    set_descriptors(volcano_system, -1.0, -1.0)
    activity = volcano_system.activity(tof_terms=["CO_ox"])
    assert abs(activity - (-1.563)) <= 1e-3


def test_volcano_point_steady_state_matches_transient(volcano_system):
    set_descriptors(volcano_system, -1.0, -1.0)
    a_transient = volcano_system.activity(tof_terms=["CO_ox"], ss_solve=False)
    a_steady = volcano_system.activity(tof_terms=["CO_ox"], ss_solve=True)
    assert a_steady == pytest.approx(a_transient, abs=5e-3)
    assert bool(volcano_system.steady_result.success)


@pytest.mark.slow
def test_volcano_point_drc_implicit_vs_fd(volcano_system):
    """Implicit-vs-FD DRC parity at the golden volcano point: every
    reaction's xi agrees to <=1e-3 and the ID-reactor sum rule holds."""
    set_descriptors(volcano_system, -1.0, -1.0)
    volcano_system.solve_odes()
    xi_imp = volcano_system.degree_of_rate_control(["CO_ox"],
                                                   mode="implicit")
    xi_fd = volcano_system.degree_of_rate_control(["CO_ox"], mode="fd",
                                                  eps=1.0e-3)
    for rname in xi_imp:
        assert abs(xi_imp[rname] - xi_fd[rname]) <= 1e-3, rname
    assert sum(xi_imp.values()) == pytest.approx(1.0, abs=1e-6)
