"""CH4 oxidation network: frontend stress test + independent
thermochemistry oracle.

Mirrors the reference's manual validation script (test/tests.py:20-194),
which cross-checks State thermochemistry against ASE's HarmonicThermo /
IdealGasThermo on test/CH4_input.json (12 plain states, 68
multi-descriptor scaling states, 60 reactions, two surfaces). ASE is not
available in this environment, so the oracle here is the same statistical
mechanics written out independently with scipy.constants -- a genuinely
separate implementation from pycatkin_tpu.ops.thermo (which uses
log-space forms and the reference's constant set).
"""

import math

import numpy as np
import pytest
import scipy.constants as sc

import pycatkin_tpu as pk
from tests.conftest import reference_path

EC, EO = 1.5, 0.2  # descriptor energies (reference tests.py:41-44)


@pytest.fixture(scope="module")
def ch4(ref_root):
    sim = pk.read_from_input_file(reference_path("test", "CH4_input.json"))
    sim.reactions["C_ads"].dErxn_user = EC
    sim.reactions["O_ads"].dErxn_user = EO
    return sim


def test_loads_full_network(ch4):
    spec = ch4.spec
    assert spec.n_species == 80   # 12 plain + 68 scaling states
    assert spec.n_reactions == 60
    assert spec.scl_idx.size == 68
    # Two site types: s* and h* (reference system.py:224-247 prefix rule)
    assert spec.groups.shape[0] == 2


def test_scaling_state_electronic_energies(ch4):
    """Multi-descriptor linear relations: Gelec = gC*EC + gO*EO + b
    (reference tests.py:48-50,100)."""
    fe = ch4.free_energy_table()
    gelec = dict(zip(ch4.snames, np.asarray(fe.gelec)))
    assert gelec["sCO"] == pytest.approx(0.45 * EC + 0.0 * EO + 0.51,
                                         abs=1e-6)
    assert gelec["sC-H--OH"] == pytest.approx(0.89 * EC + 0.46 * EO + 0.29,
                                              abs=1e-6)


def _independent_harmonic(freqs_hz, T):
    """ZPE and harmonic Helmholtz correction from scipy constants
    (independent of pycatkin_tpu.constants / ops.thermo)."""
    h_eV = sc.physical_constants["Planck constant in eV/Hz"][0]
    kT = sc.physical_constants["Boltzmann constant in eV/K"][0] * T
    zpe = 0.5 * h_eV * sum(freqs_hz)
    a_corr = zpe + kT * sum(math.log(1.0 - math.exp(-h_eV * f / kT))
                            for f in freqs_hz)
    return zpe, a_corr


def test_adsorbate_free_energy_vs_independent_oracle(ch4):
    """Harmonic free energy of sCO and the sC-H--OH TS match the
    independently computed E + ZPE + kT*sum ln(1-exp(-hf/kT))
    (reference tests.py:66-103 vs ASE HarmonicThermo)."""
    T = ch4.params["temperature"]
    fe = ch4.free_energy_table()
    gelec = dict(zip(ch4.snames, np.asarray(fe.gelec)))
    gfree = dict(zip(ch4.snames, np.asarray(fe.gfree)))
    for name in ("sCO", "sC-H--OH"):
        st = ch4.states[name]
        _, a_corr = _independent_harmonic(list(st.used_frequencies()), T)
        assert gfree[name] - gelec[name] == pytest.approx(a_corr, abs=2e-3)


def test_gas_free_energy_vs_independent_oracle(ch4):
    """O2 translational+rotational free energy against an independent
    ideal-gas implementation (reference tests.py:105-117 vs ASE
    IdealGasThermo). Linear molecule, sigma=2."""
    T = ch4.params["temperature"]
    p = ch4.params["pressure"]
    st = ch4.states["O2"]
    fe = ch4.free_energy_table()
    i = ch4.snames.index("O2")

    kB_J = sc.k
    h_J = sc.h
    JtoeV = 1.0 / sc.e
    m = st.mass * sc.physical_constants["atomic mass constant"][0]
    q_t = (kB_J * T / p) * (2 * math.pi * m * kB_J * T / h_J**2) ** 1.5
    I = max(np.asarray(st.inertia)) * 1.66053906660e-47
    q_r = 8 * math.pi**2 * kB_J * T * I / (st.sigma * h_J**2)
    g_ind = -kB_J * T * (math.log(q_t) + math.log(q_r)) * JtoeV

    ours = float(fe.gtran[i] + fe.grota[i])
    assert ours == pytest.approx(g_ind, rel=2e-3)


def test_rate_constant_consistency(ch4):
    """kf = (kBT/h) exp(-max(dGa,0)/RT) and Keq = exp(-dGr/RT) for an
    activated step; kr = kf/Keq (reference tests.py:126-194)."""
    from pycatkin_tpu.constants import R, h, kB
    T = ch4.params["temperature"]
    spec = ch4.spec
    re = ch4.reaction_energy_table()
    kf, kr, keq = ch4.rate_constant_table()
    j = spec.rindex("R1")
    dGa = max(float(re.dGa_fwd[j]), 0.0)
    dGr = float(re.dGrxn[j])
    assert kf[j] == pytest.approx(kB * T / h * math.exp(-dGa / (R * T)),
                                  rel=1e-10)
    assert keq[j] == pytest.approx(math.exp(-dGr / (R * T)), rel=1e-10)
    assert kr[j] == pytest.approx(kf[j] / keq[j], rel=1e-10)


def test_steady_state_solves(ch4):
    """Full 80-species / 60-reaction steady solve from the start state
    (reference tests.py:130 build + find_steady)."""
    res = ch4.find_steady(use_transient_guess=False)
    assert bool(res.success)
    y = np.asarray(res.x)
    sums = np.asarray(ch4.spec.groups) @ y
    np.testing.assert_allclose(sums, 1.0, atol=5e-2)
    assert np.all(y[ch4.spec.dynamic_indices] >= -1e-8)


@pytest.mark.slow
def test_steady_root_is_physical(ch4):
    """The default find_steady lands on the PHYSICAL root -- the t->inf
    limit of the start state. The CH4 network is multistable (several
    individually stable branches), so an unseeded Newton solve can
    converge onto a branch the reactor never reaches; the reference
    avoids this by always seeding find_steady from the transient tail
    (old_system.py:393-395). With no stored transient, the facade now
    integrates first (times are configured), then polishes."""
    sim = ch4.copy()
    sim.params["n_out"] = 40
    res = sim.find_steady()   # no stored solution -> auto-integrates
    assert bool(res.success)
    assert sim.solution is not None, "transient seeding did not run"
    dyn = sim.spec.dynamic_indices
    y_inf = sim.solution[-1][dyn]
    # Basin identity: the polished root is the transient tail's root.
    # 5e-6 headroom: a hard tail can carry a ~clamp_lo (1e-6) phantom
    # projection offset when the Newton finish declines to replace it.
    np.testing.assert_allclose(np.asarray(res.x)[dyn], y_inf, atol=5e-6)
    # ... and it is dynamically stable.
    from pycatkin_tpu import engine
    assert bool(engine.check_stability(sim.spec, sim.conditions(),
                                       np.asarray(res.x)))
