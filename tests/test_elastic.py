"""Elastic sweep scheduler: lease-based work queue, supervision, chaos
(robustness/scheduler.py; docs/failure_model.md "The elastic
scheduler").

Three layers of proof:

- unit tests against the pure lease/task math and the on-disk
  :class:`WorkQueue` primitives (``now`` is always passed explicitly,
  so nothing here sleeps);
- the chaos drill: a real multi-process elastic sweep with two workers
  SIGKILLed mid-chunk and a third's heartbeat stalled past the TTL,
  whose merged result must be **bit-identical** to the undisturbed
  in-process sweep of the same chunk grid;
- the poison drill: a span that kills every worker touching it must be
  bisected down to the minimum chunk and quarantined -- one lost lane,
  never a lost sweep.

The subprocess runs double as fixtures for the forensics
worker-lifecycle section and the ``obsview --workers`` timeline.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from pycatkin_tpu.robustness.faults import FaultPlan
from pycatkin_tpu.robustness.scheduler import (WorkQueue, bisect_span,
                                               covering_spans,
                                               lease_expired,
                                               lease_record, parse_task_id,
                                               run_elastic, task_id)
from pycatkin_tpu.utils.retry import classify_worker_exit

pytestmark = pytest.mark.faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------
# Pure lease/task math.

def test_task_id_roundtrip():
    assert task_id(4, 8) == "t00004_00008"
    assert parse_task_id("t00004_00008") == (4, 8)
    a, b = parse_task_id(task_id(0, 65536))
    assert (a, b) == (0, 65536)


def test_lease_expiry_math():
    rec = lease_record("w0-123", ttl_s=30.0, now=1000.0)
    assert rec["deadline"] == 1030.0
    assert not lease_expired(rec, 1029.9)
    assert lease_expired(rec, 1030.0)
    assert lease_expired({}, 0.0)            # malformed = expired
    stolen = lease_record("w1-456", 30.0, 1040.0, stolen_from="w0-123")
    assert stolen["stolen_from"] == "w0-123"


def test_bisect_floor():
    assert bisect_span(0, 8, 4) == 4         # width exactly 2*min splits
    assert bisect_span(0, 7, 4) is None      # a child would be < min
    assert bisect_span(4, 6, 1) == 5
    assert bisect_span(4, 5, 1) is None      # the quarantine floor
    assert bisect_span(0, 4096, 1) == 2048


def test_covering_spans_tiling_and_overlap():
    def rec(a, b):
        return {"start": a, "stop": b, "tid": task_id(a, b)}

    assert covering_spans([rec(0, 4)], 8) is None          # gap at tail
    assert covering_spans([rec(4, 8)], 8) is None          # gap at head
    full = covering_spans([rec(4, 8), rec(0, 4)], 8)
    assert [(a, b) for a, b, _ in full] == [(0, 4), (4, 8)]
    # Parent/child duplicates (a stalled owner finishing the parent
    # after its children were re-solved): widest span wins, in either
    # input order.
    for recs in ([rec(0, 8), rec(0, 4), rec(4, 8)],
                 [rec(0, 4), rec(4, 8), rec(0, 8)]):
        cover = covering_spans(recs, 8)
        assert [(a, b) for a, b, _ in cover] == [(0, 8)]
    # Partial overlap: child head already covered, tail still needed.
    cover = covering_spans([rec(0, 6), rec(4, 8)], 8)
    assert [(a, b) for a, b, _ in cover] == [(0, 6), (4, 8)]


def test_classify_worker_exit_taxonomy():
    ok = classify_worker_exit(0)
    assert ok.kind == "ok" and not ok.transient
    sig = classify_worker_exit(-9)
    assert sig.kind == "signal-death" and sig.transient
    rc = classify_worker_exit(3)
    assert rc.kind == "nonzero-exit" and not rc.transient
    to = classify_worker_exit(None, timed_out=True)
    assert to.kind == "timeout" and to.transient


# ---------------------------------------------------------------------
# WorkQueue primitives (explicit `now`; no sleeping).

def test_claim_is_first_wins(tmp_path):
    q = WorkQueue(str(tmp_path)).setup()
    tid = q.add_task(0, 4)
    assert q.claim(tid, "w0-1", ttl_s=10.0, now=100.0)
    assert not q.claim(tid, "w1-2", ttl_s=10.0, now=100.0)
    assert q.read_lease(tid)["owner"] == "w0-1"


def test_renew_is_fenced(tmp_path):
    q = WorkQueue(str(tmp_path)).setup()
    tid = q.add_task(0, 4)
    q.claim(tid, "w0-1", ttl_s=10.0, now=100.0)
    assert q.renew(tid, "w0-1", ttl_s=10.0, now=105.0)
    assert q.read_lease(tid)["deadline"] == 115.0
    assert not q.renew(tid, "w1-2", ttl_s=10.0, now=105.0)
    # After a steal the old owner's renewal must report the loss.
    q.requeue(tid)
    q.claim(tid, "w1-2", ttl_s=10.0, now=106.0, stolen_from="w0-1")
    assert not q.renew(tid, "w0-1", ttl_s=10.0, now=107.0)
    assert q.read_lease(tid)["owner"] == "w1-2"


def test_claim_next_steals_only_expired(tmp_path):
    q = WorkQueue(str(tmp_path)).setup()
    tid = q.add_task(0, 4)
    q.claim(tid, "w0-1", ttl_s=1.0, now=100.0)
    assert q.claim_next("w1-2", ttl_s=1.0, now=100.5) is None
    got = q.claim_next("w1-2", ttl_s=1.0, now=102.0)
    assert got == (tid, "w0-1")
    assert q.read_lease(tid)["stolen_from"] == "w0-1"


def test_done_record_is_exclusive(tmp_path):
    q = WorkQueue(str(tmp_path)).setup()
    tid = q.add_task(0, 4)
    assert q.write_done(tid, {"tid": tid, "start": 0, "stop": 4,
                              "status": "done", "owner": "w0-1"})
    assert not q.write_done(tid, {"tid": tid, "start": 0, "stop": 4,
                                  "status": "done", "owner": "w1-2"})
    assert q.done()[tid]["owner"] == "w0-1"
    assert not q.stop_requested()
    q.request_stop()
    assert q.stop_requested()


# ---------------------------------------------------------------------
# Satellite: atomic result payloads + the fsync knob.

def test_atomic_save_results_roundtrip(tmp_path, monkeypatch):
    from pycatkin_tpu.utils.io import atomic_save_results, load_results

    arrays = {"y": np.linspace(0.0, 1.0, 7),
              "success": np.ones(7, dtype=bool)}
    for fsync_env in ("", "1"):
        monkeypatch.setenv("PYCATKIN_JOURNAL_FSYNC", fsync_env)
        path = str(tmp_path / f"res_{fsync_env or '0'}.npz")
        atomic_save_results(path, arrays)
        back = load_results(path)
        for k in arrays:
            np.testing.assert_array_equal(arrays[k], back[k])
    leftovers = [f for f in os.listdir(tmp_path) if ".tmp" in f]
    assert leftovers == []


# ---------------------------------------------------------------------
# Satellite: fleet-wide fault budgets (`state_dir` ticket files) -- a
# restarted worker re-reading the same times=1 plan must NOT re-fire.

def test_fault_budget_is_fleet_wide(tmp_path):
    plan_text = json.dumps({
        "specs": [{"site": "s", "kind": "stall", "times": 1,
                   "delay_s": 0.0}],
        "state_dir": str(tmp_path / "faultstate")})
    first = FaultPlan.from_env(plan_text)
    first.on_call("s")
    assert [e["kind"] for e in first.log] == ["stall"]
    # A fresh plan from the same env text = a restarted incarnation.
    second = FaultPlan.from_env(plan_text)
    second.on_call("s")
    assert second.log == []
    # Without a state_dir the budget is per-process: both fire.
    local_text = json.dumps([{"site": "s", "kind": "stall", "times": 1,
                              "delay_s": 0.0}])
    for plan in (FaultPlan.from_env(local_text),
                 FaultPlan.from_env(local_text)):
        plan.on_call("s")
        assert len(plan.log) == 1


# ---------------------------------------------------------------------
# The chaos proof: two workers SIGKILLed mid-chunk, one heartbeat
# stalled past the TTL -- the merged sweep must be bit-identical to the
# undisturbed in-process sweep of the same chunk grid.

N_LANES = 12
CHUNK = 2


def _drill_sim():
    from pycatkin_tpu.models.synthetic import synthetic_system
    from pycatkin_tpu.parallel.batch import broadcast_conditions

    sim = synthetic_system(n_species=8, n_reactions=10, seed=0)
    conds = broadcast_conditions(sim.conditions(), N_LANES)
    conds = conds._replace(T=np.linspace(450.0, 650.0, N_LANES))
    return sim, conds


@pytest.fixture(scope="module")
def chaos_run(tmp_path_factory):
    sim, conds = _drill_sim()
    td = tmp_path_factory.mktemp("chaos")
    plan = {"specs": [
        {"site": "worker:0", "kind": "worker-crash", "times": 1},
        {"site": "worker:1", "kind": "worker-crash", "times": 1},
        {"site": "heartbeat:2", "kind": "heartbeat-stall", "times": 1,
         "delay_s": 120.0}],
        "state_dir": str(td / "faultstate")}
    out, report = run_elastic(
        sim, conds, n_workers=3, chunk=CHUNK,
        work_dir=str(td / "work"),
        worker_env={"PYCATKIN_FAULTS": json.dumps(plan),
                    "JAX_PLATFORMS": "cpu",
                    "PALLAS_AXON_POOL_IPS": ""},
        ttl_s=4.0, heartbeat_s=0.4, max_kills=5,
        restart_base_s=0.2, restart_max_s=1.0, timeout=600.0)
    return sim, conds, out, report


def test_chaos_bit_identity(chaos_run):
    from pycatkin_tpu.parallel.batch import sweep_steady_state

    sim, conds, out, report = chaos_run
    # The carnage happened: both scripted crashes landed (signal deaths
    # NOT initiated by the supervisor) plus the stall-kill, and every
    # death was supervised back to life.
    crashes = [e for e in report["exits"]
               if e["kind"] == "signal-death" and not e["self_killed"]]
    stalled = [e for e in report["exits"] if e["self_killed"]]
    assert len(crashes) >= 2
    assert len(stalled) >= 1
    assert report["restarts"] >= 3
    assert report["leases"]["expired"] >= 1
    assert report["quarantined"] == []
    assert report["n_failed_lanes"] == 0

    # Bit-identity against the undisturbed same-grid sweep: the
    # deterministic per-chunk programs make duplicate/stolen work
    # indistinguishable from first-try work.
    ref_parts = []
    for a in range(0, N_LANES, CHUNK):
        sub = type(conds)(**{
            f: np.asarray(getattr(conds, f))[a:a + CHUNK]
            for f in conds._fields})
        ref = sweep_steady_state(sim.spec, sub)
        ref_parts.append({k: np.asarray(v) for k, v in ref.items()})
    merged = {k: np.concatenate([p[k] for p in ref_parts], axis=0)
              for k in ref_parts[0]}
    assert set(out) == set(merged)
    for k in merged:
        np.testing.assert_array_equal(
            out[k], merged[k],
            err_msg=f"chaos run diverged from undisturbed sweep at {k!r}")


def test_chaos_forensics_lifecycle(chaos_run):
    from pycatkin_tpu.robustness.forensics import (format_failure_report,
                                                   sweep_failure_report,
                                                   worker_lifecycle)

    _, conds, out, report = chaos_run
    wl = worker_lifecycle(report["events"])
    assert wl["n_restarts"] >= 3
    assert wl["spawns"] >= 3
    assert wl["killed_stalled"]
    assert wl["leases_expired"]
    assert wl["quarantined"] == []
    full = sweep_failure_report(out, conds=conds,
                                events=report["events"])
    assert full["worker_lifecycle"]["n_restarts"] == wl["n_restarts"]
    text = format_failure_report(full)
    assert "worker lifecycle" in text
    assert "restarted" in text


# ---------------------------------------------------------------------
# The poison proof: a span that kills every worker touching it is
# bisected to the floor and quarantined; the rest of the sweep lands.

@pytest.fixture(scope="module")
def poison_run(tmp_path_factory):
    sim, conds = _drill_sim()
    td = tmp_path_factory.mktemp("poison")
    # Unlimited crash on any task starting at lane 4: the id encodes
    # the span, so the pattern follows the poison through bisection
    # ([4,8) -> [4,6) -> [4,5)) while the split-off healthy halves
    # ([6,8), [5,6)) escape it.
    plan = [{"site": "lease:t00004_*", "kind": "worker-crash",
             "times": None}]
    work_dir = str(td / "work")
    out, report = run_elastic(
        sim, conds, n_workers=2, chunk=4, min_chunk=1, max_kills=1,
        work_dir=work_dir,
        worker_env={"PYCATKIN_FAULTS": json.dumps(plan),
                    "JAX_PLATFORMS": "cpu",
                    "PALLAS_AXON_POOL_IPS": ""},
        ttl_s=6.0, heartbeat_s=0.5,
        restart_base_s=0.2, restart_max_s=1.0, timeout=600.0)
    return out, report, work_dir


def test_poison_bisected_to_floor_and_quarantined(poison_run):
    out, report, _ = poison_run
    assert set(report["bisected"]) == {"t00004_00008", "t00004_00006"}
    assert report["quarantined"] == ["t00004_00005"]
    assert report["restarts"] >= 3            # one per poisoned claim
    success = np.asarray(out["success"], dtype=bool)
    assert success.shape == (N_LANES,)
    assert not success[4]                     # the one poisoned lane
    assert success[np.arange(N_LANES) != 4].all()
    quarantined = np.asarray(out["quarantined"], dtype=bool)
    assert quarantined[4]
    assert int(quarantined.sum()) == 1


def test_obsview_workers_timeline(poison_run):
    _, _, work_dir = poison_run
    events_path = os.path.join(work_dir, "events.jsonl")
    assert os.path.exists(events_path)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "obsview.py"),
         "--workers", events_path],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, timeout=120)
    assert proc.returncode == 0, proc.stderr
    assert "task-quarantined" in proc.stdout
    assert "task-bisected" in proc.stdout
    assert "restart" in proc.stdout
