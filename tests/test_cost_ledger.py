"""Device cost ledger (obs/costs.py) unit + integration contracts.

The ledger is the compile-time device-cost truth the perf tooling joins
against: every prewarmed program must own a row with XLA's harvested
FLOP/byte analyses, the row must survive the AOT pack export -> import
round trip (a worker booted from a pack never recompiles, so the
analyses can only ride in the entries), and the dispatch-wall join must
derive achieved FLOP/s -- while MFU stays ABSENT on CPU, where no
honest ceiling exists. The unit half pins the defensive harvesting,
the merge semantics (compile-time harvest wins over a cache replay of
itself) and the ``count=0`` fold that lets the fused sweep attribute
its bundle materialization without double-counting the dispatch.
"""

import math
import types

import numpy as np
import pytest

from pycatkin_tpu import engine
from pycatkin_tpu.models.synthetic import synthetic_system
from pycatkin_tpu.obs import costs
from pycatkin_tpu.parallel import compile_pool
from pycatkin_tpu.parallel.batch import (broadcast_conditions,
                                         clear_program_caches,
                                         prewarm_sweep_programs,
                                         sweep_steady_state)


@pytest.fixture(autouse=True)
def _fresh_state():
    clear_program_caches()
    costs.reset()
    yield
    clear_program_caches()
    costs.reset()


# -- unit: peaks, flop model, harvesting, ledger semantics ------------

def test_device_peak_known_kinds_and_honest_absence():
    for kind in ("TPU v5 lite", "TPU v5e", "tpu v5p"):
        peak = costs.device_peak(kind)
        assert peak is not None, kind
        assert peak["flops_per_s"] > 0 and peak["bytes_per_s"] > 0
    # Returned dict is a copy: mutating it must not poison the table.
    peak = costs.device_peak("TPU v5e")
    peak["flops_per_s"] = -1.0
    assert costs.device_peak("TPU v5e")["flops_per_s"] > 0
    # No fabricated ceiling for unknown kinds -- CPU included.
    assert costs.device_peak("cpu") is None
    assert costs.device_peak("") is None
    assert costs.device_peak(None) is None


def test_flops_per_iteration_model_shape():
    base = costs.flops_per_iteration(24, 32, 20, 1)
    assert base > 0 and math.isfinite(base)
    # Chord re-solves add work; more dynamic species add work.
    assert costs.flops_per_iteration(24, 32, 20, 1, chords=4) > base
    assert costs.flops_per_iteration(24, 32, 40, 1) > base
    # Past the unrolled-solve crossover the model switches to the
    # LU 2/3 n^3 coefficient but must stay monotone in n_dyn.
    assert (costs.flops_per_iteration(700, 500, 190, 1)
            > costs.flops_per_iteration(700, 500, 48, 1))


def test_harvest_cost_defensive_probes():
    class _Broken:
        def cost_analysis(self):
            raise RuntimeError("backend refuses")
    assert costs.harvest_cost(_Broken()) is None

    class _ListCA:
        # Older jax returns a list-of-dicts; memory_analysis may raise.
        def cost_analysis(self):
            return [{"flops": 12.0, "bytes accessed": 34.0}]

        def memory_analysis(self):
            raise RuntimeError("absent on this backend")
    assert costs.harvest_cost(_ListCA()) == {"flops": 12.0,
                                             "bytes_accessed": 34.0}

    class _Sentinels:
        # Negative / non-finite values are backend sentinels, not data.
        def cost_analysis(self):
            return {"flops": -1.0, "bytes accessed": float("nan")}
    assert costs.harvest_cost(_Sentinels()) is None

    class _MemOnly:
        def cost_analysis(self):
            raise RuntimeError
        def memory_analysis(self):
            return types.SimpleNamespace(temp_size_in_bytes=10,
                                         output_size_in_bytes=20)
    assert costs.harvest_cost(_MemOnly()) == {"temp_bytes": 10.0,
                                              "output_bytes": 20.0}


def test_record_merge_first_write_wins():
    led = costs.CostLedger()
    led.record("k", kind="fused", label="fused sweep",
               cost={"flops": 5.0}, source="compiled")
    # A later cache replay of the same program must not overwrite the
    # compile-time harvest (or the identity fields).
    led.record("k", kind="other", label="other",
               cost={"flops": 9.0, "bytes_accessed": 3.0},
               source="cache")
    row = led.row("k")
    assert row["kind"] == "fused" and row["label"] == "fused sweep"
    assert row["flops"] == 5.0
    assert row["bytes_accessed"] == 3.0      # gap-filling still merges
    assert row["source"] == "compiled"
    assert led.keys() == ["k"] and len(led) == 1


def test_note_dispatch_count_zero_folds_wall_without_dispatch():
    led = costs.CostLedger()
    led.note_dispatch("k", 0.5)
    # The fused path's bundle materialization: extra blocked wall on a
    # dispatch _registered_call already counted.
    led.note_dispatch("k", 0.25, count=0)
    row = led.row("k")
    assert row["dispatches"] == 1
    assert row["blocked_wall_s"] == pytest.approx(0.75)
    # Unknown keys still get a (cost-less) row -- the count survives.
    led.note_dispatch("ghost", 0.1)
    assert led.row("ghost")["dispatches"] == 1


def test_snapshot_derives_mfu_only_with_a_known_peak():
    led = costs.CostLedger()
    led.record("k", cost={"flops": 1.519e11, "bytes_accessed": 3.228e11})
    led.note_dispatch("k", 1.0)

    snap = led.snapshot("TPU v5e")
    row = snap["programs"]["k"]
    assert row["achieved_flops_per_s"] == pytest.approx(1.519e11)
    assert row["mfu"] == pytest.approx(1.0)
    assert row["hbm_util"] == pytest.approx(1.0)
    assert snap["totals"]["mfu"] == pytest.approx(1.0)
    assert snap["peak"]["flops_per_s"] == pytest.approx(1.519e11)

    # CPU: achieved rates still derived, MFU absent -- never fabricated.
    snap = led.snapshot("cpu")
    row = snap["programs"]["k"]
    assert row["achieved_flops_per_s"] == pytest.approx(1.519e11)
    assert "mfu" not in row and "hbm_util" not in row
    assert snap["peak"] is None and "mfu" not in snap["totals"]

    # A row with cost but no dispatch derives nothing.
    led.record("idle", cost={"flops": 1.0})
    assert "achieved_flops_per_s" not in led.snapshot("cpu")["programs"]["idle"]


def test_snapshot_scores_tiered_rows_against_their_own_roofline():
    """Precision-tiered programs (``:p32`` in the kind) score against
    the native-f32 ceiling, f64 rows against the emulated-f64 one, and
    the aggregate MFU is the tier-weighted peak budget -- identical to
    the historical formula when every row is f64
    (docs/perf_precision_tiers.md)."""
    peak = costs.device_peak("TPU v5e")
    f64_peak, f32_peak = peak["flops_per_s"], peak["flops_per_s_f32"]
    assert f32_peak > f64_peak
    assert costs.peak_flops_for_tier(peak, "f64") == f64_peak
    assert costs.peak_flops_for_tier(peak, "f32-polish") == f32_peak
    assert costs.peak_flops_for_tier(None, "f32-polish") is None

    led = costs.CostLedger()
    led.record("fused:opts", kind="fused:opts",
               cost={"flops": f64_peak})
    led.note_dispatch("fused:opts", 1.0)
    led.record("fused:opts:p32", kind="fused:opts:p32",
               cost={"flops": f32_peak})
    led.note_dispatch("fused:opts:p32", 1.0)

    snap = led.snapshot("TPU v5e")
    r64 = snap["programs"]["fused:opts"]
    r32 = snap["programs"]["fused:opts:p32"]
    assert r64["tier"] == "f64" and r32["tier"] == "f32-polish"
    # Each row hits 1.0 MFU against its OWN roofline; against the f64
    # ceiling the f32 row would read a fabricated ~16x.
    assert r64["mfu"] == pytest.approx(1.0)
    assert r32["mfu"] == pytest.approx(1.0)
    assert snap["totals"]["mfu"] == pytest.approx(1.0)
    assert snap["totals"]["mfu_by_tier"] == {
        "f32-polish": pytest.approx(1.0), "f64": pytest.approx(1.0)}

    # All-f64 ledger: the tier-weighted budget reduces to the
    # historical flops / (peak * wall) formula exactly.
    led2 = costs.CostLedger()
    led2.record("a", kind="steady:a", cost={"flops": 0.5 * f64_peak})
    led2.note_dispatch("a", 2.0)
    snap2 = led2.snapshot("TPU v5e")
    assert snap2["totals"]["mfu"] == pytest.approx(
        0.5 * f64_peak / (f64_peak * 2.0))
    assert snap2["totals"]["mfu_by_tier"] == {
        "f64": pytest.approx(snap2["totals"]["mfu"])}


def test_module_level_ledger_snapshot_probes_live_device():
    costs.record("k", kind="fused", cost={"flops": 4.0})
    costs.note_dispatch("k", 0.5)
    # jax is imported (CPU backend) -> probed kind has no peak.
    snap = costs.ledger_snapshot()
    assert snap["peak"] is None
    assert snap["programs"]["k"]["achieved_flops_per_s"] == pytest.approx(8.0)
    costs.reset()
    assert len(costs.default_ledger) == 0


# -- integration: prewarm -> ledger rows -> dispatch join -------------

@pytest.fixture(scope="module")
def problem():
    sim = synthetic_system(n_species=24, n_reactions=32)
    spec = sim.spec
    n = 24
    conds = broadcast_conditions(sim.conditions(), n)
    conds = conds._replace(T=np.linspace(420.0, 780.0, n))
    mask = engine.tof_mask_for(spec, [spec.rnames[-1]])
    return spec, conds, mask


def test_every_prewarmed_program_owns_a_cost_row(tmp_path, problem):
    spec, conds, mask = problem
    cache = compile_pool.AOTCache(
        root=str(tmp_path),
        fingerprint=compile_pool.spec_fingerprint(spec))
    stats = prewarm_sweep_programs(spec, conds, tof_mask=mask,
                                   buckets=(), check_stability=False,
                                   cache=cache)
    keys = [key for (_spec, key) in compile_pool._REGISTRY]
    assert len(keys) == int(stats) >= 1
    for key in keys:
        row = costs.default_ledger.row(key)
        assert row is not None, f"prewarmed program {key} has no row"
        # The CPU backend exposes both analyses; nonneg by harvest rule.
        assert row.get("flops", -1.0) >= 0.0, key
        assert row.get("bytes_accessed", -1.0) >= 0.0, key
        assert "kind" in row, key

    # The dispatch-wall join: one sweep through the registered
    # executables must light up achieved FLOP/s on the hot programs.
    out = sweep_steady_state(spec, conds, tof_mask=mask)
    assert bool(np.all(np.asarray(out["success"])))
    snap = costs.default_ledger.snapshot("cpu")
    hot = [r for r in snap["programs"].values()
           if r.get("dispatches", 0) > 0 and r.get("blocked_wall_s", 0) > 0]
    assert hot, "no dispatch ever reached the ledger"
    assert any("achieved_flops_per_s" in r for r in hot)
    assert all("mfu" not in r for r in snap["programs"].values())
    assert snap["totals"]["dispatches"] >= 1


def test_cost_rows_survive_pack_round_trip_and_cache_reload(tmp_path,
                                                            problem):
    spec, conds, mask = problem
    fp = compile_pool.spec_fingerprint(spec)
    root_a, root_b = tmp_path / "a", tmp_path / "b"
    pack = str(tmp_path / "cache.aotpack.tgz")
    prewarm_sweep_programs(
        spec, conds, tof_mask=mask, buckets=(), check_stability=False,
        cache=compile_pool.AOTCache(root=str(root_a), fingerprint=fp))
    costed = {k: costs.default_ledger.row(k)
              for k in costs.default_ledger.keys()}
    costed = {k: r for k, r in costed.items() if "flops" in r}
    assert costed, "prewarm harvested no cost rows"

    exported = compile_pool.export_cache_pack(pack, cache_root=str(root_a))
    assert exported["entries"] >= len(costed)

    # A "worker booted from a pack": empty ledger, import only.
    costs.reset()
    assert len(costs.default_ledger) == 0
    imported = compile_pool.import_cache_pack(pack, cache_root=str(root_b))
    assert imported["imported"] == exported["entries"]
    for key, row in costed.items():
        got = costs.default_ledger.row(key)
        assert got is not None, f"pack import dropped cost row {key}"
        assert got["source"] == "pack"
        assert got["flops"] == row["flops"]
        assert got.get("bytes_accessed") == row.get("bytes_accessed")

    # A cache-warmed restart replays entry costs at load time.
    clear_program_caches()
    costs.reset()
    stats = prewarm_sweep_programs(
        spec, conds, tof_mask=mask, buckets=(), check_stability=False,
        cache=compile_pool.AOTCache(root=str(root_b), fingerprint=fp))
    assert stats.compiled == 0 and stats.loaded == int(stats)
    for key, row in costed.items():
        got = costs.default_ledger.row(key)
        assert got is not None and got["source"] == "cache", key
        assert got["flops"] == row["flops"]
