"""Perf-regression sentinel: obs/history.py math + tools/perfwatch CLI.

The sentinel's one job is telling regression from noise: baselines are
median +/- MAD (one flaky round cannot drag them), a finding needs BOTH
the MAD band and the relative floor cleared in the BAD direction, and
short histories stay silent rather than guessing. These tests pin that
math directly on hand-built series, the BENCH_r*.json ingest (wrapped
``{"parsed": ...}`` records, junk files skipped, mfu pulled from the
cost-ledger totals), the dominant-span + cost-ledger attribution, and
the CLI's exit codes -- ``--check`` is a CI gate, so exit 1 must mean
exactly "the newest round regressed beyond noise".
"""

import json
import os

import pytest

from pycatkin_tpu.obs import history as hist
from tools.perfwatch import _synthetic_round, main


# -- baseline / extraction math ---------------------------------------

def test_baseline_is_robust_to_one_flaky_round():
    b = hist.baseline([1.0, 2.0, 3.0, 4.0, 100.0])
    assert b == {"median": 3.0, "mad": 1.0, "n": 5}
    b = hist.baseline([1.0, 3.0])
    assert b["median"] == 2.0 and b["n"] == 2
    assert hist.baseline([]) is None


def test_extract_metrics_unwraps_and_falls_back_to_ledger_mfu():
    rec = {"parsed": {"value": 100.0, "max_over_median": "not-a-number",
                      "cost_ledger": {"totals": {"mfu": 0.25}}}}
    m = hist.extract_metrics(rec)
    assert m == {"value": 100.0, "mfu": 0.25}
    # An explicit top-level mfu wins over the ledger fallback.
    assert hist.extract_metrics({"value": 1.0, "mfu": 0.5})["mfu"] == 0.5
    assert hist.extract_metrics("garbage") == {}


def test_extract_metrics_pulls_serve_slos_from_the_sub_object():
    rec = {"backend": "cpu", "value": 10.0,
           "serve": {"p50_s": 0.8, "p99_s": 2.5,
                     "zero_compile_rate": 1.0, "mean_occupancy": 0.85,
                     "throughput_rps": 3.0}}  # untracked key: ignored
    m = hist.extract_metrics(rec)
    assert m["serve_p50_s"] == 0.8 and m["serve_p99_s"] == 2.5
    assert m["serve_zero_compile_rate"] == 1.0
    assert m["serve_mean_occupancy"] == 0.85
    assert "serve_throughput_rps" not in m
    # Explicit top-level serve_* wins over the sub-object fallback,
    # and a record with no serve sub-object simply lacks the metrics.
    both = hist.extract_metrics({"serve_p99_s": 9.0,
                                 "serve": {"p99_s": 1.0}})
    assert both["serve_p99_s"] == 9.0
    assert "serve_p99_s" not in hist.extract_metrics({"value": 1.0})
    # A non-dict serve field must not crash the ingest.
    assert "serve_p99_s" not in hist.extract_metrics({"serve": "gone"})


def _entries(values, metric="value"):
    return [{"metrics": {metric: v}} for v in values]


def test_flag_regressions_on_serve_slos():
    history = _entries([2.0, 2.2, 1.9, 2.1, 2.0], metric="serve_p99_s")
    cand = {"serve": {"p99_s": 6.0}}
    found = hist.flag_regressions(history, cand)
    assert [f["metric"] for f in found] == ["serve_p99_s"]
    assert found[0]["direction"] == "lower"
    # Faster tail latency is an improvement, never a finding.
    assert hist.flag_regressions(history, {"serve": {"p99_s": 1.0}}) == []
    # zero_compile_rate is higher-is-better: a warm serving path that
    # starts compiling again IS a regression.
    rate = _entries([1.0] * 5, metric="serve_zero_compile_rate")
    assert hist.flag_regressions(
        rate, {"serve": {"zero_compile_rate": 0.5}})
    assert hist.flag_regressions(
        rate, {"serve": {"zero_compile_rate": 1.0}}) == []


def test_flag_regressions_noise_band_and_direction():
    history = _entries([1000.0, 1012.0, 991.0, 1005.0, 997.0, 1008.0])
    assert hist.flag_regressions(history, {"value": 994.0}) == []
    found = hist.flag_regressions(history, {"value": 500.0})
    assert len(found) == 1
    f = found[0]
    assert f["metric"] == "value" and f["direction"] == "higher"
    assert f["ratio"] == pytest.approx(500.0 / f["median"], abs=1e-3)
    assert f["n_history"] == 6
    # Improvement in a higher-is-better metric: never a finding.
    assert hist.flag_regressions(history, {"value": 2000.0}) == []
    # Lower-is-better metric doubling IS a finding; halving is not.
    low = _entries([2.0, 2.1, 1.9, 2.05], metric="prewarm_warm_s")
    assert hist.flag_regressions(low, {"prewarm_warm_s": 4.5})
    assert hist.flag_regressions(low, {"prewarm_warm_s": 1.0}) == []


def test_flag_regressions_min_history_and_rel_floor_gates():
    history = _entries([1000.0, 1012.0, 991.0, 1005.0, 997.0, 1008.0])
    assert hist.flag_regressions(history[:2], {"value": 500.0}) == []
    # Dead-quiet history (MAD = 0): the relative floor guards against
    # flagging every rounding wobble.
    quiet = _entries([1000.0] * 5)
    assert hist.flag_regressions(quiet, {"value": 950.0}) == []
    assert hist.flag_regressions(quiet, {"value": 880.0})
    # A wider floor silences even a real-looking drop.
    assert hist.flag_regressions(quiet, {"value": 880.0},
                                 rel_floor=0.2) == []


def test_flag_regressions_segments_history_by_backend():
    """A CPU round compared against TPU throughput history would flag
    a 100x 'regression' that is really a hardware change: baselines
    must only ever mix same-backend rounds, and records predating the
    backend field count as TPU (every checked-in round before it was
    a v5e run)."""
    tpu_history = _entries([10000.0, 10120.0, 9910.0, 10050.0])
    cpu_cand = {"backend": "cpu", "value": 900.0}
    # Legacy entries (no backend anywhere) default to TPU...
    assert hist.record_backend({}) == "tpu"
    assert hist.record_backend({"parsed": {"backend": "cpu"}}) == "cpu"
    # ...so the CPU candidate has zero same-backend history: silence,
    # not a 10x finding.
    assert hist.flag_regressions(tpu_history, cpu_cand) == []
    # With enough CPU rounds on file, a real CPU regression still
    # flags -- the TPU entries are simply not its baseline.
    mixed = tpu_history + [
        {"backend": "cpu", "metrics": {"value": v}}
        for v in (900.0, 905.0, 897.0, 902.0)]
    found = hist.flag_regressions(mixed, {"backend": "cpu",
                                          "value": 450.0})
    assert len(found) == 1 and found[0]["n_history"] == 4
    # And a TPU candidate keeps ignoring the CPU rounds.
    assert hist.flag_regressions(mixed, {"value": 9950.0}) == []


def test_attribution_names_span_and_program_drops():
    prior = {"record": {"cost_ledger": {"programs": {
        "fused-key": {"label": "fused sweep", "mfu": 0.30},
        "tof-key": {"label": "tof", "mfu": 0.10}}}},
        "metrics": {"value": 1000.0}}
    cand = {"value": 500.0,
            "outlier_span": {"label": "device sweep", "extra_s": 0.8,
                             "trial": 3},
            "cost_ledger": {"programs": {
                "fused-key": {"label": "fused sweep", "mfu": 0.12},
                "tof-key": {"label": "tof", "mfu": 0.11}}}}
    attr = hist.attribute_regression(cand, [prior])
    # Only the forensic fields ride along, and only the MFU DROPS are
    # blamed (tof improved), worst ratio first.
    assert attr["dominant_span"] == {"label": "device sweep",
                                     "extra_s": 0.8}
    drops = attr["cost_ledger_drops"]
    assert [d["key"] for d in drops] == ["fused-key"]
    assert drops[0]["ratio"] == pytest.approx(0.4)
    # Bare candidates degrade to an empty attribution, never raise.
    assert hist.attribute_regression({}, []) == {}


def test_load_history_orders_rounds_and_skips_junk(tmp_path):
    for i, v in ((3, 991.0), (1, 1000.0), (2, 1012.0)):
        with open(tmp_path / f"BENCH_r{i}.json", "w") as fh:
            json.dump(_synthetic_round(i, v, mfu=0.3, prewarm=2.0), fh)
    (tmp_path / "BENCH_r9.json").write_text("{torn json")
    (tmp_path / "notes.json").write_text("{}")
    entries = hist.load_history(str(tmp_path))
    assert [e["round"] for e in entries] == [1, 2, 3]
    assert all("mfu" in e["metrics"] for e in entries)
    assert entries[0]["metrics"]["value"] == 1000.0


# -- the CLI face (make perfwatch / the CI lane) ----------------------

def _write_rounds(root, values, start=1):
    for i, v in enumerate(values, start=start):
        with open(os.path.join(str(root), f"BENCH_r{i}.json"),
                  "w", encoding="utf-8") as fh:
            json.dump(_synthetic_round(i, v, mfu=0.30, prewarm=2.0), fh)


def test_cli_selftest_passes():
    assert main(["--selftest"]) == 0


def test_cli_check_exit_codes(tmp_path, capsys):
    # Too-short history: trivially PASS -- a young repo must not fail CI.
    _write_rounds(tmp_path, [1000.0, 1012.0])
    assert main(["--check", "--root", str(tmp_path)]) == 0
    assert "PASS (trivially)" in capsys.readouterr().out

    # In-noise newest round: PASS.
    _write_rounds(tmp_path, [991.0, 1005.0, 997.0], start=3)
    assert main(["--check", "--root", str(tmp_path)]) == 0
    assert "no regression beyond noise" in capsys.readouterr().out

    # Injected 2x regression in the newest round: exit 1, named metric.
    _write_rounds(tmp_path, [500.0], start=6)
    assert main(["--check", "--root", str(tmp_path)]) == 1
    assert "REGRESSION value" in capsys.readouterr().out
