"""The serving layer (docs/serving.md): protocol, admission, packing,
drain, and the coalescer's external-scheduler contract.

The acceptance surface, smallest-first: request parsing names the
offending field JSON-pointer style; admission control answers
structured rejects (overloaded / draining / bad request) instead of
dropping connections; a sweep response always carries result +
manifest + lane telemetry + quarantine + pack + timing; two
same-bucket requests ride ONE packed flush; drain loses nothing even
with a concurrent burst in flight; and the coalescer's queue-only mode
(``autoflush=False`` + ``take_group``/``run_requests``) survives the
edge cases a serving loop actually hits -- a request due EXACTLY at
its deadline, ``flush_all`` racing a caller-forced ``result()``, and a
clock that moves backwards.

Solver-bearing tests share one bucket-16 mechanism pair at 2 lanes so
the program zoo compiles once for the module.
"""

import asyncio
import types

import numpy as np
import pytest

from pycatkin_tpu.frontend import abi
from pycatkin_tpu.models.synthetic import synthetic_system_for_bucket
from pycatkin_tpu.parallel.dispatch import SweepCoalescer
from pycatkin_tpu.serve import (DEADLINE_CLASSES, ServeConfig,
                                ServeError, SweepClient, TcpSweepClient)
from pycatkin_tpu.serve.protocol import (E_BAD_REQUEST, E_DRAINING,
                                         E_OVERLOADED,
                                         parse_sweep_request)
from pycatkin_tpu.serve.server import SweepServer
from pycatkin_tpu.utils.io import system_to_dict

N_LANES = 2
T_GRID = [500.0, 520.0]


@pytest.fixture(scope="module", autouse=True)
def abi_on():
    mp = pytest.MonkeyPatch()
    mp.setenv(abi.ABI_ENV, "1")
    yield
    mp.undo()


@pytest.fixture(scope="module")
def sims():
    return [synthetic_system_for_bucket(16, seed=s) for s in (0, 1)]


# -- the soak's bucket-targeted mechanism generator --------------------


def test_bucket_generator_lands_in_bucket_seed_invariantly(sims):
    for bucket in (16, 32, 128):
        fps = set()
        for seed in (0, 7):
            sim = (sims[0] if bucket == 16 and seed == 0
                   else synthetic_system_for_bucket(bucket, seed=seed))
            static = abi.select_static(sim.spec)
            assert static.n_species == bucket
            fps.add(abi.abi_fingerprint_of(static))
        # One fingerprint per bucket across seeds: co-packability is
        # the generator's whole contract.
        assert len(fps) == 1


def test_bucket_generator_rejects_with_the_reason_named():
    with pytest.raises(ValueError, match="not an ABI bucket"):
        synthetic_system_for_bucket(20)
    # A species count whose lowered shape (TS states included) cannot
    # land in the requested bucket names where it WOULD land.
    with pytest.raises(ValueError, match="bucket"):
        synthetic_system_for_bucket(16, n_species=60, n_reactions=40)
    with pytest.raises(ValueError):
        synthetic_system_for_bucket(32, n_species=4)


# -- protocol ----------------------------------------------------------


def test_parse_sweep_request_names_the_offending_field():
    cases = [
        ({}, "/mechanism"),
        ({"mechanism": {}}, "/conditions"),
        ({"mechanism": {}, "conditions": {}}, "/conditions/T"),
        ({"mechanism": {}, "conditions": {"T": []}}, "/conditions/T"),
        ({"mechanism": {}, "conditions": {"T": [1, 2], "p": [1]}},
         "/conditions/p"),
        ({"mechanism": {}, "conditions": {"T": 500},
          "tof_terms": "r1"}, "/tof_terms"),
        ({"mechanism": {}, "conditions": {"T": 500},
          "wait_budget_s": -1}, "/wait_budget_s"),
        ({"mechanism": {}, "conditions": {"T": 500}, "return": "y"},
         "/return"),
    ]
    for payload, field in cases:
        with pytest.raises(ServeError) as exc:
            parse_sweep_request(payload)
        assert exc.value.code == E_BAD_REQUEST
        assert field in str(exc.value), payload
    # Scalars broadcast: one T, scalar p, defaults for the rest.
    parsed = parse_sweep_request(
        {"mechanism": {}, "conditions": {"T": 500}})
    assert parsed["T"] == [500.0] and parsed["p"] == [1.0e5]
    assert parsed["deadline_class"] == "standard"


def test_serve_config_resolves_env_and_validates(monkeypatch):
    monkeypatch.setenv("PYCATKIN_SERVE_MAX_PENDING", "7")
    monkeypatch.setenv("PYCATKIN_SERVE_BUDGET_BATCH", "9.5")
    cfg = ServeConfig()
    assert cfg.max_pending == 7
    assert cfg.wait_budget_for("batch") == 9.5
    assert set(DEADLINE_CLASSES) == {"interactive", "standard", "batch"}
    # Interactive < standard < batch: the SLA ordering is the point.
    assert (cfg.wait_budget_for("interactive")
            < cfg.wait_budget_for("standard"))
    with pytest.raises(ServeError) as exc:
        cfg.wait_budget_for("realtime")
    assert exc.value.code == E_BAD_REQUEST
    with pytest.raises(ValueError):
        ServeConfig(runner="bogus")
    with pytest.raises(ValueError):
        ServeConfig(max_pending=0)


# -- admission control -------------------------------------------------


def test_admission_rejects_are_structured_responses():
    async def scenario():
        server = await SweepServer(ServeConfig()).start(listen=False)
        try:
            pong = await SweepClient(server).ping()
            assert pong["ok"] and pong["pong"]

            bad = await server.handle({"op": "conjure", "id": 3})
            assert not bad["ok"] and bad["id"] == 3
            assert bad["error"]["code"] == E_BAD_REQUEST

            bad = await server.handle({"op": "sweep", "id": 4})
            assert not bad["ok"]
            assert bad["error"]["code"] == E_BAD_REQUEST
            assert "/mechanism" in bad["error"]["message"]

            # Full pending queue: structured overload, not a hang.
            server.config.max_pending = 1
            server._taken = 5  # simulate a deep in-flush backlog
            busy = await server.handle(
                {"op": "sweep", "id": 5, "mechanism": {},
                 "conditions": {"T": 500}})
            server._taken = 0
            assert busy["error"]["code"] == E_OVERLOADED

            server._draining = True
            no = await server.handle(
                {"op": "sweep", "id": 6, "mechanism": {},
                 "conditions": {"T": 500}})
            server._draining = False
            assert no["error"]["code"] == E_DRAINING

            stats = (await SweepClient(server).stats())["stats"]
            assert stats["rejected_total"] == 4
            assert stats["requests_total"] == 3  # sweeps that got in
        finally:
            await server.stop()

    asyncio.run(scenario())


# -- sweep round trip --------------------------------------------------

RESPONSE_FIELDS = ("result", "manifest", "lane_telemetry",
                   "quarantine", "pack", "timing")


def _assert_response_schema(resp):
    assert resp["ok"], resp.get("error")
    for field in RESPONSE_FIELDS:
        assert field in resp, f"response missing {field!r}"
    assert resp["lanes"] == N_LANES
    assert len(resp["result"]["success"]) == N_LANES
    assert resp["quarantine"]["count"] == 0
    assert resp["manifest"]["abi"]["fingerprint"]
    assert {"total_s", "solve_s", "queue_s"} <= set(resp["timing"])


def test_two_same_bucket_requests_ride_one_packed_flush(sims):
    async def scenario():
        server = await SweepServer(ServeConfig()).start(listen=False)
        try:
            client = SweepClient(server)
            resps = await asyncio.gather(*(
                client.sweep(sim, T_GRID, tof_terms=[last_rname(sim)],
                             wait_budget_s=0.5, want=["y"])
                for sim in sims))
            for resp in resps:
                _assert_response_schema(resp)
                assert resp["manifest"]["abi"]["packed"]
                assert resp["pack"]["tenants"] == 2
                assert resp["pack"]["occupancy"] == 1.0
                assert len(resp["result"]["tof"]) == N_LANES
                assert len(resp["result"]["y"]) == N_LANES
            # Same flush, bitwise-identical telemetry framing.
            assert (resps[0]["pack"]["flush_seq"]
                    == resps[1]["pack"]["flush_seq"])
            stats = server.stats()
            assert stats["completed_total"] == 2
            assert stats["flushes"] == 1
            assert stats["mean_occupancy"] == 1.0
        finally:
            await server.drain()

    asyncio.run(scenario())


def last_rname(sim):
    return sim.spec.rnames[-1]


SAVE_TS = [0.0, 1e-7, 1e-5, 1e-3]


def test_parse_transient_request_names_the_offending_field():
    from pycatkin_tpu.serve.protocol import parse_transient_request
    base = {"mechanism": {}, "conditions": {"T": 500}}
    cases = [
        (dict(base), "/save_ts"),
        (dict(base, save_ts=[0.0]), "/save_ts"),
        (dict(base, save_ts=[1e-6, 1e-3]), "/save_ts"),
        (dict(base, save_ts=[0.0, 1e-3, 1e-6]), "/save_ts"),
        (dict(base, save_ts=[0.0, float("nan")]), "/save_ts"),
        (dict(base, save_ts="soon"), "/save_ts"),
    ]
    for payload, field in cases:
        with pytest.raises(ServeError) as exc:
            parse_transient_request(payload)
        assert exc.value.code == E_BAD_REQUEST
        assert field in str(exc.value), payload
    parsed = parse_transient_request(dict(base, save_ts=SAVE_TS))
    assert parsed["save_ts"] == SAVE_TS
    assert parsed["T"] == [500.0]
    assert "tof_terms" not in parsed


def test_transient_round_trip_coalesces_by_grid(sims):
    """Two same-bucket same-grid ``transient`` requests ride ONE
    packed flush; a different save grid starts its own group (grids
    are traced shapes/values of the packed program, so co-flushing
    them would be wrong). Response schema: dense-output metadata,
    per-lane ok verdicts, endpoint coverages, quarantine, pack."""
    async def scenario():
        server = await SweepServer(ServeConfig()).start(listen=False)
        try:
            client = SweepClient(server)
            resps = await asyncio.gather(*(
                client.transient(sim, T_GRID, SAVE_TS,
                                 wait_budget_s=0.5, want=["ys"])
                for sim in sims))
            n_s = np.asarray(resps[0]["result"]["endpoint"]).shape[-1]
            for resp in resps:
                assert resp["ok"], resp.get("error")
                assert resp["lanes"] == N_LANES
                assert resp["save_points"] == len(SAVE_TS)
                assert resp["manifest"]["abi"]["packed"]
                assert resp["pack"]["tenants"] == 2
                assert len(resp["result"]["ok"]) == N_LANES
                assert all(resp["result"]["ok"])
                ys = np.asarray(resp["result"]["ys"])
                assert ys.shape == (N_LANES, len(SAVE_TS), n_s)
                ep = np.asarray(resp["result"]["endpoint"])
                assert ep.shape == (N_LANES, n_s)
                assert np.array_equal(ep, ys[:, -1, :])
                assert resp["quarantine"]["count"] == 0
                assert {"total_s", "solve_s",
                        "queue_s"} <= set(resp["timing"])
            assert (resps[0]["pack"]["flush_seq"]
                    == resps[1]["pack"]["flush_seq"])
            assert server.stats()["flushes"] == 1
            # A different grid may not share the flush.
            other = await client.transient(
                sims[0], T_GRID, [0.0, 1e-6], wait_budget_s=0.05)
            assert other["ok"] and other["save_points"] == 2
            assert (other["pack"]["flush_seq"]
                    != resps[0]["pack"]["flush_seq"])
        finally:
            await server.drain()

    asyncio.run(scenario())


def test_transient_and_sweep_requests_never_co_flush(sims):
    """The coalescer keys transients apart from steady sweeps even at
    the same fingerprint and lane count -- their runners and traced
    programs differ."""
    async def scenario():
        server = await SweepServer(ServeConfig()).start(listen=False)
        try:
            client = SweepClient(server)
            rt, rs = await asyncio.gather(
                client.transient(sims[0], T_GRID, SAVE_TS,
                                 wait_budget_s=0.5),
                client.sweep(sims[1], T_GRID, wait_budget_s=0.5))
            assert rt["ok"] and rs["ok"]
            assert rt["pack"]["flush_seq"] != rs["pack"]["flush_seq"]
            assert "save_points" in rt and "save_points" not in rs
            assert server.stats()["flushes"] == 2
        finally:
            await server.drain()

    asyncio.run(scenario())


def test_tcp_round_trip_and_drain_loses_nothing(sims):
    async def scenario():
        server = await SweepServer(ServeConfig(port=0)).start()
        client = await TcpSweepClient("127.0.0.1",
                                      server.port).connect()
        try:
            assert (await client.ping())["pong"]
            # Wire-schema mechanisms: the reference input-file dict.
            mechs = [system_to_dict(s) for s in sims]
            burst = [asyncio.ensure_future(
                client.sweep(m, T_GRID, wait_budget_s=0.2))
                for m in mechs for _ in range(2)]
            # Admit the whole burst, then drain while it is in
            # flight: nothing may be dropped on the floor.
            deadline = asyncio.get_running_loop().time() + 30.0
            while (server.in_service + server.stats()["completed_total"]
                   < len(burst)):
                assert asyncio.get_running_loop().time() < deadline, \
                    "burst never reached admission"
                await asyncio.sleep(0.005)
            drainer = asyncio.ensure_future(server.drain())
            resps = await asyncio.gather(*burst)
            await drainer
            ok = [r for r in resps if r.get("ok")]
            rejected = [r for r in resps if not r.get("ok")]
            assert ok, "drain failed every burst request"
            for r in ok:
                _assert_response_schema(r)
            for r in rejected:  # the only acceptable loss mode
                assert r["error"]["code"] == E_DRAINING
            assert len(ok) + len(rejected) == len(burst)
        finally:
            await client.close()
            await server.stop()

    asyncio.run(scenario())


def test_elastic_runner_policy_is_wired():
    async def scenario():
        server = SweepServer(ServeConfig(runner="elastic"))
        co = server._make_coalescer()
        try:
            from pycatkin_tpu.parallel.dispatch import \
                _default_packed_runner
            assert co.runner is not _default_packed_runner
            assert not co.autoflush
            assert co.work_dir  # elastic runner shares an events file
        finally:
            await server.stop()

    asyncio.run(scenario())


# -- coalescer edge cases (the external-scheduler contract) ------------


def _stub_coalescer(calls, **kwargs):
    def runner(sims, conds_list, masks, x0s, **kw):
        calls.append(len(sims))
        return [{"success": np.ones(N_LANES, bool)} for _ in sims]

    kwargs.setdefault("max_occupancy", 8)
    kwargs.setdefault("max_wait_s", 1e9)
    return SweepCoalescer(runner=runner, autoflush=False, **kwargs)


def _fake_request():
    sim = types.SimpleNamespace()  # unfittable -> solo group
    conds = types.SimpleNamespace(T=np.linspace(450.0, 550.0, N_LANES))
    return sim, conds


def test_coalescer_request_due_exactly_at_its_deadline():
    calls = []
    co = _stub_coalescer(calls)
    sim, conds = _fake_request()
    req = co.submit(sim, conds, wait_budget_s=5.0)
    deadline = req.submitted_at + 5.0
    # A hair early: not due. At the deadline, to the bit: due.
    assert co.due_keys(now=deadline - 1e-9) == []
    assert co.poll(now=deadline - 1e-9) == 0
    assert co.due_keys(now=deadline) == [req.group_key]
    assert co.poll(now=deadline) == 1
    assert req.done and calls == [1] and co.pending == 0


def test_coalescer_backwards_clock_reports_nothing_due():
    calls = []
    co = _stub_coalescer(calls)
    sim, conds = _fake_request()
    req = co.submit(sim, conds, wait_budget_s=0.0)
    # wait_budget_s=0 means due NOW -- but a clock that moved
    # backwards must not flush (or crash) anything early.
    past = req.submitted_at - 3600.0
    assert co.due_keys(now=past) == []
    assert co.poll(now=past) == 0
    assert not req.done and co.pending == 1
    assert co.poll(now=req.submitted_at) == 1
    assert req.done


def test_coalescer_flush_all_racing_forced_result():
    calls = []
    co = _stub_coalescer(calls)
    sim, conds = _fake_request()
    req = co.submit(sim, conds)
    out = req.result()               # caller-forced flush wins
    assert out["success"].all() and calls == [1]
    assert co.flush_all() == 0       # the loser sees an empty queue
    assert calls == [1]              # and never re-runs the group

    req2 = co.submit(*_fake_request())
    assert co.flush_all() == 1       # scheduler-side flush wins
    assert req2.result()["success"].all()
    assert calls == [1, 1]           # result() returned the cache
    # The benign half of the take race: an already-taken key is [].
    assert co.take_group(req2.group_key) == []


def test_coalescer_solo_keys_never_alias():
    calls = []
    co = _stub_coalescer(calls)
    sim, conds = _fake_request()
    # Same unfittable sim submitted twice: two DISTINCT solo groups
    # (id(sim) is reusable after GC; the monotonic counter is not).
    r1 = co.submit(sim, conds)
    r2 = co.submit(sim, conds)
    assert r1.group_key != r2.group_key
    assert r1.group_key[0] == "solo" and r2.group_key[0] == "solo"
    assert co.pending == 2 and len(co._groups) == 2
    co.flush_all()
    assert calls == [1, 1]           # never co-flushed


def test_coalescer_take_group_limit_requeues_remainder():
    calls = []
    co = _stub_coalescer(calls)
    co._group_key = lambda *a, **k: ("fp", N_LANES, False, False)
    reqs = [co.submit(*_fake_request(), wait_budget_s=b)
            for b in (10.0, 4.0, 7.0)]
    key = reqs[0].group_key
    taken = co.take_group(key, limit=2)
    assert taken == reqs[:2] and co.pending == 1
    # The remainder's deadline is recomputed from ITS members only.
    assert co._deadlines[key] == pytest.approx(
        reqs[2].submitted_at + 7.0)
    co.run_requests(key, taken)
    assert reqs[0].done and reqs[1].done and not reqs[2].done
    co.flush_all()
    assert reqs[2].done and calls == [2, 1]
