"""Journaled chunked sweep: checkpoint/resume + degradation reporting.

End-to-end acceptance drills for the robustness subsystem on the CPU
backend (self-contained synthetic mechanism, no reference tree):

- an injected transient flake is absorbed with ZERO failed lanes and
  the degradation is visible in the structured diagnostics;
- a run killed mid-sweep by an injected permanent device loss (with
  salvage disabled, i.e. fail-fast) resumes from its journal,
  re-dispatches ONLY unfinished chunks, and produces results
  bit-identical to an uninterrupted run;
- a journal never resumes against different conditions (fingerprint
  guard).
"""

import os

import numpy as np
import pytest

from pycatkin_tpu import engine
from pycatkin_tpu.models.synthetic import synthetic_system
from pycatkin_tpu.parallel.batch import broadcast_conditions
from pycatkin_tpu.robustness import (ChunkAbandonedError, DegradationPolicy,
                                     FaultPlan, JournalMismatchError,
                                     SweepJournal, chunked_sweep_steady_state,
                                     conditions_fingerprint, fault_scope,
                                     salvage_arrays)
from pycatkin_tpu.robustness.journal import MANIFEST
from pycatkin_tpu.utils import profiling
from pycatkin_tpu.utils.io import append_json_line, read_json_lines

pytestmark = pytest.mark.faults

_FAST = DegradationPolicy(base_delay_s=0.001, max_delay_s=0.002)
_N = 12
_CHUNK = 4


@pytest.fixture(scope="module")
def problem():
    sim = synthetic_system(n_species=10, n_reactions=12)
    spec = sim.spec
    conds = broadcast_conditions(sim.conditions(), _N)
    conds = conds._replace(T=np.linspace(450.0, 650.0, _N))
    mask = engine.tof_mask_for(spec, [spec.rnames[-1]])
    return spec, conds, mask


@pytest.fixture(scope="module")
def reference_run(problem):
    """The uninterrupted run every resumed run must match bit-for-bit."""
    spec, conds, mask = problem
    out, report = chunked_sweep_steady_state(spec, conds, chunk=_CHUNK,
                                             tof_mask=mask)
    assert report["n_failed_lanes"] == 0
    return out


def _assert_bit_identical(a, b):
    assert sorted(a.keys()) == sorted(b.keys())
    for k in a:
        assert np.array_equal(a[k], b[k], equal_nan=True), k


def test_transient_fault_absorbed_zero_failed_lanes(problem,
                                                    reference_run):
    """Acceptance: injected transient flake at one chunk is absorbed by
    the retry rung -- no failed lanes, no salvage, and the event shows
    up in the structured diagnostics."""
    spec, conds, mask = problem
    profiling.drain_events()
    plan = FaultPlan([{"site": "chunk:1", "kind": "transient"}])
    with fault_scope(plan):
        out, report = chunked_sweep_steady_state(
            spec, conds, chunk=_CHUNK, tof_mask=mask, policy=_FAST)
    assert [e["kind"] for e in plan.log] == ["transient"]
    assert report["n_failed_lanes"] == 0
    assert report["salvaged"] == []
    _assert_bit_identical(out, reference_run)
    evs = profiling.drain_events()
    assert any(e["kind"] == "retry" and e["label"] == "chunk:1"
               for e in evs)


def test_kill_and_resume_bit_identical(problem, reference_run, tmp_path):
    """Acceptance: kill the sweep mid-run via an injected permanent
    device loss (fail-fast policy), restart with resume=True, verify
    only unfinished chunks are re-dispatched and the assembled result
    is bit-identical to the uninterrupted run."""
    spec, conds, mask = problem
    jdir = str(tmp_path / "journal")
    fail_fast = DegradationPolicy(base_delay_s=0.001, max_delay_s=0.002,
                                  requeue=False, host_fallback=False,
                                  salvage=False)
    plan = FaultPlan([{"site": "chunk:1", "kind": "permanent",
                       "times": None}])
    with fault_scope(plan):
        with pytest.raises(ChunkAbandonedError):
            chunked_sweep_steady_state(spec, conds, chunk=_CHUNK,
                                       tof_mask=mask, journal=jdir,
                                       policy=fail_fast)
    # The journal durably holds exactly the chunks completed pre-kill.
    recs = read_json_lines(os.path.join(jdir, MANIFEST))
    done_before = [r["chunk_id"] for r in recs if r.get("kind") == "chunk"
                   and r["status"] == "done"]
    assert done_before == [0]

    out, report = chunked_sweep_steady_state(spec, conds, chunk=_CHUNK,
                                             tof_mask=mask, journal=jdir,
                                             resume=True)
    assert report["reused"] == [0]                # only chunk 0 replayed
    assert report["n_failed_lanes"] == 0
    _assert_bit_identical(out, reference_run)

    # A second resume reuses everything.
    out2, report2 = chunked_sweep_steady_state(spec, conds, chunk=_CHUNK,
                                               tof_mask=mask, journal=jdir,
                                               resume=True)
    assert report2["reused"] == [0, 1, 2]
    _assert_bit_identical(out2, reference_run)


def test_salvaged_chunk_marks_lanes_and_resolves_on_resume(
        problem, reference_run, tmp_path):
    """With salvage enabled, a permanently dead chunk yields NaN/failed
    lanes and the run completes; the salvaged chunk is NOT reused on
    resume -- the restart re-solves it cleanly."""
    spec, conds, mask = problem
    jdir = str(tmp_path / "journal")
    pol = DegradationPolicy(base_delay_s=0.001, max_delay_s=0.002,
                            requeue=False, host_fallback=False)
    plan = FaultPlan([{"site": "chunk:2", "kind": "permanent",
                       "times": None}])
    with fault_scope(plan):
        out, report = chunked_sweep_steady_state(
            spec, conds, chunk=_CHUNK, tof_mask=mask, journal=jdir,
            policy=pol)
    assert report["salvaged"] == [2]
    assert report["n_failed_lanes"] == _CHUNK
    sl = slice(2 * _CHUNK, 3 * _CHUNK)
    assert np.isnan(out["y"][sl]).all()
    assert not out["success"][sl].any()

    out2, report2 = chunked_sweep_steady_state(
        spec, conds, chunk=_CHUNK, tof_mask=mask, journal=jdir,
        resume=True)
    assert report2["reused"] == [0, 1]            # salvaged chunk re-run
    assert report2["salvaged"] == []
    _assert_bit_identical(out2, reference_run)


def test_resume_rejects_different_conditions(problem, tmp_path):
    spec, conds, mask = problem
    jdir = str(tmp_path / "journal")
    chunked_sweep_steady_state(spec, conds, chunk=_CHUNK, tof_mask=mask,
                               journal=jdir)
    with pytest.raises(JournalMismatchError):
        chunked_sweep_steady_state(spec, conds._replace(T=conds.T + 1.0),
                                   chunk=_CHUNK, tof_mask=mask,
                                   journal=jdir, resume=True)


def test_fresh_journal_refuses_existing_manifest(tmp_path):
    jdir = str(tmp_path / "journal")
    SweepJournal(jdir, fingerprint="abc", n_lanes=4, chunk=2)
    with pytest.raises(RuntimeError, match="resume=True"):
        SweepJournal(jdir, fingerprint="abc", n_lanes=4, chunk=2)


def test_manifest_tolerates_truncated_final_line(tmp_path):
    """A kill mid-append leaves at most one partial line; replay drops
    it. A corrupt NON-final line is damage and still raises."""
    path = str(tmp_path / "m.jsonl")
    append_json_line(path, {"kind": "header", "version": 1})
    append_json_line(path, {"kind": "chunk", "chunk_id": 0})
    with open(path, "a") as fh:
        fh.write('{"kind": "chu')                 # torn write
    recs = read_json_lines(path)
    assert [r["kind"] for r in recs] == ["header", "chunk"]

    bad = str(tmp_path / "bad.jsonl")
    with open(bad, "w") as fh:
        fh.write('{"kind": "hea\n{"kind": "chunk", "chunk_id": 0}\n')
    with pytest.raises(Exception):
        read_json_lines(bad)


def test_conditions_fingerprint_sensitivity(problem):
    spec, conds, mask = problem
    base = conditions_fingerprint(conds, extra=("a",))
    assert base == conditions_fingerprint(conds, extra=("a",))
    assert base != conditions_fingerprint(
        conds._replace(T=np.asarray(conds.T) + 1e-9), extra=("a",))
    assert base != conditions_fingerprint(conds, extra=("b",))


def test_salvage_arrays_match_sweep_schema(problem, reference_run):
    spec, _, mask = problem
    salv = salvage_arrays(spec, 3, tof_mask=mask, check_stability=False)
    ref_keys = set(reference_run.keys())
    assert set(salv.keys()) == ref_keys
    for k in ref_keys:
        assert salv[k].dtype == reference_run[k].dtype, k
        assert salv[k].shape[1:] == reference_run[k].shape[1:], k
    assert not salv["success"].any()
