"""Precision-tiered solves (PYCATKIN_PRECISION_TIER=f32-polish).

The tier runs the Newton bulk march in native f32 and accepts a lane
only after a short f64 polish pass satisfies the caller's ORIGINAL f64
verdict (docs/perf_precision_tiers.md). These tests pin the contract
that makes the tier safe to flip on:

1.  VERDICT INVARIANCE -- on the clean, rescue, quarantine and
    stability-escalation corpora, every verdict/mask output of a sweep
    (solved / rescued / quarantined / stability, plus the telemetry
    strategy column) is BITWISE identical to a pure-f64 run.
    Continuous outputs agree like two independently converged
    solutions -- to the solver tolerance, not to the ulp (measured
    envelope below); per-lane iteration counts track the tier's own
    trajectory and are explicitly NOT part of the contract.

2.  FALL-THROUGH -- a lane the polish cannot carry to the f64
    thresholds is an ordinary first-pass failure: it rides the
    existing (pure-f64) rescue ladder, and the telemetry tier column
    stamps the f64 code on every ladder product.

3.  IDENTITY -- f32 and f64 programs never share a cache entry: kind
    strings and ABI fingerprints carry the ``:p32`` tag (and the f64
    tag is empty, so every pre-tier key stays byte-identical).

4.  COST -- the tiered fused clean sweep still costs exactly one
    counted host sync (the bulk, the polish and the verdict are stages
    of ONE fused program).
"""

import numpy as np
import pytest

from pycatkin_tpu import engine, precision
from pycatkin_tpu.frontend import abi
from pycatkin_tpu.models.synthetic import synthetic_system
from pycatkin_tpu.parallel import batch
from pycatkin_tpu.parallel.batch import (broadcast_conditions,
                                         clear_program_caches,
                                         sweep_steady_state)
from pycatkin_tpu.solvers import newton
from pycatkin_tpu.solvers.newton import SolverOptions
from pycatkin_tpu.utils import profiling

N_LANES = 32

# Measured on this corpus (CPU): the two tiers converge to the same
# root along different trajectories, so steady states agree like two
# independent converged solutions -- y maxrel ~1.1e-2 observed. The
# net TOF is a difference of large cancelling gross fluxes; on this
# corpus the masked step sits at equilibrium (|tof| < 1e-9 against
# O(1) gross fluxes), so tof is sub-tolerance cancellation noise under
# EITHER tier and gets an absolute noise-floor envelope; activity
# (its log10 rendering) is only compared where the tof is above that
# floor.
_Y_TOL = dict(rtol=5e-2, atol=1e-12)
_SCALE_REL = 5e-2
_TOF_NOISE = 1e-7

# Outputs that track the tier's own solve trajectory rather than the
# physics: the f32 march legitimately takes a different iteration/chord
# count and exits with a different pseudo-step and residual norm.
_TRAJECTORY_INTS = frozenset({"iterations"})
_TRAJECTORY_FLOATS = frozenset({"residual", "dt_exit"})


@pytest.fixture(scope="module")
def problem():
    sim = synthetic_system(n_species=16, n_reactions=24, seed=3)
    spec = sim.spec
    conds = broadcast_conditions(sim.conditions(), N_LANES)
    conds = conds._replace(T=np.linspace(480.0, 620.0, N_LANES))
    mask = engine.tof_mask_for(spec, [spec.rnames[-1]])
    return spec, conds, mask, sim.solver_options()


def _run_tiers(monkeypatch, spec, conds, mask=None, **kwargs):
    """(f64 reference, f32-polish result, f32 run's sync labels).

    No cache clearing: the tier rides the program kind / fingerprint,
    so the two runs select different cached programs by construction --
    that IS part of what these tests exercise."""
    monkeypatch.delenv(precision.TIER_ENV, raising=False)
    monkeypatch.delenv("PYCATKIN_FUSED_SWEEP", raising=False)
    ref = sweep_steady_state(spec, conds, tof_mask=mask, **kwargs)
    monkeypatch.setenv(precision.TIER_ENV, "f32-polish")
    with profiling.sync_budget() as budget:
        out = sweep_steady_state(spec, conds, tof_mask=mask, **kwargs)
    monkeypatch.delenv(precision.TIER_ENV, raising=False)
    return ref, out, budget.labels


def _assert_tier_equivalent(ref: dict, out: dict):
    """Verdicts/masks bitwise, floats to the measured envelope,
    trajectory diagnostics exempt (see module docstring)."""
    assert sorted(ref.keys()) == sorted(out.keys())
    for k in sorted(ref.keys()):
        a, b = np.asarray(ref[k]), np.asarray(out[k])
        assert a.shape == b.shape, f"{k}: {a.shape} vs {b.shape}"
        assert a.dtype == b.dtype, k
        if k == "lane_telemetry":
            # The strategy column is a verdict (which ladder rung
            # produced each lane); the other columns track the tier's
            # own trajectory, and the tier column differs BY DESIGN.
            assert a[:, 3].tobytes() == b[:, 3].tobytes(), (
                "telemetry strategy column differs between tiers")
            continue
        if a.dtype.kind in "biu":
            if k in _TRAJECTORY_INTS:
                continue
            assert a.tobytes() == b.tobytes(), (
                f"verdict/mask output {k!r} differs between f64 and "
                f"f32-polish")
        elif k in _TRAJECTORY_FLOATS:
            continue
        elif k == "y":
            np.testing.assert_allclose(b, a, err_msg=k, **_Y_TOL)
        elif k == "tof":
            np.testing.assert_allclose(b, a, err_msg=k,
                                       rtol=_SCALE_REL, atol=_TOF_NOISE)
        elif k == "activity":
            sig = np.abs(np.asarray(ref["tof"])) > _TOF_NOISE
            np.testing.assert_allclose(b[sig], a[sig], err_msg=k,
                                       rtol=0, atol=0.1)
        else:
            scale = float(max(np.abs(a).max(initial=0.0),
                              np.abs(b).max(initial=0.0)))
            np.testing.assert_allclose(b, a, err_msg=k, rtol=0,
                                       atol=_SCALE_REL * scale + 1e-300)


# ---------------------------------------------------------------------------
# the tier layer itself


def test_tier_registry_and_helpers(monkeypatch):
    import jax.numpy as jnp

    monkeypatch.delenv(precision.TIER_ENV, raising=False)
    assert precision.active_tier() == "f64"
    for tier in precision.TIERS:
        monkeypatch.setenv(precision.TIER_ENV, tier)
        assert precision.active_tier() == tier
    monkeypatch.setenv(precision.TIER_ENV, "f16-yolo")
    with pytest.raises(ValueError, match="f16-yolo"):
        precision.active_tier()

    # tag <-> tier roundtrip; the f64 tag MUST be empty so every
    # pre-tier program key / fingerprint / AOT pack stays byte-equal.
    assert precision.tier_tag("f64") == ""
    assert precision.tier_of_tag("steady:ptc:SolverOptions(...)") == "f64"
    tag = precision.tier_tag("f32-polish")
    assert tag and precision.tier_of_tag(f"steady:x{tag}") == "f32-polish"

    assert precision.bulk_dtype("f64") == jnp.float64
    assert precision.bulk_dtype("f32-polish") == jnp.float32
    assert precision.verify_dtype() == jnp.float64
    assert sorted(precision.TIER_CODES) == sorted(precision.TIERS)
    for tier, code in precision.TIER_CODES.items():
        assert precision.TIER_NAMES[code] == tier


def test_kernel_keyed_rejects_builder_without_kernel_param():
    """Decoration-time fail-fast: a builder that cannot receive the
    threaded `kernel` keyword must blow up at import, not with a
    confusing lru_cache TypeError on first call."""
    import functools

    with pytest.raises(TypeError, match="`kernel` keyword"):
        @precision.kernel_keyed
        @functools.lru_cache(maxsize=4)
        def _no_kernel_param(n):
            return n

    # **kwargs can absorb the keyword: accepted
    @precision.kernel_keyed
    @functools.lru_cache(maxsize=4)
    def _kwargs_builder(n, **extra):
        return n

    assert _kwargs_builder(3) == 3


def test_kernel_keyed_threads_resolved_kernel(monkeypatch):
    """The knob joins the cache key: flipping PYCATKIN_LINALG_KERNEL
    selects a DIFFERENT cached entry, and an explicit kernel= wins."""
    import functools

    calls = []

    @precision.kernel_keyed
    @functools.lru_cache(maxsize=8)
    def _builder(n, kernel="xla"):
        calls.append((n, kernel))
        return (n, kernel)

    monkeypatch.setenv(precision.KERNEL_ENV, "xla")
    assert _builder(1) == (1, "xla")
    assert _builder(1) == (1, "xla")          # cache hit, no rebuild
    assert calls == [(1, "xla")]

    monkeypatch.setenv(precision.KERNEL_ENV, "pallas")
    assert _builder(1) == (1, "pallas")       # env flip = new entry
    assert calls == [(1, "xla"), (1, "pallas")]

    assert _builder(1, kernel="xla") == (1, "xla")   # explicit wins
    assert calls == [(1, "xla"), (1, "pallas")]      # served cached

    # the lru_cache management surface passes through the wrapper
    assert _builder.cache_info().currsize == 2
    _builder.cache_clear()
    assert _builder.cache_info().currsize == 0


def test_bulk_options_floors_tolerances():
    """The f32 bulk march must not grind against its own roundoff
    noise: tolerances are floored at the bulk dtype's noise level,
    while an f64 'bulk' keeps the caller's tolerances (the floors are
    below any realistic f64 setting)."""
    import jax.numpy as jnp

    opts = SolverOptions(rate_tol=1e-10, rate_tol_rel=1e-9)
    b = newton.bulk_options(opts, "f32-polish")
    assert b.rate_tol >= 1e-5
    assert b.rate_tol_rel >= 32.0 * float(jnp.finfo(jnp.float32).eps)
    loose = SolverOptions(rate_tol=1e-3, rate_tol_rel=1e-2)
    b2 = newton.bulk_options(loose, "f32-polish")
    assert b2.rate_tol == loose.rate_tol
    assert b2.rate_tol_rel == loose.rate_tol_rel


def test_program_identity_carries_tier_tag(problem, monkeypatch):
    spec, _, _, opts = problem

    k64 = batch._steady_kind(opts, "ptc")
    k32 = batch._steady_kind(opts, "ptc", tier="f32-polish")
    assert ":p32" not in k64
    assert k32 == k64 + ":p32"
    f64k = batch._fused_kind(opts, 1e-2, "cpu", True, True)
    f32k = batch._fused_kind(opts, 1e-2, "cpu", True, True,
                             tier="f32-polish")
    assert f32k != f64k and ":p32" in f32k and ":p32" not in f64k

    # ABI: the tiers intern as DIFFERENT buckets -- distinct statics,
    # fingerprints and program-spec identities, so an f32 program can
    # never be served from an f64 AOT entry (or vice versa).
    monkeypatch.delenv(precision.TIER_ENV, raising=False)
    low64 = abi.lower_spec(spec)
    monkeypatch.setenv(precision.TIER_ENV, "f32-polish")
    low32 = abi.lower_spec(spec)
    assert low64.program_spec.static.precision == "f64"
    assert low32.program_spec.static.precision == "f32-polish"
    assert ":p32" not in low64.abi_fingerprint
    assert low32.abi_fingerprint == low64.abi_fingerprint + ":p32"
    assert low32.program_spec is not low64.program_spec


# ---------------------------------------------------------------------------
# verdict invariance on the sweep corpora


def test_clean_corpus_matches_f64_in_one_sync(problem, monkeypatch):
    spec, conds, mask, opts = problem
    ref, out, labels = _run_tiers(monkeypatch, spec, conds, mask,
                                  opts=opts, check_stability=True)
    assert bool(np.all(np.asarray(ref["success"]))), \
        "corpus must converge cleanly for this test to mean anything"
    _assert_tier_equivalent(ref, out)

    # The tiered fused clean sweep is still ONE fused program and
    # exactly one counted host sync -- the f64 polish is an in-program
    # stage, not a second dispatch.
    assert labels == ["fused tail bundle"]

    # The telemetry tier column: every accepted lane came from the
    # f32-polish first pass; the reference is all-f64.
    tel64 = np.asarray(ref["lane_telemetry"])
    tel32 = np.asarray(out["lane_telemetry"])
    np.testing.assert_array_equal(tel64[:, 4], 0)
    np.testing.assert_array_equal(
        tel32[:, 4], precision.TIER_CODES["f32-polish"])

    from pycatkin_tpu.obs import export
    assert export.lane_summary(tel32)["tiers"] == {"f32-polish": N_LANES}
    assert export.lane_summary(tel64)["tiers"] == {"f64": N_LANES}


def test_demote_rescue_corpus_matches_f64(monkeypatch):
    """Rescue-ladder corpus: a lane seeded ON an unstable root
    converges there under both tiers, fails the (always-f64) stability
    verdict, and must ride the demote/re-solve ladder to the SAME
    rung -- strategy codes bitwise, ladder product stamped f64."""
    import jax.numpy as jnp

    from pycatkin_tpu.parallel.batch import stack_conditions
    from tests.test_verdicts import A_STABLE, A_UNSTABLE, _full_y
    from tests.test_verdicts import bistable as _bistable_fixture

    sim = _bistable_fixture.__wrapped__()
    spec = sim.spec
    dyn = np.asarray(spec.dynamic_indices)
    conds = stack_conditions([sim.conditions()] * 3)
    x0 = jnp.asarray(np.stack([_full_y(sim, A_UNSTABLE)[dyn],
                               _full_y(sim, A_STABLE)[dyn],
                               _full_y(sim, 0.0)[dyn]]))
    ref, out, _ = _run_tiers(monkeypatch, spec, conds, None, x0=x0,
                             check_stability=True)
    strat = np.asarray(ref["lane_telemetry"])[:, 3]
    assert np.any(strat >= 1), \
        "corpus produced no rescued lanes -- the ladder was not " \
        "exercised"
    _assert_tier_equivalent(ref, out)
    tel32 = np.asarray(out["lane_telemetry"])
    # First-pass acceptances carry the f32 code, every ladder product
    # the f64 code -- lane-exact.
    np.testing.assert_array_equal(
        tel32[:, 4],
        np.where(tel32[:, 3] == 0,
                 precision.TIER_CODES["f32-polish"], 0))


def test_crippled_pacing_first_pass_is_stronger_not_different(
        problem, monkeypatch):
    """Under a crippled step budget the f64 fast pass fails every lane
    into the ladder, while the f32 bulk (whose floored tolerances need
    fewer steps) plus the f64 polish legitimately accepts them first
    pass -- the which-rung forensics differ BY DESIGN under artificial
    pacing cripples. What must still hold: the FINAL verdict masks are
    bitwise tier-invariant, every f32 acceptance passed the same f64
    thresholds (that is the acceptance rule), and the steady states
    agree to the converged-solution envelope."""
    spec, conds, mask, _ = problem
    opts = SolverOptions(max_steps=6, max_attempts=2)
    ref, out, _ = _run_tiers(monkeypatch, spec, conds, mask, opts=opts,
                             check_stability=True)
    st64 = np.asarray(ref["lane_telemetry"])[:, 3]
    tel32 = np.asarray(out["lane_telemetry"])
    assert np.all(st64 >= 1), \
        "cripple too weak -- the f64 fast pass still converged lanes"
    assert np.all(tel32[:, 3] == 0) and np.all(
        tel32[:, 4] == precision.TIER_CODES["f32-polish"])
    for k in ("success", "stable", "quarantined", "rate_ok", "pos_ok",
              "sums_ok"):
        assert (np.asarray(ref[k]).tobytes()
                == np.asarray(out[k]).tobytes()), k
    np.testing.assert_allclose(np.asarray(out["y"]),
                               np.asarray(ref["y"]), **_Y_TOL)


@pytest.mark.faults
def test_quarantine_corpus_matches_f64(problem, monkeypatch):
    """A NaN-poisoned lane is quarantined and re-solved identically
    under both tiers (the fault plan forces the legacy split tail in
    both, so this also covers the non-fused tiered first pass)."""
    from pycatkin_tpu.robustness import FaultPlan, FaultSpec, fault_scope

    spec, conds, mask, opts = problem
    plan = FaultPlan([FaultSpec(site="batched steady solve",
                                kind="nan", lanes=(7,), times=None)])
    monkeypatch.delenv(precision.TIER_ENV, raising=False)
    monkeypatch.delenv("PYCATKIN_FUSED_SWEEP", raising=False)
    with fault_scope(plan):
        ref = sweep_steady_state(spec, conds, tof_mask=mask, opts=opts,
                                 check_stability=True)
    monkeypatch.setenv(precision.TIER_ENV, "f32-polish")
    plan2 = FaultPlan([FaultSpec(site="batched steady solve",
                                 kind="nan", lanes=(7,), times=None)])
    with fault_scope(plan2):
        out = sweep_steady_state(spec, conds, tof_mask=mask, opts=opts,
                                 check_stability=True)
    assert bool(np.asarray(ref["quarantined"])[7]), \
        "poison did not land -- quarantine path not exercised"
    _assert_tier_equivalent(ref, out)


def test_stability_escalation_matches_f64(problem, monkeypatch):
    """Force tier-0 certificate abstention (same device-side threshold
    pin as tests/test_tiered_screen.py) so every converged lane rides
    the host eigensolve escalation -- the stability verdicts must stay
    tier-invariant through that path too."""
    spec, conds, mask, opts = problem
    orig = newton.stability_tolerance_from_scale

    def tier0_never_certifies(scale, pos_tol=1e-2, eps=None):
        t = orig(scale, pos_tol, eps)
        return t - 2.0 * scale if eps is None else t

    monkeypatch.setattr(newton, "stability_tolerance_from_scale",
                        tier0_never_certifies)
    monkeypatch.setattr(newton, "LYAPUNOV_MAX_DIM", 0)
    # Off-default pos_jac_tol -> fresh cache keys, so a
    # previously-compiled program cannot carry the real threshold.
    ref, out, labels = _run_tiers(monkeypatch, spec, conds, mask,
                                  opts=opts, check_stability=True,
                                  pos_jac_tol=0.02)
    assert "tier-0 escalation masks" in labels, \
        "escalation path was not exercised under f32-polish"
    _assert_tier_equivalent(ref, out)


# ---------------------------------------------------------------------------
# fall-through: polish failure is an ordinary first-pass failure


def test_polish_failure_falls_through_ladder(problem, monkeypatch):
    """Hard-lane drill: with the polish budget pinned to zero steps the
    raw f32 iterate cannot meet the f64 thresholds, so first-pass
    acceptance must be REFUSED and the lanes must ride the ordinary
    f64 rescue ladder to the same final verdicts -- the acceptance rule
    (f64 residual + verdict at the caller's opts) is what makes the
    tier safe, and this proves it actually gates."""
    spec, conds, mask, opts = problem
    monkeypatch.delenv("PYCATKIN_FUSED_SWEEP", raising=False)
    monkeypatch.delenv(precision.TIER_ENV, raising=False)
    ref = sweep_steady_state(spec, conds, tof_mask=mask, opts=opts,
                             check_stability=True)

    monkeypatch.setattr(newton, "POLISH_STEPS", 0)
    monkeypatch.setenv(precision.TIER_ENV, "f32-polish")
    # POLISH_STEPS is baked at trace time and the kind strings do not
    # key on it: drop the compiled programs around the patched run.
    clear_program_caches()
    try:
        out = sweep_steady_state(spec, conds, tof_mask=mask, opts=opts,
                                 check_stability=True)
    finally:
        clear_program_caches()

    # Same final verdicts -- the ladder absorbed every polish failure.
    for k in ("success", "stable", "quarantined"):
        assert (np.asarray(ref[k]).tobytes()
                == np.asarray(out[k]).tobytes()), k

    tel = np.asarray(out["lane_telemetry"])
    strat, tier = tel[:, 3], tel[:, 4]
    assert np.any(strat >= 1), (
        "no lane fell through to the ladder -- the unpolished f32 "
        "iterate passed the f64 verdict, so this drill proves nothing")
    # Ladder products are f64 (code 0); any lane the raw bulk iterate
    # DID carry over the f64 bar is a legitimate first-pass accept and
    # keeps the f32 code.
    np.testing.assert_array_equal(
        tier,
        np.where(strat == 0, precision.TIER_CODES["f32-polish"], 0))
