"""Golden regression: COOx CSTR reactor example (reference test_3).

Exercises native OUTCAR/log.vib parsing, the use_descriptor_as_reactant
scaling state, the CSTR boundary conditions and the steady solve.
Golden: CO conversion 51.143 +/- 1e-3 % at 523 K (test/test_3.py:38-43).
"""

import os

import pandas as pd
import pytest

import pycatkin_tpu as pk
from pycatkin_tpu.api import presets
from tests.conftest import reference_path


@pytest.fixture(scope="module")
def coox_cstr(ref_root):
    return pk.read_from_input_file(
        reference_path("examples", "COOxReactor", "input_Pd111.json"))


def test_cstr_co_conversion(coox_cstr, tmp_path):
    presets.run_temperatures(sim_system=coox_cstr, temperatures=[523],
                             steady_state_solve=True, save_results=True,
                             csv_path=str(tmp_path))
    fname = tmp_path / "pressures_vs_temperature.csv"
    assert os.path.isfile(fname)
    df = pd.read_csv(fname)
    pCOin = coox_cstr.params["inflow_state"]["CO"]
    pCOout = df["pCO (bar)"].values[0]
    xCO = 100.0 * (1.0 - pCOout / pCOin)
    assert abs(xCO - 51.143) <= 1e-3


def test_outcar_parsing(ref_root):
    """The native OUTCAR parser reproduces what ASE read for the reference
    (gas CO: 2 atoms, force-consistent energy, linear shape)."""
    from pycatkin_tpu.frontend import parsers
    data = parsers.read_outcar(
        reference_path("examples", "COOxReactor", "data", "CO", "OUTCAR"))
    assert data["mass"] == pytest.approx(12.011 + 15.999)
    assert data["energy"] == pytest.approx(-14.42766244)
    inertia = data["inertia"]
    assert inertia[0] == pytest.approx(0.0, abs=1e-9)
    assert inertia[1] == pytest.approx(inertia[2], rel=1e-9)
