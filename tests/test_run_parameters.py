"""run_parameters sweeps (reference presets.py:170-305): any params key,
including inflow_state_X entries, solved as one batched program."""

import os

import numpy as np
import pandas as pd
import pytest

import pycatkin_tpu as pk
from pycatkin_tpu.api import presets
from tests.conftest import reference_path


@pytest.mark.slow
def test_pressure_sweep_dmtm(ref_root, tmp_path):
    """Pressure sweep on DMTM: steady coverages stay conserved at every
    pressure and artifacts carry the swept values."""
    sim = pk.read_from_input_file(
        reference_path("examples", "DMTM", "input.json"))
    pressures = [5.0e4, 1.0e5, 2.0e5]
    finals, rates, drcs = presets.run_parameters(
        sim_system=sim, parameters=pressures, params_name="pressure",
        steady_state_solve=True, save_results=True,
        csv_path=str(tmp_path))
    assert finals.shape[0] == 3
    ads = sim.adsorbate_indices
    for row in finals:
        assert abs(np.sum(row[ads]) - 1.0) <= 1e-6
    df = pd.read_csv(tmp_path / "coverages_vs_pressure.csv")
    assert len(df) == 3
    assert np.allclose(df.iloc[:, 0].values, pressures)


@pytest.mark.slow
def test_inflow_sweep_cstr(ref_root, tmp_path):
    """Inflow CO partial-pressure sweep on the COOx CSTR: more CO in the
    feed, more CO out; conversion stays finite and physical."""
    sim = pk.read_from_input_file(
        reference_path("examples", "COOxReactor", "input_Pd111.json"))
    sim.params["temperature"] = 523.0
    feeds = [0.01, 0.02, 0.04]
    finals, rates, drcs = presets.run_parameters(
        sim_system=sim, parameters=feeds,
        params_name="inflow_state_CO", steady_state_solve=True,
        save_results=True, csv_path=str(tmp_path))
    iCO = sim.snames.index("CO")
    pCO_out = finals[:, iCO]
    assert np.all(np.diff(pCO_out) > 0), "outlet CO must rise with feed"
    conv = 100.0 * (1.0 - pCO_out / np.asarray(feeds))
    assert np.all((conv > 0) & (conv < 100))
    assert os.path.isfile(tmp_path / "pressures_vs_inflow_state_CO.csv")


def test_save_pes_energies_and_landscape_figures(ref_root, tmp_path):
    """save_pes_energies (reference presets.py:474-498) and
    draw_energy_landscapes produce the reference-named artifacts; the
    relative landscape starts at zero."""
    import matplotlib
    matplotlib.use("Agg")

    from pycatkin_tpu.api.plotting import draw_energy_landscapes

    sim = pk.read_from_input_file(
        reference_path("examples", "DMTM", "input.json"))
    presets.save_pes_energies(sim_system=sim, csv_path=str(tmp_path))
    files = [f for f in os.listdir(tmp_path) if "energy_landscape" in f]
    assert files, "no landscape CSVs written"
    df = pd.read_csv(tmp_path / files[0])
    assert df["Free (eV)"][0] == pytest.approx(0.0)
    assert df["Electronic (eV)"][0] == pytest.approx(0.0)

    draw_energy_landscapes(sim_system=sim, fig_path=str(tmp_path) + "/")
    assert any(f.endswith(".png") for f in os.listdir(tmp_path))


def test_get_tof_for_given_reactions(ref_root):
    """TOF of named steps at the transient tail (reference
    presets.py:585-597): r5 + r9 both produce methanol at the DMTM
    steady state, and each contributes non-negatively."""
    sim = pk.read_from_input_file(
        reference_path("examples", "DMTM", "input.json"))
    sim.solve_odes()
    tof_both = presets.get_tof_for_given_reactions(sim, ["r5", "r9"])
    tof_r9 = presets.get_tof_for_given_reactions(sim, ["r9"])
    assert tof_both > 0
    assert 0 <= tof_r9 <= tof_both * (1 + 1e-9)
