"""Frontend migration paths: in-memory ASE-Atoms ingestion and
reference-pickle conversion (reference state.py:24-29/77-105,
old_system.py:24-29 -- the two reference entry points that had no
native counterpart before round 5)."""

import json
import pickle
import sys
import types

import numpy as np
import pytest

from pycatkin_tpu.frontend import parsers
from pycatkin_tpu.frontend.states import GAS, State


class FakeAtoms:
    """Minimal ASE-Atoms-like object (duck-typed; ASE itself is not a
    dependency of the framework or of this test)."""

    def __init__(self, symbols, positions, masses, inertia=None,
                 energy=None):
        self._symbols = symbols
        self._positions = np.asarray(positions, dtype=float)
        self._masses = np.asarray(masses, dtype=float)
        self._inertia = inertia
        self._energy = energy

    def get_chemical_symbols(self):
        return list(self._symbols)

    def get_positions(self):
        return self._positions

    def get_masses(self):
        return self._masses

    def get_moments_of_inertia(self):
        return np.asarray(self._inertia, dtype=float)

    def get_potential_energy(self):
        if self._energy is None:
            raise RuntimeError("no calculator attached")
        return self._energy


def test_from_atoms_gas_state():
    atoms = FakeAtoms(["C", "O"], [[0, 0, 0], [0, 0, 1.13]],
                      [12.011, 15.999], inertia=[0.0, 8.97, 8.97],
                      energy=-14.8)
    st = State.from_atoms("CO", atoms, GAS, sigma=1,
                          freq=[6.5e13], i_freq=[])
    st.load()
    assert st.mass == pytest.approx(28.01)
    assert st.shape == 2                      # linear molecule
    assert st.Gelec == pytest.approx(-14.8)
    np.testing.assert_allclose(st.freq, [6.5e13])
    syms, pos = st.get_structure()
    assert syms == ["C", "O"] and pos.shape == (2, 3)


def test_from_atoms_without_calculator_or_energy():
    atoms = FakeAtoms(["Pd"] * 4, np.zeros((4, 3)), [106.42] * 4)
    st = State.from_atoms("surface", atoms, "surface")
    st.load()
    assert st.Gelec is None                   # bare structure, no energy
    assert st.mass == pytest.approx(4 * 106.42)


def test_from_atoms_matches_outcar_parser(ref_root):
    """from_atoms on data extracted from an OUTCAR must agree with the
    native OUTCAR loading path (same mass/inertia/energy)."""
    from tests.conftest import reference_path

    path = reference_path("examples", "COOxReactor", "data", "CO")
    data = parsers.read_outcar(parsers.resolve_outcar_path(path))

    class _MassesFake(FakeAtoms):
        def get_masses(self):
            # Return per-atom masses summing to the OUTCAR total.
            n = len(self._symbols)
            return np.full(n, data["mass"] / n)

    atoms = _MassesFake(data["symbols"], data["positions"],
                        np.zeros(len(data["symbols"])),
                        inertia=data["inertia"], energy=data["energy"])
    via_atoms = State.from_atoms("CO", atoms, GAS, sigma=1)
    via_path = State(name="CO", state_type=GAS, sigma=1, path=path)
    via_atoms.load()
    via_path.load()
    assert via_atoms.mass == pytest.approx(via_path.mass, rel=1e-6)
    np.testing.assert_allclose(via_atoms.inertia, via_path.inertia,
                               rtol=1e-6)
    assert via_atoms.Gelec == pytest.approx(via_path.Gelec)


# ---------------------------------------------------------------------
# reference-pickle conversion

def _ref_modules():
    """Install fake ``pycatkin.classes.*`` modules so objects can be
    PICKLED under the reference's module paths (the converter must
    never import the real reference package; this test constructs the
    bytes a real reference pickle would contain)."""
    mods = {}
    for name in ("pycatkin", "pycatkin.classes", "pycatkin.classes.state",
                 "pycatkin.classes.reaction", "pycatkin.classes.reactor",
                 "pycatkin.classes.old_system"):
        mods[name] = types.ModuleType(name)
    def make(module, clsname):
        cls = type(clsname, (), {"__module__": module})
        setattr(mods[module], clsname, cls)
        return cls
    classes = {
        "State": make("pycatkin.classes.state", "State"),
        "ScalingState": make("pycatkin.classes.state", "ScalingState"),
        "Reaction": make("pycatkin.classes.reaction", "Reaction"),
        "InfiniteDilutionReactor": make("pycatkin.classes.reactor",
                                        "InfiniteDilutionReactor"),
        "System": make("pycatkin.classes.old_system", "System"),
    }
    return mods, classes


def _build_ref_system(classes):
    def mk(cls, **attrs):
        obj = cls.__new__(cls)
        obj.__dict__.update(attrs)
        return obj

    common = dict(gasdata=None, add_to_energy=None, truncate_freq=True,
                  path=None, vibs_path=None, energy_source=None,
                  freq_source=None, Gzpe=None, Gvibr=None, Gtran=None,
                  Grota=None, Gfree=None, i_freq=np.array([]))
    A = mk(classes["State"], name="A", state_type="gas", sigma=1,
           mass=28.01, inertia=np.array([0.0, 8.97, 8.97]),
           freq=np.array([6.5e13]), Gelec=-1.0, **common)
    s = mk(classes["State"], name="s", state_type="surface", sigma=None,
           mass=None, inertia=None, freq=np.array([]), Gelec=0.0,
           **common)
    sA = mk(classes["State"], name="sA", state_type="adsorbate",
            sigma=None, mass=None, inertia=None,
            freq=np.array([2.0e13, 1.0e13]), Gelec=-1.9, **common)
    ads = mk(classes["Reaction"], name="ads", reac_type="adsorption",
             reversible=True, reactants=[A, s], products=[sA], TS=None,
             area=1.0e-19, scaling=1.0)
    reactor = mk(classes["InfiniteDilutionReactor"], name="reactor",
                 volume=None, catalyst_area=None, residence_time=None,
                 flow_rate=None)
    system = mk(classes["System"], states={"A": A, "s": s, "sA": sA},
                reactions={"ads": ads}, reactor=reactor,
                params={"times": [0.0, 1.0e6], "T": 500.0, "p": 1.0e5,
                        "start_state": {"A": 1.0, "s": 1.0},
                        "verbose": False})
    return system


def test_convert_reference_system_pickle_roundtrip(tmp_path):
    sys.path.insert(0, "/root/repo/tools")
    try:
        import convert_reference_pickle as crp
    finally:
        sys.path.pop(0)

    mods, classes = _ref_modules()
    system = _build_ref_system(classes)
    pckl = tmp_path / "system.pckl"
    sys.modules.update(mods)
    try:
        with open(pckl, "wb") as fh:
            pickle.dump(system, fh)
    finally:
        for name in mods:
            sys.modules.pop(name, None)

    # Load + convert WITHOUT the fake modules installed: the converter
    # must shim the reference classes, not import them.
    obj = crp.load_reference_pickle(str(pckl))
    assert type(obj).__module__ == "pycatkin.classes.old_system"
    doc = crp.convert(obj)
    assert set(doc) == {"states", "reactions", "reactor", "system"}
    assert doc["states"]["A"]["Gelec"] == pytest.approx(-1.0)
    assert doc["states"]["A"]["inertia"] == [0.0, 8.97, 8.97]
    assert doc["reactions"]["ads"]["reactants"] == ["A", "s"]
    assert doc["reactor"] == "InfiniteDilutionReactor"

    # The emitted JSON must load through the ordinary input reader and
    # compile to a working spec.
    out = tmp_path / "input.json"
    out.write_text(json.dumps(doc, indent=1))
    import pycatkin_tpu as pk
    sim = pk.read_from_input_file(str(out))
    spec = sim.spec
    assert set(spec.snames) == {"A", "s", "sA"}
    assert list(spec.rnames) == ["ads"]
    res = sim.find_steady()
    assert bool(res.success)
    assert bool(np.all(np.isfinite(np.asarray(res.x))))


def test_convert_single_state_pickle(tmp_path):
    sys.path.insert(0, "/root/repo/tools")
    try:
        import convert_reference_pickle as crp
    finally:
        sys.path.pop(0)

    mods, classes = _ref_modules()
    system = _build_ref_system(classes)
    pckl = tmp_path / "state_A.pckl"
    sys.modules.update(mods)
    try:
        with open(pckl, "wb") as fh:
            pickle.dump(system.states["A"], fh)
    finally:
        for name in mods:
            sys.modules.pop(name, None)

    doc = crp.convert(crp.load_reference_pickle(str(pckl)))
    assert list(doc) == ["states"]
    cfg = doc["states"]["A"]
    assert cfg["state_type"] == "gas"
    assert cfg["freq"] == [6.5e13]
    # The snippet builds a native State directly.
    st = State(name="A", **cfg)
    st.load()
    assert st.shape == 2
