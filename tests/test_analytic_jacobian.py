"""Closed-form reactor Jacobian vs the autodiff hot path.

The solvers use jax.jacfwd of the RHS (XLA batches the JVP passes well
on TPU); ops.network.reactor_jacobian is the independent closed-form
implementation (the reference's hand derivation, vectorized). Both must
agree to rounding on every reference mechanism (ID and CSTR reactors,
stoichiometric powers, gas columns) at random physical and off-manifold
states.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import pycatkin_tpu as pk
from pycatkin_tpu import engine
from pycatkin_tpu.ops import network
from tests.conftest import reference_path

CASES = [
    "examples/DMTM/input.json",
    "examples/COOxReactor/input_Pd111.json",
    "examples/COOxVolcano/input.json",
    "test/CH4_input.json",
]


def _closures(sim):
    spec, cond = sim.spec, sim.conditions()
    kf, kr, _ = engine.rate_constants(spec, cond)
    terms = engine._reactor_terms(spec, cond)
    static = dict(reac_idx=spec.reac_idx, prod_idx=spec.prod_idx,
                  is_gas=spec.is_gas, stoich=spec.stoich,
                  is_adsorbate=spec.is_adsorbate, **terms)
    rhs = lambda y: network.reactor_rhs(y, 0.0, kf, kr, **static)
    jac = lambda y: network.reactor_jacobian(y, 0.0, kf, kr, **static)
    return rhs, jac, np.asarray(cond.y0, dtype=float)


@pytest.mark.parametrize("path", CASES)
def test_analytic_matches_autodiff(ref_root, path):
    sim = pk.read_from_input_file(reference_path(*path.split("/")))
    rhs, jac, y0 = _closures(sim)
    rng = np.random.default_rng(0)
    for trial in range(3):
        if trial == 0:
            y = y0
        else:
            # off-manifold states too: Newton iterates visit them
            y = np.abs(y0 + rng.normal(0, 0.3, size=y0.shape))
        J_an = np.asarray(jac(jnp.asarray(y)))
        J_ad = np.asarray(jax.jacfwd(rhs)(jnp.asarray(y)))
        scale = np.max(np.abs(J_ad)) + 1.0
        assert np.allclose(J_an, J_ad, atol=1e-9 * scale), \
            f"{path} trial {trial}: max delta " \
            f"{np.max(np.abs(J_an - J_ad)):.3e} vs scale {scale:.3e}"


def test_analytic_jacobian_synthetic_200():
    """Same agreement at the 200-species/500-reaction benchmark scale."""
    from pycatkin_tpu.models.synthetic import synthetic_system
    sim = synthetic_system(n_species=200, n_reactions=500, seed=1)
    rhs, jac, y0 = _closures(sim)
    J_an = np.asarray(jac(jnp.asarray(y0)))
    J_ad = np.asarray(jax.jacfwd(rhs)(jnp.asarray(y0)))
    scale = np.max(np.abs(J_ad)) + 1.0
    assert np.allclose(J_an, J_ad, atol=1e-9 * scale)


def test_dynamic_jacobian_matches_autodiff(ref_root):
    """engine._dynamic_jacobian (closed-form, dynamic block) vs jacfwd of
    the dynamic residual -- the restriction used by the steady solvers."""
    sim = pk.read_from_input_file(
        reference_path("examples", "COOxReactor", "input_Pd111.json"))
    spec, cond = sim.spec, sim.conditions()
    kf, kr, _ = engine.rate_constants(spec, cond)
    fscale, dyn, y_base = engine._dynamic_fscale(spec, cond, kf, kr)
    x0 = jnp.asarray(np.asarray(y_base)[np.asarray(dyn)])
    J_an = np.asarray(engine._dynamic_jacobian(spec, cond, kf, kr)(x0))
    J_ad = np.asarray(jax.jacfwd(lambda x: fscale(x)[0])(x0))
    scale = np.max(np.abs(J_ad)) + 1.0
    assert np.allclose(J_an, J_ad, atol=1e-9 * scale)
