"""Timing-fence and transient-retry unit tests (round-5 hardening).

The honest-timing machinery (utils/profiling.checksum_fence /
result_fence / run_timed) and the transient-backend retry
(utils/retry) are what make the benchmark records trustworthy and the
driver bench crash-proof; pin their semantics.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pycatkin_tpu.utils.profiling import (checksum_fence, materialize,
                                          result_fence, run_timed)
from pycatkin_tpu.utils.retry import (call_with_backend_retry,
                                      is_transient_backend_error)


def test_checksum_fence_depends_on_every_leaf():
    fence = checksum_fence()
    tree = {"a": jnp.arange(4.0), "b": jnp.array([True, False]),
            "c": jnp.arange(3)}
    base = materialize(fence(tree))
    bumped = materialize(fence({**tree, "a": jnp.arange(4.0) + 1.0}))
    assert base == pytest.approx(0 + 1 + 2 + 3 + 1 + 0 + 1 + 2)
    assert bumped == pytest.approx(base + 4.0)


def test_checksum_fence_finite_under_nan_and_inf():
    """A NaN/Inf lane must not poison the fence scalar, but must still
    influence it (else a program could hide work behind NaNs)."""
    fence = checksum_fence()
    clean = materialize(fence(jnp.array([1.0, 2.0, 3.0])))
    dirty = materialize(fence(jnp.array([1.0, jnp.nan, jnp.inf])))
    assert np.isfinite(dirty)
    assert dirty != clean
    assert dirty == pytest.approx(1.0 + 2.0)     # 1 + two nonfinite


def test_result_fence_matches_manual_sum():
    fence = result_fence()
    y = jnp.arange(6.0).reshape(2, 3)
    act = jnp.array([1.5, jnp.nan])
    succ = jnp.array([True, True])
    got = materialize(fence(y, act, succ))
    assert got == pytest.approx(15.0 + 1.5 + 2.0)


def test_run_timed_fences_and_returns_result():
    def f(x):
        return {"y": jnp.cumsum(x), "ok": jnp.array(True)}

    result, seconds = run_timed(f, jnp.arange(100.0), repeats=2)
    assert float(np.asarray(result["y"])[-1]) == pytest.approx(4950.0)
    assert seconds >= 0.0


def test_retry_recovers_from_transient_error():
    calls = {"n": 0}

    def flaky(x):
        calls["n"] += 1
        if calls["n"] == 1:
            raise jax.errors.JaxRuntimeError(
                "INTERNAL: http://127.0.0.1:1/remote_compile: read body: "
                "response body closed before all bytes were read")
        return x + 1

    out = call_with_backend_retry(flaky, 41, attempts=3,
                                  base_delay_s=0.01, label="test")
    assert out == 42
    assert calls["n"] == 2


def test_retry_does_not_swallow_program_errors():
    def broken():
        raise ValueError("genuine bug")

    with pytest.raises(ValueError, match="genuine bug"):
        call_with_backend_retry(broken, attempts=3, base_delay_s=0.01)

    def bad_program():
        raise jax.errors.JaxRuntimeError(
            "INVALID_ARGUMENT: shapes do not match")

    with pytest.raises(jax.errors.JaxRuntimeError):
        call_with_backend_retry(bad_program, attempts=3,
                                base_delay_s=0.01)


def test_retry_gives_up_after_bounded_attempts():
    calls = {"n": 0}

    def always_flaky():
        calls["n"] += 1
        raise jax.errors.JaxRuntimeError("UNAVAILABLE: socket closed")

    with pytest.raises(jax.errors.JaxRuntimeError):
        call_with_backend_retry(always_flaky, attempts=3,
                                base_delay_s=0.01)
    assert calls["n"] == 3


def test_transient_classifier():
    assert is_transient_backend_error(jax.errors.JaxRuntimeError(
        "INTERNAL: remote_compile: read body"))
    assert is_transient_backend_error(jax.errors.JaxRuntimeError(
        "UNAVAILABLE: failed to connect to all addresses"))
    assert not is_transient_backend_error(jax.errors.JaxRuntimeError(
        "INVALID_ARGUMENT: dot_general shape mismatch"))
    assert not is_transient_backend_error(ValueError("remote_compile"))
