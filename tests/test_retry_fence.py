"""Timing-fence and transient-retry unit tests (round-5 hardening).

The honest-timing machinery (utils/profiling.checksum_fence /
result_fence / run_timed) and the transient-backend retry
(utils/retry) are what make the benchmark records trustworthy and the
driver bench crash-proof; pin their semantics.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pycatkin_tpu.utils.profiling import (checksum_fence, materialize,
                                          result_fence, run_timed)
from pycatkin_tpu.utils.retry import (call_with_backend_retry,
                                      is_transient_backend_error)


def test_checksum_fence_depends_on_every_leaf():
    fence = checksum_fence()
    tree = {"a": jnp.arange(4.0), "b": jnp.array([True, False]),
            "c": jnp.arange(3)}
    base = materialize(fence(tree))
    bumped = materialize(fence({**tree, "a": jnp.arange(4.0) + 1.0}))
    assert base == pytest.approx(0 + 1 + 2 + 3 + 1 + 0 + 1 + 2)
    assert bumped == pytest.approx(base + 4.0)


def test_checksum_fence_finite_under_nan_and_inf():
    """A NaN/Inf lane must not poison the fence scalar, but must still
    influence it (else a program could hide work behind NaNs)."""
    fence = checksum_fence()
    clean = materialize(fence(jnp.array([1.0, 2.0, 3.0])))
    dirty = materialize(fence(jnp.array([1.0, jnp.nan, jnp.inf])))
    assert np.isfinite(dirty)
    assert dirty != clean
    assert dirty == pytest.approx(1.0 + 2.0)     # 1 + two nonfinite


def test_result_fence_matches_manual_sum():
    fence = result_fence()
    y = jnp.arange(6.0).reshape(2, 3)
    act = jnp.array([1.5, jnp.nan])
    succ = jnp.array([True, True])
    got = materialize(fence(y, act, succ))
    assert got == pytest.approx(15.0 + 1.5 + 2.0)


def test_run_timed_fences_and_returns_result():
    def f(x):
        return {"y": jnp.cumsum(x), "ok": jnp.array(True)}

    result, seconds = run_timed(f, jnp.arange(100.0), repeats=2)
    assert float(np.asarray(result["y"])[-1]) == pytest.approx(4950.0)
    assert seconds >= 0.0


def test_retry_recovers_from_transient_error():
    calls = {"n": 0}

    def flaky(x):
        calls["n"] += 1
        if calls["n"] == 1:
            raise jax.errors.JaxRuntimeError(
                "INTERNAL: http://127.0.0.1:1/remote_compile: read body: "
                "response body closed before all bytes were read")
        return x + 1

    out = call_with_backend_retry(flaky, 41, attempts=3,
                                  base_delay_s=0.01, label="test")
    assert out == 42
    assert calls["n"] == 2


def test_retry_does_not_swallow_program_errors():
    def broken():
        raise ValueError("genuine bug")

    with pytest.raises(ValueError, match="genuine bug"):
        call_with_backend_retry(broken, attempts=3, base_delay_s=0.01)

    def bad_program():
        raise jax.errors.JaxRuntimeError(
            "INVALID_ARGUMENT: shapes do not match")

    with pytest.raises(jax.errors.JaxRuntimeError):
        call_with_backend_retry(bad_program, attempts=3,
                                base_delay_s=0.01)


def test_retry_gives_up_after_bounded_attempts():
    calls = {"n": 0}

    def always_flaky():
        calls["n"] += 1
        raise jax.errors.JaxRuntimeError("UNAVAILABLE: socket closed")

    with pytest.raises(jax.errors.JaxRuntimeError):
        call_with_backend_retry(always_flaky, attempts=3,
                                base_delay_s=0.01)
    assert calls["n"] == 3


def test_transient_classifier():
    assert is_transient_backend_error(jax.errors.JaxRuntimeError(
        "INTERNAL: remote_compile: read body"))
    assert is_transient_backend_error(jax.errors.JaxRuntimeError(
        "UNAVAILABLE: failed to connect to all addresses"))
    assert not is_transient_backend_error(jax.errors.JaxRuntimeError(
        "INVALID_ARGUMENT: dot_general shape mismatch"))
    assert not is_transient_backend_error(ValueError("remote_compile"))


class _FakeStatus:
    def __init__(self, name):
        self.name = name


class _FakeRpcError(Exception):
    """gRPC-style exception: status via a callable ``code()``."""

    def __init__(self, status):
        super().__init__(f"rpc failed with {status}")
        self._status = status

    def code(self):
        return _FakeStatus(self._status)


def test_transient_classifier_grpc_status_codes():
    """Raw gRPC-style exceptions classify by status code, not text:
    UNAVAILABLE/DEADLINE_EXCEEDED/ABORTED are transient;
    RESOURCE_EXHAUSTED (device OOM) and INVALID_ARGUMENT are not."""
    assert is_transient_backend_error(_FakeRpcError("UNAVAILABLE"))
    assert is_transient_backend_error(_FakeRpcError("DEADLINE_EXCEEDED"))
    assert is_transient_backend_error(_FakeRpcError("ABORTED"))
    assert not is_transient_backend_error(
        _FakeRpcError("RESOURCE_EXHAUSTED"))
    assert not is_transient_backend_error(
        _FakeRpcError("INVALID_ARGUMENT"))


def test_retry_recovers_from_grpc_transient():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] == 1:
            raise _FakeRpcError("UNAVAILABLE")
        return "ok"

    assert call_with_backend_retry(flaky, attempts=3,
                                   base_delay_s=0.01) == "ok"
    assert calls["n"] == 2


def test_retry_deadline_bounds_total_time():
    """When the next backoff would cross deadline_s, the failure
    propagates instead of sleeping past the budget."""
    import time

    calls = {"n": 0}

    def always_flaky():
        calls["n"] += 1
        raise jax.errors.JaxRuntimeError("UNAVAILABLE: socket closed")

    t0 = time.monotonic()
    with pytest.raises(jax.errors.JaxRuntimeError):
        call_with_backend_retry(always_flaky, attempts=50,
                                base_delay_s=10.0, jitter=False,
                                deadline_s=0.05)
    assert time.monotonic() - t0 < 5.0
    assert calls["n"] == 1           # first 10 s backoff already > 0.05


def test_retry_full_jitter_uses_rng_and_stays_bounded():
    """Full jitter draws each delay from U(0, min(cap, base*2^i)] via
    the provided rng -- deterministic under a seeded rng, bounded by
    the exponential envelope."""
    import random

    delays = []

    class _Rng(random.Random):
        def uniform(self, a, b):
            delays.append((a, b))
            return 0.0               # don't actually sleep in the test

    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 4:
            raise jax.errors.JaxRuntimeError("UNAVAILABLE: socket closed")
        return 1

    assert call_with_backend_retry(flaky, attempts=4, base_delay_s=0.5,
                                   max_delay_s=1.5, rng=_Rng(0)) == 1
    # Envelope: min(1.5, 0.5 * 2**i) for i = 0, 1, 2.
    assert [b for (a, b) in delays] == [0.5, 1.0, 1.5]
    assert all(a == 0.0 for (a, b) in delays)


def test_retry_log_capped(capsys):
    """Per-retry stderr lines stop after the cap; a suppression notice
    marks the cut."""
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 8:
            raise jax.errors.JaxRuntimeError("UNAVAILABLE: socket closed")
        return 1

    assert call_with_backend_retry(flaky, attempts=8, base_delay_s=0.001,
                                   jitter=False, label="capped") == 1
    err = capsys.readouterr().err
    assert err.count("transient backend error in capped") == 3
    assert "suppressing further retry logs" in err


def test_retry_records_structured_event():
    """An absorbed flake must be visible in the diagnostics event log,
    not only on stderr (a run that 'worked' after retries is a
    degraded run)."""
    from pycatkin_tpu.utils import profiling

    profiling.drain_events()
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] == 1:
            raise jax.errors.JaxRuntimeError("UNAVAILABLE: socket closed")
        return 1

    call_with_backend_retry(flaky, attempts=3, base_delay_s=0.001,
                            label="evt")
    evs = profiling.drain_events()
    assert any(e["kind"] == "retry" and e["label"] == "evt"
               for e in evs)
