"""tools/lint_fault_sites.py: every fault-site label must be
documented in docs/failure_model.md -- run the real check as tier-1
plus unit checks of the AST collection/normalization."""

import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.validate

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "tools"))

import lint_fault_sites  # noqa: E402


def test_repo_fault_sites_all_documented():
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools",
                                      "lint_fault_sites.py")],
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "all documented" in proc.stdout


def test_normalize_collapses_fstring_fields(tmp_path):
    src = (
        "def f(strategy, b, extra):\n"
        "    call_with_backend_retry(run,\n"
        "        label=f'rescue[{strategy}{extra}] @{b}')\n"
        "    timed_retry(run, f'polish @{b}')\n"
        "    timed_retry(run, 'fast pass')\n"
        "    site = f'chunk:{b}'\n"
        "    ax.plot(x, y, label='legend text')\n"      # not a fault site
        "    record_event('degradation', label=name)\n"  # dynamic: skip
    )
    path = tmp_path / "mod.py"
    path.write_text(src)
    found = lint_fault_sites.collect_sites(str(tmp_path))
    labels = sorted(label for label, _, _ in found)
    assert labels == ["chunk:<i>", "fast pass", "polish @<i>",
                      "rescue[<i>] @<i>"]


def test_missing_label_fails(tmp_path, monkeypatch, capsys):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "m.py").write_text(
        "call_with_backend_retry(run, label='undocumented site')\n")
    doc = tmp_path / "doc.md"
    doc.write_text("This doc mentions `some other site` only.\n")
    monkeypatch.setattr(lint_fault_sites, "PACKAGE", str(pkg))
    monkeypatch.setattr(lint_fault_sites, "DOC", str(doc))
    assert lint_fault_sites.main() == 1
    out = capsys.readouterr().out
    assert "undocumented site" in out
    doc.write_text("Now documented: `undocumented site`.\n")
    assert lint_fault_sites.main() == 0
