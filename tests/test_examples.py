"""End-to-end example-workflow tests (VERDICT round-1 item 5).

Runs the ported reference workflows headless at reduced sweep sizes and
asserts the reference-named artifacts and their headline numbers. The
example modules live outside the package; import them by path.
"""

import importlib.util
import os
import sys

import numpy as np
import pandas as pd
import pytest

EXAMPLES_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "examples")


def _load_example(name):
    spec = importlib.util.spec_from_file_location(
        f"examples_{name}", os.path.join(EXAMPLES_DIR, f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _exec_notebook(name):
    """Execute a walkthrough notebook's code cells top-to-bottom in one
    namespace (no jupyter dependency, headless matplotlib) and return
    the final namespace for assertions."""
    import json

    import matplotlib
    matplotlib.use("Agg")

    with open(os.path.join(EXAMPLES_DIR, f"{name}.ipynb")) as fh:
        nb = json.load(fh)
    ns = {}
    for cell in nb["cells"]:
        if cell["cell_type"] == "code":
            exec("".join(cell["source"]), ns)
    return ns


@pytest.mark.slow
def test_dmtm_example(ref_root, tmp_path):
    """DMTM workflow end-to-end: landscapes, transient, T-sweep with DRC,
    ES sweep, energy tables -- all artifacts present, DRC argmax = r9."""
    mod = _load_example("dmtm")
    out = str(tmp_path / "dmtm")
    mod.main(out, n_T=3)

    figs = os.listdir(os.path.join(out, "figures"))
    assert "electronic_energy_full_pes.png" in figs
    assert "free_energy_landscapes.png" in figs
    assert "drc_vs_temperature.png" in figs

    outputs = os.path.join(out, "outputs")
    df = pd.read_csv(os.path.join(outputs, "drcs_vs_temperature.csv"))
    assert len(df) == 3
    assert df.iloc[0, 1:].idxmax() == "r9"
    assert os.path.isfile(
        os.path.join(outputs, "energy_span_summary_full_pes.csv"))
    assert os.path.isfile(
        os.path.join(outputs, "reaction_energies_and_barriers_r0.csv"))


@pytest.mark.slow
def test_cooxreactor_example(ref_root, tmp_path):
    """COOxReactor workflow: both catalysts sweep and the Pd111 curve
    passes through the golden conversion at 523 K within the coarse-grid
    envelope (monotone rise, AuPd far less active)."""
    mod = _load_example("cooxreactor")
    out = str(tmp_path / "coox")
    mod.main(out, n_T=5)

    assert os.path.isfile(os.path.join(out, "figures", "conversion.png"))
    xCO = {}
    for name in ("AuPd", "Pd111"):
        df = pd.read_csv(os.path.join(
            out, "outputs", name, "pressures_vs_temperature.csv"))
        assert len(df) == 5
        pin = 0.02  # CO inflow (bar), input_*.json
        xCO[name] = 100.0 * (1.0 - df["pCO (bar)"].values / pin)
    # Pd111: near-zero at 423 K, high conversion at 623 K (test_3 golden
    # is 51.143% at the 523 K point of the fine grid).
    assert xCO["Pd111"][0] < 5.0
    assert xCO["Pd111"][-1] > 45.0
    assert np.max(xCO["AuPd"]) < np.max(xCO["Pd111"])


@pytest.mark.slow
def test_cooxvolcano_example(ref_root, tmp_path):
    """Batched descriptor grid: all points converge on a small grid and
    the activity surface peaks in the interior (volcano shape)."""
    mod = _load_example("cooxvolcano")
    out = str(tmp_path / "volcano")
    mod.main(out, grid_n=8)

    assert os.path.isfile(os.path.join(out, "figures", "activity.png"))
    act = np.loadtxt(os.path.join(out, "outputs", "activity.csv"),
                     delimiter=",")
    assert act.shape == (8, 8)
    assert np.all(np.isfinite(act))
    interior_max = np.max(act[1:-1, 1:-1])
    assert interior_max >= np.max(act) - 1e-9


@pytest.mark.slow
def test_dmtm_metals_example(ref_root, tmp_path):
    """DMTM metals 1-D *O volcano (dry/wet, batched): runs end-to-end
    with the shipped Cu-frame vibration substitution and produces TOF
    tables of the right shape."""
    mod = _load_example("dmtm_metals")
    out = str(tmp_path / "metals")
    mod.main(out, n_points=5)
    for study in ("dry", "wet"):
        tof = np.loadtxt(os.path.join(out, "outputs", f"tof_{study}.csv"),
                         delimiter=",")
        assert tof.shape == (3, 5)
        assert np.all(np.isfinite(tof))
        assert os.path.isfile(
            os.path.join(out, "figures", f"volcano_{study}.png"))


@pytest.mark.slow
def test_dmtm_humidity_example(ref_root, tmp_path):
    """Humidity study: wet and dry mechanisms both converge and water
    co-adsorption SUPPRESSES methanol turnover (wet TOF <= dry TOF, with
    a strict gap at the low-T end where co-adsorbed H2O binds)."""
    mod = _load_example("dmtm_humidity")
    out = str(tmp_path / "humidity")
    tofs = mod.main(out, n_T=3)
    df = pd.read_csv(os.path.join(out, "outputs", "tof_wet_vs_dry.csv"))
    assert len(df) == 3
    dry = df["TOF dry (1/s)"].values
    wet = df["TOF wet (1/s)"].values
    assert np.all(dry > 0) and np.all(wet > 0)
    assert np.all(wet <= dry * (1 + 1e-9))
    assert wet[0] < dry[0]
    assert os.path.isfile(
        os.path.join(out, "figures", "tof_wet_vs_dry.png"))
    assert os.path.isfile(
        os.path.join(out, "outputs", "coverages_vs_temperature_wet.csv"))


@pytest.mark.slow
def test_dmtm_walkthrough_notebook(ref_root):
    """The onboarding notebook (counterpart of the reference's
    examples/DMTM/dmtm.ipynb) executes top-to-bottom: code cells are
    exec'd in one namespace (no jupyter dependency), and the headline
    results hold (steady success, DRC argmax r9)."""
    ns = _exec_notebook("dmtm_walkthrough")
    assert bool(ns["res"].success)
    assert ns["top"][0][0] == "r9"
    assert np.all(np.asarray(ns["out"]["success"]))


@pytest.mark.slow
def test_cooxreactor_walkthrough_notebook(ref_root, tmp_path, monkeypatch):
    """The CSTR walkthrough notebook (counterpart of the reference's
    examples/COOxReactor/cooxreactor.ipynb) executes top-to-bottom
    headless and reproduces the 51.143 % golden conversion at 523 K
    (its own final cell asserts it; re-checked here)."""
    monkeypatch.chdir(tmp_path)     # notebook writes examples/out/...
    ns = _exec_notebook("cooxreactor_walkthrough")
    assert ns["x523"] == pytest.approx(51.143, abs=1e-2)
    assert set(ns["conv"]) == {"AuPd", "Pd111"}
    assert os.path.isfile(os.path.join(
        "examples", "out", "cooxreactor_nb", "figures", "conversion.png"))


@pytest.mark.slow
def test_butadiene_example(ref_root, tmp_path):
    """Butadiene MKM pathway study: all four pathway subsets sweep, TOFs
    are positive at the top temperature, and the pathway discrimination
    signature holds (p124 fastest, p123 slowest by orders of magnitude;
    the combined network sits BELOW the best single pathway -- the
    pathways compete for sites, they don't add)."""
    mod = _load_example("butadiene")
    out = str(tmp_path / "butadiene")
    mod.main(out, n_T=3)
    tofs = {}
    for case in ("p123_p124_p156", "p123", "p124", "p156"):
        data = np.loadtxt(
            os.path.join(out, "outputs", f"bd_tof_{case}.csv"),
            delimiter=",")
        assert data.shape == (3, 2)
        tofs[case] = data[-1, 1]
    assert all(v > 0 for v in tofs.values())
    assert tofs["p124"] > tofs["p156"] > tofs["p123"]
    assert tofs["p123_p124_p156"] < tofs["p124"]
    assert os.path.isfile(os.path.join(
        out, "figures", "Butadiene_TOF_base_case_pathways.png"))
