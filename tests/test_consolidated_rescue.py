"""Consolidated rescue program == legacy per-strategy programs.

The prewarm diet folds the r05 zoo's four per-bucket rescue variants
(seeded polish / seeded full-PTC / seeded LM / unseeded re-solve) into
ONE strategy-parameterized program per bucket (`_rescue_program`):
strategy is a static branch pair under ``lax.cond``, seededness a
traced select, pacing traced scalars. These tests pin the contract
that made the fold safe: for every variant, on clean lanes AND on a
genuinely-failing corpus, the consolidated program's results are
byte-for-byte those of the dedicated legacy program -- and the ladder
verdicts a full sweep emits survive a fault-injected retry unchanged.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pycatkin_tpu import engine
from pycatkin_tpu.models.synthetic import synthetic_system
from pycatkin_tpu.parallel import batch
from pycatkin_tpu.robustness import chunked_sweep_steady_state
from pycatkin_tpu.robustness.faults import FaultPlan, FaultSpec, fault_scope
from pycatkin_tpu.robustness.ladder import DegradationPolicy
from pycatkin_tpu.solvers.newton import SolverOptions

_FAST = DegradationPolicy(base_delay_s=0.001, max_delay_s=0.002)


@pytest.fixture(scope="module")
def problem():
    sim = synthetic_system(n_species=24, n_reactions=32)
    spec = sim.spec
    n = 48
    conds = batch.broadcast_conditions(sim.conditions(), n)
    conds = conds._replace(T=np.linspace(400.0, 800.0, n))
    mask = engine.tof_mask_for(spec, [spec.rnames[-1]])
    return spec, conds, mask


def _legacy(spec, opts, strategy, conds, keys, x0):
    return batch._steady_program(spec, opts, strategy=strategy)(
        conds, keys, x0)


def _consolidated(spec, opts, strategy, use_x0, conds, keys, x0,
                  x_dtype, n_dyn):
    prog = batch._rescue_program(spec, batch._pacing_key(opts))
    scal = (np.int32(1 if strategy == "lm" else 0), np.bool_(use_x0),
            np.float64(opts.dt0), np.float64(opts.dt_grow_min),
            np.int64(opts.max_steps), np.int64(opts.max_attempts))
    n = np.asarray(conds.T).shape[0]
    xc = (x0 if x0 is not None
          else jnp.zeros((n, n_dyn), dtype=x_dtype))
    return prog(*((conds, keys, xc) + scal))


def _ladder_variants(opts):
    """(name, rung opts, strategy, seeded) for every rung the sweep's
    rescue ladder can dispatch through the consolidated program."""
    return [
        ("polish", batch._polish_opts(opts), "ptc", True),
        ("full-ptc", opts, "ptc", True),
        ("lm", opts, "lm", True),
        ("unseeded", opts, "ptc", False),
    ]


def _assert_results_identical(name, a, b):
    for f in a._fields:
        va, vb = getattr(a, f), getattr(b, f)
        if va is None and vb is None:
            continue
        na, nb = np.asarray(va), np.asarray(vb)
        assert na.dtype == nb.dtype, (name, f)
        assert na.tobytes() == nb.tobytes(), (
            f"{name}: field {f!r} differs between legacy and "
            f"consolidated rescue programs")


def test_consolidated_matches_legacy_variants(problem):
    spec, conds, _ = problem
    opts = SolverOptions()
    n = np.asarray(conds.T).shape[0]
    dyn = jnp.asarray(spec.dynamic_indices)
    keys = jax.random.split(jax.random.PRNGKey(3), n)
    fast = batch._steady_program(spec, batch._fast_pass_opts(opts))(
        conds, keys, None)
    x0 = jnp.asarray(fast.x)[:, dyn]
    for name, o, strat, seeded in _ladder_variants(opts):
        x0arg = x0 if seeded else None
        a = _legacy(spec, o, strat, conds, keys, x0arg)
        b = _consolidated(spec, o, strat, seeded, conds, keys, x0arg,
                          fast.x.dtype, int(dyn.size))
        _assert_results_identical(name, a, b)


def test_consolidated_matches_legacy_on_failure_corpus(problem):
    # Seeded failure corpus: crippled pacing makes the fast pass fail
    # real lanes; every ladder rung must then agree bitwise between
    # the legacy per-strategy program and the consolidated one ON THE
    # FAILED SUBSET -- the lanes whose verdicts the rescue actually
    # decides.
    spec, conds, _ = problem
    opts = SolverOptions(max_steps=6, max_attempts=2)
    n = np.asarray(conds.T).shape[0]
    dyn = jnp.asarray(spec.dynamic_indices)
    keys = jax.random.split(jax.random.PRNGKey(3), n)
    fast = batch._steady_program(spec, batch._fast_pass_opts(opts))(
        conds, keys, None)
    failed = np.flatnonzero(~np.asarray(fast.success))
    assert failed.size > 0, "corpus produced no failed lanes"
    sub = jax.tree_util.tree_map(
        lambda x: jnp.asarray(np.asarray(x)[failed]), conds)
    keys_f = keys[jnp.asarray(failed)]
    x0_f = jnp.asarray(fast.x)[jnp.asarray(failed)][:, dyn]
    for name, o, strat, seeded in _ladder_variants(opts):
        x0arg = x0_f if seeded else None
        a = _legacy(spec, o, strat, sub, keys_f, x0arg)
        b = _consolidated(spec, o, strat, seeded, sub, keys_f, x0arg,
                          fast.x.dtype, int(dyn.size))
        _assert_results_identical(name, a, b)


@pytest.mark.faults
def test_ladder_verdicts_survive_injected_transient(problem):
    # The chunked runner's fault sites drive the degradation ladder
    # around the consolidated rescue: a transient at chunk:0 forces a
    # full retry of that chunk, and the journaless sweep result must
    # be byte-identical to an un-faulted run -- the retried dispatch
    # rebuilds its donated buffers rather than reusing consumed ones.
    spec, conds, mask = problem
    opts = SolverOptions(max_steps=6, max_attempts=2)
    kw = dict(chunk=16, tof_mask=mask, opts=opts, policy=_FAST)
    clean_out, clean_rep = chunked_sweep_steady_state(spec, conds, **kw)
    plan = FaultPlan([FaultSpec(site="chunk:0", kind="transient")])
    with fault_scope(plan):
        fault_out, fault_rep = chunked_sweep_steady_state(
            spec, conds, **kw)
    assert plan.log, "injected fault never fired"
    assert fault_rep["n_failed_lanes"] == clean_rep["n_failed_lanes"]
    assert set(clean_out) == set(fault_out)
    for k in clean_out:
        assert (np.asarray(clean_out[k]).tobytes()
                == np.asarray(fault_out[k]).tobytes()), k
