"""Compile pool + AOT executable cache unit tests.

Pins the ISSUE-3 contracts: a serialized executable reloaded from a
fresh cache object returns bit-identical results to the original jit
program; loading an entry against a different spec fingerprint raises
``CacheMismatch`` (never silently executes another mechanism's
physics); toolchain mismatches are silent misses; and the registry +
prewarm integration actually routes sweeps through loaded executables.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pycatkin_tpu import engine
from pycatkin_tpu.models.synthetic import synthetic_system
from pycatkin_tpu.parallel import compile_pool
from pycatkin_tpu.parallel.batch import (broadcast_conditions,
                                         clear_program_caches,
                                         prewarm_sweep_programs,
                                         sweep_steady_state,
                                         warm_from_aot_cache)


@pytest.fixture(autouse=True)
def _fresh_registry():
    clear_program_caches()
    yield
    clear_program_caches()


def test_aot_cache_round_trip_bit_identical(tmp_path):
    @jax.jit
    def f(x, y):
        return jnp.sin(x) @ y + jnp.sum(x, axis=-1)

    x = jnp.asarray(np.random.default_rng(0).normal(size=(8, 8)))
    y = jnp.asarray(np.random.default_rng(1).normal(size=(8,)))
    compiled = f.lower(x, y).compile()
    want = np.asarray(compiled(x, y))

    cache = compile_pool.AOTCache(root=str(tmp_path), fingerprint="fp0")
    key = compile_pool.program_key("test:f", (x, y))
    assert cache.save(key, compiled)
    assert (tmp_path / f"{key}.aot").exists()

    fresh = compile_pool.AOTCache(root=str(tmp_path), fingerprint="fp0")
    exe = fresh.load(key)
    assert exe is not None and fresh.hits == 1
    got = np.asarray(exe(x, y))
    np.testing.assert_array_equal(got, want)   # bit-identical


def test_cache_mismatch_on_changed_fingerprint(tmp_path):
    @jax.jit
    def f(x):
        return x * 2.0

    x = jnp.arange(4.0)
    compiled = f.lower(x).compile()
    cache = compile_pool.AOTCache(root=str(tmp_path),
                                  fingerprint="mechanism-A")
    key = compile_pool.program_key("test:g", (x,))
    assert cache.save(key, compiled)

    other = compile_pool.AOTCache(root=str(tmp_path),
                                  fingerprint="mechanism-B")
    with pytest.raises(compile_pool.CacheMismatch):
        other.load(key)
    assert other.mismatches == 1


def test_toolchain_mismatch_is_silent_miss(tmp_path):
    import pickle

    cache = compile_pool.AOTCache(root=str(tmp_path), fingerprint="fp")
    path = cache._path("deadbeef")
    entry = {"fingerprint": "fp", "jax": "0.0.0-not-this-version",
             "backend": "cpu", "device_kind": "cpu",
             "payload": b"", "in_tree": None, "out_tree": None}
    (tmp_path).mkdir(exist_ok=True)
    with open(path, "wb") as fh:
        pickle.dump(entry, fh)
    assert cache.load("deadbeef") is None
    assert cache.misses == 1


def test_corrupt_entry_is_miss_and_disabled_cache_noops(tmp_path):
    cache = compile_pool.AOTCache(root=str(tmp_path), fingerprint="fp")
    with open(cache._path("cafe"), "wb") as fh:
        fh.write(b"not a pickle")
    assert cache.load("cafe") is None and cache.misses == 1

    off = compile_pool.AOTCache(root="off")
    assert not off.enabled
    assert off.load("anything") is None
    assert off.save("anything", object()) is False


def test_program_key_separates_shapes_kinds_and_x0_none():
    a = (jnp.zeros((4, 3)), None)
    b = (jnp.zeros((4, 3)), jnp.zeros((4, 2)))
    c = (jnp.zeros((8, 3)), None)
    k = compile_pool.program_key
    assert k("s", a) != k("s", b)      # x0=None vs array: distinct
    assert k("s", a) != k("s", c)      # lane count: distinct
    assert k("s", a) != k("t", a)      # kind: distinct
    assert k("s", a) == k("s", a)      # deterministic


def test_map_compile_runs_all_and_reraises_first_error():
    calls = []

    def ok(i):
        return lambda: calls.append(i) or i

    assert compile_pool.map_compile([]) == []
    assert compile_pool.map_compile([ok(0), ok(1), ok(2)],
                                    workers=3) == [0, 1, 2]
    assert sorted(calls) == [0, 1, 2]

    def boom():
        raise RuntimeError("compile failed")

    calls.clear()
    with pytest.raises(RuntimeError, match="compile failed"):
        compile_pool.map_compile([ok(0), boom, ok(1)], workers=2)
    assert sorted(calls) == [0, 1]     # siblings were not orphaned


@pytest.fixture(scope="module")
def problem():
    sim = synthetic_system(n_species=24, n_reactions=32)
    spec = sim.spec
    n = 24
    conds = broadcast_conditions(sim.conditions(), n)
    conds = conds._replace(T=np.linspace(420.0, 780.0, n))
    mask = engine.tof_mask_for(spec, [spec.rnames[-1]])
    return spec, conds, mask


def test_prewarm_populates_cache_and_sweeps_bit_identical(tmp_path,
                                                          problem):
    spec, conds, mask = problem
    cache = compile_pool.AOTCache(
        root=str(tmp_path),
        fingerprint=compile_pool.spec_fingerprint(spec))

    # check_stability is baked into the fused program's key, so the
    # prewarm flag must match the sweeps below (the bare default).
    stats = prewarm_sweep_programs(spec, conds, tof_mask=mask,
                                   buckets=(), check_stability=False,
                                   cache=cache)
    assert int(stats) >= 1 and stats.compiled >= 1
    assert stats.cache_writes == stats.compiled
    baseline = sweep_steady_state(spec, conds, tof_mask=mask)

    # A "restarted process": drop every in-process cache, reload the
    # executables from disk only, and re-run the sweep through them.
    clear_program_caches()
    cache2 = compile_pool.AOTCache(
        root=str(tmp_path),
        fingerprint=compile_pool.spec_fingerprint(spec))
    stats2 = prewarm_sweep_programs(spec, conds, tof_mask=mask,
                                    buckets=(), check_stability=False,
                                    cache=cache2)
    assert stats2.compiled == 0
    assert stats2.loaded == int(stats2)
    out = sweep_steady_state(spec, conds, tof_mask=mask)
    for key in ("y", "tof", "activity", "residual", "success"):
        np.testing.assert_array_equal(np.asarray(out[key]),
                                      np.asarray(baseline[key]),
                                      err_msg=key)


def test_warm_from_aot_cache_registers_without_compiling(tmp_path,
                                                         problem):
    spec, conds, mask = problem
    fp = compile_pool.spec_fingerprint(spec)
    cache = compile_pool.AOTCache(root=str(tmp_path), fingerprint=fp)

    # Empty cache: zero registrations, zero errors.
    assert warm_from_aot_cache(spec, conds, tof_mask=mask,
                               cache=cache) == 0

    prewarm_sweep_programs(spec, conds, tof_mask=mask, buckets=(),
                           check_stability=False, cache=cache)
    clear_program_caches()
    n = warm_from_aot_cache(
        spec, conds, tof_mask=mask,
        cache=compile_pool.AOTCache(root=str(tmp_path), fingerprint=fp))
    assert n >= 1
    assert compile_pool.registry_size() == n
