"""pckey static half: PCL014 cache-key-completeness + PCL015
key-tag-discipline, proven by mutation.

The tripwire contract (ISSUE 19): deleting one ``kernel_keyed``
application from the REAL tree must reproduce the PR 18 stale-kernel
bug as exactly one PCL014 finding, and the shipped tree must be at 0
active findings. PCL015 is proven the same way -- swap two tag
helpers, edit a helper literal, or leak a tag literal outside its
owner module, and the declared-grammar checks fire; the real tree is
silent. Mutations run on a scratch copy of the package so the checks
exercise the real call graph, not a toy.
"""

from __future__ import annotations

import os
import shutil

import pytest

from pycatkin_tpu.lint.cache import LintCache
from pycatkin_tpu.lint.core import run_lint
from pycatkin_tpu.lint.dataflow import (CONFIG_RESOLVERS,
                                        CacheKeyChecker)
from pycatkin_tpu.lint.fused_tail import FusedTailChecker
from pycatkin_tpu.lint.key_tags import GRAMMAR_NAME, KeyTagChecker
from pycatkin_tpu.lint.project_index import ProjectIndex

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

KEYED_DECORATOR = ("@_precision.kernel_keyed\n"
                   "@lru_cache(maxsize=16)\n"
                   "def _steady_program(")


def active(findings):
    return [f for f in findings if f.suppressed is None]


@pytest.fixture()
def pkg_copy(tmp_path):
    """Scratch copy of the real package tree, mutation-ready."""
    shutil.copytree(
        os.path.join(REPO, "pycatkin_tpu"),
        tmp_path / "pycatkin_tpu",
        ignore=shutil.ignore_patterns("__pycache__"))
    return tmp_path


def _edit(root, relpath, old, new, count=1):
    p = root / relpath
    s = p.read_text(encoding="utf-8")
    s2 = s.replace(old, new, count)
    assert s2 != s, f"mutation pattern not found in {relpath}: {old!r}"
    p.write_text(s2, encoding="utf-8")


# ------------------------------------------------------------- PCL014

def test_pcl014_real_tree_is_clean():
    findings = list(CacheKeyChecker().check_project(
        ProjectIndex.build(REPO)))
    assert findings == [], [f"{f.path}:{f.lineno} {f.message}"
                            for f in findings]


def test_pcl014_resolver_registry_matches_tree():
    """Registry drift is a finding in its own right: every declared
    config resolver must still exist where the registry says."""
    index = ProjectIndex.build(REPO)
    for (relpath, fname) in CONFIG_RESOLVERS:
        mod = index.modules.get(relpath)
        assert mod is not None and fname in mod.functions, \
            (relpath, fname)


def test_pcl014_tripwire_kernel_keyed_removal(pkg_copy):
    """THE acceptance tripwire: strip one kernel_keyed application and
    the PR 18 bug class comes back as exactly one finding naming the
    builder and the fix."""
    _edit(pkg_copy, "pycatkin_tpu/parallel/batch.py",
          KEYED_DECORATOR, KEYED_DECORATOR.split("\n", 1)[1])
    result = run_lint(root=str(pkg_copy), checkers=[CacheKeyChecker()])
    act = active(result.findings)
    assert len(act) == 1, [f.message for f in act]
    f = act[0]
    assert f.rule == "PCL014"
    assert f.path == "pycatkin_tpu/parallel/batch.py"
    assert "_steady_program" in f.message
    assert "kernel_keyed" in f.message
    assert "PYCATKIN_LINALG_KERNEL" in f.message


def test_pcl014_tripwire_inlined_env_read(pkg_copy):
    """The other tripwire flavor: an env read inlined straight into a
    cached builder body (no resolver indirection at all)."""
    _edit(pkg_copy, "pycatkin_tpu/parallel/batch.py",
          "def _tof_program(spec: ModelSpec):",
          "def _tof_program(spec: ModelSpec):\n"
          "    _flavor = os.environ.get(\"PYCATKIN_FUSED_SWEEP\", \"\")")
    result = run_lint(root=str(pkg_copy), checkers=[CacheKeyChecker()])
    act = active(result.findings)
    assert len(act) == 1, [f.message for f in act]
    assert "_tof_program" in act[0].message
    assert "PYCATKIN_FUSED_SWEEP" in act[0].message


def test_pcl014_reasoned_suppression_is_honored(pkg_copy):
    _edit(pkg_copy, "pycatkin_tpu/parallel/batch.py",
          KEYED_DECORATOR,
          "@lru_cache(maxsize=16)\n"
          "def _steady_program(  # pclint: disable=PCL014 -- test: "
          "suppression plumbing for project-level taint findings\n")
    # keep the original def line's remainder parseable: the mutation
    # above turned `def _steady_program(` into a continuation, so put
    # the opening back.
    result = run_lint(root=str(pkg_copy), checkers=[CacheKeyChecker()])
    assert active(result.findings) == [], \
        [f.message for f in active(result.findings)]
    sup = [f for f in result.findings if f.suppressed == "inline"]
    assert len(sup) == 1 and "suppression plumbing" in sup[0].reason


# ------------------------------------------------------------- PCL015

def test_pcl015_real_tree_is_clean():
    findings = list(KeyTagChecker().check_project(
        ProjectIndex.build(REPO)))
    assert findings == [], [f"{f.path}:{f.lineno} {f.message}"
                            for f in findings]


def test_pcl015_tag_order_swap_is_flagged(pkg_copy):
    _edit(pkg_copy, "pycatkin_tpu/parallel/batch.py",
          "{_precision.tier_tag(tier)}{_precision.kernel_tag()}",
          "{_precision.kernel_tag()}{_precision.tier_tag(tier)}")
    act = active(run_lint(root=str(pkg_copy),
                          checkers=[KeyTagChecker()]).findings)
    assert len(act) == 1, [f.message for f in act]
    assert "out of grammar order" in act[0].message
    assert "tier_tag" in act[0].message


def test_pcl015_literal_outside_owner_is_flagged(pkg_copy):
    (pkg_copy / "pycatkin_tpu" / "obs" / "sniff.py").write_text(
        'def is_pallas(kind):\n    return ":kpl" in kind\n',
        encoding="utf-8")
    act = active(run_lint(root=str(pkg_copy),
                          checkers=[KeyTagChecker()]).findings)
    assert len(act) == 1, [f.message for f in act]
    assert act[0].path == "pycatkin_tpu/obs/sniff.py"
    assert "kernel_of_tag" in act[0].message


def test_pcl015_helper_literal_drift_is_flagged(pkg_copy):
    """A helper edited away from its grammar row (tier_tag no longer
    builds the declared `:p32`) is declaration drift."""
    _edit(pkg_copy, "pycatkin_tpu/precision.py",
          'return "" if tier == "f64" else ":p32"',
          'return "" if tier == "f64" else ":q32"')
    act = active(run_lint(root=str(pkg_copy),
                          checkers=[KeyTagChecker()]).findings)
    assert any("no longer constructs its declared literal" in f.message
               and f.path == "pycatkin_tpu/precision.py"
               for f in act), [f.message for f in act]


def test_pcl015_missing_grammar_is_drift(pkg_copy):
    _edit(pkg_copy, "pycatkin_tpu/parallel/compile_pool.py",
          "KIND_TAG_GRAMMAR = (", "_RENAMED_AWAY = (")
    act = active(run_lint(root=str(pkg_copy),
                          checkers=[KeyTagChecker()]).findings)
    assert len(act) == 1
    assert GRAMMAR_NAME in act[0].message


# ----------------------- satellite 3: project-level cache invalidation

def _project_run(root):
    cache = LintCache(root)
    result = run_lint(root=root,
                      checkers=[FusedTailChecker(), KeyTagChecker()],
                      cache=cache)
    return cache, result


def test_grammar_edit_invalidates_pcl015_cache(pkg_copy):
    """Editing the declared tag grammar must cold-miss the cached
    PCL013/PCL015 project verdicts -- a stale 'clean' here would let
    tag drift ship."""
    root = str(pkg_copy)
    c1, _ = _project_run(root)
    c1.save()
    c2, _ = _project_run(root)
    assert c2.misses == 0 and c2.hits >= 1      # warm baseline

    _edit(pkg_copy, "pycatkin_tpu/parallel/compile_pool.py",
          '{"name": "tier", "literal": ":p32"',
          '{"name": "tier-renamed", "literal": ":p32"')
    c3, _ = _project_run(root)
    assert c3.misses >= 1, "grammar edit served a stale project verdict"


def test_hotpath_decorator_edit_invalidates_project_cache(pkg_copy):
    """Editing a @hotpath decoration (PCL013's registry input) must
    re-key the project pass."""
    root = str(pkg_copy)
    c1, r1 = _project_run(root)
    c1.save()

    _edit(pkg_copy, "pycatkin_tpu/parallel/batch.py",
          "@hotpath\ndef ", "@hotpath  # registry edit\ndef ", 1)
    c2, r2 = _project_run(root)
    assert c2.misses >= 1, \
        "@hotpath edit served a stale project verdict"
