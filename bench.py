"""North-star benchmark: COOx volcano 256x256 descriptor grid.

Solves the steady state + activity of every (E_CO, E_O) grid point as ONE
batched device program (BASELINE.json north star: <10 s on a v4-8,
>=100x the scipy baseline). The scipy baseline is measured in-process:
the same mechanism integrated per point with scipy BDF (the reference's
solve path, old_system.py:315-383) on a small sample, extrapolated to the
full grid.

Prints exactly one JSON line:
  {"metric": ..., "value": pts/s, "unit": "points/s", "vs_baseline": x}
plus human-readable detail on stderr.

Durable mode (docs/failure_model.md): ``--journal DIR [--chunk N]``
runs the same grid through the chunked, journaled, degradation-tolerant
runner (pycatkin_tpu.robustness); a killed run restarted with
``--journal DIR --resume`` re-dispatches only unfinished chunks. This
mode also prints exactly one JSON line (a durability report, not a
timing record -- chunked dispatch is not the throughput path).
"""

import json
import os
import sys
import time

import numpy as np

GRID_N = int(os.environ.get("BENCH_GRID_N", "256"))
BASELINE_SAMPLE = int(os.environ.get("BENCH_BASELINE_SAMPLE", "6"))

# The production prewarm layout (shared with --smoke, which holds its
# program count to parallel.batch.PREWARM_PROGRAM_BUDGET without paying
# for the compiles). 512 rides in the EXECUTED buckets: the timed
# trials' failed subset lands there, and an AOT-only program still pays
# a ~4-7 s first-execution load. The fused sweep program subsumed the
# standalone fast-pass/screen/TOF programs, so the whole zoo is now
# 1 fused + 5 rescue + 3 tier-2 jac = 9 programs (budget 10). Tier-2
# shapes are thinned to the escalation floor (512 = TIER2_MIN_BUCKET,
# the smallest reachable jac shape), a mid rung (8192) and full shape:
# tier-2 only runs when the tier-0 certificate leaves lanes ambiguous,
# and a rare intermediate shape costs one in-band compile, not a zoo
# slot.
FULL_PREWARM_LAYOUT = dict(buckets=(64, 128, 256, 512),
                           aot_buckets=(1024,),
                           tier2_buckets=(16384,),
                           tier2_aot_buckets=(512, 8192))
REFERENCE_INPUT = os.environ.get(
    "PYCATKIN_REFERENCE_INPUT",
    "/root/reference/examples/COOxVolcano/input.json")


def log(msg):
    print(msg, file=sys.stderr, flush=True)


# Trace output directory: ``--trace DIR`` on any bench mode, or the
# PYCATKIN_TRACE env knob (docs/index.md env registry). When set, every
# mode writes Perfetto-loadable Chrome trace JSON plus the run manifest
# there; when unset, tracing costs nothing beyond the (host-side,
# sync-free) event bookkeeping the profiler already does.
TRACE_DIR = os.environ.get("PYCATKIN_TRACE") or None


def _strip_trace_arg(argv):
    """Pop ``--trace DIR`` out of ``argv`` in place (so the mode
    routing and the journal argparse never see it) and return the
    directory, falling back to the module default (PYCATKIN_TRACE)."""
    out = TRACE_DIR
    while "--trace" in argv:
        k = argv.index("--trace")
        if k + 1 >= len(argv):
            raise SystemExit("bench.py: --trace needs a directory")
        out = argv[k + 1]
        del argv[k:k + 2]
    return out


def _write_trace(name, trace):
    """Write one run trace (and the run manifest, once) under
    TRACE_DIR; no-op when tracing is off."""
    if not TRACE_DIR:
        return None
    from pycatkin_tpu.obs import run_manifest, write_chrome_trace
    os.makedirs(TRACE_DIR, exist_ok=True)
    path = os.path.join(TRACE_DIR, f"{name}.trace.json")
    write_chrome_trace(path, trace)
    man_path = os.path.join(TRACE_DIR, "manifest.json")
    if not os.path.exists(man_path):
        with open(man_path, "w") as f:
            json.dump(run_manifest(), f, indent=2, sort_keys=True)
    log(f"trace -> {path}")
    return path


def result_fence():
    """Sweep-result timing fence; canonical implementation lives in
    :mod:`pycatkin_tpu.utils.profiling` (shared with ``run_timed`` and
    bench_suite.py so the fence guarantees cannot drift apart)."""
    from pycatkin_tpu.utils.profiling import result_fence as _rf
    return _rf()


def scipy_baseline_seconds_per_point(sim, sample_points):
    """Reference-style per-point solve: scipy BDF transient to the input
    time span, TOF at the final state (test_2.py workflow). Rate-constant
    evaluation is excluded from the timing (favors the baseline)."""
    from scipy.integrate import solve_ivp

    from pycatkin_tpu import engine
    from pycatkin_tpu.constants import bartoPa
    from pycatkin_tpu.models import coox

    spec = sim.spec
    times = sim.params["times"]
    is_gas = spec.is_gas.astype(bool)
    reac_idx = spec.reac_idx
    prod_idx = spec.prod_idx
    stoich = spec.stoich
    is_ads = spec.is_adsorbate

    total = 0.0
    for (ECO, EO) in sample_points:
        coox.set_descriptors(sim, float(ECO), float(EO))
        cond = sim.conditions()
        kf, kr, _ = engine.rate_constants(spec, cond)
        kf = np.asarray(kf)
        kr = np.asarray(kr)
        y0 = np.asarray(cond.y0, dtype=float)

        def rhs(t, y):
            y_eff = np.where(is_gas, y * bartoPa, y)
            y_ext = np.concatenate([y_eff, [1.0]])
            fwd = kf * np.prod(y_ext[reac_idx], axis=-1)
            rev = kr * np.prod(y_ext[prod_idx], axis=-1)
            return (stoich @ (fwd - rev)) * is_ads

        t0 = time.perf_counter()
        sol = solve_ivp(rhs, (times[0], times[-1]), y0, method="BDF",
                        rtol=1e-8, atol=1e-10)
        total += time.perf_counter() - t0
        if not sol.success:
            log(f"  baseline point ({ECO:.2f},{EO:.2f}) did not converge")
    return total / len(sample_points)


def _build_problem():
    """(sim, spec, conds, mask, metric, have_ref) for the north-star
    grid: the reference COOx volcano when its input tree exists, else
    the self-contained synthetic fallback."""
    from pycatkin_tpu import engine

    try:
        import pycatkin_tpu as pk
        from pycatkin_tpu.models import coox
        sim = pk.read_from_input_file(REFERENCE_INPUT)
        have_ref = True
    except (OSError, FileNotFoundError):
        have_ref = False

    if have_ref:
        be = np.linspace(-2.5, 0.5, GRID_N)
        conds, shape = coox.volcano_grid_conditions(sim, be)
        mask = engine.tof_mask_for(sim.spec, ["CO_ox"])
        spec = sim.spec
        metric = f"COOx volcano {GRID_N}x{GRID_N} steady-state grid"
    else:
        # Self-contained fallback: synthetic network, T x barrier grid.
        from pycatkin_tpu.models.synthetic import synthetic_system
        from pycatkin_tpu.parallel.batch import broadcast_conditions
        sim = synthetic_system(n_species=24, n_reactions=32)
        spec = sim.spec
        n = GRID_N * GRID_N
        conds = broadcast_conditions(sim.conditions(), n)
        conds = conds._replace(T=np.linspace(400.0, 800.0, n))
        mask = engine.tof_mask_for(spec, [spec.rnames[-1]])
        metric = f"synthetic {GRID_N}x{GRID_N} steady-state grid"
    return sim, spec, conds, mask, metric, have_ref


def main():
    from pycatkin_tpu.utils.cache import enable_persistent_cache
    cache_dir = enable_persistent_cache()

    import jax

    from pycatkin_tpu.parallel.batch import sweep_steady_state

    log(f"persistent compilation cache: "
        f"{cache_dir if cache_dir else 'disabled (cpu backend)'}")

    dev = jax.devices()[0]
    log(f"device: {dev.platform} ({dev.device_kind})")

    sim, spec, conds, mask, metric, have_ref = _build_problem()

    n_points = GRID_N * GRID_N

    # Pin the condition arrays to the device ONCE: as numpy they would
    # re-upload (~tens of MB at the tunnel's ~11 MB/s, with multi-second
    # stalls) on every timed call; as device arrays only the per-trial
    # T vector moves.
    import jax.numpy as jnp
    conds = jax.tree_util.tree_map(jnp.asarray, conds)

    # Pre-warm EVERY program shape the sweep can touch (the fused
    # solve+screen+TOF+diagnostics program, the consolidated per-bucket
    # rescue program, tier-2 Jacobian): the rescue/tier-2 programs otherwise
    # compile lazily the first time lanes fail -- tens of seconds of
    # remote compile, plus its transport-flake risk, INSIDE a timed
    # trial (the round-4 bench died exactly there). On a warm
    # persistent cache this is a disk load; cold it is the full compile
    # bill, paid here and nowhere else.
    from pycatkin_tpu.parallel.batch import (PREWARM_PROGRAM_BUDGET,
                                             clear_program_caches,
                                             make_mesh,
                                             prewarm_sweep_programs)
    from pycatkin_tpu.utils.retry import call_with_backend_retry

    # Full-mesh sweep: the whole pipeline (solve, rescue ladder,
    # stability tiers, TOF) is mesh-aware and the program keys carry
    # the sharding fingerprint, so the prewarmed executables below are
    # exactly what the sharded sweeps dispatch. On one device the mesh
    # degenerates to the unsharded key space (trivial-mesh tags are
    # empty).
    mesh = make_mesh()
    log(f"mesh: {mesh.devices.size} device(s) over axis "
        f"'{mesh.axis_names[0]}'")

    def run_prewarm(verbose):
        return prewarm_sweep_programs(spec, conds, tof_mask=mask,
                                      check_stability=True,
                                      verbose=verbose,
                                      mesh=mesh,
                                      **FULL_PREWARM_LAYOUT)

    t0 = time.perf_counter()
    n_prog = run_prewarm(verbose=True)
    prewarm_cold_s = time.perf_counter() - t0
    log(f"prewarm cold ({int(n_prog)} programs: "
        f"{n_prog.compiled} compiled, {n_prog.loaded} loaded from AOT "
        f"cache): {prewarm_cold_s:.2f} s")

    # Warm-disk prewarm: drop every in-process cache (jit lru caches +
    # executable registry) and prewarm again -- the serialized AOT
    # executables written above now satisfy every program by
    # deserialization, which is what a RESTARTED process pays.
    clear_program_caches()
    t0 = time.perf_counter()
    n_prog2 = run_prewarm(verbose=False)
    prewarm_warm_s = time.perf_counter() - t0
    log(f"prewarm warm-disk ({n_prog2.loaded} loaded, "
        f"{n_prog2.compiled} compiled): {prewarm_warm_s:.2f} s")
    prewarm_s = prewarm_cold_s

    # Warm-from-PACK prewarm: archive the just-populated cache with
    # tools/aot_pack.py's library entry points, import it into a fresh
    # directory, and prewarm a third time against ONLY the pack's
    # contents -- what a new worker handed the shippable pack (instead
    # of the compile wall) pays on first boot. Target: < 30 s.
    import tempfile

    from pycatkin_tpu.parallel.compile_pool import (AOTCache,
                                                    export_cache_pack,
                                                    import_cache_pack,
                                                    spec_fingerprint)
    prewarm_warm_pack_s = None
    pack_stats = None
    try:
        with tempfile.TemporaryDirectory(prefix="pycatkin_pack_") as tmp:
            pack = os.path.join(tmp, "cache.aotpack.tgz")
            exported = export_cache_pack(pack)
            fresh = os.path.join(tmp, "fresh")
            import_cache_pack(pack, cache_root=fresh)
            clear_program_caches()
            # Under PYCATKIN_ABI=1 cache entries are bound to the
            # BUCKET fingerprint of the lowered spec, not the
            # mechanism's.
            from pycatkin_tpu.frontend.abi import maybe_lower
            pack_cache = AOTCache(
                root=fresh,
                fingerprint=spec_fingerprint(maybe_lower(spec) or spec))
            t0 = time.perf_counter()
            n_prog3 = prewarm_sweep_programs(
                spec, conds, tof_mask=mask, check_stability=True,
                verbose=False, mesh=mesh, cache=pack_cache,
                **FULL_PREWARM_LAYOUT)
            prewarm_warm_pack_s = time.perf_counter() - t0
            pack_stats = {"entries": exported["entries"],
                          "bytes": exported["bytes"],
                          "loaded": int(n_prog3.loaded),
                          "compiled": int(n_prog3.compiled)}
            log(f"prewarm warm-from-pack ({exported['entries']} entries, "
                f"{exported['bytes']} bytes; {n_prog3.loaded} loaded, "
                f"{n_prog3.compiled} compiled): "
                f"{prewarm_warm_pack_s:.2f} s")
    except (FileNotFoundError, ValueError) as e:
        # Cache disabled / empty (e.g. a backend whose executables do
        # not serialize): record the absence, never kill the bench.
        log(f"prewarm warm-from-pack skipped: {e}")

    # ABI marginal prewarm: with PYCATKIN_ABI=1 the zoo keys on the
    # shape bucket, so a SECOND mechanism landing in the warm bucket
    # must prewarm with zero fresh compiles (the whole point of the
    # mechanism ABI). Measured on a thermo-perturbed variant of the
    # bench mechanism -- same bucket by construction, different
    # operand values, hence a genuinely different mechanism to the
    # traced programs. Null when the ABI path is off or unfittable.
    from pycatkin_tpu.frontend.abi import maybe_lower as _maybe_lower
    abi_marginal_prewarm_s = None
    abi_marginal_compiled = None
    if _maybe_lower(spec) is not None:
        import dataclasses
        spec_b = dataclasses.replace(
            spec, add0=np.asarray(spec.add0) + 0.013)
        t0 = time.perf_counter()
        n_prog_b = prewarm_sweep_programs(spec_b, conds, tof_mask=mask,
                                          check_stability=True,
                                          verbose=False, mesh=mesh,
                                          **FULL_PREWARM_LAYOUT)
        abi_marginal_prewarm_s = time.perf_counter() - t0
        abi_marginal_compiled = int(n_prog_b.compiled)
        log(f"ABI marginal prewarm (2nd mechanism, warm bucket): "
            f"{abi_marginal_prewarm_s:.2f} s, "
            f"{n_prog_b.compiled} compiled, {n_prog_b.loaded} loaded")

    # Warmup sweep on SHIFTED condition values -- the timed runs below
    # must present inputs the device has not seen, so no
    # infrastructure-level caching of a repeated identical execution can
    # fake the result. NOTE on metrics: ALL compile cost (cold or
    # cache-load) is absorbed by the prewarm above and reported as
    # `prewarm_s`; this sweep's wall (`compile_s`, kept under its
    # historical key) is therefore pure warm execution of the first
    # full sweep -- it is NOT comparable to BENCH_r04's compile_s,
    # which timed first-run-including-compile before prewarming
    # existed.
    t0 = time.perf_counter()
    out = call_with_backend_retry(
        sweep_steady_state, spec, conds._replace(T=conds.T + 0.25),
        tof_mask=mask, check_stability=True, mesh=mesh,
        label="warmup sweep")
    np.asarray(out["y"])
    compile_and_run = time.perf_counter() - t0
    log(f"warmup sweep: {compile_and_run:.2f} s")
    warm_out = out

    # Median of 3 trials, each on a uniquely shifted temperature grid
    # (physically negligible, defeats result caching), each fenced by
    # FULL host materialization: jax.block_until_ready does NOT
    # synchronize on the tunneled axon TPU backend (measured round 4:
    # 0.6 ms "wall" for a 5 s computation), so device->host transfer of
    # the results is the only honest timing fence.
    # The timed sweep INCLUDES the stability verdict (reference
    # solver.py:102-106): the on-device Gershgorin certificate clears
    # the typical lane without any host eigensolve, so the screening
    # rides inside the throughput number instead of being benched off.
    #
    # Timing fence: a device-side checksum reduction materialized as
    # ONE scalar. On the tunneled backend each device->host
    # materialization call costs ~0.8-1.2 s of round trip regardless
    # of payload (measured round 4) -- an artifact of THIS tunnel, not
    # of the framework; a co-located host pays PCIe microseconds. The
    # scalar still forces the whole program chain to execute (its value
    # depends on every y and every activity), so nothing can hide; the
    # full result arrays cross AFTER the clock stops.
    checksum = result_fence()
    # compile the fence program outside the timed region
    np.asarray(checksum(warm_out["y"], warm_out["activity"],
                        warm_out["success"]))

    def timed_trial(i, attempt):
        # Fresh T shift per (trial, retry attempt): a retried trial must
        # also present inputs the device has not seen, or an
        # infrastructure-level cache of the failed-then-retried identical
        # execution could serve it and fake the wall time.
        c_i = conds._replace(T=conds.T + 1.0e-7 * (i + 1)
                             + 1.0e-8 * attempt)
        t0 = time.perf_counter()
        o = sweep_steady_state(spec, c_i, tof_mask=mask,
                               check_stability=True, mesh=mesh)
        float(np.asarray(checksum(o["y"], o["activity"], o["success"])))
        return time.perf_counter() - t0, o

    from pycatkin_tpu import obs

    # Pinned, DISCARDED warmup trial through the exact timed_trial path
    # (fence included): the first fenced trial of a process habitually
    # reads 10-30% slow (allocator growth, first transfer of the shifted
    # T vector, tunnel keepalive), which used to land in trial 0 and
    # blow max_over_median. It is paid here, logged, and thrown away;
    # the 3 counted trials start from a settled device.
    warmup_trial_s, _ = call_with_backend_retry(
        lambda: timed_trial(98, 0), label="warmup trial")
    log(f"warmup trial (discarded): {warmup_trial_s:.3f} s")

    def _span_totals(events):
        """Per-label wall totals {label: seconds} for a slice of span
        events (one trial's variance-forensics fingerprint)."""
        tot: dict = {}
        for ev in events:
            lbl = str(ev.get("label"))
            tot[lbl] = round(tot.get(lbl, 0.0)
                             + float(ev.get("dur", 0.0)), 4)
        return tot

    walls, last, trial_rescues = [], None, []
    trial_spans, trial_syncs = [], []
    for i in range(3):
        # Trial-level retry: a transient backend flake re-runs the
        # whole (pure) trial rather than killing the round's record.
        # (The library's own inner retries around each program dispatch
        # absorb most flakes first -- their backoff then lands IN the
        # trial wall, which is the conservative direction: a flaky
        # trial reads slower, never faster, and the retry is logged on
        # stderr. This outer retry is the backstop for flakes the inner
        # ones exhaust.)
        attempt = {"n": -1}

        def trial_once():
            attempt["n"] += 1
            return timed_trial(i, attempt["n"])

        # Run-scoped trace: the trial's spans, rescue events and
        # counted syncs are read off ITS OWN trace (retries included --
        # the trace wraps the retry wrapper) instead of slicing the
        # process-global event list by before/after indices.
        with obs.run_trace(f"trial {i}") as tr:
            w, out = call_with_backend_retry(trial_once,
                                             label=f"timed trial {i}")
        walls.append(w)
        last = out
        trial_spans.append(_span_totals(tr.peek("span")))
        trial_syncs.append(tr.sync_count)
        # Per-trial rescue funnel (straggler forensics for the trial
        # wall variance): each rescue pass records how many lanes it
        # received and how many stayed failed.
        rescues = [{"pass": ev.get("label"),
                    "n_failed": ev.get("n_failed"),
                    "n_remaining": ev.get("n_remaining")}
                   for ev in tr.peek("rescue")]
        trial_rescues.append(rescues)
        _write_trace(f"trial_{i}", tr)
        log(f"trial {i}: {w:.3f} s, rescue funnel: "
            f"{[(r['pass'], r['n_failed']) for r in rescues] or 'clean'}")
    wall = sorted(walls)[1]
    pts_per_s = n_points / wall
    trial_pts_per_s = [round(n_points / w, 2) for w in walls]
    n_ok = int(np.sum(np.asarray(last["success"])))
    n_stable = int(np.sum(np.asarray(last.get("stable", last["success"]))))
    log(f"batched solve walls: {['%.3f s' % w for w in walls]} "
        f"(median {wall:.3f} s, {pts_per_s:.0f} pts/s, per-trial "
        f"{trial_pts_per_s}), "
        f"{n_ok}/{n_points} converged+stable ({n_stable} stable)")

    # Slow-trial attribution, now a first-class gate: with the warmup
    # trial discarded and the fused single-dispatch tail, trials are
    # homogeneous -- any trial exceeding the median by >10% names the
    # span whose duration grew the most between the median and slowest
    # trials instead of leaving the outlier as an anonymous number.
    # The attribution itself lives in pycatkin_tpu.obs (shared with
    # tools/obsview.py, so the CLI and the bench can never disagree).
    max_over_median = round(max(walls) / wall, 3)
    outlier_span = obs.attribute_outlier(
        trial_spans, walls, threshold=1.1,
        cost_ledger=obs.ledger_snapshot())
    if outlier_span:
        log(f"slow-trial outlier: trial {outlier_span['trial']} "
            f"({max(walls):.3f} s vs median {wall:.3f} s); "
            f"dominant span: {outlier_span['label']} "
            f"(+{outlier_span['extra_s']:.3f} s)")

    # Device cost ledger: compile-time FLOPs/bytes per program (XLA's
    # own cost_analysis, harvested at prewarm) joined with the blocked
    # dispatch walls accumulated across prewarm + trials. Totals carry
    # achieved FLOP/s and -- on devices with a measured ceiling -- MFU,
    # the headline efficiency number tools/perfwatch.py tracks.
    cost_ledger = obs.ledger_snapshot()
    lane_tel = last.get("lane_telemetry")
    lanes = obs.lane_summary(lane_tel) if lane_tel is not None else None
    if lanes:
        log(f"lane telemetry: {lanes['strategies']} strategies, "
            f"iterations median {lanes['iterations']['median']}")

    vs_baseline = None
    if have_ref:
        rng = np.random.default_rng(0)
        sample = rng.uniform(-2.5, 0.5, size=(BASELINE_SAMPLE, 2))
        sec_per_pt = scipy_baseline_seconds_per_point(sim, sample)
        log(f"scipy baseline: {sec_per_pt*1e3:.1f} ms/point "
            f"(sample of {BASELINE_SAMPLE})")
        vs_baseline = (sec_per_pt * n_points) / wall

    from pycatkin_tpu import precision
    result = {
        "metric": metric,
        # Executing backend + precision tier, top-level so the
        # perfwatch history (obs/history.py) can segment baselines:
        # CPU and TPU rounds -- or f64 and f32-polish rounds -- are
        # different physical experiments.
        "backend": dev.platform,
        "tier": precision.active_tier(),
        "value": round(pts_per_s, 2),
        "unit": "points/s",
        "value_min": round(n_points / max(walls), 2),
        "value_max": round(n_points / min(walls), 2),
        "stability_screened": True,
        "converged_stable": n_ok,
        # null when no baseline could be measured (no fabricated ratio).
        "vs_baseline": (round(vs_baseline, 2) if vs_baseline is not None
                        else None),
        # First full sweep after prewarm: pure warm execution (all
        # compile/cache-load cost lives in prewarm_s). NOT comparable
        # to r4's compile_s, which timed first-run-incl-compile.
        "compile_s": round(compile_and_run, 2),
        # Crash-proofing surface: pre-compiling/loading all rescue/
        # screen/tier-2 program shapes so no XLA compile can land
        # inside a timed trial or production solve (see prewarm
        # breakdown on stderr; floor analysis in docs/perf_mfu.md).
        "prewarm_s": round(prewarm_s, 2),
        # Cold = first prewarm of this process (compile pool +
        # whatever the AOT disk cache already held); warm = identical
        # prewarm after dropping every in-process cache, i.e. what a
        # restarted process pays against the now-populated AOT cache.
        "prewarm_cold_s": round(prewarm_cold_s, 2),
        "prewarm_warm_s": round(prewarm_warm_s, 2),
        # Warm-from-pack = a FRESH directory populated only by the
        # tools/aot_pack.py export->import round trip (null when the
        # cache does not serialize on this backend); pack = the
        # shipped archive's stats + what the pack-warmed prewarm did.
        "prewarm_warm_pack_s": (round(prewarm_warm_pack_s, 2)
                                if prewarm_warm_pack_s is not None
                                else None),
        "pack": pack_stats,
        # What a DIFFERENT mechanism in the already-warm ABI bucket
        # pays (null when PYCATKIN_ABI is off): wall seconds and fresh
        # compiles -- the latter must be 0, asserted by --smoke.
        "abi_marginal_prewarm_s": (round(abi_marginal_prewarm_s, 2)
                                   if abi_marginal_prewarm_s is not None
                                   else None),
        "abi_marginal_compiled": abi_marginal_compiled,
        "prewarm_compiled": int(n_prog.compiled),
        "prewarm_loaded": int(n_prog.loaded),
        # Program-zoo diet accounting: total distinct programs the
        # prewarm ensured, held to PREWARM_PROGRAM_BUDGET by the smoke
        # lane (full-bench layout must stay within the same budget).
        "n_programs_prewarmed": int(n_prog),
        "program_budget": int(PREWARM_PROGRAM_BUDGET),
        "mesh_devices": int(mesh.devices.size),
        # Per-trial rescue funnel: [[{pass, n_failed, n_remaining}]].
        "trial_rescues": trial_rescues,
        # Variance forensics: the discarded warmup trial's wall, raw
        # per-trial walls and throughputs, counted host syncs per
        # trial, and per-trial span totals ({label: seconds}) from
        # utils.profiling -- plus the named dominant span whenever the
        # slowest trial exceeds the median by >10%. variance_ok is the
        # first-class gate: max_over_median must stay under 1.1.
        "warmup_trial_s": round(warmup_trial_s, 3),
        "trial_walls": [round(w, 3) for w in walls],
        "trial_pts_per_s": trial_pts_per_s,
        "sync_count": trial_syncs,
        "trial_spans": trial_spans,
        "max_over_median": max_over_median,
        "variance_ok": max_over_median < 1.1,
        # Full attribution dict from obs.attribute_outlier (label,
        # extra_s, trial, max_over_median, cost-ledger programs).
        "outlier_span": outlier_span,
        # Per-program device costs + achieved FLOP/s / MFU; "mfu" is
        # the ledger total, null on backends with no measured ceiling.
        "cost_ledger": cost_ledger,
        "mfu": (cost_ledger.get("totals") or {}).get("mfu"),
        # Per-lane solver telemetry aggregates of the last timed trial
        # (full [lanes, 5] arrays stay out of the JSON line at 256x256;
        # use --trace / tools/obsview.py --lanes for the heatmap).
        "lanes": lanes,
        # Self-describing record: git state, backend, mesh, every set
        # PYCATKIN_* knob, ABI bucket and aot-key version that produced
        # these numbers (pycatkin_tpu.obs.manifest schema).
        "manifest": obs.run_manifest(mesh=mesh),
        "trace_dir": TRACE_DIR,
    }

    # Regression tripwire vs the checked-in prior round (VERDICT r3
    # item 3): a >30% throughput drop is flagged in the JSON and on
    # stderr instead of passing silently as noise.
    prior = _prior_round_value()
    if prior:
        result["prior_round_value"] = prior
        if pts_per_s < 0.7 * prior:
            result["regression_vs_prior"] = True
            # Round 3 -> 4 methodology break, for the record: prior
            # rounds timed with jax.block_until_ready, which does NOT
            # synchronize on the tunneled axon backend (measured round
            # 4: 0.6 ms "wall" for a 5 s computation), and ran without
            # the stability verdict. This round's number is fenced by
            # real materialization and includes stability screening.
            result["timing_note"] = (
                "scalar-materialization fence + stability screening; "
                "prior rounds used a non-synchronizing fence")
            log(f"WARNING: throughput below prior round "
                f"({pts_per_s:.0f} vs {prior:.0f} pts/s); prior rounds "
                f"used a non-synchronizing timing fence (see "
                f"timing_note)")

    print(json.dumps(result))


def packed_batch_scenario(ks=None, n_lanes=8):
    """Packed multi-tenant batching scenario (ISSUE-12,
    docs/perf_packed_batching.md): for each K in ``ks`` (default
    1,2,4,8; override with BENCH_PACKED_KS), sweep K same-bucket
    synthetic mechanisms as ONE packed dispatch, recording pack
    occupancy, the marginal compile bill of a SECOND fresh-mechanism
    pack in the warm ``(bucket, K, lanes)`` cell (contract: zero for
    K>1), the one-counted-sync contract and per-tenant pts/s; the
    largest K is also checked bitwise against per-tenant solo sweeps.
    K=1 rides the byte-identical solo delegation and serves as the
    throughput baseline. Returns a record dict whose ``packed_ok`` is
    the --smoke hard gate."""
    if ks is None:
        ks = tuple(int(s) for s in os.environ.get(
            "BENCH_PACKED_KS", "1,2,4,8").split(","))

    from pycatkin_tpu import engine
    from pycatkin_tpu.frontend import abi
    from pycatkin_tpu.models.synthetic import synthetic_system
    from pycatkin_tpu.parallel.batch import (broadcast_conditions,
                                             packed_sweep_steady_state,
                                             prewarm_packed_sweep_programs,
                                             sweep_steady_state)
    from pycatkin_tpu.utils import profiling

    def _tenants(k, base):
        out = []
        for i in range(k):
            sim = synthetic_system(n_species=12, n_reactions=14,
                                   seed=base + i)
            conds = broadcast_conditions(sim.conditions(), n_lanes)
            conds = conds._replace(
                T=np.linspace(430.0, 720.0, n_lanes) + 2.0 * i)
            mask = engine.tof_mask_for(sim.spec,
                                       [sim.spec.rnames[-1]])
            out.append((sim.spec, conds, mask))
        return out

    # The scenario is about the packed path, so it forces the ABI gate
    # on for its own duration regardless of the ambient mode (restored
    # below -- the manifest env gate audits the post-scenario state).
    prev_abi = os.environ.get(abi.ABI_ENV)
    os.environ[abi.ABI_ENV] = "1"
    rows, failures = [], []
    try:
        for k in ks:
            tenants = _tenants(k, base=1000 * k)
            specs = [t[0] for t in tenants]
            conds_l = [t[1] for t in tenants]
            masks = [t[2] for t in tenants]
            row = {"k": int(k)}
            if k > 1:
                kb = 1 << max(0, (k - 1).bit_length())
                row["k_bucket"] = kb
                row["pack_occupancy"] = k / kb
                t0 = time.perf_counter()
                prewarm_packed_sweep_programs(specs, conds_l,
                                              tof_mask=masks,
                                              check_stability=True)
                row["prewarm_s"] = round(time.perf_counter() - t0, 2)
                fresh = _tenants(k, base=1000 * k + 500)
                n_m = prewarm_packed_sweep_programs(
                    [t[0] for t in fresh], [t[1] for t in fresh],
                    tof_mask=[t[2] for t in fresh],
                    check_stability=True)
                row["marginal_compiled"] = int(n_m.compiled)
                if n_m.compiled:
                    failures.append(
                        f"K={k}: a fresh-mechanism pack in the warm "
                        f"bucket compiled {int(n_m.compiled)} "
                        f"program(s) (must be 0)")
            # Warm (uncounted) dispatch, then the timed one under the
            # sync budget. K=1 is the solo delegation by contract.
            packed_sweep_steady_state(specs, conds_l, tof_mask=masks,
                                      check_stability=True)
            profiling.reset_sync_count()
            t0 = time.perf_counter()
            with profiling.sync_budget() as budget:
                outs = packed_sweep_steady_state(specs, conds_l,
                                                 tof_mask=masks,
                                                 check_stability=True)
            wall = time.perf_counter() - t0
            n_ok = int(sum(int(np.sum(np.asarray(o["success"])))
                           for o in outs))
            row.update({
                "wall_s": round(wall, 4),
                "sync_count": budget.count,
                "sync_labels": budget.labels,
                "converged": n_ok,
                "pts_per_s_per_tenant": round(n_lanes / wall, 1),
                "pts_per_s_total": round(k * n_lanes / wall, 1),
            })
            if n_ok != k * n_lanes:
                failures.append(f"K={k}: {n_ok}/{k * n_lanes} lanes "
                                f"converged")
            if k > 1 and (budget.count != 1 or budget.labels
                          != ["packed fused tail bundle"]):
                failures.append(
                    f"K={k}: packed clean sweep spent {budget.count} "
                    f"counted sync(s) {budget.labels} (contract: "
                    f"exactly 1)")
            if k > 1 and k == max(ks):
                mismatched = []
                for i, (s, c, m) in enumerate(tenants):
                    solo = sweep_steady_state(s, c, tof_mask=m,
                                              check_stability=True)
                    for key in sorted(set(solo) | set(outs[i])):
                        if key not in solo or key not in outs[i]:
                            mismatched.append(f"tenant {i}: {key}")
                            continue
                        a = np.asarray(solo[key])
                        b = np.asarray(outs[i][key])
                        if (a.dtype != b.dtype or a.shape != b.shape
                                or a.tobytes() != b.tobytes()):
                            mismatched.append(f"tenant {i}: {key}")
                row["equiv_ok"] = not mismatched
                if mismatched:
                    failures.append(
                        f"K={k}: packed != solo bitwise: "
                        + ", ".join(mismatched))
            rows.append(row)
    finally:
        if prev_abi is None:
            os.environ.pop(abi.ABI_ENV, None)
        else:
            os.environ[abi.ABI_ENV] = prev_abi
    return {"ks": [int(k) for k in ks], "n_lanes": n_lanes,
            "rows": rows, "failures": failures,
            "packed_ok": not failures}


def smoke_main():
    """``bench.py --smoke``: the ``make bench-smoke`` CI lane. The
    pclint static-analysis gate followed by an 8x8 sweep with prewarm
    on whatever backend is available (CPU in CI), exiting non-zero on
    any new lint finding, any crash, a clean sweep spending more
    than 2 counted host syncs (the fused single-dispatch tail spends
    exactly 1), a prewarmed program missing its cost-ledger row, a
    sweep output missing its per-lane telemetry bundle, a breach of
    the packed multi-tenant contracts (zero marginal compiles, one
    sync, bitwise-vs-solo; ``packed_ok``), a direction-kernel breach
    (interpret-mode Pallas LU vs XLA LU bit-compare + forced-kernel
    sweep verdict identity; ``kernels_ok``), any pcsan runtime
    tripwire firing on the sanitizer-guarded re-run (``san_ok``), or a
    key-integrity breach (``keys_ok``: a program-key collision under
    the armed trace-ident sanitizer, or pack-manifest jaxpr
    fingerprints failing the export/audit/import round trip) -- the
    cheap
    end-to-end canary that the correctness gates and the pipelined
    executor survive integration, not a throughput record. Prints
    exactly one JSON line."""
    global GRID_N
    GRID_N = 8

    # Static gate first: a lint breach fails the lane before any
    # compile time is spent (baseline-suppressed findings pass).
    from pycatkin_tpu.lint import lint_repo
    lint_active = lint_repo()
    if lint_active:
        for f in lint_active:
            log(f"bench-smoke: lint: {f.location()}: {f.rule} "
                f"{f.message}")
        print(json.dumps({"metric": "smoke", "lint_ok": False,
                          "lint_findings": len(lint_active)}))
        log(f"bench-smoke: FAIL -- {len(lint_active)} pclint "
            f"finding(s); run `make lint` for details")
        return 1

    from pycatkin_tpu.utils.cache import enable_persistent_cache
    enable_persistent_cache()

    import tempfile

    from pycatkin_tpu.parallel.batch import (PREWARM_PROGRAM_BUDGET,
                                             prewarm_program_count,
                                             prewarm_sweep_programs,
                                             sweep_steady_state)
    from pycatkin_tpu.utils import profiling

    sim, spec, conds, mask, metric, _ = _build_problem()
    n = GRID_N * GRID_N
    max_syncs = 2

    # Program-zoo diet gate: the production bench layout, counted
    # arithmetically (one consolidated rescue program per bucket, jac
    # at tier-2 shapes only), must fit PREWARM_PROGRAM_BUDGET. Catches
    # any layout growth or a prewarm regression back toward the r05
    # four-variants-per-bucket zoo before it costs bench wall time.
    planned = prewarm_program_count(tof=True, check_stability=True,
                                    **FULL_PREWARM_LAYOUT)

    # Scratch AOT cache: the smoke lane must not depend on (or pollute)
    # the repo's real cache directory.
    with tempfile.TemporaryDirectory(prefix="pycatkin_smoke_") as tmp:
        os.environ["PYCATKIN_AOT_CACHE"] = tmp
        # Trace-ident armed for the WHOLE lane (pckey): every program
        # fingerprinted from the prewarm on, so the scratch cache's
        # entries -- and the pack exported by the keys gate below --
        # carry jaxpr fingerprints.
        from pycatkin_tpu.san import trace_ident as _san_trace_ident
        _san_trace_ident.reset()
        _san_trace_ident.activate()
        t0 = time.perf_counter()
        n_prog = prewarm_sweep_programs(spec, conds, tof_mask=mask,
                                        buckets=(8,),
                                        check_stability=True)
        prewarm_s = time.perf_counter() - t0
        profiling.reset_sync_count()
        t0 = time.perf_counter()
        # Run-scoped trace OUTSIDE the budget: sync_budget() measures
        # the ambient trace, so entering the trace first makes the
        # budget read the smoke sweep's own counters -- and the
        # exported Chrome trace below must reproduce them exactly.
        from pycatkin_tpu import obs
        with obs.run_trace("smoke sweep") as tr:
            with profiling.sync_budget() as budget:
                out = sweep_steady_state(spec, conds, tof_mask=mask,
                                         check_stability=True)
        wall = time.perf_counter() - t0

        # ABI zero-compile gate (PYCATKIN_ABI=1 only): a second
        # mechanism landing in the warm bucket must resolve the whole
        # zoo from the registry -- zero fresh compiles, hard-failed
        # below like the sync budget. A thermo-perturbed variant is a
        # different mechanism to the traced programs but shares the
        # bucket by construction.
        from pycatkin_tpu.frontend.abi import maybe_lower
        abi_marginal_prewarm_s = None
        abi_marginal_compiled = None
        abi_zero_compile_ok = True
        if maybe_lower(spec) is not None:
            import dataclasses
            spec_b = dataclasses.replace(
                spec, add0=np.asarray(spec.add0) + 0.013)
            t0 = time.perf_counter()
            n_b = prewarm_sweep_programs(spec_b, conds, tof_mask=mask,
                                         buckets=(8,),
                                         check_stability=True)
            abi_marginal_prewarm_s = time.perf_counter() - t0
            abi_marginal_compiled = int(n_b.compiled)
            abi_zero_compile_ok = n_b.compiled == 0

        # Precision-tier gate (ISSUE-11): flipping the tier to
        # f32-polish must converge the same sweep, reproduce the f64
        # verdict masks bitwise, and stamp the telemetry tier column
        # on every first-pass acceptance
        # (docs/perf_precision_tiers.md). Runs inside the scratch AOT
        # cache block: the tiered program is a fresh compile.
        from pycatkin_tpu import precision
        ambient_tier = precision.active_tier()
        tier_prev = os.environ.get(precision.TIER_ENV)
        tier_err = None
        try:
            os.environ[precision.TIER_ENV] = "f32-polish"
            out32 = sweep_steady_state(spec, conds, tof_mask=mask,
                                       check_stability=True)
            for k in ("success", "stable", "quarantined"):
                a, b = np.asarray(out[k]), np.asarray(out32[k])
                if a.tobytes() != b.tobytes():
                    tier_err = (f"verdict {k!r} differs between "
                                f"{ambient_tier} and f32-polish")
                    break
            tel32 = np.asarray(out32["lane_telemetry"])
            code32 = precision.TIER_CODES["f32-polish"]
            if tier_err is None and not np.any(tel32[:, 4] == code32):
                tier_err = ("no telemetry row carries the f32-polish "
                            "tier code")
        except Exception as e:  # noqa: BLE001 - gate reports & fails
            tier_err = str(e)
        finally:
            if tier_prev is None:
                os.environ.pop(precision.TIER_ENV, None)
            else:
                os.environ[precision.TIER_ENV] = tier_prev
        tier_ok = tier_err is None

        # Direction-kernel gate (ISSUE-18): the interpret-mode Pallas
        # LU bit-compared against the XLA-op LU on an 8x8 lane batch
        # at two ABI bucket shapes, then the same 8x8 sweep re-run
        # with the kernel tier forced (PYCATKIN_LINALG_KERNEL=pallas
        # + PYCATKIN_LINALG_INTERPRET=1) -- verdict masks must
        # reproduce the ambient-kernel sweep bitwise and the solved
        # states stay inside the documented envelope
        # (docs/perf_pallas_linalg.md).
        kernels_err = None
        kern_prev = os.environ.get(precision.KERNEL_ENV)
        interp_prev = os.environ.get(precision.INTERPRET_ENV)
        try:
            import jax.numpy as _jnp

            from pycatkin_tpu.ops import linalg as _linalg
            from pycatkin_tpu.ops import pallas_linalg as _plk
            krng = np.random.default_rng(18)
            for nk in (16, 32):
                Ak = _jnp.asarray(
                    krng.standard_normal((GRID_N * GRID_N, nk, nk)))
                Ak = Ak + 4 * _jnp.eye(nk)
                bk = _jnp.asarray(
                    krng.standard_normal((GRID_N * GRID_N, nk)))
                import jax as _jx
                # Lane-for-lane the kernel is a bitwise twin of the
                # XLA LU (same arithmetic, same order) -- pin that on
                # one lane.
                xp1 = _plk.factor_solve(Ak[0], bk[0])
                xx1 = _linalg.lu_solve(*_linalg.lu_factor(Ak[0]),
                                       bk[0])
                if (np.asarray(xp1).tobytes()
                        != np.asarray(xx1).tobytes()):
                    kernels_err = (f"interpret-mode kernel not "
                                   f"bit-identical to the XLA LU at "
                                   f"n={nk}")
                    break
                # Under vmap XLA batches its contractions (reduction
                # reorder), so the lane batch carries a tiny measured
                # envelope instead (docs/perf_pallas_linalg.md).
                xp = _jx.vmap(_plk.factor_solve)(Ak, bk)
                xx = _jx.vmap(lambda a, r: _linalg.lu_solve(
                    *_linalg.lu_factor(a), r))(Ak, bk)
                if not np.allclose(np.asarray(xp), np.asarray(xx),
                                   rtol=1e-10, atol=1e-14):
                    kernels_err = (f"vmapped kernel left the XLA-LU "
                                   f"equivalence envelope at n={nk}")
                    break
            if kernels_err is None:
                os.environ[precision.KERNEL_ENV] = "pallas"
                os.environ[precision.INTERPRET_ENV] = "1"
                outk = sweep_steady_state(spec, conds, tof_mask=mask,
                                          check_stability=True)
                for k in ("success", "stable", "quarantined"):
                    a, b = np.asarray(out[k]), np.asarray(outk[k])
                    if a.tobytes() != b.tobytes():
                        kernels_err = (f"verdict {k!r} differs "
                                       f"between the xla and pallas "
                                       f"kernel tiers")
                        break
            if kernels_err is None:
                ya, yk = np.asarray(out["y"]), np.asarray(outk["y"])
                ok = np.asarray(out["success"], dtype=bool)
                # Cross-trajectory envelope (independently converged
                # Newton runs; see docs/perf_pallas_linalg.md).
                if not np.allclose(ya[ok], yk[ok],
                                   rtol=1e-5, atol=1e-12):
                    kernels_err = ("solved states left the kernel "
                                   "equivalence envelope")
        except Exception as e:  # noqa: BLE001 - gate reports & fails
            kernels_err = str(e)
        finally:
            if kern_prev is None:
                os.environ.pop(precision.KERNEL_ENV, None)
            else:
                os.environ[precision.KERNEL_ENV] = kern_prev
            if interp_prev is None:
                os.environ.pop(precision.INTERPRET_ENV, None)
            else:
                os.environ[precision.INTERPRET_ENV] = interp_prev
        kernels_ok = kernels_err is None

        # Packed-batch gate (ISSUE-12): K same-bucket mechanisms as one
        # dispatch each, with the zero-marginal-compile, one-sync and
        # bitwise-vs-solo contracts hard-failed below
        # (docs/perf_packed_batching.md). Runs inside the scratch AOT
        # cache block so the packed executables never touch the repo
        # cache.
        try:
            packed = packed_batch_scenario()
        except Exception as e:  # noqa: BLE001 - gate reports & fails
            packed = {"error": str(e), "packed_ok": False}
        packed_ok = bool(packed.get("packed_ok"))

        # Serve gate (ISSUE-13): a miniature soak through the live
        # serving path (docs/serving.md) -- boot, warm, stream a
        # packed burst, drain -- gated on the shared SLO checks
        # (100% zero-compile rate after warmup, schema-complete
        # responses, loss-free drain). Runs inside the scratch AOT
        # cache block so the serve zoo never touches the repo cache;
        # the serve sub-object feeds the perfwatch history
        # (serve_p50_s / serve_p99_s / ...).
        from pycatkin_tpu.serve.soak import check_soak_record, run_soak
        try:
            serve_rec = run_soak(
                n_requests=12, buckets=(16,), lanes=3,
                mechs_per_bucket=2, max_occupancy=4, concurrency=8)
            serve_problems = check_soak_record(serve_rec)
        except Exception as e:  # noqa: BLE001 - gate reports & fails
            serve_rec = {"serve": {"error": str(e)}}
            serve_problems = [f"serve soak crashed: {e}"]
        serve = serve_rec.get("serve") or {}
        serve_ok = not serve_problems

        # Router gate (ISSUE-16): a miniature fleet chaos drill --
        # boot 2 subprocess replicas behind the front router, stream
        # a small grid, SIGKILL one replica mid-stream -- gated on
        # zero lost requests, bitwise identity against the
        # undisturbed baseline and a clean duplicate audit (the
        # pack-boot zero-compile proof runs in the full
        # `make router-check` lane, not here). The router sub-object
        # feeds the perfwatch history (router_availability /
        # failover_p99_s).
        from pycatkin_tpu.serve.soak import (check_chaos_record,
                                             run_chaos_drill)
        try:
            router_rec = run_chaos_drill(
                n_requests=8, bucket=16, lanes=2, mechs=2,
                n_replicas=2, kill=1, max_occupancy=2,
                with_pack=False)
            router_problems = check_chaos_record(router_rec)
        except Exception as e:  # noqa: BLE001 - gate reports & fails
            router_rec = {"router": {"error": str(e)}}
            router_problems = [f"router chaos drill crashed: {e}"]
        router = router_rec.get("router") or {}
        router_ok = not router_problems

        # Durable gate (ISSUE-17): the durable-serving smoke -- a
        # mini journal round-trip (rotation, compaction, torn-tail
        # replay) plus a router-kill replay over stub replicas,
        # gated on bitwise journal-served duplicates and a fully
        # re-answered backlog. JAX-free, runs in well under a second;
        # its replay/recovery walls feed the perfwatch history
        # (router_recovery_s / journal_replay_s).
        from pycatkin_tpu.serve.soak import (check_durable_record,
                                             run_durable_smoke)
        try:
            durable_rec = run_durable_smoke()
            durable_problems = check_durable_record(durable_rec)
        except Exception as e:  # noqa: BLE001 - gate reports & fails
            durable_rec = {"error": str(e)}
            durable_problems = [f"durable smoke crashed: {e}"]
        durable_ok = not durable_problems

        # Sanitizer gate (ISSUE-14, pcsan): the same 8x8 sweep once
        # more with all three runtime tripwires armed -- recompile
        # (one recording pass, then mark_warm: a warm cell must
        # dispatch zero fresh programs), strict sync region at the
        # budget (an uncounted device pull raises at the pull site),
        # and the event-loop stall watchdog around an armed loop that
        # offloads the sweep to a worker thread (the serve idiom: the
        # loop itself must never block). Any trip hard-fails the lane.
        import asyncio as _asyncio

        from pycatkin_tpu import san as _san
        from pycatkin_tpu.san import recompile as _san_recompile
        from pycatkin_tpu.san import stall as _san_stall
        from pycatkin_tpu.san import syncs as _san_syncs
        san_err = None
        prev_san = os.environ.get(_san.ENV)
        os.environ[_san.ENV] = "1"

        async def _guarded_sweep():
            await _san_stall.arm()
            with _san_syncs.strict(budget=max_syncs,
                                   label="san smoke sweep"):
                # to_thread copies the context, so the strict region
                # follows the sweep onto the worker thread while the
                # armed loop stays free to detect stalls.
                return await _asyncio.to_thread(
                    sweep_steady_state, spec, conds,
                    tof_mask=mask, check_stability=True)

        try:
            _san_recompile.reset()
            _san_recompile.activate()
            sweep_steady_state(spec, conds, tof_mask=mask,
                               check_stability=True)   # records keys
            _san_recompile.mark_warm()
            with _san_stall.watchdog():
                out_san = _asyncio.run(_guarded_sweep())
            if not bool(np.all(np.asarray(out_san["success"]))):
                san_err = "sweep under sanitizers lost lanes"
        except _san.SanError as e:
            san_err = str(e)
        finally:
            _san_recompile.deactivate()
            _san_recompile.reset()
            if prev_san is None:
                os.environ.pop(_san.ENV, None)
            else:
                os.environ[_san.ENV] = prev_san
        san_ok = san_err is None

        # Key-integrity gate (pckey): the trace-ident sanitizer armed
        # since before the prewarm must report zero key collisions,
        # and the scratch cache's fingerprints must survive a pack
        # export -> manifest audit -> import round trip (the same
        # audit `tools/aot_pack.py selftest` runs). Subprocess gates
        # (serve/router/durable) write unfingerprinted entries into
        # the shared scratch cache -- legal; the audit requires every
        # CARRIED fingerprint to match this process's trace record.
        keys_err = None
        keys_rec = {}
        try:
            keys_rec = dict(_san_trace_ident.stats())
            if keys_rec["collisions"]:
                keys_err = (f"{keys_rec['collisions']} program-key "
                            f"collision(s): one key bound to two "
                            f"distinct jaxprs")
            elif not keys_rec["programs"]:
                keys_err = ("trace-ident recorded no programs -- the "
                            "dispatch-seam hook is dead")
            else:
                import tarfile as _tarfile

                from pycatkin_tpu.parallel import compile_pool as _cp
                pack = os.path.join(tmp, "keys_gate_pack.tgz")
                _cp.export_cache_pack(pack, cache_root=tmp)
                with _tarfile.open(pack, "r:gz") as tar:
                    man = json.load(tar.extractfile(_cp.PACK_MANIFEST))
                carried = mismatched = 0
                for key, meta in man.get("entries", {}).items():
                    fp = meta.get("trace_ident")
                    if not fp:
                        continue
                    carried += 1
                    local = _san_trace_ident.fingerprint_for(key)
                    if local is not None and local != fp:
                        mismatched += 1
                keys_rec.update(manifest_entries=len(
                    man.get("entries", {})), fingerprinted=carried,
                    mismatched=mismatched)
                if not carried:
                    keys_err = ("exported pack manifest carries no "
                                "jaxpr fingerprints")
                elif mismatched:
                    keys_err = (f"{mismatched} manifest fingerprint(s) "
                                f"disagree with locally-traced "
                                f"programs")
                else:
                    # Import replays fingerprints through the armed
                    # sanitizer: a contradiction raises here.
                    imp_root = os.path.join(tmp, "keys_gate_import")
                    _cp.import_cache_pack(pack, cache_root=imp_root)
        except _san.SanError as e:
            keys_err = str(e)
        except Exception as e:  # noqa: BLE001 - gate reports & fails
            keys_err = f"keys gate crashed: {e}"
        finally:
            _san_trace_ident.deactivate()
            _san_trace_ident.reset()
        keys_ok = keys_err is None
    n_ok = int(np.sum(np.asarray(out["success"])))
    clean = bool(np.all(np.asarray(out["success"])))
    # Only a CLEAN sweep is held to the budget: failed lanes buy the
    # rescue ladder its (labeled, counted) failure-path syncs.
    breach = clean and budget.count > max_syncs
    budget_breach = (int(n_prog) > PREWARM_PROGRAM_BUDGET
                     or planned > PREWARM_PROGRAM_BUDGET)

    # Observability gates (ISSUE-8): the exported Chrome trace must
    # parse and reproduce the counted sync labels verbatim (on the
    # clean fused path: exactly the "fused tail bundle" sync); the
    # metrics snapshot must have seen the prewarm's compiles/cache
    # traffic and this sweep's lanes; the run manifest must list every
    # PYCATKIN_* knob currently set (PYCATKIN_AOT_CACHE above at
    # minimum).
    from pycatkin_tpu.obs import (load_trace, run_manifest,
                                  write_chrome_trace)
    from pycatkin_tpu.obs import metrics as obs_metrics
    from pycatkin_tpu.parallel.batch import _fused_enabled
    trace_ok, trace_err = True, None
    scratch = None
    trace_dir = TRACE_DIR
    if trace_dir is None:
        scratch = tempfile.TemporaryDirectory(prefix="pycatkin_trace_")
        trace_dir = scratch.name
    else:
        os.makedirs(trace_dir, exist_ok=True)
    try:
        trace_path = os.path.join(trace_dir, "smoke.trace.json")
        write_chrome_trace(trace_path, tr)
        tobj = load_trace(trace_path)
        sync_names = [ev["name"] for ev in tobj["traceEvents"]
                      if ev.get("cat") == "sync"]
        if sync_names != budget.labels:
            raise ValueError(f"trace sync labels {sync_names} != "
                             f"budget labels {budget.labels}")
        if clean and _fused_enabled() \
                and "fused tail bundle" not in sync_names:
            raise ValueError("clean fused sweep trace is missing the "
                             "'fused tail bundle' sync")
    except (OSError, ValueError, KeyError) as e:
        trace_ok, trace_err = False, str(e)
    finally:
        if scratch is not None:
            scratch.cleanup()

    counters = obs_metrics.snapshot()["counters"]

    def _ctotal(name):
        return sum(counters.get(name, {}).values())

    compile_traffic = (_ctotal("pycatkin_compile_total")
                       + _ctotal("pycatkin_aot_cache_hits_total")
                       + _ctotal("pycatkin_aot_cache_misses_total"))
    metrics_ok = (compile_traffic > 0
                  and _ctotal("pycatkin_lanes_solved_total") >= n
                  and _ctotal("pycatkin_host_syncs_total") > 0)

    # Cost-ledger gate (ISSUE-9): every program the smoke prewarm
    # ensured must own a ledger row with nonnegative compile-time
    # flops/bytes, and the dispatched sweep must have accumulated
    # blocked wall on at least one row (the dispatch-wall join that
    # turns costs into achieved FLOP/s).
    from pycatkin_tpu.obs import lane_summary, ledger_snapshot
    cost_ledger = ledger_snapshot()
    led_rows = cost_ledger["programs"]
    n_costed = sum(1 for r in led_rows.values()
                   if r.get("flops", -1.0) >= 0.0
                   and r.get("bytes_accessed", -1.0) >= 0.0)
    dispatched = any(r.get("dispatches", 0) > 0
                     and r.get("blocked_wall_s", 0.0) > 0.0
                     for r in led_rows.values())
    costs_ok = n_costed >= int(n_prog) and dispatched

    # Per-lane telemetry gate: the sweep output must carry the packed
    # [lanes, 5] bundle (it rides inside the one counted sync) and the
    # per-lane histograms must have observed every lane.
    lane_tel = out.get("lane_telemetry")
    hists = obs_metrics.snapshot()["histograms"]
    lane_obs = sum(st["count"] for st in
                   hists.get("pycatkin_lane_iterations", {}).values())
    lane_telemetry_ok = (lane_tel is not None and len(lane_tel) == n
                         and lane_obs >= n)

    # Elastic chaos gate (ISSUE-10): a small lease-scheduled sweep
    # with an injected worker-crash must complete with zero lost lanes
    # and at least one supervised restart. The fault plan travels via
    # the WORKER environment only, so the manifest env gate below
    # (which audits this process's PYCATKIN_* vars) stays clean.
    from pycatkin_tpu.robustness.scheduler import chaos_drill
    try:
        elastic = chaos_drill()
        elastic_ok = bool(elastic["ok"])
    except Exception as e:  # noqa: BLE001 - gate reports, then fails
        elastic, elastic_ok = {"error": str(e)}, False

    manifest = run_manifest()
    set_knobs = sorted(k for k in os.environ
                       if k.startswith("PYCATKIN_"))
    manifest_ok = sorted(manifest.get("env") or {}) == set_knobs
    if TRACE_DIR:
        with open(os.path.join(TRACE_DIR, "manifest.json"), "w") as f:
            json.dump(manifest, f, indent=2, sort_keys=True)
    import jax as _jax
    result = {
        "metric": metric + " (smoke)",
        "backend": _jax.devices()[0].platform,
        "tier": ambient_tier,
        "tier_ok": tier_ok,
        "tier_error": tier_err,
        "n_points": n,
        "converged": n_ok,
        "prewarm_s": round(prewarm_s, 2),
        "prewarm_programs": int(n_prog),
        "n_programs_prewarmed": int(n_prog),
        "full_bench_programs": planned,
        "program_budget": int(PREWARM_PROGRAM_BUDGET),
        "program_budget_ok": not budget_breach,
        "wall_s": round(wall, 2),
        "host_syncs": budget.count,
        "sync_count": budget.count,
        "sync_labels": budget.labels,
        "max_syncs": max_syncs,
        "sync_budget_ok": not breach,
        "abi_marginal_prewarm_s": (round(abi_marginal_prewarm_s, 2)
                                   if abi_marginal_prewarm_s is not None
                                   else None),
        "abi_marginal_compiled": abi_marginal_compiled,
        "abi_zero_compile_ok": abi_zero_compile_ok,
        "kernels_ok": kernels_ok,
        "kernels_error": kernels_err,
        "packed": packed,
        "packed_ok": packed_ok,
        "serve": serve,
        "serve_ok": serve_ok,
        "router": router,
        "router_ok": router_ok,
        "durable": {
            "roundtrip": durable_rec.get("roundtrip"),
            "replay": durable_rec.get("replay"),
            "dup": durable_rec.get("dup"),
            "router_recovery_s": (durable_rec.get("replay")
                                  or {}).get("router_recovery_s"),
            "journal_replay_s": (durable_rec.get("replay")
                                 or {}).get("wall_s"),
            "error": durable_rec.get("error"),
        },
        "durable_ok": durable_ok,
        "san_ok": san_ok,
        "san_error": san_err,
        "keys_ok": keys_ok,
        "keys_error": keys_err,
        "keys": keys_rec,
        "lint_ok": True,
        "lint_findings": 0,
        "trace_ok": trace_ok,
        "trace_error": trace_err,
        "metrics_ok": metrics_ok,
        "manifest_ok": manifest_ok,
        "costs_ok": costs_ok,
        "cost_ledger_programs": len(led_rows),
        "mfu": (cost_ledger.get("totals") or {}).get("mfu"),
        "lane_telemetry_ok": lane_telemetry_ok,
        "elastic_ok": elastic_ok,
        "elastic": elastic,
        "lanes": (lane_summary(lane_tel) if lane_tel is not None
                  else None),
        # Small enough at 8x8 to ship whole; tools/obsview.py --lanes
        # renders this JSON line directly.
        "lane_telemetry": (np.asarray(lane_tel).tolist()
                           if lane_tel is not None else None),
        "manifest": manifest,
    }
    print(json.dumps(result))
    if not trace_ok:
        log(f"bench-smoke: FAIL -- trace export gate: {trace_err}")
        return 1
    if not metrics_ok:
        log(f"bench-smoke: FAIL -- metrics snapshot gate: compile "
            f"traffic {compile_traffic}, lanes "
            f"{_ctotal('pycatkin_lanes_solved_total')}, syncs "
            f"{_ctotal('pycatkin_host_syncs_total')}")
        return 1
    if not manifest_ok:
        log(f"bench-smoke: FAIL -- manifest env gate: manifest lists "
            f"{sorted(manifest.get('env') or {})}, process has "
            f"{set_knobs}")
        return 1
    if not costs_ok:
        log(f"bench-smoke: FAIL -- cost ledger gate: {n_costed} of "
            f"{int(n_prog)} prewarmed program(s) carry flops/bytes, "
            f"dispatch wall recorded: {dispatched}")
        return 1
    if not lane_telemetry_ok:
        log(f"bench-smoke: FAIL -- lane telemetry gate: bundle "
            f"{'missing' if lane_tel is None else len(lane_tel)}, "
            f"histogram observed {lane_obs}/{n} lanes")
        return 1
    if not elastic_ok:
        log(f"bench-smoke: FAIL -- elastic chaos gate: {elastic}")
        return 1
    if not abi_zero_compile_ok:
        log(f"bench-smoke: FAIL -- second mechanism in the warm ABI "
            f"bucket compiled {abi_marginal_compiled} program(s) "
            f"(must be 0 under PYCATKIN_ABI=1)")
        return 1
    if not tier_ok:
        log(f"bench-smoke: FAIL -- precision-tier gate: {tier_err}")
        return 1
    if not kernels_ok:
        log(f"bench-smoke: FAIL -- direction-kernel gate: "
            f"{kernels_err}")
        return 1
    if not packed_ok:
        detail = (packed.get("error")
                  or "; ".join(packed.get("failures") or ())
                  or "no rows")
        log(f"bench-smoke: FAIL -- packed-batch gate: {detail}")
        return 1
    if not serve_ok:
        log(f"bench-smoke: FAIL -- serve gate: "
            f"{'; '.join(serve_problems)}")
        return 1
    if not router_ok:
        log(f"bench-smoke: FAIL -- router gate: "
            f"{'; '.join(router_problems)}")
        return 1
    if not durable_ok:
        log(f"bench-smoke: FAIL -- durable gate: "
            f"{'; '.join(durable_problems)}")
        return 1
    if not san_ok:
        log(f"bench-smoke: FAIL -- sanitizer gate (pcsan): {san_err}")
        return 1
    if not keys_ok:
        log(f"bench-smoke: FAIL -- key-integrity gate (pckey): "
            f"{keys_err}")
        return 1
    if budget_breach:
        log(f"bench-smoke: FAIL -- program count over budget "
            f"(smoke prewarmed {int(n_prog)}, full bench layout "
            f"{planned}, budget {PREWARM_PROGRAM_BUDGET})")
        return 1
    if breach:
        log(f"bench-smoke: FAIL -- clean sweep spent {budget.count} "
            f"host syncs (budget {max_syncs}): {budget.labels}")
        return 1
    log(f"bench-smoke: OK -- {budget.count} host sync(s) on the sweep, "
        f"{n_ok}/{n} converged, {int(n_prog)} program(s) prewarmed "
        f"(full bench layout {planned}/{PREWARM_PROGRAM_BUDGET})")
    return 0


def _linalg_cells(buckets, tiers, lanes_for, iters, rng):
    """The (bucket, tier, kernel) microbench grid for linalg_main:
    batched factorize+solve wall per cell, via the SAME entry points
    the sweep hot path dispatches through (linalg.select_solver's two
    kernel tiers called directly, no env games)."""
    import jax
    import jax.numpy as jnp

    from pycatkin_tpu import precision
    from pycatkin_tpu.ops import linalg as _linalg
    from pycatkin_tpu.ops import pallas_linalg as _plk

    cells = []
    for n in buckets:
        lanes = lanes_for(n)
        for tier in tiers:
            dtype = precision.bulk_dtype(tier)
            # Well-conditioned batch: random + dominant diagonal (the
            # microbench measures kernel throughput, not rescue-ladder
            # conditioning behavior -- tests own the hard numerics).
            A = jnp.asarray(rng.standard_normal((lanes, n, n)),
                            dtype=dtype) + 4 * jnp.eye(n, dtype=dtype)
            b = jnp.asarray(rng.standard_normal((lanes, n)),
                            dtype=dtype)
            # 2/3 n^3 factorization + 2 n^2 substitution useful flops
            # per lane-solve (the classical LU count; shared numerator
            # for both kernels so the cells are comparable).
            cell_flops = lanes * (2.0 * n ** 3 / 3.0 + 2.0 * n ** 2)
            for kernel, fn in (
                    ("xla", lambda a, r: _linalg.lu_solve(
                        *_linalg.lu_factor(a), r)),
                    ("pallas", _plk.factor_solve)):
                run = jax.jit(jax.vmap(fn))
                try:
                    x = run(A, b)
                    jax.block_until_ready(x)
                    t0 = time.perf_counter()
                    for _ in range(iters):
                        x = run(A, b)
                    jax.block_until_ready(x)
                    wall = time.perf_counter() - t0
                except Exception as e:  # noqa: BLE001 - cell reports
                    cells.append({"bucket": n, "tier": tier,
                                  "kernel": kernel, "error": str(e)})
                    continue
                cells.append({
                    "bucket": n, "tier": tier, "kernel": kernel,
                    "lanes": lanes, "iters": iters,
                    "wall_s": round(wall, 4),
                    "flops_per_solve": cell_flops,
                    "achieved_flops_per_s": cell_flops * iters / wall,
                })
    return cells


def linalg_main(argv):
    """``bench.py --linalg``: the direction-kernel microbench lane
    (docs/perf_pallas_linalg.md). Batched dense factorize+solve wall,
    achieved FLOP/s and MFU per (ABI bucket, precision tier, kernel)
    cell -- the Pallas VMEM-resident LU against the XLA-op LU it
    tiers behind -- printed as exactly one JSON line.

    MFU here divides by a MEASURED per-backend ceiling: a dense-matmul
    roofline probe run at each tier's bulk dtype in-process, not a
    datasheet number and not the scaled-by-16 estimate the f32 roofline
    note used to carry. ``--quick`` shrinks lanes/iters for CI. The
    ``linalg`` sub-object (``mfu_<bucket>``) feeds the perfwatch
    history (``linalg_mfu_<bucket>`` tracked metrics)."""
    import jax
    import jax.numpy as jnp

    from pycatkin_tpu import precision

    quick = "--quick" in argv
    iters = 2 if quick else int(os.environ.get("BENCH_LINALG_ITERS",
                                               "5"))
    rng = np.random.default_rng(18)
    from pycatkin_tpu.ops.pallas_linalg import PALLAS_BUCKETS

    def lanes_for(n):
        base = 4096 if not quick else 512
        return max(2, min(256, base // n))

    # Measured compute ceiling per tier: chained square matmuls at the
    # tier's bulk dtype (the arithmetic class the solver actually
    # runs), timed on THIS backend. The real denominator the MFU
    # numbers below are honest against.
    peaks = {}
    m = 512 if quick else 1024
    for tier in precision.TIERS:
        dtype = precision.bulk_dtype(tier)
        a = jnp.asarray(rng.standard_normal((m, m)), dtype=dtype)
        mm = jax.jit(lambda x, y: x @ y)
        out = jax.block_until_ready(mm(a, a))
        reps = 4 if quick else 10
        t0 = time.perf_counter()
        for _ in range(reps):
            out = mm(a, out)
        jax.block_until_ready(out)
        wall = time.perf_counter() - t0
        peaks[tier] = 2.0 * m ** 3 * reps / wall

    cells = _linalg_cells(PALLAS_BUCKETS, precision.TIERS, lanes_for,
                          iters, rng)
    for c in cells:
        peak = peaks.get(c.get("tier"))
        if peak and c.get("achieved_flops_per_s"):
            c["mfu"] = round(c["achieved_flops_per_s"] / peak, 6)

    # Per-bucket headline MFU for perfwatch: the Pallas kernel cell at
    # f64 (the tier every sweep verdict is certified at). Absent cells
    # (a kernel that failed to run) simply leave the metric out.
    linalg_summary = {}
    for c in cells:
        if (c.get("kernel") == "pallas" and c.get("tier") == "f64"
                and c.get("mfu") is not None):
            linalg_summary[f"mfu_{c['bucket']}"] = c["mfu"]

    result = {
        "metric": "linalg microbench",
        "backend": jax.devices()[0].platform,
        "unit": "mfu vs measured matmul ceiling",
        "interpret": jax.default_backend() != "tpu",
        "peak_measured_flops_per_s": {t: round(p, 1)
                                      for t, p in peaks.items()},
        "cells": cells,
        "linalg": linalg_summary,
    }
    print(json.dumps(result))
    for c in cells:
        if "error" in c:
            log(f"bench-linalg: FAIL -- cell {c['bucket']}/{c['tier']}"
                f"/{c['kernel']}: {c['error']}")
            return 1
    log("bench-linalg: OK -- " + ", ".join(
        f"n={b}: {linalg_summary.get(f'mfu_{b}', float('nan')):.3f}"
        for b in PALLAS_BUCKETS))
    return 0


def transient_main(argv):
    """``bench.py --transient``: the fused dense-output transient lane
    (docs/perf_transient.md). Times the fused single-dispatch sweep
    (``batch_transient`` with PYCATKIN_FUSED_TRANSIENT on) against the
    host-driven chunk loop it replaces (same programs, forced
    multi-chunk as on a watchdogged TPU runtime), checks the endpoints
    bitwise-identical, pins the fused sync budget (exactly one counted
    sync, the ``fused transient bundle`` pull) and counts save-buffer
    materializations through the obs counter. Prints exactly one JSON
    line; the ``transient`` sub-object (``transient_pts_per_s``) feeds
    the perfwatch history. ``--gate`` additionally requires the >= 3x
    fused-over-chunked wall ratio the design targets; ``--quick``
    shrinks the grid for CI."""
    import jax.numpy as jnp

    from pycatkin_tpu import engine
    from pycatkin_tpu.models.synthetic import synthetic_system
    from pycatkin_tpu.obs import metrics as _metrics
    from pycatkin_tpu.parallel import batch as _batch
    from pycatkin_tpu.utils import profiling

    quick = "--quick" in argv
    gate = "--gate" in argv
    lanes = int(os.environ.get("BENCH_TRANSIENT_LANES", "2"))
    n_pts = int(os.environ.get("BENCH_TRANSIENT_PTS",
                               "513" if quick else "2049"))
    chunk = int(os.environ.get("BENCH_TRANSIENT_CHUNK", "1"))
    trials = 2 if quick else 3

    # The dense-output workload the fused scan targets (ROADMAP item
    # 4's surrogate-teacher use): a uniform fine-resolution save grid
    # where each point costs about one integrator step, so the host
    # drive pays one dispatch + one blocking pull PER POINT (chunk=1,
    # the reference implementation's solve-loop pattern) while the
    # fused program amortizes the whole grid into one dispatch. h0 is
    # matched to the grid spacing so neither path burns steps ramping
    # up from the default 1e-10.
    sim = synthetic_system(n_species=12, n_reactions=14, seed=7)
    spec = sim.spec
    conds = _batch.broadcast_conditions(sim.conditions(), lanes)
    conds = conds._replace(T=np.linspace(480.0, 560.0, lanes))
    save_ts = np.linspace(0.0, (n_pts - 1) * 1.0e-8, n_pts)
    opts = engine.ODEOptions(h0=1.0e-8)

    def _mat_count():
        vals = _metrics.counter(
            "pycatkin_transient_materializations_total").values()
        return float(sum(vals.values()))

    def run_chunked():
        # The production fallback path exactly as a TPU runtime would
        # drive it: bounded chunks, one device call + one blocking
        # pull per chunk (force_chunking skips the off-TPU collapse
        # to a single chunk so the baseline is honest about the host
        # round-trips the fused path deletes).
        cprog = _batch._transient_chunk_program(
            _batch._prog_spec(spec), opts)
        fprog = _batch._transient_finish_program(
            _batch._prog_spec(spec), engine.finish_options(opts))
        return engine.chunked_transient_drive(
            cprog, fprog, conds,
            jnp.asarray(conds.y0, dtype=jnp.float64), save_ts, opts,
            chunk, batched=True, force_chunking=True)

    def run_fused():
        return _batch.batch_transient(spec, conds, save_ts, opts=opts)

    failures = []
    prev_env = os.environ.get(engine.FUSED_TRANSIENT_ENV)
    os.environ[engine.FUSED_TRANSIENT_ENV] = "1"
    try:
        # Warm both paths (compiles excluded from the timed trials).
        ys_f, ok_f = run_fused()
        ys_c, ok_c = run_chunked()

        for name, a, b in (("ys", ys_f, ys_c), ("ok", ok_f, ok_c)):
            a, b = np.asarray(a), np.asarray(b)
            if (a.dtype != b.dtype or a.shape != b.shape
                    or a.tobytes() != b.tobytes()):
                failures.append(f"fused {name} != chunked {name} "
                                f"(bitwise)")

        m0 = _mat_count()
        profiling.reset_sync_count()
        fused_walls = []
        for _ in range(trials):
            t0 = time.perf_counter()
            with profiling.sync_budget() as budget:
                run_fused()
            fused_walls.append(time.perf_counter() - t0)
            if (budget.count != 1
                    or budget.labels != ["fused transient bundle"]):
                failures.append(
                    f"fused sweep spent {budget.count} counted "
                    f"sync(s) {budget.labels} (contract: exactly 1, "
                    f"the bundle pull)")
        fused_mat = _mat_count() - m0

        m0 = _mat_count()
        chunked_walls = []
        for _ in range(trials):
            t0 = time.perf_counter()
            run_chunked()
            chunked_walls.append(time.perf_counter() - t0)
        chunked_mat = _mat_count() - m0
    finally:
        if prev_env is None:
            os.environ.pop(engine.FUSED_TRANSIENT_ENV, None)
        else:
            os.environ[engine.FUSED_TRANSIENT_ENV] = prev_env

    fused_s = float(np.median(fused_walls))
    chunked_s = float(np.median(chunked_walls))
    speedup = chunked_s / fused_s if fused_s > 0 else float("inf")
    pts_per_s = lanes * len(save_ts) / fused_s
    if fused_mat != trials:
        failures.append(f"fused path materialized {fused_mat:.0f} "
                        f"buffers over {trials} sweeps (contract: 1 "
                        f"per sweep)")
    if gate and speedup < 3.0:
        failures.append(f"fused speedup {speedup:.2f}x < 3x gate "
                        f"(fused {fused_s:.4f}s vs chunked "
                        f"{chunked_s:.4f}s)")

    import jax
    result = {
        "metric": "transient sweep",
        "backend": jax.devices()[0].platform,
        "unit": "save points per second (fused, whole sweep)",
        "interpret": jax.default_backend() != "tpu",
        "lanes": lanes, "save_points": len(save_ts),
        "chunk": chunk, "trials": trials,
        "fused_wall_s": round(fused_s, 4),
        "chunked_wall_s": round(chunked_s, 4),
        "speedup": round(speedup, 3),
        "materializations": {"fused_per_sweep": fused_mat / trials,
                             "chunked_per_sweep":
                                 chunked_mat / trials},
        "bitwise_identical": not any("bitwise" in f
                                     for f in failures),
        "failures": failures,
        "transient": {"transient_pts_per_s": round(pts_per_s, 1)},
    }
    print(json.dumps(result))
    if failures:
        for f in failures:
            log(f"bench-transient: FAIL -- {f}")
        return 1
    log(f"bench-transient: OK -- {pts_per_s:.0f} pts/s fused, "
        f"{speedup:.2f}x over the chunked loop "
        f"({chunked_mat / trials:.0f} materializations -> 1)")
    return 0


def journal_main(argv):
    """Durable chunked sweep with checkpoint/resume (--journal mode)
    and/or per-lane failure forensics (--forensics).

    Prints exactly one JSON line: a durability report (chunks run/
    reused/degraded/salvaged, failed lanes, wall), not a throughput
    record. With ``--forensics`` the line carries a ``forensics`` key:
    the structured per-lane failure report of
    :func:`pycatkin_tpu.robustness.sweep_failure_report` (quarantined
    lanes, verdict-test breakdown, residuals, ladder history), and the
    human rendering goes to stderr.
    """
    import argparse

    ap = argparse.ArgumentParser(
        prog="bench.py",
        description="journaled chunked volcano sweep / lane forensics")
    ap.add_argument("--journal", default=None,
                    help="journal directory (created if missing)")
    ap.add_argument("--resume", action="store_true",
                    help="replay the journal, re-dispatching only "
                         "unfinished chunks")
    ap.add_argument("--chunk", type=int, default=4096,
                    help="lanes per chunk (default 4096)")
    ap.add_argument("--forensics", action="store_true",
                    help="attach the per-lane failure forensics report "
                         "to the JSON result (runs a plain sweep when "
                         "no --journal is given)")
    args = ap.parse_args(argv)
    if not args.journal and not args.forensics:
        ap.error("need --journal DIR and/or --forensics")
    if args.resume and not args.journal:
        ap.error("--resume requires --journal DIR")

    from pycatkin_tpu.utils.cache import enable_persistent_cache
    enable_persistent_cache()

    import jax

    from pycatkin_tpu.utils import profiling

    dev = jax.devices()[0]
    log(f"device: {dev.platform} ({dev.device_kind})")

    sim, spec, conds, mask, metric, _ = _build_problem()
    profiling.drain_events()        # forensics sees only this run

    # Run-scoped trace: forensics reads the degradation/retry events
    # off THIS run's trace (a fresh trace starts empty, so no stale
    # prewarm events can leak into the report), and --trace exports it.
    from pycatkin_tpu import obs

    if args.journal:
        from pycatkin_tpu.robustness import chunked_sweep_steady_state

        t0 = time.perf_counter()
        with obs.run_trace("journaled chunked sweep") as tr:
            out, report = chunked_sweep_steady_state(
                spec, conds, chunk=args.chunk, tof_mask=mask,
                opts=sim.solver_options(), check_stability=True,
                journal=args.journal, resume=args.resume, verbose=True)
        wall = time.perf_counter() - t0

        n = int(np.asarray(out["success"]).shape[0])
        result = {
            "metric": metric + " (journaled chunked mode)",
            "journal": args.journal,
            "resumed": bool(args.resume),
            "chunk": report["chunk"],
            "n_chunks": report["n_chunks"],
            "reused_chunks": report["reused"],
            "degraded_chunks": report["degraded"],
            "salvaged_chunks": report["salvaged"],
            "n_failed_lanes": report["n_failed_lanes"],
            "converged": int(np.sum(np.asarray(out["success"]))),
            "n_points": n,
            "wall_s": round(wall, 2),
        }
        events = list(report.get("events", []))
    else:
        from pycatkin_tpu.parallel.batch import sweep_steady_state

        t0 = time.perf_counter()
        with obs.run_trace("forensics sweep") as tr:
            out = sweep_steady_state(spec, conds, tof_mask=mask,
                                     opts=sim.solver_options(),
                                     check_stability=True)
        n_ok = int(np.sum(np.asarray(out["success"])))
        wall = time.perf_counter() - t0

        n = int(np.asarray(out["success"]).shape[0])
        result = {
            "metric": metric + " (forensics mode)",
            "converged": n_ok,
            "n_points": n,
            "wall_s": round(wall, 2),
        }
        events = []

    _write_trace("journal" if args.journal else "forensics", tr)

    if args.forensics:
        from pycatkin_tpu.robustness import (format_failure_report,
                                             sweep_failure_report)
        # Ladder/retry/quarantine events recorded during THIS run that
        # a chunked report does not already carry (read off the run's
        # own trace; the manifest rides inside the forensics report).
        events = events + [ev for ev in tr.drain()
                           if ev.get("kind") in ("degradation", "retry")]
        forensics = sweep_failure_report(out, conds=conds, events=events)
        result["forensics"] = forensics
        log(format_failure_report(forensics))

    print(json.dumps(result))


def _prior_round_value():
    """Throughput recorded by the most recent checked-in BENCH_r*.json
    (the driver writes one per round), or None."""
    import glob
    import re
    here = os.path.dirname(os.path.abspath(__file__))
    best = None
    for path in glob.glob(os.path.join(here, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if not m:
            continue
        try:
            with open(path) as f:
                parsed = json.load(f).get("parsed") or {}
            val = parsed.get("value")
        except (OSError, ValueError):
            continue
        if val is not None:
            key = int(m.group(1))
            if best is None or key > best[0]:
                best = (key, float(val))
    return best[1] if best else None


if __name__ == "__main__":
    # No arguments: the historical timing benchmark, exactly one JSON
    # line. --smoke is the CI canary; --linalg the direction-kernel
    # microbench lane; --transient the fused dense-output lane; any
    # other argument switches to the journaled chunked mode. --trace
    # DIR composes with every mode (stripped here so the routing below
    # never sees it).
    TRACE_DIR = _strip_trace_arg(sys.argv)
    if len(sys.argv) > 1 and sys.argv[1] == "--smoke":
        sys.exit(smoke_main())
    elif len(sys.argv) > 1 and sys.argv[1] == "--linalg":
        sys.exit(linalg_main(sys.argv[1:]))
    elif len(sys.argv) > 1 and sys.argv[1] == "--transient":
        sys.exit(transient_main(sys.argv[1:]))
    elif len(sys.argv) > 1:
        journal_main(sys.argv[1:])
    else:
        main()
