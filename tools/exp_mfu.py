"""Round-5 MFU / roofline measurement for docs/perf_mfu.md.

For bench configs 4 (COOx volcano 256x256, n_dyn=4) and 5 (synthetic
200x500, n_dyn=190): run the exact fast-pass solver program, read the
per-lane iteration counts, and divide the fenced wall by the union
iteration count (a vmapped while_loop executes the union of all lanes'
work, so wall ~= max_iters x per-iteration kernel time). Combined with
the analytic per-iteration FLOP/byte model (printed here from the spec
shapes) and tools/exp_roofline.py's measured ceilings, this pins where
each config sits on the roofline.

Run on the TPU:  python tools/exp_mfu.py
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from pycatkin_tpu.utils.cache import enable_persistent_cache  # noqa: E402

enable_persistent_cache()

import numpy as np  # noqa: E402


def flops_per_iteration(n_s, n_r, n_dyn, n_reac_cols, chords=0):
    """Analytic logical-f64 FLOPs per PTC body per lane.

    residual eval: fwd+rev flux products (~2*n_r*n_reac_cols mul) +
    2 stoich matvecs (net + gross, 2*2*n_s*n_r) ~= R
    jacobian: n_dyn JVPs ~= n_dyn * R (jacfwd)
    direction solve: Gauss-Jordan ~2*n_dyn^3 (small n) or LU 2/3 n^3 +
    chords * 2*n_dyn^2 triangular solves
    projection/verdict/SER: ~10*n_dyn
    """
    R = 2 * n_r * n_reac_cols + 2 * 2 * n_s * n_r
    jac = n_dyn * R
    solve = 2 * n_dyn ** 3 if n_dyn <= 48 else (2 / 3) * n_dyn ** 3
    chord = chords * (2 * n_dyn ** 2 + R)
    return R + jac + solve + chord + 10 * n_dyn


def fenced(prog, *args):
    import jax.numpy as jnp
    t0 = time.perf_counter()
    out = prog(*args)
    float(np.asarray(jnp.sum(out.residual) + jnp.sum(out.iterations)))
    return time.perf_counter() - t0, out


def main():
    import jax
    import jax.numpy as jnp

    import pycatkin_tpu as pk
    from pycatkin_tpu import engine
    from pycatkin_tpu.models import coox
    from pycatkin_tpu.models.synthetic import synthetic_system
    from pycatkin_tpu.parallel import batch
    from pycatkin_tpu.parallel.batch import (_fast_pass_opts,
                                             _steady_program,
                                             broadcast_conditions)
    from pycatkin_tpu.solvers.newton import SolverOptions

    results = {}

    # ---- config 4: COOx volcano fast pass at 256x256 ----
    sim = pk.read_from_input_file(
        "/root/reference/examples/COOxVolcano/input.json")
    spec = sim.spec
    be = np.linspace(-2.5, 0.5, 256)
    conds, _ = coox.volcano_grid_conditions(sim, be)
    conds = jax.tree_util.tree_map(jnp.asarray, conds)
    n = 256 * 256
    keys = jax.random.split(jax.random.PRNGKey(0), n)
    prog = _steady_program(spec, _fast_pass_opts(SolverOptions()))
    fenced(prog, conds, keys, None)              # warm
    walls = []
    for i in range(3):
        w, out = fenced(prog, conds._replace(T=conds.T + 1e-7 * (i + 1)),
                        keys, None)
        walls.append(w)
    wall = sorted(walls)[1]
    iters = np.asarray(out.iterations)
    it_max, it_mean = int(iters.max()), float(iters.mean())
    n_s, n_r, n_dyn = len(spec.snames), len(spec.rnames), \
        len(spec.dynamic_indices)
    fl = flops_per_iteration(n_s, n_r, n_dyn, spec.reac_idx.shape[1])
    results["config4"] = {
        "lanes": n, "n_s": n_s, "n_r": n_r, "n_dyn": n_dyn,
        "fast_pass_wall_s": round(wall, 3),
        "iters_max": it_max, "iters_mean": round(it_mean, 1),
        "per_iter_ms": round(wall / it_max * 1e3, 2),
        "flops_per_iter_lane": round(fl),
        "logical_f64_flops_total": round(fl * float(iters.sum())),
        "achieved_logical_f64_flops": round(fl * float(iters.sum())
                                            / wall),
        # union-of-lanes accounting: the vmapped while_loop executes
        # it_max iterations for EVERY lane (finished lanes masked)
        "union_f64_flops": round(fl * it_max * n),
        "achieved_union_f64_flops": round(fl * it_max * n / wall),
    }
    print(f"[4] wall {wall:.3f} s, iters max {it_max} mean {it_mean:.1f}, "
          f"per-union-iter {wall/it_max*1e3:.1f} ms, "
          f"{fl:.0f} flop/iter/lane -> "
          f"{fl*it_max*n/wall/1e9:.2f} Gflop64/s (union)",
          file=sys.stderr)

    # carry state HBM traffic per union iteration: x, F, dt, fnorm, k
    # (f64 = 2xf32 pairs, 16 B per logical value) read+written, plus
    # J assembly scratch.
    carry_vals = n * (2 * n_dyn + n_s + 3)
    bytes_per_iter = 2 * 16 * carry_vals
    results["config4"]["approx_carry_GBps"] = round(
        bytes_per_iter * it_max / wall / 1e9, 2)

    # ---- config 5: synthetic 200x500 with chord pacing at 128 lanes --
    sim5 = synthetic_system(n_species=200, n_reactions=500, seed=0)
    spec5 = sim5.spec
    n5 = 128
    opts5 = SolverOptions(dt0=100.0, dt_grow_min=30.0, chord_steps=4)
    Ts = np.linspace(420.0, 700.0, 8)
    ps = np.logspace(4.0, 6.0, 4)
    dEs = np.linspace(-0.15, 0.15, 4)
    TT, PP, EE = np.meshgrid(Ts, ps, dEs, indexing="ij")
    base = sim5.conditions()
    eps = np.zeros((n5, len(spec5.snames)))
    eps[:, spec5.is_adsorbate.astype(bool)] = EE.ravel()[:, None]
    conds5 = broadcast_conditions(base, n5)._replace(
        T=jnp.asarray(TT.ravel()), p=jnp.asarray(PP.ravel()),
        eps=jnp.asarray(eps))
    keys5 = jax.random.split(jax.random.PRNGKey(0), n5)
    prog5 = _steady_program(spec5, _fast_pass_opts(opts5))
    fenced(prog5, conds5, keys5, None)           # warm
    walls5 = []
    for i in range(3):
        w, out5 = fenced(prog5,
                         conds5._replace(T=conds5.T + 1e-7 * (i + 1)),
                         keys5, None)
        walls5.append(w)
    wall5 = sorted(walls5)[1]
    iters5 = np.asarray(out5.iterations)
    it5_max, it5_mean = int(iters5.max()), float(iters5.mean())
    n_s5, n_r5, n_dyn5 = len(spec5.snames), len(spec5.rnames), \
        len(spec5.dynamic_indices)
    fl5 = flops_per_iteration(n_s5, n_r5, n_dyn5,
                              spec5.reac_idx.shape[1], chords=4)
    results["config5"] = {
        "lanes": n5, "n_s": n_s5, "n_r": n_r5, "n_dyn": n_dyn5,
        "fast_pass_wall_s": round(wall5, 3),
        "iters_max": it5_max, "iters_mean": round(it5_mean, 1),
        "per_iter_ms": round(wall5 / it5_max * 1e3, 2),
        "flops_per_iter_lane": round(fl5),
        "union_f64_flops": round(fl5 * it5_max * n5),
        "achieved_union_f64_flops": round(fl5 * it5_max * n5 / wall5),
    }
    print(f"[5] wall {wall5:.3f} s, iters max {it5_max} mean "
          f"{it5_mean:.1f}, per-union-iter {wall5/it5_max*1e3:.1f} ms, "
          f"{fl5/1e6:.2f} Mflop/iter/lane -> "
          f"{fl5*it5_max*n5/wall5/1e9:.2f} Gflop64/s (union)",
          file=sys.stderr)

    print(json.dumps(results))


if __name__ == "__main__":
    main()
