#!/usr/bin/env python
"""Legacy shim: the host-sync lint now lives in the pclint framework.

The check itself is rule ``PCL001`` (:mod:`pycatkin_tpu.lint.host_sync`)
run by ``tools/pclint.py`` / ``make lint``; the hot-path function list
moved to the shared registry :mod:`pycatkin_tpu.lint.hotpath` (one
list, consumed by the checker AND tests/test_sync_budget.py). This
shim keeps the historical entry point (``make lint-syncs`` calls
pclint directly; running this file still works) and the historical
module API (``TARGET``/``HOT_FUNCTIONS``/``collect_syncs``) that the
shim's tests repoint.

Vs. the pre-pclint script, the migrated checker also fixes two
misses: a ``# sync-ok:`` annotation now matches on ANY line of a
multi-line call, and scalar pulls hiding in keyword arguments are
caught (the old ``_is_scalar_pull`` only inspected ``args[0]``).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from pycatkin_tpu.lint import host_sync as _impl          # noqa: E402
from pycatkin_tpu.lint import hotpath as _hotpath         # noqa: E402

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TARGET = os.path.join(ROOT, "pycatkin_tpu", "parallel", "batch.py")

HOT_FUNCTIONS = set(_hotpath.HOT_FUNCTIONS)
ANNOTATION = _hotpath.SYNC_ANNOTATION


def collect_syncs(path: str = None):
    """(lineno, source_line) of every raw materialization inside a hot
    function that lacks a ``# sync-ok:`` annotation. Delegates to the
    PCL001 checker; module globals are looked up at call time so tests
    can repoint TARGET/HOT_FUNCTIONS."""
    return _impl.collect_syncs(TARGET if path is None else path,
                               hot_functions=HOT_FUNCTIONS)


def main(argv=None) -> int:
    flagged = collect_syncs(TARGET)
    rel = os.path.relpath(TARGET, ROOT)
    if flagged:
        print(f"lint_host_syncs: {len(flagged)} uncounted host "
              f"materialization(s) in {rel} hot-path functions -- route "
              f"them through utils.profiling.host_sync or annotate the "
              f"line with '{ANNOTATION} <reason>':")
        for lineno, src in flagged:
            print(f"  {rel}:{lineno}: {src}")
        return 1
    print(f"lint_host_syncs: OK -- no uncounted materializations in "
          f"{rel} hot path ({', '.join(sorted(HOT_FUNCTIONS))}) "
          f"[delegated to pclint PCL001]")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
