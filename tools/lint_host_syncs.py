#!/usr/bin/env python
"""Static check: no uncounted host syncs on the sweep hot path.

The sweep's latency budget is measured in blocking device->host
materializations (on the tunneled axon backend each one costs ~0.8-1.2 s
of round trip regardless of payload; docs/index.md "Performance").
Every intentional materialization in the hot path must flow through
``utils.profiling.host_sync`` -- the counted choke point that
tests/test_sync_budget.py holds to a contractual budget -- or carry an
explicit ``# sync-ok: <reason>`` annotation on its line marking it as a
reviewed failure-path transfer.

This tool parses ``pycatkin_tpu/parallel/batch.py`` with the ``ast``
module and flags, inside the HOT_FUNCTIONS only, the two raw
materialization idioms that history shows creep in during refactors:

- ``np.asarray(...)``  (blocking copy of a device array)
- ``int(jnp....)`` / ``float(jnp....)``  (scalar pull of a device value)

Calls inside nested helper functions of a hot function count too (the
closure runs on the hot path). Exit 0 when every such call is either
routed through ``host_sync`` or annotated; 1 otherwise, listing file,
line and source line for each miss.

Run directly or via ``make lint-syncs``.
"""

from __future__ import annotations

import ast
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TARGET = os.path.join(ROOT, "pycatkin_tpu", "parallel", "batch.py")

# The sweep hot path: functions a clean (zero-failure) sweep executes,
# plus the failure-path functions whose syncs must stay labeled.
HOT_FUNCTIONS = {"batch_steady_state", "sweep_steady_state",
                 "_finish_sweep", "_rescue", "_quarantine_mask",
                 "stability_mask", "continuation_sweep"}

ANNOTATION = "# sync-ok:"


def _is_np_asarray(node: ast.Call) -> bool:
    f = node.func
    return (isinstance(f, ast.Attribute) and f.attr == "asarray"
            and isinstance(f.value, ast.Name) and f.value.id == "np")


def _is_scalar_pull(node: ast.Call) -> bool:
    """int(...)/float(...) whose argument expression mentions jnp --
    a device scalar pulled to the host."""
    f = node.func
    if not (isinstance(f, ast.Name) and f.id in ("int", "float")):
        return False
    if not node.args:
        return False
    arg = node.args[0]
    # int(host_sync(...)) IS the counted idiom, not a bypass.
    if (isinstance(arg, ast.Call) and isinstance(arg.func, ast.Name)
            and arg.func.id == "host_sync"):
        return False
    for sub in ast.walk(node.args[0]):
        if isinstance(sub, ast.Name) and sub.id == "jnp":
            return True
        if isinstance(sub, ast.Call):
            sf = sub.func
            if (isinstance(sf, ast.Attribute)
                    and isinstance(sf.value, ast.Name)
                    and sf.value.id == "jnp"):
                return True
    return False


def collect_syncs(path: str = TARGET):
    """(lineno, source_line) of every raw materialization inside a hot
    function that lacks a ``# sync-ok:`` annotation."""
    with open(path) as fh:
        source = fh.read()
    lines = source.splitlines()
    tree = ast.parse(source, filename=path)
    flagged = []
    for top in tree.body:
        if not isinstance(top, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if top.name not in HOT_FUNCTIONS:
            continue
        for node in ast.walk(top):
            if not isinstance(node, ast.Call):
                continue
            if not (_is_np_asarray(node) or _is_scalar_pull(node)):
                continue
            src = lines[node.lineno - 1]
            if ANNOTATION in src:
                continue
            flagged.append((node.lineno, src.strip()))
    return sorted(set(flagged))


def main(argv=None) -> int:
    # Globals looked up at call time so tests can repoint TARGET.
    flagged = collect_syncs(TARGET)
    rel = os.path.relpath(TARGET, ROOT)
    if flagged:
        print(f"lint_host_syncs: {len(flagged)} uncounted host "
              f"materialization(s) in {rel} hot-path functions -- route "
              f"them through utils.profiling.host_sync or annotate the "
              f"line with '{ANNOTATION} <reason>':")
        for lineno, src in flagged:
            print(f"  {rel}:{lineno}: {src}")
        return 1
    print(f"lint_host_syncs: OK -- no uncounted materializations in "
          f"{rel} hot path ({', '.join(sorted(HOT_FUNCTIONS))})")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
