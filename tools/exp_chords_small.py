"""Do chord steps pay on SMALL systems? (config 1/3/4 shapes)

Measures, with the honest chained/scalar fences:
  - CH4 single-solve marginal device latency (config-1 method)
  - DMTM 81-T sweep wall (config-3 method)
  - COOx volcano 64x64 subgrid wall (config-4 method, smaller grid to
    keep the experiment short)
for SolverOptions() vs chord1 vs chord2 at default pacing.

Run: python tools/exp_chords_small.py [ch4|dmtm|volcano]
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

from pycatkin_tpu.utils.cache import enable_persistent_cache

enable_persistent_cache()

import jax
import jax.numpy as jnp

import pycatkin_tpu as pk
from pycatkin_tpu import engine
from pycatkin_tpu.parallel.batch import (broadcast_conditions,
                                         sweep_steady_state)
from pycatkin_tpu.solvers.newton import SolverOptions

REF = "/root/reference"
VARIANTS = [("default", SolverOptions()),
            ("chord1", SolverOptions(chord_steps=1)),
            ("chord2", SolverOptions(chord_steps=2))]


def ch4():
    sim = pk.read_from_input_file(os.path.join(REF, "test",
                                               "CH4_input.json"))
    spec, cond = sim.spec, sim.conditions()
    print(f"CH4 n_dyn={len(spec.dynamic_indices)}", flush=True)
    for tag, opts in VARIANTS:
        def chain(c, n):
            def body(carry, _):
                T, _x = carry
                res = engine.steady_state(spec, c._replace(T=T),
                                          opts=opts)
                return (T + res.x[0] * 1e-12 + 1e-9, res.x), res.success
            (_, x_last), succ = jax.lax.scan(
                body, (c.T, jnp.zeros(len(spec.snames))), None, length=n)
            return jnp.sum(x_last) + jnp.sum(succ), succ
        c1 = jax.jit(lambda c: chain(c, 1))
        c25 = jax.jit(lambda c: chain(c, 25))
        np.asarray(c1(cond._replace(T=cond.T + 0.3))[0])
        np.asarray(c25(cond._replace(T=cond.T + 0.4))[0])
        rng = np.random.default_rng(4)
        vals, ok = [], True
        for _ in range(3):
            cT = cond._replace(T=cond.T + rng.uniform(0, .01))
            t0 = time.perf_counter()
            f, s1 = c1(cT)
            float(np.asarray(f))
            w1 = time.perf_counter() - t0
            t0 = time.perf_counter()
            f, s25 = c25(cT)
            float(np.asarray(f))
            w25 = time.perf_counter() - t0
            vals.append((w25 - w1) / 24.0)
            ok = ok and bool(np.all(np.asarray(s25)))
        res = engine.steady_state(spec, cond._replace(T=cond.T + 1e-9),
                                  opts=opts)
        print(f"CH4 {tag:8s} marginal {sorted(vals)[1]*1e3:7.2f} ms "
              f"(min {min(vals)*1e3:.2f}) all_ok={ok} "
              f"iters={int(res.iterations)}", flush=True)


def dmtm():
    sim = pk.read_from_input_file(os.path.join(REF, "examples", "DMTM",
                                               "input.json"))
    spec = sim.spec
    n_T = 81
    Ts = np.linspace(400.0, 800.0, n_T)
    conds = broadcast_conditions(sim.conditions(), n_T)._replace(T=Ts)
    conds = jax.tree_util.tree_map(jnp.asarray, conds)
    mask = engine.tof_mask_for(spec, ["r5", "r9"])
    from bench import result_fence
    fence = result_fence()
    for tag, opts in VARIANTS:
        warm = sweep_steady_state(spec, conds._replace(T=conds.T + .25),
                                  tof_mask=mask, opts=opts)
        np.asarray(fence(warm["y"], warm["activity"], warm["success"]))
        walls, out = [], None
        for i in range(3):
            c_i = conds._replace(T=conds.T + 1e-7 * (i + 1))
            t0 = time.perf_counter()
            out = sweep_steady_state(spec, c_i, tof_mask=mask, opts=opts)
            float(np.asarray(fence(out["y"], out["activity"],
                                   out["success"])))
            walls.append(time.perf_counter() - t0)
        n_ok = int(np.sum(np.asarray(out["success"])))
        print(f"DMTM {tag:8s} {n_T/sorted(walls)[1]:7.1f} T/s "
              f"(walls {['%.3f' % w for w in walls]}) ok {n_ok}/{n_T}",
              flush=True)


def volcano():
    from pycatkin_tpu.models import coox
    sim = pk.read_from_input_file(
        os.path.join(REF, "examples", "COOxVolcano", "input.json"))
    be = np.linspace(-2.5, 0.5, 64)
    conds, shape = coox.volcano_grid_conditions(sim, be)
    conds = jax.tree_util.tree_map(jnp.asarray, conds)
    mask = engine.tof_mask_for(sim.spec, ["CO_ox"])
    n = 64 * 64
    from bench import result_fence
    fence = result_fence()
    for tag, opts in VARIANTS:
        warm = sweep_steady_state(sim.spec,
                                  conds._replace(T=conds.T + .25),
                                  tof_mask=mask, opts=opts,
                                  check_stability=True)
        np.asarray(fence(warm["y"], warm["activity"], warm["success"]))
        walls, out = [], None
        for i in range(3):
            c_i = conds._replace(T=conds.T + 1e-7 * (i + 1))
            t0 = time.perf_counter()
            out = sweep_steady_state(sim.spec, c_i, tof_mask=mask,
                                     opts=opts, check_stability=True)
            float(np.asarray(fence(out["y"], out["activity"],
                                   out["success"])))
            walls.append(time.perf_counter() - t0)
        n_ok = int(np.sum(np.asarray(out["success"])))
        print(f"volcano64 {tag:8s} {n/sorted(walls)[1]:8.0f} pts/s "
              f"(walls {['%.3f' % w for w in walls]}) ok {n_ok}/{n}",
              flush=True)


if __name__ == "__main__":
    which = sys.argv[1:] or ["ch4", "dmtm", "volcano"]
    for w in which:
        {"ch4": ch4, "dmtm": dmtm, "volcano": volcano}[w]()
