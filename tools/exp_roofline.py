"""Round-5 roofline microbenchmarks for docs/perf_mfu.md.

Measures this chip's practical ceilings for the operation classes the
steady-state solver actually spends time in. Every measurement chains
K dependent iterations of the kernel inside ONE jitted fori_loop (loop
carries force one kernel pass per iteration -- no cross-iteration
fusion) so device time dwarfs the ~0.1 s tunnel round trip, then
fences through a scalar materialization.

  1. bf16 / f32 / emulated-f64 batched matmul (MXU + the Jacobian/LU
     arithmetic class) at the config-5 shape [128, 190, 190]
  2. emulated-f64 elementwise exp (the rate-constant class)
  3. emulated-f64 / f32 elementwise fma chain (the PTC update class)
  4. HBM streaming bandwidth (elementwise scale pass over f64)

Run on the TPU:  python tools/exp_roofline.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from pycatkin_tpu.utils.cache import enable_persistent_cache  # noqa: E402

enable_persistent_cache()

import numpy as np  # noqa: E402


def timed_loop(body, x0, k, trials=3):
    """Median fenced wall of ONE program running `body` k times in a
    fori_loop (data-dependent carry)."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def prog(x):
        y = jax.lax.fori_loop(0, k, lambda i, y: body(y), x)
        return jnp.sum(y.astype(jnp.float32))

    float(np.asarray(prog(x0)))              # compile + warm
    walls = []
    for i in range(trials):
        x = x0 + np.float32(1e-6 * (i + 1)).astype(x0.dtype)
        t0 = time.perf_counter()
        float(np.asarray(prog(x)))
        walls.append(time.perf_counter() - t0)
    return sorted(walls)[1]


def main():
    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    print(f"device: {dev.platform} ({dev.device_kind})", file=sys.stderr)

    results = {}

    # batched matmul [B, n, n] @ [B, n, n] -- config-5 Jacobian scale
    B, n = 128, 190
    flops = 2 * B * n * n * n
    for dtype, name, k in ((jnp.bfloat16, "bf16", 2048),
                           (jnp.float32, "f32", 512),
                           (jnp.float64, "f64emu", 64)):
        A = jnp.asarray(np.random.default_rng(0).normal(size=(B, n, n)),
                        dtype=dtype)
        Bm = jnp.asarray(
            np.random.default_rng(1).normal(size=(B, n, n)) / n,
            dtype=dtype)
        w = timed_loop(lambda y, Bm=Bm: y @ Bm, A, k) / k
        results[f"matmul_{name}"] = flops / w
        print(f"matmul[{B},{n},{n}] {name}: {w*1e3:9.3f} ms/iter  "
              f"{flops/w/1e12:8.3f} Tflop/s", file=sys.stderr)

    # elementwise exp, f64 emulation (rate constants / equilibrium)
    N = 1 << 24
    x = jnp.asarray(np.random.default_rng(2).uniform(-1, 1, N),
                    dtype=jnp.float64)
    w = timed_loop(lambda y: jnp.exp(y * 0.5) - 1.0, x, 32) / 32
    results["exp_f64emu"] = N / w
    print(f"exp f64emu [{N}]: {w*1e3:9.3f} ms/iter  "
          f"{N/w/1e9:6.2f} Gexp/s", file=sys.stderr)

    # elementwise fma chain (PTC update arithmetic): 16 dependent fmas
    # per loop iteration
    k_in = 16

    def fma_body(y):
        for _ in range(k_in):
            y = y * 1.0000001 + 1e-9
        return y

    for dtype, name in ((jnp.float64, "f64emu"), (jnp.float32, "f32")):
        xd = x.astype(dtype)
        w = timed_loop(fma_body, xd, 64) / 64
        results[f"fma_{name}"] = 2 * k_in * N / w
        print(f"fma-chain {name} [{N}x{k_in}]: {w*1e3:9.3f} ms/iter  "
              f"{2*k_in*N/w/1e9:6.2f} Gflop/s", file=sys.stderr)

    # HBM streaming: one multiply pass over f64 = read+write 2x16 B per
    # logical element (f64 emulation stores hi/lo f32 pairs... the jax
    # x64 array on this backend is 8 B storage; count 8 B in + 8 B out)
    w = timed_loop(lambda y: y * 1.0000001, x, 256) / 256
    bytes_moved = 2 * 8 * N
    results["hbm_stream"] = bytes_moved / w
    print(f"f64 stream [{N}]: {w*1e3:9.3f} ms/iter  "
          f"{bytes_moved/w/1e9:6.1f} GB/s", file=sys.stderr)

    import json
    print(json.dumps({k: float(v) for k, v in results.items()}))


if __name__ == "__main__":
    main()
