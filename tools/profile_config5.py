"""Decomposition profile of bench config 5 (synthetic 200x500 sweep).

Answers the round-2 verdict's question: WHERE does the 200x500 batched
steady solve spend its time? Times each component of one PTC iteration
at the exact benchmark shape (128 lanes, n_dyn=190), reports iteration
counts from the real sweep, and reconciles component times against the
measured end-to-end wall time. Run on the benchmark device:

    python tools/profile_config5.py

Results of a run are committed in docs/perf_config5.md.

CAVEAT (round-5): this script fences with ``jax.block_until_ready``,
which does NOT synchronize on the tunneled axon backend -- its
RELATIVE component comparisons on a co-located host remain valid (and
its committed conclusions were re-derived through honest fences in
docs/perf_config5.md §9-10), but for absolute walls on the tunneled
device use ``pycatkin_tpu.utils.profiling.run_timed`` instead.
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

from pycatkin_tpu.utils.cache import enable_persistent_cache

enable_persistent_cache()

import jax
import jax.numpy as jnp

from pycatkin_tpu import engine
from pycatkin_tpu.models.synthetic import synthetic_system
from pycatkin_tpu.ops import linalg
from pycatkin_tpu.parallel.batch import (broadcast_conditions,
                                         sweep_steady_state)


def timeit(fn, *args, reps=5):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps


def main():
    dev = jax.devices()[0]
    print(f"device: {dev.platform} ({dev.device_kind})")

    sim = synthetic_system(n_species=200, n_reactions=500, seed=0)
    spec = sim.spec
    dyn = np.asarray(spec.dynamic_indices)
    n_dyn = len(dyn)
    print(f"n_dyn={n_dyn}, n_reactions={spec.n_reactions}")

    Ts = np.linspace(420.0, 700.0, 8)
    ps = np.logspace(4.0, 6.0, 4)
    dEs = np.linspace(-0.15, 0.15, 4)
    TT, PP, EE = np.meshgrid(Ts, ps, dEs, indexing="ij")
    n = TT.size
    base = sim.conditions()
    eps = np.zeros((n, len(spec.snames)))
    eps[:, spec.is_adsorbate.astype(bool)] = EE.ravel()[:, None]
    conds = broadcast_conditions(base, n)._replace(
        T=TT.ravel(), p=PP.ravel(), eps=eps)
    mask = engine.tof_mask_for(spec, [spec.rnames[-1]])

    # ------------------------------------------------------------------
    # end-to-end sweep (the benchmark measurement) + iteration counts
    warm = sweep_steady_state(spec, conds._replace(T=conds.T + 0.25),
                              tof_mask=mask)
    jax.block_until_ready(warm["y"])
    t0 = time.perf_counter()
    out = sweep_steady_state(spec, conds, tof_mask=mask)
    jax.block_until_ready(out["y"])
    total_s = time.perf_counter() - t0
    iters = np.asarray(out["iterations"])
    atts = np.asarray(out["attempts"])
    print(f"\nend-to-end sweep: {total_s:.3f} s for {n} lanes "
          f"({n/total_s:.1f} lanes/s), "
          f"{int(np.sum(np.asarray(out['success'])))}/{n} converged")
    print(f"iterations: max={iters.max()} mean={iters.mean():.1f} "
          f"p50={np.percentile(iters, 50):.0f} "
          f"p90={np.percentile(iters, 90):.0f}")
    print(f"attempts:   max={atts.max()} mean={atts.mean():.2f}")

    # ------------------------------------------------------------------
    # component timings at the same batched shape
    x0 = jnp.asarray(np.asarray(conds.y0)[:, dyn])

    def jac_one(cond, x):
        kf, kr, _ = engine.rate_constants(spec, cond)
        fscale, _, _ = engine._dynamic_fscale(spec, cond, kf, kr)
        return jax.jacfwd(lambda z: fscale(z)[0])(x)

    def eval_one(cond, x):
        kf, kr, _ = engine.rate_constants(spec, cond)
        fscale, _, _ = engine._dynamic_fscale(spec, cond, kf, kr)
        return fscale(x)

    def rates_one(cond):
        return engine.rate_constants(spec, cond)[0]

    jac_b = jax.jit(jax.vmap(jac_one))
    eval_b = jax.jit(jax.vmap(eval_one))
    rates_b = jax.jit(jax.vmap(rates_one))

    t_jac = timeit(jac_b, conds, x0)
    t_eval = timeit(eval_b, conds, x0)
    t_rates = timeit(rates_b, conds)
    print(f"\nper-iteration components (batched over {n} lanes):")
    print(f"  jacfwd Jacobian [{n}x{n_dyn}x{n_dyn}]: {t_jac*1e3:8.2f} ms")
    print(f"  residual+scale eval:                 {t_eval*1e3:8.2f} ms")
    print(f"  rate constants (per solve, once):    {t_rates*1e3:8.2f} ms")

    A = jnp.asarray(np.random.default_rng(0).standard_normal(
        (n, n_dyn, n_dyn)) + 10.0 * np.eye(n_dyn))
    b = jnp.asarray(np.random.default_rng(1).standard_normal((n, n_dyn)))
    solve_b = jax.jit(jax.vmap(linalg.solve))
    t_solve = timeit(solve_b, A, b)
    print(f"  linalg.solve [{n}x{n_dyn}x{n_dyn}]:        {t_solve*1e3:8.2f} ms")

    lu_b = jax.jit(jax.vmap(lambda M: linalg.lu_factor(M)[0]))
    t_lu = timeit(lu_b, A)
    print(f"    of which lu_factor:                {t_lu*1e3:8.2f} ms")

    # reconcile: the PTC body does 1 jacfwd + 1 solve + 1 eval per step.
    per_iter = t_jac + t_solve + t_eval
    # SIMD: every lane steps until the LAST lane converges (per pass);
    # the first pass is capped at 100 steps.
    est = per_iter * iters.max()
    print(f"\nreconciliation: (jac+solve+eval) = {per_iter*1e3:.2f} ms/iter; "
          f"x max-iters {iters.max()} = {est:.3f} s "
          f"vs measured {total_s:.3f} s")
    print(f"LU share of one iteration: {t_solve/per_iter*100:.0f}% solve, "
          f"{t_jac/per_iter*100:.0f}% jacobian, "
          f"{t_eval/per_iter*100:.0f}% eval")


if __name__ == "__main__":
    main()
