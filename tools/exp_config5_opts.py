"""Sweep SolverOptions variants on the config-5 workload (TPU).

For each variant: lanes/s (scalar-fenced, fresh inputs), iteration
stats, convergence. Run: python tools/exp_config5_opts.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

from pycatkin_tpu.utils.cache import enable_persistent_cache

enable_persistent_cache()

import jax
import jax.numpy as jnp

from pycatkin_tpu import engine
from pycatkin_tpu.models.synthetic import synthetic_system
from pycatkin_tpu.parallel.batch import (broadcast_conditions,
                                         sweep_steady_state)
from pycatkin_tpu.solvers.newton import SolverOptions


def main():
    dev = jax.devices()[0]
    print(f"device: {dev.platform} ({dev.device_kind})", flush=True)

    sim = synthetic_system(n_species=200, n_reactions=500, seed=0)
    spec = sim.spec
    Ts = np.linspace(420.0, 700.0, 8)
    ps = np.logspace(4.0, 6.0, 4)
    dEs = np.linspace(-0.15, 0.15, 4)
    TT, PP, EE = np.meshgrid(Ts, ps, dEs, indexing="ij")
    n = TT.size
    base = sim.conditions()
    eps = np.zeros((n, len(spec.snames)))
    eps[:, spec.is_adsorbate.astype(bool)] = EE.ravel()[:, None]
    conds = broadcast_conditions(base, n)._replace(
        T=TT.ravel(), p=PP.ravel(), eps=eps)
    conds = jax.tree_util.tree_map(jnp.asarray, conds)
    mask = engine.tof_mask_for(spec, [spec.rnames[-1]])

    from bench import result_fence
    fence = result_fence()

    variants = [
        ("c4 g30 dt0=10",   SolverOptions(dt0=10.0, dt_grow_min=30.0,
                                           chord_steps=4)),
        ("c3 g30 dt0=1",    SolverOptions(dt0=1.0, dt_grow_min=30.0,
                                           chord_steps=3)),
        ("c5 g30 dt0=1",    SolverOptions(dt0=1.0, dt_grow_min=30.0,
                                           chord_steps=5)),
        ("c4 g30 dt0=100",  SolverOptions(dt0=100.0, dt_grow_min=30.0,
                                           chord_steps=4)),
    ]
    for tag, opts in variants:
        t0 = time.perf_counter()
        warm = sweep_steady_state(spec, conds._replace(T=conds.T + 0.25),
                                  tof_mask=mask, opts=opts)
        np.asarray(fence(warm["y"], warm["activity"], warm["success"]))
        compile_s = time.perf_counter() - t0
        walls, out = [], None
        for i in range(3):
            c_i = conds._replace(T=conds.T + 1.0e-7 * (i + 1))
            t0 = time.perf_counter()
            out = sweep_steady_state(spec, c_i, tof_mask=mask, opts=opts)
            float(np.asarray(fence(out["y"], out["activity"],
                                   out["success"])))
            walls.append(time.perf_counter() - t0)
        w = sorted(walls)[1]
        iters = np.asarray(out["iterations"])
        n_ok = int(np.sum(np.asarray(out["success"])))
        print(f"{tag:18s} {n/w:6.1f} lanes/s "
              f"(walls {['%.2f' % x for x in walls]}) "
              f"iters mean {iters.mean():.1f} max {iters.max()} "
              f"ok {n_ok}/{n} compile {compile_s:.0f}s", flush=True)


if __name__ == "__main__":
    main()
