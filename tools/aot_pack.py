#!/usr/bin/env python
"""Ship a warm AOT executable cache between machines/processes.

The prewarm wall (BENCH_r05: 136.6 s cold) is almost entirely XLA
compilation; the compiled executables are already serialized on disk
(`parallel/compile_pool.AOTCache`). This tool archives that directory
into a single shippable pack and re-imports it elsewhere, so a fleet of
workers -- or the bench after a checkout wipe -- pays the compile wall
once. Import verifies the aot-key-v2 format, the manifest<->entry spec
fingerprints, and counts (but keeps) entries from a foreign toolchain,
which `AOTCache.load` later treats as silent misses.

Usage::

    python tools/aot_pack.py export PACK [--cache-root DIR]
    python tools/aot_pack.py import PACK [--cache-root DIR] [--no-verify]
    python tools/aot_pack.py selftest          # CI round-trip gate

`selftest` proves the whole promise end-to-end on a synthetic
mechanism: prewarm into a fresh cache, export, import into a second
fresh directory, prewarm again from the pack (asserting ZERO compiles
-- everything loads), and check the pack-warmed sweep's outputs are
bit-identical to the freshly-compiled sweep's. Exit 0 iff all holds.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _cmd_export(args) -> int:
    from pycatkin_tpu.parallel import compile_pool

    stats = compile_pool.export_cache_pack(args.pack,
                                           cache_root=args.cache_root)
    print(json.dumps(stats, indent=2))
    return 0


def _cmd_import(args) -> int:
    from pycatkin_tpu.parallel import compile_pool

    stats = compile_pool.import_cache_pack(args.pack,
                                           cache_root=args.cache_root,
                                           verify=not args.no_verify)
    print(json.dumps(stats, indent=2))
    return 0


def _cmd_selftest(args) -> int:
    import tarfile
    import tempfile

    import numpy as np

    from pycatkin_tpu import engine
    from pycatkin_tpu.models.synthetic import synthetic_system
    from pycatkin_tpu.parallel import compile_pool
    from pycatkin_tpu.parallel.batch import (broadcast_conditions,
                                             clear_program_caches,
                                             prewarm_sweep_programs,
                                             sweep_steady_state)
    from pycatkin_tpu.san import trace_ident

    # Armed for the whole round trip (pckey): every prewarmed program
    # is jaxpr-fingerprinted, the exported manifest must carry those
    # fingerprints, and the import replays them -- a key bound to two
    # distinct traces anywhere in the loop raises TraceIdentSanError.
    trace_ident.reset()
    trace_ident.activate()

    sim = synthetic_system(n_species=16, n_reactions=24, seed=3)
    spec = sim.spec
    n = 32
    conds = broadcast_conditions(sim.conditions(), n)
    conds = conds._replace(T=np.linspace(420.0, 780.0, n))
    mask = engine.tof_mask_for(spec, [spec.rnames[-1]])
    fp = compile_pool.spec_fingerprint(spec)
    layout = dict(buckets=(8,), check_stability=True)

    def sweep():
        return sweep_steady_state(spec, conds, tof_mask=mask,
                                  check_stability=True)

    with tempfile.TemporaryDirectory() as tmp:
        root_a = os.path.join(tmp, "a")
        root_b = os.path.join(tmp, "b")
        pack = os.path.join(tmp, "cache.aotpack.tgz")

        stats_a = prewarm_sweep_programs(
            spec, conds, tof_mask=mask,
            cache=compile_pool.AOTCache(root=root_a, fingerprint=fp),
            **layout)
        ref = sweep()

        exported = compile_pool.export_cache_pack(pack, cache_root=root_a)
        print(f"selftest: exported {exported['entries']} entries "
              f"({exported['bytes']} bytes)")
        with tarfile.open(pack, "r:gz") as tar:
            manifest = json.load(
                tar.extractfile(compile_pool.PACK_MANIFEST))
        unfingerprinted = [
            k for k, m in manifest["entries"].items()
            if not m.get("trace_ident")
            or m["trace_ident"] != trace_ident.fingerprint_for(k)]
        if unfingerprinted:
            print("selftest: FAIL -- pack entries missing (or "
                  "contradicting) their jaxpr fingerprint: "
                  f"{unfingerprinted}")
            return 1
        imported = compile_pool.import_cache_pack(pack, cache_root=root_b)
        if imported["imported"] != exported["entries"]:
            print("selftest: FAIL -- import lost entries "
                  f"({imported['imported']} != {exported['entries']})")
            return 1

        clear_program_caches()
        stats_b = prewarm_sweep_programs(
            spec, conds, tof_mask=mask,
            cache=compile_pool.AOTCache(root=root_b, fingerprint=fp),
            **layout)
        if stats_b.compiled != 0 or stats_b.loaded != int(stats_a):
            print("selftest: FAIL -- pack-warmed prewarm recompiled "
                  f"(compiled={stats_b.compiled}, loaded={stats_b.loaded}"
                  f", expected loaded={int(stats_a)})")
            return 1
        out = sweep()

        bad = [k for k in sorted(ref)
               if np.asarray(ref[k]).tobytes()
               != np.asarray(out[k]).tobytes()]
        if bad:
            print(f"selftest: FAIL -- pack-warmed sweep differs on {bad}")
            return 1
    print(f"selftest: OK -- {exported['entries']} entries round-tripped, "
          f"{stats_b.loaded} loaded / 0 compiled from pack, sweep "
          "bit-identical")
    return _selftest_abi_cross_mechanism()


def _selftest_abi_cross_mechanism() -> int:
    """Phase 2: the ABI promise. With PYCATKIN_ABI=1 cache entries are
    keyed on the shape BUCKET, so a pack exported after warming
    mechanism A must warm a DIFFERENT mechanism B in the same bucket
    with zero compiles, and the manifest must record each entry's
    abi_version + bucket shape."""
    import tarfile
    import tempfile

    import numpy as np

    from pycatkin_tpu import engine
    from pycatkin_tpu.frontend import abi
    from pycatkin_tpu.models.synthetic import synthetic_system
    from pycatkin_tpu.parallel import compile_pool
    from pycatkin_tpu.parallel.batch import (broadcast_conditions,
                                             clear_program_caches,
                                             prewarm_sweep_programs,
                                             sweep_steady_state)

    def problem(n_species, seed):
        sim = synthetic_system(n_species=n_species, n_reactions=24,
                               seed=seed)
        spec = sim.spec
        conds = broadcast_conditions(sim.conditions(), 32)
        conds = conds._replace(T=np.linspace(420.0, 780.0, 32))
        mask = engine.tof_mask_for(spec, [spec.rnames[-1]])
        return spec, conds, mask

    prev = os.environ.get(abi.ABI_ENV)
    os.environ[abi.ABI_ENV] = "1"
    try:
        clear_program_caches()
        sA, cA, mA = problem(16, seed=3)
        sB, cB, mB = problem(17, seed=7)   # same bucket, different mech
        fpA = compile_pool.spec_fingerprint(abi.lower_spec(sA))
        fpB = compile_pool.spec_fingerprint(abi.lower_spec(sB))
        if fpA != fpB:
            print(f"selftest: FAIL -- A/B land in different buckets "
                  f"({fpA} vs {fpB})")
            return 1
        layout = dict(buckets=(8,), check_stability=True)

        with tempfile.TemporaryDirectory() as tmp:
            root_a = os.path.join(tmp, "a")
            root_b = os.path.join(tmp, "b")
            pack = os.path.join(tmp, "abi.aotpack.tgz")
            stats_a = prewarm_sweep_programs(
                sA, cA, tof_mask=mA,
                cache=compile_pool.AOTCache(root=root_a, fingerprint=fpA),
                **layout)
            exported = compile_pool.export_cache_pack(pack,
                                                      cache_root=root_a)
            with tarfile.open(pack, "r:gz") as tar:
                manifest = json.load(
                    tar.extractfile(compile_pool.PACK_MANIFEST))
            missing = [k for k, m in manifest["entries"].items()
                       if m.get("abi_version") != abi.ABI_VERSION
                       or not m.get("abi_bucket")]
            if missing:
                print("selftest: FAIL -- pack entries missing "
                      f"abi_version/abi_bucket metadata: {missing}")
                return 1
            compile_pool.import_cache_pack(pack, cache_root=root_b)

            clear_program_caches()
            stats_b = prewarm_sweep_programs(
                sB, cB, tof_mask=mB,
                cache=compile_pool.AOTCache(root=root_b, fingerprint=fpB),
                **layout)
            if stats_b.compiled != 0 or stats_b.loaded != int(stats_a):
                print("selftest: FAIL -- mechanism B recompiled from "
                      f"mechanism A's pack (compiled={stats_b.compiled}, "
                      f"loaded={stats_b.loaded}, expected "
                      f"loaded={int(stats_a)})")
                return 1
            out = sweep_steady_state(sB, cB, tof_mask=mB,
                                     check_stability=True)
            if not bool(np.all(np.asarray(out["success"]))):
                print("selftest: FAIL -- pack-warmed cross-mechanism "
                      "sweep did not converge")
                return 1
    finally:
        if prev is None:
            os.environ.pop(abi.ABI_ENV, None)
        else:
            os.environ[abi.ABI_ENV] = prev
        clear_program_caches()
    print(f"selftest: OK -- ABI cross-mechanism: {exported['entries']} "
          f"bucket-keyed entries from mechanism A warmed mechanism B "
          f"({stats_b.loaded} loaded / 0 compiled), sweep converged")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="aot_pack.py",
        description="Export/import shippable AOT executable cache packs")
    sub = ap.add_subparsers(dest="cmd", required=True)
    exp = sub.add_parser("export", help="archive a warm cache directory")
    exp.add_argument("pack", help="output pack path (tar.gz)")
    exp.add_argument("--cache-root", default=None,
                     help="cache dir (default: PYCATKIN_AOT_CACHE)")
    exp.set_defaults(fn=_cmd_export)
    imp = sub.add_parser("import", help="unpack a pack into a cache dir")
    imp.add_argument("pack", help="pack path")
    imp.add_argument("--cache-root", default=None)
    imp.add_argument("--no-verify", action="store_true",
                     help="skip per-entry verification")
    imp.set_defaults(fn=_cmd_import)
    st = sub.add_parser("selftest",
                        help="prewarm -> export -> import -> bit-identity")
    st.set_defaults(fn=_cmd_selftest)
    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
