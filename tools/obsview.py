#!/usr/bin/env python
"""obsview: summarize a pycatkin Chrome trace file.

Renders the span tree of a trace written by ``bench.py --trace DIR``
(or any :func:`pycatkin_tpu.obs.write_chrome_trace` output) as an
indented table with per-span total/self times, a per-label summary,
and the top-N slowest spans. All analysis lives in
:mod:`pycatkin_tpu.obs.export` so bench.py's outlier attribution and
this CLI can never disagree.

Usage::

    python tools/obsview.py RUN.trace.json [--top N]
    python tools/obsview.py --lanes SWEEP.json
    python tools/obsview.py --workers WORKDIR/events.jsonl
    python tools/obsview.py --selftest [--sweep]

``--lanes`` renders the per-lane solver telemetry heatmap (iteration /
chord / residual-decade / rescue-strategy, one glyph per lane) from any
JSON file carrying a packed ``lane_telemetry`` array -- a bench record
or a dumped sweep output.

``--workers`` renders the elastic scheduler's lease/restart timeline
from a work directory's ``events.jsonl`` (or any JSON file carrying an
``events`` list): every spawn, crash, restart, expired/stolen lease,
bisection and quarantine in chronological order.

``--selftest`` is the ``make obs-check`` CI lane: it round-trips a
programmatic trace through the Chrome exporter, verifies parenting,
sync-label fidelity and outlier attribution, and lints the Prometheus
exposition of a populated metrics registry. With ``--sweep`` it
additionally runs a tiny synthetic sweep (8 lanes, CPU-friendly) under
a run trace and asserts the exported trace carries the counted sync
labels -- including the fused path's ``fused tail bundle``.
"""

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _fail(msg: str) -> int:
    print(f"obsview: FAIL -- {msg}", file=sys.stderr, flush=True)
    return 1


def selftest(sweep: bool = False) -> int:
    from pycatkin_tpu.obs import (attribute_outlier, format_span_table,
                                  load_trace, run_manifest, run_trace,
                                  span_summary, span_tree,
                                  write_chrome_trace)
    from pycatkin_tpu.obs.metrics import (MetricsRegistry,
                                          validate_prometheus_text)
    from pycatkin_tpu.utils import profiling

    # 1. Trace round trip: nested spans + a counted "sync" under a
    #    run-scoped trace, exported and re-parsed.
    with run_trace("obsview-selftest") as tr:
        with profiling.span("outer"):
            with profiling.span("inner"):
                profiling.host_sync([1.0, 2.0], "selftest sync")
    with tempfile.TemporaryDirectory(prefix="obsview_") as tmp:
        path = os.path.join(tmp, "selftest.trace.json")
        write_chrome_trace(path, tr)
        obj = load_trace(path)
    names = [ev.get("name") for ev in obj["traceEvents"]]
    if "outer" not in names or "inner" not in names:
        return _fail("exported trace lost its spans")
    if "selftest sync" not in names:
        return _fail("exported trace lost its counted sync label")
    if obj["otherData"]["sync_labels"] != ["selftest sync"]:
        return _fail("trace metadata sync labels drifted")
    roots = span_tree(tr.peek("span"))
    if (len(roots) != 1 or roots[0]["label"] != "outer"
            or [c["label"] for c in roots[0]["children"]] != ["inner"]):
        return _fail("span tree parenting broken")
    if not span_summary(obj["traceEvents"]):
        return _fail("span summary empty for a trace with spans")
    print(format_span_table(obj["traceEvents"], top=3))

    # 2. Outlier attribution (the bench.py variance gate).
    out = attribute_outlier(
        [{"a": 1.0, "b": 0.1}, {"a": 1.0, "b": 0.1},
         {"a": 1.0, "b": 2.1}],
        [1.1, 1.1, 3.1])
    if not out or out["label"] != "b":
        return _fail(f"outlier attribution wrong: {out}")

    # 3. Prometheus exposition lint on a populated scratch registry.
    reg = MetricsRegistry()
    reg.counter("obsview_selftest_total", "selftest counter").inc(  # pclint: disable=PCL009 -- scratch-registry selftest fixture, never exported to production /metrics
        3, kind="demo")
    reg.gauge("obsview_selftest_gauge").set(1.5)  # pclint: disable=PCL009 -- scratch-registry selftest fixture, never exported to production /metrics
    h = reg.histogram("obsview_selftest_seconds", "selftest histogram")  # pclint: disable=PCL009 -- scratch-registry selftest fixture, never exported to production /metrics
    for v in (0.004, 0.2, 7.0):
        h.observe(v)
    problems = validate_prometheus_text(reg.prometheus_text())
    if problems:
        return _fail("prometheus exposition invalid: "
                     + "; ".join(problems))

    # ... and on the LIVE process registry (host_sync above fed it).
    from pycatkin_tpu.obs import metrics as live_metrics
    problems = validate_prometheus_text(live_metrics.prometheus_text())
    if problems:
        return _fail("live prometheus exposition invalid: "
                     + "; ".join(problems))

    # 4. Manifest sanity.
    man = run_manifest()
    if man.get("schema") != "pycatkin-run-manifest/v1":
        return _fail(f"manifest schema drifted: {man.get('schema')}")

    # 5. Lane telemetry heatmap on synthetic packed rows.
    from pycatkin_tpu.obs import format_lane_heatmap, lane_summary
    tel = [[4, 0, -10, 0, 1], [9, 3, -8, 2, 0], [30, 6, -3, 6, 0],
           [5, 0, -11, 0, 1]]
    s = lane_summary(tel)
    if (s["lanes"] != 4 or s["strategies"].get("quarantine") != 1
            or s["iterations"]["max"] != 30
            or s["tiers"].get("f32-polish") != 2):
        return _fail(f"lane summary wrong: {s}")
    heat = format_lane_heatmap(tel, width=2)
    if ".t" not in heat or "#." not in heat:
        return _fail(f"lane heatmap glyphs wrong:\n{heat}")
    print(heat)

    # 5b. Tenant-grouped heatmap (packed multi-tenant sweeps).
    from pycatkin_tpu.obs import (format_tenant_heatmaps,
                                  tenant_lane_summaries)
    tenants = [tel, [[3, 0, -9, 0, 0], [4, 1, -8, 1, 0]]]
    per = tenant_lane_summaries(tenants)
    if len(per) != 2 or per[0]["lanes"] != 4 or per[1]["lanes"] != 2:
        return _fail(f"tenant lane summaries wrong: {per}")
    theat = format_tenant_heatmaps(tenants, width=2)
    if "tenant 0" not in theat or "tenant 1" not in theat:
        return _fail(f"tenant heatmap grouping wrong:\n{theat}")
    print(theat)

    # 6. Worker lifecycle timeline on scripted scheduler events.
    from pycatkin_tpu.obs import format_worker_timeline, worker_summary
    wev = [
        {"kind": "worker", "action": "spawn", "label": "worker:0",
         "t": 100.0, "pid": 11, "incarnation": 0},
        {"kind": "worker", "action": "exit", "label": "worker:0",
         "t": 102.5, "returncode": -9, "exit_kind": "signal-death"},
        {"kind": "worker", "action": "restart", "label": "worker:0",
         "t": 102.5, "attempt": 1, "delay_s": 0.3},
        {"kind": "worker", "action": "lease-stolen",
         "label": "lease:t00000_00004", "t": 103.0, "owner": "w1-12",
         "stolen_from": "w0-11"},
        {"kind": "worker", "action": "pack-flush",
         "label": "abi-v1:s16:r16:d8:rt0:none", "t": 104.0, "tenants": 3,
         "k_bucket": 4, "pack_occupancy": 0.75, "lanes": 8,
         "tenant_quarantined": [0, 2, 0]},
        {"kind": "span", "label": "not-a-worker-event", "dur": 1.0},
    ]
    ws = worker_summary(wev)
    if ws["n_events"] != 5 or ws["restarts"].get("worker:0") != 1:
        return _fail(f"worker summary wrong: {ws}")
    if (ws.get("packs") != 1 or ws.get("pack_tenants") != 3
            or ws.get("tenant_quarantined", {}).get(
                "abi-v1:s16:r16:d8:rt0:none[1]") != 2):
        return _fail(f"pack-flush aggregation wrong: {ws}")
    timeline = format_worker_timeline(wev)
    if ("lease-stolen" not in timeline or "signal-death" not in timeline
            or "2.500s" not in timeline
            or "tenant_quarantined=[0, 2, 0]" not in timeline):
        return _fail(f"worker timeline rendering wrong:\n{timeline}")
    print(timeline)

    if sweep:
        # 7. A real (tiny, CPU-friendly) sweep under a run trace: the
        #    exported trace must reproduce the counted sync labels --
        #    on the fused clean path that is exactly one, the packed
        #    "fused tail bundle".
        from pycatkin_tpu.models.synthetic import synthetic_system
        from pycatkin_tpu.parallel.batch import (broadcast_conditions,
                                                 sweep_steady_state)
        sim = synthetic_system(n_species=16, n_reactions=24)
        conds = broadcast_conditions(sim.conditions(), 8)
        with run_trace("obsview-sweep") as tr2:
            with profiling.sync_budget() as budget:
                out = sweep_steady_state(sim.spec, conds)
        lane_tel = out.get("lane_telemetry")
        if lane_tel is None or len(lane_tel) != 8:
            return _fail("sweep output lost its per-lane telemetry")
        print(format_lane_heatmap(lane_tel))
        with tempfile.TemporaryDirectory(prefix="obsview_") as tmp:
            path = os.path.join(tmp, "sweep.trace.json")
            write_chrome_trace(path, tr2)
            obj = load_trace(path)
        sync_names = [ev["name"] for ev in obj["traceEvents"]
                      if ev.get("cat") == "sync"]
        if sync_names != budget.labels:
            return _fail(f"sweep trace sync labels {sync_names} != "
                         f"budget labels {budget.labels}")
        snap = live_metrics.snapshot()
        lanes = snap["counters"].get("pycatkin_lanes_solved_total", {})
        if sum(lanes.values()) < 8:
            return _fail("lanes-solved counter did not observe the "
                         "sweep")
        print(f"obsview: sweep trace OK -- {len(obj['traceEvents'])} "
              f"events, syncs {sync_names}")

    print("obsview: selftest OK")
    return 0


def _find_lane_telemetry(obj, key="lane_telemetry"):
    """Depth-first hunt for a telemetry array in a JSON object (bench
    records nest the sweep output; BENCH_r*.json wraps it again under
    'parsed'). ``key="tenant_lane_telemetry"`` finds a packed sweep's
    per-tenant list instead."""
    if isinstance(obj, dict):
        tel = obj.get(key)
        if tel is not None:
            return tel
        for v in obj.values():
            tel = _find_lane_telemetry(v, key)
            if tel is not None:
                return tel
    return None


def workers_view(path: str) -> int:
    from pycatkin_tpu.obs import format_worker_timeline, worker_summary
    try:
        if path.endswith(".jsonl"):
            from pycatkin_tpu.utils.io import read_json_lines
            events = read_json_lines(path)
        else:
            with open(path, encoding="utf-8") as fh:
                obj = json.load(fh)
            events = (obj.get("events", obj)
                      if isinstance(obj, dict) else obj)
    except (OSError, ValueError) as e:
        return _fail(str(e))
    if not isinstance(events, list):
        return _fail(f"{path}: no event list found")
    print(format_worker_timeline(events))
    ws = worker_summary([e for e in events if isinstance(e, dict)])
    if ws.get("packs"):
        print(f"packed flushes: {ws['packs']} "
              f"({ws['pack_tenants']} tenant sweeps)")
        tq = ws.get("tenant_quarantined") or {}
        if tq:
            print("per-tenant quarantined lanes:")
            for key in sorted(tq):
                print(f"  {key}: {tq[key]}")
        else:
            print("per-tenant quarantined lanes: none")
    if not any(e.get("kind") == "worker" for e in events
               if isinstance(e, dict)):
        return _fail(f"{path}: no worker lifecycle events in the file")
    return 0


def lanes_view(path: str) -> int:
    from pycatkin_tpu.obs import (format_lane_heatmap,
                                  format_tenant_heatmaps)
    try:
        with open(path, encoding="utf-8") as fh:
            obj = json.load(fh)
    except (OSError, ValueError) as e:
        return _fail(str(e))
    # A packed multi-tenant record renders one heatmap block per
    # tenant; a solo record keeps the flat heatmap.
    tenants = _find_lane_telemetry(obj, key="tenant_lane_telemetry")
    if tenants is not None:
        try:
            print(format_tenant_heatmaps(tenants))
        except (TypeError, ValueError) as e:
            return _fail(f"{path}: malformed tenant telemetry ({e})")
        return 0
    tel = _find_lane_telemetry(obj)
    if tel is None:
        return _fail(f"{path}: no 'lane_telemetry' (or "
                     f"'tenant_lane_telemetry') array anywhere in the "
                     f"JSON")
    try:
        print(format_lane_heatmap(tel))
    except (TypeError, ValueError) as e:
        return _fail(f"{path}: malformed lane telemetry ({e})")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="obsview.py",
        description="span-tree summary of a pycatkin Chrome trace")
    ap.add_argument("trace", nargs="?", help="trace JSON file")
    ap.add_argument("--top", type=int, default=10,
                    help="slowest-span count in the summary tail")
    ap.add_argument("--lanes", metavar="JSON",
                    help="render the per-lane telemetry heatmap from "
                         "a JSON file carrying 'lane_telemetry'")
    ap.add_argument("--workers", metavar="EVENTS",
                    help="render the elastic worker lease/restart "
                         "timeline from an events.jsonl (or a JSON "
                         "file with an 'events' list)")
    ap.add_argument("--selftest", action="store_true",
                    help="run the obs-check self-test instead of "
                         "reading a trace")
    ap.add_argument("--sweep", action="store_true",
                    help="with --selftest: also trace a tiny real "
                         "sweep (compiles a small program)")
    args = ap.parse_args(argv)

    if args.selftest:
        return selftest(sweep=args.sweep)
    if args.lanes:
        return lanes_view(args.lanes)
    if args.workers:
        return workers_view(args.workers)
    if not args.trace:
        ap.error("need a trace file (or --lanes / --selftest)")

    from pycatkin_tpu.obs import format_span_table, load_trace
    try:
        obj = load_trace(args.trace)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        return _fail(str(e))
    meta = obj.get("otherData", {})
    if meta:
        print(f"trace: {meta.get('trace_name')} "
              f"(id {meta.get('trace_id')}), "
              f"{meta.get('sync_count')} counted sync(s): "
              f"{meta.get('sync_labels')}")
    print(format_span_table(obj["traceEvents"], top=args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
