#!/usr/bin/env python
"""pclint runner: the repo's unified static-analysis gate.

Thin launcher for :mod:`pycatkin_tpu.lint` (the checker framework);
``make lint`` runs this with no arguments and must exit 0 on a clean
tree. Rules, suppression syntax (inline ``# pclint: disable=<rule> --
<reason>`` and the committed ``lint_baseline.json``), and the baseline
workflow are documented in docs/static_analysis.md.

Examples::

    python tools/pclint.py                      # everything
    python tools/pclint.py --rules PCL001       # host-sync only
    python tools/pclint.py --format sarif       # CI annotations
    python tools/pclint.py --update-baseline    # re-grandfather
    python tools/pclint.py --list-rules
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from pycatkin_tpu.lint.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
