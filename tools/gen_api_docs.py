"""Generate docs/api/*.md from the package's own docstrings+signatures.

The per-module API reference (parity with the reference's sphinx-autodoc
tree, /root/reference/docs/api/) is rendered to plain markdown so it
reads on any host (GitHub, editors) without a doc build. Re-run after
changing public surfaces:

    python tools/gen_api_docs.py
"""

import importlib
import inspect
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["PALLAS_AXON_POOL_IPS"] = ""

OUT = os.path.join(os.path.dirname(__file__), "..", "docs", "api")

# One page per module group, mirroring the package layout.
PAGES = {
    "constants": ["pycatkin_tpu.constants"],
    "frontend": ["pycatkin_tpu.frontend.states",
                 "pycatkin_tpu.frontend.reactions",
                 "pycatkin_tpu.frontend.parsers",
                 "pycatkin_tpu.frontend.loader",
                 "pycatkin_tpu.frontend.spec"],
    "ops": ["pycatkin_tpu.ops.thermo", "pycatkin_tpu.ops.rates",
            "pycatkin_tpu.ops.network", "pycatkin_tpu.ops.linalg"],
    "solvers": ["pycatkin_tpu.solvers.newton", "pycatkin_tpu.solvers.ode"],
    "engine": ["pycatkin_tpu.engine"],
    "api": ["pycatkin_tpu.api.system", "pycatkin_tpu.api.presets",
            "pycatkin_tpu.api.plotting"],
    "parallel": ["pycatkin_tpu.parallel.batch"],
    "analysis": ["pycatkin_tpu.analysis.energy_span",
                 "pycatkin_tpu.analysis.grid",
                 "pycatkin_tpu.analysis.uncertainty"],
    "models": ["pycatkin_tpu.models.reactor", "pycatkin_tpu.models.coox",
               "pycatkin_tpu.models.synthetic"],
    "utils": ["pycatkin_tpu.utils.io", "pycatkin_tpu.utils.profiling",
              "pycatkin_tpu.utils.cache"],
}


def _sig(obj):
    try:
        return str(inspect.signature(obj))
    except (ValueError, TypeError):
        return "(...)"


def _doc(obj, indent=""):
    doc = inspect.getdoc(obj)
    if not doc:
        return ""
    return "\n".join(indent + line for line in doc.splitlines())


def _is_namedtuple(cls):
    return issubclass(cls, tuple) and hasattr(cls, "_fields")


def render_module(modname):
    mod = importlib.import_module(modname)
    lines = [f"## `{modname}`", ""]
    mdoc = inspect.getdoc(mod)
    if mdoc:
        lines += [mdoc, ""]

    members = vars(mod)
    classes = [(n, o) for n, o in members.items()
               if inspect.isclass(o) and o.__module__ == modname
               and not n.startswith("_")]
    funcs = [(n, o) for n, o in members.items()
             if inspect.isfunction(o) and o.__module__ == modname
             and not n.startswith("_")]
    consts = [(n, o) for n, o in members.items()
              if isinstance(o, (int, float)) and not n.startswith("_")
              and not isinstance(o, bool)]
    if consts:
        lines += ["| constant | value |", "|---|---|"]
        lines += [f"| `{n}` | `{v!r}` |" for n, v in consts]
        lines += [""]

    for name, cls in classes:
        if _is_namedtuple(cls):
            lines += [f"### class `{name}`", ""]
            d = _doc(cls)
            if d:
                lines += [d, ""]
            lines += ["Fields: " + ", ".join(
                f"`{f}`" for f in cls._fields), ""]
            continue
        lines += [f"### class `{name}{_sig(cls)}`", ""]
        d = _doc(cls)
        if d:
            lines += [d, ""]
        methods = [(mn, mo) for mn, mo in vars(cls).items()
                   if inspect.isfunction(mo) and not mn.startswith("_")]
        props = [(mn, mo) for mn, mo in vars(cls).items()
                 if isinstance(mo, property) and not mn.startswith("_")]
        for mn, mo in methods:
            lines += [f"#### `{name}.{mn}{_sig(mo)}`", ""]
            d = _doc(mo)
            if d:
                lines += [d, ""]
        if props:
            lines += ["Properties: " + ", ".join(
                f"`{mn}`" for mn, _ in props), ""]

    for name, fn in funcs:
        lines += [f"### `{name}{_sig(fn)}`", ""]
        d = _doc(fn)
        if d:
            lines += [d, ""]
    return "\n".join(lines)


def main():
    os.makedirs(OUT, exist_ok=True)
    index = ["# API reference", "",
             "Generated from the package's docstrings by "
             "`tools/gen_api_docs.py`; regenerate after public-surface "
             "changes. Units and conventions: see "
             "[the docs index](../index.md#units-and-conventions).", ""]
    for page, modules in PAGES.items():
        body = ["# `" + page + "`", ""]
        for modname in modules:
            body.append(render_module(modname))
            body.append("")
        path = os.path.join(OUT, f"{page}.md")
        with open(path, "w") as fh:
            fh.write("\n".join(body))
        mods = ", ".join(f"`{m.split('pycatkin_tpu.')[-1]}`"
                         for m in modules)
        index.append(f"- [{page}]({page}.md) — {mods}")
        print(f"wrote {path}")
    with open(os.path.join(OUT, "index.md"), "w") as fh:
        fh.write("\n".join(index) + "\n")
    print("wrote docs/api/index.md")


if __name__ == "__main__":
    main()
