"""Experiment: chunked first-pass for the 256x256 volcano program.

Hypothesis (docs/perf_config5.md §6): XLA compile time has a
lane-dependent component (64 lanes: 23 s, 65536: 52 s), so jitting the
fast pass at chunk shape [8192] and host-looping 8 dispatches should
cut cold compile ~2x. Throughput may even improve: each chunk's
while_loop runs to its OWN max-iteration lane instead of the global
worst lane.

Run: python tools/exp_chunked_volcano.py

Durable mode: ``--journal DIR [--chunk N] [--resume]`` runs the grid
through the journaled, degradation-tolerant chunked runner
(pycatkin_tpu.robustness) instead of the timing experiment -- a killed
run restarted with ``--resume`` re-dispatches only unfinished chunks
(docs/failure_model.md).
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

from pycatkin_tpu.utils.cache import enable_persistent_cache

enable_persistent_cache()

import jax
import jax.numpy as jnp

import pycatkin_tpu as pk
from pycatkin_tpu import engine
from pycatkin_tpu.models import coox
from pycatkin_tpu.parallel import batch as pb

GRID_N = 256


def run_variant(spec, conds, mask, fence, chunk):
    n = GRID_N * GRID_N
    tag = f"chunk={chunk or 'full'}"
    t0 = time.perf_counter()
    out = sweep(spec, conds._replace(T=conds.T + 0.25), mask, chunk)
    np.asarray(fence(out["y"], out["activity"], out["success"]))
    compile_s = time.perf_counter() - t0
    walls = []
    for i in range(3):
        c_i = conds._replace(T=conds.T + 1.0e-7 * (i + 1))
        t0 = time.perf_counter()
        out = sweep(spec, c_i, mask, chunk)
        float(np.asarray(fence(out["y"], out["activity"],
                               out["success"])))
        walls.append(time.perf_counter() - t0)
    w = sorted(walls)[1]
    n_ok = int(np.sum(np.asarray(out["success"])))
    print(f"{tag:12s} compile+first {compile_s:6.1f} s; "
          f"walls {['%.2f' % x for x in walls]} -> {n/w:8.0f} pts/s; "
          f"ok {n_ok}/{n}", flush=True)


def sweep(spec, conds, mask, chunk):
    from pycatkin_tpu.solvers.newton import SolverOptions
    opts = SolverOptions()
    if not chunk:
        return pb.sweep_steady_state(spec, conds, tof_mask=mask)
    # chunked fast pass, shared finish tail
    fast = opts._replace(max_steps=100, max_attempts=1)
    n = jax.tree_util.tree_leaves(conds)[0].shape[0]
    keys = jax.random.split(jax.random.PRNGKey(0), n)
    prog = pb._steady_program(spec, fast)
    outs = []
    for i0 in range(0, n, chunk):
        sub = jax.tree_util.tree_map(lambda a: a[i0:i0 + chunk], conds)
        outs.append(prog(sub, keys[i0:i0 + chunk], None))
    res = jax.tree_util.tree_map(
        lambda *xs: jnp.concatenate(xs, axis=0), *outs)
    return pb._finish_sweep(spec, conds, res, opts, mask, False, 1e-2)


def journal_main(argv):
    """Journaled chunked sweep with checkpoint/resume (--journal mode);
    uses bench._build_problem so it also runs without the reference
    tree (synthetic fallback)."""
    import argparse
    import json

    ap = argparse.ArgumentParser(prog="exp_chunked_volcano.py")
    ap.add_argument("--journal", required=True)
    ap.add_argument("--resume", action="store_true")
    ap.add_argument("--chunk", type=int, default=8192)
    args = ap.parse_args(argv)

    from bench import _build_problem
    from pycatkin_tpu.robustness import chunked_sweep_steady_state

    dev = jax.devices()[0]
    print(f"device: {dev.platform} ({dev.device_kind})", flush=True)
    sim, spec, conds, mask, metric, _ = _build_problem()

    t0 = time.perf_counter()
    out, report = chunked_sweep_steady_state(
        spec, conds, chunk=args.chunk, tof_mask=mask,
        opts=sim.solver_options(), check_stability=True,
        journal=args.journal, resume=args.resume, verbose=True)
    wall = time.perf_counter() - t0
    print(json.dumps({
        "metric": metric + " (journaled chunked mode)",
        "chunk": report["chunk"], "n_chunks": report["n_chunks"],
        "reused_chunks": report["reused"],
        "degraded_chunks": report["degraded"],
        "salvaged_chunks": report["salvaged"],
        "n_failed_lanes": report["n_failed_lanes"],
        "converged": int(np.sum(np.asarray(out["success"]))),
        "wall_s": round(wall, 2)}), flush=True)


def main():
    if any(a.startswith("--journal") for a in sys.argv[1:]):
        journal_main(sys.argv[1:])
        return
    dev = jax.devices()[0]
    print(f"device: {dev.platform} ({dev.device_kind})", flush=True)
    sim = pk.read_from_input_file(
        "/root/reference/examples/COOxVolcano/input.json")
    be = np.linspace(-2.5, 0.5, GRID_N)
    conds, shape = coox.volcano_grid_conditions(sim, be)
    conds = jax.tree_util.tree_map(jnp.asarray, conds)
    mask = engine.tof_mask_for(sim.spec, ["CO_ox"])
    from bench import result_fence
    fence = result_fence()

    which = sys.argv[1:] or ["full", "8192", "16384"]
    for w in which:
        run_variant(sim.spec, conds, mask, fence,
                    None if w == "full" else int(w))


if __name__ == "__main__":
    main()
