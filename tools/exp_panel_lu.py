"""Prototype: fori_loop-based blocked LU with partial pivoting.

Unlike linalg.lu_factor_blocked (fully unrolled -> >10 min compile at
n=190 under f64 emulation), the panel loop here is a lax.fori_loop with
DYNAMIC panel offsets: compile size is one panel body (~B unrolled
column steps), independent of n. Elimination writes stay inside an
[n, B] panel; the trailing update and the cross-panel row swaps are
masked MXU matmuls.

Numerics check vs linalg.lu_factor on CPU, then timing on TPU.
Run: JAX_PLATFORMS=cpu python tools/exp_panel_lu.py          (parity)
     python tools/exp_panel_lu.py time                        (TPU)
"""

import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

from pycatkin_tpu.utils.cache import enable_persistent_cache

enable_persistent_cache()

import jax
import jax.numpy as jnp
from jax import lax

from pycatkin_tpu.ops import linalg


def _unit_lower_solve(L, B):
    b = L.shape[-1]
    y = B
    for r in range(1, b):
        y = y.at[r].add(-(L[r, :r] @ y[:r]))
    return y


def lu_factor_panel(A, block=32, swap_via_matmul=True):
    """Blocked right-looking LU with partial pivoting; panel loop is a
    fori_loop over dynamic offsets. Returns (LU, perm) in lu_factor's
    convention. A is padded to a multiple of ``block`` with an identity
    tail (pad pivots stay put: pad rows are zero in real columns)."""
    n = A.shape[-1]
    m = -(-n // block) * block
    dtype = A.dtype
    if m != n:
        Ap = jnp.zeros((m, m), dtype)
        Ap = Ap.at[:n, :n].set(A)
        Ap = Ap.at[jnp.arange(n, m), jnp.arange(n, m)].set(1.0)
        A = Ap
    idx = jnp.arange(m)
    carange = jnp.arange(block)

    def panel_body(o, state):
        A, perm = state
        k0 = o * block
        P = lax.dynamic_slice(A, (0, k0), (m, block))
        pvec = idx

        for c in range(block):
            j = k0 + c
            col = jnp.abs(P[:, c])
            col = jnp.where(idx < j, -jnp.inf, col)
            p = jnp.argmax(col)
            oh_p = (idx == p).astype(dtype)
            oh_j = (idx == j).astype(dtype)
            row_p = oh_p @ P                        # [B] batched-p read
            row_j = lax.dynamic_slice(P, (j, 0), (1, block))[0]
            P = (P + oh_j[:, None] * (row_p - row_j)[None, :]
                 + oh_p[:, None] * (row_j - row_p)[None, :])
            pj = lax.dynamic_slice(pvec, (j,), (1,))[0]
            pp = jnp.sum(jnp.where(idx == p, pvec, 0))
            pvec = (pvec + (oh_j * (pp - pj)).astype(pvec.dtype)
                    + (oh_p * (pj - pp)).astype(pvec.dtype))
            pivot = row_p[c]
            factors = jnp.where(idx > j, P[:, c] / pivot,
                                jnp.zeros_like(pivot))
            upd = jnp.where(carange > c, row_p, 0.0)
            P = P - factors[:, None] * upd[None, :]
            P = P.at[:, c].set(jnp.where(idx > j, factors, P[:, c]))

        # Net panel permutation applied to the FULL matrix (then panel
        # columns overwritten with the factored panel).
        if swap_via_matmul:
            P_mat = (pvec[:, None] == idx[None, :]).astype(dtype)
            A = P_mat @ A
        else:
            A = A[pvec]
        A = lax.dynamic_update_slice(A, P, (0, k0))
        perm = perm[pvec]

        # Trailing update, static width with column masking:
        # rows k0..k0+B: U12 = L11^{-1} R on trailing columns;
        # rows below:    A -= L21 @ U12.
        cmask = idx >= (k0 + block)
        rmask = idx >= (k0 + block)
        R = lax.dynamic_slice(A, (k0, 0), (block, m))
        L11 = jnp.tril(lax.dynamic_slice(P, (k0, 0), (block, block)), -1)
        U12 = _unit_lower_solve(L11, R)
        R_new = jnp.where(cmask[None, :], U12, R)
        A = lax.dynamic_update_slice(A, R_new, (k0, 0))
        Lfull = jnp.where(rmask[:, None], P, 0.0)
        U12t = jnp.where(cmask[None, :], U12, 0.0)
        A = A - Lfull @ U12t
        return A, perm

    LU, perm = lax.fori_loop(0, m // block, panel_body, (A, idx))
    return LU[:n, :n], perm[:n]


def check_parity():
    rng = np.random.default_rng(0)
    for n in (7, 48, 97, 190):
        # Hard case: rows scaled over many decades.
        A0 = rng.standard_normal((n, n))
        scale = 10.0 ** rng.uniform(-12, 12, size=(n, 1))
        for A in (A0 + 10 * np.eye(n), A0 * scale / np.abs(A0).max(1,
                                                           keepdims=True)):
            A = jnp.asarray(A)
            b = jnp.asarray(rng.standard_normal((n,)))
            LU, perm = jax.jit(partial(lu_factor_panel, block=32))(A)
            x = linalg.lu_solve(LU, perm, b)
            r = float(jnp.max(jnp.abs(A @ x - b)))
            # reconstruction check
            Lm = jnp.tril(LU, -1) + jnp.eye(n)
            Um = jnp.triu(LU)
            recon = float(jnp.max(jnp.abs(Lm @ Um - A[perm])))
            print(f"n={n:4d} residual={r:9.2e} |LU-PA|={recon:9.2e}")
            assert recon < 1e-10 * float(jnp.max(jnp.abs(A))), "parity fail"
    # batched parity at the config-5 shape
    Ab = jnp.asarray(rng.standard_normal((8, 190, 190)) + 10 * np.eye(190))
    bb = jnp.asarray(rng.standard_normal((8, 190)))
    LU, perm = jax.jit(jax.vmap(partial(lu_factor_panel, block=32)))(Ab)
    xs = jax.vmap(linalg.lu_solve)(LU, perm, bb)
    xref = jax.vmap(linalg.solve)(Ab, bb)
    d = float(jnp.max(jnp.abs(xs - xref)))
    print(f"batched vs linalg.solve: max|dx|={d:.2e}")
    assert d < 1e-9
    print("parity OK")


def time_tpu():
    from tools.exp_blocked_lu import chain_time  # noqa
    L, N = 128, 190
    rng = np.random.default_rng(0)
    A = jnp.asarray(rng.standard_normal((L, N, N)) + 10.0 * np.eye(N))
    for blk in (16, 32):
        for via_mm in (True, False):
            f = jax.vmap(partial(lu_factor_panel, block=blk,
                                 swap_via_matmul=via_mm))
            def body(X, f=f):
                LU, perm = f(X)
                return A + 1e-12 * jnp.sum(LU) + 0.0 * X
            t0 = time.perf_counter()
            tag = f"panel LU blk={blk} mm={int(via_mm)}"
            chain_time(body, A, n_hi=4, tag=tag)
            print(f"   (incl. compile wall {time.perf_counter()-t0:.1f} s)",
                  flush=True)


if __name__ == "__main__":
    if "time" in sys.argv[1:]:
        time_tpu()
    else:
        check_parity()
