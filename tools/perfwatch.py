#!/usr/bin/env python
"""perfwatch: the perf-regression sentinel over BENCH round history.

Ingests the ``BENCH_r*.json`` records the driver checks in every round
into a rolling history, computes noise-aware baselines (median +/- MAD
per tracked metric) and flags any metric of the newest round that sits
beyond the noise band in the bad direction -- with dominant-span and
cost-ledger attribution when the records carry forensics. All the math
lives in :mod:`pycatkin_tpu.obs.history`; this is the CLI face.

Usage::

    python tools/perfwatch.py --check [--root DIR] [--mad-k K]
                              [--rel-floor F] [--min-history N]
    python tools/perfwatch.py --selftest

``--check`` is the ``make perfwatch`` / CI lane: exit 1 when the newest
round regressed throughput/MFU/prewarm beyond noise, exit 0 (with a
note) when the history is still too short to call anything a
regression. ``--selftest`` proves the sentinel on deterministic
synthetic history: an injected 2x throughput regression MUST be
flagged, an in-noise wobble MUST NOT.
"""

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _fail(msg: str) -> int:
    print(f"perfwatch: FAIL -- {msg}", file=sys.stderr, flush=True)
    return 1


def _print_findings(findings: list):
    for f in findings:
        arrow = "below" if f["direction"] == "higher" else "above"
        print(f"perfwatch: REGRESSION {f['metric']}: "
              f"{f['value']:.6g} is {arrow} baseline "
              f"{f['median']:.6g} (+/- band {f['band']:.3g}, "
              f"n={f['n_history']}, ratio {f['ratio']})")
        attr = f.get("attribution") or {}
        span = attr.get("dominant_span")
        if span:
            print(f"perfwatch:   dominant span: {span.get('label')} "
                  f"(+{span.get('extra_s')}s)")
        for d in attr.get("cost_ledger_drops", []):
            print(f"perfwatch:   program slowdown: "
                  f"{d.get('label') or d['key']} "
                  f"(mfu ratio {d['ratio']})")


def check(root: str, mad_k: float, rel_floor: float,
          min_history: int) -> int:
    from pycatkin_tpu.obs import history as hist
    entries = hist.load_history(root)
    if len(entries) < min_history + 1:
        print(f"perfwatch: only {len(entries)} round(s) under {root}; "
              f"need {min_history + 1} to judge -- PASS (trivially)")
        return 0
    *past, newest = entries
    findings = hist.flag_regressions(
        past, newest["record"], mad_k=mad_k,
        rel_floor=rel_floor, min_history=min_history)
    base_note = ", ".join(
        f"{m}={v:.6g}" for m, v in sorted(newest["metrics"].items()))
    print(f"perfwatch: round {newest['round']} "
          f"({os.path.basename(newest['path'])}) vs {len(past)} prior "
          f"round(s): {base_note or 'no tracked metrics'}")
    if findings:
        _print_findings(findings)
        return 1
    print("perfwatch: no regression beyond noise -- PASS")
    return 0


def _synthetic_round(i: int, value: float, mfu: float,
                     prewarm: float) -> dict:
    """One BENCH_r*.json body shaped like the driver's check-ins:
    the bench JSON line wrapped under {"parsed": ...}, with a small
    cost-ledger so attribution has something to join."""
    return {"parsed": {
        "bench": "volcano_sweep", "value": value, "unit": "pts/s",
        "prewarm_warm_s": prewarm, "max_over_median": 1.02,
        "cost_ledger": {
            "totals": {"mfu": mfu},
            "programs": {"fused-key": {"label": "fused sweep",
                                       "mfu": mfu}},
        },
    }}


def selftest() -> int:
    from pycatkin_tpu.obs import history as hist

    # 1. Baseline math on a known series (odd and even lengths).
    b = hist.baseline([1.0, 2.0, 3.0, 4.0, 100.0])
    if b["median"] != 3.0 or b["mad"] != 1.0:
        return _fail(f"baseline math wrong: {b}")
    b = hist.baseline([1.0, 3.0])
    if b["median"] != 2.0 or b["n"] != 2:
        return _fail(f"even-length baseline wrong: {b}")

    # 2. Deterministic synthetic history through the real file-ingest
    #    path: 6 rounds of in-noise wobble around 1000 pts/s.
    wobble = [1000.0, 1012.0, 991.0, 1005.0, 997.0, 1008.0]
    with tempfile.TemporaryDirectory(prefix="perfwatch_") as tmp:
        for i, v in enumerate(wobble, start=1):
            body = _synthetic_round(i, v, mfu=0.30 + 0.002 * (i % 3),
                                    prewarm=2.0 + 0.05 * (i % 2))
            with open(os.path.join(tmp, f"BENCH_r{i}.json"), "w",
                      encoding="utf-8") as fh:
                json.dump(body, fh)
        history = hist.load_history(tmp)
    if [e["round"] for e in history] != [1, 2, 3, 4, 5, 6]:
        return _fail("load_history lost or misordered rounds")
    if any("mfu" not in e["metrics"] for e in history):
        return _fail("mfu not extracted from cost_ledger totals")

    # 3. An in-noise candidate must NOT be flagged.
    calm = _synthetic_round(7, 994.0, mfu=0.301, prewarm=2.03)
    findings = hist.flag_regressions(history, calm)
    if findings:
        return _fail(f"in-noise wobble falsely flagged: {findings}")

    # 4. An injected 2x throughput (and MFU) regression MUST be
    #    flagged, and the attribution must name the span and program.
    slow = _synthetic_round(7, 500.0, mfu=0.15, prewarm=2.0)
    slow["parsed"]["outlier"] = {"label": "device sweep",
                                 "extra_s": 0.8}
    findings = hist.flag_regressions(history, slow)
    flagged = {f["metric"] for f in findings}
    if "value" not in flagged or "mfu" not in flagged:
        return _fail(f"injected 2x regression missed: "
                     f"flagged={sorted(flagged)}")
    attr = findings[0]["attribution"]
    if (attr.get("dominant_span", {}).get("label") != "device sweep"
            or not attr.get("cost_ledger_drops")):
        return _fail(f"regression attribution incomplete: {attr}")
    _print_findings(findings)

    # 5. Direction sanity: a lower-is-better metric doubling is bad,
    #    a throughput IMPROVEMENT is not.
    bloated = _synthetic_round(7, 1003.0, mfu=0.30, prewarm=4.5)
    flagged = {f["metric"]
               for f in hist.flag_regressions(history, bloated)}
    if flagged != {"prewarm_warm_s"}:
        return _fail(f"direction handling wrong: {sorted(flagged)}")
    fast = _synthetic_round(7, 2000.0, mfu=0.45, prewarm=2.0)
    if hist.flag_regressions(history, fast):
        return _fail("an improvement was flagged as a regression")

    # 6. Short history must stay silent (min_history gate).
    if hist.flag_regressions(history[:2], slow):
        return _fail("2-round history produced a verdict")

    print("perfwatch: selftest OK")
    return 0


def main(argv=None) -> int:
    from pycatkin_tpu.obs.history import DEFAULT_MAD_K, DEFAULT_REL_FLOOR
    ap = argparse.ArgumentParser(
        prog="perfwatch.py",
        description="noise-aware perf-regression sentinel over "
                    "BENCH_r*.json history")
    ap.add_argument("--check", action="store_true",
                    help="judge the newest round against the prior "
                         "rounds' baseline (CI lane; exit 1 on "
                         "regression)")
    ap.add_argument("--root", default=_REPO_ROOT,
                    help="directory holding BENCH_r*.json "
                         "(default: repo root)")
    ap.add_argument("--mad-k", type=float, default=DEFAULT_MAD_K,
                    help="noise band width in MADs")
    ap.add_argument("--rel-floor", type=float,
                    default=DEFAULT_REL_FLOOR,
                    help="minimum relative change to flag (guards "
                         "dead-quiet histories)")
    ap.add_argument("--min-history", type=int, default=3,
                    help="baseline samples required before judging")
    ap.add_argument("--selftest", action="store_true",
                    help="prove the sentinel on synthetic history "
                         "(CI lane)")
    args = ap.parse_args(argv)

    if args.selftest:
        return selftest()
    if args.check:
        return check(args.root, args.mad_k, args.rel_floor,
                     args.min_history)
    ap.error("need --check or --selftest")


if __name__ == "__main__":
    sys.exit(main())
