"""Round-5 experiment: warm-started CH4 single-solve latency ladder.

VERDICT r4 item 4: the unseeded solve pays a ~43-iteration PTC ramp
(18.5 ms marginal) as the price of landing on the physical root; a
warm-started solve (seeded from a neighboring solution, near-Newton
pacing) should approach scipy's ~2-3 ms. This measures the marginal
device latency of seeded solves at several pacing configurations and
T-step densities, by the chain-differencing method of bench_suite
config 1 (data-dependent chained solves, one scalar fence).

Run on the TPU:  python tools/exp_warm_start.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from pycatkin_tpu.utils.cache import enable_persistent_cache  # noqa: E402

enable_persistent_cache()

import numpy as np  # noqa: E402


def main():
    import jax
    import jax.numpy as jnp

    import pycatkin_tpu as pk
    from pycatkin_tpu import engine
    from pycatkin_tpu.solvers.newton import SolverOptions

    sim = pk.read_from_input_file("/root/reference/test/CH4_input.json")
    spec, cond = sim.spec, sim.conditions()
    dyn = jnp.asarray(spec.dynamic_indices)
    print(f"n_dyn = {len(spec.dynamic_indices)}", file=sys.stderr)

    # Physical root at the base T (untimed): PTC from the start state
    # lands on it (pinned by tests/test_ch4.py).
    base = engine.steady_state(spec, cond)
    assert bool(base.success)
    x_star = jnp.asarray(base.x)[dyn]

    def chain(c, n, opts, dT):
        def body(carry, _):
            T, x = carry
            res = engine.steady_state(spec, c._replace(T=T), x0=x,
                                      opts=opts)
            return (T + dT + res.x[0] * 1e-12, res.x[dyn]), \
                (res.success, res.iterations)
        (_, x_last), (succ, iters) = jax.lax.scan(
            body, (c.T, x_star), None, length=n)
        return jnp.sum(x_last) + jnp.sum(succ), succ, iters

    configs = {
        "default": SolverOptions(),
        "newton": SolverOptions(dt0=1e6, dt_grow_min=30.0,
                                max_steps=60, max_attempts=1),
        "newton+chord2": SolverOptions(dt0=1e6, dt_grow_min=30.0,
                                       max_steps=60, max_attempts=1,
                                       chord_steps=2),
        "dt0=1": SolverOptions(dt0=1.0, dt_grow_min=10.0,
                               max_steps=60, max_attempts=1),
    }
    for dT in (0.01, 1.0, 5.0):
        for name, opts in configs.items():
            c1 = jax.jit(lambda c, o=opts, d=dT: chain(c, 1, o, d))
            c13 = jax.jit(lambda c, o=opts, d=dT: chain(c, 13, o, d))
            # compile untimed
            np.asarray(c1(cond._replace(T=cond.T + 0.3))[0])
            np.asarray(c13(cond._replace(T=cond.T + 0.4))[0])
            rng = np.random.default_rng(0)
            marg, its = [], None
            for _ in range(3):
                cT = cond._replace(T=cond.T + rng.uniform(0, .01))
                t0 = time.perf_counter()
                f, s1, _ = c1(cT)
                float(np.asarray(f))
                w1 = time.perf_counter() - t0
                t0 = time.perf_counter()
                f, s13, it13 = c13(cT)
                float(np.asarray(f))
                w13 = time.perf_counter() - t0
                marg.append((w13 - w1) / 12.0)
                its = it13
                ok = bool(np.all(np.asarray(s13)))
            m = sorted(marg)[1]
            print(f"dT={dT:5.2f} {name:14s}: {m*1e3:7.2f} ms/solve "
                  f"(min {min(marg)*1e3:.2f}, max {max(marg)*1e3:.2f}), "
                  f"iters={np.asarray(its).tolist()} ok={ok}")


if __name__ == "__main__":
    main()
