"""Convert reference-PyCatKin pickles into this framework's JSON schema.

The reference persists every class as a pickle (state.py:24-29/431-443
``state_*.pckl``, reaction.py:18-23/193-199 ``reaction_*.pckl``,
old_system.py:24-29/641-647 ``system.pckl``, reactor.py:80-86); this
framework checkpoints as reference-schema JSON (utils/io.py). This tool
is the one-shot migration bridge for users holding existing reference
pickles:

    python tools/convert_reference_pickle.py system.pckl input.json
    python tools/convert_reference_pickle.py state_CO.pckl CO.json

The pickle is loaded WITHOUT importing the reference package (or ASE,
whose Atoms objects ride inside state pickles): a restricted unpickler
maps every non-allowlisted class to an attribute-bag shim, so (a) no
reference code runs, (b) no third-party import is needed, and (c) no
arbitrary class constructor executes during load. Only numpy scalars/
arrays and core builtins deserialize as themselves.

Resolved data is preferred over paths: a pickled state that already
carries Gelec/freq (the common case -- reference objects resolve their
DFT sources before anyone pickles them) converts to an inlined,
path-free JSON state; unresolved fields fall back to the recorded
path/vibs_path + source keys so the JSON loads through the ordinary
file readers.
"""

from __future__ import annotations

import io
import json
import pickle
import sys

import numpy as np

# Exact (module, name) pairs that deserialize as themselves: the numpy
# reconstruction machinery reference pickles actually use, plus the safe
# builtin containers/scalars. Everything else in these module roots --
# numpy funcs, builtins.eval/exec/getattr, os via a collections path,
# etc. -- is REJECTED (a whole-module-root allowlist is an arbitrary-
# code-execution hole: ``builtins.eval`` is one REDUCE away). Classes
# from any other module (pycatkin.*, ase.*, user code) become a _Shim
# subclass carrying only the pickled __dict__/state.
_ALLOWED_NAMES = frozenset(
    [("numpy", "ndarray"), ("numpy", "dtype"),
     ("numpy.core.multiarray", "_reconstruct"),
     ("numpy.core.multiarray", "scalar"),
     ("numpy._core.multiarray", "_reconstruct"),   # numpy >= 2 paths
     ("numpy._core.multiarray", "scalar"),
     ("numpy.core.numeric", "_frombuffer"),        # pickle protocol 5
     ("numpy._core.numeric", "_frombuffer"),
     ("_codecs", "encode"),           # legacy (proto<=2) numpy dtypes
     ("collections", "OrderedDict"),
     ("collections", "defaultdict"),
     ("collections", "deque")]
    + [(mod, name)
       for mod in ("builtins", "__builtin__")
       for name in ("list", "dict", "set", "tuple", "frozenset",
                    "bytearray", "bytes", "str", "int", "float",
                    "complex", "bool")])

# Module roots the allowlist covers: a disallowed name under one of
# these roots is an ERROR (never shimmed -- shimming numpy internals
# would silently corrupt array data; shimming builtins would mask an
# exploit attempt). Names under any other root shim as before.
_GUARDED_ROOTS = ("numpy", "builtins", "collections", "__builtin__",
                  "_codecs")


class _Shim:
    """Attribute bag standing in for a reference (or ASE) class."""

    def __init__(self, *args, **kwargs):
        self._shim_args = args
        self._shim_kwargs = kwargs

    def __setstate__(self, state):
        if isinstance(state, dict):
            self.__dict__.update(state)
        elif isinstance(state, tuple) and len(state) == 2:
            # (dict_state, slots_state) protocol
            for part in state:
                if isinstance(part, dict):
                    self.__dict__.update(part)
        else:
            self.__dict__["_shim_state"] = state


class _RefUnpickler(pickle.Unpickler):
    def find_class(self, module, name):
        if (module, name) in _ALLOWED_NAMES:
            return super().find_class(module, name)
        root = module.split(".")[0]
        if root in _GUARDED_ROOTS:
            raise pickle.UnpicklingError(
                f"refusing to resolve {module}.{name}: not on the "
                "conversion allowlist (only numpy array/scalar "
                "reconstruction and plain builtin containers may "
                "deserialize as themselves)")
        return type(name, (_Shim,), {"__module__": module})


def load_reference_pickle(path: str):
    """Load a reference pickle as a shim object graph (no reference/ASE
    imports, no reference code execution)."""
    with open(path, "rb") as fh:
        return _RefUnpickler(io.BytesIO(fh.read())).load()


def _f(v):
    """JSON-safe scalar."""
    if v is None:
        return None
    if isinstance(v, (np.generic,)):
        return v.item()
    return v


def _name_of(obj):
    return obj if isinstance(obj, str) else getattr(obj, "name", None)


def _is_state(obj):
    return getattr(obj, "state_type", None) is not None


def _is_scaling_state(obj):
    return getattr(obj, "scaling_coeffs", None) is not None


def _is_reaction(obj):
    return getattr(obj, "reac_type", None) is not None


def _is_system(obj):
    return (isinstance(getattr(obj, "states", None), dict)
            and isinstance(getattr(obj, "reactions", None), dict))


def state_to_cfg(st) -> dict:
    """Reference State/ScalingState shim -> JSON state config (the keys
    utils/io._state_cfg writes and frontend/loader reads)."""
    cfg = {"state_type": st.state_type}
    for key in ("sigma", "mass"):
        if getattr(st, key, None) is not None:
            cfg[key] = _f(getattr(st, key))
    if getattr(st, "inertia", None) is not None:
        cfg["inertia"] = [float(x) for x in np.ravel(st.inertia)]
    freq = getattr(st, "freq", None)
    if freq is not None and np.size(freq):
        cfg["freq"] = [float(x) for x in np.ravel(freq)]
        i_freq = getattr(st, "i_freq", None)
        if i_freq is not None and np.size(i_freq):
            cfg["i_freq"] = [float(x) for x in np.ravel(i_freq)]
    for key in ("Gelec", "Gzpe", "Gvibr", "Gtran", "Grota", "Gfree"):
        if getattr(st, key, None) is not None:
            cfg[key] = _f(getattr(st, key))
    if getattr(st, "add_to_energy", None):
        cfg["add_to_energy"] = _f(st.add_to_energy)
    if getattr(st, "truncate_freq", True) is False:
        cfg["truncate_freq"] = False
    # Unresolved sources fall back to the recorded file paths.
    if "Gelec" not in cfg and getattr(st, "path", None):
        cfg["path"] = st.path
        if getattr(st, "energy_source", None):
            cfg["energy_source"] = st.energy_source
    if "freq" not in cfg and getattr(st, "vibs_path", None):
        cfg["vibs_path"] = st.vibs_path
        if getattr(st, "freq_source", None):
            cfg["freq_source"] = st.freq_source
    gasdata = getattr(st, "gasdata", None)
    if gasdata:
        cfg["gasdata"] = {
            "fraction": [_f(x) for x in gasdata["fraction"]],
            "state": [_name_of(s) for s in gasdata["state"]],
        }
    if _is_scaling_state(st):
        cfg["scaling_coeffs"] = {k: _f(v)
                                 for k, v in st.scaling_coeffs.items()} \
            if isinstance(st.scaling_coeffs, dict) else st.scaling_coeffs
        sr = {}
        for key, entry in getattr(st, "scaling_reactions", {}).items():
            e = {"reaction": _name_of(entry["reaction"])}
            if "multiplicity" in entry:
                e["multiplicity"] = _f(entry["multiplicity"])
            sr[key] = e
        cfg["scaling_reactions"] = sr
        if getattr(st, "dereference", False):
            cfg["dereference"] = True
        if getattr(st, "use_descriptor_as_reactant", False):
            cfg["use_descriptor_as_reactant"] = True
    return cfg


def reaction_to_cfg(rx) -> dict:
    """Reference Reaction shim -> JSON reaction config."""
    cfg = {"reac_type": rx.reac_type,
           "reactants": [_name_of(s) for s in (rx.reactants or [])],
           "products": [_name_of(s) for s in (rx.products or [])]}
    ts = getattr(rx, "TS", None)
    cfg["TS"] = [_name_of(s) for s in ts] if ts else None
    if getattr(rx, "area", None) is not None:
        cfg["area"] = _f(rx.area)
    if getattr(rx, "reversible", True) is False:
        cfg["reversible"] = False
    if getattr(rx, "scaling", 1.0) != 1.0:
        cfg["scaling"] = _f(rx.scaling)
    base = getattr(rx, "base_reaction", None)
    if base is not None:
        cfg["base_reaction"] = _name_of(base)
    for key in ("dErxn_user", "dGrxn_user", "dEa_fwd_user",
                "dGa_fwd_user", "dEa_rev_user", "dGa_rev_user"):
        val = getattr(rx, key, None)
        if val is not None:
            cfg[key] = ({str(k): _f(v) for k, v in val.items()}
                        if isinstance(val, dict) else _f(val))
    return cfg


def _reactor_cfg(reactor):
    if reactor is None:
        return "InfiniteDilutionReactor"
    kind = type(reactor).__name__
    if kind == "InfiniteDilutionReactor":
        return "InfiniteDilutionReactor"
    body = {}
    for key in ("residence_time", "volume", "catalyst_area", "flow_rate"):
        if getattr(reactor, key, None) is not None:
            body[key] = _f(getattr(reactor, key))
    return {kind: body}


def _json_safe(v):
    if isinstance(v, dict):
        return {str(k): _json_safe(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_json_safe(x) for x in v]
    if isinstance(v, np.ndarray):
        return [_json_safe(x) for x in v.tolist()]
    if isinstance(v, np.generic):
        return v.item()
    return v


def system_to_input(sys_shim) -> dict:
    """Reference (old_)System shim -> full JSON input dict (sections:
    states / scaling relation states / reactions / reactor / system)."""
    out = {"states": {}, "reactions": {}}
    scaling = {}
    for name, st in sys_shim.states.items():
        cfg = state_to_cfg(st)
        if _is_scaling_state(st):
            scaling[name] = cfg
        else:
            out["states"][name] = cfg
    if scaling:
        out["scaling relation states"] = scaling
    derived = {}
    for name, rx in sys_shim.reactions.items():
        cfg = reaction_to_cfg(rx)
        if "base_reaction" in cfg:
            derived[name] = cfg
        else:
            out["reactions"][name] = cfg
    if derived:
        out["reaction derived reactions"] = derived
    out["reactor"] = _reactor_cfg(getattr(sys_shim, "reactor", None))
    params = getattr(sys_shim, "params", None)
    if params:
        out["system"] = {k: _json_safe(v) for k, v in params.items()
                         if _json_safe(v) is not None
                         or v is None}
    return out


def convert(obj) -> dict:
    """Dispatch on the pickled object kind. A bare State/Reaction
    converts to a single-section snippet keyed by its name."""
    if _is_system(obj):
        return system_to_input(obj)
    if _is_state(obj):
        name = getattr(obj, "name", "state")
        key = ("scaling relation states" if _is_scaling_state(obj)
               else "states")
        return {key: {name: state_to_cfg(obj)}}
    if _is_reaction(obj):
        name = getattr(obj, "name", "reaction")
        key = ("reaction derived reactions"
               if getattr(obj, "base_reaction", None) is not None
               else "reactions")
        return {key: {name: reaction_to_cfg(obj)}}
    raise ValueError(
        f"unrecognized reference pickle payload: {type(obj).__name__} "
        "(expected a System, State or Reaction)")


def main(argv):
    if len(argv) not in (2, 3):
        print("usage: python tools/convert_reference_pickle.py "
              "<reference.pckl> [out.json]", file=sys.stderr)
        return 2
    src = argv[1]
    obj = load_reference_pickle(src)
    doc = convert(obj)
    text = json.dumps(doc, indent=1)
    if len(argv) == 3:
        with open(argv[2], "w") as fh:
            fh.write(text + "\n")
        print(f"wrote {argv[2]} ({type(obj).__name__} -> "
              f"{', '.join(doc.keys())})", file=sys.stderr)
    else:
        print(text)
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
