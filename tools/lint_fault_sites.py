#!/usr/bin/env python
"""Static check: every fault-site label is documented.

The failure subsystem (docs/failure_model.md) addresses faults by
dispatch-site label -- the strings passed as ``label=`` to
``call_with_backend_retry`` / ``run_chunk_with_ladder`` /
``record_event`` / ``record_quarantine``, the label argument of
``timed_retry``, and ``site = ...`` assignments. A label that exists in
code but not in the doc is an undocumented failure branch: a fault plan
targeting it works, but nobody reading the failure model knows it
exists.

This tool walks ``pycatkin_tpu/`` with the ``ast`` module (a regex
would miss multi-line calls), normalizes f-string labels by replacing
each interpolated field with ``<i>`` (consecutive fields collapse to
one, so ``f"rescue[{a}{b}]"`` and ``f"rescue[{s}]"`` both become
``rescue[<i>]``), and requires each normalized label to appear
backticked in ``docs/failure_model.md``. Exit 0 when all labels are
documented, 1 otherwise (listing label, file and line for each miss).

Run directly or via ``make lint-faults``.
"""

from __future__ import annotations

import ast
import os
import re
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = os.path.join(ROOT, "pycatkin_tpu")
DOC = os.path.join(ROOT, "docs", "failure_model.md")

# Only these callees take fault-site labels; collecting every `label=`
# kwarg would false-positive on matplotlib legend labels.
LABEL_FUNCS = {"call_with_backend_retry", "run_chunk_with_ladder",
               "record_event", "record_quarantine", "timed_retry"}
SITE_NAMES = {"site", "_site"}


def normalize(node) -> str | None:
    """Literal or f-string label -> normalized site string (or None for
    dynamic expressions, which cannot be statically checked)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant):
                parts.append(str(v.value))
            else:
                parts.append("<i>")
        return re.sub(r"(<i>)+", "<i>", "".join(parts))
    return None


class SiteCollector(ast.NodeVisitor):
    """Collect (normalized_label, lineno) pairs from one module."""

    def __init__(self):
        self.sites: list[tuple[str, int]] = []

    def _add(self, node, value):
        label = normalize(value)
        if label is not None:
            self.sites.append((label, node.lineno))

    def visit_Call(self, node):
        func = node.func
        fname = getattr(func, "id", None) or getattr(func, "attr", "")
        if fname in LABEL_FUNCS:
            for kw in node.keywords:
                if kw.arg == "label":
                    self._add(node, kw.value)
            if fname == "timed_retry" and len(node.args) >= 2:
                self._add(node, node.args[1])
        self.generic_visit(node)

    def visit_Assign(self, node):
        if any(isinstance(t, ast.Name) and t.id in SITE_NAMES
               for t in node.targets):
            self._add(node, node.value)
        self.generic_visit(node)


def collect_sites(package: str = PACKAGE):
    """All statically-known fault-site labels in the package:
    (label, relpath, lineno) triples, sorted."""
    found = []
    for dirpath, dirnames, filenames in os.walk(package):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            with open(path) as fh:
                tree = ast.parse(fh.read(), filename=path)
            collector = SiteCollector()
            collector.visit(tree)
            rel = os.path.relpath(path, ROOT)
            found += [(label, rel, lineno)
                      for label, lineno in collector.sites]
    return sorted(found)


def documented_labels(doc_path: str = DOC) -> set:
    """Every backticked token in the failure-model doc."""
    with open(doc_path) as fh:
        return set(re.findall(r"`([^`\n]+)`", fh.read()))


def main(argv=None) -> int:
    # Globals looked up at call time so tests can repoint PACKAGE/DOC.
    sites = collect_sites(PACKAGE)
    documented = documented_labels(DOC)
    missing = [(label, rel, lineno) for label, rel, lineno in sites
               if label not in documented]
    labels = sorted({label for label, _, _ in sites})
    if missing:
        print(f"lint_fault_sites: {len(missing)} undocumented "
              f"fault-site label(s) (add them, backticked, to "
              f"{os.path.relpath(DOC, ROOT)}):")
        for label, rel, lineno in missing:
            print(f"  {rel}:{lineno}: `{label}`")
        return 1
    print(f"lint_fault_sites: OK -- {len(sites)} site reference(s), "
          f"{len(labels)} distinct label(s), all documented")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
