#!/usr/bin/env python
"""Legacy shim: the fault-site lint now lives in the pclint framework.

The check itself is rule ``PCL002``
(:mod:`pycatkin_tpu.lint.fault_sites`) run by ``tools/pclint.py`` /
``make lint``: every fault-site label in ``pycatkin_tpu/`` must appear
backticked in ``docs/failure_model.md``. This shim keeps the
historical entry point (``make lint-faults`` calls pclint directly;
running this file still works) and the historical module API
(``PACKAGE``/``DOC``/``collect_sites``/``normalize``/
``documented_labels``) that the shim's tests repoint.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from pycatkin_tpu.lint import fault_sites as _impl        # noqa: E402

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = os.path.join(ROOT, "pycatkin_tpu")
DOC = os.path.join(ROOT, "docs", "failure_model.md")

LABEL_FUNCS = set(_impl.LABEL_FUNCS)
SITE_NAMES = set(_impl.SITE_NAMES)

normalize = _impl.normalize


def collect_sites(package: str = None):
    """All statically-known fault-site labels in the package:
    (label, relpath, lineno) triples, sorted. Delegates to the PCL002
    checker's collector; globals looked up at call time so tests can
    repoint PACKAGE."""
    return _impl.collect_sites(PACKAGE if package is None else package,
                               rel_to=ROOT)


def documented_labels(doc_path: str = None) -> set:
    """Every backticked token in the failure-model doc."""
    return _impl.documented_labels(DOC if doc_path is None else doc_path)


def main(argv=None) -> int:
    sites = collect_sites(PACKAGE)
    documented = documented_labels(DOC)
    missing = [(label, rel, lineno) for label, rel, lineno in sites
               if label not in documented]
    labels = sorted({label for label, _, _ in sites})
    if missing:
        print(f"lint_fault_sites: {len(missing)} undocumented "
              f"fault-site label(s) (add them, backticked, to "
              f"{os.path.relpath(DOC, ROOT)}):")
        for label, rel, lineno in missing:
            print(f"  {rel}:{lineno}: `{label}`")
        return 1
    print(f"lint_fault_sites: OK -- {len(sites)} site reference(s), "
          f"{len(labels)} distinct label(s), all documented "
          f"[delegated to pclint PCL002]")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
