"""Pre-compile the hot batched programs into the persistent XLA cache.

Cold-compile economics on TPU (measured, docs/perf_config5.md §6):
every emulated-f64 op instance costs ~10-20 ms of XLA compile and each
transcendental ~0.35 s, so the volcano-scale batched solve costs tens
of seconds the first time on a machine. The persistent cache
(utils/cache.py) makes every later process load the compiled
executable from disk instead; this tool front-loads that cost once --
run it after install, after a JAX upgrade, or in an image build:

    python tools/warm_cache.py [grid_n]

Programs warmed: the capped first-pass sweep program at the full
[grid_n^2] lane shape, its rescue programs (full-ladder PTC + LM at
the 64-lane bucket), the stability screen, the subset Jacobian
program, and the TOF/activity program -- the complete
sweep_steady_state surface for the flagship workload.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from pycatkin_tpu.utils.cache import enable_persistent_cache  # noqa: E402

cache_dir = enable_persistent_cache()

import numpy as np  # noqa: E402


def main():
    import time

    import jax
    import jax.numpy as jnp

    import pycatkin_tpu as pk
    from pycatkin_tpu import engine
    from pycatkin_tpu.models import coox
    from pycatkin_tpu.parallel import batch

    grid_n = int(sys.argv[1]) if len(sys.argv) > 1 else 256
    ref = os.environ.get(
        "PYCATKIN_REFERENCE_INPUT",
        "/root/reference/examples/COOxVolcano/input.json")
    print(f"cache: {cache_dir if cache_dir else 'disabled (cpu)'}")

    sim = pk.read_from_input_file(ref)
    spec = sim.spec
    be = np.linspace(-2.5, 0.5, grid_n)
    conds, _ = coox.volcano_grid_conditions(sim, be)
    mask = engine.tof_mask_for(spec, ["CO_ox"])
    n = grid_n * grid_n

    from pycatkin_tpu.solvers.newton import SolverOptions
    opts = SolverOptions()
    t0 = time.perf_counter()
    # Main sweep surface (first pass + screen + tof/activity).
    out = batch.sweep_steady_state(spec, conds, tof_mask=mask,
                                   check_stability=True)
    np.asarray(out["y"])
    print(f"sweep programs: {time.perf_counter() - t0:.1f} s")

    # Rescue programs at the 64-lane bucket (compiled lazily only when
    # lanes fail; warm them explicitly so a hard grid's first failure
    # doesn't pay the compile).
    t0 = time.perf_counter()
    sub = jax.tree_util.tree_map(lambda a: jnp.asarray(a)[:64], conds)
    keys = jax.random.split(jax.random.PRNGKey(0), 64)
    x0 = jnp.asarray(out["y"])[:64][:, jnp.asarray(spec.dynamic_indices)]
    for strat in ("ptc", "lm"):
        r = batch._steady_program(spec, opts, strategy=strat)(sub, keys,
                                                              x0)
        np.asarray(r.residual)
    # The stability demote loop rescues with use_x0=False -> x0=None,
    # which traces a DIFFERENT program than the x0-array variant above.
    r = batch._steady_program(spec, opts, strategy="ptc")(sub, keys, None)
    np.asarray(r.residual)
    # Subset Jacobian program (stability tier 2) at the same bucket.
    np.asarray(batch._jacobian_program(spec)(sub,
                                             jnp.asarray(out["y"])[:64]))
    print(f"rescue + tier-2 programs: {time.perf_counter() - t0:.1f} s")
    print(f"warm: a fresh process now loads all {n}-lane volcano "
          "programs from the persistent cache.")


if __name__ == "__main__":
    main()
