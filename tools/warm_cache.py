"""Pre-compile the hot batched programs into the persistent XLA cache.

Cold-compile economics on TPU (measured, docs/perf_config5.md §6):
every emulated-f64 op instance costs ~10-20 ms of XLA compile and each
transcendental ~0.35 s, so the volcano-scale batched solve costs tens
of seconds the first time on a machine. The persistent cache
(utils/cache.py) makes every later process load the compiled
executable from disk instead; this tool front-loads that cost once --
run it after install, after a JAX upgrade, or in an image build:

    python tools/warm_cache.py [grid_n]

Programs warmed (via parallel.batch.prewarm_sweep_programs, the same
routine bench.py runs before its timed region, with bench's exact
bucket configuration): the fast-pass sweep program at the full
[grid_n^2] lane shape, the PTC/LM rescue programs (seeded and
unseeded) at the 64/128/256/512-lane pow2 buckets (executed) plus the
1024 insurance bucket (AOT-compiled only), the stability screen +
tier-2 subset Jacobian, and the TOF/activity program -- the complete
sweep_steady_state surface for the flagship workload.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from pycatkin_tpu.utils.cache import enable_persistent_cache  # noqa: E402

cache_dir = enable_persistent_cache()

import numpy as np  # noqa: E402


def main():
    import time

    import pycatkin_tpu as pk
    from pycatkin_tpu import engine
    from pycatkin_tpu.models import coox
    from pycatkin_tpu.parallel.batch import prewarm_sweep_programs

    grid_n = int(sys.argv[1]) if len(sys.argv) > 1 else 256
    ref = os.environ.get(
        "PYCATKIN_REFERENCE_INPUT",
        "/root/reference/examples/COOxVolcano/input.json")
    print(f"cache: {cache_dir if cache_dir else 'disabled (cpu)'}")

    sim = pk.read_from_input_file(ref)
    spec = sim.spec
    be = np.linspace(-2.5, 0.5, grid_n)
    conds, _ = coox.volcano_grid_conditions(sim, be)
    mask = engine.tof_mask_for(spec, ["CO_ox"])

    t0 = time.perf_counter()
    # EXACTLY bench.py's prewarm configuration: an image warmed here
    # must leave bench's prewarm nothing to compile.
    n_prog = prewarm_sweep_programs(spec, conds, tof_mask=mask,
                                    buckets=(64, 128, 256, 512),
                                    aot_buckets=(1024,),
                                    tier2_buckets=(8192, 16384),
                                    tier2_aot_buckets=(2048, 4096),
                                    check_stability=True, verbose=True)
    print(f"warmed {n_prog} programs in {time.perf_counter() - t0:.1f} s; "
          f"a fresh process now loads all {grid_n * grid_n}-lane volcano "
          "programs from the persistent cache.")


if __name__ == "__main__":
    main()
