#!/usr/bin/env python
"""Soak the sweep service (pycatkin_tpu/serve) and gate its SLOs.

Streams randomized synthetic mechanisms through a live server
(``serve/soak.py``) and writes a BENCH-style JSON record carrying
p50/p99 latency, achieved pack occupancy and the post-warmup
zero-compile rate -- metrics ``tools/perfwatch.py`` baselines with the
same median±MAD sentinel as sweep throughput. The measured stream
mixes ``--transient-frac`` (default 0.25) dense-output ``transient``
requests into the bucket mix, warmed and coalesced like sweeps
(small buckets only -- serve/soak.py TRANSIENT_MIX_MAX_BUCKET).

Usage::

    python tools/soak.py [--n 1000] [--buckets 16,32,128] [--tcp]
                         [--json OUT.json] [--gate] [...]
    python tools/soak.py --check        # the `make serve-check` lane

``--check`` is the CI proof in two fresh processes: process 1 runs a
small soak against an empty AOT cache and exports the warmed cache as
a pack; process 2 boots its server FROM that pack (compile count of
its prewarm must be zero), streams N~64 requests, and gates on a 100%
zero-compile rate, the p99 budget, response manifest/telemetry
presence, and loss-free drain.

``--chaos`` is the fleet-tier drill (``make router-check``): boot a
3-replica pack-warmed fleet behind the router, SIGKILL 2 of 3
replicas mid-soak plus one torn line and one connection reset, and
hard-fail unless ZERO requests are lost, every answer is bitwise
identical to an undisturbed same-grid run, the duplicate-suppression
audit is clean, and the restarted replicas serve at a 100%
zero-compile rate straight from the AOT pack. By default the drill
ALSO SIGKILLs the journal-backed front router mid-stream
(docs/serving.md "Durable requests"): every request carries an
idempotency key, the rebooted router replays its write-ahead journal,
and the gate additionally requires zero acknowledged requests lost
and every journaled answer bitwise identical to the baseline
(``--no-router-crash`` reverts to the replica-only drill).

``--durable`` is the durable-serving smoke (``make durable-check``):
a JAX-free journal round-trip (rotation, compaction, torn-tail
replay) plus a router-kill replay over stub replicas, gated by
``serve/soak.py check_durable_record``.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _parse_buckets(text: str):
    return tuple(int(b) for b in text.split(",") if b.strip())


def _run(args) -> int:
    from pycatkin_tpu.serve.soak import check_soak_record, run_soak

    record = run_soak(
        out_path=args.json,
        n_requests=args.n, buckets=_parse_buckets(args.buckets),
        lanes=args.lanes, seed=args.seed,
        transport="tcp" if args.tcp else "inproc",
        mechs_per_bucket=args.mechs_per_bucket,
        max_occupancy=args.max_occupancy,
        concurrency=args.concurrency, runner=args.runner,
        aot_pack=args.aot_pack,
        transient_frac=args.transient_frac, verbose=args.verbose)
    if args.export_pack:
        from pycatkin_tpu.parallel import compile_pool
        stats = compile_pool.export_cache_pack(args.export_pack)
        print(f"soak: exported AOT pack {args.export_pack} "
              f"({stats['entries']} entries)", file=sys.stderr)
    serve = record.get("serve") or {}
    print(json.dumps(record if args.full_json else {
        "bench": record["bench"], "backend": record["backend"],
        "n_requests": record["n_requests"], "n_ok": record["n_ok"],
        "serve": serve, "wall_s": record["wall_s"]}, indent=2))
    if args.gate or args.expect_warm_compiled_zero:
        problems = check_soak_record(
            record, p99_budget_s=args.p99_budget,
            expect_warm_compiled_zero=args.expect_warm_compiled_zero)
        for p in problems:
            print(f"soak: GATE FAIL -- {p}", file=sys.stderr)
        if problems:
            return 1
        print("soak: gate OK", file=sys.stderr)
    return 0


def _cmd_check(args) -> int:
    """Two-process pack-boot proof; see module docstring."""
    me = os.path.abspath(__file__)
    with tempfile.TemporaryDirectory(prefix="pycatkin_soak_") as td:
        cache = os.path.join(td, "aot_cache")
        pack = os.path.join(td, "serve_pack.tar.gz")
        out = os.path.join(td, "soak.json")
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        env["PYCATKIN_AOT_CACHE"] = cache
        common = ["--buckets", args.buckets, "--lanes",
                  str(args.lanes), "--max-occupancy",
                  str(args.max_occupancy), "--seed", str(args.seed),
                  "--transient-frac", str(args.transient_frac)]
        warm_cmd = [sys.executable, me, "--n", "12",
                    "--mechs-per-bucket", "2",
                    "--export-pack", pack] + common
        print("serve-check: [1/2] warming cache + exporting pack",
              flush=True)
        r = subprocess.run(warm_cmd, env=env)
        if r.returncode != 0:
            print("serve-check: FAIL -- warm/export run failed",
                  file=sys.stderr)
            return 1
        # Fresh process + fresh cache dir: every warm executable must
        # come from the pack, not from this process's compiles.
        env2 = dict(env)
        env2["PYCATKIN_AOT_CACHE"] = os.path.join(td, "aot_cache2")
        check_cmd = [sys.executable, me, "--n", str(args.n),
                     "--mechs-per-bucket", "2", "--tcp",
                     "--aot-pack", pack, "--gate",
                     "--expect-warm-compiled-zero",
                     "--p99-budget", str(args.p99_budget),
                     "--json", out] + common
        print(f"serve-check: [2/2] pack-booted soak (n={args.n}, tcp)",
              flush=True)
        r = subprocess.run(check_cmd, env=env2)
        if r.returncode != 0:
            print("serve-check: FAIL -- gated soak failed",
                  file=sys.stderr)
            return 1
        with open(out) as fh:
            serve = (json.load(fh).get("serve") or {})
        print(f"serve-check: OK -- p50={serve.get('p50_s'):.3f}s "
              f"p99={serve.get('p99_s'):.3f}s "
              f"zero_compile_rate={serve.get('zero_compile_rate')} "
              f"mean_occupancy={serve.get('mean_occupancy'):.2f}")
    return 0


def _cmd_chaos(args) -> int:
    """Fleet chaos drill; see module docstring and serve/soak.py."""
    from pycatkin_tpu.serve.soak import check_chaos_record, \
        run_chaos_drill

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    record = run_chaos_drill(
        out_path=args.json, n_requests=args.n, bucket=args.bucket,
        lanes=args.lanes, mechs=args.mechs_per_bucket,
        n_replicas=args.replicas, kill=args.kill,
        max_occupancy=args.max_occupancy, seed=args.seed,
        with_pack=not args.no_pack,
        router_crash=not args.no_router_crash, verbose=args.verbose)
    router = record.get("router") or {}
    print(json.dumps(record if args.full_json else {
        "bench": record["bench"], "backend": record["backend"],
        "n_requests": record["n_requests"], "n_ok": record["n_ok"],
        "kills_fired": record["kills_fired"],
        "incarnations": record["incarnations"],
        "router": router, "durable": record.get("durable"),
        "wall_s": record["wall_s"]}, indent=2))
    problems = check_chaos_record(record)
    for p in problems:
        print(f"chaos: GATE FAIL -- {p}", file=sys.stderr)
    if problems:
        return 1
    durable = record.get("durable") or {}
    extra = ""
    if record.get("router_crash"):
        extra = (f", router killed and recovered in "
                 f"{durable.get('router_recovery_s')}s (journal "
                 f"replay {durable.get('journal_replay_s')}s)")
    print(f"chaos: OK -- {record['n_ok']}/{record['n_requests']} "
          f"answered bit-identically while "
          f"{record['kills_fired']}/{record['n_replicas']} replicas "
          f"were killed and rebooted from the pack "
          f"(availability={router.get('availability')}, "
          f"failover_p99_s={router.get('failover_p99_s')}){extra}",
          file=sys.stderr)
    return 0


def _cmd_durable(args) -> int:
    """Durable-serving smoke; see module docstring and serve/soak.py."""
    from pycatkin_tpu.serve.soak import check_durable_record, \
        run_durable_smoke

    record = run_durable_smoke(out_path=args.json,
                               verbose=args.verbose)
    print(json.dumps(record, indent=2))
    problems = check_durable_record(record)
    for p in problems:
        print(f"durable: GATE FAIL -- {p}", file=sys.stderr)
    if problems:
        return 1
    replay = record.get("replay") or {}
    print(f"durable: OK -- journal round-trip survived rotation + "
          f"compaction + a torn tail; router-kill replay re-answered "
          f"{replay.get('done')}/{replay.get('total')} pending keys "
          f"in {replay.get('wall_s'):.3f}s", file=sys.stderr)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--check", action="store_true",
                    help="two-process pack-boot CI gate")
    ap.add_argument("--chaos", action="store_true",
                    help="fleet chaos drill: kill 2-of-3 replicas "
                         "mid-soak, gate on loss-free bitwise-"
                         "identical failover")
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--kill", type=int, default=2)
    ap.add_argument("--bucket", type=int, default=16,
                    help="ABI bucket for the chaos drill grid")
    ap.add_argument("--no-pack", action="store_true",
                    help="chaos drill without the AOT boot pack "
                         "(skips the zero-compile gate)")
    ap.add_argument("--no-router-crash", action="store_true",
                    help="chaos drill without killing the front "
                         "router (replica kills only)")
    ap.add_argument("--durable", action="store_true",
                    help="durable-serving smoke: journal round-trip "
                         "+ router-kill replay over stub replicas")
    ap.add_argument("--n", type=int, default=1000)
    ap.add_argument("--buckets", default="16,32,128")
    ap.add_argument("--lanes", type=int, default=4)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--tcp", action="store_true",
                    help="full wire round-trip (default: in-process)")
    ap.add_argument("--mechs-per-bucket", type=int, default=6)
    ap.add_argument("--transient-frac", type=float, default=0.25,
                    help="fraction of transient (dense-output) "
                         "requests mixed into the measured stream "
                         "(0 disables)")
    ap.add_argument("--max-occupancy", type=int, default=8)
    ap.add_argument("--concurrency", type=int, default=16)
    ap.add_argument("--runner", choices=("inproc", "elastic"),
                    default="inproc")
    ap.add_argument("--aot-pack", default=None,
                    help="boot the server from this AOT cache pack")
    ap.add_argument("--export-pack", default=None,
                    help="export the AOT cache as a pack afterwards")
    ap.add_argument("--json", default=None,
                    help="write the full record to this path")
    ap.add_argument("--full-json", action="store_true",
                    help="print the full record, not the summary")
    ap.add_argument("--gate", action="store_true",
                    help="apply the SLO gate; nonzero exit on failure")
    ap.add_argument("--p99-budget", type=float, default=30.0)
    ap.add_argument("--expect-warm-compiled-zero", action="store_true",
                    help="gate: prewarm must compile nothing "
                         "(pack-booted server)")
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)
    if args.durable:
        return _cmd_durable(args)
    if args.chaos:
        args.n = args.n if args.n != 1000 else 24
        args.mechs_per_bucket = (args.mechs_per_bucket
                                 if args.mechs_per_bucket != 6 else 4)
        args.max_occupancy = (args.max_occupancy
                              if args.max_occupancy != 8 else 4)
        return _cmd_chaos(args)
    if args.check:
        args.n = args.n if args.n != 1000 else 64
        return _cmd_check(args)
    return _run(args)


if __name__ == "__main__":
    sys.exit(main())
