"""Measure config-5 hot-path component variants on the real TPU:
  - lu_factor at unroll 32/64/96, f64 vs f32
  - lu_solve unroll variants
  - jacfwd f64 vs f32
  - row-gather vs one-hot permutation application

Run: python tools/exp_jac_perm.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

from pycatkin_tpu.utils.cache import enable_persistent_cache

enable_persistent_cache()

import jax
import jax.numpy as jnp

from tools.exp_blocked_lu import chain_time
from pycatkin_tpu import engine
from pycatkin_tpu.models.synthetic import synthetic_system
from pycatkin_tpu.ops import linalg
from pycatkin_tpu.parallel.batch import broadcast_conditions

L, N = 128, 190


def main():
    dev = jax.devices()[0]
    print(f"device: {dev.platform} ({dev.device_kind})", flush=True)
    rng = np.random.default_rng(0)
    A = jnp.asarray(rng.standard_normal((L, N, N)) + 10.0 * np.eye(N))
    b = jnp.asarray(rng.standard_normal((L, N)))

    for unroll in (32, 64, 96):
        def body(X, u=unroll):
            LU, perm = jax.vmap(lambda M: linalg.lu_factor(M, unroll=u))(X)
            return A + 1e-12 * jnp.sum(LU) + 0.0 * X
        chain_time(body, A, n_hi=4, tag=f"f64 lu_factor unroll={unroll}")

    A32 = A.astype(jnp.float32)
    for unroll in (32, 64):
        def body32(X, u=unroll):
            LU, perm = jax.vmap(lambda M: linalg.lu_factor(M, unroll=u))(X)
            return A32 + 1e-6 * jnp.sum(LU) + 0.0 * X
        chain_time(body32, A32, n_hi=4, tag=f"f32 lu_factor unroll={unroll}")

    # full solve f32
    def solve32(X):
        x = jax.vmap(linalg.solve)(X, b.astype(jnp.float32))
        return A32 + 1e-6 * jnp.mean(x) + 0.0 * X
    chain_time(solve32, A32, n_hi=4, tag="f32 solve (factor+tri)")

    # jacfwd f64 vs f32
    sim = synthetic_system(n_species=200, n_reactions=500, seed=0)
    spec = sim.spec
    dyn = np.asarray(spec.dynamic_indices)
    Ts = np.linspace(420.0, 700.0, L)
    conds = broadcast_conditions(sim.conditions(), L)._replace(T=Ts)
    x0 = jnp.asarray(np.asarray(conds.y0)[:, dyn])

    def jac_one(cond, x):
        kf, kr, _ = engine.rate_constants(spec, cond)
        fscale, _, _ = engine._dynamic_fscale(spec, cond, kf, kr)
        return jax.jacfwd(lambda z: fscale(z)[0])(x)

    jf = jax.vmap(jac_one, in_axes=(0, 0))

    def body_jf(x):
        J = jf(conds, x)
        return x + 1e-15 * jnp.sum(J)
    chain_time(body_jf, x0, n_hi=8, tag="f64 jacfwd [128,190,190]")

    def jac_one32(cond, x):
        kf, kr, _ = engine.rate_constants(spec, cond)
        fscale, _, _ = engine._dynamic_fscale(spec, cond, kf, kr)
        kf32 = None  # tangents in f32: push f32 basis through f64 fn
        Jrow = jax.jacfwd(lambda z: fscale(z)[0])(x)
        return Jrow

    # f32 jacobian: cast primal path to f32 wholesale is invasive;
    # instead measure jacfwd of the f64 fn then cast (upper bound is the
    # f64 number). Skip true-f32 until the solver variant exists.

    # permutation application
    pv = jnp.asarray(np.stack([rng.permutation(N) for _ in range(L)]))

    def gather_body(X):
        Y = jnp.take_along_axis(X, pv[:, :, None], axis=1)
        return Y + 1e-12
    chain_time(gather_body, A, n_hi=8, tag="f64 row gather A[pvec]")

    def onehot_body(X):
        P = (pv[:, :, None] == jnp.arange(N)[None, None, :]).astype(X.dtype)
        return P @ X + 1e-12
    chain_time(onehot_body, A, n_hi=8, tag="f64 one-hot P@A")


if __name__ == "__main__":
    main()
