"""Experiment: economics of a fori-blocked LU at config-5 shapes.

Measures, with chained data-dependent iterations + single-scalar fences
(the honest methodology from bench_suite.config_1):
  1. emulated-f64 batched matmul cost at panel shapes
  2. current sequential lu_factor / lu_solve cost
  3. (once implemented) the fori-blocked variant

Run: python tools/exp_blocked_lu.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

from pycatkin_tpu.utils.cache import enable_persistent_cache

enable_persistent_cache()

import jax
import jax.numpy as jnp

from pycatkin_tpu.ops import linalg

L, N, B = 128, 190, 32


def chain_time(make_body, x0, n_hi=8, n_lo=1, reps=3, tag=""):
    """Marginal seconds per body application, via two chain lengths.

    make_body(x) -> x' must be data-dependent on x so chained calls
    cannot overlap; the return is reduced to ONE scalar (one tunnel
    round trip inside the timed window)."""
    def chain(x, n):
        def step(c, _):
            return make_body(c), ()
        y, _ = jax.lax.scan(step, x, None, length=n)
        return jnp.sum(y)

    hi = jax.jit(lambda x: chain(x, n_hi))
    lo = jax.jit(lambda x: chain(x, n_lo))
    float(np.asarray(hi(x0)))          # compile
    float(np.asarray(lo(x0)))
    rng = np.random.default_rng(0)
    vals = []
    for _ in range(reps):
        x = x0 + 1e-9 * rng.uniform()   # fresh values each trial
        t0 = time.perf_counter()
        float(np.asarray(lo(x)))
        t_lo = time.perf_counter() - t0
        t0 = time.perf_counter()
        float(np.asarray(hi(x)))
        t_hi = time.perf_counter() - t0
        vals.append((t_hi - t_lo) / (n_hi - n_lo))
    med = sorted(vals)[len(vals) // 2]
    print(f"{tag:42s} {med*1e3:9.2f} ms  "
          f"(min {min(vals)*1e3:.2f} max {max(vals)*1e3:.2f})", flush=True)
    return med


def main():
    dev = jax.devices()[0]
    print(f"device: {dev.platform} ({dev.device_kind})", flush=True)
    rng = np.random.default_rng(0)
    A = jnp.asarray(rng.standard_normal((L, N, N)) + 10.0 * np.eye(N))

    # 1. f64 batched matmul: full [L,N,N]@[L,N,N]
    chain_time(lambda X: (X @ A) * (1.0 / N), A, tag="f64 matmul [128,190,190]^2")

    # panel-shaped matmul [L,N,B]@[L,B,N]
    P0 = jnp.asarray(rng.standard_normal((L, N, B)))
    def panel_mm(X):
        P = X[:, :, :B]
        return X - 1e-6 * (P @ P.transpose(0, 2, 1))
    chain_time(panel_mm, A, tag="f64 A -= panel[190,32]@[32,190]")

    # f32 same matmul for comparison
    A32 = A.astype(jnp.float32)
    chain_time(lambda X: (X @ A32) * (1.0 / N), A32,
               tag="f32 matmul [128,190,190]^2")

    # 2. current sequential LU factor (data-dependent chaining: feed a
    # tiny function of LU back into A's diagonal)
    def lu_body(X):
        LU, perm = jax.vmap(linalg.lu_factor)(X)
        return A + 1e-12 * jnp.sum(LU) + 0.0 * X
    chain_time(lu_body, A, n_hi=4, tag="sequential lu_factor [128,190,190]")

    # full solve
    b = jnp.asarray(rng.standard_normal((L, N)))
    def solve_body(X):
        x = jax.vmap(linalg.solve)(X, b)
        return A + 1e-12 * jnp.mean(x)[None, None] + 0.0 * X
    chain_time(solve_body, A, n_hi=4, tag="sequential solve [128,190,190]")


if __name__ == "__main__":
    main()
